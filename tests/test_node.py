"""Node assembly tests — make_node boots real nodes from config files
(reference model: node/node_test.go).

Covers: single-validator boot (onlyValidatorIsUs), a 4-validator
localnet over memory transports with the TPU batch verifier in the
served path, restart/handshake recovery, and a TCP localnet pair.
"""

import asyncio
import time

import pytest

from tendermint_tpu.config import Config
from tendermint_tpu.crypto import sigcache, tpu_verifier
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.node import NodeKey, make_node
from tendermint_tpu.p2p.transport import MemoryNetwork, MemoryTransport
from tendermint_tpu.privval import FilePV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

CHAIN = "node-chain"


@pytest.fixture(autouse=True)
def _clean_crypto_install_state():
    """Node boots install the process-global device batch verifier and
    create/trip circuit breakers (make_node); teardown does not always
    unwind that state, and a later test FILE then sees the seam routed
    through this file's install (observed: test_node.py followed by
    test_sr25519.py fails test_batch_verifier_seam). Uninstall
    defensively after every test — the same pattern as test_warmpath's
    autouse fixture."""
    yield
    tpu_verifier.uninstall()
    from tendermint_tpu.crypto import breaker

    breaker.reset_all()
    sigcache.reset()


def run(coro):
    return asyncio.run(coro)


def fast_consensus(cfg: Config) -> None:
    cfg.consensus.timeout_propose = 2.0
    cfg.consensus.timeout_prevote = 1.0
    cfg.consensus.timeout_precommit = 1.0
    cfg.consensus.timeout_commit = 0.2
    cfg.consensus.peer_gossip_sleep_duration = 0.01
    cfg.consensus.peer_query_maj23_sleep_duration = 0.5


def make_home(tmp_path, i: int, genesis: GenesisDoc,
              priv: PrivKeyEd25519 | None) -> Config:
    """Lay down the on-disk home dir a real operator would have after
    `init`: config.toml-equivalent Config, genesis.json, node key,
    priv_validator files."""
    cfg = Config()
    cfg.base.home = str(tmp_path / f"node{i}")
    cfg.base.chain_id = genesis.chain_id
    cfg.base.moniker = f"node{i}"
    cfg.base.db_backend = "memdb"
    cfg.ensure_dirs()
    fast_consensus(cfg)
    cfg.rpc.laddr = "tcp://127.0.0.1:0"  # ephemeral port per node
    cfg.tpu.min_batch_size = 2  # 4-validator commits hit the device path
    genesis.save_as(cfg.base.path(cfg.base.genesis_file))
    if priv is not None:
        FilePV.from_priv_key(
            priv,
            cfg.base.path(cfg.priv_validator.key_file),
            cfg.base.path(cfg.priv_validator.state_file),
        ).save()
    else:
        cfg.base.mode = "full"
    return cfg


def make_genesis(privs) -> GenesisDoc:
    return GenesisDoc(
        chain_id=CHAIN,
        genesis_time_ns=time.time_ns(),
        validators=[
            GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs
        ],
    )


def test_single_validator_node_produces_blocks(tmp_path):
    """The minimum end-to-end slice: one node, builtin kvstore app, no
    peers (reference: onlyValidatorIsUs, node/node.go:230)."""

    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x01" * 32)
        genesis = make_genesis([priv])
        cfg = make_home(tmp_path, 0, genesis, priv)
        node = make_node(cfg)
        await node.start()
        try:
            await node.consensus.wait_for_height(4, timeout=60.0)
            assert node.block_store.height() >= 3
        finally:
            await node.stop()

    run(go())


def test_node_restart_handshake_resumes(tmp_path):
    """Stop a node and boot a fresh Node over the same home: WAL replay
    + ABCI handshake must resume the chain (reference: replay.go:240)."""

    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x02" * 32)
        genesis = make_genesis([priv])
        cfg = make_home(tmp_path, 0, genesis, priv)
        cfg.base.db_backend = "sqlite"  # must survive restart
        node = make_node(cfg)
        await node.start()
        try:
            await node.consensus.wait_for_height(3, timeout=60.0)
            h1 = node.block_store.height()
        finally:
            await node.stop()

        node2 = make_node(cfg)
        await node2.start()
        try:
            assert node2.block_store.height() >= h1
            await node2.consensus.wait_for_height(h1 + 2, timeout=60.0)
        finally:
            await node2.stop()

    run(go())


def make_mesh(tmp_path, genesis, privs, net):
    """Full-mesh make_node nodes over memory transports: homes, node
    keys, persistent peers, transports."""
    cfgs = []
    for i, p in enumerate(privs):
        cfg = make_home(tmp_path, i, genesis, p)
        cfg.p2p.laddr = f"node{i}:26656"
        cfgs.append(cfg)
    node_ids = [
        NodeKey.load_or_generate(
            c.base.path(c.base.node_key_file)
        ).node_id
        for c in cfgs
    ]
    for i, cfg in enumerate(cfgs):
        cfg.p2p.persistent_peers = ",".join(
            f"{node_ids[j]}@node{j}:26656"
            for j in range(len(cfgs))
            if j != i
        )
    return [
        make_node(c, transport=MemoryTransport(net, f"node{i}:26656"))
        for i, c in enumerate(cfgs)
    ]


def test_four_validator_localnet_memory(tmp_path):
    """4 make_node validators over memory transports produce blocks
    together, with commit verification running through the installed
    device batch verifier (the VERDICT round-1 'TPU in the served path'
    requirement). Runs with the verified-signature cache disabled: a
    warm LastCommit legitimately performs zero device dispatches (the
    sigcache tests cover that), and this test asserts the device
    WIRING."""

    async def go():
        privs = [
            PrivKeyEd25519.from_seed(bytes([i + 50]) * 32) for i in range(4)
        ]
        genesis = make_genesis(privs)
        net = MemoryNetwork()
        sigs_before = tpu_verifier.stats()["sigs"]
        nodes = make_mesh(tmp_path, genesis, privs, net)
        for n in nodes:
            await n.start()
        try:
            # 300 s: observed a 180 s timeout flake on a 1-core box with
            # a second compile-heavy process competing; the wait is
            # event-driven so the slack costs nothing when healthy
            await asyncio.gather(
                *(n.consensus.wait_for_height(4, timeout=300.0) for n in nodes)
            )
            # all nodes agree on block 3
            hashes = {n.block_store.load_block(3).hash() for n in nodes}
            assert len(hashes) == 1
        finally:
            for n in nodes:
                await n.stop()
        # the served path used the device verifier
        assert tpu_verifier.stats()["sigs"] > sigs_before

    with sigcache.disabled():
        run(go())


def test_two_validator_localnet_tcp(tmp_path):
    """Real TCP transports + SecretConnection between two make_node
    validators (the localnet BASELINE config over loopback)."""

    async def go():
        privs = [
            PrivKeyEd25519.from_seed(bytes([i + 70]) * 32) for i in range(2)
        ]
        genesis = make_genesis(privs)
        cfgs = []
        # pick free ports (fixed ones collide with concurrent runs)
        import socket

        socks = [socket.socket() for _ in range(2)]
        for s in socks:
            s.bind(("127.0.0.1", 0))
        ports = [s.getsockname()[1] for s in socks]
        for s in socks:
            s.close()
        for i in range(2):
            cfg = make_home(tmp_path, i, genesis, privs[i])
            cfg.p2p.laddr = f"127.0.0.1:{ports[i]}"
            cfgs.append(cfg)
        node_ids = [
            NodeKey.load_or_generate(
                c.base.path(c.base.node_key_file)
            ).node_id
            for c in cfgs
        ]
        for i, cfg in enumerate(cfgs):
            j = 1 - i
            cfg.p2p.persistent_peers = f"{node_ids[j]}@127.0.0.1:{ports[j]}"
        nodes = [make_node(c) for c in cfgs]
        for n in nodes:
            await n.start()
        try:
            await asyncio.gather(
                *(n.consensus.wait_for_height(3, timeout=180.0) for n in nodes)
            )
            assert (
                nodes[0].block_store.load_block(2).hash()
                == nodes[1].block_store.load_block(2).hash()
            )
        finally:
            for n in nodes:
                await n.stop()

    run(go())


def test_app_retain_height_prunes_block_store(tmp_path):
    """The app's ResponseCommit.retain_height drives live block-store
    pruning during consensus (reference: state/execution.go Commit →
    pruneBlocks; kvstore retain_blocks knob)."""
    from tendermint_tpu.abci import KVStoreApplication

    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x2b" * 32)
        genesis = make_genesis([priv])
        cfg = make_home(tmp_path, 0, genesis, priv)
        node = make_node(
            cfg,
            app=KVStoreApplication(retain_blocks=3),
            genesis=genesis,
        )
        await node.start()
        try:
            await node.consensus.wait_for_height(8, timeout=120.0)
            base = node.block_store.base()
            assert base >= 4, f"expected pruning to advance base, got {base}"
            assert node.block_store.load_block(1) is None
            assert node.block_store.load_block(base) is not None
            # consensus still advances after pruning
            tip = node.block_store.height()
            await node.consensus.wait_for_height(tip + 2, timeout=60.0)
        finally:
            await node.stop()

    run(go())


def test_validator_joins_live_and_signs(tmp_path):
    """A node not in genesis is granted power by a validator-update tx
    mid-chain, and then actively signs commits (reference:
    state_test.go TestValSetChanges family + the e2e validator-update
    manifests)."""

    async def go():
        privs = [
            PrivKeyEd25519.from_seed(bytes([i + 140]) * 32)
            for i in range(2)
        ]
        joiner_priv = PrivKeyEd25519.from_seed(b"\x8f" * 32)
        genesis = make_genesis(privs)  # joiner NOT in genesis
        net = MemoryNetwork()
        nodes = make_mesh(tmp_path, genesis, privs + [joiner_priv], net)
        for n in nodes:
            await n.start()
        try:
            await nodes[0].consensus.wait_for_height(2, timeout=60.0)
            # grant the joiner power via the kvstore validator tx
            pk_hex = joiner_priv.pub_key().bytes().hex()
            res = await nodes[0].mempool.check_tx(
                f"val:{pk_hex}!5".encode()
            )
            assert res.is_ok, res.log  # fail fast on tx rejection
            joiner_addr = joiner_priv.pub_key().address()

            deadline = time.monotonic() + 120.0
            signed = False
            scanned = 1  # incremental: never rescan old commits
            while time.monotonic() < deadline and not signed:
                await asyncio.sleep(0.3)
                store = nodes[0].block_store
                for h in range(scanned + 1, store.height() + 1):
                    commit = store.load_block_commit(h)
                    if commit is None:
                        break
                    scanned = h
                    if any(
                        sig.validator_address == joiner_addr
                        and sig.is_for_block()
                        for sig in commit.signatures
                    ):
                        signed = True
                        break
            assert signed, "joiner never signed a commit"
            # and the joiner's own chain agrees with the originals
            h = min(
                nodes[0].block_store.height(),
                nodes[2].block_store.height(),
            ) - 1
            assert (
                nodes[0].block_store.load_block(h).hash()
                == nodes[2].block_store.load_block(h).hash()
            )
        finally:
            for n in nodes:
                await n.stop()

    run(go())
