"""FilePV double-sign protection tests (reference: privval/file_test.go)."""

import asyncio
import json
import os

import pytest

from tendermint_tpu.crypto import faults
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.privval import FilePV, MockPV
from tendermint_tpu.privval.file import (
    STEP_PRECOMMIT,
    STEP_PREVOTE,
    STEP_PROPOSE,
)
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


def make_block_id(h=b"\x01" * 32) -> BlockID:
    return BlockID(hash=h, part_set_header=PartSetHeader(1, b"\x02" * 32))


def make_vote(height=1, round_=0, type_=PREVOTE_TYPE, block_id=None, addr=b"\x00" * 20):
    return Vote(
        type=type_,
        height=height,
        round=round_,
        block_id=block_id if block_id is not None else make_block_id(),
        validator_address=addr,
        validator_index=0,
    )


@pytest.fixture
def pv(tmp_path):
    return FilePV.generate(
        str(tmp_path / "priv_key.json"), str(tmp_path / "priv_state.json")
    )


def test_generate_save_load_roundtrip(tmp_path, pv):
    pv.save()
    loaded = FilePV.load(pv.key.file_path, pv.last_sign_state.file_path)
    assert loaded.key.priv_key.bytes() == pv.key.priv_key.bytes()
    assert loaded.key.address == pv.key.address


def test_load_or_generate_is_stable(tmp_path):
    k, s = str(tmp_path / "k.json"), str(tmp_path / "s.json")
    a = FilePV.load_or_generate(k, s)
    b = FilePV.load_or_generate(k, s)
    assert a.key.address == b.key.address


def test_sign_vote_and_verify(pv):
    vote = make_vote(addr=pv.key.address)
    run(pv.sign_vote("test-chain", vote))
    vote.verify("test-chain", pv.key.pub_key)


def test_sign_proposal_and_verify(pv):
    prop = Proposal(height=1, round=0, block_id=make_block_id())
    run(pv.sign_proposal("test-chain", prop))
    assert prop.verify("test-chain", pv.key.pub_key)


def test_same_hrs_reuses_signature(pv):
    v1 = make_vote(addr=pv.key.address)
    run(pv.sign_vote("c", v1))
    # Same vote, different timestamp → same signature + timestamp reused.
    v2 = make_vote(addr=pv.key.address)
    v2.timestamp_ns = v1.timestamp_ns + 1_000_000_000
    run(pv.sign_vote("c", v2))
    assert v2.signature == v1.signature
    assert v2.timestamp_ns == v1.timestamp_ns


def test_conflicting_vote_same_hrs_refused(pv):
    v1 = make_vote(addr=pv.key.address)
    run(pv.sign_vote("c", v1))
    v2 = make_vote(addr=pv.key.address, block_id=make_block_id(b"\x03" * 32))
    with pytest.raises(ValueError, match="conflicting data"):
        run(pv.sign_vote("c", v2))


def test_height_regression_refused(pv):
    run(pv.sign_vote("c", make_vote(height=10, addr=pv.key.address)))
    with pytest.raises(ValueError, match="height regression"):
        run(pv.sign_vote("c", make_vote(height=9, addr=pv.key.address)))


def test_round_regression_refused(pv):
    run(pv.sign_vote("c", make_vote(height=5, round_=3, addr=pv.key.address)))
    with pytest.raises(ValueError, match="round regression"):
        run(pv.sign_vote("c", make_vote(height=5, round_=2, addr=pv.key.address)))


def test_step_regression_refused(pv):
    v = make_vote(height=5, type_=PRECOMMIT_TYPE, addr=pv.key.address)
    run(pv.sign_vote("c", v))
    with pytest.raises(ValueError, match="step regression"):
        run(pv.sign_vote("c", make_vote(height=5, type_=PREVOTE_TYPE, addr=pv.key.address)))


def test_step_order_propose_prevote_precommit(pv):
    prop = Proposal(height=7, round=0, block_id=make_block_id())
    run(pv.sign_proposal("c", prop))
    run(pv.sign_vote("c", make_vote(height=7, type_=PREVOTE_TYPE, addr=pv.key.address)))
    run(pv.sign_vote("c", make_vote(height=7, type_=PRECOMMIT_TYPE, addr=pv.key.address)))
    assert pv.last_sign_state.step == STEP_PRECOMMIT


def test_state_survives_crash(tmp_path):
    """Signature released then process restarts: the reloaded signer must
    still refuse to sign a conflicting vote at the same HRS."""
    k, s = str(tmp_path / "k.json"), str(tmp_path / "s.json")
    pv = FilePV.generate(k, s)
    pv.save()
    run(pv.sign_vote("c", make_vote(height=3, addr=pv.key.address)))

    pv2 = FilePV.load(k, s)
    assert pv2.last_sign_state.height == 3
    assert pv2.last_sign_state.step == STEP_PREVOTE
    with pytest.raises(ValueError, match="conflicting data"):
        run(pv2.sign_vote("c", make_vote(height=3, block_id=make_block_id(b"\x09" * 32), addr=pv.key.address)))


def test_nil_vote_signing(pv):
    v = make_vote(block_id=BlockID(), addr=pv.key.address)
    run(pv.sign_vote("c", v))
    v.verify("c", pv.key.pub_key)


def test_mockpv_signs():
    pv = MockPV()
    v = make_vote()
    run(pv.sign_vote("c", v))
    pub = run(pv.get_pub_key())
    v.validator_address = pub.address()
    v.verify("c", pub)


def test_load_missing_state_file_refused(tmp_path, pv):
    """A lost state file must not silently disable double-sign protection."""
    pv.key.save()
    with pytest.raises(FileNotFoundError):
        FilePV.load(pv.key.file_path, pv.last_sign_state.file_path)
    # the explicit escape hatch still works
    pv2 = FilePV.load_empty_state(pv.key.file_path, pv.last_sign_state.file_path)
    assert pv2.last_sign_state.height == 0


def test_key_file_permissions(tmp_path, pv):
    pv.save()
    assert os.stat(pv.key.file_path).st_mode & 0o777 == 0o600
    assert os.stat(pv.last_sign_state.file_path).st_mode & 0o777 == 0o600


def test_state_file_is_json(tmp_path, pv):
    run(pv.sign_vote("c", make_vote(addr=pv.key.address)))
    with open(pv.last_sign_state.file_path) as f:
        raw = json.load(f)
    assert raw["height"] == 1 and raw["step"] == STEP_PREVOTE
    assert len(bytes.fromhex(raw["signature"])) == 64


def test_sigkill_between_fsync_and_broadcast_resends_same_vote(tmp_path):
    """THE double-sign-protection regression (ISSUE 18 acceptance
    criterion): kill the validator between the last-sign-state fsync
    and the vote leaving the process, restart, and the signer must
    re-release the IDENTICAL signature — and refuse a conflicting
    block at that HRS forever. Fails if either the atomic-save or the
    fsync-before-sign ordering in FilePV._sign_vote is broken."""
    k, s = str(tmp_path / "k.json"), str(tmp_path / "s.json")
    pv = FilePV.generate(k, s)
    pv.save()

    vote = make_vote(height=4, addr=pv.key.address)
    vote.timestamp_ns = 1_700_000_000_000_000_000
    # the SIGKILL seam: privval.release fires AFTER _save_signed (state
    # durably on disk) and BEFORE vote.signature is set (nothing ever
    # broadcast) — exactly a crash between fsync and send
    with faults.inject("privval.release", "raise", times=1):
        with pytest.raises(faults.DeviceFault):
            run(pv.sign_vote("c", vote))
    assert vote.signature == b""  # the signature never escaped

    # ...but the checkpoint DID hit disk before the crash
    restarted = FilePV.load(k, s)
    assert restarted.last_sign_state.height == 4
    assert restarted.last_sign_state.step == STEP_PREVOTE
    saved_sig = restarted.last_sign_state.signature
    assert saved_sig

    # restart path: the same vote is re-signed byte-identically (the
    # saved signature is re-released, no second signing event)
    revote = make_vote(height=4, addr=pv.key.address)
    revote.timestamp_ns = vote.timestamp_ns
    run(restarted.sign_vote("c", revote))
    assert revote.signature == saved_sig
    assert revote.verify("c", pv.key.pub_key) is None  # raises on bad sig

    # and a CONFLICTING block at the same HRS is refused outright
    evil = make_vote(
        height=4, block_id=make_block_id(b"\x66" * 32), addr=pv.key.address
    )
    with pytest.raises(ValueError, match="conflicting data"):
        run(restarted.sign_vote("c", evil))


def test_save_io_error_withholds_signature(tmp_path):
    """An fsync failure on the checkpoint (privval.save io_error) must
    abort the signing — the signature never escapes with an unpersisted
    HRS, so a crash-restart cannot be tricked into double-signing."""
    k, s = str(tmp_path / "k.json"), str(tmp_path / "s.json")
    pv = FilePV.generate(k, s)
    pv.save()
    vote = make_vote(height=2, addr=pv.key.address)
    with faults.inject("privval.save", "io_error", times=1):
        with pytest.raises(OSError):
            run(pv.sign_vote("c", vote))
    assert vote.signature == b""
    # disk still holds the pre-sign state; a reload signs cleanly
    reloaded = FilePV.load(k, s)
    assert reloaded.last_sign_state.height == 0
    run(reloaded.sign_vote("c", make_vote(height=2, addr=pv.key.address)))


def test_privval_fault_key_targets_one_node(tmp_path):
    """The privval.* points are keyed by node-home basename so a chaos
    rule can crash load1's signer while load0 keeps signing."""
    homes = {}
    for name in ("load0", "load1"):
        d = tmp_path / name / "data"
        d.mkdir(parents=True)
        homes[name] = FilePV.generate(
            str(tmp_path / name / "k.json"),
            str(d / "priv_validator_state.json"),
        )
    with faults.inject("privval.release", "raise", key="load1"):
        run(homes["load0"].sign_vote(
            "c", make_vote(addr=homes["load0"].key.address)
        ))  # untargeted node unaffected
        with pytest.raises(faults.DeviceFault):
            run(homes["load1"].sign_vote(
                "c", make_vote(addr=homes["load1"].key.address)
            ))


def test_torn_tmp_file_is_harmless(tmp_path):
    """A crash mid-atomic-write leaves only <state>.tmp debris; the
    real state file is untouched and the reloaded signer keeps its
    double-sign checkpoint."""
    k, s = str(tmp_path / "k.json"), str(tmp_path / "s.json")
    pv = FilePV.generate(k, s)
    pv.save()
    run(pv.sign_vote("c", make_vote(height=6, addr=pv.key.address)))
    # simulate the torn temp file a crash during the NEXT save leaves
    with open(s + ".tmp", "w") as f:
        f.write('{"height": 99, "round"')  # truncated json
    reloaded = FilePV.load(k, s)
    assert reloaded.last_sign_state.height == 6


def test_secp256k1_file_pv_round_trip(tmp_path):
    """reference privval/file.go:188 GenFilePV supports secp256k1;
    generate, sign a vote, persist, reload, and verify the signature
    with the reloaded public key."""
    from tendermint_tpu.privval.file import FilePV
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.vote import Vote, PRECOMMIT_TYPE

    key_path = str(tmp_path / "pv_key.json")
    state_path = str(tmp_path / "pv_state.json")
    pv = FilePV.generate(key_path, state_path, key_type="secp256k1")
    assert pv.key.pub_key.type() == "secp256k1"
    pv.save()

    reloaded = FilePV.load(key_path, state_path)
    assert reloaded.key.pub_key.bytes() == pv.key.pub_key.bytes()

    vote = Vote(
        type=PRECOMMIT_TYPE,
        height=5,
        round=0,
        block_id=BlockID(
            hash=b"\x31" * 32,
            part_set_header=PartSetHeader(total=1, hash=b"\x32" * 32),
        ),
        timestamp_ns=1_700_000_000_000_000_000,
        validator_address=pv.key.address,
        validator_index=0,
    )
    run(reloaded.sign_vote("secp-chain", vote))
    assert vote.signature
    sb = vote.sign_bytes("secp-chain")
    assert pv.key.pub_key.verify_signature(sb, vote.signature)
    # unsupported types still rejected
    with pytest.raises(ValueError):
        FilePV.generate(str(tmp_path / "x"), str(tmp_path / "y"), "sr25519x")


# -- secret redaction (tmct ct-leak-telemetry lifetime contract) --


def test_repr_never_renders_key_material(pv, tmp_path):
    """reprs reach logs, tracebacks, assertion messages, and debugger
    output. The PrivKey base redacts itself, FilePVKey/NodeKey exclude
    the field from their generated __repr__ — none of the renderings
    may contain the seed or its hex."""
    from tendermint_tpu.node.key import NodeKey

    priv = pv.key.priv_key
    raw = priv.bytes()
    needles = (raw.hex(), raw.hex().upper(), repr(raw))
    for rendering in (
        repr(priv),
        str(priv),
        repr(pv.key),
        f"{pv.key}",
        repr(NodeKey(priv_key=PrivKeyEd25519.generate())),
    ):
        for needle in needles:
            assert needle not in rendering
    assert "redacted" in repr(priv)
    # the PUBLIC half still renders usefully
    assert pv.key.pub_key.bytes().hex()[:16] in repr(pv.key.pub_key)


def test_repr_redaction_covers_every_key_class(tmp_path):
    from tendermint_tpu.crypto.keys import generate_priv_key

    for key_type in ("ed25519", "secp256k1"):
        sk = generate_priv_key(key_type)
        assert sk.bytes().hex() not in repr(sk)
        assert "redacted" in repr(sk)


def test_double_sign_refusal_error_has_no_key_material(pv):
    """The HRS-regression ValueError text reaches logs and RPC error
    surfaces — it must name heights and steps, never the key."""
    vote = make_vote(height=5, round_=1)
    run(pv.sign_vote("chain", vote))
    stale = make_vote(height=4, round_=0)
    with pytest.raises(ValueError) as exc_info:
        run(pv.sign_vote("chain", stale))
    text = str(exc_info.value)
    assert "height regression" in text
    assert pv.key.priv_key.bytes().hex() not in text
