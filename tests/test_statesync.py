"""State sync tests — a fresh node restores an app snapshot, verifies
it against fetched light blocks, block-syncs the remainder, and follows
consensus (reference model: internal/statesync/syncer_test.go,
reactor_test.go)."""

import asyncio
import time

import pytest

from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.p2p.p2ptest import TestNetwork
from tendermint_tpu.statesync import (
    ChunkRequestMessage,
    ChunkResponseMessage,
    LightBlockRequestMessage,
    SnapshotsRequestMessage,
    SnapshotsResponseMessage,
    StatesyncCodec,
)
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

from .test_reactors import CHAIN, FullNode


def run(coro):
    return asyncio.run(coro)


def test_statesync_codec_roundtrip():
    for msg in (
        SnapshotsRequestMessage(),
        SnapshotsResponseMessage(height=5, format=1, chunks=3, hash=b"\x01" * 32),
        ChunkRequestMessage(height=5, format=1, index=2),
        ChunkResponseMessage(height=5, format=1, index=2, chunk=b"data"),
        LightBlockRequestMessage(height=9),
    ):
        assert StatesyncCodec.decode(StatesyncCodec.encode(msg)) == msg


def test_sync_requires_trust_root():
    """State sync must refuse to run without an operator trust anchor
    (reference: config.go:811-895)."""

    async def go():
        from tendermint_tpu.statesync import SyncError
        from tendermint_tpu.statesync.reactor import (
            CHUNK_CHANNEL,
            LIGHT_BLOCK_CHANNEL,
            PARAMS_CHANNEL,
            SNAPSHOT_CHANNEL,
            StatesyncReactor,
        )

        reactor = StatesyncReactor(
            CHAIN, None, None, None, None,
            {
                SNAPSHOT_CHANNEL: None, CHUNK_CHANNEL: None,
                LIGHT_BLOCK_CHANNEL: None, PARAMS_CHANNEL: None,
            },
            asyncio.Queue(),
        )
        try:
            await reactor.sync()
        except SyncError as e:
            assert "trust_height" in str(e)
        else:
            raise AssertionError("sync() succeeded without a trust root")

    run(go())


def test_fresh_node_state_syncs_then_follows():
    async def go():
        privs = [PrivKeyEd25519.from_seed(bytes([i + 100]) * 32) for i in range(4)]
        genesis = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=time.time_ns(),
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs
            ],
        )
        net = TestNetwork(5, chain_id=CHAIN)
        validators = [
            FullNode(net.nodes[i], privs[i], genesis) for i in range(4)
        ]
        fresh = FullNode(net.nodes[4], None, genesis, state_sync=True)

        for v in validators:
            await v.start()
        await net.start()
        try:
            # chain advances; snapshot taken at some height
            await asyncio.gather(
                *(v.cs.wait_for_height(5, timeout=90.0) for v in validators)
            )
            snaps = [v.app.take_snapshot() for v in validators]
            snap_height = snaps[0].height
            assert snap_height >= 3
            # keep going so light blocks at h+1, h+2 exist
            await asyncio.gather(
                *(
                    v.cs.wait_for_height(snap_height + 5, timeout=90.0)
                    for v in validators
                )
            )

            await fresh.start()
            # operator supplies the trust root out-of-band
            fresh.ss_reactor.cfg.trust_height = 1
            fresh.ss_reactor.cfg.trust_hash = (
                validators[0].block_store.load_block_meta(1).header.hash().hex()
            )
            state = await asyncio.wait_for(fresh.ss_reactor.sync(), 60.0)
            assert state.last_block_height == snap_height
            # the app was restored without replaying blocks
            assert fresh.app.height == snap_height
            assert fresh.app.app_hash == state.app_hash

            # stored signed header at the base
            assert fresh.block_store.load_block_meta(snap_height) is not None

            # block sync the rest, then follow consensus
            await fresh.bs_reactor.start_sync(state)

            async def synced():
                while not fresh.bs_reactor.synced:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(synced(), 60.0)
            target = validators[0].cs.rs.height + 2
            await fresh.cs.wait_for_height(target, timeout=60.0)
        finally:
            for v in validators:
                await v.stop()
            await fresh.stop()
            await net.stop()

        # chains agree above the snapshot base
        for h in range(snap_height + 1, snap_height + 3):
            assert (
                fresh.block_store.load_block(h).hash()
                == validators[0].block_store.load_block(h).hash()
            )

    run(go())


def test_chunk_queue_spools_to_disk(tmp_path):
    """ChunkQueue holds chunk bytes on disk, not in memory: put/get
    roundtrip, first-responder-wins, discard deletes the file and
    rewinds the apply cursor, retry rewinds without deleting
    (reference: internal/statesync/chunks.go:33-54,88,160-214,303)."""
    import os

    from tendermint_tpu.statesync.chunks import ChunkQueue

    q = ChunkQueue(3, dir=str(tmp_path))
    try:
        assert q.put(0, b"a" * 100, sender="p1")
        assert not q.put(0, b"zzz", sender="p2")  # first responder wins
        assert q.put(1, b"b" * 100, sender="p2")
        assert q.put(2, b"c" * 100, sender="p3")
        assert q.get(0) == b"a" * 100 and q.sender(0) == "p1"
        assert q.missing() == set()
        # the bytes live in files under the queue dir
        qdir = q._dir
        assert len(os.listdir(qdir)) == 3
        # apply-cursor walk
        assert q.next_up() == 0
        q.mark_returned(0)
        assert q.next_up() == 1
        q.mark_returned(1)
        q.mark_returned(2)
        assert q.next_up() is None
        # retry rewinds without deleting
        q.retry(1)
        assert q.next_up() == 1 and q.has(1)
        q.mark_returned(1)
        # discard deletes + rewinds
        q.discard(0)
        assert not q.has(0) and q.next_up() == 0
        assert q.missing() == {0}
        assert len(os.listdir(qdir)) == 2
    finally:
        q.close()
    assert not os.path.exists(q._dir)


def test_apply_chunks_honors_refetch_and_retry():
    """The apply loop implements the app's control results over the
    on-disk queue: a refetch_chunks answer discards + re-fetches the
    named chunk and the app sees it again; RETRY re-applies the same
    chunk from disk (reference: syncer.go applyChunks :403-460)."""

    async def go():
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.statesync.chunks import ChunkQueue
        from tendermint_tpu.statesync.reactor import (
            CHUNK_CHANNEL,
            LIGHT_BLOCK_CHANNEL,
            PARAMS_CHANNEL,
            SNAPSHOT_CHANNEL,
            StatesyncReactor,
            _Snapshot,
        )

        reactor = StatesyncReactor(
            CHAIN, None, None, None, None,
            {
                SNAPSHOT_CHANNEL: None, CHUNK_CHANNEL: None,
                LIGHT_BLOCK_CHANNEL: None, PARAMS_CHANNEL: None,
            },
            asyncio.Queue(),
        )
        source = {i: b"chunk-%d" % i for i in range(4)}
        snapshot = _Snapshot(
            height=5, format=1, chunks=4, hash=b"h", metadata=b"",
            peers={"p1"},
        )

        refetched = []

        async def fake_fetch(snap, queue, indexes=None):
            for i in indexes if indexes is not None else range(snap.chunks):
                refetched.append(i)
                queue.put(i, source[i], sender="p1")

        reactor._fetch_chunks = fake_fetch

        applied = []

        class App:
            async def apply_snapshot_chunk(self, req):
                applied.append((req.index, req.chunk))
                # first sight of chunk 2: ask for chunk 1 again and retry
                if req.index == 2 and applied.count((2, source[2])) == 1:
                    return abci.ResponseApplySnapshotChunk(
                        result=abci.APPLY_CHUNK_RETRY,
                        refetch_chunks=(1,),
                    )
                return abci.ResponseApplySnapshotChunk(
                    result=abci.APPLY_CHUNK_ACCEPT
                )

        reactor.app = App()
        queue = ChunkQueue(4)
        try:
            await reactor._fetch_chunks(snapshot, queue)
            await reactor._apply_chunks(snapshot, queue)
        finally:
            queue.close()

        order = [i for i, _ in applied]
        # chunk 1 re-applied after its refetch, chunk 2 re-applied after
        # RETRY, then 3; every payload the app saw matches the source
        assert order == [0, 1, 2, 1, 2, 3], order
        assert all(c == source[i] for i, c in applied)
        # the refetch went through the fetch path for exactly chunk 1
        assert refetched == [0, 1, 2, 3, 1]

    run(go())


def test_restore_memory_independent_of_snapshot_size():
    """Peak Python memory during chunk apply stays O(one chunk) while
    the snapshot is 64x bigger — the point of the on-disk queue
    (reference: chunks.go tempdir spool)."""

    async def go():
        import tracemalloc

        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.statesync.chunks import ChunkQueue
        from tendermint_tpu.statesync.reactor import (
            CHUNK_CHANNEL,
            LIGHT_BLOCK_CHANNEL,
            PARAMS_CHANNEL,
            SNAPSHOT_CHANNEL,
            StatesyncReactor,
            _Snapshot,
        )

        chunk_mb = 1
        n_chunks = 64  # 64 MB snapshot
        chunk_size = chunk_mb << 20

        reactor = StatesyncReactor(
            CHAIN, None, None, None, None,
            {
                SNAPSHOT_CHANNEL: None, CHUNK_CHANNEL: None,
                LIGHT_BLOCK_CHANNEL: None, PARAMS_CHANNEL: None,
            },
            asyncio.Queue(),
        )
        snapshot = _Snapshot(
            height=5, format=1, chunks=n_chunks, hash=b"h", metadata=b"",
            peers={"p1"},
        )

        async def fake_fetch(snap, queue, indexes=None):
            # one chunk materialized at a time, spooled straight to disk
            for i in indexes if indexes is not None else range(snap.chunks):
                queue.put(i, bytes([i % 256]) * chunk_size, sender="p1")

        reactor._fetch_chunks = fake_fetch

        class App:
            async def apply_snapshot_chunk(self, req):
                assert len(req.chunk) == chunk_size
                return abci.ResponseApplySnapshotChunk(
                    result=abci.APPLY_CHUNK_ACCEPT
                )

        reactor.app = App()
        queue = ChunkQueue(n_chunks)
        try:
            tracemalloc.start()
            await reactor._fetch_chunks(snapshot, queue)
            await reactor._apply_chunks(snapshot, queue)
            _cur, peak = tracemalloc.get_traced_memory()
            tracemalloc.stop()
        finally:
            queue.close()
        # peak python allocation must be a few chunks, nowhere near the
        # 64 MB snapshot
        assert peak < 8 * chunk_size, f"peak {peak / 1e6:.1f} MB"

    run(go())


def test_backfill_stores_prior_headers():
    async def go():
        privs = [PrivKeyEd25519.from_seed(bytes([i + 100]) * 32) for i in range(4)]
        genesis = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=time.time_ns(),
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs
            ],
        )
        net = TestNetwork(5, chain_id=CHAIN)
        validators = [
            FullNode(net.nodes[i], privs[i], genesis) for i in range(4)
        ]
        fresh = FullNode(net.nodes[4], None, genesis, state_sync=True)
        for v in validators:
            await v.start()
        await net.start()
        try:
            await asyncio.gather(
                *(v.cs.wait_for_height(6, timeout=90.0) for v in validators)
            )
            snaps = [v.app.take_snapshot() for v in validators]
            snap_height = snaps[0].height
            await asyncio.gather(
                *(
                    v.cs.wait_for_height(snap_height + 4, timeout=90.0)
                    for v in validators
                )
            )
            await fresh.start()
            fresh.ss_reactor.cfg.trust_height = 1
            fresh.ss_reactor.cfg.trust_hash = (
                validators[0].block_store.load_block_meta(1).header.hash().hex()
            )
            state = await asyncio.wait_for(fresh.ss_reactor.sync(), 60.0)
            stored = await asyncio.wait_for(
                fresh.ss_reactor.backfill(state), 60.0
            )
            assert stored >= snap_height - 1  # back to height 1
            for h in range(1, snap_height):
                meta = fresh.block_store.load_block_meta(h)
                assert meta is not None and meta.header.height == h
                assert fresh.state_store.load_validators(h) is not None
        finally:
            for v in validators:
                await v.stop()
            await fresh.stop()
            await net.stop()

    run(go())


def _bare_reactor():
    from tendermint_tpu.statesync.reactor import (
        CHUNK_CHANNEL,
        LIGHT_BLOCK_CHANNEL,
        PARAMS_CHANNEL,
        SNAPSHOT_CHANNEL,
        StatesyncReactor,
    )

    return StatesyncReactor(
        CHAIN, None, None, None, None,
        {
            SNAPSHOT_CHANNEL: None, CHUNK_CHANNEL: None,
            LIGHT_BLOCK_CHANNEL: None, PARAMS_CHANNEL: None,
        },
        asyncio.Queue(),
    )


def test_apply_chunks_terminal_result_skips_refetch():
    """A terminal ABORT/REJECT answer fails the restore BEFORE any
    refetch goes to the network — fetches triggered after a terminal
    result would be thrown away (ADVICE r4; reference: syncer.go
    applyChunks checks results before honoring refetch)."""

    async def go():
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.statesync.chunks import ChunkQueue
        from tendermint_tpu.statesync.reactor import SyncError, _Snapshot

        reactor = _bare_reactor()
        snapshot = _Snapshot(
            height=5, format=1, chunks=3, hash=b"h", metadata=b"",
            peers={"p1"},
        )
        fetches = []

        async def fake_fetch(snap, queue, indexes=None):
            fetches.append(list(indexes) if indexes is not None else "all")
            for i in (indexes if indexes is not None else range(3)):
                queue.put(i, b"c%d" % i, sender="p1")

        reactor._fetch_chunks = fake_fetch

        class App:
            async def apply_snapshot_chunk(self, req):
                return abci.ResponseApplySnapshotChunk(
                    result=abci.APPLY_CHUNK_ABORT,
                    refetch_chunks=(0, 1),
                )

        reactor.app = App()
        queue = ChunkQueue(3)
        try:
            await reactor._fetch_chunks(snapshot, queue, indexes=range(3))
            with pytest.raises(SyncError):
                await reactor._apply_chunks(snapshot, queue)
        finally:
            queue.close()
        # only the initial fetch — the refetch after ABORT never ran
        assert fetches == [[0, 1, 2]], fetches

    run(go())


def test_apply_chunks_out_of_range_refetch_is_sync_error():
    """A misbehaving app naming an out-of-range refetch index fails the
    restore as a SyncError instead of crashing the reactor with a bare
    IndexError (ADVICE r4)."""

    async def go():
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.statesync.chunks import ChunkQueue
        from tendermint_tpu.statesync.reactor import SyncError, _Snapshot

        reactor = _bare_reactor()
        snapshot = _Snapshot(
            height=5, format=1, chunks=2, hash=b"h", metadata=b"",
            peers={"p1"},
        )

        async def fake_fetch(snap, queue, indexes=None):
            for i in (indexes if indexes is not None else range(2)):
                queue.put(i, b"c%d" % i, sender="p1")

        reactor._fetch_chunks = fake_fetch

        class App:
            async def apply_snapshot_chunk(self, req):
                return abci.ResponseApplySnapshotChunk(
                    result=abci.APPLY_CHUNK_ACCEPT,
                    refetch_chunks=(7,),
                )

        reactor.app = App()
        queue = ChunkQueue(2)
        try:
            await reactor._fetch_chunks(snapshot, queue, indexes=range(2))
            with pytest.raises(SyncError, match="out-of-range"):
                await reactor._apply_chunks(snapshot, queue)
        finally:
            queue.close()

    run(go())


def test_apply_chunks_reject_senders_banned_and_refetched():
    """ResponseApplySnapshotChunk.reject_senders bans the flagged peer
    for the rest of the restore — its pending chunks are discarded and
    re-fetched from other providers, and the fetch path skips it
    (ADVICE r4; reference: syncer.go:431-441)."""

    async def go():
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.statesync.chunks import ChunkQueue
        from tendermint_tpu.statesync.reactor import _Snapshot

        reactor = _bare_reactor()
        snapshot = _Snapshot(
            height=5, format=1, chunks=3, hash=b"h", metadata=b"",
            peers={"good", "bad"},
        )
        refetched = []

        async def fake_fetch(snap, queue, indexes=None):
            # mirrors the real fetch path's sender filter
            providers = [
                p for p in sorted(snap.peers)
                if p not in reactor._rejected_senders
            ]
            for i in (indexes if indexes is not None else range(3)):
                refetched.append((i, tuple(providers)))
                queue.put(i, b"fresh-%d" % i, sender=providers[0])

        reactor._fetch_chunks = fake_fetch

        seen = []

        class App:
            async def apply_snapshot_chunk(self, req):
                seen.append((req.index, req.sender, req.chunk))
                if req.index == 0:
                    # chunk 0 is fine but the app flags peer "bad"
                    return abci.ResponseApplySnapshotChunk(
                        result=abci.APPLY_CHUNK_ACCEPT,
                        reject_senders=("bad",),
                    )
                return abci.ResponseApplySnapshotChunk(
                    result=abci.APPLY_CHUNK_ACCEPT
                )

        reactor.app = App()
        queue = ChunkQueue(3)
        try:
            # initial state: chunk 0 from "good", 1 and 2 from "bad"
            queue.put(0, b"ok-0", sender="good")
            queue.put(1, b"bad-1", sender="bad")
            queue.put(2, b"bad-2", sender="bad")
            await reactor._apply_chunks(snapshot, queue)
        finally:
            queue.close()

        assert "bad" in reactor._rejected_senders
        # chunks 1 and 2 were re-fetched with "bad" excluded
        assert refetched == [(1, ("good",)), (2, ("good",))], refetched
        # the app never saw the rejected sender's payloads again
        assert seen[0] == (0, "good", b"ok-0")
        assert seen[1:] == [
            (1, "good", b"fresh-1"), (2, "good", b"fresh-2")
        ], seen

    run(go())


def test_fetch_chunks_real_path_skips_rejected_senders():
    """The REAL _fetch_chunks provider loop (not a stub) excludes
    rejected senders: with one peer banned, every chunk request goes to
    the remaining provider; with all peers banned it raises SyncError
    instead of asking the banned peer again."""

    async def go():
        from tendermint_tpu.statesync.reactor import SyncError, _Snapshot

        reactor = _bare_reactor()
        snapshot = _Snapshot(
            height=5, format=1, chunks=2, hash=b"h", metadata=b"",
            peers={"good", "bad"},
        )
        reactor._rejected_senders.add("bad")
        asked = []

        class ChunkCh:
            def try_send(self, env):
                asked.append(env.to)
                # resolve the matching waiter like the network would
                key = (
                    env.to, env.message.height, env.message.format,
                    env.message.index,
                )
                fut = reactor._chunk_waiters.pop(key)

                class Res:
                    missing = False
                    chunk = b"payload-%d" % env.message.index

                fut.set_result(Res())

        reactor.chunk_ch = ChunkCh()

        from tendermint_tpu.statesync.chunks import ChunkQueue

        queue = ChunkQueue(2)
        try:
            await reactor._fetch_chunks(snapshot, queue)
            assert queue.has(0) and queue.has(1)
        finally:
            queue.close()
        assert asked and all(p == "good" for p in asked), asked

        # all providers banned -> SyncError, no request to anyone
        reactor._rejected_senders.add("good")
        asked.clear()
        queue2 = ChunkQueue(1)
        try:
            with pytest.raises(SyncError, match="no remaining"):
                await reactor._fetch_chunks(snapshot, queue2)
        finally:
            queue2.close()
        assert asked == []

    run(go())
