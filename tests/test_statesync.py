"""State sync tests — a fresh node restores an app snapshot, verifies
it against fetched light blocks, block-syncs the remainder, and follows
consensus (reference model: internal/statesync/syncer_test.go,
reactor_test.go)."""

import asyncio
import time

from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.p2p.p2ptest import TestNetwork
from tendermint_tpu.statesync import (
    ChunkRequestMessage,
    ChunkResponseMessage,
    LightBlockRequestMessage,
    SnapshotsRequestMessage,
    SnapshotsResponseMessage,
    StatesyncCodec,
)
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

from .test_reactors import CHAIN, FullNode


def run(coro):
    return asyncio.run(coro)


def test_statesync_codec_roundtrip():
    for msg in (
        SnapshotsRequestMessage(),
        SnapshotsResponseMessage(height=5, format=1, chunks=3, hash=b"\x01" * 32),
        ChunkRequestMessage(height=5, format=1, index=2),
        ChunkResponseMessage(height=5, format=1, index=2, chunk=b"data"),
        LightBlockRequestMessage(height=9),
    ):
        assert StatesyncCodec.decode(StatesyncCodec.encode(msg)) == msg


def test_sync_requires_trust_root():
    """State sync must refuse to run without an operator trust anchor
    (reference: config.go:811-895)."""

    async def go():
        from tendermint_tpu.statesync import SyncError
        from tendermint_tpu.statesync.reactor import (
            CHUNK_CHANNEL,
            LIGHT_BLOCK_CHANNEL,
            PARAMS_CHANNEL,
            SNAPSHOT_CHANNEL,
            StatesyncReactor,
        )

        reactor = StatesyncReactor(
            CHAIN, None, None, None, None,
            {
                SNAPSHOT_CHANNEL: None, CHUNK_CHANNEL: None,
                LIGHT_BLOCK_CHANNEL: None, PARAMS_CHANNEL: None,
            },
            asyncio.Queue(),
        )
        try:
            await reactor.sync()
        except SyncError as e:
            assert "trust_height" in str(e)
        else:
            raise AssertionError("sync() succeeded without a trust root")

    run(go())


def test_fresh_node_state_syncs_then_follows():
    async def go():
        privs = [PrivKeyEd25519.from_seed(bytes([i + 100]) * 32) for i in range(4)]
        genesis = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=time.time_ns(),
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs
            ],
        )
        net = TestNetwork(5, chain_id=CHAIN)
        validators = [
            FullNode(net.nodes[i], privs[i], genesis) for i in range(4)
        ]
        fresh = FullNode(net.nodes[4], None, genesis, state_sync=True)

        for v in validators:
            await v.start()
        await net.start()
        try:
            # chain advances; snapshot taken at some height
            await asyncio.gather(
                *(v.cs.wait_for_height(5, timeout=90.0) for v in validators)
            )
            snaps = [v.app.take_snapshot() for v in validators]
            snap_height = snaps[0].height
            assert snap_height >= 3
            # keep going so light blocks at h+1, h+2 exist
            await asyncio.gather(
                *(
                    v.cs.wait_for_height(snap_height + 5, timeout=90.0)
                    for v in validators
                )
            )

            await fresh.start()
            # operator supplies the trust root out-of-band
            fresh.ss_reactor.cfg.trust_height = 1
            fresh.ss_reactor.cfg.trust_hash = (
                validators[0].block_store.load_block_meta(1).header.hash().hex()
            )
            state = await asyncio.wait_for(fresh.ss_reactor.sync(), 60.0)
            assert state.last_block_height == snap_height
            # the app was restored without replaying blocks
            assert fresh.app.height == snap_height
            assert fresh.app.app_hash == state.app_hash

            # stored signed header at the base
            assert fresh.block_store.load_block_meta(snap_height) is not None

            # block sync the rest, then follow consensus
            await fresh.bs_reactor.start_sync(state)

            async def synced():
                while not fresh.bs_reactor.synced:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(synced(), 60.0)
            target = validators[0].cs.rs.height + 2
            await fresh.cs.wait_for_height(target, timeout=60.0)
        finally:
            for v in validators:
                await v.stop()
            await fresh.stop()
            await net.stop()

        # chains agree above the snapshot base
        for h in range(snap_height + 1, snap_height + 3):
            assert (
                fresh.block_store.load_block(h).hash()
                == validators[0].block_store.load_block(h).hash()
            )

    run(go())


def test_backfill_stores_prior_headers():
    async def go():
        privs = [PrivKeyEd25519.from_seed(bytes([i + 100]) * 32) for i in range(4)]
        genesis = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=time.time_ns(),
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs
            ],
        )
        net = TestNetwork(5, chain_id=CHAIN)
        validators = [
            FullNode(net.nodes[i], privs[i], genesis) for i in range(4)
        ]
        fresh = FullNode(net.nodes[4], None, genesis, state_sync=True)
        for v in validators:
            await v.start()
        await net.start()
        try:
            await asyncio.gather(
                *(v.cs.wait_for_height(6, timeout=90.0) for v in validators)
            )
            snaps = [v.app.take_snapshot() for v in validators]
            snap_height = snaps[0].height
            await asyncio.gather(
                *(
                    v.cs.wait_for_height(snap_height + 4, timeout=90.0)
                    for v in validators
                )
            )
            await fresh.start()
            fresh.ss_reactor.cfg.trust_height = 1
            fresh.ss_reactor.cfg.trust_hash = (
                validators[0].block_store.load_block_meta(1).header.hash().hex()
            )
            state = await asyncio.wait_for(fresh.ss_reactor.sync(), 60.0)
            stored = await asyncio.wait_for(
                fresh.ss_reactor.backfill(state), 60.0
            )
            assert stored >= snap_height - 1  # back to height 1
            for h in range(1, snap_height):
                meta = fresh.block_store.load_block_meta(h)
                assert meta is not None and meta.header.height == h
                assert fresh.state_store.load_validators(h) is not None
        finally:
            for v in validators:
                await v.stop()
            await fresh.stop()
            await net.stop()

    run(go())
