"""Metrics tests: instrument semantics, exposition format, and a live
node serving real values on /metrics (reference model:
internal/consensus/metrics.go + docs/nodes/metrics.md catalog)."""

import asyncio
import time

from tendermint_tpu.libs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
)


class TestInstruments:
    def test_counter_and_labels(self):
        c = Counter("t_c", "help", label_names=("ch",))
        c.inc(ch=1)
        c.inc(5, ch=1)
        c.inc(ch=2)
        assert c.value(ch=1) == 6
        assert c.value(ch=2) == 1
        text = "\n".join(c.render())
        assert '# TYPE t_c counter' in text
        assert 't_c{ch="1"} 6' in text
        assert 't_c{ch="2"} 1' in text

    def test_gauge(self):
        g = Gauge("t_g", "help")
        g.set(3)
        g.add(2)
        assert g.value() == 5
        assert "t_g 5" in "\n".join(g.render())

    def test_histogram_buckets_and_exposition(self):
        h = Histogram("t_h", "help", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count() == 4
        assert abs(h.sum() - 55.55) < 1e-9
        text = "\n".join(h.render())
        assert 't_h_bucket{le="0.1"} 1' in text
        assert 't_h_bucket{le="1"} 2' in text
        assert 't_h_bucket{le="10"} 3' in text
        assert 't_h_bucket{le="+Inf"} 4' in text
        assert "t_h_count 4" in text

    def test_histogram_timer(self):
        h = Histogram("t_t", "help", buckets=(0.001, 10.0))
        with h.time():
            time.sleep(0.002)
        assert h.count() == 1
        assert 0.001 < h.sum() < 1.0

    def test_registry_idempotent_and_renders_all(self):
        r = Registry("ns")
        c1 = r.register(Counter("ns_a_total", "x"))
        c2 = r.register(Counter("ns_a_total", "x"))
        assert c1 is c2  # re-registration returns the original
        r.register(Gauge("ns_b", "y"))
        text = r.render()
        assert "ns_a_total" in text and "ns_b" in text


def test_node_serves_live_metrics(tmp_path):
    """Boot a node with instrumentation on; scrape /metrics over HTTP
    and find consensus height, p2p, state and device-verifier series."""
    from tendermint_tpu.config import Config
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
    from tendermint_tpu.node import make_node
    from tendermint_tpu.privval import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x71" * 32)
        genesis = GenesisDoc(
            chain_id="metrics-chain",
            genesis_time_ns=time.time_ns(),
            validators=[GenesisValidator(pub_key=priv.pub_key(), power=10)],
        )
        cfg = Config()
        cfg.base.home = str(tmp_path / "m")
        cfg.base.chain_id = "metrics-chain"
        cfg.base.db_backend = "memdb"
        cfg.consensus.timeout_commit = 0.2
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.instrumentation.prometheus = True
        cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
        cfg.ensure_dirs()
        genesis.save_as(cfg.base.path(cfg.base.genesis_file))
        FilePV.from_priv_key(
            priv,
            cfg.base.path(cfg.priv_validator.key_file),
            cfg.base.path(cfg.priv_validator.state_file),
        ).save()
        node = make_node(cfg)
        await node.start()
        try:
            await node.consensus.wait_for_height(3, timeout=60.0)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", node.metrics_port
            )
            writer.write(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
            await writer.drain()
            data = await reader.read(-1)
            writer.close()
            text = data.decode()
            assert "200 OK" in text.splitlines()[0]
            # live values from the running node
            for needle in (
                "tendermint_tpu_consensus_height",
                "tendermint_tpu_consensus_total_txs",
                "tendermint_tpu_state_block_processing_seconds_count",
                "tendermint_tpu_p2p_peers",
                "tendermint_tpu_mempool_size",
            ):
                assert needle in text, needle
            # height gauge tracks the chain
            for line in text.splitlines():
                if line.startswith("tendermint_tpu_consensus_height "):
                    assert float(line.split()[-1]) >= 2
                    break
            else:
                raise AssertionError("height series missing")
        finally:
            await node.stop()

    asyncio.run(go())
