"""Metrics tests: instrument semantics, exposition format (with a
round-trip parser), registry conflict detection, per-node registry
isolation across an in-process localnet, and a live node serving real
values on /metrics + /healthz (reference model:
internal/consensus/metrics.go + docs/nodes/metrics.md catalog)."""

import asyncio
import json
import math
import random
import time

import pytest

from tendermint_tpu.libs.metrics import (
    Counter,
    Gauge,
    Histogram,
    LatencySketch,
    Registry,
)


def parse_exposition(text: str) -> dict:
    """Minimal Prometheus text-format (0.0.4) parser: series name with
    sorted labels → float value. Raises on lines it cannot parse, so a
    malformed scrape fails the round-trip loudly."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        metric, _, value = line.rpartition(" ")
        assert metric, f"unparseable exposition line: {line!r}"
        if "{" in metric:
            name, _, rest = metric.partition("{")
            labels_raw = rest.rstrip("}")
            labels = []
            for pair in labels_raw.split(","):
                k, _, v = pair.partition("=")
                assert v.startswith('"') and v.endswith('"'), line
                labels.append((k, v[1:-1]))
            key = name + "{" + ",".join(
                f"{k}={v}" for k, v in sorted(labels)
            ) + "}"
        else:
            key = metric
        out[key] = float(value) if value != "+Inf" else float("inf")
    return out


class TestInstruments:
    def test_counter_and_labels(self):
        c = Counter("t_c", "help", label_names=("ch",))
        c.inc(ch=1)
        c.inc(5, ch=1)
        c.inc(ch=2)
        assert c.value(ch=1) == 6
        assert c.value(ch=2) == 1
        text = "\n".join(c.render())
        assert '# TYPE t_c counter' in text
        assert 't_c{ch="1"} 6' in text
        assert 't_c{ch="2"} 1' in text

    def test_gauge(self):
        g = Gauge("t_g", "help")
        g.set(3)
        g.add(2)
        assert g.value() == 5
        assert "t_g 5" in "\n".join(g.render())

    def test_histogram_buckets_and_exposition(self):
        h = Histogram("t_h", "help", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count() == 4
        assert abs(h.sum() - 55.55) < 1e-9
        text = "\n".join(h.render())
        assert 't_h_bucket{le="0.1"} 1' in text
        assert 't_h_bucket{le="1"} 2' in text
        assert 't_h_bucket{le="10"} 3' in text
        assert 't_h_bucket{le="+Inf"} 4' in text
        assert "t_h_count 4" in text

    def test_histogram_timer(self):
        h = Histogram("t_t", "help", buckets=(0.001, 10.0))
        with h.time():
            time.sleep(0.002)
        assert h.count() == 1
        assert 0.001 < h.sum() < 1.0

    def test_registry_idempotent_and_renders_all(self):
        r = Registry("ns")
        c1 = r.register(Counter("ns_a_total", "x"))
        c2 = r.register(Counter("ns_a_total", "x"))
        assert c1 is c2  # re-registration returns the original
        r.register(Gauge("ns_b", "y"))
        text = r.render()
        assert "ns_a_total" in text and "ns_b" in text

    def test_register_conflict_raises(self):
        r = Registry("ns")
        r.counter("sub", "x_total", "help")
        # same spec: idempotent
        assert r.counter("sub", "x_total", "help") is r.get(
            "ns_sub_x_total"
        )
        with pytest.raises(ValueError):  # kind conflict
            r.gauge("sub", "x_total", "help")
        with pytest.raises(ValueError):  # label-name conflict
            r.counter("sub", "x_total", "help", label_names=("ch",))
        r.histogram("sub", "h_seconds", "help", buckets=(0.1, 1.0))
        with pytest.raises(ValueError):  # bucket conflict
            r.histogram("sub", "h_seconds", "help", buckets=(0.2, 1.0))

    def test_counter_rejects_negative_inc(self):
        c = Counter("t_mono", "help")
        c.inc(2)
        with pytest.raises(ValueError):
            c.inc(-1)
        assert c.value() == 2

    def test_exposition_round_trip(self):
        r = Registry("rt")
        c = r.counter("sub", "events_total", "e", label_names=("kind",))
        c.inc(3, kind="a")
        c.inc(kind='quo"te')  # escaping must survive the round trip
        g = r.gauge("sub", "level", "l")
        g.set(2.5)
        h = r.histogram("sub", "lat_seconds", "h", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        parsed = parse_exposition(r.render())
        assert parsed['rt_sub_events_total{kind=a}'] == 3
        assert parsed['rt_sub_events_total{kind=quo\\"te}'] == 1
        assert parsed["rt_sub_level"] == 2.5
        assert parsed['rt_sub_lat_seconds_bucket{le=0.1}'] == 1
        assert parsed['rt_sub_lat_seconds_bucket{le=1}'] == 2
        assert parsed['rt_sub_lat_seconds_bucket{le=+Inf}'] == 3
        assert parsed["rt_sub_lat_seconds_count"] == 3
        assert abs(parsed["rt_sub_lat_seconds_sum"] - 5.55) < 1e-9


class TestLatencySketch:
    """The mergeable log-bucketed sketch behind per-route latency
    (docs/metrics.md documents the bound these tests pin)."""

    EPS = 0.01  # the documented relative-error bound

    DISTRIBUTIONS = {
        # name -> generator over a seeded random.Random: the bound must
        # hold regardless of shape (uniform, heavy-tailed, spiky)
        "uniform": lambda r: r.uniform(1e-4, 2.0),
        "lognormal": lambda r: r.lognormvariate(-5.0, 2.0),
        "exponential": lambda r: r.expovariate(100.0),
        "bimodal": lambda r: (
            r.uniform(1e-3, 2e-3) if r.random() < 0.9 else r.uniform(0.5, 1.5)
        ),
    }

    @staticmethod
    def _oracle(sorted_vals, q):
        """Nearest-rank quantile — the same rank rule the sketch uses,
        so the comparison isolates bucketing error."""
        rank = max(1, math.ceil(q * len(sorted_vals)))
        return sorted_vals[rank - 1]

    @pytest.mark.parametrize("dist", sorted(DISTRIBUTIONS))
    @pytest.mark.parametrize("seed", [11, 23, 47])
    def test_quantile_accuracy_vs_sorted_oracle(self, dist, seed):
        r = random.Random(seed)
        gen = self.DISTRIBUTIONS[dist]
        vals = [gen(r) for _ in range(5000)]
        sk = LatencySketch(relative_error=self.EPS)
        for v in vals:
            sk.record(v)
        sv = sorted(vals)
        checked = 0
        for q in (0.0, 0.25, 0.5, 0.9, 0.99, 0.999, 1.0):
            oracle = self._oracle(sv, q)
            if not sk.min_value <= oracle <= sk.max_value:
                continue  # the bound is documented for in-range values
            est = sk.quantile(q)
            rel = abs(est - oracle) / oracle
            assert rel <= self.EPS + 1e-9, (dist, seed, q, est, oracle)
            checked += 1
        assert checked >= 5  # the skip must not hollow out the test
        assert sk.count == len(vals)
        assert abs(sk.sum - sum(vals)) < 1e-6

    def test_merge_associative_and_matches_single_sketch(self):
        r = random.Random(5)
        vals = [r.expovariate(50.0) for _ in range(6000)]
        whole = LatencySketch(relative_error=self.EPS)
        parts = [LatencySketch(relative_error=self.EPS) for _ in range(3)]
        for i, v in enumerate(vals):
            whole.record(v)
            parts[i % 3].record(v)
        a, b, c = parts
        left = a.snapshot().merge(b.snapshot()).merge(c.snapshot())
        right = a.snapshot().merge(b.snapshot().merge(c.snapshot()))
        # bucket counts are exactly associative (sums differ only by
        # float addition order)
        dl, dr = left.to_dict(), right.to_dict()
        assert dl["counts"] == dr["counts"]
        assert dl["count"] == dr["count"] == len(vals)
        assert abs(dl["sum"] - dr["sum"]) < 1e-6
        # a merged sketch answers exactly like the sketch that saw
        # everything — the property that makes per-worker recording
        # legitimate
        for q in (0.5, 0.9, 0.99, 0.999):
            assert left.quantile(q) == whole.quantile(q)
        assert left.min == whole.min and left.max == whole.max

    def test_merge_rejects_incompatible_parameters(self):
        a = LatencySketch(relative_error=0.01)
        b = LatencySketch(relative_error=0.02)
        with pytest.raises(ValueError):
            a.merge(b)
        c = LatencySketch(relative_error=0.01, min_value=1e-3)
        with pytest.raises(ValueError):
            a.merge(c)

    def test_bounded_memory_and_dict_round_trip(self):
        sk = LatencySketch(relative_error=self.EPS)
        r = random.Random(3)
        for _ in range(50_000):
            sk.record(r.expovariate(1.0))
        # bucket count is bounded by the index range, not by N
        max_buckets = (
            math.ceil(
                math.log(sk.max_value / sk.min_value)
                / math.log((1 + self.EPS) / (1 - self.EPS))
            )
            + 2
        )
        assert len(sk._counts) <= max_buckets
        rt = LatencySketch.from_dict(
            json.loads(json.dumps(sk.to_dict()))
        )
        for q in (0.5, 0.99, 0.999):
            assert rt.quantile(q) == sk.quantile(q)
        assert rt.count == sk.count

    def test_empty_and_out_of_range(self):
        sk = LatencySketch()
        assert sk.quantile(0.99) == 0.0
        assert sk.count == 0 and sk.min == 0.0 and sk.max == 0.0
        sk.record(0.0)  # clamps into the lowest bucket, never raises
        sk.record(1e12)  # clamps into the highest
        assert sk.count == 2

    def test_sketch_exposition_round_trip(self):
        """The registry instrument renders a summary the /metrics
        parser round-trips: per-label quantile series + _sum/_count."""
        r = Registry("rt")
        s = r.sketch(
            "rpc",
            "request_latency_seconds",
            "lat",
            label_names=("route",),
        )
        lat = [0.001, 0.002, 0.004, 0.008, 0.1]
        for v in lat:
            s.observe(v, route="block")
        s.observe(0.5, route="status")
        parsed = parse_exposition(r.render())
        name = "rt_rpc_request_latency_seconds"
        assert parsed[name + "_count{route=block}"] == len(lat)
        assert abs(
            parsed[name + "_sum{route=block}"] - sum(lat)
        ) < 1e-9
        p50 = parsed[name + "{quantile=0.5,route=block}"]
        assert abs(p50 - 0.004) / 0.004 <= self.EPS
        p999 = parsed[name + "{quantile=0.999,route=block}"]
        assert abs(p999 - 0.1) / 0.1 <= self.EPS
        assert parsed[name + "_count{route=status}"] == 1
        # live child is the real mergeable sketch
        merged = s.merged()
        assert merged.count == len(lat) + 1

    def test_registry_sketch_conflict_detection(self):
        r = Registry("ns")
        r.sketch("rpc", "lat", "h", relative_error=0.01)
        assert (
            r.sketch("rpc", "lat", "h", relative_error=0.01)
            is r.get("ns_rpc_lat")
        )
        with pytest.raises(ValueError):  # error-bound conflict
            r.sketch("rpc", "lat", "h", relative_error=0.05)
        with pytest.raises(ValueError):  # kind conflict
            r.counter("rpc", "lat", "h")


async def _http_get(port: int, path: str):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    data = await reader.read(-1)
    writer.close()
    head, _, body = data.decode().partition("\r\n\r\n")
    return head.splitlines()[0], body


def test_per_node_registry_isolation_localnet(tmp_path):
    """Acceptance: a 3-node in-process localnet yields three
    non-interleaved /metrics scrapes — each node's consensus_height is
    its OWN series on its OWN registry, every scrape parses cleanly,
    and /healthz + request-line parsing behave (a request merely
    containing the substring '/metrics' is NOT a scrape)."""
    pytest.importorskip("jax")
    from tendermint_tpu.config import Config
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
    from tendermint_tpu.node import NodeKey, make_node
    from tendermint_tpu.p2p.transport import MemoryNetwork, MemoryTransport
    from tendermint_tpu.privval import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    n_nodes = 3
    privs = [
        PrivKeyEd25519.from_seed(bytes([i + 170]) * 32)
        for i in range(n_nodes)
    ]
    genesis = GenesisDoc(
        chain_id="iso-chain",
        genesis_time_ns=time.time_ns(),
        validators=[
            GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs
        ],
    )
    net = MemoryNetwork()
    cfgs = []
    for i, priv in enumerate(privs):
        cfg = Config()
        cfg.base.home = str(tmp_path / f"iso{i}")
        cfg.base.chain_id = "iso-chain"
        cfg.base.db_backend = "memdb"
        cfg.consensus.timeout_propose = 2.0
        cfg.consensus.timeout_prevote = 1.0
        cfg.consensus.timeout_precommit = 1.0
        cfg.consensus.timeout_commit = 0.2
        cfg.consensus.peer_gossip_sleep_duration = 0.01
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.laddr = f"iso{i}:26656"
        cfg.instrumentation.prometheus = True
        cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
        cfg.ensure_dirs()
        genesis.save_as(cfg.base.path(cfg.base.genesis_file))
        FilePV.from_priv_key(
            priv,
            cfg.base.path(cfg.priv_validator.key_file),
            cfg.base.path(cfg.priv_validator.state_file),
        ).save()
        cfgs.append(cfg)
    node_ids = [
        NodeKey.load_or_generate(
            c.base.path(c.base.node_key_file)
        ).node_id
        for c in cfgs
    ]
    for i, cfg in enumerate(cfgs):
        cfg.p2p.persistent_peers = ",".join(
            f"{node_ids[j]}@iso{j}:26656"
            for j in range(n_nodes)
            if j != i
        )

    async def go():
        nodes = [
            make_node(c, transport=MemoryTransport(net, f"iso{i}:26656"))
            for i, c in enumerate(cfgs)
        ]
        for n in nodes:
            await n.start()
        try:
            await asyncio.gather(
                *(
                    n.consensus.wait_for_height(2, timeout=120.0)
                    for n in nodes
                )
            )
            scrapes = []
            for n in nodes:
                status, body = await _http_get(n.metrics_port, "/metrics")
                assert "200 OK" in status
                scrapes.append(body)
            # every scrape parses cleanly and carries exactly ONE
            # consensus_height series — its own
            for n, body in zip(nodes, scrapes):
                parsed = parse_exposition(body)
                heights = [
                    k
                    for k in parsed
                    if k == "tendermint_tpu_consensus_height"
                ]
                assert len(heights) == 1
                # the chain may advance between scrape and assert
                assert (
                    1
                    <= parsed["tendermint_tpu_consensus_height"]
                    <= n.consensus.rs.height
                )
                # merged exposition must not duplicate series
                assert (
                    body.count(
                        "# TYPE tendermint_tpu_consensus_height "
                    )
                    == 1
                )

            # /healthz: height + sync status as JSON
            status, body = await _http_get(nodes[0].metrics_port, "/healthz")
            assert "200 OK" in status
            health = json.loads(body)
            assert health["height"] >= 1
            assert health["syncing"] is False
            assert health["node_id"] == node_ids[0]

            # proper request-line matching: substring tricks are 404
            for path in ("/foo?x=/metrics", "/metricsfoo", "/nope"):
                status, _ = await _http_get(nodes[0].metrics_port, path)
                assert "404" in status, path
            status, _ = await _http_get(
                nodes[0].metrics_port, "/metrics?x=1"
            )
            assert "200 OK" in status

            # the registries are truly disjoint objects: a sentinel
            # write on node0 never shows up in node1's scrape
            regs = [n.metrics_registry for n in nodes]
            assert len({id(r) for r in regs}) == n_nodes
            nodes[0].consensus.metrics.height.set(99999)
            assert (
                "tendermint_tpu_consensus_height 99999"
                in regs[0].render()
            )
            for other in regs[1:]:
                assert (
                    "tendermint_tpu_consensus_height 99999"
                    not in other.render()
                )
        finally:
            for n in nodes:
                await n.stop()

    asyncio.run(go())


def test_node_serves_live_metrics(tmp_path):
    """Boot a node with instrumentation on; scrape /metrics over HTTP
    and find consensus height, p2p, state and device-verifier series."""
    from tendermint_tpu.config import Config
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
    from tendermint_tpu.node import make_node
    from tendermint_tpu.privval import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x71" * 32)
        genesis = GenesisDoc(
            chain_id="metrics-chain",
            genesis_time_ns=time.time_ns(),
            validators=[GenesisValidator(pub_key=priv.pub_key(), power=10)],
        )
        cfg = Config()
        cfg.base.home = str(tmp_path / "m")
        cfg.base.chain_id = "metrics-chain"
        cfg.base.db_backend = "memdb"
        cfg.consensus.timeout_commit = 0.2
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.instrumentation.prometheus = True
        cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
        cfg.ensure_dirs()
        genesis.save_as(cfg.base.path(cfg.base.genesis_file))
        FilePV.from_priv_key(
            priv,
            cfg.base.path(cfg.priv_validator.key_file),
            cfg.base.path(cfg.priv_validator.state_file),
        ).save()
        node = make_node(cfg)
        await node.start()
        try:
            await node.consensus.wait_for_height(3, timeout=60.0)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", node.metrics_port
            )
            writer.write(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
            await writer.drain()
            data = await reader.read(-1)
            writer.close()
            text = data.decode()
            assert "200 OK" in text.splitlines()[0]
            # live values from the running node
            for needle in (
                "tendermint_tpu_consensus_height",
                "tendermint_tpu_consensus_total_txs",
                "tendermint_tpu_state_block_processing_seconds_count",
                "tendermint_tpu_p2p_peers",
                "tendermint_tpu_mempool_size",
            ):
                assert needle in text, needle
            # height gauge tracks the chain
            for line in text.splitlines():
                if line.startswith("tendermint_tpu_consensus_height "):
                    assert float(line.split()[-1]) >= 2
                    break
            else:
                raise AssertionError("height series missing")
        finally:
            await node.stop()

    asyncio.run(go())
