"""sr25519 (schnorrkel over ristretto255) differential + seam tests
(reference model: crypto/sr25519/sr25519_test.go, plus merlin's and
RFC 9496's published vectors for the transcript/group layers)."""

import time

import pytest

from tendermint_tpu.crypto import ristretto as rst
from tendermint_tpu.crypto.batch import (
    create_batch_verifier,
    supports_batch_verifier,
)
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.crypto.merlin import Transcript
from tendermint_tpu.crypto.sr25519 import (
    PrivKeySr25519,
    PubKeySr25519,
    Sr25519BatchVerifier,
)
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE
from tendermint_tpu.types.commit import Commit, CommitSig
from tendermint_tpu.types.validation import (
    InvalidCommitError,
    verify_commit,
    verify_commit_light,
)
from tendermint_tpu.types.validator import Validator, ValidatorSet
from tendermint_tpu.types.vote import Vote


def test_merlin_published_vector():
    """merlin's transcript equivalence test vector (merlin crate,
    transcript.rs tests)."""
    t = Transcript(b"test protocol")
    t.append_message(b"some label", b"some data")
    c = t.challenge_bytes(b"challenge", 32)
    assert c.hex() == (
        "d5a21972d0d5fe320c0d263fac7fffb8145aa640af6e9bca177c03c7efcf0615"
    )


def test_ristretto_rfc9496_generator_multiples():
    vectors = [
        "0000000000000000000000000000000000000000000000000000000000000000",
        "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
        "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
        "94741f5d5d52755ece4f23f044ee27d5d1ea1e2bd196b462166b16152a9d0259",
        "da80862773358b466ffadfe0b3293ab3d9fd53c5ea6c955358f568322daf6a57",
    ]
    for k, want in enumerate(vectors):
        assert rst.encode(rst.mul_base(k)).hex() == want
    # decode rejects non-canonical / negative encodings
    assert rst.decode(b"\x01" + b"\x00" * 31) is None  # odd => negative
    assert rst.decode(b"\xff" * 32) is None  # >= p


def test_sign_verify_roundtrip():
    sk = PrivKeySr25519.from_seed(b"\x0a" * 32)
    pk = sk.pub_key()
    assert pk.type() == "sr25519"
    assert len(pk.bytes()) == 32
    msg = b"consensus vote bytes"
    sig = sk.sign(msg)
    assert len(sig) == 64
    assert sig[63] & 0x80  # schnorrkel v1 marker bit
    assert pk.verify_signature(msg, sig)
    assert not pk.verify_signature(msg + b"x", sig)
    # tampered R and s both rejected
    for i in (0, 40):
        bad = bytearray(sig)
        bad[i] ^= 1
        assert not pk.verify_signature(msg, bytes(bad))
    # missing marker bit rejected (pre-v0.1.1 format)
    nomark = bytearray(sig)
    nomark[63] &= 0x7F
    assert not pk.verify_signature(msg, bytes(nomark))
    # wrong key rejected
    other = PrivKeySr25519.from_seed(b"\x0b" * 32).pub_key()
    assert not other.verify_signature(msg, sig)


def test_signatures_are_randomized_but_stable():
    """schnorrkel mixes fresh randomness into the witness: two
    signatures over the same message differ yet both verify."""
    sk = PrivKeySr25519.from_seed(b"\x0c" * 32)
    pk = sk.pub_key()
    s1, s2 = sk.sign(b"m"), sk.sign(b"m")
    assert s1 != s2
    assert pk.verify_signature(b"m", s1)
    assert pk.verify_signature(b"m", s2)


def test_batch_verifier_seam():
    sk = PrivKeySr25519.from_seed(b"\x0d" * 32)
    assert supports_batch_verifier(sk.pub_key())
    bv = create_batch_verifier(sk.pub_key())
    assert isinstance(bv, Sr25519BatchVerifier)
    sks = [PrivKeySr25519.from_seed(bytes([i]) * 32) for i in range(1, 7)]
    msgs = [b"msg-%d" % i for i in range(6)]
    sigs = [s.sign(m) for s, m in zip(sks, msgs)]
    for s, m, sig in zip(sks, msgs, sigs):
        bv.add(s.pub_key(), m, sig)
    ok, bitmap = bv.verify()
    assert ok and all(bitmap)
    # one corrupted signature is localized
    bv2 = create_batch_verifier(sk.pub_key())
    for i, (s, m, sig) in enumerate(zip(sks, msgs, sigs)):
        if i == 3:
            sig = sig[:10] + bytes([sig[10] ^ 1]) + sig[11:]
        bv2.add(s.pub_key(), m, sig)
    ok, bitmap = bv2.verify()
    assert not ok
    assert bitmap == [True, True, True, False, True, True]
    # foreign key type rejected at add()
    with pytest.raises(TypeError):
        bv2.add(PrivKeyEd25519.from_seed(b"\x01" * 32).pub_key(), b"m", b"s" * 64)


def _mixed_commit(n_ed: int, n_sr: int, chain_id: str = "mixed-chain"):
    privs = [
        PrivKeyEd25519.from_seed(bytes([10 + i]) * 32) for i in range(n_ed)
    ] + [
        PrivKeySr25519.from_seed(bytes([60 + i]) * 32) for i in range(n_sr)
    ]
    vals = ValidatorSet(
        [Validator(pub_key=p.pub_key(), voting_power=10) for p in privs]
    )
    block_id = BlockID(
        hash=b"\x11" * 32,
        part_set_header=PartSetHeader(total=1, hash=b"\x22" * 32),
    )
    now = time.time_ns()
    order = {v.address: i for i, v in enumerate(vals.validators)}
    commit_sigs = [None] * len(privs)
    for p in privs:
        addr = p.pub_key().address()
        vote = Vote(
            type=PRECOMMIT_TYPE,
            height=5,
            round=0,
            block_id=block_id,
            timestamp_ns=now,
            validator_address=addr,
            validator_index=order[addr],
        )
        sig = p.sign(vote.sign_bytes(chain_id))
        commit_sigs[order[addr]] = CommitSig.for_block(sig, addr, now)
    commit = Commit(
        height=5, round=0, block_id=block_id, signatures=commit_sigs
    )
    return vals, commit, block_id, privs, order


class TestMixedKeyCommit:
    """BASELINE stress config 5's shape: mixed ed25519/sr25519
    validator sets through VerifyCommit — per-key-type batch grouping
    (the reference's single-verifier batch errors out of mixed sets)."""

    def test_mixed_commit_verifies(self):
        vals, commit, block_id, _, _ = _mixed_commit(5, 4)
        verify_commit("mixed-chain", vals, block_id, 5, commit)
        verify_commit_light("mixed-chain", vals, block_id, 5, commit)

    def test_mixed_commit_bad_sr_sig_flagged(self):
        vals, commit, block_id, privs, order = _mixed_commit(5, 4)
        # corrupt one sr25519 signature (validator index of the first
        # sr25519 key)
        sr_addr = privs[5].pub_key().address()
        idx = order[sr_addr]
        cs = commit.signatures[idx]
        commit.signatures[idx] = CommitSig.for_block(
            cs.signature[:-2] + bytes([cs.signature[-2] ^ 1, cs.signature[-1]]),
            cs.validator_address,
            cs.timestamp_ns,
        )
        with pytest.raises(InvalidCommitError, match=f"#{idx}"):
            verify_commit("mixed-chain", vals, block_id, 5, commit)

    def test_mixed_commit_bad_ed_sig_flagged(self):
        vals, commit, block_id, privs, order = _mixed_commit(5, 4)
        ed_addr = privs[2].pub_key().address()
        idx = order[ed_addr]
        cs = commit.signatures[idx]
        commit.signatures[idx] = CommitSig.for_block(
            bytes([cs.signature[0] ^ 1]) + cs.signature[1:],
            cs.validator_address,
            cs.timestamp_ns,
        )
        with pytest.raises(InvalidCommitError, match=f"#{idx}"):
            verify_commit("mixed-chain", vals, block_id, 5, commit)


def test_single_verify_device_route(monkeypatch):
    """With the device factory installed and an accelerator attached,
    single sr25519 verifies route through the installed seam (metrics
    counted, mesh verifier honored) — same accept/reject answers as
    the pure-Python path."""
    from tendermint_tpu.crypto import tpu_verifier as T
    from tendermint_tpu.crypto.sr25519 import PrivKeySr25519

    priv = PrivKeySr25519.from_seed(b"\x2a" * 32)
    pub = priv.pub_key()
    msg = b"single-route"
    sig = priv.sign(msg)
    bad = sig[:5] + bytes([sig[5] ^ 1]) + sig[6:]

    monkeypatch.setattr(T, "_INSTALLED", True)
    monkeypatch.setattr(T, "_STREAMING", True)  # pretend accelerator
    T.sr_single_breaker().close_now()  # route proven (bucket compiled)
    assert T.single_sr_verifier() is not None
    sigs_before = T.stats()["sigs"]
    assert pub.verify_signature(msg, sig)
    assert not pub.verify_signature(msg, bad)
    assert not pub.verify_signature(msg, b"\x00" * 10)  # malformed size
    assert T.stats()["sigs"] == sigs_before + 2  # device path counted
    # without the accelerator the python path answers identically and
    # the factory gate returns None (single stays CPU)
    monkeypatch.setattr(T, "_STREAMING", False)
    assert T.single_sr_verifier() is None
    assert pub.verify_signature(msg, sig)
    assert not pub.verify_signature(msg, bad)
    assert T.stats()["sigs"] == sigs_before + 2


def test_native_sr_batch_equation_paths():
    """CPU sr25519 batches ride the native schnorrkel batch equation
    (reference: crypto/sr25519/batch.go via curve25519-voi): all-valid
    batches return all-True in one call; any invalid signature falls
    back per-signature for the exact bitmap."""
    from tendermint_tpu import native
    from tendermint_tpu.crypto import sr25519 as S

    if native.ed25519_batch_lib() is None:
        pytest.skip("no native toolchain")
    privs = [
        S.PrivKeySr25519.from_seed(bytes([i + 61]) * 32) for i in range(6)
    ]
    n = max(S._NATIVE_BATCH_MIN, 24)
    bv = S.Sr25519BatchVerifier()
    for i in range(n):
        p = privs[i % 6]
        m = b"srn-%d" % i
        bv.add(p.pub_key(), m, p.sign(m))
    ok, bits = bv.verify()
    assert ok and bits == [True] * n

    # per-index attribution on failure
    bv = S.Sr25519BatchVerifier()
    for i in range(n):
        p = privs[i % 6]
        m = b"srn2-%d" % i
        sig = p.sign(m)
        if i == 7:
            m = b"tampered"
        bv.add(p.pub_key(), m, sig)
    ok, bits = bv.verify()
    assert not ok
    assert [i for i, b in enumerate(bits) if not b] == [7]


def test_native_sr_batch_differential_edges():
    """Native batch agrees with the pure-Python schnorrkel path on edge
    signatures: missing marker bit, non-canonical s, undecodable R
    encoding, wrong message binding."""
    from tendermint_tpu import native
    from tendermint_tpu.crypto import ristretto as rst
    from tendermint_tpu.crypto import sr25519 as S

    if native.ed25519_batch_lib() is None:
        pytest.skip("no native toolchain")
    priv = S.PrivKeySr25519.from_seed(b"\x51" * 32)
    pub = priv.pub_key()
    n = max(S._NATIVE_BATCH_MIN, 12)
    items = []
    expected = []
    for i in range(n):
        m = b"edge-%d" % i
        sig = priv.sign(m)
        if i % 4 == 1:  # strip the schnorrkel v1 marker
            sb = bytearray(sig)
            sb[63] &= 0x7F
            sig = bytes(sb)
        elif i % 4 == 2:  # non-canonical s (>= L, marker kept)
            s = int.from_bytes(
                sig[32:63] + bytes([sig[63] & 0x7F]), "little"
            )
            s += rst.L
            if s < 2**255:
                nb = bytearray(s.to_bytes(32, "little"))
                nb[31] |= 0x80
                sig = sig[:32] + bytes(nb)
        elif i % 4 == 3:  # undecodable R (odd s-field = negative)
            sig = b"\x01" + sig[1:]
        items.append((pub, m, sig))
        expected.append(pub.verify_signature(m, sig))
    bv = S.Sr25519BatchVerifier()
    for pk, m, sig in items:
        bv.add(pk, m, sig)
    ok, bits = bv.verify()
    assert bits == expected
    assert ok == all(expected)


def test_single_route_gated_on_warm(monkeypatch):
    """Until install()'s probe has compiled and proven the smallest
    sr25519 bucket, single verifies stay on the CPU path — a per-vote
    verify must never block behind the first XLA compile (ADVICE r3).
    The gate is the single-route breaker, which starts OPEN (cold and
    tripped are the same state: not currently proven)."""
    from tendermint_tpu.crypto import breaker, tpu_verifier as T

    breaker.reset_all()  # fresh cold breaker, no probe armed
    monkeypatch.setattr(T, "_INSTALLED", True)
    monkeypatch.setattr(T, "_STREAMING", True)
    assert T.sr_single_breaker().state() == breaker.OPEN
    assert T.single_sr_verifier() is None


def test_single_verify_device_fault_falls_back(monkeypatch):
    """A device route that raises must not propagate out of
    verify_signature (total-predicate contract — it sits under
    per-vote and evidence verification): the pure-Python ristretto
    path answers instead (ADVICE r3 medium)."""
    from tendermint_tpu.crypto import tpu_verifier as T
    from tendermint_tpu.crypto.sr25519 import PrivKeySr25519

    priv = PrivKeySr25519.from_seed(b"\x2b" * 32)
    pub = priv.pub_key()
    msg = b"fault-route"
    sig = priv.sign(msg)
    bad = sig[:5] + bytes([sig[5] ^ 1]) + sig[6:]

    class Boom:
        def add(self, *a):
            raise RuntimeError("device fault")

        def verify(self):  # pragma: no cover - add raises first
            raise RuntimeError("device fault")

    from tendermint_tpu.crypto import breaker

    breaker.reset_all()
    T.sr_single_breaker().close_now()
    monkeypatch.setattr(T, "single_sr_verifier", lambda: Boom())
    assert pub.verify_signature(msg, sig)
    # the fault trips the route's breaker so later votes skip the
    # device retry (and its warning) entirely
    assert T.sr_single_breaker().state() == breaker.OPEN
    assert not pub.verify_signature(msg, bad)


def test_native_merlin_challenge_differential():
    """The C merlin transcript (STROBE-128 over Keccak-f in
    native/ed25519_batch.c) must produce bit-identical challenges to
    the pure-Python oracle (crypto/merlin.py, which reproduces
    merlin's published test vector) across STROBE rate boundaries
    (166-byte blocks) and the empty message."""
    import ctypes

    from tendermint_tpu import native
    from tendermint_tpu.crypto import sr25519 as sr

    lib = native.ed25519_batch_lib()
    if lib is None:
        pytest.skip("no native toolchain")
    pk = bytes(range(32))
    r = bytes(reversed(range(32)))
    for mlen in (0, 1, 17, 120, 165, 166, 167, 300, 1000):
        msg = ((b"\xa5" * 97 + bytes(range(256))) * 4)[:mlen]
        assert len(msg) == mlen
        out = ctypes.create_string_buffer(32)
        lib.tm_sr25519_challenge_test(pk, r, msg, mlen, out)
        k_c = int.from_bytes(out.raw, "little")
        k_py = sr._challenge(sr._signing_transcript(msg), pk, r)
        assert k_c == k_py, mlen


def test_native_sr_full_marker_and_canonicality():
    """tm_sr25519_verify_full enforces schnorrkel signature rules
    itself: a missing v1 marker bit or a non-canonical s (>= L) makes
    the whole batch report invalid, and the per-signature fallback
    attributes the exact index."""
    from tendermint_tpu import native
    from tendermint_tpu.crypto import sr25519 as sr

    if native.ed25519_batch_lib() is None:
        pytest.skip("no native toolchain")
    sks = [
        sr.PrivKeySr25519.from_seed(bytes([i + 1, 0xAB]) + b"\x13" * 30)
        for i in range(6)
    ]
    items = []
    for i, k in enumerate(sks):
        m = b"mk-%d" % i
        items.append((k.pub_key(), m, k.sign(m)))
    assert sr._native_batch_all_valid(items) is True

    # strip the marker bit from one signature
    pk, m, s = items[2]
    bad = s[:63] + bytes([s[63] & 0x7F])
    tampered = list(items)
    tampered[2] = (pk, m, bad)
    assert sr._native_batch_all_valid(tampered) is False
    bv = sr.Sr25519BatchVerifier()
    for pk2, m2, s2 in tampered:
        bv.add(pk2, m2, s2)
    ok, bits = bv.verify()
    assert not ok and [i for i, b in enumerate(bits) if not b] == [2]

    # non-canonical s: s' = s + L satisfies the equation mod L, so only
    # the explicit s < L check rejects it — the classic malleation the
    # sc4_gte(SC_L) branch exists for. s + L fits in 255 bits, marker
    # bit intact, so nothing else can catch a regression there.
    pk, m, s = items[4]
    s_val = int.from_bytes(
        bytes([*s[32:63], s[63] & 0x7F]), "little"
    )
    mall = (s_val + sr.L).to_bytes(32, "little")
    assert mall[31] & 0x80 == 0  # still leaves room for the marker
    mall = bytes([*mall[:31], mall[31] | 0x80])
    malleated = list(items)
    malleated[4] = (pk, m, s[:32] + mall)
    assert sr._native_batch_all_valid(malleated) is False
    bv = sr.Sr25519BatchVerifier()
    for pk2, m2, s2 in malleated:
        bv.add(pk2, m2, s2)
    ok, bits = bv.verify()
    assert not ok and [i for i, b in enumerate(bits) if not b] == [4]


def test_single_verify_undecodable_r_rejected():
    """A signature whose R bytes are not a valid ristretto encoding:
    the native path reports undecodable (rc -1 -> None) and the
    pure-Python oracle gives the authoritative False."""
    from tendermint_tpu.crypto.sr25519 import PrivKeySr25519

    k = PrivKeySr25519.from_seed(b"\x42" * 32)
    pub = k.pub_key()
    sig = k.sign(b"m")
    # high bit set makes the encoding non-canonical -> undecodable
    bad_r = bytes([sig[0]]) + sig[1:31] + bytes([sig[31] | 0x80])
    assert not pub.verify_signature(b"m", bad_r + sig[32:])
    assert pub.verify_signature(b"m", sig)


def test_native_basemul_matches_python_oracle():
    """tm_ristretto_basemul (constant-time fixed-base multiply +
    ristretto encode, the sign/keygen hot path) against the pure-
    Python oracle across edge scalars — 0 (identity), 1 (basepoint),
    window boundaries, L-1 (= -B) — and seeded random ones."""
    import random

    from tendermint_tpu import native
    from tendermint_tpu.crypto import ristretto as rst
    from tendermint_tpu.crypto.sr25519 import L

    if native.ed25519_batch_lib() is None:
        import pytest

        pytest.skip("native library unavailable")
    rng = random.Random(1307)
    cases = [0, 1, 2, 15, 16, 17, 255, 256, 2**51, 2**252, L - 1] + [
        rng.randrange(1, L) for _ in range(64)
    ]
    for k in cases:
        nat = native.ristretto_basemul(int(k).to_bytes(32, "little"))
        assert nat == rst.encode(rst.mul_base(k)), k


def test_native_entry_points_reject_short_buffers():
    """ADVICE r5: the C side unconditionally reads 32 bytes from
    scalar/pub/R — a shorter buffer from a future caller would be an
    out-of-bounds read, so the Python wrappers must reject it BEFORE
    the ctypes call (native library not required: the check comes
    first)."""
    import pytest

    from tendermint_tpu import native

    for bad in (b"", b"\x01" * 31, b"\x01" * 33):
        with pytest.raises(ValueError, match="32 bytes"):
            native.ristretto_basemul(bad)
        with pytest.raises(ValueError, match="32 bytes"):
            native.sr25519_challenge(bad, b"\x02" * 32, b"msg")
        with pytest.raises(ValueError, match="32 bytes"):
            native.sr25519_challenge(b"\x02" * 32, bad, b"msg")
    # exact 32-byte inputs still go through (or return None without
    # a toolchain) — the guard must not reject valid calls
    try:
        native.sr25519_challenge(b"\x02" * 32, b"\x03" * 32, b"msg")
    except ValueError as e:  # pragma: no cover - guard regression
        raise AssertionError(f"valid 32-byte input rejected: {e}")
