"""Test configuration.

Tests run on a virtual 8-device CPU mesh so sharding/collective code paths
(`tendermint_tpu.parallel`) are exercised without TPU hardware. This must be
set before jax is imported anywhere.
"""

import os
import sys

# The ambient environment may pin JAX_PLATFORMS to the real TPU backend;
# unit tests always run on a virtual 8-device CPU mesh so sharding and
# collective paths are exercised deterministically (and the TPU tunnel is
# left to bench.py). jax.config wins over the env pin.
os.environ["JAX_PLATFORMS"] = "cpu"
# Drop the device-plugin site dir from the import path entirely: plugin
# *discovery* opens the device tunnel even under JAX_PLATFORMS=cpu, and a
# wedged tunnel then hangs every test process at jax import. Match the
# exact directory name, not a substring of the whole path.
_PLUGIN_DIR = ".axon_site"
sys.path = [p for p in sys.path if os.path.basename(p) != _PLUGIN_DIR]
os.environ["PYTHONPATH"] = os.pathsep.join(
    p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
    if p and os.path.basename(p) != _PLUGIN_DIR
)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices; the XLA_FLAGS
    # xla_force_host_platform_device_count=8 above covers it there
    pass

import pytest  # noqa: E402


def _enable_compilation_cache() -> None:
    """Persist XLA compilations across test runs (the ed25519 kernel is a
    big program; first compile is ~1-4 min, cached reloads are instant)."""
    import jax

    cache_dir = os.path.join(
        os.path.dirname(__file__), "..", ".jax_cache"
    )
    jax.config.update("jax_compilation_cache_dir", os.path.abspath(cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


_enable_compilation_cache()


@pytest.fixture(autouse=True, scope="module")
def _release_compiled_executables():
    """Drop compiled-executable references after every test module.

    Each XLA:CPU LoadedExecutable holds many mmap'd regions; across the
    full suite they accumulate to the kernel's vm.max_map_count limit
    (65530 — observed 65313 maps one minute before a C-level abort in
    backend_compile_and_load at the late test_sharding module, 4 runs
    in a row, never in isolation). Clearing jax's caches lets the
    executables GC and unmap, so the per-process peak stays at the
    biggest single module, not the sum of all modules. Recompiles on
    module boundaries are mostly persistent-cache hits."""
    yield
    import gc

    jax.clear_caches()
    gc.collect()


@pytest.fixture(autouse=True, scope="module")
def _byz_plane_leak_guard():
    """Fail fast when a test module leaks the byzantine plane.

    The adversary plane is ambient process state (TM_TPU_BYZ env,
    byzantine._RULES, the installed-harness registry): a module that
    arms it and forgets to disarm silently turns every LATER module's
    consensus nodes byzantine — failures would surface far from the
    leak (the tmmc model checker is especially exposed: its builds
    call byzantine.maybe_install on every node). Checked at every
    module boundary; the plane is healed before failing so one leak
    produces one failure, not a cascade."""
    yield
    import os as _os

    from tendermint_tpu.consensus import byzantine

    leaks = []
    if _os.environ.get("TM_TPU_BYZ"):
        leaks.append(f"TM_TPU_BYZ={_os.environ['TM_TPU_BYZ']!r} still set")
    n_rules = len(byzantine.rules())
    if n_rules:
        leaks.append(f"{n_rules} armed rule(s)")
    n_harn = len(byzantine.harnesses())
    if n_harn:
        leaks.append(f"{n_harn} registered harness(es)")
    if leaks:
        _os.environ.pop("TM_TPU_BYZ", None)
        byzantine.reset()
        pytest.fail(
            "byzantine plane leaked past a test module: "
            + "; ".join(leaks)
            + " (arm via monkeypatch/ExitStack and reset() in teardown)"
        )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running gates (ASAN sweep, big e2e runs)"
    )


# The suites that exercise real cross-thread lock interleavings
# (breaker probes, gather watchdogs, fault-plane chaos, schedule
# fuzzing) run under the lockwatch observer; everything else skips the
# wrapping overhead.
_LOCKWATCH_FILES = {
    "test_chaos_consensus.py",
    "test_faults.py",
    "test_fuzz.py",
    "test_schedule_fuzz.py",
}


@pytest.fixture(autouse=True)
def _lockwatch_guard(request):
    """Record the lock-acquisition graph during the chaos/fault/fuzz
    suites and fail the test on any witnessed lock-order cycle or
    rank-table violation — the runtime analog of `go test -race`
    plus Go's lockrank (tendermint_tpu/analysis/lockwatch.py; the
    proven-acyclic order is documented in its RANK table). Long holds
    are reported as warnings, not failures — a loaded CI box parks
    threads for unpredictable stretches — but every overrun also lands
    in the structured lockwatch.HOLD_LOG record, and
    tests/test_tmlive.py::test_witnessed_overruns_statically_explained
    asserts each one is either a tmlive-flagged/suppressed blocking
    site under that lock or covered by holdflow.OVERRUN_OK's reviewed
    scheduler-noise rationale."""
    if os.path.basename(str(request.node.fspath)) not in _LOCKWATCH_FILES:
        yield
        return
    from tendermint_tpu.analysis import lockwatch

    lockwatch.enable()
    try:
        yield
    finally:
        report = lockwatch.disable()
        assert not report.cycles, (
            "lockwatch: lock-order cycle witnessed\n" + report.render()
        )
        assert not report.order_violations(), (
            "lockwatch: rank-table violation\n" + report.render()
        )
        if report.long_holds:
            import warnings

            warnings.warn(
                "lockwatch: hold-time budget exceeded\n" + report.render(),
                stacklevel=1,
            )


@pytest.fixture(autouse=True)
def _fresh_fault_plane():
    """Disarm the fault plane and drop every circuit breaker after each
    test: a chaos test that tripped a route breaker must not silently
    reroute a later test's device-path assertions to the CPU factory.
    Breakers are created on demand (closed) so non-fault tests see the
    exact pre-breaker behavior."""
    yield
    from tendermint_tpu.crypto import breaker, faults

    faults.reset()
    breaker.reset_all()


@pytest.fixture(autouse=True)
def _fresh_sigcache():
    """Start every test with a cold verified-signature cache: the test
    fixtures are deterministic (fixed seeds/timestamps), so identical
    triples recur across modules and the process-global cache would
    otherwise make crypto-call-count and device-dispatch assertions
    order-dependent. The cache is pure speed — resetting never changes
    behavior."""
    from tendermint_tpu.crypto import sigcache

    sigcache.reset()
    yield


@pytest.fixture
def tmp_home(tmp_path):
    from tendermint_tpu.config import Config

    cfg = Config()
    cfg.base.home = str(tmp_path)
    cfg.ensure_dirs()
    return cfg
