"""Test configuration.

Tests run on a virtual 8-device CPU mesh so sharding/collective code paths
(`tendermint_tpu.parallel`) are exercised without TPU hardware. This must be
set before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture
def tmp_home(tmp_path):
    from tendermint_tpu.config import Config

    cfg = Config()
    cfg.base.home = str(tmp_path)
    cfg.ensure_dirs()
    return cfg
