"""Operator CLI tests (reference model: cmd/tendermint/commands/*_test.go).

Drives the argparse surface exactly as an operator would: init a home,
start a node briefly, roll back, build a testnet, and run the verifying
light proxy against a live node.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tendermint_tpu.cmd import main as cli_main
from tendermint_tpu.config import load_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(*argv) -> int:
    return cli_main(list(argv))


def test_init_writes_home(tmp_path, capsys):
    home = str(tmp_path / "home")
    assert run_cli("--home", home, "init", "validator",
                   "--chain-id", "cli-chain") == 0
    for rel in (
        "config/config.toml",
        "config/genesis.json",
        "config/node_key.json",
        "config/priv_validator_key.json",
    ):
        assert os.path.exists(os.path.join(home, rel)), rel
    cfg = load_config(os.path.join(home, "config", "config.toml"))
    assert cfg.base.chain_id == "cli-chain"
    assert cfg.base.mode == "validator"
    # idempotent: a second init keeps the genesis
    assert run_cli("--home", home, "init", "validator") == 0
    cfg2 = load_config(os.path.join(home, "config", "config.toml"))
    assert cfg2.base.chain_id == "cli-chain"


def test_key_commands(tmp_path, capsys):
    home = str(tmp_path / "home")
    assert run_cli("--home", home, "init", "validator") == 0
    capsys.readouterr()
    assert run_cli("--home", home, "show-node-id") == 0
    node_id = capsys.readouterr().out.strip()
    assert len(node_id) == 40
    assert run_cli("--home", home, "show-validator") == 0
    val = json.loads(capsys.readouterr().out)
    assert val["type"] == "ed25519" and len(val["value"]) == 64
    assert run_cli("gen-validator") == 0
    gv = json.loads(capsys.readouterr().out)
    assert len(gv["priv_key"]["value"]) in (64, 128)
    assert run_cli("version") == 0
    assert capsys.readouterr().out.strip()


def test_gen_validator_secp256k1(capsys):
    """reference: commands/gen_validator.go --key — secp256k1 is
    first-class through the native backend (the PR-1 shim raised
    here), and the emitted key actually signs/verifies."""
    from tendermint_tpu.crypto.keys import (
        privkey_from_type_and_bytes,
        pubkey_from_type_and_bytes,
    )

    assert run_cli("gen-validator", "--key", "secp256k1") == 0
    gv = json.loads(capsys.readouterr().out)
    assert gv["priv_key"]["type"] == "secp256k1"
    assert len(gv["pub_key"]["value"]) == 66  # 33-byte compressed point
    assert len(gv["priv_key"]["value"]) == 64
    priv = privkey_from_type_and_bytes(
        "secp256k1", bytes.fromhex(gv["priv_key"]["value"])
    )
    pub = pubkey_from_type_and_bytes(
        "secp256k1", bytes.fromhex(gv["pub_key"]["value"])
    )
    assert priv.pub_key() == pub
    assert pub.address().hex().upper() == gv["address"]
    sig = priv.sign(b"cli keygen smoke")
    assert pub.verify_signature(b"cli keygen smoke", sig)
    # unknown types exit 1 through the argparse choices/ValueError path
    assert run_cli("gen-validator", "--key", "ed25519") == 0
    capsys.readouterr()


def test_testnet_layout(tmp_path, capsys):
    out = str(tmp_path / "net")
    assert run_cli("testnet", "-v", "3", "-o", out,
                   "--chain-id", "net-chain", "--starting-port", "30000") == 0
    genesis_hashes = set()
    for i in range(3):
        home = os.path.join(out, f"node{i}")
        cfg = load_config(os.path.join(home, "config", "config.toml"))
        assert cfg.base.chain_id == "net-chain"
        # fully meshed persistent peers
        assert cfg.p2p.persistent_peers.count("@") == 2
        with open(os.path.join(home, "config", "genesis.json")) as f:
            genesis_hashes.add(f.read())
    assert len(genesis_hashes) == 1  # identical genesis across homes


def test_start_runs_and_produces_blocks(tmp_path):
    """`start` in a subprocess: SIGTERM stops it cleanly; a restart plus
    `rollback` exercises the recovery surface."""
    home = str(tmp_path / "home")
    assert run_cli("--home", home, "init", "validator",
                   "--chain-id", "start-chain") == 0
    # speed up consensus + free RPC port
    cfg_path = os.path.join(home, "config", "config.toml")
    cfg = load_config(cfg_path)
    cfg.consensus.timeout_commit = 0.2
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    from tendermint_tpu.config import write_config

    write_config(cfg, cfg_path)

    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cmd",
         "--home", home, "start"],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        cwd=REPO,
    )
    try:
        deadline = time.time() + 90
        from tendermint_tpu.state import StateStore
        from tendermint_tpu.store.kv import open_db

        height = 0
        while time.time() < deadline and height < 2:
            time.sleep(2.0)
            try:
                db = open_db("state", "sqlite", os.path.join(home, "data"))
                st = StateStore(db).load()
                height = st.last_block_height if st else 0
                db.close()
            except Exception:
                pass
        assert height >= 2, "node produced no blocks"
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
    assert proc.returncode == 0

    # rollback rewinds one height
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert run_cli("--home", home, "rollback") == 0
    assert "rolled back state to height" in buf.getvalue()

    # unsafe-reset-all clears data but keeps keys
    with redirect_stdout(buf):
        assert run_cli("--home", home, "unsafe-reset-all") == 0
    assert os.path.exists(
        os.path.join(home, "config", "priv_validator_key.json")
    )
    assert not os.path.exists(
        os.path.join(home, "data", "state.sqlite")
    )


def test_debug_bundle(tmp_path, capsys):
    """`debug` collects config/genesis/WAL/store summary after a run
    (reference: commands/debug/dump.go)."""
    import asyncio as aio
    import tarfile

    home = str(tmp_path / "dbg")
    assert run_cli("--home", home, "init", "validator",
                   "--chain-id", "dbg-chain") == 0
    # produce a little history in-process
    from tendermint_tpu.node import make_node
    from tendermint_tpu.config import load_config, write_config

    cfg_path = os.path.join(home, "config", "config.toml")
    cfg = load_config(cfg_path)
    cfg.consensus.timeout_commit = 0.2
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    write_config(cfg, cfg_path)

    async def produce():
        cfg2 = load_config(cfg_path)
        cfg2.base.home = home
        node = make_node(cfg2)
        await node.start()
        try:
            await node.consensus.wait_for_height(3, timeout=60.0)
        finally:
            await node.stop()

    aio.run(produce())
    out = str(tmp_path / "bundle.tar.gz")
    assert run_cli("--home", home, "debug", "-o", out) == 0
    with tarfile.open(out) as tar:
        names = tar.getnames()
        assert "config.toml" in names
        assert "genesis.json" in names
        assert "summary.json" in names
        assert "cs.wal" in names
        # span-trace ring rides along as valid Chrome-trace JSON
        assert "trace.json" in names
        chrome = json.loads(tar.extractfile("trace.json").read())
        assert "traceEvents" in chrome
        summary = json.loads(
            tar.extractfile("summary.json").read()
        )
        assert summary["block_store"]["height"] >= 2
        assert summary["state"]["chain_id"] == "dbg-chain"


def test_replay_console(tmp_path, monkeypatch, capsys):
    """`replay --console` steps the current height's WAL records one
    at a time with next/back/rs/n (reference: replay_file.go console,
    :54,188-193)."""
    import asyncio as aio

    home = str(tmp_path / "rc")
    assert run_cli("--home", home, "init", "validator",
                   "--chain-id", "rc-chain") == 0
    from tendermint_tpu.config import load_config, write_config
    from tendermint_tpu.node import make_node

    cfg_path = os.path.join(home, "config", "config.toml")
    cfg = load_config(cfg_path)
    cfg.consensus.timeout_commit = 0.2
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    write_config(cfg, cfg_path)

    async def produce():
        cfg2 = load_config(cfg_path)
        cfg2.base.home = home
        node = make_node(cfg2)
        await node.start()
        try:
            await node.consensus.wait_for_height(3, timeout=60.0)
        finally:
            await node.stop()

    aio.run(produce())

    script = iter(
        ["n", "next 3", "rs", "rs locked_round", "back 1", "n", "quit"]
    )
    monkeypatch.setattr(
        "builtins.input", lambda prompt="": next(script)
    )
    assert run_cli("--home", home, "replay", "--console") == 0
    out = capsys.readouterr().out
    assert "console:" in out
    assert "WAL records after EndHeight" in out
    # rs short prints height/round/step
    import re

    assert re.search(r"^\d+/\d+/\d+$", out, re.M), out
    assert "rewound to" in out


def test_debug_kill(tmp_path):
    """`debug --kill PID` collects the bundle then SIGABRTs the target
    (reference: cmd/tendermint/commands/debug/kill.go)."""
    import signal as sig
    import subprocess as sp
    import tarfile

    home = str(tmp_path / "dk")
    assert run_cli("--home", home, "init", "validator",
                   "--chain-id", "dk-chain") == 0
    victim = sp.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)"]
    )
    try:
        out = str(tmp_path / "kill_bundle.tar.gz")
        assert run_cli(
            "--home", home, "debug", "-o", out, "--kill", str(victim.pid)
        ) == 0
        victim.wait(timeout=10)
        assert victim.returncode == -sig.SIGABRT
        with tarfile.open(out) as tar:
            assert "config.toml" in tar.getnames()
    finally:
        if victim.poll() is None:
            victim.terminate()
            victim.wait()


def test_debug_bundle_device_profile(tmp_path):
    """`debug --device-profile` packs an XLA profiler trace of a
    verify batch into the bundle (SURVEY §5 device-trace analog of the
    reference's pprof collection)."""
    import tarfile

    home = str(tmp_path / "dbgp")
    assert run_cli("--home", home, "init", "validator",
                   "--chain-id", "dbgp-chain") == 0
    out = str(tmp_path / "bundle_prof.tar.gz")
    assert run_cli(
        "--home", home, "debug", "-o", out, "--device-profile"
    ) == 0
    with tarfile.open(out) as tar:
        names = tar.getnames()
        assert "summary.json" in names
        summary = json.loads(tar.extractfile("summary.json").read())
        assert "device_profile_error.txt" not in names, names
        prof = summary["device_profile"]
        assert prof["batch"] == 256 and prof["profiled_run_s"] > 0
        assert any(n.startswith("device_profile/") for n in names), (
            names
        )


def test_light_proxy_serves_verified_headers(tmp_path):
    """Boot a full node in-process, run the light proxy logic against
    its RPC, and fetch a verified header through the proxy surface
    (reference: commands/light.go)."""
    from tendermint_tpu.config import Config
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
    from tendermint_tpu.light import Client, LightStore, TrustOptions
    from tendermint_tpu.light.provider import HTTPProvider
    from tendermint_tpu.node import make_node
    from tendermint_tpu.privval import FilePV
    from tendermint_tpu.rpc import HTTPClient
    from tendermint_tpu.store.kv import MemKV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x31" * 32)
        genesis = GenesisDoc(
            chain_id="light-cli",
            genesis_time_ns=time.time_ns(),
            validators=[GenesisValidator(pub_key=priv.pub_key(), power=5)],
        )
        cfg = Config()
        cfg.base.home = str(tmp_path / "full")
        cfg.base.chain_id = "light-cli"
        cfg.base.db_backend = "memdb"
        cfg.consensus.timeout_commit = 0.2
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.ensure_dirs()
        genesis.save_as(cfg.base.path(cfg.base.genesis_file))
        FilePV.from_priv_key(
            priv,
            cfg.base.path(cfg.priv_validator.key_file),
            cfg.base.path(cfg.priv_validator.state_file),
        ).save()
        node = make_node(cfg)
        await node.start()
        try:
            await node.consensus.wait_for_height(4, timeout=60.0)
            addr = f"127.0.0.1:{node.rpc_server.bound_port}"
            # trust root = block 1 via the HTTP provider
            provider = HTTPProvider(addr)
            lb1 = await provider.light_block(1)
            client = Client(
                "light-cli",
                TrustOptions(
                    period_ns=10**18,
                    height=1,
                    hash=lb1.signed_header.hash(),
                ),
                provider,
                [],
                LightStore(MemKV()),
            )
            lb3 = await client.verify_light_block_at_height(
                3, time.time_ns()
            )
            want = node.block_store.load_block(3).hash()
            assert lb3.signed_header.header.hash() == want
        finally:
            await node.stop()

    asyncio.run(go())


def test_abci_cli_against_kvstore_socket(tmp_path, capsys):
    """abci-cli parity: serve the kvstore over a socket (one process),
    drive echo/deliver-tx/commit/query through the `abci` subcommands
    (reference: abci/cmd/ abci-cli + example kvstore server)."""
    import socket
    import subprocess
    import sys as _sys
    import time as _time

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"tcp://127.0.0.1:{port}"
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    srv = subprocess.Popen(
        [_sys.executable, "-m", "tendermint_tpu.cmd", "abci",
         "kvstore", "--addr", addr],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        env=env,
        text=True,
    )
    try:
        line = srv.stdout.readline()
        assert "listening" in line, line
        assert run_cli("abci", "echo", "ping", "--addr", addr) == 0
        assert "-> data: ping" in capsys.readouterr().out
        assert run_cli(
            "abci", "deliver-tx", "name=satoshi", "--addr", addr
        ) == 0
        assert "-> code: OK" in capsys.readouterr().out
        assert run_cli("abci", "commit", "--addr", addr) == 0
        capsys.readouterr()
        assert run_cli("abci", "query", "name", "--addr", addr) == 0
        out = capsys.readouterr().out
        assert "-> value: satoshi" in out
        assert run_cli("abci", "info", "--addr", addr) == 0
        assert "last_block_height" in capsys.readouterr().out
    finally:
        srv.terminate()
        try:
            srv.wait(timeout=10)
        except subprocess.TimeoutExpired:
            srv.kill()


def test_reindex_event_rebuilds_tx_index(tmp_path, capsys):
    """`reindex-event` repopulates a wiped tx/block index from stored
    blocks + ABCI responses (reference: commands/reindex_event.go)."""
    import asyncio as aio

    home = str(tmp_path / "reidx")
    assert run_cli("--home", home, "init", "validator",
                   "--chain-id", "reidx-chain") == 0
    from tendermint_tpu.config import load_config, write_config
    from tendermint_tpu.node import make_node

    cfg_path = os.path.join(home, "config", "config.toml")
    cfg = load_config(cfg_path)
    cfg.consensus.timeout_commit = 0.2
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.base.db_backend = "sqlite"
    write_config(cfg, cfg_path)

    tx = b"reindex=me"

    async def produce():
        cfg2 = load_config(cfg_path)
        cfg2.base.home = home
        node = make_node(cfg2)
        await node.start()
        try:
            await node.consensus.wait_for_height(2, timeout=60.0)
            await node.mempool.check_tx(tx)
            tip = node.block_store.height()
            await node.consensus.wait_for_height(tip + 2, timeout=60.0)
        finally:
            await node.stop()

    aio.run(produce())

    # wipe the index, then rebuild it
    import glob

    for f in glob.glob(os.path.join(home, "data", "tx_index*")):
        os.remove(f)
    assert run_cli("--home", home, "reindex-event") == 0
    out = capsys.readouterr().out
    assert "reindexed" in out

    from tendermint_tpu.state.indexer import KVSink
    from tendermint_tpu.store.kv import open_db
    from tendermint_tpu.types.tx import tx_hash

    idb = open_db("tx_index", "sqlite", os.path.join(home, "data"))
    try:
        sink = KVSink(idb)
        got = sink.get_tx_by_hash(tx_hash(tx))
        assert got is not None and got.tx == tx
        assert sink.has_block(2)
    finally:
        idb.close()


def test_offline_commands_refuse_running_node(tmp_path, capsys):
    """reindex-event/rollback/unsafe-reset-all check the advisory data
    LOCK so they cannot race a live node's databases."""
    import subprocess
    import sys as _sys

    home = str(tmp_path / "locked")
    assert run_cli("--home", home, "init", "validator",
                   "--chain-id", "lock-chain") == 0
    lock_dir = os.path.join(home, "data")
    os.makedirs(lock_dir, exist_ok=True)
    lock = os.path.join(lock_dir, "LOCK")

    # a live foreign pid holds the lock -> refused
    other = subprocess.Popen([_sys.executable, "-c", "import time; time.sleep(30)"])
    try:
        with open(lock, "w") as f:
            f.write(str(other.pid))
        assert run_cli("--home", home, "reindex-event") == 1
        assert run_cli("--home", home, "rollback") == 1
        assert run_cli("--home", home, "unsafe-reset-all") == 1
    finally:
        other.kill()
        other.wait()

    # dead pid -> stale lock, command proceeds past the guard
    with open(lock, "w") as f:
        f.write(str(other.pid))  # now dead
    assert run_cli("--home", home, "unsafe-reset-all") == 0


def test_e2e_cli_generate_and_run(tmp_path, capsys):
    """`e2e generate` writes TOML manifests the parser accepts;
    `e2e run` executes one and reports the invariant results
    (reference: the standalone test/e2e runner + generator)."""
    out = str(tmp_path / "manifests")
    assert run_cli("e2e", "generate", "--seed", "2", "--count", "2",
                   "-o", out) == 0
    paths = sorted(
        os.path.join(out, f) for f in os.listdir(out)
    )
    assert len(paths) == 2
    # round-trip: generated TOML parses back into a valid manifest
    from tendermint_tpu.e2e import Manifest

    manifests = [Manifest.from_toml(p) for p in paths]
    for m in manifests:
        m.validate()
    # pick a small one to actually run
    small = min(
        zip(paths, manifests),
        key=lambda pm: (len(pm[1].nodes), pm[1].target_height),
    )[0]
    capsys.readouterr()
    rc = run_cli("e2e", "run", small,
                 "--home-dir", str(tmp_path / "net"),
                 "--timeout", "180")
    out_text = capsys.readouterr().out
    assert rc == 0, out_text
    report = json.loads(out_text[out_text.index("{"):])
    assert report["ok"] and report["reached_height"] >= 3


def test_key_migrate_translates_legacy_layout(tmp_path, capsys):
    """`key-migrate` rewrites the reference's v0.34-style ASCII keys
    (H:/P:/C:/SC:/BH:, stateKey/validatorsKey:…) into the current
    binary-prefix layout, after which BlockStore/StateStore read the
    data (reference: scripts/keymigrate/migrate.go). Re-running is a
    no-op (resumable contract)."""
    import struct

    from tendermint_tpu.state.store import StateStore
    from tendermint_tpu.store.block_store import BlockStore
    from tendermint_tpu.store.kv import open_db

    from tests.test_store import make_chain_block

    home = str(tmp_path / "legacy")
    assert run_cli("--home", home, "init", "validator",
                   "--chain-id", "mig-chain") == 0
    cfg = load_config(os.path.join(home, "config", "config.toml"))
    cfg_db_dir = cfg.base.path(cfg.base.db_dir)

    # build canonical encodings with the CURRENT store, then rewrite
    # the db into the legacy key layout
    block = make_chain_block(3)
    parts = block.make_part_set()
    from tendermint_tpu.types import BlockID, Commit, CommitSig
    from tendermint_tpu.types.block_id import PartSetHeader
    from tendermint_tpu.types.block_meta import BlockMeta

    meta = BlockMeta.from_block(block, len(block.to_proto()))
    seen = Commit(
        height=3,
        round=0,
        block_id=BlockID(hash=block.hash(),
                         part_set_header=parts.header()),
        signatures=[CommitSig.absent()],
    )
    db = open_db("blockstore", "sqlite", cfg_db_dir)
    db.set(b"H:3", meta.to_proto())
    for i in range(parts.header().total):
        db.set(b"P:3:%d" % i, parts.get_part(i).to_proto())
    db.set(b"C:2", block.last_commit.to_proto())
    db.set(b"SC:2", seen.to_proto())  # superseded by SC:3
    db.set(b"SC:3", seen.to_proto())
    db.set(b"BH:" + block.hash().hex().encode(), b"3")
    db.close()

    assert run_cli("--home", home, "key-migrate") == 0
    out = capsys.readouterr().out
    assert "blockstore" in out and "completed database migration" in out

    db = open_db("blockstore", "sqlite", cfg_db_dir)
    try:
        bs = BlockStore(db)
        assert bs.height() == 3
        got = bs.load_block(3)
        assert got is not None and got.hash() == block.hash()
        assert bs.load_block_meta_by_hash(block.hash()).header.height == 3
        assert bs.load_seen_commit().height == 3
        # legacy keys are gone
        assert db.get(b"H:3") is None and db.get(b"SC:2") is None
    finally:
        db.close()

    # second run: nothing legacy left
    assert run_cli("--home", home, "key-migrate") == 0
    assert "completed database migration: 0 key(s)" in capsys.readouterr().out
