"""Evidence pool + reactor tests: double-sign evidence is formed,
verified, gossiped, committed into a block, and reported to the app
(reference model: internal/evidence/pool_test.go, verify_test.go,
reactor_test.go)."""

import asyncio

import pytest

from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.evidence import EvidenceError, EvidencePool
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.vote import Vote
from tendermint_tpu.store.kv import MemKV

from .test_reactors import CHAIN, make_cluster, start_cluster, stop_cluster


def run(coro):
    return asyncio.run(coro)


def make_double_sign(priv, height, vals, time_ns, index=0):
    """Two conflicting precommits by the same validator."""
    addr = priv.pub_key().address()

    def vote_for(tag):
        v = Vote(
            type=PRECOMMIT_TYPE,
            height=height,
            round=0,
            block_id=BlockID(
                hash=tag * 32, part_set_header=PartSetHeader(1, tag * 32)
            ),
            timestamp_ns=time_ns,
            validator_address=addr,
            validator_index=index,
        )
        v.signature = priv.sign(v.sign_bytes(CHAIN))
        return v

    va, vb = vote_for(b"\xaa"), vote_for(b"\xbb")
    return DuplicateVoteEvidence.from_votes(
        va, vb, block_time_ns=time_ns, val_set=vals
    )


def test_pool_verifies_and_admits_double_sign_evidence():
    async def go():
        net, nodes = make_cluster(4)
        await start_cluster(net, nodes)
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(3, timeout=60.0) for n in nodes)
            )
        finally:
            await stop_cluster(net, nodes)

        node = nodes[0]
        vals = node.state_store.load_validators(2)
        header_time = node.block_store.load_block_meta(2).header.time_ns
        # priv index 1 double-signed at height 2
        priv = PrivKeyEd25519.from_seed(bytes([101]) * 32)
        idx, _val = vals.get_by_address(priv.pub_key().address())
        ev = make_double_sign(priv, 2, vals, header_time, index=idx)

        node.evpool.add_evidence(ev)
        assert node.evpool.is_pending(ev)
        pending, size = node.evpool.pending_evidence(1 << 20)
        assert len(pending) == 1 and size > 0
        node.evpool.check_evidence(pending)  # block-validation path

        # garbage evidence is refused
        bad = make_double_sign(priv, 2, vals, header_time, index=idx)
        bad.vote_b.signature = b"\x00" * 64
        with pytest.raises(EvidenceError):
            node.evpool.add_evidence(bad)

    run(go())


def test_evidence_gossips_and_commits():
    async def go():
        net, nodes = make_cluster(4)
        await start_cluster(net, nodes)
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(3, timeout=60.0) for n in nodes)
            )
            node = nodes[0]
            vals = node.state_store.load_validators(2)
            header_time = node.block_store.load_block_meta(2).header.time_ns
            priv = PrivKeyEd25519.from_seed(bytes([102]) * 32)
            idx, _ = vals.get_by_address(priv.pub_key().address())
            ev = make_double_sign(priv, 2, vals, header_time, index=idx)
            node.evpool.add_evidence(ev)

            # evidence must reach every pool and land in a committed block
            async def committed_everywhere():
                while not all(n.evpool.is_committed(ev) for n in nodes):
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(committed_everywhere(), 60.0)
        finally:
            await stop_cluster(net, nodes)

        # find the block carrying it and check ABCI byzantine report
        found = False
        for h in range(1, nodes[0].block_store.height() + 1):
            block = nodes[0].block_store.load_block(h)
            if block.evidence:
                found = True
                assert block.evidence[0].hash() == ev.hash()
        assert found, "evidence never committed into a block"
        for n in nodes:
            assert not n.evpool.is_pending(ev)

    run(go())


def test_consensus_reported_conflicting_votes_become_evidence():
    async def go():
        net, nodes = make_cluster(4)
        await start_cluster(net, nodes)
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(3, timeout=60.0) for n in nodes)
            )
            node = nodes[0]
            vals = node.state_store.load_validators(2)
            header_time = node.block_store.load_block_meta(2).header.time_ns
            priv = PrivKeyEd25519.from_seed(bytes([103]) * 32)
            idx, _ = vals.get_by_address(priv.pub_key().address())
            ev = make_double_sign(priv, 2, vals, header_time, index=idx)
            # simulate what consensus does on ConflictingVoteError
            node.evpool.report_conflicting_votes(ev.vote_a, ev.vote_b)
            assert node.evpool.size() == 0  # buffered, not yet materialized

            async def materialized():
                while node.evpool.size() == 0:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(materialized(), 60.0)
            assert node.evpool.size() == 1
        finally:
            await stop_cluster(net, nodes)

    run(go())


# ---------------------------------------------------------------------------
# verification edge cases — table-driven, mirroring the reference
# internal/evidence/verify_test.go (TestVerifyDuplicateVoteEvidence,
# TestVerifyLightClientAttack*, the expiry corners of TestVerify)


from types import SimpleNamespace

from tendermint_tpu.evidence.verify import (
    verify_duplicate_vote,
    verify_evidence,
    verify_light_client_attack,
)
from tendermint_tpu.state.types import State
from tendermint_tpu.types.evidence import LightClientAttackEvidence
from tendermint_tpu.types.header import Header
from tendermint_tpu.types.params import ConsensusParams, EvidenceParams

from .test_light import CHAIN as LIGHT_CHAIN
from .test_light import build_chain, make_set
from .test_types import make_validators

NS = 1_000_000_000


def _vals_one():
    priv = PrivKeyEd25519.from_seed(bytes([7]) * 32)
    from tendermint_tpu.types.validator import Validator, ValidatorSet

    vals = ValidatorSet(
        [Validator(pub_key=priv.pub_key(), voting_power=10)]
    )
    return vals, priv


class TestDuplicateVoteValidateBasic:
    """reference: types/evidence_test.go TestDuplicateVoteEvidence
    ValidateBasic corners, via the table in verify_test.go:202."""

    def _good(self):
        vals, priv = _vals_one()
        return make_double_sign(priv, 10, vals, 5 * NS), vals, priv

    def test_good_evidence_passes(self):
        ev, _, _ = self._good()
        ev.validate_basic()

    def test_missing_vote_rejected(self):
        ev, _, _ = self._good()
        ev.vote_a = None
        with pytest.raises(ValueError, match="empty duplicate vote"):
            ev.validate_basic()

    def test_votes_in_wrong_order_rejected(self):
        """from_votes sorts by BlockID key; hand-built evidence with
        the order flipped must not validate."""
        ev, _, _ = self._good()
        ev.vote_a, ev.vote_b = ev.vote_b, ev.vote_a
        with pytest.raises(ValueError, match="invalid order"):
            ev.validate_basic()

    def test_identical_votes_rejected(self):
        ev, _, _ = self._good()
        ev.vote_b = ev.vote_a
        with pytest.raises(ValueError, match="invalid order|same block"):
            ev.validate_basic()

    def test_unsigned_vote_rejected(self):
        ev, _, _ = self._good()
        ev.vote_a.signature = b""
        with pytest.raises(ValueError, match="signature is missing"):
            ev.validate_basic()

    def test_from_votes_orders_by_block_id_key(self):
        """NewDuplicateVoteEvidence's canonical ordering: whichever
        argument order, vote_a gets the smaller BlockID key."""
        vals, priv = _vals_one()
        ev1 = make_double_sign(priv, 10, vals, 5 * NS)
        ev2 = DuplicateVoteEvidence.from_votes(
            ev1.vote_b, ev1.vote_a, block_time_ns=5 * NS, val_set=vals
        )
        assert ev2.vote_a.block_id == ev1.vote_a.block_id
        assert ev2.vote_b.block_id == ev1.vote_b.block_id


class TestVerifyDuplicateVote:
    """reference: internal/evidence/verify_test.go:202-263 table."""

    def _setup(self):
        vals, priv = _vals_one()
        ev = make_double_sign(priv, 10, vals, 5 * NS)
        return ev, vals, priv

    def test_valid_evidence_verifies(self):
        ev, vals, _ = self._setup()
        verify_duplicate_vote(ev, CHAIN, vals)

    @pytest.mark.parametrize(
        "mutate,err",
        [
            (lambda ev: setattr(ev.vote_b, "height", 11), "does not match"),
            (lambda ev: setattr(ev.vote_b, "round", 1), "does not match"),
            (
                lambda ev: setattr(
                    ev.vote_b,
                    "type",
                    1,  # PREVOTE vs vote_a's PRECOMMIT
                ),
                "does not match",
            ),
            (
                lambda ev: setattr(
                    ev.vote_b, "validator_address", b"\x42" * 20
                ),
                "addresses do not match",
            ),
            (
                lambda ev: setattr(ev.vote_b, "block_id", ev.vote_a.block_id),
                "same",
            ),
            (
                lambda ev: setattr(ev, "validator_power", 3),
                "validator power",
            ),
            (
                lambda ev: setattr(ev, "total_voting_power", 1),
                "total voting power",
            ),
        ],
        ids=[
            "height-mismatch",
            "round-mismatch",
            "type-mismatch",
            "address-mismatch",
            "same-block-id",
            "validator-power-mismatch",
            "total-power-mismatch",
        ],
    )
    def test_mismatches_rejected(self, mutate, err):
        ev, vals, _ = self._setup()
        mutate(ev)
        with pytest.raises(ValueError, match=err):
            verify_duplicate_vote(ev, CHAIN, vals)

    def test_validator_not_in_set_rejected(self):
        ev, _, _ = self._setup()
        from tendermint_tpu.types.validator import Validator, ValidatorSet

        stranger = PrivKeyEd25519.from_seed(bytes([9]) * 32)
        vals2 = ValidatorSet(
            [Validator(pub_key=stranger.pub_key(), voting_power=10)]
        )
        with pytest.raises(ValueError, match="was not a validator"):
            verify_duplicate_vote(ev, CHAIN, vals2)

    def test_forged_signature_rejected(self):
        ev, vals, _ = self._setup()
        sig = bytearray(ev.vote_b.signature)
        sig[0] ^= 0xFF
        ev.vote_b.signature = bytes(sig)
        with pytest.raises(ValueError, match="invalid signature"):
            verify_duplicate_vote(ev, CHAIN, vals)


def _lca_fixture(common_height=10, attack_height=10, n_heights=10):
    """Trusted chain + a conflicting chain (different app_hash, same
    validators) and the assembled LightClientAttackEvidence."""
    base = 1_700_000_000 * NS
    trusted = build_chain(n_heights, base_time_ns=base)
    conflicting = build_chain(
        n_heights, base_time_ns=base, app_hash=b"\x66" * 32
    )
    vals = trusted[common_height].validator_set
    ev = LightClientAttackEvidence(
        conflicting_block=conflicting[attack_height],
        common_height=common_height,
        total_voting_power=vals.total_voting_power(),
        timestamp_ns=trusted[common_height].signed_header.header.time_ns,
    )
    return ev, vals, trusted


class TestVerifyLightClientAttack:
    """reference: internal/evidence/verify_test.go:159-200 —
    including the equivocation corner where CommonHeight == the
    conflicting block's Height (no forward lunatic gap)."""

    def test_common_height_equals_height_verifies(self):
        ev, vals, trusted = _lca_fixture(10, 10)
        assert ev.conflicting_block.signed_header.header.height == (
            ev.common_height
        )
        verify_light_client_attack(
            ev, LIGHT_CHAIN, vals, trusted[10].signed_header.header
        )
        # ValidateBasic holds for the same shape
        ev.validate_basic()

    def test_conflicting_equals_trusted_is_not_attack(self):
        ev, vals, trusted = _lca_fixture(10, 10)
        same = trusted[10]
        ev.conflicting_block = same
        with pytest.raises(ValueError, match="not an attack"):
            verify_light_client_attack(
                ev, LIGHT_CHAIN, vals, trusted[10].signed_header.header
            )

    def test_total_voting_power_mismatch_rejected(self):
        ev, vals, trusted = _lca_fixture(10, 10)
        ev.total_voting_power += 1
        with pytest.raises(ValueError, match="total voting power"):
            verify_light_client_attack(
                ev, LIGHT_CHAIN, vals, trusted[10].signed_header.header
            )

    def test_incomplete_conflicting_block_rejected(self):
        ev, vals, trusted = _lca_fixture(10, 10)
        ev.conflicting_block = SimpleNamespace(signed_header=None)
        with pytest.raises(ValueError, match="incomplete"):
            verify_light_client_attack(
                ev, LIGHT_CHAIN, vals, trusted[10].signed_header.header
            )

    def test_commit_without_trusted_third_rejected(self):
        """The conflicting commit must carry 1/3 of the common-height
        set: a disjoint signer set fails the trusting verify."""
        ev, _, trusted = _lca_fixture(10, 10)
        stranger_vals, _ = make_set([21, 22, 23, 24])
        ev.total_voting_power = stranger_vals.total_voting_power()
        with pytest.raises(ValueError):
            verify_light_client_attack(
                ev,
                LIGHT_CHAIN,
                stranger_vals,
                trusted[10].signed_header.header,
            )

    def test_common_height_must_be_positive(self):
        ev, _, _ = _lca_fixture(10, 10)
        ev.common_height = 0
        with pytest.raises(ValueError, match="common height"):
            ev.validate_basic()


class _StateStore:
    def __init__(self, vals):
        self._vals = vals

    def load_validators(self, height):
        return self._vals


class _BlockStore:
    def __init__(self, headers):
        self._headers = headers  # height -> Header

    def load_block_meta(self, height):
        h = self._headers.get(height)
        return SimpleNamespace(header=h) if h is not None else None


def _expiry_fixture(age_blocks, age_ns):
    """Evidence at height 1 with state advanced by (age_blocks,
    age_ns) past it, expiry params 10 blocks / 100 s."""
    vals, priv = _vals_one()
    t0 = 1_700_000_000 * NS
    ev = make_double_sign(priv, 1, vals, t0)
    header = Header(chain_id=CHAIN, height=1, time_ns=t0)
    state = State(
        chain_id=CHAIN,
        last_block_height=1 + age_blocks,
        last_block_time_ns=t0 + age_ns,
        consensus_params=ConsensusParams(
            evidence=EvidenceParams(
                max_age_num_blocks=10,
                max_age_duration_ns=100 * NS,
            )
        ),
    )
    return ev, state, _StateStore(vals), _BlockStore({1: header})


class TestEvidenceExpiry:
    """reference verify.go:24-61: evidence expires only when BOTH the
    block-count and duration bounds are exceeded — expired on one
    bound but not the other must still verify (the corner VERDICT
    next #9 asks for)."""

    def test_fresh_on_both_bounds_verifies(self):
        ev, state, ss, bs = _expiry_fixture(age_blocks=5, age_ns=50 * NS)
        verify_evidence(ev, state, ss, bs)

    def test_expired_blocks_but_fresh_duration_verifies(self):
        ev, state, ss, bs = _expiry_fixture(age_blocks=50, age_ns=50 * NS)
        verify_evidence(ev, state, ss, bs)

    def test_expired_duration_but_fresh_blocks_verifies(self):
        ev, state, ss, bs = _expiry_fixture(age_blocks=5, age_ns=500 * NS)
        verify_evidence(ev, state, ss, bs)

    def test_expired_on_both_bounds_rejected(self):
        ev, state, ss, bs = _expiry_fixture(
            age_blocks=50, age_ns=500 * NS
        )
        with pytest.raises(ValueError, match="too old"):
            verify_evidence(ev, state, ss, bs)

    def test_exactly_at_both_bounds_verifies(self):
        """Go uses strict `>` on both comparisons: exactly at the
        bounds is NOT expired."""
        ev, state, ss, bs = _expiry_fixture(
            age_blocks=10, age_ns=100 * NS
        )
        verify_evidence(ev, state, ss, bs)

    def test_missing_header_rejected(self):
        ev, state, ss, _ = _expiry_fixture(5, 50 * NS)
        with pytest.raises(ValueError, match="don't have header"):
            verify_evidence(ev, state, ss, _BlockStore({}))

    def test_timestamp_mismatch_with_block_rejected(self):
        ev, state, ss, bs = _expiry_fixture(5, 50 * NS)
        ev.timestamp_ns += 1
        with pytest.raises(ValueError, match="different time"):
            verify_evidence(ev, state, ss, bs)


# ---------------------------------------------------------------------------
# pool metrics + pruning (ISSUE 18 satellites): the evidence metrics
# family tracks the lifecycle on a live net, pruning counts expiries,
# and committed evidence is never re-admitted


def test_pool_metrics_and_pruning_on_live_net():
    from tendermint_tpu.evidence import EvidenceMetrics
    from tendermint_tpu.libs.metrics import Registry

    async def go():
        net, nodes = make_cluster(4)
        await start_cluster(net, nodes)
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(3, timeout=60.0) for n in nodes)
            )
            node = nodes[0]
            # a private registry: the cluster harness pools share
            # DEFAULT_REGISTRY, where four nodes' gauges overwrite
            # each other (real nodes get per-node registries from
            # node assembly)
            node.evpool.metrics = EvidenceMetrics(Registry())
            m = node.evpool.metrics
            assert m.pool_size.value() == 0.0

            vals = node.state_store.load_validators(2)
            t2 = node.block_store.load_block_meta(2).header.time_ns
            priv = PrivKeyEd25519.from_seed(bytes([103]) * 32)
            idx, _ = vals.get_by_address(priv.pub_key().address())
            ev = make_double_sign(priv, 2, vals, t2, index=idx)
            node.evpool.add_evidence(ev)
            assert m.pool_size.value() == 1.0

            async def committed():
                while not node.evpool.is_committed(ev):
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(committed(), 60.0)
            # committed: drained from pending, counted once, and
            # never re-admitted (the no-regossip guarantee)
            assert m.pool_size.value() == 0.0
            assert m.committed_total.value() >= 1.0
            node.evpool.add_evidence(ev)
            assert not node.evpool.is_pending(ev)
            assert m.pool_size.value() == 0.0

            # pruning: fresh evidence at height 2, then a state past
            # BOTH expiry bounds (verify.py's AND-semantics) — the
            # prune drops it and counts the missed accountability
            priv2 = PrivKeyEd25519.from_seed(bytes([102]) * 32)
            idx2, _ = vals.get_by_address(priv2.pub_key().address())
            ev2 = make_double_sign(priv2, 2, vals, t2, index=idx2)
            node.evpool.add_evidence(ev2)
            assert m.pool_size.value() == 1.0
            aged = State(
                chain_id=CHAIN,
                last_block_height=2 + 50,
                last_block_time_ns=t2 + 500 * NS,
                consensus_params=ConsensusParams(
                    evidence=EvidenceParams(
                        max_age_num_blocks=10,
                        max_age_duration_ns=100 * NS,
                    )
                ),
            )
            node.evpool.update(aged, [])
            assert not node.evpool.is_pending(ev2)
            assert m.pool_size.value() == 0.0
            assert m.expired_total.value() == 1.0
        finally:
            await stop_cluster(net, nodes)

    run(go())
