"""Evidence pool + reactor tests: double-sign evidence is formed,
verified, gossiped, committed into a block, and reported to the app
(reference model: internal/evidence/pool_test.go, verify_test.go,
reactor_test.go)."""

import asyncio

import pytest

from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.evidence import EvidenceError, EvidencePool
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.vote import Vote
from tendermint_tpu.store.kv import MemKV

from .test_reactors import CHAIN, make_cluster, start_cluster, stop_cluster


def run(coro):
    return asyncio.run(coro)


def make_double_sign(priv, height, vals, time_ns, index=0):
    """Two conflicting precommits by the same validator."""
    addr = priv.pub_key().address()

    def vote_for(tag):
        v = Vote(
            type=PRECOMMIT_TYPE,
            height=height,
            round=0,
            block_id=BlockID(
                hash=tag * 32, part_set_header=PartSetHeader(1, tag * 32)
            ),
            timestamp_ns=time_ns,
            validator_address=addr,
            validator_index=index,
        )
        v.signature = priv.sign(v.sign_bytes(CHAIN))
        return v

    va, vb = vote_for(b"\xaa"), vote_for(b"\xbb")
    return DuplicateVoteEvidence.from_votes(
        va, vb, block_time_ns=time_ns, val_set=vals
    )


def test_pool_verifies_and_admits_double_sign_evidence():
    async def go():
        net, nodes = make_cluster(4)
        await start_cluster(net, nodes)
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(3, timeout=60.0) for n in nodes)
            )
        finally:
            await stop_cluster(net, nodes)

        node = nodes[0]
        vals = node.state_store.load_validators(2)
        header_time = node.block_store.load_block_meta(2).header.time_ns
        # priv index 1 double-signed at height 2
        priv = PrivKeyEd25519.from_seed(bytes([101]) * 32)
        idx, _val = vals.get_by_address(priv.pub_key().address())
        ev = make_double_sign(priv, 2, vals, header_time, index=idx)

        node.evpool.add_evidence(ev)
        assert node.evpool.is_pending(ev)
        pending, size = node.evpool.pending_evidence(1 << 20)
        assert len(pending) == 1 and size > 0
        node.evpool.check_evidence(pending)  # block-validation path

        # garbage evidence is refused
        bad = make_double_sign(priv, 2, vals, header_time, index=idx)
        bad.vote_b.signature = b"\x00" * 64
        with pytest.raises(EvidenceError):
            node.evpool.add_evidence(bad)

    run(go())


def test_evidence_gossips_and_commits():
    async def go():
        net, nodes = make_cluster(4)
        await start_cluster(net, nodes)
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(3, timeout=60.0) for n in nodes)
            )
            node = nodes[0]
            vals = node.state_store.load_validators(2)
            header_time = node.block_store.load_block_meta(2).header.time_ns
            priv = PrivKeyEd25519.from_seed(bytes([102]) * 32)
            idx, _ = vals.get_by_address(priv.pub_key().address())
            ev = make_double_sign(priv, 2, vals, header_time, index=idx)
            node.evpool.add_evidence(ev)

            # evidence must reach every pool and land in a committed block
            async def committed_everywhere():
                while not all(n.evpool.is_committed(ev) for n in nodes):
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(committed_everywhere(), 60.0)
        finally:
            await stop_cluster(net, nodes)

        # find the block carrying it and check ABCI byzantine report
        found = False
        for h in range(1, nodes[0].block_store.height() + 1):
            block = nodes[0].block_store.load_block(h)
            if block.evidence:
                found = True
                assert block.evidence[0].hash() == ev.hash()
        assert found, "evidence never committed into a block"
        for n in nodes:
            assert not n.evpool.is_pending(ev)

    run(go())


def test_consensus_reported_conflicting_votes_become_evidence():
    async def go():
        net, nodes = make_cluster(4)
        await start_cluster(net, nodes)
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(3, timeout=60.0) for n in nodes)
            )
            node = nodes[0]
            vals = node.state_store.load_validators(2)
            header_time = node.block_store.load_block_meta(2).header.time_ns
            priv = PrivKeyEd25519.from_seed(bytes([103]) * 32)
            idx, _ = vals.get_by_address(priv.pub_key().address())
            ev = make_double_sign(priv, 2, vals, header_time, index=idx)
            # simulate what consensus does on ConflictingVoteError
            node.evpool.report_conflicting_votes(ev.vote_a, ev.vote_b)
            assert node.evpool.size() == 0  # buffered, not yet materialized

            async def materialized():
                while node.evpool.size() == 0:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(materialized(), 60.0)
            assert node.evpool.size() == 1
        finally:
            await stop_cluster(net, nodes)

    run(go())
