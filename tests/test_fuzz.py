"""Fuzz tests: random/mutated bytes against every surface that parses
untrusted input (reference: test/fuzz/{mempool/checktx.go,
p2p/secretconnection, rpc/jsonrpc}, plus internal/consensus/wal_fuzz.go).

Deterministic seeds: failures reproduce. The property under test is
always "rejects cleanly or round-trips" — never a crash, hang, or
uncontrolled exception type.
"""

import asyncio
import random

import pytest

from tendermint_tpu.abci import KVStoreApplication, LocalClient
from tendermint_tpu.config import MempoolConfig
from tendermint_tpu.mempool import MempoolError, TxMempool

random.seed(0xF22)


def _rand_bytes(max_len=512):
    return bytes(
        random.randrange(256) for _ in range(random.randrange(max_len))
    )


class TestProtoDecoderFuzz:
    """Every from_proto must raise ValueError-family errors (or parse)
    on arbitrary bytes — never IndexError/KeyError/UnboundLocal/hangs."""

    CODECS = None

    @classmethod
    def _codecs(cls):
        if cls.CODECS is None:
            from tendermint_tpu.blocksync import msgs as bs
            from tendermint_tpu.consensus import msgs as cs
            from tendermint_tpu.p2p.pex import _Codec as PexCodec
            from tendermint_tpu.statesync import msgs as ss
            from tendermint_tpu.types.block import Block
            from tendermint_tpu.types.commit import Commit
            from tendermint_tpu.types.evidence import evidence_from_proto
            from tendermint_tpu.types.header import Header
            from tendermint_tpu.types.light import LightBlock
            from tendermint_tpu.types.proposal import Proposal
            from tendermint_tpu.types.validator import ValidatorSet
            from tendermint_tpu.types.vote import Vote

            cls.CODECS = [
                Vote.from_proto,
                Proposal.from_proto,
                Commit.from_proto,
                Header.from_proto,
                Block.from_proto,
                LightBlock.from_proto,
                ValidatorSet.from_proto,
                evidence_from_proto,
                cs.decode_msg,
                bs.BlocksyncCodec.decode,
                ss.StatesyncCodec.decode,
                PexCodec.decode,
            ]
            cls.CODECS = [c for c in cls.CODECS if c is not None]
        return cls.CODECS

    @pytest.mark.parametrize("trial", range(8))
    def test_random_bytes(self, trial):
        random.seed(0x1000 + trial)
        for decoder in self._codecs():
            for _ in range(40):
                data = _rand_bytes()
                try:
                    decoder(data)
                except (ValueError, KeyError, TypeError, EOFError):
                    # structured rejection is fine; KeyError/TypeError
                    # would ideally normalize to ValueError but must at
                    # least be deterministic exceptions, not crashes
                    pass

    def test_mutated_valid_messages(self):
        """Bit-flip real encodings: decoders must reject or reparse,
        never wedge."""
        import time as _time

        from tendermint_tpu.types.block_id import BlockID, PartSetHeader
        from tendermint_tpu.types.canonical import PRECOMMIT_TYPE
        from tendermint_tpu.types.vote import Vote

        vote = Vote(
            type=PRECOMMIT_TYPE,
            height=7,
            round=1,
            block_id=BlockID(
                hash=b"\x01" * 32,
                part_set_header=PartSetHeader(total=3, hash=b"\x02" * 32),
            ),
            timestamp_ns=_time.time_ns(),
            validator_address=b"\x03" * 20,
            validator_index=2,
            signature=b"\x04" * 64,
        )
        blob = vote.to_proto()
        random.seed(0xBEEF)
        for _ in range(300):
            b = bytearray(blob)
            for _ in range(random.randrange(1, 4)):
                b[random.randrange(len(b))] ^= 1 << random.randrange(8)
            try:
                Vote.from_proto(bytes(b))
            except (ValueError, KeyError, TypeError, EOFError):
                pass


class TestMempoolCheckTxFuzz:
    """reference: test/fuzz/mempool/checktx.go — arbitrary tx bytes
    through CheckTx must be accepted or rejected, never corrupt the
    pool accounting."""

    def test_random_txs(self):
        async def go():
            app = KVStoreApplication()
            mp = TxMempool(
                LocalClient(app), MempoolConfig(size=100, cache_size=200)
            )
            random.seed(0x2000)
            accepted = 0
            for _ in range(300):
                tx = _rand_bytes(64)
                try:
                    res = await mp.check_tx(tx)
                    if res.is_ok:
                        accepted += 1
                except MempoolError:
                    pass
            assert mp.size() <= 100
            assert mp.size_bytes() >= 0
            # pool accounting must reconcile with the entries
            assert mp.size_bytes() == sum(
                w.size() for w in mp._txs.values()
            )

        asyncio.run(go())


class TestSecretConnectionFuzz:
    """reference: test/fuzz/p2p/secretconnection — garbage on the wire
    during/after the handshake must fail cleanly."""

    def test_garbage_handshake(self):
        from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
        from tendermint_tpu.p2p.conn import SecretConnection

        async def go():
            random.seed(0x3000)
            for trial in range(10):
                server_up = asyncio.Event()
                # exceptions in a start_server handler task never
                # propagate; record the outcome and assert after
                result = {}

                async def evil_client(reader, writer):
                    writer.write(_rand_bytes(200) or b"\x00")
                    try:
                        await writer.drain()
                        writer.close()
                    except ConnectionError:
                        pass

                async def handle(reader, writer):
                    try:
                        await asyncio.wait_for(
                            SecretConnection.handshake(
                                reader,
                                writer,
                                PrivKeyEd25519.from_seed(b"\x05" * 32),
                            ),
                            timeout=5.0,
                        )
                        result["accepted_garbage"] = True
                    except Exception:
                        result["accepted_garbage"] = False  # rejected
                    finally:
                        server_up.set()
                        writer.close()

                server = await asyncio.start_server(
                    handle, "127.0.0.1", 0
                )
                port = server.sockets[0].getsockname()[1]
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port
                )
                await evil_client(reader, writer)
                await asyncio.wait_for(server_up.wait(), timeout=10.0)
                server.close()
                await server.wait_closed()
                assert result.get("accepted_garbage") is False, (
                    f"trial {trial}: handshake accepted garbage"
                )

        asyncio.run(go())

    def test_tampered_frames_post_handshake(self):
        """AEAD must reject modified ciphertext as a connection error."""
        from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
        from tendermint_tpu.p2p.conn import SecretConnection

        async def go():
            done = asyncio.Event()
            result = {}

            async def server_side(reader, writer):
                try:
                    sc = await SecretConnection.handshake(
                        reader, writer, PrivKeyEd25519.from_seed(b"\x06" * 32)
                    )
                    await sc.read_frame()
                    result["ok"] = True
                except Exception as e:
                    result["err"] = type(e).__name__
                finally:
                    done.set()
                    writer.close()

            server = await asyncio.start_server(
                server_side, "127.0.0.1", 0
            )
            port = server.sockets[0].getsockname()[1]
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            sc = await SecretConnection.handshake(
                reader, writer, PrivKeyEd25519.from_seed(b"\x07" * 32)
            )
            # write a frame, then flip one ciphertext byte before it
            # hits the wire: emulate by sending a manually corrupted
            # frame (encrypt honestly, tamper the bytes)
            import tendermint_tpu.p2p.conn as connmod

            frame = b"hello underneath the aead"
            # encrypt via the real path into a buffer
            class _Cap:
                def __init__(self):
                    self.buf = b""

                def write(self, b):
                    self.buf += b

                async def drain(self):
                    pass

            cap = _Cap()
            real_writer = sc._writer
            sc._writer = cap
            await sc.write_frame(frame)
            sc._writer = real_writer
            tampered = bytearray(cap.buf)
            tampered[-1] ^= 1
            real_writer.write(bytes(tampered))
            await real_writer.drain()
            await asyncio.wait_for(done.wait(), timeout=10.0)
            assert "ok" not in result, "tampered frame accepted"
            server.close()
            await server.wait_closed()
            writer.close()

        asyncio.run(go())


class TestJSONRPCServerFuzz:
    """reference: test/fuzz/rpc/jsonrpc — random bodies against the
    HTTP handler must produce JSON-RPC errors, not crashes."""

    def test_random_bodies(self):
        from tendermint_tpu.rpc.jsonrpc import JSONRPCServer, RPCRequest

        async def ok_handler(req: RPCRequest):
            return {"ok": True}

        async def go():
            srv = JSONRPCServer({"m": ok_handler})
            await srv.start("127.0.0.1", 0)
            port = srv.bound_port
            random.seed(0x4000)
            try:
                for _ in range(25):
                    body = _rand_bytes(300)
                    reader, writer = await asyncio.open_connection(
                        "127.0.0.1", port
                    )
                    writer.write(
                        b"POST / HTTP/1.1\r\nHost: x\r\n"
                        b"Content-Length: %d\r\n\r\n" % len(body) + body
                    )
                    await writer.drain()
                    line = await asyncio.wait_for(
                        reader.readline(), timeout=5.0
                    )
                    assert b"200" in line  # JSON-RPC error inside a 200
                    writer.close()
                # and a valid call still works afterwards
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port
                )
                body = b'{"jsonrpc":"2.0","id":1,"method":"m","params":{}}'
                writer.write(
                    b"POST / HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Length: %d\r\n\r\n" % len(body) + body
                )
                await writer.drain()
                line = await asyncio.wait_for(reader.readline(), timeout=5.0)
                assert b"200" in line
                writer.close()
            finally:
                await srv.stop()

        asyncio.run(go())


class TestWALFuzz:
    """reference: internal/consensus/wal_fuzz.go — arbitrary trailing
    garbage in the WAL file must be truncated at the last valid record,
    never crash recovery."""

    def test_garbage_tails(self, tmp_path):
        from tendermint_tpu.consensus.wal import WAL, iter_wal_records

        async def go():
            random.seed(0x5000)
            for trial in range(10):
                path = str(tmp_path / f"wal{trial}")
                wal = WAL(path)
                await wal.start()
                for h in (1, 2, 3):
                    wal.write_end_height(h)
                await wal.stop()
                with open(path, "ab") as f:
                    f.write(_rand_bytes(100))
                records = list(iter_wal_records(path))
                assert len(records) >= 3  # valid prefix kept, no crash
                # recovery opens and appends cleanly
                wal2 = WAL(path)
                await wal2.start()
                wal2.write_end_height(4)
                await wal2.stop()

        asyncio.run(go())
