"""Real-process e2e: separate OS processes, TCP p2p, socket ABCI,
real signals (reference: test/e2e/runner/perturb.go:43-77).

These spawn actual `python -m tendermint_tpu.cmd start` subprocesses —
minutes, not seconds — so they carry the slow marker. They are the
only tests where SIGKILL'd-for-real WAL recovery and ABCI handshake
replay against a surviving app process are exercised end-to-end.
"""

import asyncio
import os
import signal

import pytest

from tendermint_tpu.e2e.manifest import Manifest
from tendermint_tpu.e2e.process_runner import ProcessRunner


def run(coro):
    return asyncio.run(coro)


@pytest.mark.slow
def test_process_net_converges(tmp_path):
    """A 2-validator process net reaches its target height; invariants
    (hash agreement over RPC) and the block-interval benchmark hold."""
    m = Manifest(
        chain_id="proc-ci",
        validators={"v0": 10, "v1": 10},
        target_height=4,
    )
    m.validate()
    rep = run(ProcessRunner(m, str(tmp_path), timeout=240.0).run())
    assert rep.ok, rep.failures
    assert rep.reached_height >= 4
    assert rep.blocks >= 3


@pytest.mark.slow
def test_process_net_sigkill_recovery(tmp_path):
    """SIGKILL one of four validators mid-run: the dead process's WAL
    and sqlite stores are reopened by a fresh process, the ABCI
    handshake replays against the still-running app, and the network
    converges with no fork (the crash path the in-process runner
    cannot exercise).

    History: this test stalled on the seed (the restarted validator
    wedged at its boot height while the net ran ~270 heights ahead).
    Root cause — diagnosed with tmlive's thread-root/reachability
    substrate and debug-level process logs — was NOT a blocking site
    but catchup-vote loss: the reborn node announces its height while
    its consensus reactor is still in wait_sync (blocksync grace), the
    peers stream the stored-commit precommits into the void and mark
    them delivered, and nothing ever resends. Fixed by the gossip-votes
    stall-reset in consensus/reactor.py (`vote_catchup_stall`); the
    deterministic regression lives at tests/test_reactors.py::
    test_catchup_votes_dropped_during_wait_sync_are_resent."""
    m = Manifest.parse(
        {
            "chain_id": "proc-kill-ci",
            "target_height": 5,
            "validators": {"v0": 10, "v1": 10, "v2": 10, "v3": 10},
            "node": {"v1": {"perturb": ["kill:2"]}},
            "load": {"tx_rate": 1, "tx_size": 48},
        }
    )
    m.validate()
    runner = ProcessRunner(m, str(tmp_path), timeout=340.0)
    rep = run(runner.run())
    assert rep.ok, rep.failures
    assert rep.reached_height >= 5
    # the kill really happened: the first node process is dead and a
    # different pid carried the node to the end
    log = open(
        os.path.join(str(tmp_path), "v1", "node.log"), "rb"
    ).read()
    # "completed ABCI handshake" appears exactly once per successful
    # boot (replay.py) — two completions prove the post-SIGKILL
    # process really re-handshook ("ABCI handshake" alone would match
    # twice in a single boot)
    assert log.count(b"completed ABCI handshake") >= 2, (
        "expected a second completed handshake from the post-SIGKILL "
        "process"
    )
    assert rep.txs_submitted > 0 and rep.txs_committed > 0


@pytest.mark.slow
def test_process_net_partition_heal_during_catchup(tmp_path):
    """ISSUE 13: the PR-9 wedge class under REAL faults — SIGKILL one
    of four validators, then cut the reborn process off mid-catchup
    with a genuine p2p-level partition (TM_TPU_PARTITION_FILE: every
    child polls the shared spec file; its links drop every frame while
    the process keeps running and serving RPC), then heal. The
    surviving 3/4 majority must keep committing through the partition,
    and after heal the victim must converge to the target with no fork
    — which exercises both the catchup stall-reset (PR 9) and the
    live-height gossip stall-reset (this PR) against marks that lied
    because frames died on a surviving connection."""
    m = Manifest.parse(
        {
            "chain_id": "proc-part-ci",
            "target_height": 10,
            "validators": {"v0": 10, "v1": 10, "v2": 10, "v3": 10},
            "node": {
                "v1": {"perturb": ["kill:2", "partition:4", "heal:8"]}
            },
            "load": {"tx_rate": 1, "tx_size": 48},
        }
    )
    m.validate()
    runner = ProcessRunner(m, str(tmp_path), timeout=340.0)
    rep = run(runner.run())
    assert rep.ok, rep.failures
    assert rep.reached_height >= 10
    # the kill really happened (two completed ABCI handshakes = two
    # real boots), and the partition file really mutated
    log = open(
        os.path.join(str(tmp_path), "v1", "node.log"), "rb"
    ).read()
    assert log.count(b"completed ABCI handshake") >= 2
    spec = open(os.path.join(str(tmp_path), "partition.spec")).read()
    assert spec == ""  # healed at the end
    assert rep.txs_submitted > 0 and rep.txs_committed > 0


def test_process_runner_rejects_inprocess_only_features(tmp_path):
    m = Manifest.parse(
        {
            "chain_id": "p",
            "validators": {"v0": 10},
            "node": {"v0": {"misbehaviors": {"double-prevote": 3}}},
        }
    )
    with pytest.raises(ValueError, match="in-process"):
        ProcessRunner(m, str(tmp_path))


def test_child_env_strips_device_plugin():
    """Child node processes must never touch the TPU tunnel: the axon
    site dir is stripped and JAX_PLATFORMS pinned to cpu."""
    from tendermint_tpu.e2e.process_runner import _child_env

    env = _child_env()
    assert env["JAX_PLATFORMS"] == "cpu"
    assert ".axon_site" not in env.get("PYTHONPATH", "")


def test_partition_perturbation_parses_and_maps():
    """partition/heal are first-class manifest perturbations: they
    parse, round-trip validation, and the process runner maps them to
    partition-file writes (TM_TPU_PARTITION_FILE plumbing)."""
    import inspect

    from tendermint_tpu.e2e import process_runner as pr
    from tendermint_tpu.e2e.manifest import Perturbation

    p = Perturbation.parse("partition:4")
    assert (p.action, p.height) == ("partition", 4)
    assert Perturbation.parse("heal:8").action == "heal"
    src = inspect.getsource(pr.ProcessRunner._apply_perturbation)
    assert "partition" in src and "heal" in src
    spawn = inspect.getsource(pr.ProcessRunner._spawn_node)
    assert "TM_TPU_PARTITION_FILE" in spawn


def test_perturbation_signals_map():
    """kill/restart/pause/disconnect all map to real signals in the
    process runner (SIGKILL / SIGTERM / SIGSTOP+SIGCONT)."""
    import inspect

    from tendermint_tpu.e2e import process_runner as pr

    src = inspect.getsource(pr.ProcessRunner._apply_perturbation)
    assert "SIGKILL" in src and "SIGTERM" in src
    assert "SIGSTOP" in src and "SIGCONT" in src
    assert signal.SIGKILL  # the platform actually has them


@pytest.mark.slow
def test_process_net_state_sync(tmp_path):
    """A late-joining full node in its own OS process state-syncs from
    snapshot-serving app processes: trust root seeded over live RPC,
    chunks restored via socket ABCI, and the end state proves a real
    restore (earliest stored block above genesis)."""
    m = Manifest.parse(
        {
            "chain_id": "proc-ss-ci",
            "target_height": 8,
            "validators": {"v0": 10, "v1": 10, "v2": 10},
            "node": {
                "joiner": {
                    "mode": "full",
                    "state_sync": True,
                    "start_at": 5,
                }
            },
            "load": {"tx_rate": 1, "tx_size": 48},
        }
    )
    m.validate()
    rep = run(ProcessRunner(m, str(tmp_path), timeout=340.0).run())
    assert rep.ok, rep.failures
    assert rep.state_synced.get("joiner") is True


@pytest.mark.slow
def test_process_remote_signer_node(tmp_path):
    """A validator whose key lives in a SEPARATE signer process (the
    tmkms deployment shape): the node exposes [priv_validator]
    listen_addr, `cmd signer` dials it over SecretConnection, and the
    chain only advances once the signer is attached. SIGKILLing the
    signer stalls signing; a restarted signer (same last-sign state on
    disk) resumes it."""
    import subprocess
    import sys
    import time as _time

    from tendermint_tpu.e2e.process_runner import _child_env, _free_port

    home = str(tmp_path / "val")
    env = _child_env()
    subprocess.run(
        [sys.executable, "-m", "tendermint_tpu.cmd", "--home", home,
         "init", "validator", "--chain-id", "proc-signer-ci"],
        check=True, env=env, capture_output=True,
    )
    pv_port = _free_port()
    rpc_port = _free_port()
    # point the node at the remote signer + fast consensus timeouts
    from tendermint_tpu.cmd.commands import _load_home
    from tendermint_tpu.config import write_config

    cfg = _load_home(home)
    cfg.priv_validator.listen_addr = f"tcp://127.0.0.1:{pv_port}"
    cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
    cfg.consensus.timeout_commit = 0.2
    write_config(cfg, f"{home}/config/config.toml")

    node_log = open(tmp_path / "node.log", "wb")
    node = subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cmd", "--home", home,
         "start"],
        stdout=node_log, stderr=subprocess.STDOUT, env=env,
    )
    signer_log = open(tmp_path / "signer.log", "wb")
    signer = subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.cmd", "--home", home,
         "signer", "--addr", f"tcp://127.0.0.1:{pv_port}"],
        stdout=signer_log, stderr=subprocess.STDOUT, env=env,
    )

    def height() -> int:
        import json
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{rpc_port}/",
            data=json.dumps(
                {"jsonrpc": "2.0", "id": 1, "method": "status",
                 "params": {}}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=3) as r:
            res = json.loads(r.read())
        return int(
            res["result"]["sync_info"]["latest_block_height"]
        )

    try:
        deadline = _time.monotonic() + 120
        h = -1
        while _time.monotonic() < deadline:
            try:
                h = height()
                if h >= 3:
                    break
            except Exception:
                pass
            _time.sleep(0.5)
        assert h >= 3, f"remote-signer chain stuck at {h}"

        # kill the signer: the chain must stall (no local key at all)
        signer.kill()
        signer.wait()
        _time.sleep(3.0)

        def height_retry(tries=8):
            last = None
            for _ in range(tries):
                try:
                    return height()
                except Exception as e:
                    last = e
                    _time.sleep(0.5)
            raise last

        stalled = height_retry()
        _time.sleep(4.0)
        assert height_retry() <= stalled + 1, (
            "chain advanced without signer"
        )

        # a fresh signer process resumes from the on-disk sign state
        signer = subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu.cmd", "--home", home,
             "signer", "--addr", f"tcp://127.0.0.1:{pv_port}"],
            stdout=signer_log, stderr=subprocess.STDOUT, env=env,
        )
        deadline = _time.monotonic() + 90
        resumed = False
        while _time.monotonic() < deadline:
            try:
                if height() >= stalled + 2:
                    resumed = True
                    break
            except Exception:
                pass
            _time.sleep(0.5)
        assert resumed, "chain did not resume after signer restart"
    finally:
        for p in (signer, node):
            if p.poll() is None:
                p.terminate()
        for p in (signer, node):
            try:
                p.wait(20)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        node_log.close()
        signer_log.close()
