"""Real-process e2e: separate OS processes, TCP p2p, socket ABCI,
real signals (reference: test/e2e/runner/perturb.go:43-77).

These spawn actual `python -m tendermint_tpu.cmd start` subprocesses —
minutes, not seconds — so they carry the slow marker. They are the
only tests where SIGKILL'd-for-real WAL recovery and ABCI handshake
replay against a surviving app process are exercised end-to-end.
"""

import asyncio
import os
import signal

import pytest

from tendermint_tpu.e2e.manifest import Manifest
from tendermint_tpu.e2e.process_runner import ProcessRunner


def run(coro):
    return asyncio.run(coro)


@pytest.mark.slow
def test_process_net_converges(tmp_path):
    """A 2-validator process net reaches its target height; invariants
    (hash agreement over RPC) and the block-interval benchmark hold."""
    m = Manifest(
        chain_id="proc-ci",
        validators={"v0": 10, "v1": 10},
        target_height=4,
    )
    m.validate()
    rep = run(ProcessRunner(m, str(tmp_path), timeout=240.0).run())
    assert rep.ok, rep.failures
    assert rep.reached_height >= 4
    assert rep.blocks >= 3


@pytest.mark.slow
def test_process_net_sigkill_recovery(tmp_path):
    """SIGKILL one of four validators mid-run: the dead process's WAL
    and sqlite stores are reopened by a fresh process, the ABCI
    handshake replays against the still-running app, and the network
    converges with no fork (the crash path the in-process runner
    cannot exercise)."""
    m = Manifest.parse(
        {
            "chain_id": "proc-kill-ci",
            "target_height": 5,
            "validators": {"v0": 10, "v1": 10, "v2": 10, "v3": 10},
            "node": {"v1": {"perturb": ["kill:2"]}},
            "load": {"tx_rate": 1, "tx_size": 48},
        }
    )
    m.validate()
    runner = ProcessRunner(m, str(tmp_path), timeout=340.0)
    rep = run(runner.run())
    assert rep.ok, rep.failures
    assert rep.reached_height >= 5
    # the kill really happened: the first node process is dead and a
    # different pid carried the node to the end
    log = open(
        os.path.join(str(tmp_path), "v1", "node.log"), "rb"
    ).read()
    # "completed ABCI handshake" appears exactly once per successful
    # boot (replay.py) — two completions prove the post-SIGKILL
    # process really re-handshook ("ABCI handshake" alone would match
    # twice in a single boot)
    assert log.count(b"completed ABCI handshake") >= 2, (
        "expected a second completed handshake from the post-SIGKILL "
        "process"
    )
    assert rep.txs_submitted > 0 and rep.txs_committed > 0


def test_process_runner_rejects_inprocess_only_features(tmp_path):
    m = Manifest.parse(
        {
            "chain_id": "p",
            "validators": {"v0": 10},
            "node": {"v0": {"misbehaviors": {"double-prevote": 3}}},
        }
    )
    with pytest.raises(ValueError, match="in-process"):
        ProcessRunner(m, str(tmp_path))


def test_child_env_strips_device_plugin():
    """Child node processes must never touch the TPU tunnel: the axon
    site dir is stripped and JAX_PLATFORMS pinned to cpu."""
    from tendermint_tpu.e2e.process_runner import _child_env

    env = _child_env()
    assert env["JAX_PLATFORMS"] == "cpu"
    assert ".axon_site" not in env.get("PYTHONPATH", "")


def test_perturbation_signals_map():
    """kill/restart/pause/disconnect all map to real signals in the
    process runner (SIGKILL / SIGTERM / SIGSTOP+SIGCONT)."""
    import inspect

    from tendermint_tpu.e2e import process_runner as pr

    src = inspect.getsource(pr.ProcessRunner._apply_perturbation)
    assert "SIGKILL" in src and "SIGTERM" in src
    assert "SIGSTOP" in src and "SIGCONT" in src
    assert signal.SIGKILL  # the platform actually has them


@pytest.mark.slow
def test_process_net_state_sync(tmp_path):
    """A late-joining full node in its own OS process state-syncs from
    snapshot-serving app processes: trust root seeded over live RPC,
    chunks restored via socket ABCI, and the end state proves a real
    restore (earliest stored block above genesis)."""
    m = Manifest.parse(
        {
            "chain_id": "proc-ss-ci",
            "target_height": 8,
            "validators": {"v0": 10, "v1": 10, "v2": 10},
            "node": {
                "joiner": {
                    "mode": "full",
                    "state_sync": True,
                    "start_at": 5,
                }
            },
            "load": {"tx_rate": 1, "tx_size": 48},
        }
    )
    m.validate()
    rep = run(ProcessRunner(m, str(tmp_path), timeout=340.0).run())
    assert rep.ok, rep.failures
    assert rep.state_synced.get("joiner") is True
