"""Consensus locking cells: lock / relock / unlock / POL safety.

Reference model: internal/consensus/state_test.go:449-1264
(TestStateLockNoPOL, TestStateLockPOLRelock, TestStateLockPOLUnlock,
TestStateLockPOLUnlockOnUnknownBlock, TestStateLockPOLSafety1/2).
One real ConsensusState (cs1) is driven deterministically; the other
three validators are scripted stubs whose votes are signed with MockPV
and injected through the peer queue — the reference's randState(4) +
signAddVotes pattern. Every assertion targets the lock/POL conditions
in consensus/state.py _enter_precommit (+2/3-nil unlock, relock,
lock-on-proposal, unlock-on-unknown) and _default_do_prevote's
locked-block branch.
"""

import asyncio
import time

from tendermint_tpu.consensus import RoundStep
from tendermint_tpu.consensus.msgs import (
    BlockPartMessage,
    ProposalMessage,
    VoteMessage,
)
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.privval import MockPV
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from tendermint_tpu.types.commit import Commit
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.validator import Validator, ValidatorSet
from tendermint_tpu.types.vote import Vote

from tests.test_consensus_state import Node, fast_config

CHAIN = "lock-chain"


def run(coro):
    return asyncio.run(coro)


async def wait_for(pred, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise TimeoutError(f"timed out waiting for {what}")
        await asyncio.sleep(0.01)


class LockHarness:
    """One real cs1 + three scripted vote stubs over 4 equal-power
    validators. cs1 gets the height-1 round-0 proposer key by default
    (the reference's cells are written from the round-0 proposer's
    seat), so its round-0 proposal block B1 is the lock target."""

    def __init__(
        self,
        seed_base: int,
        cs1_proposes: bool = True,
        cs1_round: int = 0,
    ):
        privs = [
            PrivKeyEd25519.from_seed(bytes([seed_base + i]) * 32)
            for i in range(4)
        ]
        vals = ValidatorSet(
            [Validator(pub_key=p.pub_key(), voting_power=10) for p in privs]
        )
        by_addr = {p.pub_key().address(): p for p in privs}
        if cs1_round == 0:
            proposer_priv = by_addr[vals.get_proposer().address]
        else:
            # give cs1 the key of the proposer of a LATER round of
            # height 1 (the valid-block re-proposal cells need cs1 to
            # propose round 1); callers must assert this holds at
            # runtime since priorities evolve with the live set
            later = vals.copy_increment_proposer_priority(cs1_round)
            proposer_priv = by_addr[later.get_proposer().address]
        if cs1_proposes:
            cs1_priv = proposer_priv
        else:
            cs1_priv = next(p for p in privs if p is not proposer_priv)
        self.genesis = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=10)
                for p in privs
            ],
        )
        self.node = Node(cs1_priv, self.genesis, cfg=fast_config())
        self.cs = self.node.cs
        self.cs1_addr = cs1_priv.pub_key().address()
        self.stubs = [p for p in privs if p is not cs1_priv]

        self.sent: list = []  # every message cs1 feeds into itself
        self.events: list = []  # (kind, round) round-state events
        orig_send = self.cs._send_internal
        orig_pub = self.cs._publish_round_state_event

        def record_send(msg):
            self.sent.append(msg)
            orig_send(msg)

        def record_event(kind):
            self.events.append((kind, self.cs.rs.round))
            orig_pub(kind)

        self.cs._send_internal = record_send
        self.cs._publish_round_state_event = record_event

    # -- cs1 observation ------------------------------------------------

    def own_votes(self, vtype: int, round_: int) -> list:
        return [
            m.vote
            for m in self.sent
            if isinstance(m, VoteMessage)
            and m.vote.type == vtype
            and m.vote.round == round_
            and m.vote.validator_address == self.cs1_addr
        ]

    async def wait_own_vote(self, vtype: int, round_: int) -> Vote:
        await wait_for(
            lambda: self.own_votes(vtype, round_),
            what=f"cs1 vote type={vtype} round={round_}",
        )
        return self.own_votes(vtype, round_)[0]

    # -- stub actions ---------------------------------------------------

    async def make_vote(
        self, priv, vtype: int, round_: int, block_id: BlockID
    ) -> Vote:
        """One signed stub vote (reusable: redelivering the SAME object
        models gossip redelivery byte-for-byte)."""
        addr = priv.pub_key().address()
        idx, _ = self.cs.rs.validators.get_by_address(addr)
        vote = Vote(
            type=vtype,
            height=self.cs.rs.height,
            round=round_,
            block_id=block_id,
            timestamp_ns=time.time_ns(),
            validator_address=addr,
            validator_index=idx,
        )
        await MockPV(priv).sign_vote(CHAIN, vote)
        return vote

    def send_vote(self, vote: Vote) -> None:
        self.cs.send_peer_msg(
            VoteMessage(vote=vote),
            f"stub-{vote.validator_address.hex()[:8]}",
        )

    async def stub_votes(
        self, vtype: int, round_: int, block_id: BlockID, stubs=None
    ) -> None:
        """Sign and inject votes from the given stubs (default: all)."""
        for priv in stubs if stubs is not None else self.stubs:
            self.send_vote(
                await self.make_vote(priv, vtype, round_, block_id)
            )

    def make_stub_block(self, proposer_priv):
        """A valid height-1 block as the given stub would propose it
        (shadow executor over the same genesis — different proposer
        address means a different block hash than cs1's B1)."""
        shadow = Node(proposer_priv, self.genesis)
        empty = Commit(
            height=0, round=0, block_id=BlockID(), signatures=[]
        )
        return shadow.exec.create_proposal_block(
            1,
            shadow.state_store.load(),
            empty,
            proposer_priv.pub_key().address(),
        )

    async def inject_proposal(
        self, proposer_priv, round_: int, block, parts, pol_round: int = -1
    ) -> None:
        proposal = Proposal(
            height=1,
            round=round_,
            pol_round=pol_round,
            block_id=BlockID(
                hash=block.hash(), part_set_header=parts.header()
            ),
        )
        await MockPV(proposer_priv).sign_proposal(CHAIN, proposal)
        self.cs.send_peer_msg(
            ProposalMessage(proposal=proposal), "stub-proposer"
        )
        for i in range(parts.total):
            self.cs.send_peer_msg(
                BlockPartMessage(
                    height=1, round=round_, part=parts.get_part(i)
                ),
                "stub-proposer",
            )

    # -- canned sequences ------------------------------------------------

    async def lock_b1_round0(self):
        """Drive cs1 to lock its own round-0 proposal B1: two stubs
        prevote B1 (+2/3 with cs1's own prevote), cs1 locks and
        precommits B1. Returns cs1's round-0 prevote (carrying B1's
        BlockID)."""
        prevote = await self.wait_own_vote(PREVOTE_TYPE, 0)
        assert prevote.block_id.hash, "cs1 should prevote its proposal"
        await self.stub_votes(
            PREVOTE_TYPE, 0, prevote.block_id, stubs=self.stubs[:2]
        )
        precommit = await self.wait_own_vote(PRECOMMIT_TYPE, 0)
        assert precommit.block_id.hash == prevote.block_id.hash
        rs = self.cs.rs
        assert rs.locked_round == 0
        assert rs.locked_block is not None
        assert rs.locked_block.hash() == prevote.block_id.hash
        assert ("lock", 0) in self.events
        return prevote

    async def push_to_round1_nil_precommits(self):
        """Two stubs precommit nil in round 0; with cs1's block
        precommit that is +2/3-any, so precommit-wait times out into
        round 1."""
        await self.stub_votes(
            PRECOMMIT_TYPE, 0, BlockID(), stubs=self.stubs[:2]
        )
        await wait_for(
            lambda: self.cs.rs.round >= 1, what="round 1",
        )


def test_lock_no_pol_prevotes_locked_block_and_stays_locked():
    """TestStateLockNoPOL cell 1-2 (state_test.go:449): after locking
    B1 in round 0, cs1 must prevote B1 in round 1 with NO proposal in
    sight, and a nil-majority-free prevote round must precommit nil
    WITHOUT touching the lock."""

    async def go():
        h = LockHarness(seed_base=140)
        await h.cs.start()
        try:
            prevote = await h.lock_b1_round0()
            await h.push_to_round1_nil_precommits()
            # round 1, no proposal delivered: the locked block is
            # prevoted (state.py _default_do_prevote locked branch)
            rv = await h.wait_own_vote(PREVOTE_TYPE, 1)
            assert rv.block_id.hash == prevote.block_id.hash, (
                "locked validator must prevote its locked block"
            )
            # two stubs prevote nil: +2/3-any but no majority ->
            # precommit nil, lock unchanged
            await h.stub_votes(
                PREVOTE_TYPE, 1, BlockID(), stubs=h.stubs[:2]
            )
            pc = await h.wait_own_vote(PRECOMMIT_TYPE, 1)
            assert pc.block_id.hash == b"", (
                "no +2/3 prevotes: precommit must be nil"
            )
            rs = h.cs.rs
            assert rs.locked_round == 0, "lock must survive a no-POL round"
            assert rs.locked_block is not None
            assert rs.locked_block.hash() == prevote.block_id.hash
            assert ("unlock", 1) not in h.events
        finally:
            await h.cs.stop()

    run(go())


def test_relock_on_new_pol_for_locked_block_commits():
    """TestStateLockPOLRelock (state_test.go:592): a fresh +2/3
    prevote POL for the already-locked block in round 1 relocks
    (locked_round 0 -> 1), precommits the block, and the height
    commits in round 1."""

    async def go():
        h = LockHarness(seed_base=150)
        await h.cs.start()
        try:
            prevote = await h.lock_b1_round0()
            b1 = prevote.block_id
            await h.push_to_round1_nil_precommits()
            await h.wait_own_vote(PREVOTE_TYPE, 1)  # locked prevote
            # new POL for B1 in round 1
            await h.stub_votes(PREVOTE_TYPE, 1, b1, stubs=h.stubs[:2])
            pc = await h.wait_own_vote(PRECOMMIT_TYPE, 1)
            assert pc.block_id.hash == b1.hash
            assert h.cs.rs.locked_round == 1, "POL must update locked_round"
            assert ("relock", 1) in h.events
            # stubs precommit B1 -> commit at round 1
            await h.stub_votes(PRECOMMIT_TYPE, 1, b1, stubs=h.stubs[:2])
            await wait_for(
                lambda: h.node.block_store.height() >= 1, what="commit",
            )
            block = h.node.block_store.load_block(1)
            assert block.hash() == b1.hash
            seen = h.node.block_store.load_seen_commit()
            assert seen.round == 1, "commit must carry the relock round"
        finally:
            await h.cs.stop()

    run(go())


def test_unlock_on_nil_polka():
    """TestStateLockPOLUnlock (state_test.go:722): +2/3 nil prevotes
    in round 1 unlock the round-0 lock and cs1 precommits nil."""

    async def go():
        h = LockHarness(seed_base=160)
        await h.cs.start()
        try:
            prevote = await h.lock_b1_round0()
            await h.push_to_round1_nil_precommits()
            await h.wait_own_vote(PREVOTE_TYPE, 1)
            # ALL three stubs prevote nil: 30/40 power is a nil polka
            await h.stub_votes(PREVOTE_TYPE, 1, BlockID())
            pc = await h.wait_own_vote(PRECOMMIT_TYPE, 1)
            assert pc.block_id.hash == b""
            rs = h.cs.rs
            assert rs.locked_round == -1, "nil polka must unlock"
            assert rs.locked_block is None
            assert rs.locked_block_parts is None
            assert prevote.block_id.hash  # (B1 existed; lock was real)
        finally:
            await h.cs.stop()

    run(go())


def test_unlock_on_nil_polka_delivered_before_round_entry():
    """Same cell, other code path: when the round-1 nil prevotes all
    arrive while cs1 is still in round 0, the recent-polka unlock in
    _add_vote cannot fire (vote.round > rs.round at add time) — the
    +2/3-nil unlock inside _enter_precommit must do it (reference
    state.go:1469 vs the addVote-path unlock at :2139)."""

    async def go():
        h = LockHarness(seed_base=165)
        await h.cs.start()
        try:
            await h.lock_b1_round0()
            # all three stubs prevote nil for round 1 while cs1 is
            # still in round 0; 2/3-any pulls cs1 into round 1
            await h.stub_votes(PREVOTE_TYPE, 1, BlockID())
            await wait_for(lambda: h.cs.rs.round >= 1, what="round 1")
            pc = await h.wait_own_vote(PRECOMMIT_TYPE, 1)
            assert pc.block_id.hash == b""
            rs = h.cs.rs
            assert rs.locked_round == -1, (
                "+2/3 nil at precommit entry must unlock"
            )
            assert rs.locked_block is None
        finally:
            await h.cs.stop()

    run(go())


def test_unlock_on_polka_for_unknown_block():
    """TestStateLockPOLUnlockOnUnknownBlock (state_test.go:1037): a
    +2/3 prevote POL for a block cs1 has never seen unlocks, precommits
    nil, and re-arms the part set for the unknown block so it can be
    fetched."""

    async def go():
        h = LockHarness(seed_base=170)
        await h.cs.start()
        try:
            await h.lock_b1_round0()
            await h.push_to_round1_nil_precommits()
            await h.wait_own_vote(PREVOTE_TYPE, 1)
            unknown = BlockID(
                hash=b"\xc0" * 32,
                part_set_header=PartSetHeader(total=1, hash=b"\xc1" * 32),
            )
            await h.stub_votes(PREVOTE_TYPE, 1, unknown)
            pc = await h.wait_own_vote(PRECOMMIT_TYPE, 1)
            assert pc.block_id.hash == b"", (
                "cs1 must not precommit a block it has not validated"
            )
            rs = h.cs.rs
            assert rs.locked_round == -1 and rs.locked_block is None
            assert rs.proposal_block is None
            assert rs.proposal_block_parts is not None
            assert rs.proposal_block_parts.has_header(
                unknown.part_set_header
            ), "part set must be re-armed to fetch the polka block"
        finally:
            await h.cs.stop()

    run(go())


def test_lock_switches_to_new_proposal_on_higher_pol():
    """The lock-change rule (state_test.go POLSafety family): locked on
    B1 at round 0, cs1 still prevotes B1 in round 1 (lock discipline),
    but a round-1 +2/3 POL for the round-1 proposer's block C — which
    cs1 HAS and can validate — moves the lock to C and precommits C."""

    async def go():
        h = LockHarness(seed_base=180)
        await h.cs.start()
        try:
            prevote = await h.lock_b1_round0()
            await h.push_to_round1_nil_precommits()
            await wait_for(
                lambda: h.cs.rs.step >= RoundStep.PROPOSE,
                what="round 1 propose",
            )
            proposer_addr = h.cs.rs.validators.get_proposer().address
            assert proposer_addr != h.cs1_addr, (
                "round-1 proposer must rotate away from cs1"
            )
            proposer_priv = next(
                p
                for p in h.stubs
                if p.pub_key().address() == proposer_addr
            )
            block_c, parts_c = h.make_stub_block(proposer_priv)
            assert block_c.hash() != prevote.block_id.hash
            await h.inject_proposal(proposer_priv, 1, block_c, parts_c)
            await wait_for(
                lambda: h.cs.rs.proposal_block is not None,
                what="proposal C assembled",
            )
            # lock discipline: cs1's round-1 prevote is still B1
            rv = await h.wait_own_vote(PREVOTE_TYPE, 1)
            assert rv.block_id.hash == prevote.block_id.hash
            # +2/3 POL for C at round 1
            c_id = BlockID(
                hash=block_c.hash(), part_set_header=parts_c.header()
            )
            await h.stub_votes(PREVOTE_TYPE, 1, c_id)
            pc = await h.wait_own_vote(PRECOMMIT_TYPE, 1)
            assert pc.block_id.hash == block_c.hash(), (
                "POL at a higher round must move the lock to C"
            )
            rs = h.cs.rs
            assert rs.locked_round == 1
            assert rs.locked_block is not None
            assert rs.locked_block.hash() == block_c.hash()
            assert ("lock", 1) in h.events
        finally:
            await h.cs.stop()

    run(go())


def test_valid_block_reproposed_with_pol_round():
    """The valid-block rule (reference: state.go:1215-1266
    defaultDecideProposal + the valid_block updates in addVote): a
    polka observed AFTER cs1 already precommitted nil records the block
    as VALID (without locking), and when cs1 proposes the next round it
    must re-propose that block with pol_round set to the polka round —
    so the network converges on the round-0 block instead of making a
    fresh one."""

    async def go():
        h = LockHarness(seed_base=230, cs1_round=1)
        await h.cs.start()
        try:
            # round 0: cs1 is not the proposer and no proposal arrives;
            # propose timeout -> cs1 prevotes nil
            pv = await h.wait_own_vote(PREVOTE_TYPE, 0)
            assert pv.block_id.hash == b""
            # the three stubs polka the round-0 proposer's block B —
            # which cs1 has NOT seen: prevote-wait expires and cs1
            # precommits nil via the unknown-block arm (parts armed)
            r0_proposer = next(
                p
                for p in h.stubs
                if p.pub_key().address()
                == h.cs.rs.validators.get_proposer().address
            )
            block_b, parts_b = h.make_stub_block(r0_proposer)
            b_id = BlockID(
                hash=block_b.hash(), part_set_header=parts_b.header()
            )
            await h.stub_votes(PREVOTE_TYPE, 0, b_id)
            pc = await h.wait_own_vote(PRECOMMIT_TYPE, 0)
            assert pc.block_id.hash == b""
            # B arrives late; completing it against the known polka
            # must record it as VALID (no lock — cs1 precommitted nil)
            await h.inject_proposal(r0_proposer, 0, block_b, parts_b)
            await wait_for(
                lambda: h.cs.rs.valid_round == 0
                and h.cs.rs.valid_block is not None,
                what="valid block recorded",
            )
            assert h.cs.rs.locked_round == -1, "valid is not locked"
            # push to round 1 via nil precommits
            await h.stub_votes(
                PRECOMMIT_TYPE, 0, BlockID(), stubs=h.stubs[:3]
            )
            await wait_for(lambda: h.cs.rs.round >= 1, what="round 1")
            # cs1 proposes round 1: it must re-propose B with
            # pol_round = 0 (the polka round)
            assert h.cs.rs.validators.get_proposer().address == h.cs1_addr, (
                "harness assumption broke: cs1 should propose round 1"
            )
            await wait_for(
                lambda: any(
                    isinstance(m, ProposalMessage)
                    and m.proposal.round == 1
                    for m in h.sent
                ),
                what="cs1's round-1 proposal",
            )
            prop = next(
                m.proposal
                for m in h.sent
                if isinstance(m, ProposalMessage) and m.proposal.round == 1
            )
            assert prop.block_id.hash == block_b.hash(), (
                "round-1 proposer must re-propose the valid block"
            )
            assert prop.pol_round == 0, (
                f"pol_round must carry the polka round, got {prop.pol_round}"
            )
            # and cs1 prevotes it (proposal complete: POL prevotes known)
            rv = await h.wait_own_vote(PREVOTE_TYPE, 1)
            assert rv.block_id.hash == block_b.hash()
        finally:
            await h.cs.stop()

    run(go())


def test_commit_from_future_round_with_late_block():
    """Catchup commit (reference: state.go addVote handling of
    future-round precommits + enterCommit's unknown-block arm,
    :1573-1634): +2/3 precommits from round 2 arrive while cs1 is
    still in round 0, for a block it has never seen. cs1 must jump to
    the commit step, arm the part set for the unknown block, and
    finalize as soon as the parts arrive."""

    async def go():
        h = LockHarness(seed_base=240)
        await h.cs.start()
        try:
            await h.wait_own_vote(PREVOTE_TYPE, 0)  # cs1 is busy in r0
            # the round-2 proposer's block C (valid at height 1)
            vals_r2 = h.cs.rs.validators.copy_increment_proposer_priority(2)
            r2_addr = vals_r2.get_proposer().address
            r2_priv = next(
                (
                    p
                    for p in h.stubs
                    if p.pub_key().address() == r2_addr
                ),
                None,
            )
            assert r2_priv is not None, (
                "harness assumption broke: round-2 proposer should be a stub"
            )
            block_c, parts_c = h.make_stub_block(r2_priv)
            c_id = BlockID(
                hash=block_c.hash(), part_set_header=parts_c.header()
            )
            # +2/3 precommits for C at round 2 (cs1 never saw rounds 1-2)
            await h.stub_votes(PRECOMMIT_TYPE, 2, c_id)
            await wait_for(
                lambda: h.cs.rs.step >= RoundStep.COMMIT,
                what="commit step from future round",
            )
            assert h.cs.rs.commit_round == 2
            # block unknown: the part set must be armed for C
            assert h.cs.rs.proposal_block_parts is not None
            assert h.cs.rs.proposal_block_parts.has_header(
                c_id.part_set_header
            )
            assert h.node.block_store.height() == 0  # not finalized yet
            # deliver the parts; finalization follows
            for i in range(parts_c.total):
                h.cs.send_peer_msg(
                    BlockPartMessage(
                        height=1, round=2, part=parts_c.get_part(i)
                    ),
                    "stub-parts",
                )
            await wait_for(
                lambda: h.node.block_store.height() >= 1,
                what="late-block finalization",
            )
            assert h.node.block_store.load_block(1).hash() == block_c.hash()
            seen = h.node.block_store.load_seen_commit()
            assert seen.round == 2
        finally:
            await h.cs.stop()

    run(go())


def test_no_lock_or_precommit_without_seen_proposal():
    """POL safety from the non-proposer seat (state_test.go
    TestStateLockPOLSafety1 opening cell): cs1 never saw any proposal,
    prevotes nil, and even a +2/3 polka for an unseen block must not
    produce a lock or a block precommit."""

    async def go():
        h = LockHarness(seed_base=190, cs1_proposes=False)
        await h.cs.start()
        try:
            # no proposal is ever delivered: propose times out, nil prevote
            prevote = await h.wait_own_vote(PREVOTE_TYPE, 0)
            assert prevote.block_id.hash == b""
            unseen = BlockID(
                hash=b"\xc2" * 32,
                part_set_header=PartSetHeader(total=2, hash=b"\xc3" * 32),
            )
            await h.stub_votes(PREVOTE_TYPE, 0, unseen)
            pc = await h.wait_own_vote(PRECOMMIT_TYPE, 0)
            assert pc.block_id.hash == b"", (
                "polka for an unseen block must precommit nil"
            )
            rs = h.cs.rs
            assert rs.locked_round == -1 and rs.locked_block is None
            assert all(kind != "lock" for kind, _ in h.events)
            # the part set is armed to fetch the polka block
            assert rs.proposal_block_parts is not None
            assert rs.proposal_block_parts.has_header(
                unseen.part_set_header
            )
        finally:
            await h.cs.stop()

    run(go())
