"""gRPC ABCI transport tests (reference: abci/client/grpc_client.go,
abci/server/grpc_server.go — the third client/server variant)."""

import asyncio
import time

import pytest

grpc = pytest.importorskip("grpc")

from tendermint_tpu.abci import types as T  # noqa: E402
from tendermint_tpu.abci.grpc_transport import (  # noqa: E402
    GRPCClient,
    GRPCServer,
)
from tendermint_tpu.abci.kvstore import KVStoreApplication  # noqa: E402


def run(coro):
    return asyncio.run(coro)


def test_grpc_roundtrip_all_methods():
    """Every ABCI method over the wire against the kvstore app."""

    async def go():
        app = KVStoreApplication()
        srv = GRPCServer("127.0.0.1:0", app)
        await srv.start()
        client = GRPCClient(f"127.0.0.1:{srv.bound_port}")
        await client.start()
        try:
            assert (await client.echo("ping")).message == "ping"
            await client.flush()
            info = await client.info(T.RequestInfo())
            assert info.last_block_height == 0

            ct = await client.check_tx(T.RequestCheckTx(tx=b"k=v"))
            assert ct.is_ok
            await client.begin_block(T.RequestBeginBlock())
            dt = await client.deliver_tx(T.RequestDeliverTx(tx=b"k=v"))
            assert dt.is_ok
            await client.end_block(T.RequestEndBlock(height=1))
            commit = await client.commit()
            assert commit.data  # app hash

            q = await client.query(
                T.RequestQuery(path="/store", data=b"k")
            )
            assert q.value == b"v"

            snap = app.take_snapshot()
            snaps = await client.list_snapshots(T.RequestListSnapshots())
            assert any(s.height == snap.height for s in snaps.snapshots)
        finally:
            await client.stop()
            await srv.stop()

    run(go())


def test_node_runs_against_grpc_app(tmp_path):
    """A make_node validator with abci=grpc drives an out-of-process
    (separate event-loop-task) kvstore through the gRPC proxy and
    produces blocks."""
    from tendermint_tpu.config import Config
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
    from tendermint_tpu.node import make_node
    from tendermint_tpu.privval import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    async def go():
        app_srv = GRPCServer("127.0.0.1:0", KVStoreApplication())
        await app_srv.start()

        priv = PrivKeyEd25519.from_seed(b"\x61" * 32)
        genesis = GenesisDoc(
            chain_id="grpc-chain",
            genesis_time_ns=time.time_ns(),
            validators=[
                GenesisValidator(pub_key=priv.pub_key(), power=10)
            ],
        )
        cfg = Config()
        cfg.base.home = str(tmp_path / "node")
        cfg.base.chain_id = "grpc-chain"
        cfg.base.db_backend = "memdb"
        cfg.base.abci = "grpc"
        cfg.base.proxy_app = f"127.0.0.1:{app_srv.bound_port}"
        cfg.consensus.timeout_commit = 0.2
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.ensure_dirs()
        genesis.save_as(cfg.base.path(cfg.base.genesis_file))
        FilePV.from_priv_key(
            priv,
            cfg.base.path(cfg.priv_validator.key_file),
            cfg.base.path(cfg.priv_validator.state_file),
        ).save()
        node = make_node(cfg, genesis=genesis)
        await node.start()
        try:
            await node.consensus.wait_for_height(3, timeout=60.0)
            assert node.block_store.height() >= 2
        finally:
            await node.stop()
            await app_srv.stop()

    run(go())


def test_grpc_app_exception_maps_to_client_error():
    """An app that raises comes back as ABCIClientError with the
    ResponseException contract, matching the socket transport."""
    from tendermint_tpu.abci.client import ABCIClientError

    class Exploding(KVStoreApplication):
        def deliver_tx(self, req):
            raise RuntimeError("boom")

    async def go():
        srv = GRPCServer("127.0.0.1:0", Exploding())
        await srv.start()
        client = GRPCClient(f"127.0.0.1:{srv.bound_port}")
        await client.start()
        try:
            with pytest.raises(ABCIClientError, match="boom"):
                await client.deliver_tx(T.RequestDeliverTx(tx=b"x"))
            # transport survives the app exception
            assert (await client.echo("still-up")).message == "still-up"
        finally:
            await client.stop()
            await srv.stop()

    run(go())
