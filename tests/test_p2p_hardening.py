"""P2P hardening tests: channel priorities, flow control, keepalive,
per-IP accept limiting, peer scoring, and the PEX reactor
(reference models: internal/p2p/conn/connection.go,
internal/p2p/conn_tracker.go, internal/p2p/pex/reactor_test.go,
internal/p2p/peermanager_scoring_test.go)."""

import asyncio
import time

import pytest

from tendermint_tpu.encoding.proto import FieldReader, ProtoWriter
from tendermint_tpu.p2p.p2ptest import TestNetwork
from tendermint_tpu.p2p.peermanager import PeerManager, PeerManagerOptions
from tendermint_tpu.p2p.pex import (
    PEX_CHANNEL_ID,
    PexReactor,
    PexRequest,
    PexResponse,
    _Codec,
    pex_channel_descriptor,
)
from tendermint_tpu.p2p.router import (
    PING_CHANNEL_ID,
    RouterOptions,
    _PeerSendQueue,
    _RateLimiter,
)
from tendermint_tpu.p2p.types import ChannelDescriptor, Envelope


def run(coro):
    return asyncio.run(coro)


class _Blob:
    """Opaque bytes codec for raw test channels."""

    @staticmethod
    def encode(msg: bytes) -> bytes:
        return msg

    @staticmethod
    def decode(data: bytes) -> bytes:
        return data


def _desc(cid, priority, cap=1024):
    return ChannelDescriptor(
        channel_id=cid,
        message_type=_Blob,
        priority=priority,
        send_queue_capacity=cap,
        name=f"ch{cid}",
    )


class TestPrioritySendQueue:
    def test_higher_priority_drains_first(self):
        async def go():
            q = _PeerSendQueue()
            q.register(_desc(0x21, priority=5))   # data/parts
            q.register(_desc(0x22, priority=10))  # votes
            for i in range(10):
                assert q.put(0x21, b"part%d" % i)
            for i in range(3):
                assert q.put(0x22, b"vote%d" % i)
            order = [await q.get() for _ in range(13)]
            # all votes first, then parts in FIFO order
            assert [c for c, _ in order[:3]] == [0x22] * 3
            assert [p for _, p in order[:3]] == [b"vote0", b"vote1", b"vote2"]
            assert [p for _, p in order[3:5]] == [b"part0", b"part1"]

        run(go())

    def test_channel_capacity_drops_not_blocks(self):
        async def go():
            q = _PeerSendQueue()
            q.register(_desc(0x30, priority=1, cap=2))
            assert q.put(0x30, b"a")
            assert q.put(0x30, b"b")
            assert not q.put(0x30, b"c")  # full: dropped
            # keepalive traffic ignores capacity and outranks everything
            q.put_keepalive(b"\x01")
            cid, payload = await q.get()
            assert cid == PING_CHANNEL_ID  # max priority
            # pongs coalesce: many queued pings produce ONE pending pong
            for _ in range(50):
                q.put_keepalive(b"\x02")
            cid, payload = await q.get()
            assert (cid, payload) == (PING_CHANNEL_ID, b"\x02")
            cid, payload = await q.get()
            assert cid == 0x30  # no second pong queued

        run(go())


class TestRateLimiter:
    def test_throttles_to_rate(self):
        async def go():
            limiter = _RateLimiter(rate=100_000)  # 100 KB/s
            t0 = time.monotonic()
            # 1 burst (100 KB free) + 100 KB owed = ~1s
            for _ in range(20):
                await limiter.wait(10_000)
            return time.monotonic() - t0

        elapsed = run(go())
        assert 0.7 < elapsed < 3.0, elapsed

    def test_zero_rate_means_unlimited(self):
        async def go():
            limiter = _RateLimiter(rate=0)
            t0 = time.monotonic()
            for _ in range(1000):
                await limiter.wait(1 << 20)
            return time.monotonic() - t0

        assert run(go()) < 0.5


class TestVotesPreemptBlockParts:
    """The VERDICT acceptance test: with a saturated send path, votes
    (high-priority channel) must reach the peer before the bulk of the
    queued block parts (low-priority channel)."""

    def test_priority_under_load(self):
        async def go():
            net = TestNetwork(2)
            a, b = net.nodes
            # throttle a's send path so the queue actually backs up
            a.router.opts.send_rate = 400_000  # bytes/s
            data_a = a.open_channel(_desc(0x21, priority=5))
            votes_a = a.open_channel(_desc(0x22, priority=10))
            data_b = b.open_channel(_desc(0x21, priority=5))
            votes_b = b.open_channel(_desc(0x22, priority=10))
            await net.start()
            try:
                part = bytes(40_000)
                # saturate: ~30 parts at 40 KB = 1.2 MB ≈ 3s of budget
                for _ in range(30):
                    await data_a.send(
                        Envelope(to=b.node_id, message=part)
                    )
                await asyncio.sleep(0.05)  # let the queue build
                await votes_a.send(Envelope(to=b.node_id, message=b"VOTE"))

                got_vote_after_parts = 0

                async def count_parts():
                    nonlocal got_vote_after_parts
                    async for env in data_b:
                        got_vote_after_parts += 1

                counter = asyncio.ensure_future(count_parts())
                env = await asyncio.wait_for(votes_b.receive(), timeout=10.0)
                assert env.message == b"VOTE"
                counter.cancel()
                # the vote jumped the queue: far fewer than all 30 parts
                # were delivered first
                assert got_vote_after_parts < 15, got_vote_after_parts
            finally:
                await net.stop()

        run(go())


class TestKeepalive:
    def test_unresponsive_peer_disconnected(self):
        async def go():
            net = TestNetwork(2)
            a, b = net.nodes
            a.router.opts.ping_interval = 0.2
            a.router.opts.pong_timeout = 0.2
            await net.start()
            try:
                assert len(a.peer_manager.peers()) == 1
                # sever b's reply path: cancel b's tasks so it never
                # answers pings (simulates a hung process)
                for t in list(b.router._tasks):
                    t.cancel()
                deadline = time.monotonic() + 5.0
                while (
                    a.peer_manager.peers()
                    and time.monotonic() < deadline
                ):
                    await asyncio.sleep(0.05)
                assert not a.peer_manager.peers(), "peer never evicted"
            finally:
                await net.stop()

        run(go())

    def test_idle_but_responsive_peers_stay_connected(self):
        async def go():
            net = TestNetwork(2)
            a, b = net.nodes
            for n in (a, b):
                n.router.opts.ping_interval = 0.1
                n.router.opts.pong_timeout = 0.3
            await net.start()
            try:
                await asyncio.sleep(1.0)  # many ping cycles, no traffic
                assert len(a.peer_manager.peers()) == 1
                assert len(b.peer_manager.peers()) == 1
            finally:
                await net.stop()

        run(go())


class TestConnTracker:
    def test_per_ip_accept_rate_limit(self):
        async def go():
            net = TestNetwork(1)
            router = net.nodes[0].router
            router.opts.max_incoming_per_ip = 3
            router.opts.incoming_window = 10.0
            assert router._track_incoming("10.0.0.1:1001")
            assert router._track_incoming("10.0.0.1:1002")
            assert router._track_incoming("10.0.0.1:1003")
            assert not router._track_incoming("10.0.0.1:1004")
            # other IPs unaffected
            assert router._track_incoming("10.0.0.2:1001")

        run(go())


class TestPeerScoring:
    def test_scores_move_and_rank_dials(self):
        pm = PeerManager("a" * 40, PeerManagerOptions())
        pm.add("b" * 40 + "@hostb:26656")
        pm.add("c" * 40 + "@hostc:26656")
        # c misbehaved in the past: lower score
        pm._peers["c" * 40].score = -5
        pm._peers["b" * 40].score = 5
        cand = pm._next_dial_candidate()
        assert cand[0].node_id == "b" * 40

    def test_sustained_uptime_raises_score_errored_lowers(self):
        async def go():
            pm = PeerManager("a" * 40, PeerManagerOptions())
            pm.add("b" * 40 + "@hostb:26656")
            peer = pm._peers["b" * 40]
            peer.dialing = True
            pm.dialed("b" * 40)
            pm.ready("b" * 40)
            s0 = peer.score
            # a short-lived session awards nothing (anti reconnect-churn)
            pm.disconnected("b" * 40)
            assert peer.score == s0
            # a long clean session awards +1
            peer.dialing = True
            pm.dialed("b" * 40)
            pm.ready("b" * 40)
            peer.connected_at -= 601.0  # simulate 10+ min of uptime
            pm.disconnected("b" * 40)
            assert peer.score == s0 + 1
            # misbehavior docks far more than uptime earns
            peer.dialing = True
            pm.dialed("b" * 40)
            pm.ready("b" * 40)
            pm.errored("b" * 40, "bad message")
            assert peer.score < s0

        run(go())


class TestPexCodec:
    def test_roundtrip(self):
        req = _Codec.decode(_Codec.encode(PexRequest()))
        assert isinstance(req, PexRequest)
        resp = PexResponse(
            addresses=["a" * 40 + "@h1:26656", "b" * 40 + "@h2:26656"]
        )
        back = _Codec.decode(_Codec.encode(resp))
        assert back.addresses == resp.addresses
        with pytest.raises(ValueError):
            _Codec.decode(b"")


class TestPexReactor:
    def test_addresses_propagate(self):
        """A knows B; B knows C. After PEX polls, A learns C's address
        (reference: pex/reactor_test.go TestReactorBasic...)."""

        async def go():
            net = TestNetwork(3)
            a, b, c = net.nodes
            reactors = []
            for n in net.nodes:
                ch = n.open_channel(pex_channel_descriptor())
                r = PexReactor(n.peer_manager, ch, n.peer_manager.subscribe())
                reactors.append(r)
            # speed up polling
            import tendermint_tpu.p2p.pex as pexmod

            old = pexmod._MIN_POLL_INTERVAL
            pexmod._MIN_POLL_INTERVAL = 0.1
            try:
                # wire only a<->b and b<->c (NOT a<->c)
                await a.router.start()
                await b.router.start()
                await c.router.start()
                for r in reactors:
                    await r.start()
                a.peer_manager.add(f"{b.node_id}@{b.addr}")
                c.peer_manager.add(f"{b.node_id}@{b.addr}")
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline:
                    # a learns c's address via pex through b, then dials
                    if c.node_id in a.peer_manager.peers():
                        break
                    await asyncio.sleep(0.1)
                if c.node_id not in a.peer_manager.peers():
                    diag = {
                        "a.peers": a.peer_manager.peers(),
                        "b.peers": b.peer_manager.peers(),
                        "c.peers": c.peer_manager.peers(),
                        "a.book": {
                            pid: sorted(p.addresses)
                            for pid, p in a.peer_manager._peers.items()
                        },
                        "b.book": {
                            pid: sorted(p.addresses)
                            for pid, p in b.peer_manager._peers.items()
                        },
                        "a.requested": reactors[0]._requested,
                        "a.available": reactors[0]._available,
                        "a.added": reactors[0].total_added,
                        "ids": {
                            "a": a.node_id, "b": b.node_id, "c": c.node_id
                        },
                    }
                    pytest.fail(f"pex never propagated c to a: {diag}")
            finally:
                pexmod._MIN_POLL_INTERVAL = old
                for r in reactors:
                    await r.stop()
                await net.stop()

        run(go())


class TestDialAcceptCrossover:
    def test_simultaneous_dial_converges(self):
        """Both peers learn each other's address at the same instant
        and dial simultaneously. The deterministic crossover rule (the
        lower node ID keeps its outbound) must converge to ONE live
        connection instead of livelocking on mutual 'already connected'
        rejections (reference concern: peermanager.go:569,636)."""

        async def go():
            for trial in range(6):
                net = TestNetwork(2)
                a, b = net.nodes
                await a.router.start()
                await b.router.start()
                try:
                    # add both directions in the same loop tick: both
                    # dial loops wake together -> crossover
                    a.peer_manager.add(f"{b.node_id}@{b.addr}")
                    b.peer_manager.add(f"{a.node_id}@{a.addr}")
                    deadline = time.monotonic() + 20.0
                    while time.monotonic() < deadline:
                        if (
                            b.node_id in a.peer_manager.peers()
                            and a.node_id in b.peer_manager.peers()
                        ):
                            break
                        await asyncio.sleep(0.05)
                    assert b.node_id in a.peer_manager.peers(), (
                        f"trial {trial}: a never connected to b"
                    )
                    assert a.node_id in b.peer_manager.peers(), (
                        f"trial {trial}: b never connected to a"
                    )
                finally:
                    await net.stop()

        run(go())
