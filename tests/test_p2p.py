"""P2P stack tests — secret connection, peer manager lifecycle, memory
network routing, TCP router end-to-end
(reference model: internal/p2p/*_test.go)."""

import asyncio

import pytest

from tendermint_tpu.consensus import msgs as cmsgs
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.p2p import (
    ChannelDescriptor,
    Envelope,
    MemoryNetwork,
    MemoryTransport,
    NodeInfo,
    PeerError,
    PeerManager,
    PeerManagerOptions,
    PeerStatus,
    Router,
    TCPTransport,
    node_id_from_pubkey,
    parse_node_address,
)
from tendermint_tpu.p2p.conn import HandshakeError, SecretConnection
from tendermint_tpu.p2p.p2ptest import TestNetwork


def run(coro):
    return asyncio.run(coro)


# -- addresses --


def test_parse_node_address():
    nid = "ab" * 20
    assert parse_node_address(f"{nid}@1.2.3.4:26656") == (nid, "1.2.3.4", 26656)
    assert parse_node_address(f"tcp://{nid}@host") == (nid, "host", 26656)
    assert parse_node_address("1.2.3.4:9")[0] == ""
    with pytest.raises(ValueError):
        parse_node_address("zz" * 20 + "@x:1")


# -- secret connection --


def test_secret_connection_roundtrip_and_tamper():
    async def go():
        a_priv = PrivKeyEd25519.from_seed(b"\x0a" * 32)
        b_priv = PrivKeyEd25519.from_seed(b"\x0b" * 32)
        server_conn = {}
        got = asyncio.Event()

        async def on_client(reader, writer):
            sc = await SecretConnection.handshake(reader, writer, b_priv)
            server_conn["sc"] = sc
            got.set()

        server = await asyncio.start_server(on_client, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        client = await SecretConnection.handshake(reader, writer, a_priv)
        await got.wait()
        srv = server_conn["sc"]

        # mutual authentication
        assert client.remote_pubkey.bytes() == b_priv.pub_key().bytes()
        assert srv.remote_pubkey.bytes() == a_priv.pub_key().bytes()

        # encrypted roundtrips both directions
        await client.write_frame(b"hello from a")
        assert await srv.read_frame() == b"hello from a"
        await srv.write_frame(b"hello from b")
        assert await client.read_frame() == b"hello from b"

        # large frame
        big = bytes(range(256)) * 4000  # ~1MB
        await client.write_frame(big)
        assert await srv.read_frame() == big

        client.close()
        srv.close()
        server.close()
        await server.wait_closed()

    run(go())


def test_secret_connection_wrong_key_rejected():
    """A MITM re-signing the challenge with a different key must fail the
    pubkey/node-ID binding check at the transport layer; here we check
    that the signature itself must match the derived challenge."""
    async def go():
        a_priv = PrivKeyEd25519.from_seed(b"\x0c" * 32)

        import struct as _s

        # conn's own primitives: the wheel's classes when installed,
        # the gated RFC fallbacks otherwise — the MITM speaks whichever
        # dialect the server does
        from tendermint_tpu.p2p.conn import (
            ChaCha20Poly1305,
            Encoding,
            PublicFormat,
            X25519PrivateKey,
            X25519PublicKey,
            _auth_sig_bytes,
            _derive,
        )

        async def on_client(reader, writer):
            # speak the handshake but sign garbage instead of the challenge
            eph = X25519PrivateKey.generate()
            eph_pub = eph.public_key().public_bytes(
                Encoding.Raw, PublicFormat.Raw
            )
            writer.write(eph_pub)
            remote = await reader.readexactly(32)
            shared = eph.exchange(X25519PublicKey.from_public_bytes(remote))
            send_key, recv_key, challenge = _derive(shared, eph_pub, remote)
            mitm = PrivKeyEd25519.from_seed(b"\x0d" * 32)
            bad_sig = mitm.sign(b"not the challenge")
            ct = ChaCha20Poly1305(send_key).encrypt(
                _s.pack("<Q", 0) + b"\x00" * 4,
                _auth_sig_bytes(mitm.pub_key(), bad_sig),
                None,
            )
            writer.write(_s.pack(">I", len(ct)) + ct)
            await writer.drain()
            writer.close()  # else Server.wait_closed() blocks on 3.12

        server = await asyncio.start_server(on_client, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        with pytest.raises(HandshakeError, match="challenge"):
            await asyncio.wait_for(
                SecretConnection.handshake(reader, writer, a_priv), timeout=5
            )
        writer.close()
        server.close()
        await server.wait_closed()

    run(go())


# -- peer manager --


def test_peer_manager_dial_lifecycle():
    async def go():
        pm = PeerManager("aa" * 20, PeerManagerOptions(max_connected=2))
        nid1, nid2 = "bb" * 20, "cc" * 20
        assert pm.add(f"{nid1}@h1:1")
        assert not pm.add(f"{nid1}@h1:1")  # duplicate
        assert pm.add(f"{nid2}@h2:2")
        # self is never added
        assert not pm.add(f"{'aa' * 20}@self:1")

        node_id, host, port = await pm.dial_next()
        pm.dialed(node_id)
        got2, _, _ = await pm.dial_next()
        assert {node_id, got2} == {nid1, nid2}
        pm.dialed(got2)
        assert pm.num_connected() == 2

        sub = pm.subscribe()
        pm.ready(nid1)
        up = await asyncio.wait_for(sub.get(), 1)
        assert up.node_id == nid1 and up.status == PeerStatus.UP
        pm.disconnected(nid1)
        down = await asyncio.wait_for(sub.get(), 1)
        assert down.status == PeerStatus.DOWN
        assert pm.num_connected() == 1

    run(go())


def test_peer_manager_backoff_after_failure():
    async def go():
        pm = PeerManager(
            "aa" * 20,
            PeerManagerOptions(min_retry_time=5.0),  # long backoff
        )
        nid = "bb" * 20
        pm.add(f"{nid}@h:1")
        node_id, _, _ = await pm.dial_next()
        pm.dial_failed(node_id)
        # backoff: no candidate available immediately
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(pm.dial_next(), timeout=0.3)

    run(go())


def test_peer_manager_persistent_priority():
    async def go():
        pm = PeerManager(
            "aa" * 20,
            PeerManagerOptions(persistent_peers=[f"{'dd' * 20}@pp:1"]),
        )
        pm.add(f"{'bb' * 20}@h:1")
        node_id, _, _ = await pm.dial_next()
        assert node_id == "dd" * 20  # persistent dialed first

    run(go())


def test_peer_manager_evicts_on_error():
    async def go():
        pm = PeerManager("aa" * 20)
        nid = "bb" * 20
        pm.add(f"{nid}@h:1")
        node_id, _, _ = await pm.dial_next()
        pm.dialed(node_id)
        pm.ready(node_id)
        pm.errored(node_id, "bad message")
        victim = await asyncio.wait_for(pm.evict_next(), 1)
        assert victim == nid

    run(go())


def test_peer_manager_dial_accept_crossover():
    """Simultaneous dial resolution is deterministic: the LOWER node ID
    keeps its outbound (rejecting the inbound), the higher accepts the
    inbound and lets its own dial fail — one connection survives
    instead of a mutual-close livelock
    (reference concern: peermanager.go:569,636)."""

    async def go():
        # lower-ID side: inbound during our dial is rejected, our
        # outbound completes
        pm = PeerManager("aa" * 20)
        nid = "bb" * 20
        pm.add(f"{nid}@h:1")
        node_id, _, _ = await pm.dial_next()
        with pytest.raises(ValueError, match="crossover"):
            pm.accepted(nid)
        pm.dialed(node_id)
        pm.ready(node_id)
        assert pm.num_connected() == 1
        assert pm.peers() == [nid]

        # higher-ID side: the inbound wins, our own dial raises, and a
        # failed dial must not clobber the live inbound state
        pm2 = PeerManager("cc" * 20)
        pm2.add(f"{nid}@h:1")
        node_id2, _, _ = await pm2.dial_next()
        pm2.accepted(nid)
        pm2.ready(nid)
        with pytest.raises(ValueError, match="already connected"):
            pm2.dialed(node_id2)
        pm2.dial_failed(node_id2)
        assert pm2.num_connected() == 1
        assert pm2.peers() == [nid]

    run(go())


def test_peer_manager_address_book_persists():
    from tendermint_tpu.store.kv import MemKV

    db = MemKV()
    pm = PeerManager("aa" * 20, store=db)
    pm.add(f"{'bb' * 20}@host1:26656")
    pm2 = PeerManager("aa" * 20, store=db)
    assert pm2.advertise(10) == [f"{'bb' * 20}@host1:26656"]


# -- routed networks --

ECHO_CH = ChannelDescriptor(
    channel_id=0x99,
    message_type=cmsgs.HasVoteMessage,
    name="echo",
)


def test_memory_network_broadcast_and_unicast():
    async def go():
        net = TestNetwork(3)
        channels = [n.open_channel(ECHO_CH) for n in net.nodes]
        await net.start()

        # broadcast from node0 reaches node1 and node2
        await channels[0].send(
            Envelope(
                message=cmsgs.HasVoteMessage(height=7, round=0, type=1, index=3),
                broadcast=True,
            )
        )
        for ch in channels[1:]:
            env = await asyncio.wait_for(ch.receive(), 5)
            assert env.message.height == 7
            assert env.from_peer == net.nodes[0].node_id

        # unicast node1 → node2 only
        await channels[1].send(
            Envelope(
                message=cmsgs.HasVoteMessage(height=9, round=1, type=2, index=0),
                to=net.nodes[2].node_id,
            )
        )
        env = await asyncio.wait_for(channels[2].receive(), 5)
        assert env.message.height == 9
        assert channels[0].in_queue.empty()

        await net.stop()

    run(go())


def test_peer_error_evicts_peer():
    async def go():
        net = TestNetwork(2)
        channels = [n.open_channel(ECHO_CH) for n in net.nodes]
        await net.start()
        bad = net.nodes[1].node_id
        sub = net.nodes[0].peer_manager.subscribe()
        seeded = await asyncio.wait_for(sub.get(), 5)
        assert seeded.status == PeerStatus.UP  # subscribe seeds live peers
        await channels[0].send_error(PeerError(node_id=bad, err="misbehaved"))
        update = await asyncio.wait_for(sub.get(), 5)
        assert update.node_id == bad and update.status == PeerStatus.DOWN
        # misbehavior applies dial backoff
        peer = net.nodes[0].peer_manager._peers[bad]
        assert peer.last_dial_failure > 0 and peer.score < 0
        await net.stop()

    run(go())


def test_tcp_router_end_to_end():
    async def go():
        privs = [PrivKeyEd25519.from_seed(bytes([i + 30]) * 32) for i in range(2)]
        ids = [node_id_from_pubkey(p.pub_key()) for p in privs]
        transports = [TCPTransport(), TCPTransport()]
        infos = [
            NodeInfo(node_id=ids[i], network="tcp-chain", moniker=f"n{i}")
            for i in range(2)
        ]
        pms = [PeerManager(ids[i]) for i in range(2)]
        routers = [
            Router(
                infos[i], privs[i], pms[i], transports[i],
                listen_addr=f"127.0.0.1:0",
            )
            for i in range(2)
        ]
        channels = [r.open_channel(ECHO_CH) for r in routers]
        for r in routers:
            await r.start()
        # node0 dials node1's ephemeral port
        port = transports[1].listen_port
        pms[0].add(f"{ids[1]}@127.0.0.1:{port}")

        async def connected():
            while not (pms[0].peers() and pms[1].peers()):
                await asyncio.sleep(0.01)

        await asyncio.wait_for(connected(), 10)

        await channels[0].send(
            Envelope(
                message=cmsgs.HasVoteMessage(height=42, round=0, type=1, index=1),
                to=ids[1],
            )
        )
        env = await asyncio.wait_for(channels[1].receive(), 5)
        assert env.message.height == 42
        assert env.from_peer == ids[0]

        # and the reverse direction over the same connection
        await channels[1].send(
            Envelope(
                message=cmsgs.HasVoteMessage(height=43, round=0, type=1, index=1),
                to=ids[0],
            )
        )
        env0 = await asyncio.wait_for(channels[0].receive(), 5)
        assert env0.message.height == 43

        for r in routers:
            await r.stop()

    run(go())


def test_tcp_wrong_network_rejected():
    async def go():
        privs = [PrivKeyEd25519.from_seed(bytes([i + 40]) * 32) for i in range(2)]
        ids = [node_id_from_pubkey(p.pub_key()) for p in privs]
        transports = [TCPTransport(), TCPTransport()]
        infos = [
            NodeInfo(node_id=ids[0], network="chain-A", moniker="n0"),
            NodeInfo(node_id=ids[1], network="chain-B", moniker="n1"),
        ]
        pms = [PeerManager(ids[i]) for i in range(2)]
        routers = [
            Router(infos[i], privs[i], pms[i], transports[i],
                   listen_addr="127.0.0.1:0")
            for i in range(2)
        ]
        for r in routers:
            r.open_channel(ECHO_CH)
            await r.start()
        pms[0].add(f"{ids[1]}@127.0.0.1:{transports[1].listen_port}")
        await asyncio.sleep(0.5)
        assert not pms[0].peers()  # incompatible networks never connect
        assert not pms[1].peers()
        for r in routers:
            await r.stop()

    run(go())


def test_tampered_frame_drops_peer_not_router():
    """A peer sending a garbled AEAD frame must only lose its own
    connection — the router (and other peers) survive."""
    async def go():
        net = TestNetwork(3)
        channels = [n.open_channel(ECHO_CH) for n in net.nodes]
        await net.start()

        # reach into node1's TCP-less memory conn: memory transport has no
        # crypto, so instead test via the TCP path with 2 extra nodes
        privs = [PrivKeyEd25519.from_seed(bytes([i + 70]) * 32) for i in range(2)]
        ids = [node_id_from_pubkey(p.pub_key()) for p in privs]
        transports = [TCPTransport(), TCPTransport()]
        pms = [PeerManager(ids[i]) for i in range(2)]
        routers = [
            Router(
                NodeInfo(node_id=ids[i], network="x", moniker=f"t{i}"),
                privs[i], pms[i], transports[i], listen_addr="127.0.0.1:0",
            )
            for i in range(2)
        ]
        chans = [r.open_channel(ECHO_CH) for r in routers]
        for r in routers:
            await r.start()
        pms[0].add(f"{ids[1]}@127.0.0.1:{transports[1].listen_port}")

        async def connected():
            while not (pms[0].peers() and pms[1].peers()):
                await asyncio.sleep(0.01)

        await asyncio.wait_for(connected(), 10)

        # corrupt node1→node0 traffic by writing junk into the raw socket
        sub = pms[0].subscribe()
        seeded = await asyncio.wait_for(sub.get(), 5)
        assert seeded.status == PeerStatus.UP  # subscribe seeds live peers
        conn = routers[1]._peer_conns[ids[0]]
        conn._secret._writer.write(b"\x00\x00\x00\x08" + b"garbage!")
        await conn._secret._writer.drain()

        # node0 drops the peer (DOWN event) but the router itself survives
        update = await asyncio.wait_for(sub.get(), 10)
        assert update.node_id == ids[1] and update.status == PeerStatus.DOWN
        assert routers[0].is_running
        for r in routers:
            await r.stop()
        await net.stop()

    run(go())
