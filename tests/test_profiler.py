"""libs/profiler.py — the wall-clock sampling profiler (ISSUE 16).

Pins the profiling plane's contracts: subsystem bucketing, the
kill-switched label hook, sampler lifecycle (enable starts a daemon
thread, disable stops AND joins it, switch interval saved/restored),
attribution of a busy registered thread and of a labeled asyncio task,
the bounded-aggregation collapse policy, the folded export format, the
bottleneck-ledger join (loadgen/profilemerge.py), the report CLI
(scripts/profile_report.py), and the cost-budgeted `profile` RPC
route.
"""

import asyncio
import importlib.util
import json
import os
import sys
import threading
import time

import pytest

from tendermint_tpu.libs import profiler
from tendermint_tpu.loadgen.profilemerge import (
    build_ledger,
    capture_profile,
)

_SPEC = importlib.util.spec_from_file_location(
    "profile_report",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
        "profile_report.py",
    ),
)
profile_report = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(profile_report)


@pytest.fixture(autouse=True)
def _clean_profiler():
    """The profiler is process-wide module state: every test starts and
    ends disabled, disarmed, empty, with the defaults restored."""
    profiler.disable()
    profiler.disarm_labels()
    profiler.reset()
    yield
    profiler.disable()
    profiler.disarm_labels()
    profiler.reset()
    profiler._hz = profiler.DEFAULT_HZ
    profiler._max_stacks = profiler.DEFAULT_MAX_STACKS


# -- subsystem map ---------------------------------------------------------


def test_subsystem_of_maps_the_package_layout():
    cases = {
        "consensus/state.py": "consensus",
        "mempool/mempool.py": "mempool",
        "p2p/transport.py": "p2p",
        "rpc/jsonrpc.py": "rpc",
        "pubsub/__init__.py": "eventbus",
        "eventbus/__init__.py": "eventbus",
        "crypto/merkle.py": "merkle",
        "crypto/tmhash.py": "merkle",
        "crypto/ed25519.py": "crypto-batch",
        "store/blockstore.py": "store",
        "state/execution.py": "store",
        "encoding/codec.py": "serialization",
        "types/block.py": "serialization",
        "libs/metrics.py": "metrics",
        "libs/service.py": "libs",
        "loadgen/run.py": "harness",
    }
    for rel, want in cases.items():
        assert profiler.subsystem_of(rel) == want, rel
    # unmatched in-package files still get a NAMED home
    assert profiler.subsystem_of("version.py") == "version"


def test_describe_code_in_package_vs_stdlib():
    ent, sub = profiler._describe_code(
        profiler.subsystem_of.__code__
    )
    assert ent == "libs.profiler:subsystem_of"
    assert sub == "libs"
    ent, sub = profiler._describe_code(json.dumps.__code__)
    assert ent.endswith("json.__init__:dumps")
    assert sub == ""


def test_classify_leaf_idle_wait_stdlib():
    assert profiler._classify_leaf("python3.10.selectors:select") == "idle"
    assert profiler._classify_leaf("python3.10.threading:wait") == "wait"
    assert profiler._classify_leaf("python3.10.queue:get") == "wait"
    assert profiler._classify_leaf("json.encoder:encode") == "stdlib"


# -- label hook ------------------------------------------------------------


class _FakeTask:
    def __init__(self, name="Task-7"):
        self._name = name

    def get_name(self):
        return self._name

    def get_loop(self):
        raise RuntimeError("no loop")


def test_label_task_kill_switch_writes_nothing():
    t = _FakeTask()
    assert profiler.label_task(t, "rpc:conn") is t
    assert not hasattr(t, "_tt_profile_label")
    # falls back to the asyncio task name
    assert profiler.task_label(t) == "Task-7"


def test_label_task_armed_records_and_task_label_prefers_it():
    profiler.arm_labels()
    assert profiler.labels_armed()
    t = _FakeTask()
    profiler.label_task(t, "service:consensus:main")
    assert t._tt_profile_label == "service:consensus:main"
    assert profiler.task_label(t) == "service:consensus:main"
    profiler.disarm_labels()
    assert not profiler.labels_armed()


# -- sampler lifecycle -----------------------------------------------------


def _profiler_threads():
    return [
        t for t in threading.enumerate() if t.name == "tt-profiler"
    ]


def test_enable_disable_lifecycle_thread_and_switch_interval():
    saved = sys.getswitchinterval()
    assert not profiler.is_enabled()
    assert _profiler_threads() == []
    profiler.enable(hz=200)
    try:
        assert profiler.is_enabled()
        assert len(_profiler_threads()) == 1
        assert _profiler_threads()[0].daemon
        # GIL convoy-bias mitigation: forced preemption at 1 ms
        assert sys.getswitchinterval() == pytest.approx(0.001)
        profiler.enable(hz=200)  # idempotent: no second thread
        assert len(_profiler_threads()) == 1
    finally:
        profiler.disable()
    # disable STOPS AND JOINS: no surviving thread, interval restored
    assert not profiler.is_enabled()
    assert _profiler_threads() == []
    assert sys.getswitchinterval() == pytest.approx(saved)
    # and no further samples accrue once stopped
    n = profiler.stats()["samples_total"]
    time.sleep(0.05)
    assert profiler.stats()["samples_total"] == n


def test_enable_rejects_bad_params():
    with pytest.raises(ValueError):
        profiler.enable(hz=0)
    with pytest.raises(ValueError):
        profiler.enable(max_stacks=0)
    assert not profiler.is_enabled()


def test_sampler_attributes_busy_registered_thread():
    stop = threading.Event()

    def burn():
        profiler.register_thread("bench-busy")
        while not stop.is_set():
            sum(i * i for i in range(500))

    t = threading.Thread(target=burn, daemon=True)
    profiler.enable(hz=300)
    t.start()
    try:
        deadline = time.time() + 5.0
        while (
            profiler.stats()["samples_total"] < 20
            and time.time() < deadline
        ):
            time.sleep(0.02)
    finally:
        stop.set()
        t.join()
        profiler.disable()
    snap = profiler.snapshot()
    assert snap, "no samples collected"
    roles = {e["role"] for e in snap}
    assert "bench-busy" in roles
    busy = [e for e in snap if e["role"] == "bench-busy"]
    # the burn loop lives in this test file: out-of-package frames,
    # but the stack still names the function
    assert any("burn" in e["stack"] for e in busy)
    counts = profiler.subsystem_counts()
    assert sum(counts.values()) == profiler.stats()["samples_total"]
    shares = profiler.subsystem_shares()
    assert sum(shares.values()) == pytest.approx(1.0)


def test_sampler_attributes_current_asyncio_task():
    def _labeled():
        return [
            e
            for e in profiler.snapshot()
            if e["task"] == "rpc:test-pump"
        ]

    async def main():
        profiler.register_thread("loop")
        profiler.register_loop()
        deadline = time.time() + 8.0

        async def pump():
            # each burst must outlast the 1 ms forced-preemption
            # interval, or every sample lands between tasks (in the
            # selector) where current_task(loop) is None
            while time.time() < deadline and not _labeled():
                sum(i * i for i in range(60_000))
                await asyncio.sleep(0)

        task = asyncio.ensure_future(pump())
        profiler.label_task(task, "rpc:test-pump")
        await task

    profiler.enable(hz=300)
    try:
        asyncio.run(main())
    finally:
        profiler.disable()
    assert _labeled(), "no samples attributed to the labeled task"


def test_bounded_aggregation_collapses_past_the_cap():
    with profiler._agg_lock:
        pass  # touch the lock once so the next block reads naturally
    profiler.enable(hz=1)  # sampler parked; we inject directly
    profiler.disable()
    profiler.reset()
    profiler._max_stacks = 3
    # simulate the sampler hitting 5 novel stacks with a 3-slot table
    with profiler._agg_lock:
        for i in range(5):
            key = ("loop", "", f"mod:fn{i}", "consensus")
            if key in profiler._agg:
                profiler._agg[key] += 1
            elif len(profiler._agg) < profiler._max_stacks:
                profiler._agg[key] = 1
            else:
                ck = ("loop", "", "<collapsed>", "consensus")
                profiler._agg[ck] = profiler._agg.get(ck, 0) + 1
                profiler._collapsed_total += 1
            profiler._samples_total += 1
    st = profiler.stats()
    assert st["stacks"] == 4  # 3 real + 1 collapse key
    assert st["collapsed_samples"] == 2
    # collapse keeps the subsystem attribution
    assert profiler.subsystem_counts() == {"consensus": 5}


def test_folded_format_and_snapshot_order():
    with profiler._agg_lock:
        profiler._agg[("loop", "rpc:conn", "a:f;b:g", "rpc")] = 7
        profiler._agg[("wal", "", "c:h", "store")] = 9
        profiler._samples_total = 16
    snap = profiler.snapshot()
    assert [e["count"] for e in snap] == [9, 7]  # highest first
    assert snap[0] == {
        "role": "wal",
        "task": "",
        "stack": "c:h",
        "subsystem": "store",
        "count": 9,
    }
    lines = profiler.folded()
    assert "wal;c:h 9" in lines
    assert "loop;rpc:conn;a:f;b:g 7" in lines
    assert len(profiler.snapshot(max_entries=1)) == 1
    doc = json.loads(profiler.to_profile_json())
    assert doc["stats"]["samples_total"] == 16
    assert doc["subsystem_shares"]["store"] == pytest.approx(9 / 16)
    assert len(doc["stacks"]) == 2


# -- bottleneck ledger (loadgen/profilemerge.py) ---------------------------


def _seed_agg(counts):
    with profiler._agg_lock:
        for i, (sub, n) in enumerate(counts.items()):
            profiler._agg[("loop", "", f"m:f{i}", sub)] = n
            profiler._samples_total += n


def test_capture_profile_window_isolates_the_measured_counts():
    _seed_agg({"consensus": 10, "rpc": 4})
    before = profiler.subsystem_counts()
    with profiler._agg_lock:
        profiler._agg[("loop", "", "m:f0", "consensus")] += 5
        profiler._agg[("loop", "", "m:g", "eventbus")] = 3
        profiler._samples_total += 8
    doc = capture_profile(before)
    assert doc["subsystem_counts"] == {
        "consensus": 15,
        "eventbus": 3,
        "rpc": 4,
    }
    # the window diff: only what accrued after `before`, positives only
    assert doc["window_counts"] == {"consensus": 5, "eventbus": 3}
    assert doc["stats"]["samples_total"] == 22


def test_build_ledger_ranks_joins_and_splits():
    profile = {
        "stats": {"samples_total": 100},
        "window_counts": {
            "consensus": 30,
            "rpc": 20,
            "eventbus": 10,
            "idle": 25,
            "wait": 5,
            "stdlib": 10,
        },
    }
    sat = {
        "eventbus_fanout_lag_max": 72.0,
        "consensus_total_txs_delta": 791.0,
        "unrelated_key": 1.0,
    }
    timeline = {
        "heights_attributed": 12,
        "rounds_burned_total": 0,
        "timeouts_total": 1,
        "proposal_to_polka": {"mean_ms": 3.0, "max_ms": 9.0},
        "polka_to_quorum": {"mean_ms": 2.0, "max_ms": 5.0},
        "commit_spread": {"mean_ms": 1.0, "max_ms": 2.0},
    }
    led = build_ledger(profile, sat, timeline)
    assert led["samples_total"] == 100
    assert led["attributed_share"] == pytest.approx(0.90)
    assert led["unattributed_share"] == pytest.approx(0.10)
    assert led["idle_share"] == pytest.approx(0.30)
    entries = led["entries"]
    # ranked by share, work buckets only (no idle/wait/stdlib rows)
    assert [e["subsystem"] for e in entries] == [
        "consensus",
        "rpc",
        "eventbus",
    ]
    assert entries[0]["rank"] == 1
    assert entries[0]["share"] == pytest.approx(0.30)
    assert entries[0]["work_share"] == pytest.approx(0.5)
    # the saturation join: only the subsystem's own signal keys
    assert entries[2]["signals"] == {"eventbus_fanout_lag_max": 72.0}
    assert entries[0]["signals"] == {
        "consensus_total_txs_delta": 791.0
    }
    split = led["consensus_vs_serving"]
    assert split["serving_share"] == pytest.approx(0.30)  # rpc+eventbus
    assert split["consensus_share"] == pytest.approx(0.30)
    assert split["timeline"]["heights_attributed"] == 12


def test_build_ledger_prefers_window_counts_and_survives_empty():
    profile = {
        "subsystem_counts": {"rpc": 100},
        "window_counts": {"rpc": 1},
    }
    assert build_ledger(profile, None, None)["samples_total"] == 1
    led = build_ledger({}, None, None)
    assert led["samples_total"] == 0
    assert led["entries"] == []


# -- scripts/profile_report.py ---------------------------------------------


_FOLDED_FIXTURE = [
    "loop;rpc:conn;a.mod:outer;a.mod:inner 6",
    "loop;a.mod:outer;b.mod:leaf 3",
    "wal;c.mod:sync 1",
]


def test_profile_report_parses_folded_and_profile_json(tmp_path):
    f = tmp_path / "stacks.folded"
    f.write_text("\n".join(_FOLDED_FIXTURE) + "\n")
    entries, shares = profile_report.load_stacks(str(f), folded=True)
    assert [e["count"] for e in entries] == [6, 3, 1]
    assert entries[0]["stack"][0] == "loop"
    assert shares == {}

    doc = {
        "stats": {"samples_total": 10},
        "subsystem_shares": {"rpc": 0.6, "idle": 0.4},
        "stacks": [
            {
                "role": "loop",
                "task": "rpc:conn",
                "stack": "a.mod:outer;a.mod:inner",
                "subsystem": "rpc",
                "count": 6,
            }
        ],
    }
    p = tmp_path / "profile.json"
    p.write_text(json.dumps(doc))
    entries, shares = profile_report.load_stacks(str(p), folded=False)
    assert entries == [
        {
            "stack": ["loop", "rpc:conn", "a.mod:outer", "a.mod:inner"],
            "count": 6,
        }
    ]
    assert shares == {"rpc": 0.6, "idle": 0.4}
    # tmload-report nesting (the `profile` block) also loads
    p2 = tmp_path / "report.json"
    p2.write_text(json.dumps({"profile": doc}))
    assert profile_report.load_stacks(str(p2), folded=False) == (
        entries,
        shares,
    )


def test_profile_report_self_and_cumulative():
    entries = [
        {"stack": ["loop", "a:f", "b:g"], "count": 6},
        {"stack": ["loop", "a:f"], "count": 3},
        {"stack": ["wal", "c:h"], "count": 1},
    ]
    self_c, cum_c = profile_report.self_cumulative(entries)
    assert self_c == {"b:g": 6, "a:f": 3, "c:h": 1}
    # a:f is on both loop stacks' paths: cumulative 9
    assert cum_c["a:f"] == 9
    assert cum_c["loop"] == 9
    assert cum_c["b:g"] == 6


def test_profile_report_cli_exit_codes(tmp_path, capsys):
    f = tmp_path / "stacks.folded"
    f.write_text("\n".join(_FOLDED_FIXTURE) + "\n")
    assert profile_report.main([str(f), "--folded"]) == 0
    out = capsys.readouterr().out
    assert "a.mod:inner" in out and "self" in out
    empty = tmp_path / "empty.folded"
    empty.write_text("")
    assert profile_report.main([str(empty), "--folded"]) == 2
    assert profile_report.main([str(tmp_path / "missing.json")]) == 2


# -- RPC route -------------------------------------------------------------


def test_profile_rpc_route_lifecycle(tmp_path):
    from tendermint_tpu.loadgen.localnet import start_localnet
    from tendermint_tpu.rpc.client import HTTPClient

    async def go():
        with_home = str(tmp_path / "profnet")
        net = await start_localnet(1, with_home)
        cli = HTTPClient(net.rpc_addrs[0])
        try:
            st = await cli.call("profile")
            assert st["stats"]["enabled"] is False

            st = await cli.call(
                "profile", action="start", hz=211, reset=True
            )
            assert st["stats"]["enabled"] is True
            assert st["stats"]["hz"] == 211
            # hz clamps to [1, 997] rather than erroring
            st = await cli.call("profile", action="start", hz=5000)
            assert st["stats"]["hz"] == 997

            deadline = time.time() + 5.0
            while time.time() < deadline:
                st = await cli.call("profile")
                if st["stats"]["samples_total"] >= 10:
                    break
                await asyncio.sleep(0.05)
            assert st["stats"]["samples_total"] >= 10
            assert st["subsystem_shares"], "no shares while sampling"

            # paged snapshot under the server page cap
            page = await cli.call(
                "profile", action="snapshot", max_stacks=2
            )
            assert len(page["stacks"]) <= 2
            assert page["total_stacks"] >= len(page["stacks"])
            assert page["next"] == len(page["stacks"])
            page2 = await cli.call(
                "profile", action="snapshot", after=page["next"]
            )
            assert page2["next"] >= page["next"]

            st = await cli.call("profile", action="stop")
            assert st["stats"]["enabled"] is False

            with pytest.raises(Exception):
                await cli.call("profile", action="flamethrower")
        finally:
            await cli.close()
            await net.stop()

    asyncio.run(go())
