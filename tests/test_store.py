"""Storage: KV backends, BlockStore, StateStore."""

import pytest

from tendermint_tpu.state import (
    ABCIResponses,
    State,
    StateStore,
    state_from_genesis,
)
from tendermint_tpu.store import Batch, BlockStore, MemKV, SqliteKV
from tendermint_tpu.types import Commit, GenesisDoc, GenesisValidator
from tendermint_tpu.types.genesis import GenesisValidator
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519

from .test_types import CHAIN_ID, make_validators


@pytest.fixture(params=["mem", "sqlite"])
def db(request, tmp_path):
    if request.param == "mem":
        yield MemKV()
    else:
        kv = SqliteKV(str(tmp_path / "test.sqlite"))
        yield kv
        kv.close()


class TestKV:
    def test_roundtrip_and_order(self, db):
        db.set(b"b", b"2")
        db.set(b"a", b"1")
        db.set(b"c", b"3")
        assert db.get(b"a") == b"1"
        assert [k for k, _ in db.iterate()] == [b"a", b"b", b"c"]
        assert [k for k, _ in db.iterate(reverse=True)] == [b"c", b"b", b"a"]
        assert [k for k, _ in db.iterate(b"b")] == [b"b", b"c"]
        assert [k for k, _ in db.iterate(b"a", b"c")] == [b"a", b"b"]

    def test_batch_atomic(self, db):
        b = Batch()
        b.set(b"x", b"1")
        b.set(b"y", b"2")
        b.delete(b"x")
        db.write_batch(b)
        assert db.get(b"x") is None
        assert db.get(b"y") == b"2"


def make_chain_block(height, prev_commit=None):
    """A minimal valid block at `height` for store tests."""
    from tendermint_tpu.types import make_block

    b = make_block(height, [b"tx-%d" % height], prev_commit or Commit(), [])
    b.header.chain_id = CHAIN_ID
    b.header.validators_hash = b"\x01" * 32
    b.header.next_validators_hash = b"\x01" * 32
    b.header.consensus_hash = b"\x02" * 32
    b.header.proposer_address = b"\x03" * 20
    return b


class TestBlockStore:
    def test_empty(self, db):
        bs = BlockStore(db)
        assert bs.base() == 0
        assert bs.height() == 0
        assert bs.size() == 0
        assert bs.load_block(1) is None

    def test_save_load_roundtrip(self, db):
        bs = BlockStore(db)
        blocks = []
        for h in range(1, 6):
            b = make_chain_block(h)
            parts = b.make_part_set(128)
            seen = Commit(height=h)
            bs.save_block(b, parts, seen)
            blocks.append(b)
        assert bs.base() == 1
        assert bs.height() == 5
        assert bs.size() == 5
        b3 = bs.load_block(3)
        assert b3.hash() == blocks[2].hash()
        meta = bs.load_block_meta(3)
        assert meta.header.height == 3
        assert meta.num_txs == 1
        by_hash = bs.load_block_by_hash(blocks[2].hash())
        assert by_hash.header.height == 3
        part = bs.load_block_part(3, 0)
        assert part is not None and part.index == 0

    def test_save_rejects_gap(self, db):
        bs = BlockStore(db)
        b1 = make_chain_block(1)
        bs.save_block(b1, b1.make_part_set(128), Commit(height=1))
        b5 = make_chain_block(5)
        with pytest.raises(ValueError, match="expected 2"):
            bs.save_block(b5, b5.make_part_set(128), Commit(height=5))

    def test_prune(self, db):
        bs = BlockStore(db)
        for h in range(1, 6):
            b = make_chain_block(h)
            bs.save_block(b, b.make_part_set(128), Commit(height=h))
        pruned = bs.prune_blocks(4)
        assert pruned == 3
        assert bs.base() == 4
        assert bs.height() == 5
        assert bs.load_block(2) is None
        assert bs.load_block(4) is not None


def make_genesis(n=3):
    privs = [
        PrivKeyEd25519.from_seed(bytes([i + 1]) * 32) for i in range(n)
    ]
    return GenesisDoc(
        chain_id=CHAIN_ID,
        genesis_time_ns=1_700_000_000 * 10**9,
        validators=[
            GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs
        ],
    ), privs


class TestStateStore:
    def test_genesis_state_save_load(self, db):
        gen, _ = make_genesis()
        st = state_from_genesis(gen)
        ss = StateStore(db)
        ss.save(st)
        loaded = ss.load()
        assert loaded.chain_id == CHAIN_ID
        assert loaded.last_block_height == 0
        assert loaded.validators.hash() == st.validators.hash()
        assert (
            loaded.consensus_params.block.max_bytes
            == st.consensus_params.block.max_bytes
        )

    def test_validators_by_height(self, db):
        gen, _ = make_genesis()
        st = state_from_genesis(gen)
        ss = StateStore(db)
        ss.save(st)
        v1 = ss.load_validators(1)
        assert v1 is not None
        assert v1.hash() == st.validators.hash()
        v2 = ss.load_validators(2)
        assert v2 is not None

    def test_params_by_height(self, db):
        gen, _ = make_genesis()
        st = state_from_genesis(gen)
        ss = StateStore(db)
        ss.save(st)
        p = ss.load_params(1)
        assert p is not None
        assert p.block.max_bytes == st.consensus_params.block.max_bytes

    def test_abci_responses(self, db):
        ss = StateStore(db)
        resp = ABCIResponses(deliver_txs=[b"\x08\x01", b""], end_block=b"")
        ss.save_abci_responses(7, resp)
        loaded = ss.load_abci_responses(7)
        assert loaded.deliver_txs == [b"\x08\x01", b""]

    def test_genesis_json_roundtrip(self, tmp_path):
        gen, _ = make_genesis()
        path = str(tmp_path / "genesis.json")
        gen.save_as(path)
        gen2 = GenesisDoc.from_file(path)
        assert gen2.chain_id == gen.chain_id
        assert gen2.genesis_time_ns == gen.genesis_time_ns
        assert len(gen2.validators) == 3
        assert (
            gen2.validator_set().hash() == gen.validator_set().hash()
        )


class TestPruneAndRollback:
    """Regression tests for sparse-pointer pruning and rollback
    semantics (matching internal/state/store.go:243-330 and
    internal/state/rollback.go:13-104)."""

    def _grown_chain(self, db, heights=6):
        """State store saved at each height with an unchanged val set
        (so later records are sparse pointers to height 1)."""
        gen, _ = make_genesis()
        st = state_from_genesis(gen)
        ss = StateStore(db)
        ss.save(st)
        for h in range(1, heights):
            st = st.copy()
            st.last_block_height = h
            st.last_validators = st.validators
            st.validators = st.next_validators
            st.next_validators = st.next_validators.copy_increment_proposer_priority(1)
            ss.save(st)
        return ss, st

    def test_prune_materializes_pointed_to_records(self, db):
        ss, st = self._grown_chain(db)
        assert ss.load_validators(5) is not None
        ss.prune(5)
        # records below 5 are gone, but 5+ still loadable
        assert ss.load_validators(5) is not None
        assert ss.load_validators(6) is not None
        assert ss.load_params(5) is not None

    def test_rollback(self, db):
        from tendermint_tpu.store import MemKV

        ss, st = self._grown_chain(db, heights=4)
        bs = BlockStore(MemKV())
        for h in range(1, 4):
            b = make_chain_block(h)
            bs.save_block(b, b.make_part_set(128), Commit(height=h))
        rolled = ss.rollback(bs)
        assert rolled.last_block_height == 2
        # time comes from block 2's header, not block 3's
        assert rolled.last_block_time_ns == bs.load_block_meta(2).header.time_ns
        assert rolled.validators.hash() == st.last_validators.hash()

    def test_rollback_noop_when_blockstore_ahead(self, db):
        from tendermint_tpu.store import MemKV

        ss, st = self._grown_chain(db, heights=3)  # state at height 2
        bs = BlockStore(MemKV())
        for h in range(1, 4):  # blockstore at height 3 (one ahead)
            b = make_chain_block(h)
            bs.save_block(b, b.make_part_set(128), Commit(height=h))
        rolled = ss.rollback(bs)
        assert rolled.last_block_height == st.last_block_height

    def test_block_store_prune_removes_commits(self, db):
        bs = BlockStore(db)
        for h in range(1, 6):
            b = make_chain_block(h)
            bs.save_block(b, b.make_part_set(128), Commit(height=h))
        bs.prune_blocks(4)
        assert bs.load_block_commit(2) is None  # commit for pruned height
        assert bs.load_block_commit(4) is not None


class TestBackendRegistry:
    """Pluggable engine selection (reference: config/config.go:179-197
    selects among five engines by the db-backend knob; here the same
    knob resolves through store.kv's registry)."""

    def test_builtin_names(self, tmp_path):
        from tendermint_tpu.store.kv import open_db

        assert isinstance(open_db("a", "memdb", str(tmp_path)), MemKV)
        assert isinstance(open_db("a", "mem", str(tmp_path)), MemKV)
        for alias in ("sqlite", "goleveldb", "default"):
            db = open_db(alias, alias, str(tmp_path))
            assert isinstance(db, SqliteKV)
            db.close()

    def test_unknown_backend_lists_registered(self, tmp_path):
        from tendermint_tpu.store.kv import open_db

        with pytest.raises(ValueError, match="memdb"):
            open_db("a", "no-such-engine", str(tmp_path))

    def test_register_custom_engine(self, tmp_path):
        from tendermint_tpu.store.kv import _BACKENDS, open_db, register_backend

        calls = []

        def factory(name, db_dir):
            calls.append((name, db_dir))
            return MemKV()

        register_backend("custom-engine", factory)
        try:
            db = open_db("blockstore", "custom-engine", str(tmp_path))
            assert isinstance(db, MemKV)
            assert calls == [("blockstore", str(tmp_path))]
        finally:
            _BACKENDS.pop("custom-engine", None)
