"""EventBus — the node-wide typed event backbone.

reference: internal/eventbus/event_bus.go (:24 EventBus over pubsub.Server,
:87 publish with flattened ABCI events, :113-176 typed helpers). Every
reactor publishes here; RPC websocket subscribers and the indexer consume.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from ..libs.metrics import DEFAULT_REGISTRY, Registry
from ..libs.service import Service
from ..pubsub import Query, Server, Subscription, compile_query
from ..types import events as E

__all__ = ["EventBus", "EventBusMetrics"]


class EventBusMetrics:
    """Fanout saturation instruments (go-kit pattern; node assembly
    threads the per-node Registry). The headline series is
    `eventbus_fanout_lag`: the deepest subscriber queue observed at the
    latest publish — the signal the ROADMAP's fanout-batching follow-on
    will be judged against (a healthy bus sits near 0; a bus whose
    subscribers can't drain climbs toward the per-subscription queue
    limit and starts dropping them)."""

    def __init__(self, registry: Optional[Registry] = None) -> None:
        r = registry if registry is not None else DEFAULT_REGISTRY
        self.published = r.counter(
            "eventbus",
            "published_total",
            "Events published onto the bus.",
        )
        self.deliveries = r.counter(
            "eventbus",
            "deliveries_total",
            "Per-subscriber deliveries (one publish fans out to every "
            "matching subscription).",
        )
        self.fanout_lag = r.gauge(
            "eventbus",
            "fanout_lag",
            "Deepest subscriber queue after the latest publish — how "
            "far the slowest live subscriber lags the publisher.",
        )
        self.subscriptions = r.gauge(
            "eventbus",
            "subscriptions",
            "Live subscriptions on the bus.",
        )
        self.dropped_subscriptions = r.counter(
            "eventbus",
            "dropped_subscriptions_total",
            "Subscriptions terminated because their bounded queue "
            "overflowed (slow consumer).",
        )


def _flatten_abci_events(abci_events: Iterable) -> Dict[str, List[str]]:
    """abci.Event list → {"type.key": [values]} tag map
    (reference: internal/pubsub/pubsub.go events flattening)."""
    tags: Dict[str, List[str]] = {}
    for ev in abci_events or ():
        if not ev.type:
            continue
        for attr in ev.attributes:
            key = f"{ev.type}.{attr.key.decode(errors='replace')}"
            tags.setdefault(key, []).append(attr.value.decode(errors="replace"))
    return tags


class EventBus(Service):
    def __init__(self, metrics: Optional[EventBusMetrics] = None) -> None:
        super().__init__(name="eventbus")
        self._server = Server(name="eventbus.pubsub")
        self.metrics = metrics

    async def on_start(self) -> None:
        await self._server.start()

    async def on_stop(self) -> None:
        await self._server.stop()

    # -- subscription --

    def _sync_sub_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.subscriptions.set(
                self._server.num_subscriptions()
            )

    def subscribe(
        self, client_id: str, query: "Query | str", limit: int = 100
    ) -> Subscription:
        sub = self._server.subscribe(client_id, query, limit)
        self._sync_sub_gauge()
        return sub

    def unsubscribe(self, client_id: str, query: "Query | str") -> None:
        self._server.unsubscribe(client_id, query)
        self._sync_sub_gauge()

    def unsubscribe_all(self, client_id: str) -> None:
        self._server.unsubscribe_all(client_id)
        self._sync_sub_gauge()

    def num_clients(self) -> int:
        return self._server.num_clients()

    def max_subscriber_lag(self) -> int:
        """Deepest subscriber queue right now (scrape-time view of the
        same signal `eventbus_fanout_lag` tracks per publish)."""
        return self._server.max_queue_depth()

    # -- publishing --

    def _publish(
        self,
        event_value: str,
        data: object,
        extra_tags: Optional[Dict[str, List[str]]] = None,
    ) -> None:
        tags = dict(extra_tags or {})
        tags.setdefault(E.EVENT_TYPE_KEY, []).append(event_value)
        matched, max_depth, dropped = self._server.publish(data, tags)
        m = self.metrics
        if m is not None:
            m.published.inc()
            # deliveries = messages actually enqueued: a matched
            # subscriber whose queue overflowed (or was already dead)
            # never received this message
            if matched > dropped:
                m.deliveries.inc(matched - dropped)
            m.fanout_lag.set(max_depth)
            if dropped:
                m.dropped_subscriptions.inc(dropped)
                self._sync_sub_gauge()

    def publish_new_block(self, data: E.EventDataNewBlock) -> None:
        tags = _flatten_abci_events(
            getattr(data.result_begin_block, "events", ())
        )
        for k, v in _flatten_abci_events(
            getattr(data.result_end_block, "events", ())
        ).items():
            tags.setdefault(k, []).extend(v)
        tags[E.BLOCK_HEIGHT_KEY] = [str(data.block.header.height)]
        self._publish(E.EventValue.NEW_BLOCK, data, tags)

    def publish_new_block_header(self, data: E.EventDataNewBlockHeader) -> None:
        tags = {E.BLOCK_HEIGHT_KEY: [str(data.header.height)]}
        self._publish(E.EventValue.NEW_BLOCK_HEADER, data, tags)

    def publish_new_evidence(self, data: E.EventDataNewEvidence) -> None:
        self._publish(E.EventValue.NEW_EVIDENCE, data)

    def publish_tx(self, data: E.EventDataTx, tx_hash: bytes) -> None:
        """reference: internal/eventbus/event_bus.go:135-160 — app events
        from DeliverTx plus the reserved tx.hash/tx.height keys."""
        tags = _flatten_abci_events(getattr(data.result, "events", ()))
        tags[E.TX_HASH_KEY] = [tx_hash.hex().upper()]
        tags[E.TX_HEIGHT_KEY] = [str(data.height)]
        self._publish(E.EventValue.TX, data, tags)

    def publish_validator_set_updates(
        self, data: E.EventDataValidatorSetUpdates
    ) -> None:
        self._publish(E.EventValue.VALIDATOR_SET_UPDATES, data)

    def publish_vote(self, data: E.EventDataVote) -> None:
        self._publish(E.EventValue.VOTE, data)

    def publish_new_round(self, data: E.EventDataNewRound) -> None:
        self._publish(E.EventValue.NEW_ROUND, data)

    def publish_new_round_step(self, data: E.EventDataRoundState) -> None:
        self._publish(E.EventValue.NEW_ROUND_STEP, data)

    def publish_complete_proposal(self, data: E.EventDataCompleteProposal) -> None:
        self._publish(E.EventValue.COMPLETE_PROPOSAL, data)

    def publish_polka(self, data: E.EventDataRoundState) -> None:
        self._publish(E.EventValue.POLKA, data)

    def publish_valid_block(self, data: E.EventDataRoundState) -> None:
        self._publish(E.EventValue.VALID_BLOCK, data)

    def publish_lock(self, data: E.EventDataRoundState) -> None:
        self._publish(E.EventValue.LOCK, data)

    def publish_relock(self, data: E.EventDataRoundState) -> None:
        self._publish(E.EventValue.RELOCK, data)

    def publish_timeout_propose(self, data: E.EventDataRoundState) -> None:
        self._publish(E.EventValue.TIMEOUT_PROPOSE, data)

    def publish_timeout_wait(self, data: E.EventDataRoundState) -> None:
        self._publish(E.EventValue.TIMEOUT_WAIT, data)

    def publish_block_sync_status(self, data: E.EventDataBlockSyncStatus) -> None:
        self._publish(E.EventValue.BLOCK_SYNC_STATUS, data)

    def publish_state_sync_status(self, data: E.EventDataStateSyncStatus) -> None:
        self._publish(E.EventValue.STATE_SYNC_STATUS, data)
