"""Consensus wire messages — the codec for the four consensus p2p
channels and the WAL.

reference: internal/consensus/msgs.go (domain ⇄ proto conversion),
proto/tendermint/consensus/types.pb.go (field numbers cited per message),
proto/tendermint/consensus/wal.proto (WAL records).

These are plain dataclasses with deterministic proto encoding via the
framework's ProtoWriter — no generated code.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..encoding.proto import (
    FieldReader,
    ProtoWriter,
    decode_varint,
    encode_varint,
)
from ..libs.bits import BitArray
from ..types.block_id import BlockID, PartSetHeader
from ..types.part_set import Part
from ..types.proposal import Proposal
from ..types.timestamp import decode_timestamp, encode_timestamp
from ..types.vote import Vote

__all__ = [
    "NewRoundStepMessage",
    "NewValidBlockMessage",
    "ProposalMessage",
    "ProposalPOLMessage",
    "BlockPartMessage",
    "VoteMessage",
    "HasVoteMessage",
    "VoteSetMaj23Message",
    "VoteSetBitsMessage",
    "encode_msg",
    "decode_msg",
    "MsgInfo",
    "TimeoutInfo",
    "EndHeightMessage",
    "EventDataRoundStateWAL",
    "encode_timed_wal_message",
    "decode_timed_wal_message",
    "encode_bit_array",
    "decode_bit_array",
]


# -- BitArray proto (reference: libs/bits/types.pb.go: bits=1, elems=2) --
#
# `elems` is `repeated uint64` and proto3 packs repeated scalars: ONE
# length-delimited field holding concatenated varints. Packing is not
# just fidelity — the earlier per-word `w.uint(2, word)` form reused
# the SINGULAR writer, whose proto3 zero-omission dropped all-zero
# middle words, shifting every later word down on decode (bit 190
# silently became bit 126 once a validator set crossed 128 and a word
# went quiet). Packed varints have no zero-omission.


def encode_bit_array(ba: Optional[BitArray]) -> Optional[bytes]:
    if ba is None:
        return None
    w = ProtoWriter()
    w.int(1, ba.size)
    packed = bytearray()
    for word in ba.to_words():
        packed += encode_varint(word)
    w.bytes(2, bytes(packed))
    return w.finish()


def decode_bit_array(data: Optional[bytes]) -> Optional[BitArray]:
    if data is None:
        return None
    r = FieldReader(data)
    size = r.int64(1)
    words: list = []
    for v in r.get_all(2):
        if isinstance(v, bytes):
            # packed (canonical): concatenated varints
            off = 0
            while off < len(v):
                word, off = decode_varint(v, off)
                words.append(word)
        else:
            # legacy unpacked record (pre-packed WAL entries); zero
            # words were dropped by the old writer, so trailing
            # placement is best-effort — packed is the canonical form
            words.append(v)
    return BitArray.from_words(size, words)


# -- channel messages --


@dataclass
class NewRoundStepMessage:
    """reference: consensus/types.pb.go:31-35."""

    height: int = 0
    round: int = 0
    step: int = 0
    seconds_since_start_time: int = 0
    last_commit_round: int = 0

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        w.int(2, self.round)
        w.uint(3, self.step)
        w.int(4, self.seconds_since_start_time)
        w.int(5, self.last_commit_round)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "NewRoundStepMessage":
        r = FieldReader(data)
        return cls(
            height=r.int64(1),
            round=r.int64(2),
            step=r.uint(3),
            seconds_since_start_time=r.int64(4),
            last_commit_round=r.int64(5),
        )

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.height == 1 and self.last_commit_round != -1:
            raise ValueError("initial height must have LastCommitRound -1")


@dataclass
class NewValidBlockMessage:
    """reference: consensus/types.pb.go:112-116."""

    height: int = 0
    round: int = 0
    block_part_set_header: PartSetHeader = field(default_factory=PartSetHeader)
    block_parts: Optional[BitArray] = None
    is_commit: bool = False

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        w.int(2, self.round)
        w.message(3, self.block_part_set_header.to_proto())
        w.message(4, encode_bit_array(self.block_parts))
        w.bool(5, self.is_commit)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "NewValidBlockMessage":
        r = FieldReader(data)
        psh = r.get(3)
        return cls(
            height=r.int64(1),
            round=r.int64(2),
            block_part_set_header=(
                PartSetHeader.from_proto(psh)
                if psh is not None
                else PartSetHeader()
            ),
            block_parts=decode_bit_array(r.get(4)),
            is_commit=r.bool(5),
        )

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        self.block_part_set_header.validate_basic()
        if (
            self.block_parts is not None
            and self.block_parts.size != self.block_part_set_header.total
        ):
            raise ValueError(
                f"blockParts bit array size {self.block_parts.size} "
                f"not equal to BlockPartSetHeader.Total "
                f"{self.block_part_set_header.total}"
            )


@dataclass
class ProposalMessage:
    """reference: consensus/types.pb.go:189."""

    proposal: Proposal = field(default_factory=Proposal)

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.message(1, self.proposal.to_proto())
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "ProposalMessage":
        r = FieldReader(data)
        p = r.get(1)
        return cls(
            proposal=Proposal.from_proto(p) if p is not None else Proposal()
        )

    def validate_basic(self) -> None:
        self.proposal.validate_basic()


@dataclass
class ProposalPOLMessage:
    """reference: consensus/types.pb.go:234-236."""

    height: int = 0
    proposal_pol_round: int = 0
    proposal_pol: Optional[BitArray] = None

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        w.int(2, self.proposal_pol_round)
        w.message(3, encode_bit_array(self.proposal_pol))
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "ProposalPOLMessage":
        r = FieldReader(data)
        return cls(
            height=r.int64(1),
            proposal_pol_round=r.int64(2),
            proposal_pol=decode_bit_array(r.get(3)),
        )

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative Height")
        if self.proposal_pol_round < 0:
            raise ValueError("negative ProposalPOLRound")
        if self.proposal_pol is None or self.proposal_pol.size == 0:
            raise ValueError("empty ProposalPOL bit array")


def _empty_part() -> Part:
    from ..crypto import merkle

    return Part(index=0, bytes=b"", proof=merkle.Proof(total=0, index=0, leaf_hash=b""))


@dataclass
class BlockPartMessage:
    """reference: consensus/types.pb.go:295-297."""

    height: int = 0
    round: int = 0
    part: Part = field(default_factory=_empty_part)

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        w.int(2, self.round)
        w.message(3, self.part.to_proto())
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "BlockPartMessage":
        r = FieldReader(data)
        p = r.get(3)
        if p is None:
            # the old `else Part()` fallback ALWAYS crashed (Part has
            # no field defaults) — a missing part is a parse error,
            # same as the reference's nil-Part FromProto failure
            raise ValueError("BlockPartMessage: missing part field")
        return cls(
            height=r.int64(1),
            round=r.int64(2),
            part=Part.from_proto(p),
        )

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        self.part.validate_basic()


@dataclass
class VoteMessage:
    """reference: consensus/types.pb.go:356."""

    vote: Vote = field(default_factory=Vote)

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.message(1, self.vote.to_proto())
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "VoteMessage":
        r = FieldReader(data)
        v = r.get(1)
        return cls(vote=Vote.from_proto(v) if v is not None else Vote())

    def validate_basic(self) -> None:
        self.vote.validate_basic()


@dataclass
class HasVoteMessage:
    """reference: consensus/types.pb.go:401-404."""

    height: int = 0
    round: int = 0
    type: int = 0
    index: int = 0

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        w.int(2, self.round)
        w.int(3, self.type)
        w.int(4, self.index)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "HasVoteMessage":
        r = FieldReader(data)
        return cls(
            height=r.int64(1),
            round=r.int64(2),
            type=r.uint(3),
            index=r.int64(4),
        )

    def validate_basic(self) -> None:
        from ..types.vote import is_vote_type_valid

        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if not is_vote_type_valid(self.type):
            raise ValueError("invalid Type")
        if self.index < 0:
            raise ValueError("negative Index")


@dataclass
class VoteSetMaj23Message:
    """reference: consensus/types.pb.go:470-473."""

    height: int = 0
    round: int = 0
    type: int = 0
    block_id: BlockID = field(default_factory=BlockID)

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        w.int(2, self.round)
        w.int(3, self.type)
        w.message(4, self.block_id.to_proto())
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "VoteSetMaj23Message":
        r = FieldReader(data)
        bid = r.get(4)
        return cls(
            height=r.int64(1),
            round=r.int64(2),
            type=r.uint(3),
            block_id=BlockID.from_proto(bid) if bid is not None else BlockID(),
        )

    def validate_basic(self) -> None:
        from ..types.vote import is_vote_type_valid

        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if not is_vote_type_valid(self.type):
            raise ValueError("invalid Type")
        self.block_id.validate_basic()


@dataclass
class VoteSetBitsMessage:
    """reference: consensus/types.pb.go:540-544."""

    height: int = 0
    round: int = 0
    type: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    votes: Optional[BitArray] = None

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        w.int(2, self.round)
        w.int(3, self.type)
        w.message(4, self.block_id.to_proto())
        w.message(5, encode_bit_array(self.votes))
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "VoteSetBitsMessage":
        r = FieldReader(data)
        bid = r.get(4)
        return cls(
            height=r.int64(1),
            round=r.int64(2),
            type=r.uint(3),
            block_id=BlockID.from_proto(bid) if bid is not None else BlockID(),
            votes=decode_bit_array(r.get(5)),
        )

    def validate_basic(self) -> None:
        from ..types.vote import is_vote_type_valid

        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if not is_vote_type_valid(self.type):
            raise ValueError("invalid Type")
        self.block_id.validate_basic()


# The Message oneof (reference: consensus/types.pb.go:669-693)
_MSG_FIELDS = {
    1: NewRoundStepMessage,
    2: NewValidBlockMessage,
    3: ProposalMessage,
    4: ProposalPOLMessage,
    5: BlockPartMessage,
    6: VoteMessage,
    7: HasVoteMessage,
    8: VoteSetMaj23Message,
    9: VoteSetBitsMessage,
}
_MSG_FIELD_OF = {cls: num for num, cls in _MSG_FIELDS.items()}


def encode_msg(msg) -> bytes:
    """Wrap a consensus message in the Message oneof envelope."""
    num = _MSG_FIELD_OF.get(type(msg))
    if num is None:
        raise TypeError(f"unknown consensus message: {type(msg).__name__}")
    w = ProtoWriter()
    w.message(num, msg.to_proto())
    return w.finish()


def decode_msg(data: bytes):
    r = FieldReader(data)
    for num, cls in _MSG_FIELDS.items():
        body = r.get(num)
        if body is not None:
            return cls.from_proto(body)
    raise ValueError("empty or unknown consensus Message envelope")


# -- WAL records (reference: proto/tendermint/consensus/wal.proto) --


@dataclass
class MsgInfo:
    """A consensus input from a peer ('' = internal)
    (reference: internal/consensus/state.go msgInfo)."""

    msg: object = None
    peer_id: str = ""

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.message(1, encode_msg(self.msg))
        w.string(2, self.peer_id)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "MsgInfo":
        r = FieldReader(data)
        m = r.get(1)
        return cls(
            msg=decode_msg(m) if m is not None else None,
            peer_id=r.string(2),
        )


@dataclass
class TimeoutInfo:
    """A scheduled timeout for (height, round, step)
    (reference: internal/consensus/state.go timeoutInfo, ticker.go)."""

    duration_s: float = 0.0
    height: int = 0
    round: int = 0
    step: int = 0  # RoundStep value

    def to_proto(self) -> bytes:
        # google.protobuf.Duration: seconds=1, nanos=2
        d = ProtoWriter()
        total_ns = int(self.duration_s * 1e9)
        d.int(1, total_ns // 1_000_000_000)
        d.int(2, total_ns % 1_000_000_000)
        w = ProtoWriter()
        w.message(1, d.finish())
        w.int(2, self.height)
        w.int(3, self.round)
        w.uint(4, self.step)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "TimeoutInfo":
        r = FieldReader(data)
        dur = r.get(1)
        duration_s = 0.0
        if dur is not None:
            dr = FieldReader(dur)
            duration_s = dr.int64(1) + dr.int64(2) / 1e9
        return cls(
            duration_s=duration_s,
            height=r.int64(2),
            round=r.int64(3),
            step=r.uint(4),
        )

    def __repr__(self) -> str:
        return (
            f"{self.duration_s:.3f}s@{self.height}/{self.round}/{self.step}"
        )


@dataclass
class EndHeightMessage:
    """Marks a height as completely finished in the WAL — replay starts
    after the last one (reference: internal/consensus/wal.go:36-42)."""

    height: int = 0

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "EndHeightMessage":
        return cls(height=FieldReader(data).int64(1))


@dataclass
class EventDataRoundStateWAL:
    """Round-step transition marker in the WAL
    (reference: proto/tendermint/types/events.proto EventDataRoundState)."""

    height: int = 0
    round: int = 0
    step: str = ""

    def to_proto(self) -> bytes:
        w = ProtoWriter()
        w.int(1, self.height)
        w.int(2, self.round)
        w.string(3, self.step)
        return w.finish()

    @classmethod
    def from_proto(cls, data: bytes) -> "EventDataRoundStateWAL":
        r = FieldReader(data)
        return cls(height=r.int64(1), round=r.int64(2), step=r.string(3))


# WALMessage oneof (wal.proto: event_data_round_state=1, msg_info=2,
# timeout_info=3, end_height=4)
_WAL_FIELDS = {
    1: EventDataRoundStateWAL,
    2: MsgInfo,
    3: TimeoutInfo,
    4: EndHeightMessage,
}
_WAL_FIELD_OF = {cls: num for num, cls in _WAL_FIELDS.items()}


def encode_timed_wal_message(time_ns: int, msg) -> bytes:
    """TimedWALMessage{time=1, msg=2} (wal.proto)."""
    num = _WAL_FIELD_OF.get(type(msg))
    if num is None:
        raise TypeError(f"unknown WAL message: {type(msg).__name__}")
    inner = ProtoWriter()
    inner.message(num, msg.to_proto())
    w = ProtoWriter()
    w.message(1, encode_timestamp(time_ns))
    w.message(2, inner.finish())
    return w.finish()


def decode_timed_wal_message(data: bytes):
    """→ (time_ns, msg)."""
    r = FieldReader(data)
    ts = r.get(1)
    time_ns = decode_timestamp(ts) if ts is not None else 0
    body = r.get(2)
    if body is None:
        raise ValueError("TimedWALMessage without msg")
    br = FieldReader(body)
    for num, cls in _WAL_FIELDS.items():
        sub = br.get(num)
        if sub is not None:
            return time_ns, cls.from_proto(sub)
    raise ValueError("unknown WALMessage oneof")
