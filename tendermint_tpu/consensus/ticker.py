"""TimeoutTicker — schedules round-step timeouts.

reference: internal/consensus/ticker.go. One pending timeout at a time;
scheduling a newer (height, round, step) replaces the pending one, stale
schedules are ignored. Fired timeouts land on an asyncio queue consumed
by the consensus receive loop.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..libs.log import get_logger
from ..libs.service import Service
from .msgs import TimeoutInfo

__all__ = ["TimeoutTicker"]


class TimeoutTicker(Service):
    def __init__(self) -> None:
        super().__init__(name="ticker", logger=get_logger("consensus.ticker"))
        self._out: asyncio.Queue[TimeoutInfo] = asyncio.Queue()
        self._pending: Optional[TimeoutInfo] = None
        self._timer: Optional[asyncio.Task] = None

    @property
    def timeout_queue(self) -> "asyncio.Queue[TimeoutInfo]":
        return self._out

    async def on_stop(self) -> None:
        self._stop_timer()

    def schedule(self, ti: TimeoutInfo) -> None:
        """Schedule ti, unless something newer is already pending
        (reference: ticker.go:92-126 timeoutRoutine)."""
        cur = self._pending
        if cur is not None:
            if ti.height < cur.height:
                return
            if ti.height == cur.height:
                if ti.round < cur.round:
                    return
                if ti.round == cur.round and cur.step > 0 and ti.step <= cur.step:
                    return
        self._stop_timer()
        self._pending = ti
        self._timer = self.spawn(self._fire_after(ti), "timeout-timer")

    def _stop_timer(self) -> None:
        if self._timer is not None and not self._timer.done():
            self._timer.cancel()
        self._timer = None
        # prune finished/cancelled timers so _tasks doesn't grow per round
        self._tasks = [t for t in self._tasks if not t.done()]

    async def _fire_after(self, ti: TimeoutInfo) -> None:
        await asyncio.sleep(ti.duration_s)
        self.logger.debug("timed out", ti=repr(ti))
        if self._pending is ti:
            self._pending = None
        self._out.put_nowait(ti)
