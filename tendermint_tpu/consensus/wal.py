"""Write-ahead log for consensus inputs.

Every message is written to the WAL BEFORE it is processed, so a crashed
node replays exactly the inputs it had seen and lands in the same round
state (reference: internal/consensus/wal.go; write-before-process in
state.go:855-870).

Record framing (reference: wal.go encoder :268-292):
    crc32(4, big-endian) | length(4, big-endian) | proto(TimedWALMessage)
CRC is Python's zlib.crc32 (IEEE polynomial) rather than the reference's
Castagnoli table — on-disk WALs are framework-local, not cross-verified.

Own votes/proposals use write_sync (fsync) so a signature can never
outlive its WAL record across a crash (reference: state.go:861).
"""

from __future__ import annotations

import io
import os
import struct
import time
import zlib
from typing import Iterator, Optional, Tuple

from ..libs.log import get_logger
from ..libs.service import Service
from .msgs import (
    EndHeightMessage,
    decode_timed_wal_message,
    encode_timed_wal_message,
)

__all__ = ["WAL", "NopWAL", "WALDecodeError", "iter_wal_records"]

MAX_MSG_SIZE = 1 << 20  # 1 MB (reference: wal.go maxMsgSizeBytes)
FLUSH_INTERVAL_S = 2.0  # reference: wal.go walDefaultFlushInterval


class WALDecodeError(Exception):
    """Corrupt record (bad CRC / overlong / truncated mid-record)."""


def _frame(payload: bytes) -> bytes:
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return struct.pack(">II", crc, len(payload)) + payload


def _read_record(f: io.BufferedReader) -> Optional[bytes]:
    """One framed record, None at clean EOF, WALDecodeError if torn."""
    hdr = f.read(8)
    if len(hdr) == 0:
        return None
    if len(hdr) < 8:
        raise WALDecodeError("truncated record header")
    crc, length = struct.unpack(">II", hdr)
    if length > MAX_MSG_SIZE:
        raise WALDecodeError(f"record too big: {length}")
    payload = f.read(length)
    if len(payload) < length:
        raise WALDecodeError("truncated record body")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise WALDecodeError("CRC mismatch")
    return payload


def iter_wal_records(path: str) -> Iterator[Tuple[int, object]]:
    """Yield (time_ns, msg) from a WAL file, stopping at the first torn
    record (a crash mid-write leaves a torn tail; everything before it is
    intact — reference: wal.go:97-103 repair semantics)."""
    with open(path, "rb") as f:
        while True:
            try:
                payload = _read_record(f)
            except WALDecodeError:
                return
            if payload is None:
                return
            yield decode_timed_wal_message(payload)


class WAL(Service):
    """reference: internal/consensus/wal.go BaseWAL."""

    def __init__(self, path: str) -> None:
        super().__init__(name="wal", logger=get_logger("consensus.wal"))
        self.path = path
        self._f: Optional[io.BufferedWriter] = None
        self._dirty = False

    async def on_start(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._truncate_torn_tail()
        self._f = open(self.path, "ab")
        self.spawn(self._flush_routine(), "wal-flush")

    async def on_stop(self) -> None:
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
            self._f = None

    def _truncate_torn_tail(self) -> None:
        """Drop a torn final record left by a crash so appends start at a
        record boundary."""
        if not os.path.exists(self.path):
            return
        good_end = 0
        with open(self.path, "rb") as f:
            while True:
                try:
                    if _read_record(f) is None:
                        break
                    good_end = f.tell()
                except WALDecodeError:
                    self.logger.error(
                        "WAL has a torn tail; truncating",
                        good_bytes=good_end,
                    )
                    break
        if good_end < os.path.getsize(self.path):
            with open(self.path, "r+b") as f:
                f.truncate(good_end)

    # -- writes --

    def write(self, msg) -> None:
        """Buffered append (peer messages, timeouts — reference:
        wal.go:173)."""
        if self._f is None:
            return
        payload = encode_timed_wal_message(time.time_ns(), msg)
        if len(payload) > MAX_MSG_SIZE:
            raise ValueError(f"WAL message too big: {len(payload)}")
        self._f.write(_frame(payload))
        self._dirty = True

    def write_sync(self, msg) -> None:
        """Append + flush + fsync. Used for own messages: the signature
        this record describes must hit disk before it leaves the process
        (reference: wal.go:183-196, state.go:861)."""
        self.write(msg)
        self.flush_and_sync()

    def flush_and_sync(self) -> None:
        if self._f is None or not self._dirty:
            return
        self._f.flush()
        os.fsync(self._f.fileno())
        self._dirty = False

    async def _flush_routine(self) -> None:
        """Periodic group flush (reference: wal.go:116 processFlushTicks)."""
        import asyncio

        while True:
            await asyncio.sleep(FLUSH_INTERVAL_S)
            self.flush_and_sync()

    # -- replay support --

    def write_end_height(self, height: int) -> None:
        """Height fully committed; the replay cut point
        (reference: state.go:867 updateToState → wal.WriteSync(EndHeight))."""
        self.write_sync(EndHeightMessage(height=height))

    def search_for_end_height(
        self, height: int
    ) -> Optional[list]:
        """All messages recorded AFTER EndHeight(height), i.e. the inputs
        of height+1 onward, or None if that marker isn't in the log
        (reference: wal.go:202-254). height 0 means 'from the start' when
        no EndHeight(0) exists but the log is non-empty."""
        if not os.path.exists(self.path):
            return None
        out: list = []
        found = False
        for _ts, msg in iter_wal_records(self.path):
            if isinstance(msg, EndHeightMessage) and msg.height == height:
                found = True
                out = []
                continue
            # Later EndHeight markers ARE returned so catchup replay can
            # detect an inconsistent store/WAL (crash between EndHeight
            # fsync and state save) instead of silently merging heights.
            if found or height == 0:
                out.append(msg)
        if found:
            return out
        # Special case: a fresh WAL that never completed `height` but has
        # records (reference treats missing EndHeight(0) as start-of-file).
        if height == 0 and out:
            return out
        return None


class NopWAL:
    """For tests and non-validator replay paths
    (reference: wal.go nilWAL)."""

    def write(self, msg) -> None: ...

    def write_sync(self, msg) -> None: ...

    def flush_and_sync(self) -> None: ...

    def write_end_height(self, height: int) -> None: ...

    def search_for_end_height(self, height: int):
        return None

    async def start(self) -> None: ...

    async def stop(self) -> None: ...
