"""Write-ahead log for consensus inputs.

Every message is written to the WAL BEFORE it is processed, so a crashed
node replays exactly the inputs it had seen and lands in the same round
state (reference: internal/consensus/wal.go; write-before-process in
state.go:855-870).

Record framing (reference: wal.go encoder :268-292):
    crc32(4, big-endian) | length(4, big-endian) | proto(TimedWALMessage)
CRC is Python's zlib.crc32 (IEEE polynomial) rather than the reference's
Castagnoli table — on-disk WALs are framework-local, not cross-verified.

Own votes/proposals use write_sync (fsync) so a signature can never
outlive its WAL record across a crash (reference: state.go:861).
"""

from __future__ import annotations

import io
import os
import struct
import time
import zlib
from typing import Iterator, Optional, Tuple

from ..crypto import faults
from ..libs.log import get_logger
from ..libs.service import Service
from .msgs import (
    EndHeightMessage,
    decode_timed_wal_message,
    encode_timed_wal_message,
)

__all__ = [
    "WAL",
    "NopWAL",
    "WALDecodeError",
    "iter_wal_records",
    "iter_wal_group",
]

MAX_MSG_SIZE = 1 << 20  # 1 MB (reference: wal.go maxMsgSizeBytes)
FLUSH_INTERVAL_S = 2.0  # reference: wal.go walDefaultFlushInterval
# autofile-group analog (reference: internal/libs/autofile/group.go:66-100):
# the head rotates once it crosses HEAD_SIZE_LIMIT, and the oldest rotated
# files are pruned when the whole group exceeds TOTAL_SIZE_LIMIT
HEAD_SIZE_LIMIT = 10 << 20  # group.go defaultHeadSizeLimit (10 MB)
TOTAL_SIZE_LIMIT = 1 << 30  # group.go defaultTotalSizeLimit (1 GB)


class WALDecodeError(Exception):
    """Corrupt record (bad CRC / overlong / truncated mid-record)."""


def _frame(payload: bytes) -> bytes:
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return struct.pack(">II", crc, len(payload)) + payload


def _read_record(f: io.BufferedReader) -> Optional[bytes]:
    """One framed record, None at clean EOF, WALDecodeError if torn."""
    hdr = f.read(8)
    if len(hdr) == 0:
        return None
    if len(hdr) < 8:
        raise WALDecodeError("truncated record header")
    crc, length = struct.unpack(">II", hdr)
    if length > MAX_MSG_SIZE:
        raise WALDecodeError(f"record too big: {length}")
    payload = f.read(length)
    if len(payload) < length:
        raise WALDecodeError("truncated record body")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise WALDecodeError("CRC mismatch")
    return payload


def _decode_record(payload: bytes):
    """decode_timed_wal_message, with decode failures (e.g. an unknown
    message type from a WAL written by a newer binary) re-raised as
    WALDecodeError so a CRC-valid-but-undecodable record degrades like
    a torn/corrupt one instead of crashing boot/crash-recovery."""
    try:
        return decode_timed_wal_message(payload)
    except (ValueError, TypeError, KeyError, IndexError, struct.error) as e:
        # any shape of malformed-but-CRC-valid payload (wrong wire
        # type, truncated field, unknown message tag) is corruption
        raise WALDecodeError(f"undecodable record: {e}") from e


def iter_wal_records(path: str) -> Iterator[Tuple[int, object]]:
    """Yield (time_ns, msg) from a WAL file, stopping at the first torn
    record (a crash mid-write leaves a torn tail; everything before it is
    intact — reference: wal.go:97-103 repair semantics)."""
    with open(path, "rb") as f:
        while True:
            try:
                payload = _read_record(f)
                if payload is None:
                    return
                msg = _decode_record(payload)
            except WALDecodeError:
                return
            yield msg


def wal_group_files(path: str) -> list:
    """The WAL group for head file `path`, oldest first: rotated files
    `path.NNN` in index order, then the head (reference: autofile
    group.go — Head plus {Head.Path}.NNN chunks)."""
    d = os.path.dirname(path) or "."
    base = os.path.basename(path)
    rotated = []
    try:
        names = os.listdir(d)
    except FileNotFoundError:
        return []
    for name in names:
        if name.startswith(base + "."):
            suffix = name[len(base) + 1:]
            if suffix.isdigit():
                rotated.append((int(suffix), os.path.join(d, name)))
    out = [p for _, p in sorted(rotated)]
    if os.path.exists(path):
        out.append(path)
    return out


def _read_chunk(path: str) -> Tuple[list, bool]:
    """All messages of one chunk in order plus a clean-EOF flag (False
    when decoding stopped at a torn/corrupt record)."""
    msgs: list = []
    with open(path, "rb") as f:
        while True:
            try:
                payload = _read_record(f)
                if payload is None:
                    return msgs, True
                msg = _decode_record(payload)[1]
            except WALDecodeError:
                return msgs, False
            msgs.append(msg)


def iter_wal_group(path: str) -> Iterator[Tuple[int, object]]:
    """iter_wal_records across the whole rotated group, oldest record
    first. Rotated files are closed at record boundaries, so only the
    head can have a torn tail; a decode error anywhere (external
    corruption) ends the WHOLE iteration — records after a corrupt one
    are not trustworthy input history, same as the single-file
    semantics."""
    for p in wal_group_files(path):
        with open(p, "rb") as f:
            while True:
                try:
                    payload = _read_record(f)
                    if payload is None:
                        break
                    msg = _decode_record(payload)
                except WALDecodeError:
                    return
                yield msg


class WAL(Service):
    """reference: internal/consensus/wal.go BaseWAL, writing through an
    autofile-group analog (internal/libs/autofile/group.go): the head
    file rotates to `{path}.NNN` once it crosses head_size_limit, and
    the oldest rotated files are pruned when the group's total size
    exceeds total_size_limit — a long-running validator's WAL is
    size-bounded instead of growing forever."""

    def __init__(
        self,
        path: str,
        head_size_limit: int = HEAD_SIZE_LIMIT,
        total_size_limit: int = TOTAL_SIZE_LIMIT,
    ) -> None:
        super().__init__(name="wal", logger=get_logger("consensus.wal"))
        self.path = path
        self.head_size_limit = head_size_limit
        self.total_size_limit = total_size_limit
        self._f: Optional[io.BufferedWriter] = None
        self._dirty = False
        self._head_size = 0
        self._prune_pending = False

    async def on_start(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._truncate_torn_tail()
        self._f = open(self.path, "ab")
        self._head_size = os.path.getsize(self.path)
        # next rotation index, computed once so _rotate never listdirs
        rotated = wal_group_files(self.path)[:-1]
        self._next_chunk_idx = 0
        if rotated:
            last = os.path.basename(rotated[-1])
            self._next_chunk_idx = (
                int(last[len(os.path.basename(self.path)) + 1:]) + 1
            )
        self.spawn(self._flush_routine(), "wal-flush")

    async def on_stop(self) -> None:
        if self._f is not None:
            self._f.flush()
            # tmlive: block-ok — final durability barrier at shutdown:
            # the last signed messages must hit disk before the file
            # closes; the node is stopping, there is no serving path
            # left to stall (reference: wal.go Stop -> FlushAndSync)
            os.fsync(self._f.fileno())
            self._f.close()
            self._f = None
        if self._prune_pending:
            # settle deferred pruning so a clean shutdown leaves the
            # group within its size bound
            self._prune_pending = False
            self._enforce_total_size()

    def _truncate_torn_tail(self) -> None:
        """Drop a torn OR undecodable final record left by a crash (or
        by a newer binary) so appends start after the last good record
        — otherwise everything written after the bad record would be
        invisible to recovery, which stops at the first corruption
        (reference: wal.go:97-103 repair semantics)."""
        if not os.path.exists(self.path):
            return
        good_end = 0
        with open(self.path, "rb") as f:
            while True:
                try:
                    payload = _read_record(f)
                    if payload is None:
                        break
                    _decode_record(payload)
                    good_end = f.tell()
                except WALDecodeError:
                    self.logger.error(
                        "WAL has a torn/undecodable tail; truncating",
                        good_bytes=good_end,
                    )
                    break
        if good_end < os.path.getsize(self.path):
            with open(self.path, "r+b") as f:
                f.truncate(good_end)

    # -- writes --

    def write(self, msg) -> None:
        """Buffered append (peer messages, timeouts — reference:
        wal.go:173). Crossing the head-size limit rotates at the record
        boundary just written (reference: group.go checkHeadSizeLimit —
        there on a ticker; synchronous here keeps the bound exact)."""
        if self._f is None:
            return
        payload = encode_timed_wal_message(time.time_ns(), msg)
        if len(payload) > MAX_MSG_SIZE:
            raise ValueError(f"WAL message too big: {len(payload)}")
        frame = _frame(payload)
        if faults.armed():
            # crypto/faults.py "wal.write" short_write rule: persist
            # only a prefix of the frame — the on-disk shape a crash
            # mid-write leaves, normally only reachable by killing the
            # process at exactly the wrong instruction. Recovery
            # (_truncate_torn_tail + search_for_end_height) must treat
            # it exactly like a hand-truncated file.
            frame = faults.clip("wal.write", frame)
        self._f.write(frame)
        self._dirty = True
        self._head_size += len(frame)
        if self._head_size >= self.head_size_limit:
            self._rotate()

    def write_sync(self, msg) -> None:
        """Append + flush + fsync. Used for own messages: the signature
        this record describes must hit disk before it leaves the process
        (reference: wal.go:183-196, state.go:861)."""
        self.write(msg)
        self.flush_and_sync()

    def flush_and_sync(self) -> None:
        if self._f is None or not self._dirty:
            return
        self._f.flush()
        if faults.armed():
            faults.fire("wal.fsync")  # io_error rule -> OSError
        # tmlive: block-ok — protocol-required durability: an own
        # vote/proposal must be on disk BEFORE it leaves the process,
        # or a crash double-signs (reference: state.go:861 fsyncs on
        # the consensus goroutine too). The stall cost is bounded by
        # group commit — peer messages ride the 2 s flush ticker, only
        # own-message records pay a synchronous fsync.
        os.fsync(self._f.fileno())
        self._dirty = False

    async def _flush_routine(self) -> None:
        """Periodic group flush (reference: wal.go:116 processFlushTicks)
        plus deferred group pruning — the directory scan lives here, off
        the write path (the reference prunes on a background ticker,
        group.go processTicks)."""
        import asyncio

        while True:
            await asyncio.sleep(FLUSH_INTERVAL_S)
            self.flush_and_sync()
            if self._prune_pending:
                self._prune_pending = False
                self._enforce_total_size()

    # -- rotation (autofile-group analog) --

    def _rotate(self) -> None:
        """fsync + close the head, rename it to the next `.NNN` chunk,
        and open a fresh head (reference: group.go rotateFile). The
        fsync must stay on this path: write_sync's durability promise
        has to hold for a record that just landed in the rotated-out
        chunk (its flush_and_sync afterwards only reaches the new
        head). Pruning — the directory scan — is deferred to the flush
        routine so the consensus loop doesn't pay it at every 10 MB
        boundary (reference prunes on a ticker, checkTotalSizeLimit
        group.go:100-160)."""
        assert self._f is not None
        self._f.flush()
        if faults.armed():
            # the rotation fsync is the durability hinge: write_sync's
            # promise for a record that just landed in the rotating
            # chunk holds ONLY if this fsync really reached disk, so an
            # injected failure here must propagate (never be swallowed)
            faults.fire("wal.fsync")
        # tmlive: block-ok — rotation durability hinge: write_sync's
        # promise for a record that just landed in the rotating-out
        # chunk holds only if this fsync reached disk before the
        # rename; amortized once per 10 MB of WAL (reference:
        # group.go rotateFile)
        os.fsync(self._f.fileno())
        self._f.close()
        target = f"{self.path}.{self._next_chunk_idx:03d}"
        self._next_chunk_idx += 1
        os.replace(self.path, target)
        self._f = open(self.path, "ab")
        self._head_size = 0
        self._dirty = False
        self.logger.info("rotated WAL head", chunk=os.path.basename(target))
        self._prune_pending = True

    def _enforce_total_size(self) -> None:
        """Delete oldest rotated chunks while the group exceeds
        total_size_limit. The head is never deleted (reference:
        group.go:129 checkTotalSizeLimit, which skips index maxIndex)."""
        files = wal_group_files(self.path)
        sizes = {p: os.path.getsize(p) for p in files}
        total = sum(sizes.values())
        for p in files[:-1]:  # oldest first; never the head
            if total <= self.total_size_limit:
                break
            os.remove(p)
            total -= sizes[p]
            self.logger.info(
                "pruned oldest WAL chunk over total-size limit",
                chunk=os.path.basename(p),
            )

    # -- replay support --

    def write_end_height(self, height: int) -> None:
        """Height fully committed; the replay cut point
        (reference: state.go:867 updateToState → wal.WriteSync(EndHeight))."""
        self.write_sync(EndHeightMessage(height=height))

    def search_for_end_height(
        self, height: int
    ) -> Optional[list]:
        """All messages recorded AFTER EndHeight(height), i.e. the inputs
        of height+1 onward, or None if that marker isn't in the log
        (reference: wal.go:202-254 — a backwards group scan). Chunks are
        read newest-first so crash recovery touches only the tail of the
        group (the marker is almost always in the head) and corruption
        in an OLD chunk can never mask an intact recent tail. height 0
        means 'from the start' when no EndHeight(0) exists but the log
        is non-empty. Later EndHeight markers ARE returned so catchup
        replay can detect an inconsistent store/WAL (crash between
        EndHeight fsync and state save) instead of silently merging
        heights."""
        files = wal_group_files(self.path)
        if not files:
            return None
        # chunks newer than the marker, newest first (concatenated once
        # at return — no quadratic re-copying while scanning)
        newer: list = []
        for p in reversed(files):
            msgs, clean = _read_chunk(p)
            if not clean and p != self.path:
                # Only the head may legitimately end short (torn tail).
                # A short decode of a ROTATED chunk is real corruption,
                # and the records lost after the corruption point would
                # leave a silent hole in the replayed input history —
                # fail the search loudly instead of replaying a gapped
                # history into consensus.
                self.logger.error(
                    "corrupt record inside rotated WAL chunk; refusing "
                    "to assemble a replay history with a gap",
                    chunk=os.path.basename(p),
                )
                return None
            marker = None
            for j, m in enumerate(msgs):
                if isinstance(m, EndHeightMessage) and m.height == height:
                    marker = j
            if marker is not None:
                out = msgs[marker + 1:]
                for chunk_msgs in reversed(newer):
                    out.extend(chunk_msgs)
                return out
            newer.append(msgs)
        # Special case: a fresh WAL that never completed `height` but has
        # records (reference treats missing EndHeight(0) as start-of-file).
        if height == 0 and any(newer):
            out = []
            for chunk_msgs in reversed(newer):
                out.extend(chunk_msgs)
            return out
        return None


class NopWAL:
    """For tests and non-validator replay paths
    (reference: wal.go nilWAL)."""

    def write(self, msg) -> None: ...

    def write_sync(self, msg) -> None: ...

    def flush_and_sync(self) -> None: ...

    def write_end_height(self, height: int) -> None: ...

    def search_for_end_height(self, height: int):
        return None

    async def start(self) -> None: ...

    async def stop(self) -> None: ...
