"""Consensus engine — BFT state machine, WAL, timeouts, wire messages.

reference: internal/consensus/. The compute-heavy verification paths it
drives (per-vote signature checks, whole-commit batch verification) live
in the crypto/types layers and run on the device; this package is the
host-side orchestration.
"""

from .state import ConsensusState
from .ticker import TimeoutTicker
from .timeline import TimelineRecorder, events_from_wal
from .types import HeightVoteSet, RoundState, RoundStep, step_name
from .wal import WAL, NopWAL, iter_wal_records

__all__ = [
    "ConsensusState",
    "TimeoutTicker",
    "TimelineRecorder",
    "HeightVoteSet",
    "RoundState",
    "RoundStep",
    "step_name",
    "WAL",
    "NopWAL",
    "events_from_wal",
    "iter_wal_records",
]
