"""Node-driven WAL generator — the replay/crash-test fixture.

reference: internal/consensus/wal_generator.go (WALGenerateNBlocks:
"boot a node, run it until N blocks, hand back the WAL bytes"). Tests
that hand-build WAL records exercise the codec but not the real
sequencing of propose/vote/timeout inputs a live consensus run writes;
this fixture produces the real thing: a single-validator node over the
builtin kvstore app runs in-process until `n_blocks` are committed, and
the WAL file it wrote is returned.
"""

from __future__ import annotations

import os
import time
from typing import Optional

__all__ = ["generate_wal"]


async def generate_wal(
    home: str,
    n_blocks: int,
    chain_id: str = "wal-generator",
    timeout: float = 60.0,
    seed: bytes = b"\x57" * 32,
):
    """Run a real node until `n_blocks` are committed; returns
    (wal_path, genesis, priv_key). The node is stopped (WAL closed and
    flushed) before returning."""
    from ..config import Config
    from ..crypto.ed25519 import PrivKeyEd25519
    from ..node.node import make_node
    from ..privval.file import FilePV
    from ..types.genesis import GenesisDoc, GenesisValidator

    priv = PrivKeyEd25519.from_seed(seed)
    genesis = GenesisDoc(
        chain_id=chain_id,
        genesis_time_ns=time.time_ns(),
        validators=[
            GenesisValidator(pub_key=priv.pub_key(), power=10)
        ],
    )
    cfg = Config()
    cfg.base.home = home
    cfg.base.chain_id = chain_id
    cfg.base.db_backend = "memdb"
    cfg.consensus.timeout_commit = 0.05
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.tpu.enable = False  # the fixture is about WAL bytes, not crypto
    cfg.ensure_dirs()
    genesis.save_as(cfg.base.path(cfg.base.genesis_file))
    FilePV.from_priv_key(
        priv,
        cfg.base.path(cfg.priv_validator.key_file),
        cfg.base.path(cfg.priv_validator.state_file),
    ).save()

    node = make_node(cfg)
    await node.start()
    try:
        await node.consensus.wait_for_height(
            n_blocks + 1, timeout=timeout
        )
    finally:
        await node.stop()
    wal_path = cfg.base.path(cfg.consensus.wal_file)
    assert os.path.exists(wal_path), wal_path
    return wal_path, genesis, priv
