"""ConsensusState — the Tendermint BFT state machine.

reference: internal/consensus/state.go. One async receive loop serializes
every input (peer messages, own messages, timeouts) through the WAL, then
drives the round-step transitions:

    NewHeight → NewRound → Propose → Prevote → (PrevoteWait) →
    Precommit → (PrecommitWait) → Commit → NewHeight …

Single-writer by construction (reference: state.go:803 receiveRoutine):
all mutation happens on the receive task; producers only enqueue. The
signature-verification hot paths hit the device:
  - per-vote verify in VoteSet.add_vote (crypto layer seam),
  - whole-LastCommit batch verify inside BlockExecutor.validate_block →
    types.validation.verify_commit (the TPU kernel's north-star call).
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from ..config import ConsensusConfig
from ..eventbus import EventBus
from ..libs import trace
from ..libs.log import get_logger
from ..libs.timeutil import NS_PER_S, ns_to_s, s_to_ns
from ..libs.service import Service
from ..privval.types import PrivValidator
from ..state.execution import BlockExecutor
from ..state.types import State
from ..store.block_store import BlockStore
from ..types import events as E
from ..types.block import Block
from ..types.block_id import BlockID, PartSetHeader
from ..types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from ..types.commit import Commit
from ..types.part_set import PartSet
from ..types.proposal import Proposal
from ..types.vote import Vote

from ..types.vote_set import ConflictingVoteError, VoteSet, commit_to_vote_set
from .msgs import (
    BlockPartMessage,
    EndHeightMessage,
    EventDataRoundStateWAL,
    MsgInfo,
    ProposalMessage,
    TimeoutInfo,
    VoteMessage,
)
from .metrics import ConsensusMetrics
from .ticker import TimeoutTicker
from .timeline import (
    EV_NEW_ROUND,
    EV_STEP,
    EV_TIMEOUT,
    TimelineRecorder,
)
from .types import HeightVoteSet, RoundState, RoundStep, step_name
from .wal import WAL, NopWAL

__all__ = ["ConsensusState"]

# wait_for_height poll interval — integer nanoseconds, like all time
# math in this module (det-float); converted to float seconds only at
# the asyncio.sleep boundary via libs.timeutil
_WAIT_POLL_NS = 10 * NS_PER_S // 1000


class ConsensusState(Service):
    """reference: internal/consensus/state.go:60 (struct), :803
    (receiveRoutine)."""

    def __init__(
        self,
        cfg: ConsensusConfig,
        state: State,
        block_exec: BlockExecutor,
        block_store: BlockStore,
        privval: Optional[PrivValidator] = None,
        event_bus: Optional[EventBus] = None,
        wal: "WAL | NopWAL | None" = None,
        evidence_pool=None,
        replay_mode: bool = False,
        metrics: Optional[ConsensusMetrics] = None,
        timeline: Optional[TimelineRecorder] = None,
    ) -> None:
        super().__init__(name="consensus", logger=get_logger("consensus"))
        self.cfg = cfg
        # reference: internal/consensus/metrics.go threaded via
        # CSMetrics; per-node registry when node assembly provides one
        self.metrics = metrics if metrics is not None else ConsensusMetrics()
        # per-node consensus flight recorder (consensus/timeline.py);
        # node assembly threads the config-built one, bare
        # constructions get a default-capacity ring feeding the same
        # metrics struct
        self.timeline: TimelineRecorder = (
            timeline
            if timeline is not None
            else TimelineRecorder(metrics=self.metrics)
        )
        self.block_exec = block_exec
        self.block_store = block_store
        self.privval = privval
        self.privval_pub_key = None
        self.event_bus = event_bus
        # annotated with the real WAL so whole-program analyses
        # (tmcheck/tmlive) resolve write_sync/fsync edges on the
        # consensus path; NopWAL (tests/replay) is a no-op duck twin
        self.wal: WAL = wal if wal is not None else NopWAL()
        self.evpool = evidence_pool

        self.rs = RoundState()
        self.state: Optional[State] = None

        self.peer_msg_queue: asyncio.Queue = asyncio.Queue(maxsize=1000)
        self.internal_msg_queue: asyncio.Queue = asyncio.Queue(maxsize=1000)
        self.ticker = TimeoutTicker()
        # replay_mode=True builds a playback-only instance (replay
        # console): signing errors are silenced and the caller feeds
        # recorded inputs via replay_one() instead of start()
        self._replay_mode = replay_mode
        # height of the last EndHeight marker found in the WAL on boot
        self._done_first_block = asyncio.Event()

        # overridable for Byzantine tests
        # (reference: state.go decideProposal/doPrevote function fields)
        self.decide_proposal = self._default_decide_proposal
        self.do_prevote = self._default_do_prevote

        self._update_to_state(state)
        self._reconstruct_last_commit_from_store(state)

    # ------------------------------------------------------------------
    # lifecycle

    async def on_start(self) -> None:
        if self.privval is not None:
            self.privval_pub_key = await self.privval.get_pub_key()
        await self.wal.start()
        await self.ticker.start()
        await self._catchup_replay(self.rs.height)
        self.spawn(self._receive_routine(), "receive")
        self._schedule_round_0()

    async def on_stop(self) -> None:
        await self.ticker.stop()
        await self.wal.stop()

    # ------------------------------------------------------------------
    # public API (used by reactor / RPC / tests)

    def get_round_state(self) -> RoundState:
        return self.rs

    def send_peer_msg(self, msg, peer_id: str) -> None:
        """Enqueue a consensus message from the network. Drops on
        overflow — gossip is redundant and retried, and a slow consensus
        loop must backpressure peers, not crash the reactor."""
        try:
            self.peer_msg_queue.put_nowait(MsgInfo(msg=msg, peer_id=peer_id))
        except asyncio.QueueFull:
            self.logger.debug(
                "peer msg queue full; dropping",
                msg_type=type(msg).__name__, peer=peer_id[:12],
            )

    def _send_internal(self, msg) -> None:
        self.internal_msg_queue.put_nowait(MsgInfo(msg=msg, peer_id=""))

    def is_proposer(self, address: bytes) -> bool:
        return self.rs.validators.get_proposer().address == address

    def privval_address(self) -> Optional[bytes]:
        return (
            self.privval_pub_key.address()
            if self.privval_pub_key is not None
            else None
        )

    async def wait_for_height(self, height: int, timeout: float = 30) -> None:
        """Test/RPC helper: block until consensus reaches `height`."""
        deadline_ns = time.monotonic_ns() + s_to_ns(timeout)
        while self.rs.height < height:
            if time.monotonic_ns() > deadline_ns:
                raise TimeoutError(
                    f"height {height} not reached (at {self.rs.height})"
                )
            await asyncio.sleep(ns_to_s(_WAIT_POLL_NS))

    # ------------------------------------------------------------------
    # state transitions between heights

    def _update_to_state(self, state: State) -> None:
        """Reset the RoundState for the height after state.last_block_height
        (reference: state.go:670-792 updateToState)."""
        rs = self.rs
        if rs.commit_round > -1 and 0 < rs.height != state.last_block_height:
            raise RuntimeError(
                f"updateToState at height {state.last_block_height} "
                f"while at {rs.height}/{rs.commit_round}"
            )
        if (
            self.state is not None
            and self.state.last_block_height > 0
            and self.state.last_block_height + 1 != rs.height
        ):
            # (LastBlockHeight==0 means genesis; rs.height is then
            # initial_height which may be > 1 — reference: state.go:688-700)
            raise RuntimeError("inconsistent state for ConsensusState")

        # Carry over +2/3 precommits as the new LastCommit
        last_commit: Optional[VoteSet] = None
        if state.last_block_height > 0 and rs.commit_round > -1:
            precommits = rs.votes.precommits(rs.commit_round)
            if precommits is None or not precommits.has_two_thirds_majority():
                raise RuntimeError(
                    "updateToState called without +2/3 precommits"
                )
            last_commit = precommits
        elif state.last_block_height > 0:
            last_commit = rs.last_commit  # restart path, set by reconstruct

        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height

        # tmlint: disable=det-wallclock — protocol-required: height
        # start time derives from local clock + timeout_commit
        # (reference: state.go updateToState)
        now_ns = time.time_ns()
        if rs.commit_time_ns == 0:
            start_time_ns = now_ns + s_to_ns(self.cfg.timeout_commit)
        else:
            start_time_ns = rs.commit_time_ns + s_to_ns(
                self.cfg.timeout_commit
            )

        validators = state.validators
        rs.height = height
        rs.round = 0
        rs.step = RoundStep.NEW_HEIGHT
        rs.start_time_ns = start_time_ns
        rs.validators = validators
        rs.proposal = None
        rs.proposal_block = None
        rs.proposal_block_parts = None
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        rs.valid_round = -1
        rs.valid_block = None
        rs.valid_block_parts = None
        rs.votes = HeightVoteSet(state.chain_id, height, validators)
        rs.commit_round = -1
        rs.last_commit = last_commit
        rs.last_validators = state.last_validators
        rs.triggered_timeout_precommit = False
        self.state = state
        self.timeline.mark_new_height(height)
        self.metrics.height.set(height)
        self.metrics.rounds.set(0)
        self.metrics.validators.set(validators.size())
        self.metrics.validators_power.set(validators.total_voting_power())

    def _reconstruct_last_commit_from_store(self, state: State) -> None:
        """On restart, rebuild LastCommit from the stored seen-commit
        (reference: state.go:640-668 reconstructLastCommit)."""
        if state.last_block_height == 0:
            return
        if self.rs.last_commit is not None:
            return
        seen = self.block_store.load_seen_commit()
        if seen is None or seen.height != state.last_block_height:
            seen = self.block_store.load_block_commit(state.last_block_height)
        if seen is None:
            raise RuntimeError(
                f"failed to reconstruct last commit; commit for height "
                f"{state.last_block_height} not found"
            )
        vote_set = commit_to_vote_set(
            state.chain_id, seen, state.last_validators
        )
        if not vote_set.has_two_thirds_majority():
            raise RuntimeError(
                "failed to reconstruct last commit; does not have +2/3"
            )
        self.rs.last_commit = vote_set

    def _schedule_round_0(self) -> None:
        """reference: state.go scheduleRound0."""
        # tmlint: disable=det-wallclock — local timeout scheduling;
        # never enters sign-bytes or hashes
        delay_ns = max(0, self.rs.start_time_ns - time.time_ns())
        self._schedule_timeout(
            ns_to_s(delay_ns), self.rs.height, 0, RoundStep.NEW_HEIGHT
        )

    def _schedule_timeout(
        self, duration_s: float, height: int, round_: int, step: int
    ) -> None:
        self.ticker.schedule(
            TimeoutInfo(
                duration_s=duration_s, height=height, round=round_, step=step
            )
        )

    # ------------------------------------------------------------------
    # the receive loop (reference: state.go:803 receiveRoutine)

    async def _receive_routine(self) -> None:
        internal_get = peer_get = timeout_get = None
        loop = asyncio.get_event_loop()
        try:
            while True:
                if internal_get is None:
                    internal_get = loop.create_task(
                        self.internal_msg_queue.get()
                    )
                if peer_get is None:
                    peer_get = loop.create_task(self.peer_msg_queue.get())
                if timeout_get is None:
                    timeout_get = loop.create_task(
                        self.ticker.timeout_queue.get()
                    )
                done, _pending = await asyncio.wait(
                    {internal_get, peer_get, timeout_get},
                    return_when=asyncio.FIRST_COMPLETED,
                )
                # Internal messages drain first: own votes/proposal must
                # apply before further peer input (the reference processes
                # whichever select case fires; strict priority here is
                # deterministic).
                if internal_get in done:
                    mi = internal_get.result()
                    internal_get = None
                    self.wal.write_sync(mi)  # own message: fsync before act
                    await self._handle_msg(mi)
                if peer_get in done:
                    mi = peer_get.result()
                    peer_get = None
                    # verify-ahead: drain whatever else is already
                    # queued (bounded) and batch-verify the vote
                    # signatures in one device call before processing
                    # serially (SURVEY §7; reference hot path:
                    # state.go:2010,2058 + vote_set.go:203 verifies one
                    # by one on CPU)
                    batch = [mi]
                    while len(batch) < 256:
                        try:
                            batch.append(self.peer_msg_queue.get_nowait())
                        except asyncio.QueueEmpty:
                            break
                    self._preverify_votes(batch)
                    for m in batch:
                        # own messages keep strict priority over the
                        # rest of the drained batch: a just-signed own
                        # vote must be fsynced + applied before further
                        # peer input (same invariant as the un-batched
                        # loop above). The pending internal_get task may
                        # have already claimed a queued own message —
                        # consume it there first, or the vote would sit
                        # in the completed task until the batch ends.
                        while True:
                            own = None
                            if (
                                internal_get is not None
                                and internal_get.done()
                            ):
                                own = internal_get.result()
                                internal_get = None
                            else:
                                try:
                                    own = (
                                        self.internal_msg_queue.get_nowait()
                                    )
                                except asyncio.QueueEmpty:
                                    break
                            self.wal.write_sync(own)
                            await self._handle_msg(own)
                        self.wal.write(m)
                        await self._handle_msg(m)
                if timeout_get in done:
                    ti = timeout_get.result()
                    timeout_get = None
                    self.wal.write(ti)
                    await self._handle_timeout(ti)
        finally:
            for t in (internal_get, peer_get, timeout_get):
                if t is not None and not t.done():
                    t.cancel()

    def _preverify_votes(self, batch: list) -> None:
        """Batch-verify signatures of queued votes for the CURRENT
        height in one device call; valid triples populate the process-
        wide verified-signature cache (crypto.sigcache), so
        VoteSet.add_vote's Vote.verify — and the NEXT height's
        verify_commit of the LastCommit assembled from these very
        precommits — skip the per-signature CPU verify. Runs inside the
        single-writer loop against rs.validators — the exact set every
        HeightVoteSet of this height verifies with — and the cache key
        binds the exact triple bytes, so it never widens acceptance.
        Failed or foreign-height votes are left uncached and take the
        normal verify path (which produces the proper per-vote
        error)."""
        with trace.span("preverify_votes", queued=len(batch)):
            self._preverify_votes_impl(batch)

    def _preverify_votes_impl(self, batch: list) -> None:
        from ..crypto import sigcache
        from ..crypto.batch import (
            create_batch_verifier,
            drain_and_cache,
            supports_batch_verifier,
        )

        if not sigcache.enabled():
            # nowhere to record the result: the per-vote path in
            # add_vote does the work (and produces identical behavior)
            return
        rs = self.rs
        # one candidate group per key type: a mixed ed25519/sr25519
        # validator set pre-verifies every type, each through its own
        # batch verifier (same per-type grouping as
        # types/validation.py's commit path)
        groups: dict = {}
        for mi in batch:
            msg = mi.msg
            if not isinstance(msg, VoteMessage):
                continue
            vote = msg.vote
            if (
                vote.height != rs.height
                or not vote.signature
                or len(vote.signature) != 64
            ):
                # malformed entries go to the per-vote path; they must
                # not make bv.add throw and kill the whole batch (one
                # hostile 63-byte signature would otherwise disable the
                # fast path for every vote in the burst)
                continue
            addr, val = rs.validators.get_by_index(vote.validator_index)
            if val is None or addr != vote.validator_address:
                continue
            if val.pub_key.address() != vote.validator_address:
                continue  # same check Vote.verify performs
            groups.setdefault(val.pub_key.type(), []).append(
                (vote, val.pub_key)
            )
        chain_id = self.state.chain_id
        for candidates in groups.values():
            if not supports_batch_verifier(candidates[0][1]):
                continue
            # assemble only cache misses (duplicates of an earlier
            # burst, or re-gossiped votes, are already proven) — one
            # bulk set-intersection over the burst instead of a
            # per-vote generation probe (sigcache.seen_keys_bulk)
            keys = [
                sigcache.key_for(
                    pk.bytes(), vote.sign_bytes(chain_id), vote.signature
                )
                for vote, pk in candidates
            ]
            hit_set = sigcache.seen_keys_bulk(keys)
            triples = [
                (pk, vote.sign_bytes(chain_id), vote.signature, ckey)
                for (vote, pk), ckey in zip(candidates, keys)
                if ckey not in hit_set
            ]
            if len(triples) < 2:
                continue
            try:
                bv = create_batch_verifier(
                    triples[0][0], size_hint=len(triples)
                )
                for pk, sign_bytes, sig, _ckey in triples:
                    bv.add(pk, sign_bytes, sig)
                # valid triples land in the cache; failures stay out,
                # so add_vote re-verifies them for the proper error
                drain_and_cache(bv, [t[3] for t in triples])
            except Exception as e:
                # a device hiccup: fall back to the per-vote path for
                # this group (candidate filtering already excluded
                # malformed signatures)
                self.logger.debug("verify-ahead batch failed", err=str(e))
                continue

    async def _handle_msg(self, mi: MsgInfo) -> None:
        """reference: state.go:891-960 handleMsg."""
        msg, peer_id = mi.msg, mi.peer_id
        try:
            if isinstance(msg, ProposalMessage):
                self._set_proposal(msg.proposal)
            elif isinstance(msg, BlockPartMessage):
                added = await self._add_proposal_block_part(msg, peer_id)
                if added:
                    await self._handle_complete_proposal()
            elif isinstance(msg, VoteMessage):
                await self._try_add_vote(msg.vote, peer_id)
            else:
                self.logger.error(
                    "unknown msg type in receive loop", type=type(msg).__name__
                )
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self.logger.error(
                "failed to process message",
                height=self.rs.height,
                round=self.rs.round,
                msg_type=type(msg).__name__,
                err=str(e),
            )

    async def _handle_timeout(self, ti: TimeoutInfo) -> None:
        """reference: state.go:962-1011 handleTimeout."""
        rs = self.rs
        if (
            ti.height != rs.height
            or ti.round < rs.round
            or (ti.round == rs.round and ti.step < rs.step)
        ):
            self.logger.debug("ignoring tock because we are ahead", ti=repr(ti))
            return
        tl = self.timeline
        if tl.enabled:
            tl.record(
                EV_TIMEOUT,
                ti.height,
                ti.round,
                step=step_name(ti.step),
                duration_s=ti.duration_s,
            )
        if ti.step == RoundStep.NEW_HEIGHT:
            await self._enter_new_round(ti.height, 0)
        elif ti.step == RoundStep.NEW_ROUND:
            await self._enter_propose(ti.height, 0)
        elif ti.step == RoundStep.PROPOSE:
            self._publish_round_state_event("timeout_propose")
            await self._enter_prevote(ti.height, ti.round)
        elif ti.step == RoundStep.PREVOTE_WAIT:
            self._publish_round_state_event("timeout_wait")
            await self._enter_precommit(ti.height, ti.round)
        elif ti.step == RoundStep.PRECOMMIT_WAIT:
            self._publish_round_state_event("timeout_wait")
            await self._enter_precommit(ti.height, ti.round)
            await self._enter_new_round(ti.height, ti.round + 1)
        else:
            raise RuntimeError(f"invalid timeout step {ti.step}")

    # ------------------------------------------------------------------
    # round-step transitions

    async def _enter_new_round(self, height: int, round_: int) -> None:
        """reference: state.go:1062-1142."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step != RoundStep.NEW_HEIGHT
        ):
            return
        self.metrics.rounds.set(round_)
        self.logger.info(
            "entering new round",
            height=height,
            round=round_,
            current=rs.height_round_step(),
        )
        validators = rs.validators
        if rs.round < round_:
            validators = validators.copy()
            validators.increment_proposer_priority(round_ - rs.round)
        rs.round = round_
        rs.step = RoundStep.NEW_ROUND
        rs.validators = validators
        tl = self.timeline
        if tl.enabled and round_ != 0:
            # round 0 is covered by new_height; later entries are the
            # burned rounds the fleet merger attributes
            tl.record(EV_NEW_ROUND, height, round_)
        if round_ != 0:
            # round 0 keeps the proposal from NewHeight; later rounds start
            # over (valid block, if any, is re-proposed by the new proposer)
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round_ + 1)  # track next round's votes too
        rs.triggered_timeout_precommit = False
        if self.event_bus:
            self.event_bus.publish_new_round(
                E.EventDataNewRound(
                    height=height,
                    round=round_,
                    step=step_name(rs.step),
                    proposer_address=rs.validators.get_proposer().address,
                )
            )
        await self._enter_propose(height, round_)

    async def _enter_propose(self, height: int, round_: int) -> None:
        """reference: state.go:1144-1213."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PROPOSE
        ):
            return
        self.logger.debug("entering propose step", hrs=rs.height_round_step())
        rs.step = RoundStep.PROPOSE
        self._new_step()

        # Propose timeout regardless of proposer identity
        self._schedule_timeout(
            self.cfg.propose_timeout(round_), height, round_, RoundStep.PROPOSE
        )

        # Replay runs this too: the privval re-signs (same-HRS returns the
        # identical signature) and the queued message dedups against the
        # replayed one — matching the reference, where replayMode only
        # silences logging (reference: replay.go:98-100, state.go:1258).
        addr = self.privval_address()
        if addr is not None and rs.validators.has_address(addr):
            if self.is_proposer(addr):
                self.logger.debug("our turn to propose")
                await self.decide_proposal(height, round_)

        if self._is_proposal_complete():
            await self._enter_prevote(height, round_)

    async def _default_decide_proposal(self, height: int, round_: int) -> None:
        """reference: state.go:1215-1266 defaultDecideProposal."""
        rs = self.rs
        if rs.valid_block is not None:
            block, block_parts = rs.valid_block, rs.valid_block_parts
        else:
            commit = self._load_commit_for_proposal(height)
            if commit is None:
                self.logger.error("propose: no last commit available")
                return
            block, block_parts = self.block_exec.create_proposal_block(
                height, self.state, commit, self.privval_address()
            )

        block_id = BlockID(
            hash=block.hash(), part_set_header=block_parts.header()
        )
        proposal = Proposal(
            height=height,
            round=round_,
            pol_round=rs.valid_round,
            block_id=block_id,
        )
        try:
            await self.privval.sign_proposal(self.state.chain_id, proposal)
        except Exception as e:
            if not self._replay_mode:
                self.logger.error("propose: failed to sign proposal", err=str(e))
            return
        self._send_internal(ProposalMessage(proposal=proposal))
        for i in range(block_parts.total):
            part = block_parts.get_part(i)
            self._send_internal(
                BlockPartMessage(height=rs.height, round=round_, part=part)
            )
        self.logger.info(
            "signed proposal", height=height, round=round_,
            hash=block.hash().hex()[:16],
        )

    def _load_commit_for_proposal(self, height: int) -> Optional[Commit]:
        if height == self.state.initial_height:
            return Commit(height=0, round=0, block_id=BlockID(), signatures=[])
        if (
            self.rs.last_commit is not None
            and self.rs.last_commit.has_two_thirds_majority()
        ):
            return self.rs.last_commit.make_commit()
        return None

    def _is_proposal_complete(self) -> bool:
        """reference: state.go:1268-1282."""
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    async def _enter_prevote(self, height: int, round_: int) -> None:
        """reference: state.go:1323-1352."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PREVOTE
        ):
            return
        self.logger.debug("entering prevote step", hrs=rs.height_round_step())
        rs.step = RoundStep.PREVOTE
        self._new_step()
        await self.do_prevote(height, round_)

    async def _default_do_prevote(self, height: int, round_: int) -> None:
        """reference: state.go:1354-1417 defaultDoPrevote."""
        rs = self.rs
        if rs.locked_block is not None:
            self.logger.debug("prevote: locked block")
            await self._sign_add_vote(PREVOTE_TYPE, rs.locked_block.hash(),
                                      rs.locked_block_parts.header())
            return
        if rs.proposal_block is None:
            self.logger.debug("prevote: ProposalBlock is nil; voting nil")
            await self._sign_add_vote(PREVOTE_TYPE, b"", None)
            return
        try:
            self.block_exec.validate_block(self.state, rs.proposal_block)
        except Exception as e:
            self.logger.error(
                "prevote: ProposalBlock is invalid; voting nil", err=str(e)
            )
            await self._sign_add_vote(PREVOTE_TYPE, b"", None)
            return
        await self._sign_add_vote(
            PREVOTE_TYPE,
            rs.proposal_block.hash(),
            rs.proposal_block_parts.header(),
        )

    async def _enter_prevote_wait(self, height: int, round_: int) -> None:
        """reference: state.go enterPrevoteWait."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PREVOTE_WAIT
        ):
            return
        prevotes = rs.votes.prevotes(round_)
        if prevotes is None or not prevotes.has_two_thirds_any():
            raise RuntimeError(
                "enterPrevoteWait without +2/3 prevotes for any block"
            )
        rs.step = RoundStep.PREVOTE_WAIT
        self._new_step()
        self._schedule_timeout(
            self.cfg.prevote_timeout(round_),
            height, round_, RoundStep.PREVOTE_WAIT,
        )

    async def _enter_precommit(self, height: int, round_: int) -> None:
        """reference: state.go:1419-1540."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= RoundStep.PRECOMMIT
        ):
            return
        self.logger.debug("entering precommit step", hrs=rs.height_round_step())
        rs.step = RoundStep.PRECOMMIT
        self._new_step()

        prevotes = rs.votes.prevotes(round_)
        block_id, ok = (
            prevotes.two_thirds_majority() if prevotes else (BlockID(), False)
        )

        if not ok:
            self.logger.debug("precommit: no +2/3 prevotes; precommitting nil")
            await self._sign_add_vote(PRECOMMIT_TYPE, b"", None)
            return

        self._publish_round_state_event("polka")

        if block_id.is_zero():
            # +2/3 prevoted nil: unlock and precommit nil
            if rs.locked_block is not None:
                self.logger.debug("precommit: +2/3 prevoted nil; unlocking")
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
            await self._sign_add_vote(PRECOMMIT_TYPE, b"", None)
            return

        if rs.locked_block is not None and rs.locked_block.hashes_to(
            block_id.hash
        ):
            self.logger.debug("precommit: +2/3 prevoted locked block; relocking")
            rs.locked_round = round_
            self._publish_round_state_event("relock")
            await self._sign_add_vote(
                PRECOMMIT_TYPE, block_id.hash, block_id.part_set_header
            )
            return

        if rs.proposal_block is not None and rs.proposal_block.hashes_to(
            block_id.hash
        ):
            self.logger.debug(
                "precommit: +2/3 prevoted proposal block; locking",
                hash=block_id.hash.hex()[:16],
            )
            self.block_exec.validate_block(self.state, rs.proposal_block)
            rs.locked_round = round_
            rs.locked_block = rs.proposal_block
            rs.locked_block_parts = rs.proposal_block_parts
            self._publish_round_state_event("lock")
            await self._sign_add_vote(
                PRECOMMIT_TYPE, block_id.hash, block_id.part_set_header
            )
            return

        # +2/3 prevotes for a block we don't have: unlock, fetch it
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
            block_id.part_set_header
        ):
            rs.proposal_block = None
            rs.proposal_block_parts = PartSet.from_header(
                block_id.part_set_header
            )
        await self._sign_add_vote(PRECOMMIT_TYPE, b"", None)

    async def _enter_precommit_wait(self, height: int, round_: int) -> None:
        """reference: state.go enterPrecommitWait."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.triggered_timeout_precommit
        ):
            return
        precommits = rs.votes.precommits(round_)
        if precommits is None or not precommits.has_two_thirds_any():
            raise RuntimeError(
                "enterPrecommitWait without +2/3 precommits for any block"
            )
        rs.triggered_timeout_precommit = True
        self._new_step()
        self._schedule_timeout(
            self.cfg.precommit_timeout(round_),
            height, round_, RoundStep.PRECOMMIT_WAIT,
        )

    async def _enter_commit(self, height: int, commit_round: int) -> None:
        """reference: state.go:1573-1634."""
        rs = self.rs
        if rs.height != height or rs.step >= RoundStep.COMMIT:
            return
        self.logger.info(
            "entering commit step", hrs=rs.height_round_step(),
            commit_round=commit_round,
        )
        rs.step = RoundStep.COMMIT
        rs.commit_round = commit_round
        # tmlint: disable=det-wallclock — local commit-time anchor
        # for the next height's start (reference: state.go enterCommit)
        rs.commit_time_ns = time.time_ns()
        self._new_step()

        precommits = rs.votes.precommits(commit_round)
        block_id, ok = precommits.two_thirds_majority()
        if not ok:
            raise RuntimeError("enterCommit expects +2/3 precommits")

        if rs.locked_block is not None and rs.locked_block.hashes_to(
            block_id.hash
        ):
            rs.proposal_block = rs.locked_block
            rs.proposal_block_parts = rs.locked_block_parts
        if rs.proposal_block is None or not rs.proposal_block.hashes_to(
            block_id.hash
        ):
            if rs.proposal_block_parts is None or not (
                rs.proposal_block_parts.has_header(block_id.part_set_header)
            ):
                self.logger.info(
                    "commit is for a block we do not know about; "
                    "set ProposalBlock=nil",
                    commit=block_id.hash.hex()[:16],
                )
                rs.proposal_block = None
                rs.proposal_block_parts = PartSet.from_header(
                    block_id.part_set_header
                )
                self._publish_round_state_event("valid_block")
        await self._try_finalize_commit(height)

    async def _try_finalize_commit(self, height: int) -> None:
        """reference: state.go:1636-1662."""
        rs = self.rs
        if rs.height != height:
            raise RuntimeError("tryFinalizeCommit at wrong height")
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, ok = precommits.two_thirds_majority()
        if not ok or block_id.is_zero():
            self.logger.error(
                "failed attempt to finalize commit; there was no +2/3 majority "
                "or +2/3 was for nil"
            )
            return
        if rs.proposal_block is None or not rs.proposal_block.hashes_to(
            block_id.hash
        ):
            self.logger.debug(
                "failed attempt to finalize commit; we do not have the "
                "commit block",
                proposal_block=(
                    rs.proposal_block.hash().hex()[:16]
                    if rs.proposal_block else "nil"
                ),
                commit_block=block_id.hash.hex()[:16],
            )
            return
        await self._finalize_commit(height)

    async def _finalize_commit(self, height: int) -> None:
        """Save the block, write EndHeight, ApplyBlock, advance
        (reference: state.go:1664-1777)."""
        rs = self.rs
        if rs.height != height or rs.step != RoundStep.COMMIT:
            return
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, _ = precommits.two_thirds_majority()
        block, block_parts = rs.proposal_block, rs.proposal_block_parts

        block.validate_basic()
        self.block_exec.validate_block(self.state, block)

        self.logger.info(
            "finalizing commit of block",
            height=height,
            hash=block.hash().hex()[:16],
            num_txs=len(block.txs),
        )
        self.timeline.mark_commit(
            height, rs.commit_round, len(block.txs), block.hash().hex()[:16]
        )
        if block.evidence:
            self.timeline.mark_evidence_committed(
                height,
                rs.commit_round,
                len(block.evidence),
                [ev.height() for ev in block.evidence],
            )
        self.metrics.num_txs.set(len(block.txs))
        self.metrics.total_txs.inc(len(block.txs))
        self.metrics.block_size.set(block.size())
        if self.state.last_block_time_ns:
            interval_ns = max(
                0, block.header.time_ns - self.state.last_block_time_ns
            )
            self.metrics.block_interval.observe(ns_to_s(interval_ns))

        if self.block_store.height() < block.header.height:
            seen_commit = precommits.make_commit()
            self.block_store.save_block(block, block_parts, seen_commit)
        else:
            self.logger.debug(
                "calling finalizeCommit on already stored block", height=height
            )

        # EndHeight implies the blockstore has the block; crash before it →
        # ApplyBlock re-runs via handshake on restart (reference:
        # state.go:1714-1733)
        self.wal.write_end_height(height)

        state_copy = self.state.copy()
        new_state = await self.block_exec.apply_block(
            state_copy,
            BlockID(hash=block.hash(), part_set_header=block_parts.header()),
            block,
        )

        self._update_to_state(new_state)
        self._done_first_block.set()

        if self.privval is not None:
            try:
                self.privval_pub_key = await self.privval.get_pub_key()
            except Exception as e:
                self.logger.error(
                    "failed to refetch privval pubkey", err=str(e)
                )
        self._schedule_round_0()

    # ------------------------------------------------------------------
    # proposals

    def _set_proposal(self, proposal: Proposal) -> None:
        """reference: state.go:1786-1836 defaultSetProposal."""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        if proposal.pol_round < -1 or (
            0 <= proposal.pol_round >= proposal.round
        ):
            raise ValueError("invalid proposal POL round")
        proposer = rs.validators.get_proposer()
        if not proposal.verify(self.state.chain_id, proposer.pub_key):
            raise ValueError("invalid proposal signature")
        rs.proposal = proposal
        self.timeline.mark_proposal(proposal.height, proposal.round)
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet.from_header(
                proposal.block_id.part_set_header
            )
        self.logger.info(
            "received proposal",
            height=proposal.height,
            round=proposal.round,
            hash=proposal.block_id.hash.hex()[:16],
        )

    async def _add_proposal_block_part(
        self, msg: BlockPartMessage, peer_id: str
    ) -> bool:
        """reference: state.go:1838-1896. True if the part was added."""
        rs = self.rs
        if msg.height != rs.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        added = rs.proposal_block_parts.add_part(msg.part)
        if added and rs.proposal_block_parts.is_complete():
            data = rs.proposal_block_parts.assemble()
            rs.proposal_block = Block.from_proto(data)
            self.timeline.mark_block(rs.height, rs.round)
            self.logger.info(
                "received complete proposal block",
                height=rs.proposal_block.header.height,
                hash=rs.proposal_block.hash().hex()[:16],
            )
            if self.event_bus:
                self.event_bus.publish_complete_proposal(
                    E.EventDataCompleteProposal(
                        height=rs.height,
                        round=rs.round,
                        step=step_name(rs.step),
                        block_id=BlockID(
                            hash=rs.proposal_block.hash(),
                            part_set_header=rs.proposal_block_parts.header(),
                        ),
                    )
                )
        return added

    async def _handle_complete_proposal(self) -> None:
        """reference: state.go:1898-1942."""
        rs = self.rs
        if rs.proposal_block is None:
            return
        prevotes = rs.votes.prevotes(rs.round)
        if prevotes is not None:
            block_id, has_two_thirds = prevotes.two_thirds_majority()
            if (
                has_two_thirds
                and not block_id.is_zero()
                and rs.valid_round < rs.round
            ):
                if rs.proposal_block.hashes_to(block_id.hash):
                    rs.valid_round = rs.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
        if rs.step <= RoundStep.PROPOSE and self._is_proposal_complete():
            await self._enter_prevote(rs.height, rs.round)
        elif rs.step == RoundStep.COMMIT:
            await self._try_finalize_commit(rs.height)

    # ------------------------------------------------------------------
    # votes

    async def _try_add_vote(self, vote: Vote, peer_id: str) -> bool:
        """reference: state.go:2010-2056."""
        try:
            return await self._add_vote(vote, peer_id)
        except ConflictingVoteError as e:
            addr = self.privval_address()
            if addr is not None and vote.validator_address == addr:
                self.logger.error(
                    "found conflicting vote from ourselves; "
                    "did you unsafe_reset a validator?",
                    height=vote.height, round=vote.round, type=vote.type,
                )
                return False
            if self.evpool is not None and hasattr(
                self.evpool, "report_conflicting_votes"
            ):
                self.evpool.report_conflicting_votes(e.vote_a, e.vote_b)
                self.timeline.mark_evidence_seen(
                    vote.height,
                    vote.round,
                    vote.validator_address.hex(),
                )
            self.logger.debug(
                "found and sent conflicting votes to the evidence pool",
                vote_a=str(e.vote_a), vote_b=str(e.vote_b),
            )
            return False
        except ValueError as e:
            self.logger.info("failed attempting to add vote", err=str(e))
            return False

    async def _add_vote(self, vote: Vote, peer_id: str) -> bool:
        """reference: state.go:2058-2235. The span is the root of the
        commit-verification trace tree: when this vote completes a +2/3
        precommit, finalize runs inside it, so batch_accumulate /
        tpu_dispatch / merkle_hash all nest under addVote."""
        with trace.span(
            "addVote",
            height=vote.height,
            round=vote.round,
            type=vote.type,
        ):
            return await self._add_vote_impl(vote, peer_id)

    async def _add_vote_impl(self, vote: Vote, peer_id: str) -> bool:
        rs = self.rs
        height = rs.height

        # Late precommit for the previous height (during timeout_commit)
        if vote.height + 1 == height and vote.type == PRECOMMIT_TYPE:
            if rs.step != RoundStep.NEW_HEIGHT:
                return False
            if rs.last_commit is None:
                return False
            added = rs.last_commit.add_vote(vote)
            if not added:
                return False
            self._publish_vote_event(vote)
            if self.cfg.skip_timeout_commit and rs.last_commit.has_all():
                await self._enter_new_round(height, 0)
            return added

        if vote.height != height:
            return False

        added = rs.votes.add_vote(vote, peer_id)
        if not added:
            return False
        self._publish_vote_event(vote)

        if vote.type == PREVOTE_TYPE:
            await self._after_prevote_added(vote)
        elif vote.type == PRECOMMIT_TYPE:
            await self._after_precommit_added(vote)
        return added

    async def _after_prevote_added(self, vote: Vote) -> None:
        rs = self.rs
        height = rs.height
        prevotes = rs.votes.prevotes(vote.round)
        if prevotes.has_two_thirds_any():
            self.timeline.mark_prevote_any(height, vote.round)
        block_id, ok = prevotes.two_thirds_majority()
        if ok:
            if not block_id.is_zero():
                # a nil polka (+2/3 AGAINST the proposal) is not the
                # EV_POLKA crossing and must not feed the
                # proposal->polka latency sketch — mirror of the
                # precommit-quorum guard in _after_precommit_added
                self.timeline.mark_polka(height, vote.round)
            # Unlock on a newer POL for a different block
            if (
                rs.locked_block is not None
                and rs.locked_round < vote.round <= rs.round
                and not rs.locked_block.hashes_to(block_id.hash)
            ):
                self.logger.debug(
                    "unlocking because of POL", locked_round=rs.locked_round,
                    pol_round=vote.round,
                )
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
            # Update the valid block
            if (
                not block_id.is_zero()
                and rs.valid_round < vote.round == rs.round
            ):
                if rs.proposal_block is not None and rs.proposal_block.hashes_to(
                    block_id.hash
                ):
                    rs.valid_round = vote.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
                else:
                    # polka for a block we don't have: fetch it
                    rs.proposal_block = None
                if rs.proposal_block_parts is None or not (
                    rs.proposal_block_parts.has_header(
                        block_id.part_set_header
                    )
                ):
                    rs.proposal_block_parts = PartSet.from_header(
                        block_id.part_set_header
                    )
                self._publish_round_state_event("valid_block")

        if rs.round < vote.round and prevotes.has_two_thirds_any():
            await self._enter_new_round(height, vote.round)
        elif rs.round == vote.round and rs.step >= RoundStep.PREVOTE:
            block_id, ok = prevotes.two_thirds_majority()
            if ok and (self._is_proposal_complete() or block_id.is_zero()):
                await self._enter_precommit(height, vote.round)
            elif prevotes.has_two_thirds_any():
                await self._enter_prevote_wait(height, vote.round)
        elif (
            rs.proposal is not None
            and 0 <= rs.proposal.pol_round == vote.round
        ):
            if self._is_proposal_complete():
                await self._enter_prevote(height, rs.round)

    async def _after_precommit_added(self, vote: Vote) -> None:
        rs = self.rs
        height = rs.height
        precommits = rs.votes.precommits(vote.round)
        block_id, ok = precommits.two_thirds_majority()
        if ok and not block_id.is_zero():
            self.timeline.mark_precommit_quorum(height, vote.round)
        if ok:
            await self._enter_new_round(height, vote.round)
            await self._enter_precommit(height, vote.round)
            if not block_id.is_zero():
                await self._enter_commit(height, vote.round)
                if self.cfg.skip_timeout_commit and precommits.has_all():
                    await self._enter_new_round(rs.height, 0)
            else:
                await self._enter_precommit_wait(height, vote.round)
        elif rs.round <= vote.round and precommits.has_two_thirds_any():
            await self._enter_new_round(height, vote.round)
            await self._enter_precommit_wait(height, vote.round)

    async def _sign_add_vote(
        self, msg_type: int, hash_: bytes, header
    ) -> Optional[Vote]:
        """Sign our vote and feed it back through the internal queue
        (reference: state.go:2316-2372 signAddVote)."""
        rs = self.rs
        if self.privval is None or self.privval_pub_key is None:
            return None
        addr = self.privval_pub_key.address()
        if not rs.validators.has_address(addr):
            return None
        idx, _ = rs.validators.get_by_address(addr)
        vote = Vote(
            type=msg_type,
            height=rs.height,
            round=rs.round,
            block_id=BlockID(
                hash=hash_,
                part_set_header=header if header is not None else PartSetHeader(),
            ),
            timestamp_ns=self._vote_time(),
            validator_address=addr,
            validator_index=idx,
        )
        try:
            await self.privval.sign_vote(self.state.chain_id, vote)
        except Exception as e:
            if not self._replay_mode:
                self.logger.error("failed signing vote", err=str(e))
            return None
        self._send_internal(VoteMessage(vote=vote))
        self.logger.debug(
            "signed and pushed vote", height=rs.height, round=rs.round,
            type=msg_type,
        )
        return vote

    def _vote_time(self) -> int:
        """Monotonic vote time: now, but never before lastBlockTime+1ms
        (reference: state.go voteTime)."""
        # tmlint: disable=det-wallclock — protocol-required vote
        # timestamp (reference: state.go voteTime); monotonicity is
        # enforced against lastBlockTime below
        now = time.time_ns()
        min_vote_time = now
        if self.state is not None and self.state.last_block_time_ns > 0:
            min_vote_time = self.state.last_block_time_ns + 1_000_000
        return max(now, min_vote_time)

    # ------------------------------------------------------------------
    # WAL replay (crash recovery)

    async def _catchup_replay(self, height: int) -> None:
        """Replay WAL messages recorded after the last EndHeight
        (reference: internal/consensus/replay.go:96-170)."""
        # At the chain's first height there is no EndHeight(height-1)
        # record; the WAL opens with EndHeight(0)
        # (reference: replay.go:127-129).
        end_height = height - 1
        if self.state is not None and height == self.state.initial_height:
            end_height = 0
        msgs = self.wal.search_for_end_height(end_height)
        if msgs is None:
            return
        self._replay_mode = True
        try:
            for msg in msgs:
                await self.replay_one(msg)
        finally:
            self._replay_mode = False
        self.logger.info("replayed WAL messages", count=len(msgs), height=height)

    async def replay_one(self, msg) -> None:
        """Feed ONE recorded WAL input through the state machine — the
        single place replay dispatch (and its invariants) lives; used
        by crash catchup and the replay console. An EndHeight record is
        a store/WAL inconsistency (crash between the EndHeight fsync
        and the state save) and raises instead of silently merging
        heights (reference: replay.go readReplayMessage)."""
        if isinstance(msg, MsgInfo):
            await self._handle_msg(msg)
        elif isinstance(msg, TimeoutInfo):
            await self._handle_timeout(msg)
        elif isinstance(msg, EndHeightMessage):
            raise RuntimeError(
                f"unexpected EndHeight {msg.height} during replay at "
                f"height {self.rs.height}"
            )
        # EventDataRoundStateWAL markers are informational

    # ------------------------------------------------------------------
    # events

    def _new_step(self) -> None:
        step = step_name(self.rs.step)
        if not self._replay_mode:
            # round-state marker into the WAL (reference: state.go
            # newStep -> wal.Write(rs)) — the step events the
            # post-mortem reconstruction (timeline.events_from_wal)
            # rebuilds the timeline from; buffered, no fsync
            self.wal.write(
                EventDataRoundStateWAL(
                    height=self.rs.height,
                    round=self.rs.round,
                    step=step,
                )
            )
        tl = self.timeline
        if tl.enabled:
            tl.record(EV_STEP, self.rs.height, self.rs.round, step=step)
        rsw = E.EventDataRoundState(
            height=self.rs.height,
            round=self.rs.round,
            step=step,
        )
        if self.event_bus and not self._replay_mode:
            self.event_bus.publish_new_round_step(rsw)

    def _publish_round_state_event(self, kind: str) -> None:
        if self.event_bus is None or self._replay_mode:
            return
        data = E.EventDataRoundState(
            height=self.rs.height,
            round=self.rs.round,
            step=step_name(self.rs.step),
        )
        publish = {
            "timeout_propose": self.event_bus.publish_timeout_propose,
            "timeout_wait": self.event_bus.publish_timeout_wait,
            "polka": self.event_bus.publish_polka,
            "relock": self.event_bus.publish_relock,
            "lock": self.event_bus.publish_lock,
            "valid_block": self.event_bus.publish_valid_block,
        }.get(kind)
        if publish:
            publish(data)

    def _publish_vote_event(self, vote: Vote) -> None:
        if self.event_bus and not self._replay_mode:
            self.event_bus.publish_vote(E.EventDataVote(vote=vote))
