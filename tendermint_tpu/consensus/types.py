"""Consensus round state — steps, RoundState, HeightVoteSet.

reference: internal/consensus/types/round_state.go (RoundStepType :12-40,
RoundState :65-115) and internal/consensus/types/height_vote_set.go.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..types.block import Block
from ..types.block_id import BlockID
from ..types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from ..types.commit import Commit
from ..types.part_set import PartSet
from ..types.proposal import Proposal
from ..types.validator import ValidatorSet
from ..types.vote import Vote
from ..types.vote_set import ConflictingVoteError, VoteSet

__all__ = [
    "RoundStep",
    "RoundState",
    "HeightVoteSet",
    "step_name",
]


class RoundStep:
    """reference: round_state.go:12-40."""

    NEW_HEIGHT = 1
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8


_STEP_NAMES = {
    1: "RoundStepNewHeight",
    2: "RoundStepNewRound",
    3: "RoundStepPropose",
    4: "RoundStepPrevote",
    5: "RoundStepPrevoteWait",
    6: "RoundStepPrecommit",
    7: "RoundStepPrecommitWait",
    8: "RoundStepCommit",
}


def step_name(step: int) -> str:
    return _STEP_NAMES.get(step, f"RoundStepUnknown({step})")


@dataclass
class RoundState:
    """The consensus-internal state exposed to the reactor and RPC
    (reference: round_state.go:65-115)."""

    height: int = 0
    round: int = 0
    step: int = RoundStep.NEW_HEIGHT
    start_time_ns: int = 0
    commit_time_ns: int = 0
    validators: Optional[ValidatorSet] = None
    proposal: Optional[Proposal] = None
    proposal_block: Optional[Block] = None
    proposal_block_parts: Optional[PartSet] = None
    locked_round: int = -1
    locked_block: Optional[Block] = None
    locked_block_parts: Optional[PartSet] = None
    valid_round: int = -1  # last POL round, if any
    valid_block: Optional[Block] = None
    valid_block_parts: Optional[PartSet] = None
    votes: Optional["HeightVoteSet"] = None
    commit_round: int = -1
    last_commit: Optional[VoteSet] = None
    last_validators: Optional[ValidatorSet] = None
    triggered_timeout_precommit: bool = False

    def height_round_step(self) -> str:
        return f"{self.height}/{self.round}/{step_name(self.step)}"


class HeightVoteSet:
    """Prevotes and precommits for every round of one height.

    Tracks rounds 0..round+1 plus bounded peer-triggered catchup rounds
    (one per peer) so a Byzantine peer can't force unbounded memory
    (reference: height_vote_set.go:14-38 design comment).
    """

    def __init__(
        self, chain_id: str, height: int, val_set: ValidatorSet
    ) -> None:
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self.round = 0
        self._round_vote_sets: Dict[int, Tuple[VoteSet, VoteSet]] = {}
        self._peer_catchup_rounds: Dict[str, List[int]] = {}
        self._add_round(0)
        self._add_round(1)

    def _add_round(self, round_: int) -> None:
        if round_ in self._round_vote_sets:
            return
        self._round_vote_sets[round_] = (
            VoteSet(self.chain_id, self.height, round_, PREVOTE_TYPE, self.val_set),
            VoteSet(self.chain_id, self.height, round_, PRECOMMIT_TYPE, self.val_set),
        )

    def set_round(self, round_: int) -> None:
        """Track rounds up to round_+1 (reference: height_vote_set.go:77)."""
        new_round = self.round + 1  # replays of old rounds keep existing sets
        if round_ < new_round and self._round_vote_sets:
            raise ValueError("SetRound() must increment the round")
        for r in range(new_round, round_ + 2):
            self._add_round(r)
        self.round = round_

    def add_vote(self, vote: Vote, peer_id: str = "") -> bool:
        """reference: height_vote_set.go:109-135. Raises
        ConflictingVoteError on double-signs, ValueError on junk."""
        if vote.type not in (PREVOTE_TYPE, PRECOMMIT_TYPE):
            raise ValueError(f"unexpected vote type {vote.type}")
        vs = self._get(vote.round, vote.type)
        if vs is None:
            rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
            if len(rounds) < 2:
                self._add_round(vote.round)
                vs = self._get(vote.round, vote.type)
                rounds.append(vote.round)
            else:
                # Peer has sent votes for 2 unexpected rounds already
                raise ValueError(
                    "peer has sent a vote that does not match our round "
                    "for more than one round"
                )
        return vs.add_vote(vote)

    def _get(self, round_: int, type_: int) -> Optional[VoteSet]:
        pair = self._round_vote_sets.get(round_)
        if pair is None:
            return None
        return pair[0] if type_ == PREVOTE_TYPE else pair[1]

    def prevotes(self, round_: int) -> Optional[VoteSet]:
        return self._get(round_, PREVOTE_TYPE)

    def precommits(self, round_: int) -> Optional[VoteSet]:
        return self._get(round_, PRECOMMIT_TYPE)

    def pol_info(self) -> Tuple[int, Optional[BlockID]]:
        """Last round with a prevote 2/3 majority, scanning down
        (reference: height_vote_set.go:154-165)."""
        for r in range(self.round, -1, -1):
            vs = self.prevotes(r)
            if vs is not None:
                block_id, ok = vs.two_thirds_majority()
                if ok:
                    return r, block_id
        return -1, None

    def set_peer_maj23(
        self, round_: int, type_: int, peer_id: str, block_id: BlockID
    ) -> None:
        """reference: height_vote_set.go:185-198."""
        self._add_round(round_)
        vs = self._get(round_, type_)
        if vs is not None:
            vs.set_peer_maj23(peer_id, block_id)
