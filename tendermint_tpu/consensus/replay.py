"""ABCI handshake — sync the application with the stores on boot.

reference: internal/consensus/replay.go (Handshaker :240, ReplayBlocks
:283-445, replayBlocks :447-520, mock proxy app replay_stubs.go).

On restart the app may be behind the block store (crash before Commit),
or the state store may be one height behind the block store (crash
between SaveBlock and state save). The handshake queries the app's
height via Info, then replays stored blocks into it until app, store,
and state agree.
"""

from __future__ import annotations

from typing import Optional

from ..abci import types as abci
from ..abci.client import ABCIClient
from ..abci.codec import (
    _dec_resp_begin_block,
    _dec_resp_deliver_tx,
    _dec_resp_end_block,
)
from ..crypto.merkle import hash_from_byte_slices
from ..eventbus import EventBus
from ..libs.log import get_logger
from ..mempool.nop import NopMempool
from ..state.execution import (
    BlockExecutor,
    build_last_commit_info,
    validator_updates_from_abci,
)
from ..state.store import StateStore
from ..state.types import State
from ..store.block_store import BlockStore
from ..types.block import Block
from ..types.genesis import GenesisDoc
from ..types.validator import ValidatorSet

__all__ = ["Handshaker", "HandshakeError"]


class HandshakeError(Exception):
    pass


class _MockReplayClient:
    """Duck-typed ABCI client serving recorded responses for the 'ran
    Commit but crashed before saving state' case (reference:
    replay_stubs.go:57-95 newMockProxyApp)."""

    def __init__(self, app_hash: bytes, abci_responses) -> None:
        self._app_hash = app_hash
        self._deliver = [
            _dec_resp_deliver_tx(b) for b in abci_responses.deliver_txs
        ]
        self._end_block = (
            _dec_resp_end_block(abci_responses.end_block)
            if abci_responses.end_block
            else abci.ResponseEndBlock()
        )
        # Serve the recorded BeginBlock too: apply_block re-saves the
        # responses it sees, and an empty stand-in would permanently
        # replace the genuine begin_block events at this height.
        self._begin_block = (
            _dec_resp_begin_block(abci_responses.begin_block)
            if abci_responses.begin_block
            else abci.ResponseBeginBlock()
        )
        self._i = 0

    async def begin_block(self, req) -> abci.ResponseBeginBlock:
        return self._begin_block

    async def deliver_tx(self, req) -> abci.ResponseDeliverTx:
        r = self._deliver[self._i]
        self._i += 1
        return r

    async def end_block(self, req) -> abci.ResponseEndBlock:
        return self._end_block

    async def commit(self) -> abci.ResponseCommit:
        return abci.ResponseCommit(data=self._app_hash)

    async def flush(self) -> None: ...

    # unused surface
    async def echo(self, msg: str): ...
    async def info(self, req): ...
    async def init_chain(self, req): ...
    async def query(self, req): ...
    async def check_tx(self, req): ...
    async def list_snapshots(self, req): ...
    async def offer_snapshot(self, req): ...
    async def load_snapshot_chunk(self, req): ...
    async def apply_snapshot_chunk(self, req): ...


class Handshaker:
    """reference: internal/consensus/replay.go:214-281."""

    def __init__(
        self,
        state_store: StateStore,
        state: State,
        block_store: BlockStore,
        genesis: GenesisDoc,
        event_bus: Optional[EventBus] = None,
    ) -> None:
        self.state_store = state_store
        self.initial_state = state
        self.block_store = block_store
        self.genesis = genesis
        self.event_bus = event_bus
        self.logger = get_logger("consensus.handshaker")
        self.n_blocks = 0  # blocks replayed into the app

    async def handshake(self, app_client: ABCIClient) -> bytes:
        """Info → ReplayBlocks; returns the app hash both sides agree on
        (reference: replay.go:240-281)."""
        res = await app_client.info(abci.RequestInfo(version="tpu"))
        block_height = res.last_block_height
        if block_height < 0:
            raise HandshakeError(
                f"got negative last block height {block_height} from app"
            )
        app_hash = res.last_block_app_hash
        self.logger.info(
            "ABCI handshake",
            app_height=block_height,
            app_hash=app_hash.hex()[:16],
        )
        app_hash = await self.replay_blocks(
            self.initial_state, app_hash, block_height, app_client
        )
        self.logger.info(
            "completed ABCI handshake",
            app_height=block_height,
            replayed=self.n_blocks,
        )
        return app_hash

    async def replay_blocks(
        self,
        state: State,
        app_hash: bytes,
        app_block_height: int,
        app_client: ABCIClient,
    ) -> bytes:
        """The decision table over (app, store, state) heights
        (reference: replay.go:283-445)."""
        store_base = self.block_store.base()
        store_height = self.block_store.height()
        state_height = state.last_block_height
        self.logger.info(
            "ABCI replay blocks",
            app_height=app_block_height,
            store_height=store_height,
            state_height=state_height,
        )

        # Genesis: send InitChain
        if app_block_height == 0:
            res = await app_client.init_chain(self._init_chain_request())
            app_hash = res.app_hash
            if state_height == 0:
                state = self._apply_init_chain_response(state, res)
                self.state_store.save(state)
                self.initial_state = state

        if store_height == 0:
            return app_hash
        if app_block_height == 0 and state.initial_height < store_base:
            raise HandshakeError(
                f"app has no state; block store is pruned above initial "
                f"height (base {store_base})"
            )
        if 0 < app_block_height < store_base - 1:
            raise HandshakeError(
                f"app height {app_block_height} is too far below store "
                f"base {store_base}"
            )
        if store_height < app_block_height:
            raise HandshakeError(
                f"app height {app_block_height} ahead of store "
                f"{store_height}"
            )
        if store_height < state_height:
            raise RuntimeError(
                f"state height {state_height} > store height {store_height}"
            )
        if store_height > state_height + 1:
            raise RuntimeError(
                f"store height {store_height} > state height + 1 "
                f"({state_height + 1})"
            )

        if store_height == state_height:
            # Commit ran and state saved: app replay only, no state change
            if app_block_height < store_height:
                return await self._replay_blocks_into_app(
                    state, app_client, app_block_height, store_height,
                    mutate_state=False,
                )
            return app_hash  # all synced

        # store == state + 1: block saved, state not updated
        if app_block_height < state_height:
            return await self._replay_blocks_into_app(
                state, app_client, app_block_height, store_height,
                mutate_state=True,
            )
        if app_block_height == state_height:
            # Commit never ran: replay final block with the real app
            self.logger.info("replaying last block with real app")
            new_state = await self._replay_block(
                state, store_height, app_client
            )
            return new_state.app_hash
        if app_block_height == store_height:
            # Commit ran but state save didn't: mock app from saved responses
            responses = self.state_store.load_abci_responses(store_height)
            if responses is None:
                raise HandshakeError(
                    f"no saved ABCI responses for height {store_height}"
                )
            self.logger.info("replaying last block with mock app")
            mock = _MockReplayClient(app_hash, responses)
            new_state = await self._replay_block(state, store_height, mock)
            return new_state.app_hash
        raise RuntimeError(
            f"uncovered handshake case: app={app_block_height} "
            f"store={store_height} state={state_height}"
        )

    # -- helpers --

    def _init_chain_request(self) -> abci.RequestInitChain:
        updates = tuple(
            abci.ValidatorUpdate(
                pub_key=abci.PubKey(
                    key_type=gv.pub_key.type(), data=gv.pub_key.bytes()
                ),
                power=gv.power,
            )
            for gv in self.genesis.validators
        )
        return abci.RequestInitChain(
            time_ns=self.genesis.genesis_time_ns,
            chain_id=self.genesis.chain_id,
            consensus_params=None,
            validators=updates,
            app_state_bytes=self.genesis.app_state,
            initial_height=self.genesis.initial_height,
        )

    def _apply_init_chain_response(
        self, state: State, res: abci.ResponseInitChain
    ) -> State:
        """reference: replay.go:330-355."""
        state = state.copy()
        if res.app_hash:
            state.app_hash = res.app_hash
        if res.validators:
            vals = validator_updates_from_abci(res.validators)
            state.validators = ValidatorSet(vals)
            nxt = ValidatorSet(vals)
            nxt.increment_proposer_priority(1)
            state.next_validators = nxt
        elif not self.genesis.validators:
            raise HandshakeError(
                "validator set is nil in genesis and still empty after "
                "InitChain"
            )
        if res.consensus_params is not None:
            state.consensus_params = state.consensus_params.update(
                res.consensus_params
            )
            state.app_version = state.consensus_params.version.app_version
        state.last_results_hash = hash_from_byte_slices([])
        return state

    async def _replay_blocks_into_app(
        self,
        state: State,
        app_client: ABCIClient,
        app_block_height: int,
        store_height: int,
        mutate_state: bool,
    ) -> bytes:
        """Replay blocks app_height+1..store_height into the app without
        touching consensus state; if mutate_state, the final block goes
        through full ApplyBlock (reference: replay.go:447-520)."""
        app_hash = b""
        final_block = store_height - 1 if mutate_state else store_height
        first_block = app_block_height + 1
        if first_block == 1:
            first_block = state.initial_height
        for height in range(first_block, final_block + 1):
            self.logger.info("applying block against app", height=height)
            block = self.block_store.load_block(height)
            app_hash = await self._exec_commit_block(
                app_client, block, state.initial_height
            )
            self.n_blocks += 1
        if mutate_state:
            new_state = await self._replay_block(
                state, store_height, app_client
            )
            app_hash = new_state.app_hash
        return app_hash

    async def _exec_commit_block(
        self, client: ABCIClient, block: Block, initial_height: int
    ) -> bytes:
        """BeginBlock → DeliverTx×N → EndBlock → Commit without state
        bookkeeping (reference: internal/state/execution.go
        ExecCommitBlock)."""
        commit_info = self._last_commit_info(block, initial_height)
        await client.begin_block(
            abci.RequestBeginBlock(
                hash=block.hash(),
                header_bytes=block.header.to_proto(),
                last_commit_info=commit_info,
            )
        )
        for tx in block.txs:
            await client.deliver_tx(abci.RequestDeliverTx(tx=tx))
        await client.end_block(
            abci.RequestEndBlock(height=block.header.height)
        )
        res = await client.commit()
        return res.data

    def _last_commit_info(
        self, block: Block, initial_height: int
    ) -> abci.LastCommitInfo:
        vals = self.state_store.load_validators(block.header.height - 1)
        return build_last_commit_info(block, vals, initial_height)

    async def _replay_block(
        self, state: State, height: int, client: ABCIClient
    ) -> State:
        """Full ApplyBlock of the stored block at `height`
        (reference: replay.go:522-544)."""
        block = self.block_store.load_block(height)
        meta = self.block_store.load_block_meta(height)
        block_exec = BlockExecutor(
            self.state_store,
            client,
            NopMempool(),
            block_store=self.block_store,
            event_bus=self.event_bus,
        )
        new_state = await block_exec.apply_block(
            state, meta.block_id, block
        )
        self.n_blocks += 1
        return new_state
