"""Consensus metrics struct (reference: internal/consensus/metrics.go).

The go-kit pattern: one struct holding every consensus instrument,
built against a Registry and threaded through the constructor. Node
assembly passes a per-node Registry so in-process localnet nodes keep
disjoint series; constructing without one lands on DEFAULT_REGISTRY
(idempotent — repeated default constructions share instruments).
"""

from __future__ import annotations

from typing import Optional

from ..libs.metrics import DEFAULT_REGISTRY, Registry

__all__ = ["ConsensusMetrics"]


class ConsensusMetrics:
    def __init__(self, registry: Optional[Registry] = None) -> None:
        r = registry if registry is not None else DEFAULT_REGISTRY
        self.height = r.gauge(
            "consensus", "height", "Height of the chain."
        )
        self.rounds = r.gauge(
            "consensus", "rounds", "Number of rounds at the current height."
        )
        self.validators = r.gauge(
            "consensus", "validators", "Number of validators."
        )
        self.validators_power = r.gauge(
            "consensus",
            "validators_power",
            "Total voting power of validators.",
        )
        self.block_interval = r.histogram(
            "consensus",
            "block_interval_seconds",
            "Time between this and the last block.",
            buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
        )
        self.num_txs = r.gauge(
            "consensus",
            "num_txs",
            "Number of transactions in the latest block.",
        )
        self.total_txs = r.counter(
            "consensus",
            "total_txs",
            "Total number of transactions committed.",
        )
        self.block_size = r.gauge(
            "consensus", "block_size_bytes", "Size of the latest block."
        )
