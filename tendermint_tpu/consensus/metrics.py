"""Consensus metrics struct (reference: internal/consensus/metrics.go).

The go-kit pattern: one struct holding every consensus instrument,
built against a Registry and threaded through the constructor. Node
assembly passes a per-node Registry so in-process localnet nodes keep
disjoint series; constructing without one lands on DEFAULT_REGISTRY
(idempotent — repeated default constructions share instruments).
"""

from __future__ import annotations

from typing import Optional

from ..libs.metrics import DEFAULT_REGISTRY, Registry

__all__ = ["ConsensusMetrics"]


class ConsensusMetrics:
    def __init__(self, registry: Optional[Registry] = None) -> None:
        r = registry if registry is not None else DEFAULT_REGISTRY
        self.height = r.gauge(
            "consensus", "height", "Height of the chain."
        )
        self.rounds = r.gauge(
            "consensus", "rounds", "Number of rounds at the current height."
        )
        self.validators = r.gauge(
            "consensus", "validators", "Number of validators."
        )
        self.validators_power = r.gauge(
            "consensus",
            "validators_power",
            "Total voting power of validators.",
        )
        # quantile sketch rather than the reference's histogram: the
        # chaos/load planes read p99 block interval directly (ISSUE 15
        # reference-parity metrics; see docs/metrics.md "Latency
        # sketches" for the error bound)
        self.block_interval = r.sketch(
            "consensus",
            "block_interval_seconds",
            "Time between this and the last block.",
        )
        self.rounds_per_height = r.histogram(
            "consensus",
            "rounds_per_height",
            "Rounds needed to commit a height (1 = no burned round).",
            buckets=(1.0, 2.0, 3.0, 4.0, 6.0, 11.0),
        )
        self.quorum_prevote_latency = r.sketch(
            "consensus",
            "quorum_prevote_seconds",
            "Proposal received to +2/3 prevotes (polka), same round.",
        )
        self.quorum_precommit_latency = r.sketch(
            "consensus",
            "quorum_precommit_seconds",
            "+2/3 prevotes (polka) to +2/3 precommits, same round.",
        )
        self.stall_resets = r.counter(
            "consensus",
            "stall_resets_total",
            "Gossip stall-reset ticks (forget-and-resend of optimistic "
            "delivered-marks) by reset site: catchup (peer >=2 behind), "
            "live (same height), last_commit (peer one behind).",
            label_names=("kind",),
        )
        self.num_txs = r.gauge(
            "consensus",
            "num_txs",
            "Number of transactions in the latest block.",
        )
        self.total_txs = r.counter(
            "consensus",
            "total_txs",
            "Total number of transactions committed.",
        )
        self.block_size = r.gauge(
            "consensus", "block_size_bytes", "Size of the latest block."
        )
