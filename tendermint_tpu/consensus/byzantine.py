"""Byzantine adversary plane — seeded, kill-switched misbehavior.

Every fault the repo could inject before this module was crash-shaped:
crypto/faults.py wedges devices, drops links, tears writes. Tendermint's
actual adversary model is stronger — up to 1/3 of voting power can LIE —
and the evidence pipeline (vote_set conflict detection →
DuplicateVoteEvidence → pool → gossip → block inclusion) only earns its
keep against a validator that equivocates on purpose. This module makes
one designated in-process localnet validator misbehave on a seeded
schedule, behind the same armed()/env-spec/inject() contract as
crypto/faults.py, so the byzantine scenario catalog (loadgen/byz.py,
BENCH_BYZ.json) can prove safety and accountability machine-checkably.

Behaviors (the misbehavior taxonomy, docs/resilience.md):

    equivocate            after the victim signs its honest vote A, a
                          ByzantinePrivVal (no double-sign protection)
                          signs a second vote B at the same (height,
                          round, type) for a fabricated block and sends
                          it DIRECTLY to half the peer set — the
                          classic duplicate-vote attack. Honest gossip
                          spreads A everywhere, so the targeted half
                          holds conflicting votes and the vote_set
                          raises ConflictingVoteError → evidence.
    conflicting_proposal  when the victim is proposer, a second signed
                          Proposal for a fabricated BlockID follows the
                          honest one to half the peers (honest nodes
                          lock the first proposal they accept; the
                          round degrades, safety holds).
    amnesia               at round > 0 the victim forgets its lock
                          (clears locked_block/locked_round) before
                          prevoting — the lock-violation replay of the
                          amnesia attack. Different rounds → no
                          duplicate-vote evidence; the verdict is
                          safety-only.
    withhold              the victim signs nothing in the window —
                          liveness pressure, never evidence.

A lying light-client primary is a SCENARIO, not a consensus hook: the
loadgen/byz.py lightclient_fork control scenario forges a ≥1/3
coalition block at the provider layer (light/provider.py) instead.

Rules use the crypto/faults.py grammar, armed via TM_TPU_BYZ:

    TM_TPU_BYZ="equivocate:h=4..7:seed=7:victim=load1"
    TM_TPU_BYZ="withhold:h=5..6;equivocate:h=8..9:step=precommit"

`behavior[:h=LO..HI][:p=..][:seed=..][:times=..][:victim=..][:step=..]`
— semicolons separate rules, `victim` names the misbehaving node's
moniker (default load1: in a 4-node localnet that is f=1 < n/3),
`step` restricts equivocation/withholding to prevote or precommit.
Every rule owns a `random.Random(seed)` advanced once per matching
consult, so the misbehavior schedule is a pure function of
(seed, consult index) — byzantine campaigns reproduce exactly.

Kill switch: node assembly consults `armed()` ONCE and only installs
hooks on a node whose moniker matches a rule's victim. A disarmed
process (TM_TPU_BYZ unset) never wraps a method and never consults a
rule — `consults()` stays 0, which tests/test_byz_plane.py pins as the
zero-overhead contract. The victim's PRODUCTION signer (privval/file.py
FilePV) keeps its double-sign protection throughout: only the harness's
ByzantinePrivVal — a deliberately unprotected MockPV — produces the
conflicting signatures.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
from typing import List, Optional

from ..libs.log import get_logger
from ..p2p.types import Envelope
from ..privval.types import MockPV
from ..types.block_id import BlockID, PartSetHeader
from ..types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from ..types.proposal import Proposal
from ..types.vote import Vote
from .msgs import ProposalMessage, VoteMessage

__all__ = [
    "BEHAVIORS",
    "ByzRule",
    "ByzantineHarness",
    "ByzantinePrivVal",
    "armed",
    "consults",
    "harnesses",
    "inject",
    "load_env",
    "maybe_install",
    "reset",
    "rules",
]

logger = get_logger("byzantine")

BEHAVIORS = frozenset(
    {"equivocate", "conflicting_proposal", "amnesia", "withhold"}
)

# fabricated BlockID the evil votes/proposals point at — can never
# collide with a real block hash (blocks hash through SHA-256 merkle)
EVIL_BLOCK_ID = BlockID(
    hash=b"\xde" * 32,
    part_set_header=PartSetHeader(total=1, hash=b"\xad" * 32),
)

_STEPS = {"prevote": PREVOTE_TYPE, "precommit": PRECOMMIT_TYPE}


class ByzRule:
    """One armed misbehavior: a behavior, a height window, a victim
    moniker, and a seeded RNG that decides — reproducibly — which
    consults fire."""

    def __init__(
        self,
        behavior: str,
        h_lo: int = 1,
        h_hi: Optional[int] = None,
        p: float = 1.0,
        seed: int = 0,
        times: Optional[int] = None,
        victim: str = "load1",
        step: Optional[str] = None,
    ) -> None:
        if behavior not in BEHAVIORS:
            raise ValueError(f"unknown byzantine behavior {behavior!r}")
        if step is not None and step not in _STEPS:
            raise ValueError(f"unknown byzantine step {step!r}")
        self.behavior = behavior
        self.h_lo = int(h_lo)
        self.h_hi = int(h_hi) if h_hi is not None else None
        self.p = float(p)
        self.seed = int(seed)
        self.times = times  # None = unlimited
        self.victim = victim
        self.step = step  # prevote/precommit filter (None = both)
        self.rng = random.Random(self.seed)
        self.fired = 0  # consults that actually misbehaved

    def matches(
        self, behavior: str, height: int, vote_type: Optional[int] = None
    ) -> bool:
        if self.behavior != behavior:
            return False
        if height < self.h_lo:
            return False
        if self.h_hi is not None and height > self.h_hi:
            return False
        if (
            self.step is not None
            and vote_type is not None
            and _STEPS[self.step] != vote_type
        ):
            return False
        return True

    def _roll(self) -> bool:
        """One seeded decision. The RNG advances on every matching
        consult — fired or not — so the misbehavior pattern depends
        only on (seed, consult index), never on wall time (same
        contract as crypto/faults.py Rule._roll)."""
        if self.times is not None and self.fired >= self.times:
            return False
        if self.p < 1.0 and self.rng.random() >= self.p:
            return False
        self.fired += 1
        return True

    def __repr__(self) -> str:  # failure messages name the seed
        hi = "inf" if self.h_hi is None else self.h_hi
        return (
            f"ByzRule({self.behavior}:h={self.h_lo}..{hi} p={self.p} "
            f"seed={self.seed} victim={self.victim} fired={self.fired})"
        )


_RULES: List[ByzRule] = []
_LOCK = threading.Lock()
_ARMED = False  # mirrors bool(_RULES); read lock-free at assembly
_ENV_LOADED = False
_CONSULTS = 0  # every rule-list consult; 0 while disarmed (pinned)
# installed harnesses, for scenario runners to read fired logs.
# tmlive: bounded= one per victim node per localnet (maybe_install
# appends at node assembly only), cleared wholesale by reset()
_HARNESSES: List["ByzantineHarness"] = []


def armed() -> bool:
    """Cheap assembly-time gate: False means no rule is armed and no
    byzantine code is installed at all. The env var is parsed on the
    first call so test processes that set TM_TPU_BYZ after import
    still arm (same latch ordering as crypto/faults.py armed())."""
    if not _ENV_LOADED:
        load_env()
    return _ARMED


def load_env() -> None:
    """(Re-)parse TM_TPU_BYZ into armed rules. Idempotent per value:
    clears previously env-loaded rules first (inject() rules survive).
    A malformed spec raises ONCE — the latch and _ARMED refresh run in
    the finally so the plane then stays disarmed instead of re-raising
    from every armed() check."""
    global _ENV_LOADED
    spec = os.environ.get("TM_TPU_BYZ", "")
    with _LOCK:
        _RULES[:] = [r for r in _RULES if not getattr(r, "_from_env", False)]
        try:
            parsed = []
            for part in spec.split(";"):
                part = part.strip()
                if not part:
                    continue
                rule = _parse_rule(part)
                rule._from_env = True
                parsed.append(rule)
            # all-or-nothing: a spec that fails mid-list arms none
            _RULES.extend(parsed)
        finally:
            _refresh_armed()
            _ENV_LOADED = True


def _parse_rule(spec: str) -> ByzRule:
    """`behavior[:h=LO..HI][:p=..][:seed=..][:times=..][:victim=..]
    [:step=..]` — `h=N` pins a single height."""
    fields = spec.split(":")
    kwargs = {}
    for opt in fields[1:]:
        if "=" not in opt:
            raise ValueError(f"bad TM_TPU_BYZ option {opt!r} in {spec!r}")
        k, v = opt.split("=", 1)
        if k == "h":
            lo, _, hi = v.partition("..")
            kwargs["h_lo"] = int(lo)
            kwargs["h_hi"] = int(hi) if hi else int(lo)
        elif k == "p":
            kwargs["p"] = float(v)
        elif k == "seed":
            kwargs["seed"] = int(v)
        elif k == "times":
            kwargs["times"] = int(v)
        elif k == "victim":
            kwargs["victim"] = v
        elif k == "step":
            kwargs["step"] = v
        else:
            raise ValueError(f"unknown byzantine option {k!r} in {spec!r}")
    return ByzRule(fields[0], **kwargs)


def _refresh_armed() -> None:
    global _ARMED
    _ARMED = bool(_RULES)


@contextlib.contextmanager
def inject(
    behavior: str,
    h_lo: int = 1,
    h_hi: Optional[int] = None,
    p: float = 1.0,
    seed: int = 0,
    times: Optional[int] = None,
    victim: str = "load1",
    step: Optional[str] = None,
):
    """Arm one rule for the duration of the scope (byzantine tests).
    Yields the ByzRule so the test can assert how often it fired. Note
    hooks are installed at NODE ASSEMBLY — arm before start_localnet."""
    rule = ByzRule(behavior, h_lo=h_lo, h_hi=h_hi, p=p, seed=seed,
                   times=times, victim=victim, step=step)
    with _LOCK:
        _RULES.append(rule)
        _refresh_armed()
    try:
        yield rule
    finally:
        with _LOCK:
            try:
                _RULES.remove(rule)
            except ValueError:  # pragma: no cover - double-removal
                pass
            _refresh_armed()


def reset() -> None:
    """Disarm everything — rules, harness registry, consult counter
    (tests). Installed hooks on still-running nodes become inert (their
    consults find no rules) but are not unwrapped; stop the localnet."""
    global _CONSULTS
    with _LOCK:
        _RULES.clear()
        _HARNESSES.clear()
        _CONSULTS = 0
        _refresh_armed()


def rules() -> List[ByzRule]:
    """Snapshot of the armed rules (diagnostics/tests)."""
    with _LOCK:
        return list(_RULES)


def consults() -> int:
    """How many times an installed hook consulted the rule list. The
    zero-overhead contract: a disarmed process never installs a hook,
    so this stays 0 (pinned by tests/test_byz_plane.py)."""
    with _LOCK:
        return _CONSULTS


def harnesses() -> List["ByzantineHarness"]:
    """Snapshot of installed harnesses (scenario runners read the
    per-victim fired logs for accountability verdicts)."""
    with _LOCK:
        return list(_HARNESSES)


def _plan(
    behavior: str,
    height: int,
    victim: str,
    vote_type: Optional[int] = None,
) -> Optional[ByzRule]:
    """Consult the rule list at a misbehavior point. Returns the fired
    rule (first match wins) or None. Only installed hooks call this,
    so the disarmed consult count is exactly 0."""
    global _CONSULTS
    with _LOCK:
        _CONSULTS += 1
        for r in _RULES:
            if r.victim != victim:
                continue
            if not r.matches(behavior, height, vote_type):
                continue
            if not r._roll():
                continue
            return r
    return None


class ByzantinePrivVal(MockPV):
    """The adversary's signer: a MockPV over the victim's REAL key,
    counting signatures. Deliberately no last-sign-state — producing a
    conflicting signature at an already-signed HRS is its entire job.
    The victim's FilePV is untouched; this signer only ever signs the
    harness's fabricated votes/proposals."""

    def __init__(self, priv_key) -> None:
        super().__init__(priv_key)
        self.signed_votes = 0
        self.signed_proposals = 0

    async def sign_vote(self, chain_id: str, vote: Vote) -> None:
        self.signed_votes += 1
        await super().sign_vote(chain_id, vote)

    async def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        self.signed_proposals += 1
        await super().sign_proposal(chain_id, proposal)


class ByzantineHarness:
    """Installed hooks on ONE victim node: wraps the consensus state's
    overridable seams (decide_proposal/do_prevote, the state.go
    function-field pattern) plus _sign_add_vote, and sends the evil
    duplicates through the victim's own reactor channels to a
    deterministic half of the peer set."""

    def __init__(self, cs, reactor, moniker: str) -> None:
        self.cs = cs
        self.reactor = reactor
        self.moniker = moniker
        self.signer: Optional[ByzantinePrivVal] = None
        # (behavior, height, round, vote_type) per misbehavior, read by
        # loadgen/byz.py for the accountability verdict.
        # tmlive: bounded= by the rules' height windows / times caps
        # (a rule stops firing outside its window), localnet-lifetime
        self.fired: List[tuple] = []
        self._orig_sign_add_vote = None
        self._orig_do_prevote = None
        self._orig_decide_proposal = None

    # -- install ---------------------------------------------------------

    def install(self) -> None:
        key = getattr(self.cs.privval, "key", None)
        priv_key = (
            key.priv_key if key is not None
            else getattr(self.cs.privval, "priv_key", None)
        )
        if priv_key is None:  # pragma: no cover - no signer to steal
            logger.error("byzantine install: victim has no priv key",
                         victim=self.moniker)
            return
        self.signer = ByzantinePrivVal(priv_key)
        self._orig_sign_add_vote = self.cs._sign_add_vote
        self._orig_do_prevote = self.cs.do_prevote
        self._orig_decide_proposal = self.cs.decide_proposal
        self.cs._sign_add_vote = self._byz_sign_add_vote
        self.cs.do_prevote = self._byz_do_prevote
        self.cs.decide_proposal = self._byz_decide_proposal
        logger.info("byzantine harness installed", victim=self.moniker,
                    rules=[repr(r) for r in rules()])

    # -- targeted sends --------------------------------------------------

    def _target_peers(self) -> List[str]:
        """The lexicographically-first half of the connected peers —
        the disjoint subset that receives the conflicting message while
        honest gossip carries the real one everywhere."""
        peers = sorted(self.reactor.peers)
        return peers[: max(1, len(peers) // 2)] if peers else []

    # -- hooks -----------------------------------------------------------

    async def _byz_sign_add_vote(self, msg_type, hash_, header):
        """equivocate + withhold seam: runs instead of the victim's
        _sign_add_vote for BOTH prevotes and precommits."""
        cs = self.cs
        height = cs.rs.height
        if _plan("withhold", height, self.moniker, msg_type) is not None:
            self.fired.append(("withhold", height, cs.rs.round, msg_type))
            logger.info("byzantine: withholding vote", height=height,
                        round=cs.rs.round, type=msg_type)
            return None
        vote = await self._orig_sign_add_vote(msg_type, hash_, header)
        if vote is None:
            return None
        rule = _plan("equivocate", height, self.moniker, msg_type)
        if rule is not None:
            await self._send_equivocation(vote, rule)
        return vote

    async def _send_equivocation(self, vote: Vote, rule: ByzRule) -> None:
        evil = Vote(
            type=vote.type,
            height=vote.height,
            round=vote.round,
            block_id=EVIL_BLOCK_ID,
            timestamp_ns=vote.timestamp_ns,
            validator_address=vote.validator_address,
            validator_index=vote.validator_index,
        )
        await self.signer.sign_vote(self.cs.state.chain_id, evil)
        targets = self._target_peers()
        for pid in targets:
            self.reactor.vote_ch.try_send(
                Envelope(message=VoteMessage(vote=evil), to=pid)
            )
        self.fired.append(
            ("equivocate", vote.height, vote.round, vote.type)
        )
        logger.info(
            "byzantine: equivocated", height=vote.height, round=vote.round,
            type=vote.type, seed=rule.seed, targets=len(targets),
        )

    async def _byz_do_prevote(self, height, round_):
        """amnesia seam: forget the lock before prevoting."""
        cs = self.cs
        if (
            round_ > 0
            and cs.rs.locked_block is not None
            and _plan("amnesia", height, self.moniker) is not None
        ):
            self.fired.append(("amnesia", height, round_, PREVOTE_TYPE))
            logger.info("byzantine: amnesia — dropping lock",
                        height=height, round=round_,
                        locked_round=cs.rs.locked_round)
            cs.rs.locked_block = None
            cs.rs.locked_block_parts = None
            cs.rs.locked_round = -1
        await self._orig_do_prevote(height, round_)

    async def _byz_decide_proposal(self, height, round_):
        """conflicting_proposal seam: a second signed proposal chases
        the honest one to half the peers."""
        await self._orig_decide_proposal(height, round_)
        rule = _plan("conflicting_proposal", height, self.moniker)
        if rule is None:
            return
        cs = self.cs
        evil = Proposal(
            height=height,
            round=round_,
            pol_round=cs.rs.valid_round,
            block_id=EVIL_BLOCK_ID,
        )
        await self.signer.sign_proposal(cs.state.chain_id, evil)
        targets = self._target_peers()
        for pid in targets:
            self.reactor.data_ch.try_send(
                Envelope(message=ProposalMessage(proposal=evil), to=pid)
            )
        self.fired.append(("conflicting_proposal", height, round_, None))
        logger.info("byzantine: conflicting proposal sent", height=height,
                    round=round_, targets=len(targets))


def maybe_install(cs, reactor, moniker: str) -> Optional[ByzantineHarness]:
    """Install misbehavior hooks when a rule names this node as victim.
    Called once from node assembly, AFTER the consensus reactor exists;
    a disarmed process (armed() False) never reaches this. Returns the
    harness, or None when this node is honest."""
    with _LOCK:
        mine = [r for r in _RULES if r.victim == moniker]
    if not mine:
        return None
    harness = ByzantineHarness(cs, reactor, moniker)
    harness.install()
    with _LOCK:
        _HARNESSES.append(harness)
    return harness
