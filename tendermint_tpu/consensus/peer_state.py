"""PeerState — what we know about a peer's round state.

reference: internal/consensus/peer_state.go. The gossip routines consult
this to decide which proposal parts and votes the peer still needs; the
reactor updates it from NewRoundStep/NewValidBlock/HasVote/ProposalPOL
messages and from everything we send the peer.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional, Tuple

from ..libs import rng
from ..libs.bits import BitArray
from ..types.block_id import PartSetHeader
from ..types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from ..types.vote import Vote
from .types import RoundState, RoundStep

__all__ = ["PeerRoundState", "PeerState"]


@dataclass
class PeerRoundState:
    """reference: internal/consensus/types/peer_round_state.go."""

    height: int = 0
    round: int = -1
    step: int = 0
    start_time_ns: int = 0
    proposal: bool = False
    proposal_block_parts_header: PartSetHeader = field(
        default_factory=PartSetHeader
    )
    proposal_block_parts: Optional[BitArray] = None
    proposal_pol_round: int = -1
    proposal_pol: Optional[BitArray] = None
    prevotes: Optional[BitArray] = None
    precommits: Optional[BitArray] = None
    last_commit_round: int = -1
    last_commit: Optional[BitArray] = None
    catchup_commit_round: int = -1
    catchup_commit: Optional[BitArray] = None


class PeerState:
    def __init__(self, peer_id: str) -> None:
        self.peer_id = peer_id
        self.prs = PeerRoundState()

    # -- applying peer messages (reference: peer_state.go:340-470) --

    def apply_new_round_step(self, msg) -> None:
        prs = self.prs
        if (
            msg.height < prs.height
            or (msg.height == prs.height and msg.round < prs.round)
        ):
            return
        psh, pparts = prs.proposal_block_parts_header, prs.proposal_block_parts
        start_time = time.time_ns() - msg.seconds_since_start_time * 1_000_000_000
        old_height, old_round = prs.height, prs.round
        prs.height = msg.height
        prs.round = msg.round
        prs.step = msg.step
        prs.start_time_ns = start_time
        if old_height != msg.height or old_round != msg.round:
            prs.proposal = False
            prs.proposal_block_parts_header = PartSetHeader()
            prs.proposal_block_parts = None
            prs.proposal_pol_round = -1
            prs.proposal_pol = None
            prs.prevotes = None
            prs.precommits = None
        if old_height == msg.height and old_round != msg.round and (
            msg.round == prs.catchup_commit_round
        ):
            prs.precommits = prs.catchup_commit
        if old_height != msg.height:
            if old_height == msg.height - 1:
                prs.last_commit = prs.precommits
                prs.last_commit_round = old_round
            else:
                prs.last_commit = None
                prs.last_commit_round = msg.last_commit_round
            prs.catchup_commit_round = -1
            prs.catchup_commit = None

    def apply_new_valid_block(self, msg) -> None:
        prs = self.prs
        if prs.height != msg.height:
            return
        if prs.round != msg.round and not msg.is_commit:
            return
        prs.proposal_block_parts_header = msg.block_part_set_header
        prs.proposal_block_parts = msg.block_parts

    def apply_proposal_pol(self, msg) -> None:
        prs = self.prs
        if prs.height != msg.height:
            return
        if prs.proposal_pol_round != msg.proposal_pol_round:
            return
        prs.proposal_pol = msg.proposal_pol

    def apply_has_vote(self, msg) -> None:
        if self.prs.height != msg.height:
            return
        self.set_has_vote(msg.height, msg.round, msg.type, msg.index)

    def apply_vote_set_bits(self, msg, our_votes: Optional[BitArray]) -> None:
        """reference: peer_state.go ApplyVoteSetBitsMessage. The bits we
        know the peer has = (what we tracked minus what we asked about)
        OR the peer's reply."""
        votes = self._get_vote_bits(msg.height, msg.round, msg.type)
        if votes is None or msg.votes is None:
            return
        if our_votes is None:
            votes.update(msg.votes)
        else:
            other_votes = votes.sub(our_votes)
            has_votes = other_votes.or_(msg.votes)
            votes.update(has_votes)

    # -- tracking what we've sent (reference: peer_state.go:150-330) --

    def set_has_proposal(self, proposal) -> None:
        prs = self.prs
        if prs.height != proposal.height or prs.round != proposal.round:
            return
        if prs.proposal:
            return
        prs.proposal = True
        if prs.proposal_block_parts is not None:
            return  # already set by NewValidBlock
        prs.proposal_block_parts_header = proposal.block_id.part_set_header
        prs.proposal_block_parts = BitArray(
            max(1, proposal.block_id.part_set_header.total)
        )
        prs.proposal_pol_round = proposal.pol_round
        prs.proposal_pol = None

    def set_has_proposal_block_part(
        self, height: int, round_: int, index: int
    ) -> None:
        prs = self.prs
        if prs.height != height or prs.round != round_:
            return
        if prs.proposal_block_parts is None:
            return
        if 0 <= index < prs.proposal_block_parts.size:
            prs.proposal_block_parts.set(index, True)

    def set_has_vote(
        self, height: int, round_: int, vote_type: int, index: int
    ) -> None:
        votes = self._get_vote_bits(height, round_, vote_type)
        if votes is not None and 0 <= index < votes.size:
            votes.set(index, True)

    def ensure_vote_bits(self, num_validators: int) -> None:
        """Allocate vote bit arrays once the validator count is known
        (reference: peer_state.go EnsureVoteBitArrays)."""
        prs = self.prs
        if prs.prevotes is None:
            prs.prevotes = BitArray(num_validators)
        if prs.precommits is None:
            prs.precommits = BitArray(num_validators)
        if prs.proposal_pol is None and prs.proposal_pol_round >= 0:
            prs.proposal_pol = BitArray(num_validators)
        if prs.last_commit is None and prs.last_commit_round >= 0:
            prs.last_commit = BitArray(num_validators)
        if prs.catchup_commit is None and prs.catchup_commit_round >= 0:
            prs.catchup_commit = BitArray(num_validators)

    def ensure_catchup_commit_round(
        self, height: int, round_: int, num_validators: int
    ) -> None:
        prs = self.prs
        if prs.height != height:
            return
        if prs.catchup_commit_round == round_:
            return
        prs.catchup_commit_round = round_
        prs.catchup_commit = BitArray(num_validators)

    def reset_catchup_precommits(
        self, height: int, round_: int, num_validators: int
    ) -> None:
        """Forget our delivered-marks for the stored-commit precommits
        of (height, round_) so catchup gossip resends them. The marks
        are optimistic — a vote sent while the peer's reactor was
        still in wait_sync (block-syncing) was dropped unseen — and a
        fully-marked array with a peer that never advances means the
        marks lied; dup votes are idempotent on the receiver
        (HeightVoteSet dedups by validator index)."""
        prs = self.prs
        if prs.height != height:
            return
        if prs.round == round_:
            prs.precommits = BitArray(num_validators)
        elif prs.catchup_commit_round == round_:
            prs.catchup_commit = BitArray(num_validators)

    def reset_live_votes(self) -> None:
        """Forget our delivered-marks for the CURRENT height's prevotes
        and precommits (and the POL bits) so live-height gossip resends
        them. Same rationale as reset_catchup_precommits one branch up:
        set_has_vote marks are optimistic — on a lossy or partitioned
        link the connection survives while the frame doesn't, and a
        fully-marked bit array with a peer that never advances means
        the marks lied. Dup votes are idempotent on the receiver
        (HeightVoteSet dedups by validator index)."""
        prs = self.prs
        if prs.prevotes is not None:
            prs.prevotes = BitArray(prs.prevotes.size)
        if prs.precommits is not None:
            prs.precommits = BitArray(prs.precommits.size)
        if prs.proposal_pol is not None:
            prs.proposal_pol = BitArray(prs.proposal_pol.size)

    def _get_vote_bits(
        self, height: int, round_: int, vote_type: int
    ) -> Optional[BitArray]:
        prs = self.prs
        if prs.height == height:
            if prs.round == round_:
                return (
                    prs.prevotes
                    if vote_type == PREVOTE_TYPE
                    else prs.precommits
                )
            if prs.catchup_commit_round == round_ and vote_type == PRECOMMIT_TYPE:
                return prs.catchup_commit
            if prs.proposal_pol_round == round_ and vote_type == PREVOTE_TYPE:
                return prs.proposal_pol
            return None
        if prs.height == height + 1:
            if prs.last_commit_round == round_ and vote_type == PRECOMMIT_TYPE:
                return prs.last_commit
            return None
        return None

    # -- vote selection for gossip (reference: peer_state.go:196-260) --

    def pick_vote_to_send(self, votes) -> Optional[Vote]:
        """Given a VoteSet-like (with bit_array()/get_by_index()), pick a
        random vote the peer doesn't have."""
        if votes is None or votes.size() == 0:
            return None
        height = votes.height
        round_ = votes.round
        vote_type = votes.signed_msg_type
        if self.prs.height == height:
            self.ensure_vote_bits(votes.size())
        peer_bits = self._get_vote_bits(height, round_, vote_type)
        if peer_bits is None:
            return None
        ours = votes.bit_array()
        missing = ours.sub(peer_bits)
        candidates = list(missing.indices())
        if not candidates:
            return None
        index = rng.choice(candidates)
        return votes.get_by_index(index)
