"""Consensus flight recorder — the per-node height/round timeline.

The observability planes built so far see *requests* (rpc/metrics.py
per-route SLO sketches + libs/trace.py exemplars) and *processes*
(libs/trace.py spans + per-node metric registries), but nothing sees
*consensus*: a chaos verdict carries a bare TTFC number, and the two
gossip-wedge diagnoses (PRs 9/13) each took manual log archaeology.
This module records the causal story of every height as structured
events:

    new_height -> new_round -> step transitions (Propose/Prevote/...)
    proposal received -> complete block -> +2/3 prevote (any) ->
    polka (+2/3 for one block) -> +2/3 precommit -> commit

plus timeout fires and — critically — the gossip stall-reset ticks
(`vote_catchup_stall` / `_vote_stall_tick`, reactor.py) that used to
fire invisibly: a wedge-save is now distinguishable from a quiet net.

Design follows libs/trace.py: a bounded ring (old events evicted,
never blocked on), kill-switched (`[instrumentation]
consensus_timeline`), with a consensus-grade-cheap disabled path —
call sites in consensus/state.py guard on the plain `enabled`
attribute, so a disabled recorder adds one attribute read to a step
transition (bench.py `timeline_overhead` pins it). Unlike the trace
ring the recorder is PER NODE (constructed in node assembly beside the
metric Registry), so in-process localnet nodes keep disjoint
timelines — the fleet merger (loadgen/timeline.py) depends on it.

Events carry BOTH clocks: `t_mono_ns` (time.monotonic_ns — durations
within one node) and `t_wall_ns` (time.time_ns — cross-node alignment
on one box, and alignment with WAL record timestamps). The recorder
also feeds the reference-parity consensus metrics from the same
crossing events: the proposal->polka and polka->+2/3-precommit
latency sketches, the rounds-per-height histogram, and the
stall-reset counters (consensus/metrics.py) observe whether or not
the ring itself is enabled — the kill switch silences the *ring*, not
the metrics plane.

Post-mortem twin: `events_from_wal()` reconstructs the same event
stream from a consensus WAL — every input the node saw (proposals,
parts, votes, timeouts) plus the round-step markers `_new_step`
writes — so a wedged or dead node explains itself with zero live
state (scripts/timeline_replay.py is the CLI).
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "DEFAULT_CAPACITY",
    "EV_BLOCK",
    "EV_COMMIT",
    "EV_EVIDENCE_COMMITTED",
    "EV_EVIDENCE_SEEN",
    "EV_NEW_HEIGHT",
    "EV_NEW_ROUND",
    "EV_POLKA",
    "EV_PRECOMMIT_QUORUM",
    "EV_PREVOTE_ANY",
    "EV_PROPOSAL",
    "EV_STALL_RESET",
    "EV_STEP",
    "EV_TIMEOUT",
    "TimelineEvent",
    "TimelineRecorder",
    "events_from_wal",
    "summarize_heights",
]

DEFAULT_CAPACITY = 4096

# Event kinds — one shared vocabulary for the live recorder, the WAL
# reconstruction, and the fleet merger. Keep in sync with
# docs/observability.md's event table.
EV_STEP = "step"  # round-step transition (step attr = RoundStep name)
EV_NEW_HEIGHT = "new_height"  # entered a new height
EV_NEW_ROUND = "new_round"  # entered round > 0 (rounds burned)
EV_PROPOSAL = "proposal"  # signature-verified proposal accepted
EV_BLOCK = "block"  # complete proposal block assembled
EV_PREVOTE_ANY = "prevote_any"  # +2/3 prevotes for any block (mixed)
EV_POLKA = "polka"  # +2/3 prevotes for ONE block
EV_PRECOMMIT_QUORUM = "precommit_quorum"  # +2/3 precommits for a block
EV_TIMEOUT = "timeout"  # a scheduled timeout actually fired
EV_STALL_RESET = "stall_reset"  # gossip forget-and-resend tick
EV_COMMIT = "commit"  # block finalized into the store
EV_EVIDENCE_SEEN = "evidence_seen"  # conflicting votes detected here
EV_EVIDENCE_COMMITTED = "evidence_committed"  # block carried evidence


class TimelineEvent:
    """One recorded consensus event. Plain slots object — the ring
    holds tens of thousands of these under chaos load."""

    __slots__ = (
        "seq",
        "kind",
        "height",
        "round",
        "step",
        "t_mono_ns",
        "t_wall_ns",
        "attrs",
    )

    def __init__(
        self,
        seq: int,
        kind: str,
        height: int,
        round_: int,
        step: str,
        t_mono_ns: int,
        t_wall_ns: int,
        attrs: Optional[Dict[str, Any]],
    ) -> None:
        self.seq = seq
        self.kind = kind
        self.height = height
        self.round = round_
        self.step = step
        self.t_mono_ns = t_mono_ns
        self.t_wall_ns = t_wall_ns
        self.attrs = attrs

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "seq": self.seq,
            "kind": self.kind,
            "height": self.height,
            "round": self.round,
            "t_mono_ns": self.t_mono_ns,
            "t_wall_ns": self.t_wall_ns,
        }
        if self.step:
            d["step"] = self.step
        if self.attrs:
            d.update(self.attrs)
        return d


class TimelineRecorder:
    """Bounded, kill-switched per-node ring of consensus events.

    Hot-path contract (mirrors libs/trace.py's): consensus/state.py's
    step-transition sites guard on the plain `enabled` attribute and
    skip the call entirely when off, so the disabled recorder costs
    one attribute read (pinned by the counting-stub test and the
    `timeline_overhead` bench row). The `mark_*` crossing helpers are
    ALWAYS called — they feed the consensus metrics sketches/counters
    — and append to the ring only when enabled.

    Single-writer by construction: every producer (consensus receive
    loop, reactor gossip tasks, RPC readers) lives on the node's
    asyncio loop, so ring appends never race and no lock is needed.
    """

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        enabled: bool = True,
        metrics=None,
    ) -> None:
        if capacity < 1:
            raise ValueError(
                f"timeline capacity must be >= 1: {capacity}"
            )
        self.enabled = enabled
        self.capacity = capacity
        self.metrics = metrics  # ConsensusMetrics or None
        # tmlive: bounded= ring (deque maxlen=capacity)
        self._ring: deque = deque(maxlen=capacity)
        self._next_seq = 1
        # crossing dedup + latency anchors for the CURRENT height only
        # — both cleared on every mark_new_height, so they are bounded
        # by the events of one height
        self._once: set = set()
        self._anchors: Dict[str, Tuple[int, int]] = {}

    # -- lifecycle -----------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        """Kill switch: subsequent events are not recorded (metric
        feeds from mark_* keep observing — the switch silences the
        ring, not the metrics plane)."""
        self.enabled = False

    def reset(self) -> None:
        """Drop every recorded event (tests; debug-dump isolation)."""
        self._ring.clear()
        self._once.clear()
        self._anchors.clear()

    def __len__(self) -> int:
        return len(self._ring)

    # -- recording -----------------------------------------------------

    def record(
        self,
        kind: str,
        height: int,
        round_: int,
        step: str = "",
        **attrs: Any,
    ) -> None:
        """Append one event (no-op when disabled). Hot call sites
        check `enabled` themselves first to skip argument building."""
        if not self.enabled:
            return
        seq = self._next_seq
        self._next_seq = seq + 1
        self._ring.append(
            TimelineEvent(
                seq,
                kind,
                height,
                round_,
                step,
                time.monotonic_ns(),
                time.time_ns(),
                attrs or None,
            )
        )

    def _record_once(
        self,
        kind: str,
        height: int,
        round_: int,
        **attrs: Any,
    ) -> bool:
        """Record a threshold crossing exactly once per (kind, height,
        round) — detection sites (e.g. _after_prevote_added) re-fire on
        every later vote. Returns True on the FIRST crossing whether or
        not the ring is enabled, so metric anchors stay exact under the
        kill switch."""
        key = (kind, height, round_)
        if key in self._once:
            return False
        self._once.add(key)
        if self.enabled:
            self.record(kind, height, round_, **attrs)
        return True

    # -- crossing marks (always called; they feed the metrics) ---------

    def mark_new_height(self, height: int, round_: int = 0) -> None:
        """Entering a height: clears the per-height dedup/anchor state
        (bounded growth: both sets live one height)."""
        self._once.clear()
        self._anchors.clear()
        self._anchor("new_height")
        if self.enabled:
            self.record(EV_NEW_HEIGHT, height, round_)

    def mark_proposal(self, height: int, round_: int) -> None:
        if self._record_once(EV_PROPOSAL, height, round_):
            self._anchor("proposal", round_)

    def mark_block(self, height: int, round_: int) -> None:
        self._record_once(EV_BLOCK, height, round_)

    def mark_prevote_any(self, height: int, round_: int) -> None:
        self._record_once(EV_PREVOTE_ANY, height, round_)

    def mark_polka(self, height: int, round_: int) -> None:
        if self._record_once(EV_POLKA, height, round_):
            lat = self._anchor_lat("proposal", round_)
            self._anchor("polka", round_)
            if lat is not None and self.metrics is not None:
                self.metrics.quorum_prevote_latency.observe(lat)

    def mark_precommit_quorum(self, height: int, round_: int) -> None:
        if self._record_once(EV_PRECOMMIT_QUORUM, height, round_):
            lat = self._anchor_lat("polka", round_)
            self._anchor("precommit_quorum", round_)
            if lat is not None and self.metrics is not None:
                self.metrics.quorum_precommit_latency.observe(lat)

    def mark_commit(
        self, height: int, round_: int, num_txs: int, block_hash: str
    ) -> None:
        if self.metrics is not None:
            # rounds needed to commit this height (1 = no burned round)
            self.metrics.rounds_per_height.observe(round_ + 1)
        if self.enabled:
            self.record(
                EV_COMMIT,
                height,
                round_,
                num_txs=num_txs,
                block=block_hash,
            )

    def mark_evidence_seen(
        self, height: int, round_: int, validator: str
    ) -> None:
        """This node's vote_set caught conflicting votes (the
        equivocation detection site, state.py _try_add_vote). Once per
        (height, round): gossip re-delivers the same conflicting pair
        from every peer that holds it."""
        self._record_once(
            EV_EVIDENCE_SEEN, height, round_, validator=validator[:12]
        )

    def mark_evidence_committed(
        self, height: int, round_: int, count: int, ev_heights: list
    ) -> None:
        """A finalized block carried evidence — the accountability
        endpoint the byzantine campaign SLO-checks (loadgen/byz.py
        joins evidence_seen -> evidence_committed across the fleet for
        per-height evidence-commit latency). `ev_heights` are the
        heights the committed items incriminate."""
        self._record_once(
            EV_EVIDENCE_COMMITTED,
            height,
            round_,
            count=count,
            ev_heights=ev_heights,
        )

    def mark_stall_reset(
        self, kind: str, height: int, round_: int, peer: str
    ) -> None:
        """One gossip forget-and-resend tick fired (reactor.py).
        `kind` is the reset site: catchup (>=2 behind, PR 9) | live
        (same height, PR 13) | last_commit (one behind, PR 13). The
        counter makes a wedge-save distinguishable from a quiet net
        even with the ring disabled."""
        if self.metrics is not None:
            self.metrics.stall_resets.inc(kind=kind)
        if self.enabled:
            self.record(
                EV_STALL_RESET,
                height,
                round_,
                reset=kind,
                peer=peer[:12],
            )

    def _anchor(self, name: str, round_: int = 0) -> None:
        self._anchors[name] = (round_, time.monotonic_ns())

    def _anchor_lat(self, name: str, round_: int) -> Optional[float]:
        """Seconds since anchor `name`, only if it was set in the SAME
        round (a proposal from round 0 must not time a round-3 polka)."""
        got = self._anchors.get(name)
        if got is None or got[0] != round_:
            return None
        return (time.monotonic_ns() - got[1]) / 1e9

    # -- export --------------------------------------------------------

    def snapshot(self) -> List[TimelineEvent]:
        """The recorded events, oldest first."""
        return list(self._ring)

    def dropped_before(self) -> int:
        """How many events were evicted by the ring bound (0 when the
        whole history is still resident)."""
        if not self._ring:
            return self._next_seq - 1
        return self._ring[0].seq - 1

    def page(
        self, after_seq: int, limit: int
    ) -> Tuple[List[Dict[str, Any]], int, int]:
        """Events with seq > after_seq, oldest first, at most `limit`
        of them (callers clamp `limit` — rpc/core.py pins the server
        cap). Returns (events, next_seq, dropped_before): pass
        next_seq back as after_seq to resume the cursor."""
        out: List[Dict[str, Any]] = []
        next_seq = after_seq
        for e in self._ring:
            if e.seq <= after_seq:
                continue
            if len(out) >= limit:
                break
            out.append(e.to_dict())
            next_seq = e.seq
        return out, next_seq, self.dropped_before()

    def to_json(self) -> str:
        """The whole resident ring (debug bundle `timeline.json`)."""
        return json.dumps(
            {
                "timeline": [e.to_dict() for e in self._ring],
                "dropped_before": self.dropped_before(),
                "enabled": self.enabled,
            },
            default=str,
        )


# ----------------------------------------------------------------------
# WAL post-mortem reconstruction
#
# The WAL records every input the consensus loop processed (proposals,
# block parts, votes, timeouts — write-before-process) plus the
# EventDataRoundStateWAL step markers _new_step writes (reference:
# state.go newStep -> wal.Write(rs)), each stamped with the wall clock
# at write time. That is enough to rebuild the same event stream the
# live recorder captured — for a node that is wedged or dead, with
# zero live state.


def events_from_wal(
    path: str, validators: int = 0
) -> List[Dict[str, Any]]:
    """Reconstruct the timeline event stream from a WAL group.

    `validators` sets the committee size for the vote-threshold
    reconstruction; 0 infers it as max(validator_index)+1 over the
    log. Thresholds are COUNT-based (> 2/3 of the committee, counted
    per voted non-nil block — a mixed or all-nil vote set never fakes
    a crossing), exact for equal-power validator sets (every
    localnet/e2e net here) and an approximation otherwise — the
    caveat every derived `polka` / `precommit_quorum` event carries
    in its `derived` attr. Gossip
    stall-resets are reactor-side state, not consensus inputs, so they
    do not appear in a WAL reconstruction.
    """
    from ..types.canonical import PREVOTE_TYPE
    from .msgs import (
        BlockPartMessage,
        EndHeightMessage,
        EventDataRoundStateWAL,
        MsgInfo,
        ProposalMessage,
        TimeoutInfo,
        VoteMessage,
    )
    from .types import step_name
    from .wal import iter_wal_group

    records = list(iter_wal_group(path))
    if validators <= 0:
        top = -1
        for _, msg in records:
            if isinstance(msg, MsgInfo) and isinstance(
                msg.msg, VoteMessage
            ):
                top = max(top, msg.msg.vote.validator_index)
        validators = top + 1
    quorum = (2 * validators) // 3 + 1 if validators > 0 else 0

    events: List[Dict[str, Any]] = []
    seq = 0

    def emit(
        t_ns: int, kind: str, height: int, round_: int, **attrs: Any
    ) -> None:
        nonlocal seq
        seq += 1
        d: Dict[str, Any] = {
            "seq": seq,
            "kind": kind,
            "height": height,
            "round": round_,
            "t_wall_ns": t_ns,
        }
        d.update(attrs)
        events.append(d)

    # per-(height, round, type, block_id) distinct voters — keyed by
    # the voted block so a mixed or all-nil vote set never fakes a
    # crossing the live recorder would not have recorded (the live
    # sites require +2/3 for ONE non-nil block); per-height part totals
    voters: Dict[Tuple[int, int, int, bytes], set] = {}
    seen_voters: Dict[Tuple[int, int, int], set] = {}
    part_totals: Dict[Tuple[int, int], int] = {}
    parts_seen: Dict[Tuple[int, int], set] = {}
    crossed: set = set()
    last_height = 0

    for t_ns, msg in records:
        if isinstance(msg, EventDataRoundStateWAL):
            if msg.height != last_height:
                emit(t_ns, EV_NEW_HEIGHT, msg.height, msg.round)
                last_height = msg.height
            emit(
                t_ns, EV_STEP, msg.height, msg.round, step=msg.step
            )
            continue
        if isinstance(msg, TimeoutInfo):
            emit(
                t_ns,
                EV_TIMEOUT,
                msg.height,
                msg.round,
                step=step_name(msg.step),
                duration_s=msg.duration_s,
            )
            continue
        if isinstance(msg, EndHeightMessage):
            emit(t_ns, EV_COMMIT, msg.height, -1, derived="end_height")
            continue
        if not isinstance(msg, MsgInfo):
            continue
        inner = msg.msg
        if isinstance(inner, ProposalMessage):
            p = inner.proposal
            key = (p.height, p.round)
            part_totals[key] = p.block_id.part_set_header.total
            emit(t_ns, EV_PROPOSAL, p.height, p.round)
        elif isinstance(inner, BlockPartMessage):
            key = (inner.height, inner.round)
            seen = parts_seen.setdefault(key, set())
            seen.add(inner.part.index)
            total = part_totals.get(key)
            if (
                total is not None
                and len(seen) >= total
                and ("block",) + key not in crossed
            ):
                crossed.add(("block",) + key)
                emit(t_ns, EV_BLOCK, inner.height, inner.round)
        elif isinstance(inner, VoteMessage):
            v = inner.vote
            vkey = (v.height, v.round, v.type)
            seen_all = seen_voters.setdefault(vkey, set())
            if v.validator_index in seen_all:
                continue  # gossip dup: must not re-fire the crossing
            seen_all.add(v.validator_index)
            if v.block_id.is_zero():
                continue  # nil votes never form a polka/quorum
            seen = voters.setdefault(
                vkey + (v.block_id.key(),), set()
            )
            seen.add(v.validator_index)
            if quorum and len(seen) == quorum:
                kind = (
                    EV_POLKA
                    if v.type == PREVOTE_TYPE
                    else EV_PRECOMMIT_QUORUM
                )
                emit(
                    t_ns,
                    kind,
                    v.height,
                    v.round,
                    derived="count_threshold",
                    voters=len(seen),
                    committee=validators,
                )
    return events


def summarize_heights(
    events: List[Dict[str, Any]]
) -> List[Dict[str, Any]]:
    """Per-height post-mortem rows from a reconstructed (or exported)
    event stream: when each phase first happened, rounds burned,
    timeout count, and the wall-clock spans between phases — the
    human-readable half of scripts/timeline_replay.py."""
    by_height: Dict[int, List[Dict[str, Any]]] = {}
    for e in events:
        by_height.setdefault(e["height"], []).append(e)
    rows: List[Dict[str, Any]] = []
    for h in sorted(k for k in by_height if k > 0):
        evs = by_height[h]
        first: Dict[str, int] = {}
        for e in evs:
            t = e.get("t_wall_ns")
            if t is None:
                continue
            k = e["kind"]
            if k not in first:
                first[k] = t
        rounds = max((e["round"] for e in evs), default=0)
        # the NewHeight timeout is the normal per-height pacing tick
        # (timeout_commit); only the round-step timeouts are anomalies
        timeouts = sum(
            1
            for e in evs
            if e["kind"] == EV_TIMEOUT
            and e.get("step") != "RoundStepNewHeight"
        )
        stalls = sum(1 for e in evs if e["kind"] == EV_STALL_RESET)

        def span_ms(a: str, b: str) -> Optional[float]:
            if a in first and b in first:
                return round((first[b] - first[a]) / 1e6, 3)
            return None

        rows.append(
            {
                "height": h,
                "rounds": max(rounds, 0),
                "timeouts": timeouts,
                "stall_resets": stalls,
                "events": len(evs),
                "proposal_to_polka_ms": span_ms(
                    EV_PROPOSAL, EV_POLKA
                ),
                "polka_to_precommit_quorum_ms": span_ms(
                    EV_POLKA, EV_PRECOMMIT_QUORUM
                ),
                "precommit_quorum_to_commit_ms": span_ms(
                    EV_PRECOMMIT_QUORUM, EV_COMMIT
                ),
                "first_event_to_commit_ms": span_ms(
                    next(
                        (
                            k
                            for k in (
                                EV_NEW_HEIGHT,
                                EV_STEP,
                                EV_PROPOSAL,
                            )
                            if k in first
                        ),
                        EV_COMMIT,
                    ),
                    EV_COMMIT,
                ),
            }
        )
    return rows
