"""Consensus reactor — gossips the consensus protocol over p2p.

reference: internal/consensus/reactor.go. Four channels (State 0x20,
Data 0x21, Vote 0x22, VoteSetBits 0x23; descriptors :31-75); per-peer
gossip tasks (gossipDataRoutine :492, gossipVotesRoutine :752,
queryMaj23Routine :850); round-step/HasVote broadcasts driven by event
bus observation (:362).
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional

from ..config import ConsensusConfig
from ..eventbus import EventBus
from ..libs import rng
from ..libs.log import get_logger
from ..libs.service import Service
from ..p2p.channel import Channel
from ..p2p.peermanager import PeerStatus
from ..p2p.types import ChannelDescriptor, Envelope, PeerError
from ..pubsub import SubscriptionError
from ..types import events as E
from ..types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from .msgs import (
    BlockPartMessage,
    HasVoteMessage,
    NewRoundStepMessage,
    NewValidBlockMessage,
    ProposalMessage,
    ProposalPOLMessage,
    VoteMessage,
    VoteSetBitsMessage,
    VoteSetMaj23Message,
    decode_msg,
    encode_msg,
)
from .peer_state import PeerState
from .state import ConsensusState
from .types import RoundStep

__all__ = [
    "ConsensusReactor",
    "STATE_CHANNEL",
    "DATA_CHANNEL",
    "VOTE_CHANNEL",
    "VOTE_SET_BITS_CHANNEL",
    "consensus_channel_descriptors",
]

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23


class _MsgCodec:
    """All four channels share the consensus Message oneof envelope."""

    encode = staticmethod(encode_msg)
    decode = staticmethod(decode_msg)


def consensus_channel_descriptors():
    """reference: reactor.go:31-67 (priorities and queue sizes)."""
    return {
        STATE_CHANNEL: ChannelDescriptor(
            channel_id=STATE_CHANNEL, message_type=_MsgCodec, priority=8,
            send_queue_capacity=64, recv_buffer_capacity=128, name="state",
        ),
        DATA_CHANNEL: ChannelDescriptor(
            channel_id=DATA_CHANNEL, message_type=_MsgCodec, priority=12,
            send_queue_capacity=64, recv_buffer_capacity=512, name="data",
        ),
        VOTE_CHANNEL: ChannelDescriptor(
            channel_id=VOTE_CHANNEL, message_type=_MsgCodec, priority=10,
            send_queue_capacity=64, recv_buffer_capacity=4096, name="vote",
        ),
        VOTE_SET_BITS_CHANNEL: ChannelDescriptor(
            channel_id=VOTE_SET_BITS_CHANNEL, message_type=_MsgCodec,
            priority=5, send_queue_capacity=8, recv_buffer_capacity=128,
            name="votebits",
        ),
    }


class ConsensusReactor(Service):
    def __init__(
        self,
        cs: ConsensusState,
        channels: Dict[int, Channel],
        peer_updates: asyncio.Queue,
        event_bus: EventBus,
        cfg: Optional[ConsensusConfig] = None,
        wait_sync: bool = False,
    ) -> None:
        super().__init__(name="consensus.reactor", logger=get_logger("consensus.reactor"))
        self.cs = cs
        self.state_ch = channels[STATE_CHANNEL]
        self.data_ch = channels[DATA_CHANNEL]
        self.vote_ch = channels[VOTE_CHANNEL]
        self.vote_bits_ch = channels[VOTE_SET_BITS_CHANNEL]
        self.peer_updates = peer_updates
        self.event_bus = event_bus
        self.cfg = cfg or cs.cfg
        self.peers: Dict[str, PeerState] = {}
        self._peer_tasks: Dict[str, list] = {}
        # wait_sync: started in block-sync mode; consensus runs after
        # switch_to_consensus (reference: reactor.go:252 SwitchToConsensus)
        self.wait_sync = wait_sync

    async def on_start(self) -> None:
        if not self.wait_sync:
            await self.cs.start()
        self.spawn(self._peer_update_routine(), "peer-updates")
        self.spawn(self._recv_routine(self.state_ch, self._handle_state_msg), "recv-state")
        self.spawn(self._recv_routine(self.data_ch, self._handle_data_msg), "recv-data")
        self.spawn(self._recv_routine(self.vote_ch, self._handle_vote_msg), "recv-vote")
        self.spawn(self._recv_routine(self.vote_bits_ch, self._handle_vote_bits_msg), "recv-votebits")
        self.spawn(self._broadcast_routine(), "broadcasts")

    async def on_stop(self) -> None:
        if self.cs.is_running:
            await self.cs.stop()

    async def switch_to_consensus(self, state, skip_wal: bool = False) -> None:
        """Called by block sync when caught up
        (reference: reactor.go:252-306)."""
        self.logger.info("switching to consensus")
        self.wait_sync = False
        await self.cs.start()

    # ------------------------------------------------------------------
    # per-peer lifecycle

    async def _peer_update_routine(self) -> None:
        while True:
            update = await self.peer_updates.get()
            if update.status == PeerStatus.UP:
                self._add_peer(update.node_id)
            elif update.status == PeerStatus.DOWN:
                self._remove_peer(update.node_id)

    def _add_peer(self, peer_id: str) -> None:
        if peer_id in self.peers:
            return
        ps = PeerState(peer_id)
        self.peers[peer_id] = ps
        tasks = [
            self.spawn(self._gossip_data_routine(ps), f"gossip-data-{peer_id[:8]}"),
            self.spawn(self._gossip_votes_routine(ps), f"gossip-votes-{peer_id[:8]}"),
            self.spawn(self._query_maj23_routine(ps), f"maj23-{peer_id[:8]}"),
        ]
        self._peer_tasks[peer_id] = tasks
        # tell the new peer where we are
        self.state_ch.try_send(
            Envelope(message=self._our_new_round_step(), to=peer_id)
        )

    def _remove_peer(self, peer_id: str) -> None:
        self.peers.pop(peer_id, None)
        for t in self._peer_tasks.pop(peer_id, []):
            if not t.done():
                t.cancel()
        self._tasks = [t for t in self._tasks if not t.done()]

    # ------------------------------------------------------------------
    # broadcasts (reference: reactor.go:362-430)

    async def _broadcast_routine(self) -> None:
        sub_steps = self.event_bus.subscribe(
            f"cs-reactor-{id(self)}",
            f"{E.EVENT_TYPE_KEY} = '{E.EventValue.NEW_ROUND_STEP}'",
            limit=256,
        )
        sub_votes = self.event_bus.subscribe(
            f"cs-reactor-{id(self)}",
            f"{E.EVENT_TYPE_KEY} = '{E.EventValue.VOTE}'",
            limit=4096,
        )
        step_t = asyncio.ensure_future(sub_steps.next())
        vote_t = asyncio.ensure_future(sub_votes.next())
        try:
            while True:
                done, _ = await asyncio.wait(
                    {step_t, vote_t}, return_when=asyncio.FIRST_COMPLETED
                )
                if step_t in done:
                    try:
                        step_t.result()
                        self.state_ch.try_send(
                            Envelope(
                                message=self._our_new_round_step(),
                                broadcast=True,
                            )
                        )
                    except SubscriptionError:
                        return
                    step_t = asyncio.ensure_future(sub_steps.next())
                if vote_t in done:
                    try:
                        msg = vote_t.result()
                        vote = msg.data.vote
                        self.state_ch.try_send(
                            Envelope(
                                message=HasVoteMessage(
                                    height=vote.height,
                                    round=vote.round,
                                    type=vote.type,
                                    index=vote.validator_index,
                                ),
                                broadcast=True,
                            )
                        )
                    except SubscriptionError:
                        return
                    vote_t = asyncio.ensure_future(sub_votes.next())
        finally:
            for t in (step_t, vote_t):
                if not t.done():
                    t.cancel()

    def _our_new_round_step(self) -> NewRoundStepMessage:
        rs = self.cs.rs
        import time as _time

        secs = max(0, (_time.time_ns() - rs.start_time_ns) // 1_000_000_000)
        last_commit_round = -1
        if rs.last_commit is not None:
            last_commit_round = rs.last_commit.round
        return NewRoundStepMessage(
            height=rs.height,
            round=rs.round,
            step=rs.step,
            seconds_since_start_time=secs,
            last_commit_round=last_commit_round,
        )

    # ------------------------------------------------------------------
    # inbound handlers

    async def _recv_routine(self, channel: Channel, handler) -> None:
        async for envelope in channel:
            try:
                await handler(envelope)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.logger.error(
                    "failed to process message",
                    ch=channel.name,
                    peer=envelope.from_peer[:12],
                    err=str(e),
                )
                await channel.send_error(
                    PeerError(node_id=envelope.from_peer, err=str(e))
                )

    async def _handle_state_msg(self, envelope: Envelope) -> None:
        """reference: reactor.go:1088-1164 handleStateMessage."""
        ps = self.peers.get(envelope.from_peer)
        if ps is None:
            return
        msg = envelope.message
        if isinstance(msg, NewRoundStepMessage):
            msg.validate_basic()
            ps.apply_new_round_step(msg)
        elif isinstance(msg, NewValidBlockMessage):
            msg.validate_basic()
            ps.apply_new_valid_block(msg)
        elif isinstance(msg, HasVoteMessage):
            msg.validate_basic()
            ps.ensure_vote_bits(self.cs.rs.validators.size())
            ps.apply_has_vote(msg)
        elif isinstance(msg, VoteSetMaj23Message):
            msg.validate_basic()
            rs = self.cs.rs
            if rs.height != msg.height:
                return
            rs.votes.set_peer_maj23(
                msg.round, msg.type, ps.peer_id, msg.block_id
            )
            # respond with our bits for that block ID
            if msg.type == PREVOTE_TYPE:
                our_votes_set = rs.votes.prevotes(msg.round)
            else:
                our_votes_set = rs.votes.precommits(msg.round)
            bits = (
                our_votes_set.bit_array_by_block_id(msg.block_id)
                if our_votes_set is not None
                else None
            )
            self.vote_bits_ch.try_send(
                Envelope(
                    message=VoteSetBitsMessage(
                        height=msg.height,
                        round=msg.round,
                        type=msg.type,
                        block_id=msg.block_id,
                        votes=bits,
                    ),
                    to=ps.peer_id,
                )
            )
        else:
            raise ValueError(
                f"unexpected message on state channel: {type(msg).__name__}"
            )

    async def _handle_data_msg(self, envelope: Envelope) -> None:
        """reference: reactor.go:1166-1212."""
        ps = self.peers.get(envelope.from_peer)
        if ps is None:
            return
        if self.wait_sync:
            return  # ignore consensus data while block-syncing
        msg = envelope.message
        if isinstance(msg, ProposalMessage):
            msg.validate_basic()
            ps.set_has_proposal(msg.proposal)
            self.cs.send_peer_msg(msg, ps.peer_id)
        elif isinstance(msg, ProposalPOLMessage):
            msg.validate_basic()
            ps.apply_proposal_pol(msg)
        elif isinstance(msg, BlockPartMessage):
            msg.validate_basic()
            ps.set_has_proposal_block_part(
                msg.height, msg.round, msg.part.index
            )
            self.cs.send_peer_msg(msg, ps.peer_id)
        else:
            raise ValueError(
                f"unexpected message on data channel: {type(msg).__name__}"
            )

    async def _handle_vote_msg(self, envelope: Envelope) -> None:
        """reference: reactor.go:1214-1244."""
        ps = self.peers.get(envelope.from_peer)
        if ps is None:
            return
        if self.wait_sync:
            return
        msg = envelope.message
        if not isinstance(msg, VoteMessage):
            raise ValueError(
                f"unexpected message on vote channel: {type(msg).__name__}"
            )
        msg.validate_basic()
        vote = msg.vote
        ps.ensure_vote_bits(self.cs.rs.validators.size())
        ps.set_has_vote(vote.height, vote.round, vote.type, vote.validator_index)
        self.cs.send_peer_msg(msg, ps.peer_id)

    async def _handle_vote_bits_msg(self, envelope: Envelope) -> None:
        """reference: reactor.go:1246-1290."""
        ps = self.peers.get(envelope.from_peer)
        if ps is None:
            return
        msg = envelope.message
        if not isinstance(msg, VoteSetBitsMessage):
            raise ValueError(
                f"unexpected message on votebits channel: "
                f"{type(msg).__name__}"
            )
        msg.validate_basic()
        rs = self.cs.rs
        our_votes = None
        if rs.height == msg.height:
            if msg.type == PREVOTE_TYPE:
                vs = rs.votes.prevotes(msg.round)
            else:
                vs = rs.votes.precommits(msg.round)
            if vs is not None:
                our_votes = vs.bit_array_by_block_id(msg.block_id)
        ps.apply_vote_set_bits(msg, our_votes)

    # ------------------------------------------------------------------
    # gossip routines

    async def _gossip_data_routine(self, ps: PeerState) -> None:
        """Send the peer proposal/parts it lacks; catch it up from the
        block store when behind (reference: reactor.go:492-610)."""
        sleep = self.cfg.peer_gossip_sleep_duration
        while True:
            rs = self.cs.rs
            prs = ps.prs
            sent = False

            # 1) proposal first: it carries the part-set header the peer
            # needs before parts are useful (reference sends parts only
            # once headers match, reactor.go:505-540)
            if (
                rs.height == prs.height
                and rs.round == prs.round
                and rs.proposal is not None
                and not prs.proposal
            ):
                if self.data_ch.try_send(
                    Envelope(
                        message=ProposalMessage(proposal=rs.proposal),
                        to=ps.peer_id,
                    )
                ):
                    ps.set_has_proposal(rs.proposal)
                    sent = True
                if 0 <= rs.proposal.pol_round:
                    pol = rs.votes.prevotes(rs.proposal.pol_round)
                    if pol is not None:
                        self.data_ch.try_send(
                            Envelope(
                                message=ProposalPOLMessage(
                                    height=rs.height,
                                    proposal_pol_round=rs.proposal.pol_round,
                                    proposal_pol=pol.bit_array(),
                                ),
                                to=ps.peer_id,
                            )
                        )

            # 2) same height/round with matching part-set headers: a
            # WINDOW of missing parts per iteration — one part per
            # sleep tick made part delivery the block-latency floor
            # for multi-part blocks (total_parts × sleep). try_send
            # keeps the existing slow-peer shedding as backpressure:
            # a full send queue truncates the window instead of
            # stalling the routine.
            if (
                not sent
                and rs.proposal_block_parts is not None
                and rs.height == prs.height
                and rs.round == prs.round
                and prs.proposal_block_parts is not None
                and prs.proposal_block_parts_header
                == rs.proposal_block_parts.header()
            ):
                for _ in range(max(1, self.cfg.peer_gossip_part_window)):
                    part = self._pick_part_to_send(
                        rs.proposal_block_parts, prs.proposal_block_parts
                    )
                    if part is None:
                        break
                    if not self.data_ch.try_send(
                        Envelope(
                            message=BlockPartMessage(
                                height=rs.height, round=rs.round, part=part
                            ),
                            to=ps.peer_id,
                        )
                    ):
                        break  # peer's send queue full: shed the rest
                    ps.set_has_proposal_block_part(
                        rs.height, rs.round, part.index
                    )
                    sent = True

            # 3) peer is behind: a window of parts of its next
            # committed block (same shedding backpressure)
            if (
                not sent
                and 0 < prs.height < rs.height
                and prs.height >= self.cs.block_store.base()
            ):
                for _ in range(max(1, self.cfg.peer_gossip_part_window)):
                    if not self._gossip_catchup_part(ps):
                        break
                    sent = True

            if not sent:
                await asyncio.sleep(sleep)
            else:
                await asyncio.sleep(0)  # yield

    def _pick_part_to_send(self, our_parts, peer_bits):
        missing = our_parts.parts_bit_array.sub(peer_bits)
        candidates = list(missing.indices())
        if not candidates:
            return None
        return our_parts.get_part(rng.choice(candidates))

    def _gossip_catchup_part(self, ps: PeerState) -> bool:
        """reference: reactor.go gossipDataForCatchup."""
        prs = ps.prs
        meta = self.cs.block_store.load_block_meta(prs.height)
        if meta is None:
            return False
        # make sure the peer's part-set header matches the stored block
        if prs.proposal_block_parts is None:
            ps.prs.proposal_block_parts_header = meta.block_id.part_set_header
            from ..libs.bits import BitArray

            ps.prs.proposal_block_parts = BitArray(
                max(1, meta.block_id.part_set_header.total)
            )
        if prs.proposal_block_parts_header != meta.block_id.part_set_header:
            return False
        missing = [
            i
            for i in range(prs.proposal_block_parts_header.total)
            if not prs.proposal_block_parts.get(i)
        ]
        if not missing:
            # We think we sent everything, yet the peer hasn't advanced.
            # Our marks are optimistic (a part can be dropped before the
            # peer's part tracker exists, e.g. arriving ahead of the
            # precommits that initialize it in enterCommit) — so after a
            # stall, forget and resend. Parts are idempotent.
            ps.catchup_stall = getattr(ps, "catchup_stall", 0) + 1
            if ps.catchup_stall * self.cfg.peer_gossip_sleep_duration > 1.0:
                ps.catchup_stall = 0
                ps.prs.proposal_block_parts = None
            return False
        ps.catchup_stall = 0
        index = rng.choice(missing)
        part = self.cs.block_store.load_block_part(prs.height, index)
        if part is None:
            return False
        if self.data_ch.try_send(
            Envelope(
                message=BlockPartMessage(
                    height=prs.height, round=prs.round, part=part
                ),
                to=ps.peer_id,
            )
        ):
            ps.set_has_proposal_block_part(prs.height, prs.round, index)
            return True
        return False

    async def _gossip_votes_routine(self, ps: PeerState) -> None:
        """reference: reactor.go:752-848."""
        sleep = self.cfg.peer_gossip_sleep_duration
        while True:
            rs = self.cs.rs
            prs = ps.prs
            sent = False

            if rs.height == prs.height:
                sent = self._gossip_votes_same_height(ps)
                if not sent:
                    # The optimistic-marks hazard of the two catchup
                    # branches below, at the LIVE height (ISSUE 13):
                    # (kind="live" in the stall-reset observability)
                    # a partitioned or lossy link drops the frame
                    # while the connection survives, our bits claim
                    # delivery, and with < 2/3 prevotes delivered no
                    # timeout ever fires — the whole net parks at
                    # (height, round, PREVOTE) forever (witnessed:
                    # 2|2 partition heal in the chaos campaign).
                    # After a sustained both-sides-frozen stall with
                    # nothing to send, forget the live-height marks
                    # and resend — dup votes are idempotent on the
                    # receiver, and the burst is bounded to one
                    # vote-set resend per stall window.
                    self._vote_stall_tick(ps, ps.reset_live_votes, "live")
            elif (
                prs.height != 0
                and rs.height == prs.height + 1
                and rs.last_commit is not None
            ):
                # peer one behind us: send them our last commit precommits
                sent = self._send_vote(ps, ps.pick_vote_to_send(rs.last_commit))
                if not sent:
                    # same hazard, one height back: when the partition
                    # straddles a commit boundary, the lagging side is
                    # exactly one behind and the marks these sends
                    # left (they land in the peer's CURRENT-height
                    # precommit bits via _get_vote_bits) are the lying
                    # ones (witnessed: the 2|2 campaign scenario
                    # wedged here after the live-height reset landed)
                    self._vote_stall_tick(
                        ps, ps.reset_live_votes, "last_commit"
                    )
            elif (
                prs.height != 0
                and rs.height >= prs.height + 2
                and prs.height >= self.cs.block_store.base()
            ):
                # far behind: votes from the stored commit for their height
                commit = self.cs.block_store.load_block_commit(prs.height)
                if commit is not None:
                    n = self._validators_size_at(prs.height)
                    # allocate the bit arrays the pick/mark cycle uses —
                    # unallocated bits would mean every send repeats
                    ps.ensure_vote_bits(n)
                    ps.ensure_catchup_commit_round(
                        prs.height, commit.round, n
                    )
                    sent = self._send_commit_vote(ps, commit)
                    if sent:
                        ps.vote_catchup_stall = 0
                    else:
                        # Same optimistic-marks hazard _gossip_catchup_
                        # part documents for block parts, on the votes
                        # side: precommits streamed while the peer's
                        # reactor was still in wait_sync (its blocksync
                        # grace window) were dropped unseen, yet our
                        # bits say delivered — the peer then wedges at
                        # prs.height FOREVER with nobody resending
                        # (witnessed: process-net SIGKILL recovery, the
                        # restarted validator stuck at its boot height
                        # while the net ran 270 heights ahead). After a
                        # stall with no progress, forget and resend —
                        # dup votes are idempotent on the receiver.
                        ps.vote_catchup_stall = (
                            getattr(ps, "vote_catchup_stall", 0) + 1
                        )
                        if ps.vote_catchup_stall * sleep > 1.0:
                            ps.vote_catchup_stall = 0
                            # visible wedge-save: counter + timeline
                            # event (ISSUE 15 — these ticks used to
                            # fire invisibly)
                            self.cs.timeline.mark_stall_reset(
                                "catchup",
                                prs.height,
                                commit.round,
                                ps.peer_id,
                            )
                            ps.reset_catchup_precommits(
                                prs.height, commit.round, n
                            )

            if not sent:
                await asyncio.sleep(sleep)
            else:
                ps.live_vote_stall = 0
                await asyncio.sleep(0)

    def _vote_stall_tick(self, ps: PeerState, reset, kind: str) -> None:
        """Count a nothing-to-send gossip tick while BOTH sides'
        round states are frozen; past the stall window, run `reset`
        (forget the optimistic delivered-marks so gossip resends).
        Any progress — a successful send, or either side moving —
        zeroes the counter, so healthy nets pay one integer bump per
        idle tick and never reset. `kind` labels the reset site
        ("live" | "last_commit") in the stall-reset counter and the
        flight-recorder event — a wedge-save used to be
        indistinguishable from a quiet net (ISSUE 15)."""
        rs = self.cs.rs
        prs = ps.prs
        snap = (
            rs.height, rs.round, rs.step,
            prs.height, prs.round, prs.step,
        )
        if getattr(ps, "live_stall_snap", None) != snap:
            ps.live_stall_snap = snap
            ps.live_vote_stall = 0
        ps.live_vote_stall = getattr(ps, "live_vote_stall", 0) + 1
        if (
            ps.live_vote_stall * self.cfg.peer_gossip_sleep_duration
            > 2.0
        ):
            ps.live_vote_stall = 0
            self.cs.timeline.mark_stall_reset(
                kind, rs.height, rs.round, ps.peer_id
            )
            reset()

    def _validators_size_at(self, height: int) -> int:
        vals = self.cs.block_exec.store.load_validators(height)
        return vals.size() if vals is not None else self.cs.rs.validators.size()

    def _gossip_votes_same_height(self, ps: PeerState) -> bool:
        """reference: reactor.go gossipVotesForHeight."""
        rs = self.cs.rs
        prs = ps.prs
        # peer's round matches a previous POL round → its prevotes
        if prs.step == RoundStep.NEW_HEIGHT and rs.last_commit is not None:
            if self._send_vote(ps, ps.pick_vote_to_send(rs.last_commit)):
                return True
        if prs.step <= RoundStep.PROPOSE and prs.round != -1 and (
            prs.round <= rs.round and prs.proposal_pol_round != -1
        ):
            pol = rs.votes.prevotes(prs.proposal_pol_round)
            if pol is not None and self._send_vote(
                ps, ps.pick_vote_to_send(pol)
            ):
                return True
        if prs.step <= RoundStep.PREVOTE_WAIT and prs.round != -1 and (
            prs.round <= rs.round
        ):
            prevotes = rs.votes.prevotes(prs.round)
            if prevotes is not None and self._send_vote(
                ps, ps.pick_vote_to_send(prevotes)
            ):
                return True
        if prs.step <= RoundStep.PRECOMMIT_WAIT and prs.round != -1 and (
            prs.round <= rs.round
        ):
            precommits = rs.votes.precommits(prs.round)
            if precommits is not None and self._send_vote(
                ps, ps.pick_vote_to_send(precommits)
            ):
                return True
        if prs.proposal_pol_round != -1:
            pol = rs.votes.prevotes(prs.proposal_pol_round)
            if pol is not None and self._send_vote(
                ps, ps.pick_vote_to_send(pol)
            ):
                return True
        return False

    def _send_vote(self, ps: PeerState, vote) -> bool:
        if vote is None:
            return False
        if self.vote_ch.try_send(
            Envelope(message=VoteMessage(vote=vote), to=ps.peer_id)
        ):
            ps.set_has_vote(vote.height, vote.round, vote.type, vote.validator_index)
            return True
        return False

    def _send_commit_vote(self, ps: PeerState, commit) -> bool:
        """Send a random precommit out of a stored commit. Picks and marks
        against the SAME bit array (_get_vote_bits), like the reference's
        PickSendVote — checking one array but marking another loops
        forever (reference: peer_state.go PickSendVote/SetHasVote)."""
        peer_bits = ps._get_vote_bits(
            commit.height, commit.round, PRECOMMIT_TYPE
        )
        missing = [
            i
            for i, sig in enumerate(commit.signatures)
            if not sig.is_absent()
            and (
                peer_bits is None
                or (i < peer_bits.size and not peer_bits.get(i))
            )
        ]
        if not missing:
            return False
        index = rng.choice(missing)
        vote = commit.get_vote(index)
        return self._send_vote(ps, vote)

    async def _query_maj23_routine(self, ps: PeerState) -> None:
        """Periodically tell peers about our 2/3 majorities
        (reference: reactor.go:850-966)."""
        sleep = self.cfg.peer_query_maj23_sleep_duration
        while True:
            await asyncio.sleep(sleep)
            # periodic re-announce: a NewRoundStep broadcast dropped on a
            # full queue must not leave the peer's view of us stale forever.
            # Not while syncing — advertising the stale pre-sync height
            # would trigger catchup gossip we'd just discard.
            if self.wait_sync:
                continue
            self.state_ch.try_send(
                Envelope(message=self._our_new_round_step(), to=ps.peer_id)
            )
            rs = self.cs.rs
            prs = ps.prs
            if rs.height != prs.height or rs.votes is None:
                continue
            for vote_type, vs in (
                (PREVOTE_TYPE, rs.votes.prevotes(prs.round)),
                (PRECOMMIT_TYPE, rs.votes.precommits(prs.round)),
            ):
                if vs is None:
                    continue
                block_id, ok = vs.two_thirds_majority()
                if ok:
                    self.state_ch.try_send(
                        Envelope(
                            message=VoteSetMaj23Message(
                                height=prs.height,
                                round=prs.round,
                                type=vote_type,
                                block_id=block_id,
                            ),
                            to=ps.peer_id,
                        )
                    )
