"""Pure-Python ed25519 group arithmetic (reference semantics, not a port).

This is the host-side "gold" implementation of the curve math:

- It defines the exact ZIP-215 verification semantics the framework uses
  (reference: crypto/ed25519/ed25519.go:27-29 — Tendermint pins ZIP-215 so
  batch and single verification agree), serving as the differential oracle
  for the TPU kernel in tendermint_tpu.ops.ed25519_kernel.
- It generates the fixed-base window tables embedded in the kernel.
- It is the CPU fallback for edge-case signatures the fast OpenSSL path
  (RFC 8032 strict) rejects but ZIP-215 accepts.

ZIP-215 rules (https://zips.z.cash/zip-0215):
  1. A and R are decoded per RFC 8032 §5.1.3 *except* that non-canonical
     y-coordinates (y >= p) are accepted (decode y mod p).
  2. S must be canonical: 0 <= S < L.
  3. Accept iff [8][S]B == [8]R + [8][k]A, k = SHA512(R || A || M) mod L.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Tuple

__all__ = [
    "P",
    "L",
    "D",
    "B_POINT",
    "Point",
    "decompress",
    "compress",
    "point_add",
    "point_double",
    "scalar_mult",
    "mul_base",
    "mul_base_ct",
    "zip215_verify",
    "sha512_mod_l",
]

P = 2**255 - 19
D = (-121665 * pow(121666, P - 2, P)) % P
L = 2**252 + 27742317777372353535851937790883648493
SQRT_M1 = pow(2, (P - 1) // 4, P)

# Extended homogeneous coordinates (X, Y, Z, T) with x = X/Z, y = Y/Z,
# x*y = T/Z on -x^2 + y^2 = 1 + d x^2 y^2.
Point = Tuple[int, int, int, int]

IDENTITY: Point = (0, 1, 1, 0)


def _recover_x(y: int, sign: int) -> Optional[int]:
    x2_num = (y * y - 1) % P
    x2_den = (D * y * y + 1) % P
    x2 = x2_num * pow(x2_den, P - 2, P) % P
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x == 0 and sign == 1:
        # x = -0 is not representable; RFC 8032 and ZIP-215 both reject.
        return None
    if x & 1 != sign:
        x = P - x
    return x


def decompress(data: bytes, zip215: bool = True) -> Optional[Point]:
    """Decode a 32-byte point. ZIP-215 accepts non-canonical y (y >= p),
    reducing mod p; strict RFC 8032 rejects them."""
    if len(data) != 32:
        return None
    y = int.from_bytes(data, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    if y >= P:
        if not zip215:
            return None
        y %= P
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def compress(pt: Point) -> bytes:
    X, Y, Z, _ = pt
    zinv = pow(Z, P - 2, P)
    x, y = X * zinv % P, Y * zinv % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def point_add(p: Point, q: Point) -> Point:
    # add-2008-hwcd-3 for a = -1 twisted Edwards
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = T1 * 2 * D * T2 % P
    Dv = Z1 * 2 * Z2 % P
    E, F, G, H = B - A, Dv - C, Dv + C, B + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_double(p: Point) -> Point:
    # dbl-2008-hwcd
    X1, Y1, Z1, _ = p
    A = X1 * X1 % P
    B = Y1 * Y1 % P
    C = 2 * Z1 * Z1 % P
    H = A + B
    E = (H - (X1 + Y1) * (X1 + Y1)) % P
    G = A - B
    F = C + G
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_neg(p: Point) -> Point:
    X, Y, Z, T = p
    return (P - X if X else 0, Y, Z, P - T if T else 0)


def point_eq(p: Point, q: Point) -> bool:
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def scalar_mult(k: int, p: Point) -> Point:
    q = IDENTITY
    while k:
        if k & 1:
            q = point_add(q, p)
        p = point_double(p)
        k >>= 1
    return q


_B_Y = 4 * pow(5, P - 2, P) % P
_B_X = _recover_x(_B_Y, 0)
assert _B_X is not None
B_POINT: Point = (_B_X, _B_Y, 1, _B_X * _B_Y % P)

# lazy 4-bit fixed-base comb: 64 windows x 15 odd multiples of B.
# mul_base costs 63 adds instead of ~380 double/adds — the pure-Python
# basepoint mult is what sr25519 sign/keygen spend their time on
# (reference gets this from curve25519-voi's precomputed tables).
_BASE_COMB: list | None = None


def _base_comb() -> list:
    global _BASE_COMB
    if _BASE_COMB is None:
        tbl = []
        base = B_POINT
        for _ in range(64):
            row = [IDENTITY]
            for _i in range(15):
                row.append(point_add(row[-1], base))
            tbl.append(row)
            base = point_add(row[15], base)  # base * 16
        _BASE_COMB = tbl
    return _BASE_COMB


def mul_base(k: int) -> Point:
    """k*B for any k: reduced mod L up front (B has order L, so the
    product is identical and the 64-window comb always covers it).

    PUBLIC-scalar path only (verification): the loop bound and the
    window branch depend on k. Secret scalars — signing nonces,
    expanded keys — go through mul_base_ct (the tmct gate pins the
    split)."""
    tbl = _base_comb()
    k %= L
    q = IDENTITY
    w = 0
    while k:
        d = k & 15
        if d:
            q = point_add(q, tbl[w][d])
        k >>= 4
        w += 1
    return q


def _comb_select(row: list, d: int) -> Point:
    """Constant-structure row lookup: scan all 16 entries, keep the
    match via an arithmetic mask — `((j ^ d) - 1) >> 4` is -1 exactly
    when j == d, else 0. No comparison or subscript on the secret."""
    x = y = z = t = 0
    for j in range(16):
        mask = ((j ^ d) - 1) >> 4
        ex, ey, ez, et = row[j]
        x |= ex & mask
        y |= ey & mask
        z |= ez & mask
        t |= et & mask
    return x, y, z, t


def mul_base_ct(k: int) -> Point:
    """k*B with a fixed execution structure for SECRET scalars: all 64
    comb windows are walked, every window does one masked row scan and
    one unified addition (add-2008-hwcd-3 is identity-safe on the
    prime-order subgroup), so neither the trace shape nor the table
    access order is a function of k's bits. Pure Python cannot be
    cycle-constant; the contract is structural (docs/static_analysis.md
    tmct: structure-not-cycles)."""
    tbl = _base_comb()
    k %= L
    q = IDENTITY
    for w in range(64):
        q = point_add(q, _comb_select(tbl[w], (k >> (4 * w)) & 15))
    return q


def sha512_mod_l(*chunks: bytes) -> int:
    h = hashlib.sha512()
    for c in chunks:
        h.update(c)
    return int.from_bytes(h.digest(), "little") % L


def zip215_verify(pubkey: bytes, msg: bytes, sig: bytes) -> bool:
    """ZIP-215 cofactored verification: [8][S]B == [8]R + [8][k]A."""
    if len(sig) != 64 or len(pubkey) != 32:
        return False
    A = decompress(pubkey, zip215=True)
    if A is None:
        return False
    R_bytes, S_bytes = sig[:32], sig[32:]
    R = decompress(R_bytes, zip215=True)
    if R is None:
        return False
    S = int.from_bytes(S_bytes, "little")
    if S >= L:
        return False
    k = sha512_mod_l(R_bytes, pubkey, msg)
    # [S]B - [k]A - R, then multiply by cofactor 8 and compare to identity.
    lhs = mul_base(S)
    rhs = point_add(scalar_mult(k, A), R)
    diff = point_add(lhs, point_neg(rhs))
    for _ in range(3):
        diff = point_double(diff)
    return point_eq(diff, IDENTITY)
