"""Key and batch-verifier interfaces — the plugin boundary.

Mirrors the semantics of the reference's crypto.PubKey / crypto.PrivKey /
crypto.BatchVerifier interfaces (reference: crypto/crypto.go:23-61). The
BatchVerifier contract is the seam the whole TPU offload hangs on:

    add(pubkey, message, signature) -> None   (queue; may raise on bad input)
    verify() -> (all_ok: bool, per_item: list[bool])

`verify()` must report exactly which indices failed — consensus uses the
bitmap to attribute invalid signatures to validators
(reference: types/validation.go:240-249).
"""

from __future__ import annotations

import hashlib
from abc import ABC, abstractmethod
from typing import List, Tuple

__all__ = [
    "PubKey",
    "PrivKey",
    "BatchVerifier",
    "Address",
    "address_hash",
    "register_key_type",
    "pubkey_from_type_and_bytes",
    "pubkey_to_proto",
    "pubkey_from_proto",
]

ADDRESS_SIZE = 20  # tmhash truncated size (reference: crypto/crypto.go:11-19)

Address = bytes


def address_hash(data: bytes) -> Address:
    """sha256(data)[:20] (reference: crypto/crypto.go AddressHash)."""
    return hashlib.sha256(data).digest()[:ADDRESS_SIZE]


class PubKey(ABC):
    @abstractmethod
    def address(self) -> Address: ...

    @abstractmethod
    def bytes(self) -> bytes: ...

    @abstractmethod
    def verify_signature(self, msg: bytes, sig: bytes) -> bool: ...

    @abstractmethod
    def type(self) -> str: ...

    def equals(self, other: "PubKey") -> bool:
        return self.type() == other.type() and self.bytes() == other.bytes()

    def __eq__(self, other: object) -> bool:
        return isinstance(other, PubKey) and self.equals(other)

    def __hash__(self) -> int:
        return hash((self.type(), self.bytes()))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.bytes().hex()[:16]}…)"


class PrivKey(ABC):
    @abstractmethod
    def bytes(self) -> bytes: ...

    @abstractmethod
    def sign(self, msg: bytes) -> bytes: ...

    @abstractmethod
    def pub_key(self) -> PubKey: ...

    @abstractmethod
    def type(self) -> str: ...

    def __repr__(self) -> str:
        # never render key material: reprs reach logs, tracebacks, and
        # debugger output (tmct ct-leak-telemetry lifetime contract)
        return f"<{type(self).__name__} redacted>"


class BatchVerifier(ABC):
    """Accumulate (pk, msg, sig) triples, verify all at once.

    Implementations: CPU per-curve batchers and the TPU-backed verifier in
    tendermint_tpu.crypto.tpu_verifier. Semantics of verify() follow
    reference crypto/crypto.go:53-61: returns (every sig valid, bitmap). The
    bitmap has one entry per add() in order. verify() is one-shot on every
    backend — it drains the queue, and a second call without new add()s
    returns (False, []) (a verifier is one batch, matching the reference's
    one-BatchVerifier-per-commit usage).
    """

    @abstractmethod
    def add(self, pub_key: PubKey, message: bytes, signature: bytes) -> None: ...

    @abstractmethod
    def verify(self) -> Tuple[bool, List[bool]]: ...

    def __len__(self) -> int:  # number of queued items; override if cheap
        raise NotImplementedError


# -- key type registry (reference: crypto/encoding/codec.go + jsontypes) --

_KEY_TYPES: dict[str, type] = {}
_PROTO_FIELD: dict[str, int] = {}  # key type -> PublicKey oneof field number


def register_key_type(key_type: str, pubkey_cls: type, proto_field: int) -> None:
    _KEY_TYPES[key_type] = pubkey_cls
    _PROTO_FIELD[key_type] = proto_field


def pubkey_from_type_and_bytes(key_type: str, data: bytes) -> PubKey:
    cls = _KEY_TYPES.get(key_type)
    if cls is None:
        raise ValueError(f"unknown key type {key_type!r}")
    return cls(data)


# privval key types (reference: privval/file.go:188 GenFilePV's switch —
# ed25519 default, secp256k1 on request). One dispatch for the three
# consumers: FilePVKey.load, FilePV.generate, and the gen-validator CLI.


def _privval_priv_cls(key_type: str) -> type:
    if key_type in ("", "ed25519"):
        from .ed25519 import PrivKeyEd25519

        return PrivKeyEd25519
    if key_type == "secp256k1":
        from .secp256k1 import PrivKeySecp256k1

        return PrivKeySecp256k1
    raise ValueError(f"key type: {key_type} is not supported")


def generate_priv_key(key_type: str = "ed25519") -> PrivKey:
    return _privval_priv_cls(key_type).generate()


def privkey_from_type_and_bytes(key_type: str, data: bytes) -> PrivKey:
    return _privval_priv_cls(key_type)(data)


def pubkey_to_proto(pk: PubKey) -> bytes:
    """Encode as tendermint.crypto.PublicKey (oneof: ed25519=1,
    secp256k1=2, sr25519=3 — reference: proto/tendermint/crypto/keys.pb.go).
    Used verbatim in validator-set hashing (types/validator.go:130)."""
    from ..encoding.proto import ProtoWriter

    field = _PROTO_FIELD.get(pk.type())
    if field is None:
        raise ValueError(f"key type {pk.type()!r} has no proto mapping")
    w = ProtoWriter()
    w.bytes(field, pk.bytes())
    return w.finish()


def pubkey_from_proto(data: bytes) -> PubKey:
    from ..encoding.proto import iter_fields

    for field, _wt, value in iter_fields(data):
        for key_type, f in _PROTO_FIELD.items():
            if f == field:
                return pubkey_from_type_and_bytes(key_type, value)
    raise ValueError("PublicKey proto has no recognized key")
