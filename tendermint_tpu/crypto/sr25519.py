"""sr25519: schnorrkel Schnorr signatures over ristretto255.

Mirrors the reference's sr25519 key type (crypto/sr25519/{privkey,
pubkey,batch}.go, backed by curve25519-voi's schnorrkel-compatible
implementation): MiniSecretKey expansion in Ed25519 mode, merlin
transcript Fiat-Shamir with an empty signing context
(privkey.go:16 NewSigningContext([]byte{})), R||s signatures with the
schnorrkel v1 marker bit, and a BatchVerifier behind the same
crypto.batch seam.

Wire compatibility: the merlin transcript layer reproduces merlin's
published test vector (crypto/merlin.py) and the ristretto encoding
matches RFC 9496's vectors, so signatures produced here follow the
schnorrkel construction exactly.
"""

from __future__ import annotations

import hashlib
import os
from typing import List, Optional, Tuple

from . import ristretto as rst
from .keys import (
    Address,
    BatchVerifier,
    PrivKey,
    PubKey,
    address_hash,
    register_key_type,
)
from .merlin import Transcript

__all__ = [
    "PubKeySr25519",
    "PrivKeySr25519",
    "Sr25519BatchVerifier",
    "KEY_TYPE",
]

KEY_TYPE = "sr25519"
PUBKEY_SIZE = 32
PRIVKEY_SIZE = 32  # MiniSecretKey
SIGNATURE_SIZE = 64
JSON_PUBKEY_NAME = "tendermint/PubKeySr25519"
JSON_PRIVKEY_NAME = "tendermint/PrivKeySr25519"

L = rst.L


_SIGNING_PREFIX: Optional[Transcript] = None


def _basemul_encode(k: int) -> bytes:
    """encode(k*B): native fixed-base multiply when the batch library
    is available (tm_ristretto_basemul — the sign/keygen hot spot),
    else the pure-Python comb. Differential-tested against each other
    in tests/test_sr25519.py."""
    from .. import native

    out = native.ristretto_basemul(int(k).to_bytes(32, "little"))
    if out is not None:
        return out
    # every _basemul_encode caller passes a secret scalar (expanded
    # key in keygen, merlin witness nonce in sign) — CT comb only
    return rst.encode(rst.mul_base_ct(k))


def _signing_transcript(msg: bytes) -> Transcript:
    """signing_context([]).bytes(msg) (reference: privkey.go:16,48).
    The state after the two constant appends is identical for every
    signature, so it is computed once and cloned per call."""
    global _SIGNING_PREFIX
    if _SIGNING_PREFIX is None:
        t = Transcript(b"SigningContext")
        t.append_message(b"", b"")  # empty context
        _SIGNING_PREFIX = t
    t = _SIGNING_PREFIX.clone()
    t.append_message(b"sign-bytes", msg)
    return t


def _challenge(t: Transcript, pk_bytes: bytes, r_bytes: bytes) -> int:
    """The schnorrkel Fiat-Shamir challenge k (sign.rs):
    proto-name, sign:pk, sign:R, then a 512-bit scalar from sign:c."""
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pk_bytes)
    t.append_message(b"sign:R", r_bytes)
    wide = t.challenge_bytes(b"sign:c", 64)
    return int.from_bytes(wide, "little") % L


def challenge_batch(pks, msgs, rs) -> list:
    """Fiat-Shamir challenges for a whole batch: (G, 64)-vectorized
    merlin transcripts per message-length group (crypto/merlin.py
    TranscriptBatch; the STROBE control flow depends only on lengths),
    permuted with one native keccakf_n call per step. Returns one
    scalar int (already reduced mod L) per (pk, msg, R) triple, in
    input order. This is the host-prep fast path for the sr25519
    device verifier (ops/sr25519_kernel.py)."""
    import numpy as np

    from .merlin import TranscriptBatch

    # ensure the cached signing-context prefix exists
    _signing_transcript(b"")
    out: list = [None] * len(msgs)
    groups: dict = {}
    for i, m in enumerate(msgs):
        groups.setdefault(len(m), []).append(i)
    for mlen, idxs in groups.items():
        tb = TranscriptBatch(_SIGNING_PREFIX, len(idxs))
        rows = lambda items, w: np.frombuffer(  # noqa: E731
            b"".join(items), dtype=np.uint8
        ).reshape(len(idxs), w)
        tb.append_messages(
            b"sign-bytes", rows([msgs[i] for i in idxs], mlen)
        )
        tb.append_message_const(b"proto-name", b"Schnorr-sig")
        tb.append_messages(b"sign:pk", rows([pks[i] for i in idxs], 32))
        tb.append_messages(b"sign:R", rows([rs[i] for i in idxs], 32))
        wides = tb.challenge_bytes(b"sign:c", 64)
        for row, i in enumerate(idxs):
            out[i] = (
                int.from_bytes(wides[row].tobytes(), "little") % L
            )
    return out


def _native_verify_one(
    pk_bytes: bytes, msg: bytes, sig: bytes
) -> Optional[bool]:
    """One schnorrkel verify through the whole-batch native entry at
    n=1: parsing, the merlin transcript, and the cofactored equation
    [8](s*B - k*A - R) == identity all in C — which for decoded (2E)
    representatives is exactly ristretto coset equality with
    encode(s*B - k*A) == R, the pure-Python check below. The
    small-batch Straus path makes this ~0.12 ms vs ~6 ms pure Python.
    None when the native kernel is unavailable (caller falls through).

    rc == -1 (undecodable pk/R encoding OR alloc failure) also
    returns None: unlike the batch seam, the caller here IS the
    authoritative per-signature path, so falling through to the
    Python oracle — which rejects undecodable encodings itself — is
    the correct recovery for both causes."""
    import ctypes

    from .. import native

    lib = native.ed25519_batch_lib()
    if lib is None:
        return None
    if len(sig) != SIGNATURE_SIZE:
        return False
    offs = (ctypes.c_uint64 * 2)(0, len(msg))
    rc = lib.tm_sr25519_verify_full(
        pk_bytes, sig, msg, offs, os.urandom(16), 1
    )
    # rc is the verifier's public accept/reject verdict; the urandom
    # argument is the batch equation's public randomizer coin (RLC
    # soundness), not key material
    if rc == 1:  # tmct: ct-ok — public verdict of a public-input verify
        return True
    if rc == 0:  # tmct: ct-ok — public verdict of a public-input verify
        return False
    return None  # undecodable encoding or alloc failure: oracle decides


def _scalar_divide_by_cofactor(b: bytes) -> int:
    """schnorrkel scalars.rs divide_scalar_bytes_by_cofactor: the
    clamped ed25519-style scalar is stored right-shifted by 3 bits."""
    return int.from_bytes(b, "little") >> 3


class PubKeySr25519(PubKey):
    __slots__ = ("_bytes", "_point")

    def __init__(self, data: bytes) -> None:
        if len(data) != PUBKEY_SIZE:
            raise ValueError(f"sr25519 pubkey must be {PUBKEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._point = None  # decoded lazily

    def address(self) -> Address:
        return address_hash(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def type(self) -> str:
        return KEY_TYPE

    def _decode(self):
        if self._point is None:
            self._point = rst.decode(self._bytes)
        return self._point

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        # With the device backend installed AND a real accelerator
        # attached, even a single verify is cheaper as a 1-element
        # kernel batch than through the pure-Python ristretto below
        # (~6 ms/sig — the off-hot-path cost VERDICT r2 flagged for
        # evidence checks and per-vote sr25519 verifies). Routed via
        # the installed factory so the mesh-sharded verifier and the
        # tpu metrics see it like any batch; CPU processes keep the
        # Python path (same results, no backend init, no compile
        # stalls — see tpu_verifier.on_accelerator).
        from .tpu_verifier import single_sr_verifier

        bv = single_sr_verifier()
        if bv is not None:
            if len(sig) != SIGNATURE_SIZE:
                return False
            # Total-predicate contract: this method must never raise —
            # it sits under per-vote and evidence verification. A device
            # fault (XLA failure, lost tunnel, compile error) falls
            # through to the pure-Python ristretto path below, which is
            # semantically identical.
            try:
                bv.add(self, msg, sig)
                _ok, bits = bv.verify()
                # report the DEVICE outcome to the single route's own
                # breaker (verify() contains faults and reports only to
                # the batch "sr25519" breaker): without this, a
                # half-open admission ticket would never be paid back
                # and the route would wedge half-open
                from .tpu_verifier import sr_single_breaker

                if getattr(bv, "faulted", False):
                    sr_single_breaker().record_failure()
                else:
                    sr_single_breaker().record_success()
                return bool(bits and bits[0])
            except Exception as e:
                from ..libs.log import get_logger
                from .tpu_verifier import sr_single_breaker

                # trip the route's breaker: a faulted device must not
                # be re-tried (seconds of error surfacing + a log
                # line) on every subsequent vote. The breaker's
                # single-flight probe re-arms the route after backoff
                # if the fault was transient; a dead device converges
                # to one quiet probe per backoff cap. (verify() itself
                # contains device faults and answers from the CPU
                # factory, so this only fires on failures outside that
                # containment — the total-predicate belt under it.)
                sr_single_breaker().record_failure()
                get_logger("crypto.sr25519").warning(
                    "sr25519 device verify failed; singles tripped to CPU",
                    err=repr(e),
                )
        return self.verify_signature_cpu(msg, sig)

    def verify_signature_cpu(self, msg: bytes, sig: bytes) -> bool:
        """The host-only verify (native C batch entry at n=1, else pure
        Python ristretto) — never touches the device. This is both the
        tail of verify_signature and the oracle the device-fault
        containment layer uses to DISPROVE a device verdict
        (crypto/tpu_verifier.py): an oracle that routed back to the
        device could never catch the device lying."""
        native = _native_verify_one(self._bytes, msg, sig)
        if native is not None:
            return native
        parsed = _parse_signature(sig)
        if parsed is None:
            return False
        r_bytes, s = parsed
        A = self._decode()
        R = rst.decode(r_bytes)
        if A is None or R is None:
            return False
        k = _challenge(_signing_transcript(msg), self._bytes, r_bytes)
        # R' = s*B - k*A; accept iff it encodes back to R's bytes
        # (ristretto encoding is canonical, sign.rs verify)
        neg_k = (L - k) % L
        rp = rst.add(rst.mul_base(s), rst.scalar_mult(neg_k, A))
        return rst.encode(rp) == r_bytes


def _parse_signature(sig: bytes) -> Optional[Tuple[bytes, int]]:
    """R bytes + scalar s; enforces the schnorrkel v1 marker bit
    (sig[63] & 128) and s < L canonicality."""
    if len(sig) != SIGNATURE_SIZE:
        return None
    if not sig[63] & 0x80:
        return None  # pre-v0.1.1 signature without the marker
    s_bytes = bytearray(sig[32:])
    s_bytes[31] &= 0x7F
    s = int.from_bytes(bytes(s_bytes), "little")
    if s >= L:
        return None
    return sig[:32], s


class PrivKeySr25519(PrivKey):
    """MiniSecretKey, expanded in Ed25519 mode (schnorrkel keys.rs
    ExpansionMode::Ed25519 — what curve25519-voi and substrate use)."""

    __slots__ = ("_mini", "_key", "_nonce", "_pub")

    def __init__(self, data: bytes) -> None:
        if len(data) != PRIVKEY_SIZE:
            raise ValueError(f"sr25519 privkey must be {PRIVKEY_SIZE} bytes")
        self._mini = bytes(data)
        h = hashlib.sha512(self._mini).digest()
        key = bytearray(h[:32])
        key[0] &= 248
        key[31] &= 63
        key[31] |= 64
        self._key = _scalar_divide_by_cofactor(bytes(key)) % L
        self._nonce = h[32:]
        self._pub = _basemul_encode(self._key)

    @classmethod
    def generate(cls) -> "PrivKeySr25519":
        return cls(os.urandom(PRIVKEY_SIZE))

    @classmethod
    def from_seed(cls, seed: bytes) -> "PrivKeySr25519":
        return cls(seed)

    def bytes(self) -> bytes:
        return self._mini

    def sign(self, msg: bytes) -> bytes:
        # witness scalar: nonce + message + fresh randomness (the
        # schnorrkel witness construction mixes an external RNG, so the
        # exact bytes are implementation-defined; verification only
        # depends on R and s). The message is bound directly — no
        # transcript clone — so the same construction serves both the
        # native and pure-Python challenge paths below.
        from .. import native

        r_seed = hashlib.sha512(
            b"sr25519-witness" + self._nonce + msg + os.urandom(32)
        ).digest()
        r = int.from_bytes(r_seed, "little") % L
        r_bytes = _basemul_encode(r)
        k_bytes = native.sr25519_challenge(self._pub, r_bytes, msg)
        if k_bytes is not None:
            k = int.from_bytes(k_bytes, "little")
        else:
            k = _challenge(_signing_transcript(msg), self._pub, r_bytes)
        s = (k * self._key + r) % L
        s_bytes = bytearray(int(s).to_bytes(32, "little"))
        s_bytes[31] |= 0x80  # schnorrkel v1 marker
        return r_bytes + bytes(s_bytes)

    def pub_key(self) -> PubKey:
        return PubKeySr25519(self._pub)

    def type(self) -> str:
        return KEY_TYPE


# The native equation wins from n=2 up (Straus small-batch MSM), and
# the bar is LOW here anyway: the sequential fallback is pure-Python
# ristretto at ~6 ms/sig.
_NATIVE_BATCH_MIN = 2


def _native_batch_all_valid(items) -> Optional[bool]:
    """One shot of the schnorrkel batch verification entirely in C
    (native/ed25519_batch.c tm_sr25519_verify_full — the analog of
    schnorrkel's own RLC batch verification, which curve25519-voi wraps
    for the reference's crypto/sr25519/batch.go). Signature parsing,
    merlin transcript challenges (STROBE-128 over Keccak-f in C), the
    random-linear-combination products, and the cofactored equation
    over ristretto decoding all run inside the one native call —
    Python only concatenates the inputs, mirroring the ed25519 path
    (tm_ed25519_verify_full). The RLC randomness is drawn here and
    passed in, so the weights stay under the caller's control.

    True = every signature valid; False = at least one invalid,
    malformed, or undecodable (caller falls back per-signature for the
    bitmap); None = native unavailable."""
    from .. import native
    from .ed25519 import _call_verify_full

    lib = native.ed25519_batch_lib()
    if lib is None:
        return None
    return _call_verify_full(lib.tm_sr25519_verify_full, items)


class Sr25519BatchVerifier(BatchVerifier):
    """CPU batch verifier behind the crypto.batch seam
    (reference: crypto/sr25519/batch.go, backed by curve25519-voi's
    schnorrkel batch). Batches >= _NATIVE_BATCH_MIN go through
    tm_sr25519_verify_full — parsing, merlin challenges, RLC products,
    and the equation all native (~13 us/sig @1024 vs ~6 ms/sig for the
    pure-Python sequential path); on batch failure signatures are
    re-checked one-by-one for the exact bitmap. The device path
    (ops/sr25519_kernel.py) batches the double-scalar multiplications
    on TPU instead."""

    def __init__(self) -> None:
        self._items: List[Tuple[PubKeySr25519, bytes, bytes]] = []

    def add(self, pub_key: PubKey, message: bytes, signature: bytes) -> None:
        if not isinstance(pub_key, PubKeySr25519):
            raise TypeError("Sr25519BatchVerifier requires sr25519 keys")
        if len(signature) != SIGNATURE_SIZE:
            raise ValueError("malformed signature size")
        self._items.append((pub_key, bytes(message), bytes(signature)))

    def verify(self) -> Tuple[bool, List[bool]]:
        """One-shot: drains the queue (same contract as the device and
        ed25519 CPU verifiers — see Ed25519BatchVerifier.verify)."""
        if not self._items:
            return False, []
        items, self._items = self._items, []
        if len(items) >= _NATIVE_BATCH_MIN:
            if _native_batch_all_valid(items) is True:
                return True, [True] * len(items)
            # invalid somewhere (or native unavailable): fall through
            # to per-signature verification for the exact bitmap
        bitmap = [
            pk.verify_signature(msg, sig) for pk, msg, sig in items
        ]
        return all(bitmap), bitmap

    def __len__(self) -> int:
        return len(self._items)


register_key_type(KEY_TYPE, PubKeySr25519, proto_field=3)
