"""Injectable fault plane — seeded, scoped chaos for unreliable edges.

The north star puts consensus-critical crypto on an accelerator, which
makes the dispatch/gather boundary of crypto/tpu_verifier.py a new
Byzantine surface: the XLA runtime can raise, the device (or its
tunnel) can wedge, and a mis-compiled or mis-sharded program can return
wrong-shaped or bit-flipped results. Tendermint tolerates 1/3 Byzantine
validators; this module exists so the test suite can prove the port
tolerates Byzantine *devices* too — the same treat-the-offload-engine-
as-unreliable stance as the FPGA ECDSA engine (arXiv:2112.02229) and
the committee-consensus measurements (arXiv:2302.00418), both of which
keep a mandatory software fallback.

Fault points are NAMED strings consulted at the boundary they model:

    tpu.dispatch   crypto/tpu_verifier.py, before every device launch
    tpu.gather     crypto/tpu_verifier.py, inside the gather barrier
    wal.write      consensus/wal.py, the framed append (short writes)
    wal.fsync      consensus/wal.py, every fsync (rotation included)
    privval.save   privval/file.py, the last-sign-state checkpoint
                   write (io_error = fsync failure, raise = crash
                   before persist), keyed by the node home's basename
    privval.release privval/file.py, between the last-sign-state fsync
                   and the signature leaving the signer — a raise here
                   IS the SIGKILL-between-sign-and-send arc the
                   double-sign invariant is proven across (same key)
    rpc.route      rpc/jsonrpc.py _dispatch, keyed by method name —
                   inside the per-route latency measurement, so an
                   injected hang produces an honest SLO-breach
                   exemplar and an injected raise exercises the
                   error-counting path (loadgen smoke tests)
    p2p.send       p2p/router.py _send_peer, keyed (src, dst, ch) —
                   outbound link faults per asymmetric direction and
                   channel
    p2p.recv       p2p/router.py _recv_peer, keyed (src, dst, ch) —
                   inbound link faults (src = the remote peer)
    p2p.dial       p2p/transport.py dial(), keyed (src, dst) — the
                   connection-establishment boundary

Modes (the fault taxonomy, docs/resilience.md):

    raise       the point raises DeviceFault (an XlaRuntimeError-alike)
    hang        the point sleeps `hang_s` — under the gather deadline
                watchdog this surfaces as DeviceTimeout
    misshape    mangle() drops a result lane (wrong-shaped output)
    bitflip     mangle() inverts one result lane (silent corruption)
    io_error    the point raises OSError (fsync failure)
    short_write clip() truncates the buffer (torn record on crash)

Network modes (consulted via net_plan(), interpreted by the p2p
router/transport — the plane never sleeps the event loop itself):

    drop        the message / dial is discarded (packet loss)
    delay       the caller sleeps `delay_s` before proceeding (latency)
    duplicate   the message is delivered `dup` extra times (gossip echo)
    reorder     the message is held and swapped behind its successor
                (the send side only parks a frame when a successor is
                already queued; a recv-side hold is flushed after
                0.5 s if no successor arrives — so on an idle link
                reorder delays, it never silently drops)

Network rules take extra (src, dst, ch) filters so asymmetric links
and channel-targeted loss are expressible:

    TM_TPU_FAULT="p2p.send:drop:p=0.4:seed=7:src=load0:dst=load1:ch=34"

`src`/`dst` match a node's net labels (moniker, node ID, listen host)
exactly, or as a prefix when the member is >= 8 chars (node-ID
prefixes). On top of per-message rules, named PARTITION SETS cut whole
links: `TM_TPU_PARTITION="load0,load1|load2,load3"` blocks every
send/recv/dial between members of different groups (members in no
group are unaffected). The partition is runtime-mutable —
`set_partition()` in-process, or point TM_TPU_PARTITION_FILE at a
file whose content is re-read on change (throttled stat), so a chaos
scenario can HEAL a partition mid-run, including across process
boundaries (the e2e process-net runner uses the file form).

Every rule owns a `random.Random(seed)`, so whether a given consult
fires is a pure function of (seed, consult index) — chaos runs
reproduce exactly, the same way libs/schedulefuzz.py seeds orderings.
Rules are scoped: the `inject()` context manager removes its rule on
exit, and `TM_TPU_FAULT` arms rules process-wide for black-box runs:

    TM_TPU_FAULT="tpu.dispatch:raise:p=0.3:seed=7;tpu.gather:hang:hang_s=0.5"

The hot path pays one module-global boolean (`armed()`) when the plane
is empty — production traffic never touches a rule list.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from typing import List, Optional

__all__ = [
    "DeviceFault",
    "DeviceTimeout",
    "NetPlan",
    "Rule",
    "armed",
    "clip",
    "fire",
    "inject",
    "load_env",
    "mangle",
    "net_armed",
    "net_plan",
    "partition_blocked",
    "partition_spec",
    "reset",
    "rules",
    "set_partition",
]


class DeviceFault(RuntimeError):
    """A device dispatch/gather failed — the XlaRuntimeError-alike the
    fault plane raises, and the type crypto/tpu_verifier.py uses for
    faults it detects itself (mis-shaped results, disproven lanes)."""


class DeviceTimeout(DeviceFault):
    """A gather exceeded its deadline (hung device / lost tunnel)."""


_RAISE_MODES = {"raise", "io_error"}
_DATA_MODES = {"misshape", "bitflip"}
_CLIP_MODES = {"short_write"}
_NET_MODES = {"drop", "delay", "duplicate", "reorder"}
_ALL_MODES = (
    _RAISE_MODES | _DATA_MODES | _CLIP_MODES | _NET_MODES | {"hang"}
)


class Rule:
    """One armed fault: a point pattern, a mode, and a seeded RNG that
    decides — reproducibly — which consults fire."""

    def __init__(
        self,
        point: str,
        mode: str,
        p: float = 1.0,
        seed: int = 0,
        times: Optional[int] = None,
        hang_s: float = 30.0,
        key: Optional[str] = None,
        src: Optional[str] = None,
        dst: Optional[str] = None,
        ch: Optional[int] = None,
        delay_s: float = 0.05,
        dup: int = 1,
    ) -> None:
        if mode not in _ALL_MODES:
            raise ValueError(f"unknown fault mode {mode!r}")
        self.point = point
        self.mode = mode
        self.p = float(p)
        self.seed = int(seed)
        self.times = times  # None = unlimited
        self.hang_s = float(hang_s)
        self.key = key  # key-type filter for tpu points (None = any)
        # network filters/knobs (p2p.* points; None = match any)
        self.src = src
        self.dst = dst
        self.ch = int(ch) if ch is not None else None
        self.delay_s = float(delay_s)
        self.dup = int(dup)
        self.rng = random.Random(self.seed)
        self.fired = 0  # consults that actually faulted

    def _matches(self, point: str, key: Optional[str]) -> bool:
        if self.point != point:
            return False
        if self.key is not None and key is not None and self.key != key:
            return False
        return True

    def _matches_net(
        self,
        point: str,
        src_labels: tuple,
        dst_labels: tuple,
        ch: Optional[int],
    ) -> bool:
        if self.point != point:
            return False
        if self.ch is not None and ch is not None and self.ch != ch:
            return False
        if self.src is not None and not _label_match(self.src, src_labels):
            return False
        if self.dst is not None and not _label_match(self.dst, dst_labels):
            return False
        return True

    def _roll(self) -> bool:
        """One seeded decision. The RNG advances on every matching
        consult — fired or not — so the fire pattern depends only on
        (seed, consult index), never on wall time."""
        if self.times is not None and self.fired >= self.times:
            return False
        if self.p < 1.0 and self.rng.random() >= self.p:
            return False
        self.fired += 1
        return True

    def __repr__(self) -> str:  # failure messages name the seed
        return (
            f"Rule({self.point}:{self.mode} p={self.p} seed={self.seed} "
            f"fired={self.fired})"
        )


_HEX_DIGITS = frozenset("0123456789abcdef")


def _label_match(member: str, labels: tuple) -> bool:
    """A spec member names a node if it equals one of the node's net
    labels exactly, or — ONLY when the member looks like a node-ID
    prefix (>= 8 lowercase hex chars) — prefixes one. Monikers and
    hosts match exactly, so "validator1" can never swallow
    "validator10"; node IDs are 40-char hex and an 8+-char prefix is
    unambiguous in any real deployment."""
    id_prefix = len(member) >= 8 and all(c in _HEX_DIGITS for c in member)
    for label in labels:
        if member == label:
            return True
        if id_prefix and label.startswith(member):
            return True
    return False


class NetPlan:
    """The combined verdict of every fired network rule at one consult:
    what the router should do with this message/dial."""

    __slots__ = ("drop", "delay_s", "dup", "reorder")

    def __init__(self) -> None:
        self.drop = False
        self.delay_s = 0.0
        self.dup = 0  # EXTRA copies to deliver
        self.reorder = False

    def __repr__(self) -> str:
        return (
            f"NetPlan(drop={self.drop} delay_s={self.delay_s} "
            f"dup={self.dup} reorder={self.reorder})"
        )


_RULES: List[Rule] = []
_LOCK = threading.Lock()
_ARMED = False  # mirrors bool(_RULES); read lock-free on hot paths
_NET_ARMED = False  # p2p rules or a live/file partition; ditto
_ENV_LOADED = False
# named partition sets: groups of net-label members; links between
# members of DIFFERENT groups are cut, everything else flows.
# tmlive: bounded= replaced wholesale by set_partition (size = the
# operator's parsed spec), never grown incrementally
_PARTITION: List[List[str]] = []
_PARTITION_SPEC = ""
_PARTITION_FILE: Optional[str] = None
_PARTITION_FILE_SIG: Optional[tuple] = None  # (mtime_ns, size)
_PARTITION_NEXT_POLL = 0.0
_PARTITION_POLL_S = 0.2  # stat() throttle for the file form


def armed() -> bool:
    """Cheap hot-path gate: False means no rule is armed and no fault
    code runs at all. The env var is parsed on the first call so test
    processes that set TM_TPU_FAULT after import still arm."""
    if not _ENV_LOADED:
        # load_env sets the latch under _LOCK only AFTER the rules are
        # parsed and _ARMED refreshed: a racing caller either sees the
        # latch down and blocks on _LOCK itself, or sees it up with the
        # armed state already published (tmrace found the old
        # flag-first ordering, where a racer could answer False between
        # the flag write and the parse)
        load_env()
    return _ARMED


def net_armed() -> bool:
    """Cheap hot-path gate for the p2p fault points: False means no
    network rule or partition is live and the router/transport run
    fault-free code only (same contract as armed())."""
    if not _ENV_LOADED:
        load_env()
    return _NET_ARMED


def load_env() -> None:
    """(Re-)parse TM_TPU_FAULT into armed rules (and TM_TPU_PARTITION /
    TM_TPU_PARTITION_FILE into the partition state). Idempotent per
    value: clears previously env-loaded rules first (inject() rules
    survive)."""
    global _ENV_LOADED, _PARTITION_SPEC, _PARTITION_FILE
    global _PARTITION_FILE_SIG, _PARTITION_NEXT_POLL
    spec = os.environ.get("TM_TPU_FAULT", "")
    with _LOCK:
        _RULES[:] = [r for r in _RULES if not getattr(r, "_from_env", False)]
        try:
            # partition env FIRST: a malformed TM_TPU_FAULT must not
            # strip the partition plane as collateral (an e2e child
            # whose partition file silently never armed would measure
            # an un-partitioned net)
            _PARTITION[:] = _parse_partition(
                os.environ.get("TM_TPU_PARTITION", "")
            )
            _PARTITION_SPEC = os.environ.get("TM_TPU_PARTITION", "")
            _PARTITION_FILE = (
                os.environ.get("TM_TPU_PARTITION_FILE") or None
            )
            _PARTITION_FILE_SIG = None
            _PARTITION_NEXT_POLL = 0.0
            parsed = []
            for part in spec.split(";"):
                part = part.strip()
                if not part:
                    continue
                rule = _parse_rule(part)
                rule._from_env = True
                parsed.append(rule)
            _RULES.extend(parsed)
        finally:
            # latch + refresh even when a malformed spec raises: the
            # ValueError surfaces ONCE (from the first armed() call),
            # after which the plane runs disarmed — without this, every
            # hot-path armed() check re-enters the parse and re-raises
            # forever. parsed is appended all-or-nothing so a spec
            # that fails mid-list arms none of its rules.
            _refresh_armed()
            _ENV_LOADED = True


def _parse_rule(spec: str) -> Rule:
    """`point:mode[:p=..][:seed=..][:times=..][:hang_s=..][:key=..]
    [:src=..][:dst=..][:ch=..][:delay_s=..][:dup=..]`"""
    fields = spec.split(":")
    if len(fields) < 2:
        raise ValueError(f"bad TM_TPU_FAULT rule {spec!r} (want point:mode)")
    kwargs = {}
    for opt in fields[2:]:
        if "=" not in opt:
            raise ValueError(f"bad fault option {opt!r} in {spec!r}")
        k, v = opt.split("=", 1)
        if k == "p":
            kwargs["p"] = float(v)
        elif k == "seed":
            kwargs["seed"] = int(v)
        elif k == "times":
            kwargs["times"] = int(v)
        elif k == "hang_s":
            kwargs["hang_s"] = float(v)
        elif k == "key":
            kwargs["key"] = v
        elif k == "src":
            kwargs["src"] = v
        elif k == "dst":
            kwargs["dst"] = v
        elif k == "ch":
            kwargs["ch"] = int(v)
        elif k == "delay_s":
            kwargs["delay_s"] = float(v)
        elif k == "dup":
            kwargs["dup"] = int(v)
        else:
            raise ValueError(f"unknown fault option {k!r} in {spec!r}")
    return Rule(fields[0], fields[1], **kwargs)


def _parse_partition(spec: str) -> List[List[str]]:
    """`"a,b|c,d"` → [[a, b], [c, d]]. Empty spec = no partition."""
    groups: List[List[str]] = []
    for part in spec.split("|"):
        members = [m.strip() for m in part.split(",") if m.strip()]
        if members:
            groups.append(members)
    return groups


def _refresh_armed() -> None:
    global _ARMED, _NET_ARMED
    _ARMED = bool(_RULES)
    _NET_ARMED = (
        bool(_PARTITION)
        or _PARTITION_FILE is not None
        or any(r.point.startswith("p2p.") for r in _RULES)
    )


@contextlib.contextmanager
def inject(
    point: str,
    mode: str,
    p: float = 1.0,
    seed: int = 0,
    times: Optional[int] = None,
    hang_s: float = 30.0,
    key: Optional[str] = None,
    src: Optional[str] = None,
    dst: Optional[str] = None,
    ch: Optional[int] = None,
    delay_s: float = 0.05,
    dup: int = 1,
):
    """Arm one rule for the duration of the scope (chaos tests). Yields
    the Rule so the test can assert how often it actually fired."""
    rule = Rule(point, mode, p=p, seed=seed, times=times,
                hang_s=hang_s, key=key, src=src, dst=dst, ch=ch,
                delay_s=delay_s, dup=dup)
    with _LOCK:
        _RULES.append(rule)
        _refresh_armed()
    try:
        yield rule
    finally:
        with _LOCK:
            try:
                _RULES.remove(rule)
            except ValueError:  # pragma: no cover - double-removal
                pass
            _refresh_armed()


def reset() -> None:
    """Disarm everything — rules AND partition state (tests)."""
    global _PARTITION_SPEC, _PARTITION_FILE, _PARTITION_FILE_SIG
    with _LOCK:
        _RULES.clear()
        _PARTITION.clear()
        _PARTITION_SPEC = ""
        _PARTITION_FILE = None
        _PARTITION_FILE_SIG = None
        _refresh_armed()


def set_partition(spec: str) -> None:
    """Install (or with "" heal) the named partition sets at runtime —
    the in-process half of the runtime-mutable contract; process nets
    mutate via TM_TPU_PARTITION_FILE instead."""
    global _PARTITION_SPEC
    if not _ENV_LOADED:
        # latch the env first or a later lazy load_env() would clobber
        # the runtime spec with the (stale) env value
        load_env()
    groups = _parse_partition(spec)
    with _LOCK:
        _PARTITION[:] = groups
        _PARTITION_SPEC = spec
        _refresh_armed()


def partition_spec() -> str:
    """The currently installed spec (diagnostics/tests)."""
    with _LOCK:
        return _PARTITION_SPEC


def _poll_partition_file_locked() -> None:
    """File form of the runtime-mutable partition: re-read the spec
    when the file changes, stat()ing at most every _PARTITION_POLL_S.
    Callers hold _LOCK."""
    global _PARTITION_FILE_SIG, _PARTITION_NEXT_POLL, _PARTITION_SPEC
    now = time.monotonic()
    if now < _PARTITION_NEXT_POLL:
        return
    _PARTITION_NEXT_POLL = now + _PARTITION_POLL_S
    try:
        st = os.stat(_PARTITION_FILE)
        sig = (st.st_mtime_ns, st.st_size)
        if sig == _PARTITION_FILE_SIG:
            return
        with open(_PARTITION_FILE, "r") as f:
            spec = f.read().strip()
        _PARTITION_FILE_SIG = sig
    except OSError:
        # missing/unreadable file = no partition (a scenario that
        # deletes the file heals the net)
        _PARTITION_FILE_SIG = None
        spec = ""
    _PARTITION[:] = _parse_partition(spec)
    _PARTITION_SPEC = spec


def _group_of(labels: tuple) -> Optional[int]:
    for i, group in enumerate(_PARTITION):
        for member in group:
            if _label_match(member, labels):
                return i
    return None


def partition_blocked(src_labels: tuple, dst_labels: tuple) -> bool:
    """True when the live partition cuts the src→dst link: both
    endpoints are named, in different groups. Callers gate on
    net_armed()."""
    with _LOCK:
        if _PARTITION_FILE is not None:
            _poll_partition_file_locked()
        if not _PARTITION:
            return False
        a = _group_of(src_labels)
        if a is None:
            return False
        b = _group_of(dst_labels)
        return b is not None and a != b


def net_plan(
    point: str,
    src: tuple = (),
    dst: tuple = (),
    ch: Optional[int] = None,
) -> Optional[NetPlan]:
    """Consult the network rules at a p2p fault point. Returns None
    when nothing fired (the common armed-but-filtered case), else the
    combined NetPlan. The plane never sleeps or raises here — the
    router/transport interpret the plan (delay via asyncio.sleep, so
    the event loop is never blocked). Each matching rule's seeded RNG
    advances exactly once per consult, fired or not, so the fault
    schedule is a pure function of (seed, consult index)."""
    plan: Optional[NetPlan] = None
    with _LOCK:
        for r in _RULES:
            if r.mode not in _NET_MODES:
                continue
            if not r._matches_net(point, src, dst, ch):
                continue
            if not r._roll():
                continue
            if plan is None:
                plan = NetPlan()
            if r.mode == "drop":
                plan.drop = True
            elif r.mode == "delay":
                plan.delay_s = max(plan.delay_s, r.delay_s)
            elif r.mode == "duplicate":
                plan.dup += max(r.dup, 0)
            elif r.mode == "reorder":
                plan.reorder = True
    return plan


def rules() -> List[Rule]:
    """Snapshot of the armed rules (diagnostics/tests)."""
    with _LOCK:
        return list(_RULES)


def fire(point: str, key: Optional[str] = None) -> None:
    """Consult the plane at a control-flow fault point. May raise
    (`raise` → DeviceFault, `io_error` → OSError) or stall (`hang`);
    data modes are left to mangle()/clip(). Callers gate on armed()."""
    with _LOCK:
        actions = [
            r for r in _RULES
            if r.mode in ("raise", "hang", "io_error")
            and r._matches(point, key) and r._roll()
        ]
    for r in actions:
        if r.mode == "raise":
            raise DeviceFault(
                f"injected device fault at {point} (seed={r.seed})"
            )
        if r.mode == "io_error":
            raise OSError(
                f"injected I/O fault at {point} (seed={r.seed})"
            )
        if r.mode == "hang":
            # tmlive: block-ok — the injected hang IS the fault under
            # test: it simulates a wedged device/disk so the watchdog,
            # breaker and chaos suites can prove containment; duration
            # is the rule's hang_s, chosen by the test, and the plane
            # is never armed in production (TM_TPU_FAULT unset)
            time.sleep(r.hang_s)


def mangle(point: str, bits: list, key: Optional[str] = None) -> list:
    """Apply data faults to a gather result: `misshape` drops the last
    lane (wrong-shaped device output), `bitflip` inverts one seeded
    lane (silent result corruption). Returns the (possibly) mangled
    bitmap; the containment layer must detect and recover."""
    with _LOCK:
        actions = [
            r for r in _RULES
            if r.mode in _DATA_MODES and r._matches(point, key) and r._roll()
        ]
    for r in actions:
        if r.mode == "misshape" and bits:
            bits = bits[:-1]
        elif r.mode == "bitflip" and bits:
            i = r.rng.randrange(len(bits))
            bits = list(bits)
            bits[i] = not bits[i]
    return bits


def clip(point: str, data: bytes) -> bytes:
    """Apply a `short_write` fault: return a strict seeded prefix of
    `data` — the shape a crash mid-write leaves on disk."""
    with _LOCK:
        actions = [
            r for r in _RULES
            if r.mode in _CLIP_MODES and r._matches(point, None) and r._roll()
        ]
    for r in actions:
        data = data[: r.rng.randrange(len(data))] if data else data
    return data
