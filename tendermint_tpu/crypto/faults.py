"""Injectable fault plane — seeded, scoped chaos for unreliable edges.

The north star puts consensus-critical crypto on an accelerator, which
makes the dispatch/gather boundary of crypto/tpu_verifier.py a new
Byzantine surface: the XLA runtime can raise, the device (or its
tunnel) can wedge, and a mis-compiled or mis-sharded program can return
wrong-shaped or bit-flipped results. Tendermint tolerates 1/3 Byzantine
validators; this module exists so the test suite can prove the port
tolerates Byzantine *devices* too — the same treat-the-offload-engine-
as-unreliable stance as the FPGA ECDSA engine (arXiv:2112.02229) and
the committee-consensus measurements (arXiv:2302.00418), both of which
keep a mandatory software fallback.

Fault points are NAMED strings consulted at the boundary they model:

    tpu.dispatch   crypto/tpu_verifier.py, before every device launch
    tpu.gather     crypto/tpu_verifier.py, inside the gather barrier
    wal.write      consensus/wal.py, the framed append (short writes)
    wal.fsync      consensus/wal.py, every fsync (rotation included)
    rpc.route      rpc/jsonrpc.py _dispatch, keyed by method name —
                   inside the per-route latency measurement, so an
                   injected hang produces an honest SLO-breach
                   exemplar and an injected raise exercises the
                   error-counting path (loadgen smoke tests)

Modes (the fault taxonomy, docs/resilience.md):

    raise       the point raises DeviceFault (an XlaRuntimeError-alike)
    hang        the point sleeps `hang_s` — under the gather deadline
                watchdog this surfaces as DeviceTimeout
    misshape    mangle() drops a result lane (wrong-shaped output)
    bitflip     mangle() inverts one result lane (silent corruption)
    io_error    the point raises OSError (fsync failure)
    short_write clip() truncates the buffer (torn record on crash)

Every rule owns a `random.Random(seed)`, so whether a given consult
fires is a pure function of (seed, consult index) — chaos runs
reproduce exactly, the same way libs/schedulefuzz.py seeds orderings.
Rules are scoped: the `inject()` context manager removes its rule on
exit, and `TM_TPU_FAULT` arms rules process-wide for black-box runs:

    TM_TPU_FAULT="tpu.dispatch:raise:p=0.3:seed=7;tpu.gather:hang:hang_s=0.5"

The hot path pays one module-global boolean (`armed()`) when the plane
is empty — production traffic never touches a rule list.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from typing import List, Optional

__all__ = [
    "DeviceFault",
    "DeviceTimeout",
    "Rule",
    "armed",
    "clip",
    "fire",
    "inject",
    "load_env",
    "mangle",
    "reset",
    "rules",
]


class DeviceFault(RuntimeError):
    """A device dispatch/gather failed — the XlaRuntimeError-alike the
    fault plane raises, and the type crypto/tpu_verifier.py uses for
    faults it detects itself (mis-shaped results, disproven lanes)."""


class DeviceTimeout(DeviceFault):
    """A gather exceeded its deadline (hung device / lost tunnel)."""


_RAISE_MODES = {"raise", "io_error"}
_DATA_MODES = {"misshape", "bitflip"}
_CLIP_MODES = {"short_write"}
_ALL_MODES = _RAISE_MODES | _DATA_MODES | _CLIP_MODES | {"hang"}


class Rule:
    """One armed fault: a point pattern, a mode, and a seeded RNG that
    decides — reproducibly — which consults fire."""

    def __init__(
        self,
        point: str,
        mode: str,
        p: float = 1.0,
        seed: int = 0,
        times: Optional[int] = None,
        hang_s: float = 30.0,
        key: Optional[str] = None,
    ) -> None:
        if mode not in _ALL_MODES:
            raise ValueError(f"unknown fault mode {mode!r}")
        self.point = point
        self.mode = mode
        self.p = float(p)
        self.seed = int(seed)
        self.times = times  # None = unlimited
        self.hang_s = float(hang_s)
        self.key = key  # key-type filter for tpu points (None = any)
        self.rng = random.Random(self.seed)
        self.fired = 0  # consults that actually faulted

    def _matches(self, point: str, key: Optional[str]) -> bool:
        if self.point != point:
            return False
        if self.key is not None and key is not None and self.key != key:
            return False
        return True

    def _roll(self) -> bool:
        """One seeded decision. The RNG advances on every matching
        consult — fired or not — so the fire pattern depends only on
        (seed, consult index), never on wall time."""
        if self.times is not None and self.fired >= self.times:
            return False
        if self.p < 1.0 and self.rng.random() >= self.p:
            return False
        self.fired += 1
        return True

    def __repr__(self) -> str:  # failure messages name the seed
        return (
            f"Rule({self.point}:{self.mode} p={self.p} seed={self.seed} "
            f"fired={self.fired})"
        )


_RULES: List[Rule] = []
_LOCK = threading.Lock()
_ARMED = False  # mirrors bool(_RULES); read lock-free on hot paths
_ENV_LOADED = False


def armed() -> bool:
    """Cheap hot-path gate: False means no rule is armed and no fault
    code runs at all. The env var is parsed on the first call so test
    processes that set TM_TPU_FAULT after import still arm."""
    if not _ENV_LOADED:
        # load_env sets the latch under _LOCK only AFTER the rules are
        # parsed and _ARMED refreshed: a racing caller either sees the
        # latch down and blocks on _LOCK itself, or sees it up with the
        # armed state already published (tmrace found the old
        # flag-first ordering, where a racer could answer False between
        # the flag write and the parse)
        load_env()
    return _ARMED


def load_env() -> None:
    """(Re-)parse TM_TPU_FAULT into armed rules. Idempotent per value:
    clears previously env-loaded rules first (inject() rules survive)."""
    global _ENV_LOADED
    spec = os.environ.get("TM_TPU_FAULT", "")
    with _LOCK:
        _RULES[:] = [r for r in _RULES if not getattr(r, "_from_env", False)]
        try:
            parsed = []
            for part in spec.split(";"):
                part = part.strip()
                if not part:
                    continue
                rule = _parse_rule(part)
                rule._from_env = True
                parsed.append(rule)
            _RULES.extend(parsed)
        finally:
            # latch + refresh even when a malformed spec raises: the
            # ValueError surfaces ONCE (from the first armed() call),
            # after which the plane runs disarmed — without this, every
            # hot-path armed() check re-enters the parse and re-raises
            # forever. parsed is appended all-or-nothing so a spec
            # that fails mid-list arms none of its rules.
            _refresh_armed()
            _ENV_LOADED = True


def _parse_rule(spec: str) -> Rule:
    """`point:mode[:p=..][:seed=..][:times=..][:hang_s=..][:key=..]`"""
    fields = spec.split(":")
    if len(fields) < 2:
        raise ValueError(f"bad TM_TPU_FAULT rule {spec!r} (want point:mode)")
    kwargs = {}
    for opt in fields[2:]:
        if "=" not in opt:
            raise ValueError(f"bad fault option {opt!r} in {spec!r}")
        k, v = opt.split("=", 1)
        if k == "p":
            kwargs["p"] = float(v)
        elif k == "seed":
            kwargs["seed"] = int(v)
        elif k == "times":
            kwargs["times"] = int(v)
        elif k == "hang_s":
            kwargs["hang_s"] = float(v)
        elif k == "key":
            kwargs["key"] = v
        else:
            raise ValueError(f"unknown fault option {k!r} in {spec!r}")
    return Rule(fields[0], fields[1], **kwargs)


def _refresh_armed() -> None:
    global _ARMED
    _ARMED = bool(_RULES)


@contextlib.contextmanager
def inject(
    point: str,
    mode: str,
    p: float = 1.0,
    seed: int = 0,
    times: Optional[int] = None,
    hang_s: float = 30.0,
    key: Optional[str] = None,
):
    """Arm one rule for the duration of the scope (chaos tests). Yields
    the Rule so the test can assert how often it actually fired."""
    rule = Rule(point, mode, p=p, seed=seed, times=times,
                hang_s=hang_s, key=key)
    with _LOCK:
        _RULES.append(rule)
        _refresh_armed()
    try:
        yield rule
    finally:
        with _LOCK:
            try:
                _RULES.remove(rule)
            except ValueError:  # pragma: no cover - double-removal
                pass
            _refresh_armed()


def reset() -> None:
    """Disarm everything (tests)."""
    with _LOCK:
        _RULES.clear()
        _refresh_armed()


def rules() -> List[Rule]:
    """Snapshot of the armed rules (diagnostics/tests)."""
    with _LOCK:
        return list(_RULES)


def fire(point: str, key: Optional[str] = None) -> None:
    """Consult the plane at a control-flow fault point. May raise
    (`raise` → DeviceFault, `io_error` → OSError) or stall (`hang`);
    data modes are left to mangle()/clip(). Callers gate on armed()."""
    with _LOCK:
        actions = [
            r for r in _RULES
            if r.mode in ("raise", "hang", "io_error")
            and r._matches(point, key) and r._roll()
        ]
    for r in actions:
        if r.mode == "raise":
            raise DeviceFault(
                f"injected device fault at {point} (seed={r.seed})"
            )
        if r.mode == "io_error":
            raise OSError(
                f"injected I/O fault at {point} (seed={r.seed})"
            )
        if r.mode == "hang":
            # tmlive: block-ok — the injected hang IS the fault under
            # test: it simulates a wedged device/disk so the watchdog,
            # breaker and chaos suites can prove containment; duration
            # is the rule's hang_s, chosen by the test, and the plane
            # is never armed in production (TM_TPU_FAULT unset)
            time.sleep(r.hang_s)


def mangle(point: str, bits: list, key: Optional[str] = None) -> list:
    """Apply data faults to a gather result: `misshape` drops the last
    lane (wrong-shaped device output), `bitflip` inverts one seeded
    lane (silent result corruption). Returns the (possibly) mangled
    bitmap; the containment layer must detect and recover."""
    with _LOCK:
        actions = [
            r for r in _RULES
            if r.mode in _DATA_MODES and r._matches(point, key) and r._roll()
        ]
    for r in actions:
        if r.mode == "misshape" and bits:
            bits = bits[:-1]
        elif r.mode == "bitflip" and bits:
            i = r.rng.randrange(len(bits))
            bits = list(bits)
            bits[i] = not bits[i]
    return bits


def clip(point: str, data: bytes) -> bytes:
    """Apply a `short_write` fault: return a strict seeded prefix of
    `data` — the shape a crash mid-write leaves on disk."""
    with _LOCK:
        actions = [
            r for r in _RULES
            if r.mode in _CLIP_MODES and r._matches(point, None) and r._roll()
        ]
    for r in actions:
        data = data[: r.rng.randrange(len(data))] if data else data
    return data
