"""Per-route circuit breakers for the device offload paths.

Generalizes (and replaces) the ad-hoc `trip_sr_singles`/`_SR_WARM`
machinery that guarded only the sr25519 single-verify route: every
device entry point — the ed25519/sr25519 batch factories, the sr25519
single route, streaming chunk dispatch — consults a named breaker, and
a tripped breaker routes new work to the CPU factories with zero
per-call warnings or device touches.

State machine (docs/resilience.md has the full diagram):

    CLOSED ──failure──▶ OPEN ──backoff elapsed──▶ HALF_OPEN
      ▲                  ▲                            │
      │                  └────────probe failed────────┤
      └───────────────────probe succeeded─────────────┘

Policy, inherited from the machinery it replaces (the device-claim
discipline in PERF.md — "never pile onto a wedged claim"):

- OPEN serves every caller a CPU fallback instantly; nobody waits.
- Re-arming is probed by ONE background thread, never by consensus
  traffic: when a probe fn is configured, `allow()` keeps answering
  False through HALF_OPEN and the single-flight probe decides. A
  breaker without a probe fn instead hands exactly one caller a
  HALF_OPEN ticket (classic half-open admission).
- Backoff is exponential (base × 2^(trips-1), capped), so a dead
  device converges to one cheap probe per cap interval — no retry
  storm, bounded probe count.

Instruments (DEFAULT_REGISTRY, process-global like the tpu_* family):
`breaker_state{name=}` gauge (0 closed / 1 open / 2 half-open),
`breaker_trips_total{name=}`, `breaker_probes_total{name=}`.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from ..libs import metrics as M

__all__ = [
    "CLOSED",
    "OPEN",
    "HALF_OPEN",
    "CircuitBreaker",
    "breaker_for",
    "discard",
    "fresh",
    "reset_all",
]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

_m_state = M.new_gauge(
    "breaker", "state",
    "Circuit-breaker state (0 closed, 1 open, 2 half-open).",
    label_names=("name",),
)
_m_trips = M.new_counter(
    "breaker", "trips_total",
    "Circuit-breaker transitions into OPEN.",
    label_names=("name",),
)
_m_probes = M.new_counter(
    "breaker", "probes_total",
    "Circuit-breaker re-arm probes launched.",
    label_names=("name",),
)


def _env_backoff(default: float) -> float:
    try:
        return float(os.environ.get("TM_TPU_BREAKER_BACKOFF_S", default))
    except ValueError:  # pragma: no cover - operator typo
        return default


class CircuitBreaker:
    """One route's breaker. Thread-safe; cheap when CLOSED (one lock +
    one compare per allow())."""

    def __init__(
        self,
        name: str,
        backoff_base_s: Optional[float] = None,
        backoff_max_s: float = 300.0,
        probe: Optional[Callable[[], bool]] = None,
        start_open: bool = False,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.name = name
        self.backoff_base_s = (
            _env_backoff(10.0) if backoff_base_s is None else backoff_base_s
        )
        self.backoff_max_s = backoff_max_s
        self._probe_fn = probe
        self._clock = clock
        self._lock = threading.Lock()
        self._state = OPEN if start_open else CLOSED
        self._trips = 0  # consecutive OPEN entries (backoff exponent)
        # a cold (start_open) breaker waits a full base backoff before
        # admitting any caller-probe: only probe_now() — install()'s
        # deliberate warm-up — may touch the device sooner
        self._retry_at = self._clock() + (
            self.backoff_base_s if start_open else 0.0
        )
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_timer: Optional[threading.Timer] = None
        self._half_open_ticket = False  # probe-less mode: one admission
        self._ticket_at = float("-inf")  # when the last ticket went out
        # bumped by operator overrides (open_now/close_now): a probe
        # launched before the override must not publish over it
        self._probe_gen = 0
        self._probes = 0
        self._publish()

    # -- introspection --

    def state(self) -> str:
        with self._lock:
            return self._state

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "trips": self._trips,
                "probes": self._probes,
                "retry_in_s": max(0.0, self._retry_at - self._clock()),
            }

    def probe_in_flight(self) -> bool:
        with self._lock:
            t = self._probe_thread
        return t is not None and t.is_alive()

    # -- configuration --

    def set_probe(self, fn: Optional[Callable[[], bool]]) -> None:
        """Install the background re-arm probe (device-touching; must
        return truthy on success and never block forever — wrap device
        calls in the same gather deadline the hot path uses)."""
        with self._lock:
            self._probe_fn = fn

    def configure(self, backoff_base_s=None, backoff_max_s=None) -> None:
        with self._lock:
            if backoff_base_s is not None:
                self.backoff_base_s = backoff_base_s
            if backoff_max_s is not None:
                self.backoff_max_s = backoff_max_s

    # -- the gate --

    def allow(self) -> bool:
        """True when callers may route to the device. OPEN/HALF_OPEN
        answer False when a probe fn is configured (traffic never
        pilots a possibly-wedged device — the probe does); without one,
        HALF_OPEN admits one caller per backoff interval, who SHOULD
        report back via record_success()/record_failure(). A ticket
        whose holder never reports (its work got rerouted, its process
        path died) expires after the current backoff and a fresh one
        is issued — the half-open state can stall the route, never
        wedge it."""
        kick = False
        with self._lock:
            if self._state == CLOSED:
                return True
            now = self._clock()
            if self._state == OPEN and now >= self._retry_at:
                self._set_state(HALF_OPEN)
                if self._probe_fn is not None:
                    kick = True
                else:
                    self._half_open_ticket = True
            if self._state == HALF_OPEN and self._probe_fn is None:
                if self._half_open_ticket or (
                    now - self._ticket_at >= self._backoff_s()
                ):
                    self._half_open_ticket = False
                    self._ticket_at = now
                    return True
                return False
            if kick:
                self._kick_probe_locked()
        return False

    def _backoff_s(self) -> float:
        """Current backoff window (call with the lock held)."""
        return min(
            self.backoff_base_s * (2 ** max(self._trips - 1, 0)),
            self.backoff_max_s,
        )

    def record_success(self) -> None:
        """A device interaction completed correctly: HALF_OPEN (ticket
        holder or probe) closes the breaker; CLOSED stays closed and
        resets the backoff exponent."""
        with self._lock:
            self._record_success_locked()

    def _record_success_locked(self) -> None:
        self._trips = 0
        if self._state != CLOSED:
            self._set_state(CLOSED)
        self._cancel_timer_locked()

    def record_failure(self) -> None:
        """A device interaction faulted: open (or re-open) with
        exponential backoff. When a probe fn is configured, the next
        probe is timer-scheduled at backoff expiry so the route re-arms
        even with no traffic poking allow()."""
        with self._lock:
            self._record_failure_locked()

    def _record_failure_locked(self) -> None:
        self._trips += 1
        backoff = self._backoff_s()
        self._retry_at = self._clock() + backoff
        self._half_open_ticket = False
        self._set_state(OPEN)
        _m_trips.inc(name=self.name)
        if self._probe_fn is not None:
            self._schedule_probe_locked(backoff)

    def probe_now(self) -> None:
        """Launch the single-flight probe immediately (install-time
        warm-up of a start_open breaker)."""
        with self._lock:
            if self._state == OPEN:
                self._set_state(HALF_OPEN)
            self._kick_probe_locked()

    def close_now(self) -> None:
        """Force CLOSED (tests; operator override). Retires any probe
        already in flight: its verdict must not land on top of an
        explicit operator decision."""
        with self._lock:
            self._probe_gen += 1
            self._record_success_locked()

    def open_now(self, backoff_s: Optional[float] = None) -> None:
        """Force OPEN without scheduling a probe timer (bench's
        degraded-mode row; operator kill switch). `backoff_s` defaults
        to the max backoff so the route stays down until re-armed.
        Retires any in-flight probe — a probe that launched before the
        override succeeded against the device must NOT silently close
        the breaker the operator just ordered open."""
        with self._lock:
            self._probe_gen += 1
            self._retry_at = self._clock() + (
                self.backoff_max_s if backoff_s is None else backoff_s
            )
            self._half_open_ticket = False
            self._cancel_timer_locked()
            if self._state != OPEN:
                self._trips += 1
                self._set_state(OPEN)
                _m_trips.inc(name=self.name)

    # -- internals (call with self._lock held) --

    def _set_state(self, state: str) -> None:
        self._state = state
        _m_state.set(_STATE_CODE[state], name=self.name)

    def _publish(self) -> None:
        _m_state.set(_STATE_CODE[self._state], name=self.name)

    def _cancel_timer_locked(self) -> None:
        if self._probe_timer is not None:
            self._probe_timer.cancel()
            self._probe_timer = None

    def _schedule_probe_locked(self, delay_s: float) -> None:
        """One timer per OPEN window; a newer failure replaces it (the
        old 10-second probe-delay policy: a wedge is never re-touched
        instantly, and never by more than one thread)."""
        self._cancel_timer_locked()
        t = threading.Timer(delay_s, self._timer_fired)
        t.daemon = True
        t.name = f"breaker-retry-{self.name}"
        self._probe_timer = t
        t.start()

    def _timer_fired(self) -> None:
        with self._lock:
            self._probe_timer = None
            if self._state != OPEN or self._clock() < self._retry_at:
                return
            self._set_state(HALF_OPEN)
            self._kick_probe_locked()

    def _kick_probe_locked(self) -> None:
        if self._probe_fn is None:
            return
        if self._probe_thread is not None and self._probe_thread.is_alive():
            return  # single-flight: alive-check and publish share the lock
        self._probes += 1
        _m_probes.inc(name=self.name)
        gen = self._probe_gen
        t = threading.Thread(
            target=self._run_probe,
            args=(gen,),
            daemon=True,
            name=f"breaker-probe-{self.name}",
        )
        self._probe_thread = t
        t.start()

    def _run_probe(self, gen: int) -> None:
        try:
            ok = bool(self._probe_fn())
        except Exception:  # a probe failure is data, never fatal
            ok = False
        # generation check and state mutation under ONE lock hold: an
        # operator override (open_now/close_now) landing between them
        # would otherwise be silently overwritten by this verdict
        with self._lock:
            if gen != self._probe_gen:
                return  # superseded by an operator override
            if ok:
                self._record_success_locked()
            else:
                self._record_failure_locked()


# -- registry ---------------------------------------------------------

_REGISTRY: Dict[str, CircuitBreaker] = {}
_REG_LOCK = threading.Lock()


def breaker_for(name: str, **kwargs) -> CircuitBreaker:
    """The process-wide breaker for a route, created on first use with
    `kwargs` (later calls return the live instance unchanged)."""
    with _REG_LOCK:
        b = _REGISTRY.get(name)
        if b is None:
            b = _REGISTRY[name] = CircuitBreaker(name, **kwargs)
        return b


def fresh(name: str, **kwargs) -> CircuitBreaker:
    """Replace the registered breaker with a new instance — a new
    install() generation. A stale in-flight probe finishes against the
    orphaned object, which nobody consults anymore (the generation
    retirement the old _SR_WARM_GEN counter implemented by hand)."""
    with _REG_LOCK:
        old = _REGISTRY.pop(name, None)
        if old is not None:
            with old._lock:
                old._cancel_timer_locked()
        b = _REGISTRY[name] = CircuitBreaker(name, **kwargs)
        return b


def discard(name: str) -> None:
    with _REG_LOCK:
        old = _REGISTRY.pop(name, None)
    if old is not None:
        with old._lock:
            old._cancel_timer_locked()


def reset_all() -> None:
    """Drop every breaker (tests)."""
    with _REG_LOCK:
        names = list(_REGISTRY)
    for n in names:
        discard(n)
