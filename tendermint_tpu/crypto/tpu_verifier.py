"""Device-backed BatchVerifier — the TPU side of the plugin boundary.

The reference gates all batch verification behind crypto.BatchVerifier
(crypto/crypto.go:53-61) with curve25519-voi underneath
(crypto/ed25519/ed25519.go:202-237). Here the implementation underneath
is the XLA program in tendermint_tpu.ops.ed25519_kernel; install() makes
crypto.batch.create_batch_verifier return it for ed25519 keys when the
batch is large enough to beat host latency. CPU remains the default
until install() is called, exactly like the reference defaults to pure
Go.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

from ..libs import metrics as M
from .batch import register_device_factory
from .keys import BatchVerifier, PubKey

# device-offload observability (no reference analog — this is the
# north-star seam's instrumentation)
_m_batches = M.new_counter(
    "tpu", "verify_batches_total", "Device batch-verify invocations."
)
_m_sigs = M.new_counter(
    "tpu", "verify_sigs_total", "Signatures verified on device."
)
_m_verify_time = M.new_histogram(
    "tpu",
    "verify_seconds",
    "Wall time of one batch verification.",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5),
)

__all__ = [
    "TpuEd25519BatchVerifier",
    "TpuSr25519BatchVerifier",
    "install",
    "installed",
    "stats",
    "DEFAULT_MIN_BATCH",
]

# Below this many signatures the fixed dispatch cost (host packing +
# device roundtrip, ~100s of µs) exceeds CPU verify time; let CPU win.
DEFAULT_MIN_BATCH = 8

# lazily cached "is the backend a real accelerator" decision
_STREAMING: Optional[bool] = None


def on_accelerator() -> bool:
    """True when this process's jax backend is a real accelerator.

    CPU-pinned processes (jax_platforms == "cpu" — the test suite, any
    CPU-only node) are answered from the config STRING without
    initializing a backend, so consensus-critical callers like
    sr25519's single-verify route never stall on backend init just to
    learn they should use the Python path. A process with no TPU
    runtime installed at all (no libtpu wheel) is likewise answered
    without backend init. Everything else pays one backend query,
    cached — those processes are about to dispatch to the device
    anyway. CPU-only deployments that leave jax_platforms unset and DO
    ship libtpu should set jax_platforms=cpu explicitly to keep jax
    backend initialization out of the first verify call."""
    global _STREAMING
    if _STREAMING is None:
        import jax

        plats = None
        try:
            plats = jax.config.jax_platforms  # no backend init
        except AttributeError:  # pragma: no cover - very old jax
            pass
        if plats and set(plats.split(",")) == {"cpu"}:
            _STREAMING = False
        elif not plats and not _has_tpu_runtime():
            # only an UNSET platform string consults the runtime sniff:
            # an explicit jax_platforms=tpu (e.g. libtpu loaded via
            # TPU_LIBRARY_PATH, no importable module) must reach the
            # backend query, symmetric with the explicit-cpu case
            _STREAMING = False
        else:
            _STREAMING = jax.default_backend() == "tpu"
    return _STREAMING


def _has_tpu_runtime() -> bool:
    """Whether a TPU runtime could plausibly be attached, decided
    WITHOUT initializing a jax backend: the libtpu wheel must be
    importable (jax's own TPU discovery path). On boxes without it,
    jax.default_backend() could only ever answer cpu/gpu — so answering
    False here is exact, and keeps backend init out of the verify hot
    path on CPU-only nodes with jax_platforms unset."""
    import importlib.util

    import os

    if os.environ.get("TPU_LIBRARY_PATH"):
        # libtpu attached via env var, no importable module
        return True
    try:
        return (
            importlib.util.find_spec("libtpu") is not None
            or importlib.util.find_spec("jax_plugins") is not None
        )
    except (ImportError, ValueError):  # pragma: no cover - spec quirks
        return True  # unknown: fall through to the backend query


class _TpuBatchVerifier(BatchVerifier):
    """Queues triples on host, verifies on device.

    Same verify() contract as the CPU path: (all_ok, bitmap), bitmap
    aligned with add() order, malformed entries reported invalid
    per-index rather than raising at verify time.

    On a TPU backend, full STREAM_CHUNK-sized slices are dispatched
    asynchronously AS add() fills them, so the host-side assembly loop
    (sign-bytes, address lookups — ~2 us/sig in VerifyCommit) overlaps
    device compute instead of serializing in front of it; verify()
    dispatches the remainder and gathers every in-flight handle in add
    order. The chunk matches a configured bucket so no new program
    shapes are compiled.
    """

    KEY_TYPE = ""  # subclasses set
    STREAM_CHUNK = 2048  # == a DEFAULT_BUCKET_SIZES entry

    def __init__(self, verifier=None) -> None:
        self._verifier = verifier
        self._kernel = self._kernel_module()
        self._pks: List[bytes] = []
        self._msgs: List[bytes] = []
        self._sigs: List[bytes] = []
        self._handles: List[tuple] = []  # (backing, handle, n), add order

    @staticmethod
    def _kernel_module():
        raise NotImplementedError

    def _backing(self):
        return (
            self._verifier
            if self._verifier is not None
            else self._kernel.default_verifier()
        )

    @staticmethod
    def _streaming() -> bool:
        """Chunked dispatch only pays on an accelerator (CPU 'device'
        programs are the bottleneck themselves, and extra bucket shapes
        would mean extra test-suite compiles)."""
        return on_accelerator()

    def _dispatch_pending(self, v) -> None:
        """Asynchronously launch the queued triples on `v` and clear
        the queue; the handle is gathered in verify(). Each dispatch is
        one device invocation for the metrics."""
        self._handles.append(
            (v, v.dispatch(self._pks, self._msgs, self._sigs),
             len(self._pks))
        )
        self._pks, self._msgs, self._sigs = [], [], []
        _m_batches.inc()

    def add(self, pub_key: PubKey, message: bytes, signature: bytes) -> None:
        if pub_key.type() != self.KEY_TYPE:
            raise TypeError(
                f"{type(self).__name__} requires {self.KEY_TYPE} keys"
            )
        if len(signature) != 64:
            raise ValueError("malformed signature size")
        self._pks.append(pub_key.bytes())
        self._msgs.append(bytes(message))
        self._sigs.append(bytes(signature))
        if len(self._pks) >= self.STREAM_CHUNK and self._streaming():
            v = self._backing()
            # injected verifiers only promise verify(); stream solely
            # when the dispatch()/gather() pair is actually there
            if hasattr(v, "dispatch") and hasattr(v, "gather"):
                self._dispatch_pending(v)

    def verify(self) -> Tuple[bool, List[bool]]:
        """Drains the queue: a verifier is a one-shot batch (matching
        the reference's use — one BatchVerifier per commit); calling
        verify() again without new add()s reports (False, []) on every
        backend. In streaming mode verify_seconds times the remainder
        dispatch + gather barrier (chunk dispatches already ran inside
        add, overlapped with the caller's assembly loop)."""
        if not self._pks and not self._handles:
            return False, []
        with _m_verify_time.time():
            total = sum(n for _, _, n in self._handles) + len(self._pks)
            v = self._backing()
            if self._handles:
                if self._pks:
                    self._dispatch_pending(v)
                bits: List[bool] = []
                try:
                    for bv, handle, _n in self._handles:
                        bits.extend(bool(b) for b in bv.gather(handle))
                finally:
                    # a gather that raises mid-loop must still leave the
                    # verifier drained: a retry would otherwise re-gather
                    # stale handles and double-count _m_sigs, and
                    # __len__ would keep reporting the in-flight count
                    self._handles = []
            else:
                try:
                    bits = [
                        bool(b)
                        for b in v.verify(self._pks, self._msgs, self._sigs)
                    ]
                finally:
                    self._pks, self._msgs, self._sigs = [], [], []
                _m_batches.inc()
        _m_sigs.inc(total)
        return all(bits), bits

    def __len__(self) -> int:
        return len(self._pks) + sum(n for _, _, n in self._handles)


class TpuEd25519BatchVerifier(_TpuBatchVerifier):
    KEY_TYPE = "ed25519"

    @staticmethod
    def _kernel_module():
        from ..ops import ed25519_kernel

        return ed25519_kernel


class TpuSr25519BatchVerifier(_TpuBatchVerifier):
    """Device sr25519 batch verifier (reference: crypto/sr25519/batch.go
    backed by curve25519-voi; here ops/sr25519_kernel.py — ristretto
    decode + schnorrkel equation on the shared curve core)."""

    KEY_TYPE = "sr25519"

    @staticmethod
    def _kernel_module():
        from ..ops import sr25519_kernel

        return sr25519_kernel


_SHARED_VERIFIER = None
_SHARED_VERIFIER_SR = None
_MIN_BATCH = DEFAULT_MIN_BATCH
_INSTALLED = False
# Single sr25519 verifies only route to the device once the smallest
# bucket's program is compiled (the install() warm thread flips this);
# until then they stay on the pure-Python path, so a consensus-critical
# per-vote verify can never block behind an XLA compile. The thread
# handle is kept so tests (and embedders) can join before reading.
_SR_WARM = False
_SR_WARM_THREAD = None
# bumped (under _SR_WARM_LOCK) by every install() BEFORE the shared
# verifier swap: a warm thread only publishes its result if its
# generation is still current, so a slow warm from a superseded install
# can never vouch for a verifier it didn't compile
_SR_WARM_GEN = 0
_SR_WARM_LOCK = threading.Lock()


def installed() -> Optional[int]:
    """The currently-installed min_batch threshold, or None if the
    device factory has never been registered. Install state is
    process-global (one device runtime per process); multi-node
    embedders share whichever install ran last."""
    return _MIN_BATCH if _INSTALLED else None


def stats() -> dict:
    """Device-path usage counters — lets the node (and tests) assert the
    batch path actually runs on device in the served configuration."""
    return {
        "batches": int(_m_batches.value()),
        "sigs": int(_m_sigs.value()),
    }


def _factory(size_hint: int) -> Optional[BatchVerifier]:
    if 0 < size_hint < _MIN_BATCH:
        return None  # CPU fallback for tiny batches
    return TpuEd25519BatchVerifier(_SHARED_VERIFIER)


def _factory_sr(size_hint: int) -> Optional[BatchVerifier]:
    # per-curve threshold: the sr25519 CPU fallback is pure-Python
    # ristretto (~6 ms/sig), so on a real accelerator ANY batch —
    # including a single signature — wins on device; the shared
    # min-batch gate only applies where the CPU path is native-fast
    min_b = 1 if on_accelerator() else _MIN_BATCH
    if 0 < size_hint < min_b:
        return None
    return TpuSr25519BatchVerifier(_SHARED_VERIFIER_SR)


def single_sr_verifier() -> Optional[BatchVerifier]:
    """A device batch verifier for ONE sr25519 signature, or None when
    the device path is not installed / not worthwhile (CPU backend).
    Used by PubKeySr25519.verify_signature so per-vote and evidence
    verifies ride the kernel — through the installed (possibly
    mesh-sharded) verifier and the tpu metrics, same as batches.
    Gated on the warm flag: until install()'s background thread has
    compiled the smallest sr25519 bucket, singles stay on the CPU path
    instead of stalling a vote behind the first XLA compile."""
    if not (_INSTALLED and _SR_WARM):
        return None
    return _factory_sr(1)


def trip_sr_singles() -> None:
    """Demote single sr25519 verifies back to the CPU path after a
    device fault (called by PubKeySr25519.verify_signature's fallback).
    Without the trip, a persistently faulted device would be re-tried —
    and a warning logged — on every per-vote verify. A fresh warm probe
    is started immediately: if the fault was transient the probe's
    successful device verify re-arms the route; if the device is truly
    down the probe fails quietly and singles stay on CPU (one probe per
    trip — no retry storm, and batches keep their own error paths)."""
    global _SR_WARM
    with _SR_WARM_LOCK:
        _SR_WARM = False
    if _INSTALLED:
        # one probe at a time (enforced inside, under the gate lock),
        # and not immediately: if the fault is a wedge rather than a
        # raising error, an instant re-touch of the device would just
        # hang another thread (device-claim discipline: never pile onto
        # a wedged claim)
        _start_sr_warm_thread(delay_s=10.0, single_flight=True)


def _start_sr_warm_thread(
    delay_s: float = 0.0, single_flight: bool = False
) -> None:
    """Compile the smallest sr25519 bucket off the install() path, then
    flip _SR_WARM so single verifies start routing to the device. Runs
    on a daemon thread: install() itself must never touch the backend
    (a wedged device claim would hang node startup — PERF.md claim
    discipline), and a warm that stalls only delays the device upgrade
    of single verifies, never a vote."""
    global _SR_WARM_THREAD, _SR_WARM_GEN

    with _SR_WARM_LOCK:
        if single_flight and (
            _SR_WARM_THREAD is not None and _SR_WARM_THREAD.is_alive()
        ):
            # a probe is already in flight (alive-check and thread
            # publication share this lock, so concurrent trips cannot
            # both slip past it)
            return
        # snapshot generation AND verifier together: the probe must
        # only ever vouch for the verifier it actually compiled, and
        # install() swaps both under this same lock
        gen = _SR_WARM_GEN
        snap = _SHARED_VERIFIER_SR
        # publish the thread object under the same lock as the alive
        # check above; `warm` is late-bound — defined below, before
        # start() runs
        _SR_WARM_THREAD = thread = threading.Thread(
            target=lambda: warm(), daemon=True, name="sr25519-warm"
        )

    def publish(ok: bool) -> None:
        """Set the warm flag iff this thread's snapshot is still
        current — checked and written under the gate lock so a
        superseded warm (older generation OR swapped verifier) can
        never vouch for a verifier it didn't compile."""
        global _SR_WARM
        with _SR_WARM_LOCK:
            if (
                ok
                and gen == _SR_WARM_GEN
                and snap is _SHARED_VERIFIER_SR
            ):
                _SR_WARM = True

    def warm() -> None:
        try:
            if delay_s:
                time.sleep(delay_s)
            if not on_accelerator() and _MIN_BATCH > 1:
                # CPU process with the min-batch gate keeping singles
                # off the kernel: nothing to compile. (min_batch <= 1
                # would route singles to the CPU-backend kernel, so
                # that case falls through to the real probe below.)
                publish(True)
                return
            from .sr25519 import PrivKeySr25519

            priv = PrivKeySr25519.from_seed(b"\x77" * 32)
            msg = b"sr25519-warm"
            v = snap
            if v is None:
                from ..ops import sr25519_kernel

                v = sr25519_kernel.default_verifier()
            ok = v.verify(
                [priv.pub_key().bytes()], [msg], [priv.sign(msg)]
            )
            publish(bool(ok.all()))
        except Exception as e:  # pragma: no cover - warm is best-effort
            from ..libs.log import get_logger

            get_logger("crypto.tpu").warning(
                "sr25519 device warm-up failed; singles stay on CPU",
                err=repr(e),
            )

    thread.start()


def install(
    min_batch: int = DEFAULT_MIN_BATCH, mesh=None
) -> None:
    """Register the device factories (ed25519 + sr25519). With a mesh,
    ed25519 batches are sharded across it
    (tendermint_tpu.parallel.sharding); otherwise single-chip."""
    global _SHARED_VERIFIER, _SHARED_VERIFIER_SR, _MIN_BATCH, _INSTALLED
    global _SR_WARM, _SR_WARM_GEN
    _MIN_BATCH = min_batch
    _INSTALLED = True
    # warm the native keccak library here (a subprocess cc compile on
    # first use) so the first consensus-critical sr25519 verify never
    # stalls behind a compiler
    from .merlin import _native_lib

    _native_lib()
    if mesh is not None:
        from ..parallel.sharding import (
            ShardedEd25519Verifier,
            ShardedSr25519Verifier,
        )

        new_ed = ShardedEd25519Verifier(mesh)
        new_sr = ShardedSr25519Verifier(mesh)
    else:
        new_ed = None
        new_sr = None
    # gate drop + generation bump + verifier swap are ONE atomic step:
    # a concurrent vote (or a trip-started warm probe) must never see
    # the new uncompiled verifier behind a still-true warm flag, nor a
    # current generation paired with the old verifier
    with _SR_WARM_LOCK:
        _SR_WARM = False
        _SR_WARM_GEN += 1
        _SHARED_VERIFIER = new_ed
        _SHARED_VERIFIER_SR = new_sr
    register_device_factory("ed25519", _factory)
    register_device_factory("sr25519", _factory_sr)
    _start_sr_warm_thread()
    # merged multi-commit batches (light sequential windows) only pay
    # off on an accelerator ONCE THIS FACTORY IS INSTALLED: _factory
    # serves every >=_MIN_BATCH batch regardless of backend, and on a
    # CPU-backed JAX kernel the bucket padding of a merged window
    # inverts the win (measured 5x slower). Uninstalled processes get
    # batch.native_cpu_affinity's module default instead (the native
    # RLC equation is exact-size, so merging wins there). The decision
    # needs jax.default_backend(), which initializes the backend —
    # deferred to first use so a wedged device claim cannot hang
    # install() itself at node startup (PERF.md, claim discipline).
    from .batch import set_group_affinity_fn

    def _affinity() -> int:
        import jax

        return 32 if jax.default_backend() == "tpu" else 1

    set_group_affinity_fn(_affinity)


def uninstall() -> None:
    """Remove the device factories and reset install state — the
    counterpart of install(), mirroring ops/merkle_kernel.uninstall()
    (tests and embedders switching a node back to the CPU seam). The
    generation bump retires any in-flight warm thread — it only
    publishes under a current generation — and the merged-window
    affinity falls back to the module default
    (batch.native_cpu_affinity) unless an operator pinned a value
    explicitly."""
    global _SHARED_VERIFIER, _SHARED_VERIFIER_SR, _MIN_BATCH, _INSTALLED
    global _SR_WARM, _SR_WARM_GEN
    from .batch import (
        native_cpu_affinity,
        set_group_affinity_fn,
        unregister_device_factory,
    )

    unregister_device_factory("ed25519")
    unregister_device_factory("sr25519")
    with _SR_WARM_LOCK:
        _SR_WARM = False
        _SR_WARM_GEN += 1
        _SHARED_VERIFIER = None
        _SHARED_VERIFIER_SR = None
    _MIN_BATCH = DEFAULT_MIN_BATCH
    _INSTALLED = False
    set_group_affinity_fn(native_cpu_affinity)
