"""Device-backed BatchVerifier — the TPU side of the plugin boundary.

The reference gates all batch verification behind crypto.BatchVerifier
(crypto/crypto.go:53-61) with curve25519-voi underneath
(crypto/ed25519/ed25519.go:202-237). Here the implementation underneath
is the XLA program in tendermint_tpu.ops.ed25519_kernel; install() makes
crypto.batch.create_batch_verifier return it for ed25519 keys when the
batch is large enough to beat host latency. CPU remains the default
until install() is called, exactly like the reference defaults to pure
Go.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

from ..libs import metrics as M
from ..libs import trace
from .batch import register_device_factory
from .keys import BatchVerifier, PubKey

# device-offload observability (no reference analog — this is the
# north-star seam's instrumentation). Deliberately process-global on
# DEFAULT_REGISTRY, unlike the per-node subsystem metrics: there is one
# device runtime per process, and multi-node embedders share it.
_m_batches = M.new_counter(
    "tpu", "verify_batches_total", "Device batch-verify invocations."
)
_m_sigs = M.new_counter(
    "tpu", "verify_sigs_total", "Signatures verified on device."
)
_m_verify_time = M.new_histogram(
    "tpu",
    "verify_seconds",
    "Wall time of one batch verification.",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5),
)
# dispatch telemetry: decompose verify_seconds into the host-side
# assembly (packing triples into device arrays + async launch) and the
# device wall (gather barrier) — the split PERF.md demands before any
# device number is believed — plus bucket-padding waste and
# warm-generation hit/miss for compile-stall attribution.
_m_host_prep = M.new_histogram(
    "tpu",
    "host_prep_seconds",
    "Host-side packing + async dispatch of one batch.",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.25),
)
_m_device_wall = M.new_histogram(
    "tpu",
    "device_wall_seconds",
    "Device wall time (gather barrier) of one batch.",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5),
)
_m_pad_waste = M.new_counter(
    "tpu",
    "pad_waste_slots_total",
    "Signature slots wasted padding batches to bucket shapes.",
)
_m_warm_hits = M.new_counter(
    "tpu",
    "warm_bucket_hits_total",
    "Dispatches into a bucket already run this install generation.",
)
_m_warm_misses = M.new_counter(
    "tpu",
    "warm_bucket_misses_total",
    "First dispatches into a bucket (likely paying an XLA compile).",
)

__all__ = [
    "TpuEd25519BatchVerifier",
    "TpuSr25519BatchVerifier",
    "install",
    "installed",
    "stats",
    "DEFAULT_MIN_BATCH",
]

# Below this many signatures the fixed dispatch cost (host packing +
# device roundtrip, ~100s of µs) exceeds CPU verify time; let CPU win.
DEFAULT_MIN_BATCH = 8

# lazily cached "is the backend a real accelerator" decision
_STREAMING: Optional[bool] = None

# (key type, backing verifier id, bucket) triples dispatched at least
# once since the last install()/uninstall(): first touch of a bucket
# shape likely pays an XLA compile, so dispatch telemetry labels it a
# warm miss. Cleared on install/uninstall — a new generation's programs
# are cold again.
_WARM_BUCKETS: set = set()


def _bucket_of(verifier, n: int) -> int:
    """The padded bucket `n` signatures land in, from the backing
    verifier's configured sizes (without importing the jax-backed ops
    module: telemetry must not initialize a backend)."""
    sizes = getattr(verifier, "bucket_sizes", None)
    if not sizes:
        from ..config import DEFAULT_BUCKET_SIZES

        sizes = DEFAULT_BUCKET_SIZES
    for b in sorted(sizes):
        if b >= n:
            return b
    return n


def _note_bucket_warmth(key_type: str, verifier, bucket: int) -> bool:
    """Record (and count) whether this bucket shape has been dispatched
    before in this install generation. Returns the hit/miss verdict for
    the span attributes."""
    key = (key_type, id(verifier), bucket)
    if key in _WARM_BUCKETS:
        _m_warm_hits.inc()
        return True
    _WARM_BUCKETS.add(key)
    _m_warm_misses.inc()
    return False


def on_accelerator() -> bool:
    """True when this process's jax backend is a real accelerator.

    CPU-pinned processes (jax_platforms == "cpu" — the test suite, any
    CPU-only node) are answered from the config STRING without
    initializing a backend, so consensus-critical callers like
    sr25519's single-verify route never stall on backend init just to
    learn they should use the Python path. A process with no TPU
    runtime installed at all (no libtpu wheel) is likewise answered
    without backend init. Everything else pays one backend query,
    cached — those processes are about to dispatch to the device
    anyway. CPU-only deployments that leave jax_platforms unset and DO
    ship libtpu should set jax_platforms=cpu explicitly to keep jax
    backend initialization out of the first verify call."""
    global _STREAMING
    if _STREAMING is None:
        import jax

        plats = None
        try:
            plats = jax.config.jax_platforms  # no backend init
        except AttributeError:  # pragma: no cover - very old jax
            pass
        if plats and set(plats.split(",")) == {"cpu"}:
            _STREAMING = False
        elif not plats and not _has_tpu_runtime():
            # only an UNSET platform string consults the runtime sniff:
            # an explicit jax_platforms=tpu (e.g. libtpu loaded via
            # TPU_LIBRARY_PATH, no importable module) must reach the
            # backend query, symmetric with the explicit-cpu case
            _STREAMING = False
        else:
            _STREAMING = jax.default_backend() == "tpu"
    return _STREAMING


def _has_tpu_runtime() -> bool:
    """Whether a TPU runtime could plausibly be attached, decided
    WITHOUT initializing a jax backend: the libtpu wheel must be
    importable (jax's own TPU discovery path). On boxes without it,
    jax.default_backend() could only ever answer cpu/gpu — so answering
    False here is exact, and keeps backend init out of the verify hot
    path on CPU-only nodes with jax_platforms unset."""
    import importlib.util

    import os

    if os.environ.get("TPU_LIBRARY_PATH"):
        # libtpu attached via env var, no importable module
        return True
    try:
        return (
            importlib.util.find_spec("libtpu") is not None
            or importlib.util.find_spec("jax_plugins") is not None
        )
    except (ImportError, ValueError):  # pragma: no cover - spec quirks
        return True  # unknown: fall through to the backend query


class _TpuBatchVerifier(BatchVerifier):
    """Queues triples on host, verifies on device.

    Same verify() contract as the CPU path: (all_ok, bitmap), bitmap
    aligned with add() order, malformed entries reported invalid
    per-index rather than raising at verify time.

    On a TPU backend, full STREAM_CHUNK-sized slices are dispatched
    asynchronously AS add() fills them, so the host-side assembly loop
    (sign-bytes, address lookups — ~2 us/sig in VerifyCommit) overlaps
    device compute instead of serializing in front of it; verify()
    dispatches the remainder and gathers every in-flight handle in add
    order. The chunk matches a configured bucket so no new program
    shapes are compiled.
    """

    KEY_TYPE = ""  # subclasses set
    STREAM_CHUNK = 2048  # == a DEFAULT_BUCKET_SIZES entry

    def __init__(self, verifier=None) -> None:
        self._verifier = verifier
        self._kernel = self._kernel_module()
        self._pks: List[bytes] = []
        self._msgs: List[bytes] = []
        self._sigs: List[bytes] = []
        self._handles: List[tuple] = []  # (backing, handle, n), add order
        # dispatch telemetry accumulated across THIS one-shot batch
        # (streaming chunks launch from add(), before verify() runs)
        self._last_bucket = 0
        self._pad_waste = 0
        self._cold_dispatch = False

    @staticmethod
    def _kernel_module():
        raise NotImplementedError

    def _backing(self):
        return (
            self._verifier
            if self._verifier is not None
            else self._kernel.default_verifier()
        )

    @staticmethod
    def _streaming() -> bool:
        """Chunked dispatch only pays on an accelerator (CPU 'device'
        programs are the bottleneck themselves, and extra bucket shapes
        would mean extra test-suite compiles)."""
        return on_accelerator()

    def _account_dispatch(self, v, n: int) -> None:
        """Telemetry for ONE device dispatch of n triples: bucket
        padding waste and warm-generation hit/miss. Called on every
        launch — streaming chunks from add() included, since that is
        exactly where a first-touch XLA compile stalls the hot path."""
        bucket = _bucket_of(v, n)
        waste = bucket - n
        self._last_bucket = bucket
        if waste:
            self._pad_waste += waste
            _m_pad_waste.inc(waste)
        if not _note_bucket_warmth(self.KEY_TYPE, v, bucket):
            self._cold_dispatch = True

    def _dispatch_pending(self, v) -> None:
        """Asynchronously launch the queued triples on `v` and clear
        the queue; the handle is gathered in verify(). Each dispatch is
        one device invocation for the metrics."""
        self._account_dispatch(v, len(self._pks))
        self._handles.append(
            (v, v.dispatch(self._pks, self._msgs, self._sigs),
             len(self._pks))
        )
        self._pks, self._msgs, self._sigs = [], [], []
        _m_batches.inc()

    def add(self, pub_key: PubKey, message: bytes, signature: bytes) -> None:
        if pub_key.type() != self.KEY_TYPE:
            raise TypeError(
                f"{type(self).__name__} requires {self.KEY_TYPE} keys"
            )
        if len(signature) != 64:
            raise ValueError("malformed signature size")
        self._pks.append(pub_key.bytes())
        self._msgs.append(bytes(message))
        self._sigs.append(bytes(signature))
        if len(self._pks) >= self.STREAM_CHUNK and self._streaming():
            v = self._backing()
            # injected verifiers only promise verify(); stream solely
            # when the dispatch()/gather() pair is actually there
            if hasattr(v, "dispatch") and hasattr(v, "gather"):
                self._dispatch_pending(v)

    def verify(self) -> Tuple[bool, List[bool]]:
        """Drains the queue: a verifier is a one-shot batch (matching
        the reference's use — one BatchVerifier per commit); calling
        verify() again without new add()s reports (False, []) on every
        backend. In streaming mode verify_seconds times the remainder
        dispatch + gather barrier (chunk dispatches already ran inside
        add, overlapped with the caller's assembly loop).

        The tpu_dispatch span (and the host_prep/device_wall
        histograms) split the wall time at the async-launch boundary:
        everything before the handle exists is host packing, everything
        after is the device barrier. Backings without the
        dispatch()/gather() pair (injected test verifiers) report one
        undivided wall time."""
        if not self._pks and not self._handles:
            return False, []
        t0 = time.perf_counter()
        with trace.span(
            "tpu_dispatch", hist=_m_verify_time, key=self.KEY_TYPE
        ):
            total = sum(n for _, _, n in self._handles) + len(self._pks)
            v = self._backing()
            host_prep: Optional[float] = None
            if self._handles:
                if self._pks:
                    self._dispatch_pending(v)
                host_prep = time.perf_counter() - t0
                bits: List[bool] = []
                try:
                    for bv, handle, _n in self._handles:
                        bits.extend(bool(b) for b in bv.gather(handle))
                finally:
                    # a gather that raises mid-loop must still leave the
                    # verifier drained: a retry would otherwise re-gather
                    # stale handles and double-count _m_sigs, and
                    # __len__ would keep reporting the in-flight count
                    self._handles = []
            elif hasattr(v, "dispatch") and hasattr(v, "gather"):
                # split verify() at the same boundary the streaming path
                # uses (gather(dispatch()) is exactly v.verify())
                self._account_dispatch(v, len(self._pks))
                try:
                    handle = v.dispatch(self._pks, self._msgs, self._sigs)
                    host_prep = time.perf_counter() - t0
                    bits = [bool(b) for b in v.gather(handle)]
                finally:
                    self._pks, self._msgs, self._sigs = [], [], []
                _m_batches.inc()
            else:
                self._account_dispatch(v, len(self._pks))
                try:
                    bits = [
                        bool(b)
                        for b in v.verify(self._pks, self._msgs, self._sigs)
                    ]
                finally:
                    self._pks, self._msgs, self._sigs = [], [], []
                _m_batches.inc()
            if host_prep is not None:
                device_wall = time.perf_counter() - t0 - host_prep
                _m_host_prep.observe(host_prep)
                _m_device_wall.observe(device_wall)
                trace.add_attrs(
                    host_prep_s=round(host_prep, 6),
                    device_wall_s=round(device_wall, 6),
                )
            trace.add_attrs(
                batch=total,
                bucket=self._last_bucket,
                pad_waste=self._pad_waste,
                warm=not self._cold_dispatch,
            )
        _m_sigs.inc(total)
        return all(bits), bits

    def __len__(self) -> int:
        return len(self._pks) + sum(n for _, _, n in self._handles)


class TpuEd25519BatchVerifier(_TpuBatchVerifier):
    KEY_TYPE = "ed25519"

    @staticmethod
    def _kernel_module():
        from ..ops import ed25519_kernel

        return ed25519_kernel


class TpuSr25519BatchVerifier(_TpuBatchVerifier):
    """Device sr25519 batch verifier (reference: crypto/sr25519/batch.go
    backed by curve25519-voi; here ops/sr25519_kernel.py — ristretto
    decode + schnorrkel equation on the shared curve core)."""

    KEY_TYPE = "sr25519"

    @staticmethod
    def _kernel_module():
        from ..ops import sr25519_kernel

        return sr25519_kernel


_SHARED_VERIFIER = None
_SHARED_VERIFIER_SR = None
_MIN_BATCH = DEFAULT_MIN_BATCH
_INSTALLED = False
# Single sr25519 verifies only route to the device once the smallest
# bucket's program is compiled (the install() warm thread flips this);
# until then they stay on the pure-Python path, so a consensus-critical
# per-vote verify can never block behind an XLA compile. The thread
# handle is kept so tests (and embedders) can join before reading.
_SR_WARM = False
_SR_WARM_THREAD = None
# bumped (under _SR_WARM_LOCK) by every install() BEFORE the shared
# verifier swap: a warm thread only publishes its result if its
# generation is still current, so a slow warm from a superseded install
# can never vouch for a verifier it didn't compile
_SR_WARM_GEN = 0
_SR_WARM_LOCK = threading.Lock()


def installed() -> Optional[int]:
    """The currently-installed min_batch threshold, or None if the
    device factory has never been registered. Install state is
    process-global (one device runtime per process); multi-node
    embedders share whichever install ran last."""
    return _MIN_BATCH if _INSTALLED else None


def stats() -> dict:
    """Device-path usage counters — lets the node (and tests) assert the
    batch path actually runs on device in the served configuration."""
    return {
        "batches": int(_m_batches.value()),
        "sigs": int(_m_sigs.value()),
    }


def _factory(size_hint: int) -> Optional[BatchVerifier]:
    if 0 < size_hint < _MIN_BATCH:
        return None  # CPU fallback for tiny batches
    return TpuEd25519BatchVerifier(_SHARED_VERIFIER)


def _factory_sr(size_hint: int) -> Optional[BatchVerifier]:
    # per-curve threshold: the sr25519 CPU fallback is pure-Python
    # ristretto (~6 ms/sig), so on a real accelerator ANY batch —
    # including a single signature — wins on device; the shared
    # min-batch gate only applies where the CPU path is native-fast
    min_b = 1 if on_accelerator() else _MIN_BATCH
    if 0 < size_hint < min_b:
        return None
    return TpuSr25519BatchVerifier(_SHARED_VERIFIER_SR)


def single_sr_verifier() -> Optional[BatchVerifier]:
    """A device batch verifier for ONE sr25519 signature, or None when
    the device path is not installed / not worthwhile (CPU backend).
    Used by PubKeySr25519.verify_signature so per-vote and evidence
    verifies ride the kernel — through the installed (possibly
    mesh-sharded) verifier and the tpu metrics, same as batches.
    Gated on the warm flag: until install()'s background thread has
    compiled the smallest sr25519 bucket, singles stay on the CPU path
    instead of stalling a vote behind the first XLA compile."""
    if not (_INSTALLED and _SR_WARM):
        return None
    return _factory_sr(1)


def trip_sr_singles() -> None:
    """Demote single sr25519 verifies back to the CPU path after a
    device fault (called by PubKeySr25519.verify_signature's fallback).
    Without the trip, a persistently faulted device would be re-tried —
    and a warning logged — on every per-vote verify. A fresh warm probe
    is started immediately: if the fault was transient the probe's
    successful device verify re-arms the route; if the device is truly
    down the probe fails quietly and singles stay on CPU (one probe per
    trip — no retry storm, and batches keep their own error paths)."""
    global _SR_WARM
    with _SR_WARM_LOCK:
        _SR_WARM = False
    if _INSTALLED:
        # one probe at a time (enforced inside, under the gate lock),
        # and not immediately: if the fault is a wedge rather than a
        # raising error, an instant re-touch of the device would just
        # hang another thread (device-claim discipline: never pile onto
        # a wedged claim)
        _start_sr_warm_thread(delay_s=10.0, single_flight=True)


def _start_sr_warm_thread(
    delay_s: float = 0.0, single_flight: bool = False
) -> None:
    """Compile the smallest sr25519 bucket off the install() path, then
    flip _SR_WARM so single verifies start routing to the device. Runs
    on a daemon thread: install() itself must never touch the backend
    (a wedged device claim would hang node startup — PERF.md claim
    discipline), and a warm that stalls only delays the device upgrade
    of single verifies, never a vote."""
    global _SR_WARM_THREAD, _SR_WARM_GEN

    with _SR_WARM_LOCK:
        if single_flight and (
            _SR_WARM_THREAD is not None and _SR_WARM_THREAD.is_alive()
        ):
            # a probe is already in flight (alive-check and thread
            # publication share this lock, so concurrent trips cannot
            # both slip past it)
            return
        # snapshot generation AND verifier together: the probe must
        # only ever vouch for the verifier it actually compiled, and
        # install() swaps both under this same lock
        gen = _SR_WARM_GEN
        snap = _SHARED_VERIFIER_SR
        # publish the thread object under the same lock as the alive
        # check above; `warm` is late-bound — defined below, before
        # start() runs
        _SR_WARM_THREAD = thread = threading.Thread(
            target=lambda: warm(), daemon=True, name="sr25519-warm"
        )

    def publish(ok: bool) -> None:
        """Set the warm flag iff this thread's snapshot is still
        current — checked and written under the gate lock so a
        superseded warm (older generation OR swapped verifier) can
        never vouch for a verifier it didn't compile."""
        global _SR_WARM
        with _SR_WARM_LOCK:
            if (
                ok
                and gen == _SR_WARM_GEN
                and snap is _SHARED_VERIFIER_SR
            ):
                _SR_WARM = True

    def warm() -> None:
        try:
            if delay_s:
                time.sleep(delay_s)
            if not on_accelerator() and _MIN_BATCH > 1:
                # CPU process with the min-batch gate keeping singles
                # off the kernel: nothing to compile. (min_batch <= 1
                # would route singles to the CPU-backend kernel, so
                # that case falls through to the real probe below.)
                publish(True)
                return
            from .sr25519 import PrivKeySr25519

            priv = PrivKeySr25519.from_seed(b"\x77" * 32)
            msg = b"sr25519-warm"
            v = snap
            if v is None:
                from ..ops import sr25519_kernel

                v = sr25519_kernel.default_verifier()
            ok = v.verify(
                [priv.pub_key().bytes()], [msg], [priv.sign(msg)]
            )
            publish(bool(ok.all()))
        except Exception as e:  # pragma: no cover - warm is best-effort
            from ..libs.log import get_logger

            get_logger("crypto.tpu").warning(
                "sr25519 device warm-up failed; singles stay on CPU",
                err=repr(e),
            )

    thread.start()


def install(
    min_batch: int = DEFAULT_MIN_BATCH, mesh=None
) -> None:
    """Register the device factories (ed25519 + sr25519). With a mesh,
    ed25519 batches are sharded across it
    (tendermint_tpu.parallel.sharding); otherwise single-chip."""
    global _SHARED_VERIFIER, _SHARED_VERIFIER_SR, _MIN_BATCH, _INSTALLED
    global _SR_WARM, _SR_WARM_GEN
    _MIN_BATCH = min_batch
    _INSTALLED = True
    # warm the native keccak library here (a subprocess cc compile on
    # first use) so the first consensus-critical sr25519 verify never
    # stalls behind a compiler
    from .merlin import _native_lib

    _native_lib()
    if mesh is not None:
        from ..parallel.sharding import (
            ShardedEd25519Verifier,
            ShardedSr25519Verifier,
        )

        new_ed = ShardedEd25519Verifier(mesh)
        new_sr = ShardedSr25519Verifier(mesh)
    else:
        new_ed = None
        new_sr = None
    # gate drop + generation bump + verifier swap are ONE atomic step:
    # a concurrent vote (or a trip-started warm probe) must never see
    # the new uncompiled verifier behind a still-true warm flag, nor a
    # current generation paired with the old verifier
    with _SR_WARM_LOCK:
        _SR_WARM = False
        _SR_WARM_GEN += 1
        _SHARED_VERIFIER = new_ed
        _SHARED_VERIFIER_SR = new_sr
    _WARM_BUCKETS.clear()  # new generation: every bucket is cold again
    register_device_factory("ed25519", _factory)
    register_device_factory("sr25519", _factory_sr)
    _start_sr_warm_thread()
    # merged multi-commit batches (light sequential windows) only pay
    # off on an accelerator ONCE THIS FACTORY IS INSTALLED: _factory
    # serves every >=_MIN_BATCH batch regardless of backend, and on a
    # CPU-backed JAX kernel the bucket padding of a merged window
    # inverts the win (measured 5x slower). Uninstalled processes get
    # batch.native_cpu_affinity's module default instead (the native
    # RLC equation is exact-size, so merging wins there). The decision
    # needs jax.default_backend(), which initializes the backend —
    # deferred to first use so a wedged device claim cannot hang
    # install() itself at node startup (PERF.md, claim discipline).
    from .batch import set_group_affinity_fn

    def _affinity() -> int:
        import jax

        return 32 if jax.default_backend() == "tpu" else 1

    set_group_affinity_fn(_affinity)


def uninstall() -> None:
    """Remove the device factories and reset install state — the
    counterpart of install(), mirroring ops/merkle_kernel.uninstall()
    (tests and embedders switching a node back to the CPU seam). The
    generation bump retires any in-flight warm thread — it only
    publishes under a current generation — and the merged-window
    affinity falls back to the module default
    (batch.native_cpu_affinity) unless an operator pinned a value
    explicitly."""
    global _SHARED_VERIFIER, _SHARED_VERIFIER_SR, _MIN_BATCH, _INSTALLED
    global _SR_WARM, _SR_WARM_GEN
    from .batch import (
        native_cpu_affinity,
        set_group_affinity_fn,
        unregister_device_factory,
    )

    unregister_device_factory("ed25519")
    unregister_device_factory("sr25519")
    with _SR_WARM_LOCK:
        _SR_WARM = False
        _SR_WARM_GEN += 1
        _SHARED_VERIFIER = None
        _SHARED_VERIFIER_SR = None
    _WARM_BUCKETS.clear()
    _MIN_BATCH = DEFAULT_MIN_BATCH
    _INSTALLED = False
    set_group_affinity_fn(native_cpu_affinity)
