"""Device-backed BatchVerifier — the TPU side of the plugin boundary.

The reference gates all batch verification behind crypto.BatchVerifier
(crypto/crypto.go:53-61) with curve25519-voi underneath
(crypto/ed25519/ed25519.go:202-237). Here the implementation underneath
is the XLA program in tendermint_tpu.ops.ed25519_kernel; install() makes
crypto.batch.create_batch_verifier return it for ed25519 keys when the
batch is large enough to beat host latency. CPU remains the default
until install() is called, exactly like the reference defaults to pure
Go.

Device-fault containment: the device is treated as an UNRELIABLE
coprocessor (docs/resilience.md). Every dispatch/gather is a fault
point of crypto/faults.py; gathers run under a deadline watchdog
(a hung device surfaces as DeviceTimeout instead of wedging consensus);
a faulted batch is transparently re-verified through the registered CPU
factory with byte-identical result semantics (same bitmap alignment,
so the same wrong-signature index) and is never allowed to populate the
verified-signature cache. Each route consults a named circuit breaker
(crypto/breaker.py): a tripped breaker sends new work straight to the
CPU factories — zero per-call device touches, zero per-call warnings —
until a single-flight background probe proves the device again.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional, Tuple

from ..libs import metrics as M
from ..libs import trace
from . import breaker as _breaker_mod
from . import faults
from .batch import cpu_factory, register_device_factory
from .faults import DeviceFault, DeviceTimeout
from .keys import BatchVerifier, PubKey

# device-offload observability (no reference analog — this is the
# north-star seam's instrumentation). Deliberately process-global on
# DEFAULT_REGISTRY, unlike the per-node subsystem metrics: there is one
# device runtime per process, and multi-node embedders share it.
_m_batches = M.new_counter(
    "tpu", "verify_batches_total", "Device batch-verify invocations."
)
_m_sigs = M.new_counter(
    "tpu", "verify_sigs_total", "Signatures verified on device."
)
_m_device_faults = M.new_counter(
    "tpu",
    "device_faults_total",
    "Device faults contained (raise/timeout/mis-shape/disproven result).",
)
_m_verify_time = M.new_histogram(
    "tpu",
    "verify_seconds",
    "Wall time of one batch verification.",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5),
)
# dispatch telemetry: decompose verify_seconds into the host-side
# assembly (packing triples into device arrays + async launch) and the
# device wall (gather barrier) — the split PERF.md demands before any
# device number is believed — plus bucket-padding waste and
# warm-generation hit/miss for compile-stall attribution.
_m_host_prep = M.new_histogram(
    "tpu",
    "host_prep_seconds",
    "Host-side packing + async dispatch of one batch.",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.25),
)
_m_device_wall = M.new_histogram(
    "tpu",
    "device_wall_seconds",
    "Device wall time (gather barrier) of one batch.",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5),
)
_m_pad_waste = M.new_counter(
    "tpu",
    "pad_waste_slots_total",
    "Signature slots wasted padding batches to bucket shapes.",
)
_m_warm_hits = M.new_counter(
    "tpu",
    "warm_bucket_hits_total",
    "Dispatches into a bucket already run this install generation.",
)
_m_warm_misses = M.new_counter(
    "tpu",
    "warm_bucket_misses_total",
    "First dispatches into a bucket (likely paying an XLA compile).",
)

__all__ = [
    "TpuEd25519BatchVerifier",
    "TpuSr25519BatchVerifier",
    "DeviceFault",
    "DeviceTimeout",
    "install",
    "installed",
    "stats",
    "DEFAULT_MIN_BATCH",
    "DEFAULT_GATHER_DEADLINE_S",
]

# Below this many signatures the fixed dispatch cost (host packing +
# device roundtrip, ~100s of µs) exceeds CPU verify time; let CPU win.
DEFAULT_MIN_BATCH = 8

# Gather deadline when none is configured. XLA compiles block in
# dispatch() (tracing + compile are synchronous), so the gather barrier
# of an already-launched program on a healthy chip is sub-second plus
# the ~50 ms tunnel RTT; 60 s of silence at the barrier means a wedged
# claim or a dead relay, not a slow batch.
DEFAULT_GATHER_DEADLINE_S = 60.0

# lazily cached "is the backend a real accelerator" decision
_STREAMING: Optional[bool] = None

# (key type, backing verifier id, bucket) triples dispatched at least
# once since the last install()/uninstall(): first touch of a bucket
# shape likely pays an XLA compile, so dispatch telemetry labels it a
# warm miss. Cleared on install/uninstall — a new generation's programs
# are cold again.
_WARM_BUCKETS: set = set()


def _bucket_of(verifier, n: int) -> int:
    """The padded bucket `n` signatures land in, from the backing
    verifier's configured sizes (without importing the jax-backed ops
    module: telemetry must not initialize a backend)."""
    sizes = getattr(verifier, "bucket_sizes", None)
    if not sizes:
        from ..config import DEFAULT_BUCKET_SIZES

        sizes = DEFAULT_BUCKET_SIZES
    for b in sorted(sizes):
        if b >= n:
            return b
    return n


def _note_bucket_warmth(key_type: str, verifier, bucket: int) -> bool:
    """Record (and count) whether this bucket shape has been dispatched
    before in this install generation. Returns the hit/miss verdict for
    the span attributes."""
    key = (key_type, id(verifier), bucket)
    if key in _WARM_BUCKETS:
        _m_warm_hits.inc()
        return True
    # tmlint: disable=lock-global-mutation — telemetry-only set;
    # a racing probe thread at worst double-counts one warm miss
    _WARM_BUCKETS.add(key)
    _m_warm_misses.inc()
    return False


def on_accelerator() -> bool:
    """True when this process's jax backend is a real accelerator.

    CPU-pinned processes (jax_platforms == "cpu" — the test suite, any
    CPU-only node) are answered from the config STRING without
    initializing a backend, so consensus-critical callers like
    sr25519's single-verify route never stall on backend init just to
    learn they should use the Python path. A process with no TPU
    runtime installed at all (no libtpu wheel) is likewise answered
    without backend init. Everything else pays one backend query,
    cached — those processes are about to dispatch to the device
    anyway. CPU-only deployments that leave jax_platforms unset and DO
    ship libtpu should set jax_platforms=cpu explicitly to keep jax
    backend initialization out of the first verify call."""
    global _STREAMING
    if _STREAMING is None:
        import jax

        plats = None
        try:
            plats = jax.config.jax_platforms  # no backend init
        except AttributeError:  # pragma: no cover - very old jax
            pass
        if plats and set(plats.split(",")) == {"cpu"}:
            # tmrace: race-ok — idempotent latch: every racer computes
            # the same value from process-wide config; bool store is
            # GIL-atomic
            _STREAMING = False
        elif not plats and not _has_tpu_runtime():
            # only an UNSET platform string consults the runtime sniff:
            # an explicit jax_platforms=tpu (e.g. libtpu loaded via
            # TPU_LIBRARY_PATH, no importable module) must reach the
            # backend query, symmetric with the explicit-cpu case
            _STREAMING = False  # tmrace: race-ok — same idempotent latch
        else:
            # tmrace: race-ok — same idempotent latch (jax backend init
            # is internally synchronized)
            _STREAMING = jax.default_backend() == "tpu"
    return _STREAMING


def _has_tpu_runtime() -> bool:
    """Whether a TPU runtime could plausibly be attached, decided
    WITHOUT initializing a jax backend: the libtpu wheel must be
    importable (jax's own TPU discovery path). On boxes without it,
    jax.default_backend() could only ever answer cpu/gpu — so answering
    False here is exact, and keeps backend init out of the verify hot
    path on CPU-only nodes with jax_platforms unset."""
    import importlib.util

    import os

    if os.environ.get("TPU_LIBRARY_PATH"):
        # libtpu attached via env var, no importable module
        return True
    try:
        return (
            importlib.util.find_spec("libtpu") is not None
            or importlib.util.find_spec("jax_plugins") is not None
        )
    except (ImportError, ValueError):  # pragma: no cover - spec quirks
        return True  # unknown: fall through to the backend query


# -- fault containment plumbing --------------------------------------


# (env string, parsed deadline) — the string is still read per call so
# tests can flip the env var, but the float parse is paid once per value
_DEADLINE_CACHE: tuple = (None, None)


def gather_deadline() -> Optional[float]:
    """The gather watchdog deadline, or None (direct call, no watchdog
    thread). TM_TPU_GATHER_DEADLINE_S pins it explicitly (0 disables);
    otherwise the default applies only where a gather can actually
    wedge — a real accelerator behind a claim/tunnel — or while the
    fault plane is armed (chaos tests exercise the hang mode). Plain
    CPU-backed processes keep a thread-free hot path."""
    global _DEADLINE_CACHE
    env = os.environ.get("TM_TPU_GATHER_DEADLINE_S")
    if env is not None:
        if _DEADLINE_CACHE[0] != env:
            try:
                dl = float(env)  # tmlint: disable=dev-host-sync — env-var string, host data
            except ValueError:
                dl = DEFAULT_GATHER_DEADLINE_S
            # tmrace: race-ok — idempotent per env value; racers
            # parse the same string and the tuple store is GIL-atomic
            _DEADLINE_CACHE = (env, dl if dl > 0 else None)
        return _DEADLINE_CACHE[1]
    if faults.armed() or on_accelerator():
        return DEFAULT_GATHER_DEADLINE_S
    return None


# Abandoned watchdog workers still blocked inside a wedged gather.
# Bounded: once the cap is hit, further deadline calls fail fast with
# DeviceTimeout instead of stacking another forever-blocked thread —
# otherwise the breaker's periodic probes against a dead device would
# leak one thread per probe for the life of the process. Healthy
# workers are recycled through a small free-list, so the steady-state
# hot path pays one Event set/wait per gather, not a thread spawn.
_MAX_WEDGED_GATHERS = 8
_MAX_IDLE_WATCHDOGS = 4
_IDLE_WATCHDOGS: list = []  # guarded by _wedged_lock
_wedged_gathers = 0
_wedged_lock = threading.Lock()


class _Watchdog:
    """One reusable daemon worker: runs one job at a time, parks on an
    Event between jobs. A worker whose job wedged is abandoned (never
    returned to the free-list) and retires itself if the job ever
    finishes; a daemon thread cannot block process exit either way."""

    __slots__ = ("_job", "_wake", "thread")

    def __init__(self) -> None:
        self._job = None
        self._wake = threading.Event()
        self.thread = threading.Thread(
            target=self._loop, daemon=True, name="tpu-gather-watchdog"
        )
        self.thread.start()

    def run(self, job: tuple) -> None:
        # tmrace: race-ok — Event handshake: the _job store
        # happens-before _wake.set(), and a worker is owned by exactly
        # one caller between its free-list pop (under _wedged_lock) and
        # its requeue, so no second run() can interleave
        self._job = job
        self._wake.set()

    def _loop(self) -> None:
        global _wedged_gathers
        while True:
            # tmlive: block-ok — parked watchdog worker between jobs:
            # blocking HERE is this daemon thread's whole job (it
            # exists so the *caller* can bound its wait with
            # done.wait(deadline_s)); an idle worker must cost zero CPU
            self._wake.wait()
            # tmrace: race-ok — other half of the run() Event
            # handshake: wait() returned, so the owner's _job store is
            # visible, and nobody re-runs this worker until it requeues
            self._wake.clear()
            fn, result, done, state = self._job
            self._job = None  # tmrace: race-ok — same handshake
            try:
                result["val"] = fn()
            except BaseException as e:  # delivered to the caller
                result["exc"] = e
            with _wedged_lock:
                done.set()  # inside the lock: atomic vs timeout path
                if state["abandoned"]:
                    # the wedge finally resolved; the slot frees but
                    # this worker retires (its result was discarded)
                    _wedged_gathers -= 1
                    return
                if len(_IDLE_WATCHDOGS) >= _MAX_IDLE_WATCHDOGS:
                    return
                _IDLE_WATCHDOGS.append(self)


def _deadline_call(fn, deadline_s: float):
    """Run fn on a watchdog worker, bounded by deadline_s. On expiry
    the worker is ABANDONED (a blocked gather cannot be interrupted
    from Python) and DeviceTimeout raises in the caller — the breaker
    then keeps everyone else off the wedged claim. Abandoned-but-
    still-blocked workers are counted and capped (_MAX_WEDGED_GATHERS):
    at the cap, calls fail fast, so a permanently dead device costs a
    fixed number of parked threads, not one per probe."""
    global _wedged_gathers
    with _wedged_lock:
        if _wedged_gathers >= _MAX_WEDGED_GATHERS:
            raise DeviceTimeout(
                f"device gather skipped: {_wedged_gathers} wedged "
                f"gathers already outstanding"
            )
        w = _IDLE_WATCHDOGS.pop() if _IDLE_WATCHDOGS else None
    if w is None:
        w = _Watchdog()
    result: dict = {}
    state = {"abandoned": False}
    done = threading.Event()
    w.run((fn, result, done, state))
    if not done.wait(deadline_s):
        with _wedged_lock:
            if not done.is_set():  # really wedged, not a photo finish
                state["abandoned"] = True
                _wedged_gathers += 1
        if state["abandoned"]:
            raise DeviceTimeout(
                f"device gather exceeded its {deadline_s}s deadline"
            )
    if "exc" in result:
        raise result["exc"]
    return result["val"]


def _gather_guarded(v, handle, key_type: str) -> List[bool]:
    """One gather with the full containment stack: fault-plane hooks
    (raise/hang fire inside the watchdog so a hang surfaces as
    DeviceTimeout), the deadline, and data-fault mangling applied to
    the bitmap exactly where a broken device would corrupt it."""

    def call():
        if faults.armed():
            faults.fire("tpu.gather", key=key_type)
        return v.gather(handle)

    dl = gather_deadline()
    out = call() if dl is None else _deadline_call(call, dl)
    bits = [bool(b) for b in out]
    if faults.armed():
        bits = faults.mangle("tpu.gather", bits, key=key_type)
    return bits


def _breaker(key_type: str):
    return _breaker_mod.breaker_for(key_type)


class _RoutedToCpu(Exception):
    """Internal: the breaker is open — reroute silently, no fault."""


class _TpuBatchVerifier(BatchVerifier):
    """Queues triples on host, verifies on device.

    Same verify() contract as the CPU path: (all_ok, bitmap), bitmap
    aligned with add() order, malformed entries reported invalid
    per-index rather than raising at verify time.

    On a TPU backend, full STREAM_CHUNK-sized slices are dispatched
    asynchronously AS add() fills them, so the host-side assembly loop
    (sign-bytes, address lookups — ~2 us/sig in VerifyCommit) overlaps
    device compute instead of serializing in front of it; verify()
    dispatches the remainder and gathers every in-flight handle in add
    order. The chunk matches a configured bucket so no new program
    shapes are compiled.

    Fault containment: every triple is retained (as references) until
    verify() returns, so ANY device failure — a raising dispatch, a
    gather past its deadline, a mis-shaped bitmap, a device-invalidated
    lane the CPU disproves — drains the batch through the registered
    CPU factory instead. The CPU bitmap is add-order aligned, so
    callers see the same wrong-signature index either way; `faulted`
    is left True so crypto.batch.drain_and_cache refuses to populate
    the verified-signature cache from a batch the device touched and
    lied about (or died under)."""

    KEY_TYPE = ""  # subclasses set
    STREAM_CHUNK = 2048  # == a DEFAULT_BUCKET_SIZES entry

    def __init__(self, verifier=None) -> None:
        self._verifier = verifier
        self._kernel = self._kernel_module()
        # authoritative add-order record, kept until verify() returns
        # (the CPU re-verify fallback needs the PubKey objects)
        self._all: List[Tuple[PubKey, bytes, bytes]] = []
        # pending window awaiting dispatch (bytes for the kernel)
        self._pks: List[bytes] = []
        self._msgs: List[bytes] = []
        self._sigs: List[bytes] = []
        self._handles: List[tuple] = []  # (backing, handle, n), add order
        self._stream_fault: Optional[BaseException] = None
        self.faulted = False  # True once a device fault was contained
        # dispatch telemetry accumulated across THIS one-shot batch
        # (streaming chunks launch from add(), before verify() runs)
        self._last_bucket = 0
        self._pad_waste = 0
        self._cold_dispatch = False

    @staticmethod
    def _kernel_module():
        raise NotImplementedError

    def _backing(self):
        return (
            self._verifier
            if self._verifier is not None
            else self._kernel.default_verifier()
        )

    @staticmethod
    def _streaming() -> bool:
        """Chunked dispatch only pays on an accelerator (CPU 'device'
        programs are the bottleneck themselves, and extra bucket shapes
        would mean extra test-suite compiles)."""
        return on_accelerator()

    def _account_dispatch(self, v, n: int) -> None:
        """Telemetry for ONE device dispatch of n triples: bucket
        padding waste and warm-generation hit/miss. Called on every
        launch — streaming chunks from add() included, since that is
        exactly where a first-touch XLA compile stalls the hot path."""
        bucket = _bucket_of(v, n)
        waste = bucket - n
        self._last_bucket = bucket
        if waste:
            self._pad_waste += waste
            _m_pad_waste.inc(waste)
        if not _note_bucket_warmth(self.KEY_TYPE, v, bucket):
            self._cold_dispatch = True

    def _dispatch_pending(self, v) -> None:
        """Asynchronously launch the queued triples on `v` and clear
        the queue; the handle is gathered in verify(). Each dispatch is
        one device invocation for the metrics."""
        if faults.armed():
            faults.fire("tpu.dispatch", key=self.KEY_TYPE)
        self._account_dispatch(v, len(self._pks))
        self._handles.append(
            (v, v.dispatch(self._pks, self._msgs, self._sigs),
             len(self._pks))
        )
        self._pks, self._msgs, self._sigs = [], [], []
        _m_batches.inc()

    def add(self, pub_key: PubKey, message: bytes, signature: bytes) -> None:
        if pub_key.type() != self.KEY_TYPE:
            raise TypeError(
                f"{type(self).__name__} requires {self.KEY_TYPE} keys"
            )
        if len(signature) != 64:
            raise ValueError("malformed signature size")
        message = bytes(message)
        signature = bytes(signature)
        self._all.append((pub_key, message, signature))
        self._pks.append(pub_key.bytes())
        self._msgs.append(message)
        self._sigs.append(signature)
        if (
            len(self._pks) >= self.STREAM_CHUNK
            and self._streaming()
            and self._stream_fault is None
        ):
            v = self._backing()
            # injected verifiers only promise verify(); stream solely
            # when the dispatch()/gather() pair is actually there —
            # and only onto a fully healthy route (state(), not
            # allow(): a chunk launch must never consume the one
            # half-open admission ticket the factory gate hands out)
            if (
                hasattr(v, "dispatch")
                and hasattr(v, "gather")
                and _breaker(self.KEY_TYPE).state() == _breaker_mod.CLOSED
            ):
                try:
                    self._dispatch_pending(v)
                except Exception as e:
                    # a faulted async launch must not raise out of
                    # add() — its contract is malformed-input errors
                    # only. The window stays queued; verify() sees the
                    # recorded fault and drains everything on CPU.
                    self._stream_fault = e

    def verify(self) -> Tuple[bool, List[bool]]:
        """Drains the queue: a verifier is a one-shot batch (matching
        the reference's use — one BatchVerifier per commit); calling
        verify() again without new add()s reports (False, []) on every
        backend. In streaming mode verify_seconds times the remainder
        dispatch + gather barrier (chunk dispatches already ran inside
        add, overlapped with the caller's assembly loop).

        The tpu_dispatch span (and the host_prep/device_wall
        histograms) split the wall time at the async-launch boundary:
        everything before the handle exists is host packing, everything
        after is the device barrier. Backings without the
        dispatch()/gather() pair (injected test verifiers) report one
        undivided wall time.

        Any device fault — including a mis-shaped bitmap or a lane the
        device invalidated that the CPU disproves — re-verifies the
        WHOLE batch through the CPU factory (a faulted device's earlier
        answers are not trusted either), records the fault on this key
        type's breaker, and marks the batch `faulted` so its results
        never reach the verified-signature cache. tpu_verify_sigs_total
        counts only work the device actually completed."""
        if not self._all and not self._handles:
            return False, []
        t0 = time.perf_counter()
        with trace.span(
            "tpu_dispatch", hist=_m_verify_time, key=self.KEY_TYPE
        ):
            work = self._all
            total = len(work)
            v = self._backing()
            bits: Optional[List[bool]] = None
            fault: Optional[BaseException] = None
            device_sigs = 0  # lanes with a COMPLETED device verdict
            host_prep: Optional[float] = None
            try:
                if self._stream_fault is not None:
                    raise self._stream_fault
                # side-effect-free OPEN check (not allow(): this
                # verifier was already admitted at creation — possibly
                # holding the route's one half-open ticket, which a
                # second allow() here would have burned, wedging the
                # breaker in HALF_OPEN forever). A HALF_OPEN attempt
                # proceeds and reports its outcome below: the admitted
                # verifier IS the probe on probe-less breakers.
                if (
                    not self._handles
                    and _breaker(self.KEY_TYPE).state() == _breaker_mod.OPEN
                ):
                    raise _RoutedToCpu()
                if self._handles:
                    if self._pks:
                        self._dispatch_pending(v)
                    host_prep = time.perf_counter() - t0
                    got: List[bool] = []
                    try:
                        for bv, handle, n in self._handles:
                            lane = _gather_guarded(bv, handle, self.KEY_TYPE)
                            if len(lane) != n:
                                raise DeviceFault(
                                    f"mis-shaped device result: "
                                    f"{len(lane)} lanes for {n} signatures"
                                )
                            got.extend(lane)
                            device_sigs += n
                    finally:
                        # a gather that raises mid-loop must still
                        # leave the verifier drained: a retry would
                        # otherwise re-gather stale handles, and
                        # __len__ would keep reporting in-flight work
                        self._handles = []
                    bits = got
                elif hasattr(v, "dispatch") and hasattr(v, "gather"):
                    # split verify() at the same boundary the streaming
                    # path uses (gather(dispatch()) is v.verify())
                    self._account_dispatch(v, len(self._pks))
                    if faults.armed():
                        faults.fire("tpu.dispatch", key=self.KEY_TYPE)
                    handle = v.dispatch(self._pks, self._msgs, self._sigs)
                    host_prep = time.perf_counter() - t0
                    _m_batches.inc()
                    bits = _gather_guarded(v, handle, self.KEY_TYPE)
                    if len(bits) != total:
                        raise DeviceFault(
                            f"mis-shaped device result: {len(bits)} "
                            f"lanes for {total} signatures"
                        )
                    device_sigs = total
                else:
                    self._account_dispatch(v, len(self._pks))
                    if faults.armed():
                        faults.fire("tpu.dispatch", key=self.KEY_TYPE)
                    raw = v.verify(self._pks, self._msgs, self._sigs)
                    _m_batches.inc()
                    bits = [bool(b) for b in raw]
                    if faults.armed():
                        bits = faults.mangle(
                            "tpu.gather", bits, key=self.KEY_TYPE
                        )
                    if len(bits) != total:
                        raise DeviceFault(
                            f"mis-shaped device result: {len(bits)} "
                            f"lanes for {total} signatures"
                        )
                    device_sigs = total
                if not all(bits):
                    self._disprove_invalid_lanes(work, bits)
            except _RoutedToCpu:
                bits = None  # silent reroute: breaker already open
            except Exception as e:
                bits = None
                fault = e
            finally:
                # one-shot on every path: success, fault, or reroute
                self._handles = []
                self._pks, self._msgs, self._sigs = [], [], []
                self._all = []
                self._stream_fault = None
            if bits is None:
                _m_sigs.inc(device_sigs)
                return self._cpu_fallback(work, fault, total)
            _breaker(self.KEY_TYPE).record_success()
            if host_prep is not None:
                device_wall = time.perf_counter() - t0 - host_prep
                _m_host_prep.observe(host_prep)
                _m_device_wall.observe(device_wall)
                trace.add_attrs(
                    host_prep_s=round(host_prep, 6),
                    device_wall_s=round(device_wall, 6),
                )
            trace.add_attrs(
                batch=total,
                bucket=self._last_bucket,
                pad_waste=self._pad_waste,
                warm=not self._cold_dispatch,
            )
        _m_sigs.inc(device_sigs)
        return all(bits), bits

    def _disprove_invalid_lanes(self, work, bits: List[bool]) -> None:
        """Cross-examine every lane the device called invalid against a
        CPU verify. A genuinely wrong signature fails both ways (the
        normal cost: one CPU verify per bad lane, on an exceptional
        path); a lane the CPU verifies is a device lie — a bit-flipped
        result — and the whole batch is escalated to a fault. The
        asymmetric flip (bad signature reported GOOD) cannot be caught
        without re-verifying everything; it is excluded by the batch
        equation itself on a correct program, and chaos coverage pins
        the symmetric case (tests/test_faults.py).

        The oracle must be HOST-ONLY: key types whose verify_signature
        routes singles back to the device (sr25519) expose
        verify_signature_cpu for exactly this — an oracle that asked
        the device about the device's own verdict could never catch it
        lying (and would recurse through the single route)."""
        for i, ok in enumerate(bits):
            if ok:
                continue
            pub_key, msg, sig = work[i]
            oracle = getattr(
                pub_key, "verify_signature_cpu", pub_key.verify_signature
            )
            if oracle(msg, sig):
                raise DeviceFault(
                    f"device invalidated lane {i} but the CPU verifies "
                    f"it: result disproven"
                )

    def _cpu_fallback(self, work, fault, total: int) -> Tuple[bool, List[bool]]:
        """Drain `work` through the registered CPU factory. With
        `fault` set this is containment (breaker notified, fault
        counted, batch marked so the sigcache never learns from it);
        with fault=None the breaker was already open and this is just
        the quiet degraded route."""
        if fault is not None:
            self.faulted = True
            _m_device_faults.inc()
            _breaker(self.KEY_TYPE).record_failure()
            from ..libs.log import get_logger

            get_logger("crypto.tpu").warning(
                "device batch fault contained; re-verifying on CPU",
                key=self.KEY_TYPE,
                sigs=total,
                err=repr(fault),
            )
        trace.add_attrs(batch=total, fallback="cpu")
        cpu = cpu_factory(self.KEY_TYPE)
        if cpu is None:  # no CPU fallback registered: surface the fault
            if fault is not None:
                raise fault
            raise RuntimeError(
                f"no CPU batch factory for {self.KEY_TYPE!r}"
            )
        bv = cpu()
        for pub_key, msg, sig in work:
            bv.add(pub_key, msg, sig)
        return bv.verify()

    def __len__(self) -> int:
        return len(self._all)


class TpuEd25519BatchVerifier(_TpuBatchVerifier):
    KEY_TYPE = "ed25519"

    @staticmethod
    def _kernel_module():
        from ..ops import ed25519_kernel

        return ed25519_kernel


class TpuSr25519BatchVerifier(_TpuBatchVerifier):
    """Device sr25519 batch verifier (reference: crypto/sr25519/batch.go
    backed by curve25519-voi; here ops/sr25519_kernel.py — ristretto
    decode + schnorrkel equation on the shared curve core)."""

    KEY_TYPE = "sr25519"

    @staticmethod
    def _kernel_module():
        from ..ops import sr25519_kernel

        return sr25519_kernel


_SHARED_VERIFIER = None
_SHARED_VERIFIER_SR = None
_MIN_BATCH = DEFAULT_MIN_BATCH
_INSTALLED = False

# The route breakers (crypto/breaker.py), by name:
#   "ed25519" / "sr25519"     the batch factories + streaming dispatch
#   "sr25519-single"          the per-vote single-verify device route
# The single route's breaker starts OPEN — "cold" and "tripped" are the
# same state: not currently proven. install() arms a probe that
# compiles/verifies the smallest bucket off the critical path and
# closes the breaker, replacing the old _SR_WARM flag; a device fault
# re-opens it with the same never-pile-onto-a-wedged-claim backoff the
# old trip_sr_singles delay implemented by hand.
_SR_SINGLE = "sr25519-single"

# cached self-signed probe triples, one per key type
_PROBE_TRIPLES: dict = {}


def installed() -> Optional[int]:
    """The currently-installed min_batch threshold, or None if the
    device factory has never been registered. Install state is
    process-global (one device runtime per process); multi-node
    embedders share whichever install ran last."""
    return _MIN_BATCH if _INSTALLED else None


def stats() -> dict:
    """Device-path usage counters — lets the node (and tests) assert the
    batch path actually runs on device in the served configuration."""
    return {
        "batches": int(_m_batches.value()),
        "sigs": int(_m_sigs.value()),
        "faults": int(_m_device_faults.value()),
    }


def _factory(size_hint: int) -> Optional[BatchVerifier]:
    if 0 < size_hint < _MIN_BATCH:
        return None  # CPU fallback for tiny batches
    if not _breaker("ed25519").allow():
        return None  # tripped breaker: CPU, silently
    return TpuEd25519BatchVerifier(_SHARED_VERIFIER)


def _factory_sr(size_hint: int) -> Optional[BatchVerifier]:
    # per-curve threshold: the sr25519 CPU fallback is pure-Python
    # ristretto (~6 ms/sig), so on a real accelerator ANY batch —
    # including a single signature — wins on device; the shared
    # min-batch gate only applies where the CPU path is native-fast
    min_b = 1 if on_accelerator() else _MIN_BATCH
    if 0 < size_hint < min_b:
        return None
    if not _breaker("sr25519").allow():
        return None  # tripped breaker: CPU, silently
    return TpuSr25519BatchVerifier(_SHARED_VERIFIER_SR)


def single_sr_verifier() -> Optional[BatchVerifier]:
    """A device batch verifier for ONE sr25519 signature, or None when
    the device path is not installed / not worthwhile (CPU backend).
    Used by PubKeySr25519.verify_signature so per-vote and evidence
    verifies ride the kernel — through the installed (possibly
    mesh-sharded) verifier and the tpu metrics, same as batches.
    Gated on the single-route breaker: until install()'s probe has
    compiled and proven the smallest sr25519 bucket the breaker stays
    open and singles stay on the CPU path — a vote can never stall
    behind the first XLA compile or pile onto a wedged claim."""
    if not _INSTALLED:
        return None
    if not sr_single_breaker().allow():
        return None
    return _factory_sr(1)


def sr_single_breaker():
    """The breaker guarding the sr25519 single-verify device route
    (created cold/OPEN if install() has not armed it yet)."""
    return _breaker_mod.breaker_for(_SR_SINGLE, start_open=True)


def _probe_triple(key_type: str) -> tuple:
    cached = _PROBE_TRIPLES.get(key_type)
    if cached is None:
        if key_type == "sr25519":
            from .sr25519 import PrivKeySr25519 as Priv
        else:
            from .ed25519 import PrivKeyEd25519 as Priv
        priv = Priv.from_seed(b"\x77" * 32)
        msg = b"breaker-probe-" + key_type.encode()
        cached = (priv.pub_key().bytes(), msg, priv.sign(msg))
        # tmlint: disable=lock-global-mutation — idempotent memo;
        # racing fills compute byte-identical values
        # tmlive: bounded=keyed by key_type, a fixed two-element set
        # (ed25519/sr25519); one cached probe triple per key type
        _PROBE_TRIPLES[key_type] = cached
    return cached


def _device_probe(key_type: str, backing) -> bool:
    """One self-signed signature end-to-end through the device path,
    with the SAME fault hooks and gather deadline as production
    traffic — so a probe against a still-faulty device fails exactly
    like the traffic it stands in for, and a probe against a healed
    one proves the route. Used single-flight by the breakers; never
    called from consensus threads."""
    pk, msg, sig = _probe_triple(key_type)
    v = backing()
    if faults.armed():
        faults.fire("tpu.dispatch", key=key_type)
    if hasattr(v, "dispatch") and hasattr(v, "gather"):
        handle = v.dispatch([pk], [msg], [sig])
        bits = _gather_guarded(v, handle, key_type)
    else:
        raw = v.verify([pk], [msg], [sig])
        bits = [bool(b) for b in raw]
        if faults.armed():
            bits = faults.mangle("tpu.gather", bits, key=key_type)
    return len(bits) == 1 and bool(bits[0])


def _ed_backing():
    if _SHARED_VERIFIER is not None:
        return _SHARED_VERIFIER
    from ..ops import ed25519_kernel

    return ed25519_kernel.default_verifier()


def _sr_backing():
    if _SHARED_VERIFIER_SR is not None:
        return _SHARED_VERIFIER_SR
    from ..ops import sr25519_kernel

    return sr25519_kernel.default_verifier()


def _sr_single_probe() -> bool:
    """The single-route warm/re-arm probe: on a CPU process with the
    min-batch gate keeping singles off the kernel there is nothing to
    compile or prove — close immediately (the factory gate returns
    None for singles there anyway). Otherwise one real device verify
    of the smallest sr25519 bucket."""
    if not on_accelerator() and _MIN_BATCH > 1:
        return True
    return _device_probe("sr25519", _sr_backing)


def install(
    min_batch: int = DEFAULT_MIN_BATCH, mesh=None
) -> None:
    """Register the device factories (ed25519 + sr25519). With a mesh,
    ed25519 batches are sharded across it
    (tendermint_tpu.parallel.sharding); otherwise single-chip.

    Each install is a new breaker generation: fresh instances replace
    the registered ones, so a probe still in flight from a superseded
    install publishes into an orphaned object nobody consults — the
    atomicity the old _SR_WARM_GEN counter provided by hand."""
    global _SHARED_VERIFIER, _SHARED_VERIFIER_SR, _MIN_BATCH, _INSTALLED
    # tmrace: race-ok — install() runs on the startup/main thread; the
    # only cross-thread readers are breaker probes, and a probe from a
    # superseded generation publishes into an orphaned breaker (see
    # docstring), so a GIL-atomic old-or-new read mid-install is benign
    _MIN_BATCH = min_batch
    _INSTALLED = True  # tmrace: race-ok — same generation protocol
    # warm the native keccak library here (a subprocess cc compile on
    # first use) so the first consensus-critical sr25519 verify never
    # stalls behind a compiler
    from .merlin import _native_lib

    _native_lib()
    if mesh is not None:
        from ..parallel.sharding import (
            ShardedEd25519Verifier,
            ShardedSr25519Verifier,
        )

        new_ed = ShardedEd25519Verifier(mesh)
        new_sr = ShardedSr25519Verifier(mesh)
    else:
        new_ed = None
        new_sr = None
    # tmrace: race-ok — same generation protocol: a stale probe
    # reading the new verifier mid-swap still reports into an
    # orphaned breaker nobody consults
    _SHARED_VERIFIER = new_ed
    _SHARED_VERIFIER_SR = new_sr  # tmrace: race-ok — same protocol
    # new generation: every bucket is cold again
    # tmlint: disable=lock-global-mutation — install() runs on the
    # startup/main thread before traffic
    _WARM_BUCKETS.clear()
    b_ed = _breaker_mod.fresh("ed25519")
    b_ed.set_probe(lambda: _device_probe("ed25519", _ed_backing))
    b_sr = _breaker_mod.fresh("sr25519")
    b_sr.set_probe(lambda: _device_probe("sr25519", _sr_backing))
    b_single = _breaker_mod.fresh(_SR_SINGLE, start_open=True)
    b_single.set_probe(_sr_single_probe)
    # warm the single route off the install path: install() itself must
    # never touch the backend (a wedged device claim would hang node
    # startup — PERF.md claim discipline); a probe that stalls only
    # delays the device upgrade of single verifies, never a vote
    b_single.probe_now()
    register_device_factory("ed25519", _factory)
    register_device_factory("sr25519", _factory_sr)
    # merged multi-commit batches (light sequential windows) only pay
    # off on an accelerator ONCE THIS FACTORY IS INSTALLED: _factory
    # serves every >=_MIN_BATCH batch regardless of backend, and on a
    # CPU-backed JAX kernel the bucket padding of a merged window
    # inverts the win (measured 5x slower). Uninstalled processes get
    # batch.native_cpu_affinity's module default instead (the native
    # RLC equation is exact-size, so merging wins there). The decision
    # needs jax.default_backend(), which initializes the backend —
    # deferred to first use so a wedged device claim cannot hang
    # install() itself at node startup (PERF.md, claim discipline).
    from .batch import set_group_affinity_fn

    def _affinity() -> int:
        import jax

        return 32 if jax.default_backend() == "tpu" else 1

    set_group_affinity_fn(_affinity)


def uninstall() -> None:
    """Remove the device factories and reset install state — the
    counterpart of install(), mirroring ops/merkle_kernel.uninstall()
    (tests and embedders switching a node back to the CPU seam). The
    breakers are discarded — an in-flight probe publishes into an
    orphaned object — and the merged-window affinity falls back to the
    module default (batch.native_cpu_affinity) unless an operator
    pinned a value explicitly."""
    global _SHARED_VERIFIER, _SHARED_VERIFIER_SR, _MIN_BATCH, _INSTALLED
    from .batch import (
        native_cpu_affinity,
        set_group_affinity_fn,
        unregister_device_factory,
    )

    unregister_device_factory("ed25519")
    unregister_device_factory("sr25519")
    _SHARED_VERIFIER = None
    _SHARED_VERIFIER_SR = None
    # tmlint: disable=lock-global-mutation — uninstall() is a
    # main-thread test/embedder seam, never concurrent with traffic
    _WARM_BUCKETS.clear()
    _MIN_BATCH = DEFAULT_MIN_BATCH
    _INSTALLED = False
    for name in ("ed25519", "sr25519", _SR_SINGLE):
        _breaker_mod.discard(name)
    set_group_affinity_fn(native_cpu_affinity)
