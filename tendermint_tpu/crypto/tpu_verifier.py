"""Device-backed BatchVerifier — the TPU side of the plugin boundary.

The reference gates all batch verification behind crypto.BatchVerifier
(crypto/crypto.go:53-61) with curve25519-voi underneath
(crypto/ed25519/ed25519.go:202-237). Here the implementation underneath
is the XLA program in tendermint_tpu.ops.ed25519_kernel; install() makes
crypto.batch.create_batch_verifier return it for ed25519 keys when the
batch is large enough to beat host latency. CPU remains the default
until install() is called, exactly like the reference defaults to pure
Go.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..libs import metrics as M
from .batch import register_device_factory
from .keys import BatchVerifier, PubKey

# device-offload observability (no reference analog — this is the
# north-star seam's instrumentation)
_m_batches = M.new_counter(
    "tpu", "verify_batches_total", "Device batch-verify invocations."
)
_m_sigs = M.new_counter(
    "tpu", "verify_sigs_total", "Signatures verified on device."
)
_m_verify_time = M.new_histogram(
    "tpu",
    "verify_seconds",
    "Wall time of one batch verification.",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5),
)

__all__ = [
    "TpuEd25519BatchVerifier",
    "install",
    "installed",
    "stats",
    "DEFAULT_MIN_BATCH",
]

# Below this many signatures the fixed dispatch cost (host packing +
# device roundtrip, ~100s of µs) exceeds CPU verify time; let CPU win.
DEFAULT_MIN_BATCH = 8


class TpuEd25519BatchVerifier(BatchVerifier):
    """Queues triples on host, verifies in one device program.

    Same verify() contract as the CPU path: (all_ok, bitmap), bitmap
    aligned with add() order, malformed entries reported invalid
    per-index rather than raising at verify time.
    """

    def __init__(self, verifier=None) -> None:
        from ..ops import ed25519_kernel

        self._verifier = verifier
        self._kernel = ed25519_kernel
        self._pks: List[bytes] = []
        self._msgs: List[bytes] = []
        self._sigs: List[bytes] = []

    def add(self, pub_key: PubKey, message: bytes, signature: bytes) -> None:
        if pub_key.type() != "ed25519":
            raise TypeError("TpuEd25519BatchVerifier requires ed25519 keys")
        if len(signature) != 64:
            raise ValueError("malformed signature size")
        self._pks.append(pub_key.bytes())
        self._msgs.append(bytes(message))
        self._sigs.append(bytes(signature))

    def verify(self) -> Tuple[bool, List[bool]]:
        if not self._pks:
            return False, []
        with _m_verify_time.time():
            if self._verifier is not None:
                bitmap = self._verifier.verify(
                    self._pks, self._msgs, self._sigs
                )
            else:
                bitmap = self._kernel.batch_verify_host(
                    self._pks, self._msgs, self._sigs
                )
        _m_batches.inc()
        _m_sigs.inc(len(self._pks))
        bits = [bool(b) for b in bitmap]
        return all(bits), bits

    def __len__(self) -> int:
        return len(self._pks)


_SHARED_VERIFIER = None
_MIN_BATCH = DEFAULT_MIN_BATCH
_INSTALLED = False


def installed() -> Optional[int]:
    """The currently-installed min_batch threshold, or None if the
    device factory has never been registered. Install state is
    process-global (one device runtime per process); multi-node
    embedders share whichever install ran last."""
    return _MIN_BATCH if _INSTALLED else None


def stats() -> dict:
    """Device-path usage counters — lets the node (and tests) assert the
    batch path actually runs on device in the served configuration."""
    return {
        "batches": int(_m_batches.value()),
        "sigs": int(_m_sigs.value()),
    }


def _factory(size_hint: int) -> Optional[BatchVerifier]:
    if 0 < size_hint < _MIN_BATCH:
        return None  # CPU fallback for tiny batches
    return TpuEd25519BatchVerifier(_SHARED_VERIFIER)


def install(
    min_batch: int = DEFAULT_MIN_BATCH, mesh=None
) -> None:
    """Register the device factory. With a mesh, batches are sharded
    across it (tendermint_tpu.parallel.sharding); otherwise single-chip."""
    global _SHARED_VERIFIER, _MIN_BATCH, _INSTALLED
    _MIN_BATCH = min_batch
    _INSTALLED = True
    if mesh is not None:
        from ..parallel.sharding import ShardedEd25519Verifier

        _SHARED_VERIFIER = ShardedEd25519Verifier(mesh)
    else:
        _SHARED_VERIFIER = None
    register_device_factory("ed25519", _factory)
