"""Batch-verifier dispatch — the offload decision point.

Mirrors crypto/batch/batch.go:11-33 (CreateBatchVerifier /
SupportsBatchVerifier switching on key type) and extends it with the
device registry: when a TPU/accelerator backend has been registered (see
tendermint_tpu.crypto.tpu_verifier.install) and the caller hints a large
enough batch, the returned verifier runs on device. CPU remains the
default, exactly like the reference keeps pure-Go as the default.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from .keys import BatchVerifier, PubKey

__all__ = [
    "create_batch_verifier",
    "cpu_factory",
    "drain_and_cache",
    "supports_batch_verifier",
    "register_device_factory",
    "device_factory_installed",
]

# key type -> CPU batch verifier factory
_CPU_FACTORIES: dict[str, Callable[[], BatchVerifier]] = {}
# key type -> device batch verifier factory (size_hint -> verifier or None)
_DEVICE_FACTORIES: dict[
    str, Callable[[int], Optional[BatchVerifier]]
] = {}


def register_cpu_factory(
    key_type: str, factory: Callable[[], BatchVerifier]
) -> None:
    # tmlint: disable=lock-global-mutation — single GIL-atomic dict
    # write from import-time defaults / main-thread embedder setup
    _CPU_FACTORIES[key_type] = factory


def register_device_factory(
    key_type: str, factory: Callable[[int], Optional[BatchVerifier]]
) -> None:
    # tmlint: disable=lock-global-mutation — single GIL-atomic dict
    # write from install(), a main-thread seam (PERF.md claim
    # discipline keeps install off worker threads)
    _DEVICE_FACTORIES[key_type] = factory


def unregister_device_factory(key_type: str) -> None:
    """Remove a device factory (tpu_verifier.uninstall's half)."""
    # tmlint: disable=lock-global-mutation — single GIL-atomic pop
    # from uninstall(), a main-thread test/embedder seam
    _DEVICE_FACTORIES.pop(key_type, None)


def device_factory_installed(key_type: str) -> bool:
    return key_type in _DEVICE_FACTORIES


def cpu_factory(key_type: str) -> Optional[Callable[[], BatchVerifier]]:
    """The registered CPU factory for a key type, or None. This is the
    mandatory software fallback of the device-fault containment layer:
    crypto/tpu_verifier.py re-verifies a faulted device batch through
    it with the identical (all_ok, bitmap) contract."""
    return _CPU_FACTORIES.get(key_type)


# How many independent commits' signatures callers should merge into
# one batch verifier when they have several available (the light
# client's sequential window, statesync backfill). 1 = verify each
# commit separately. The device install raises it when an accelerator
# backend is live: merged batches amortize dispatch and fill buckets,
# but on a CPU-backed kernel the padding waste inverts the win.
# The value may be provided lazily (set_group_affinity_fn): deciding
# it can require jax backend initialization, which must not happen at
# install() time — a wedged device claim would hang node startup.
_GROUP_AFFINITY: Optional[int] = 1
_GROUP_AFFINITY_FN: Optional[Callable[[], int]] = None
_GROUP_AFFINITY_EXPLICIT = False
# guards the affinity triple: group_affinity()'s lazy init is a
# check-then-act on module state, and verify paths on probe threads
# race the first consensus caller (tmlint: lock-global-mutation)
_affinity_lock = threading.Lock()


def set_group_affinity(n: int) -> None:
    """Operator override — wins over any install-provided default
    (set_group_affinity_fn will not replace it)."""
    global _GROUP_AFFINITY, _GROUP_AFFINITY_FN, _GROUP_AFFINITY_EXPLICIT
    with _affinity_lock:
        _GROUP_AFFINITY = max(1, int(n))
        _GROUP_AFFINITY_FN = None
        _GROUP_AFFINITY_EXPLICIT = True


def set_group_affinity_fn(fn: Callable[[], int]) -> None:
    """Defer the affinity decision until the first caller needs it.
    A no-op if an operator already pinned a value explicitly."""
    global _GROUP_AFFINITY, _GROUP_AFFINITY_FN
    with _affinity_lock:
        if _GROUP_AFFINITY_EXPLICIT:
            return
        _GROUP_AFFINITY = None
        _GROUP_AFFINITY_FN = fn


def group_affinity() -> int:
    global _GROUP_AFFINITY
    while True:
        # consistent (value, fn) snapshot: all writers hold the lock
        with _affinity_lock:
            value = _GROUP_AFFINITY
            fn = _GROUP_AFFINITY_FN
        if value is not None:
            return value
        # resolve the deferred fn OUTSIDE the lock: it may initialize
        # the jax backend (slow, possibly wedged) and must never park
        # every verify path behind one device claim
        computed = max(1, int(fn())) if fn is not None else 1
        with _affinity_lock:
            if _GROUP_AFFINITY is not None:
                return _GROUP_AFFINITY
            if _GROUP_AFFINITY_FN is fn:
                _GROUP_AFFINITY = computed
                return computed
            # the fn changed while we computed (install landed mid-
            # flight) — loop and resolve the new one


def group_affinity_state() -> tuple:
    """Snapshot for restore_group_affinity — the save/restore idiom
    for tests and embedders. Restoring via set_group_affinity(old)
    would pin the explicit-override flag forever and silently disable
    any later install()'s affinity fn."""
    return (_GROUP_AFFINITY, _GROUP_AFFINITY_FN, _GROUP_AFFINITY_EXPLICIT)


def restore_group_affinity(state: tuple) -> None:
    global _GROUP_AFFINITY, _GROUP_AFFINITY_FN, _GROUP_AFFINITY_EXPLICIT
    with _affinity_lock:
        _GROUP_AFFINITY, _GROUP_AFFINITY_FN, _GROUP_AFFINITY_EXPLICIT = state


def supports_batch_verifier(pk: Optional[PubKey]) -> bool:
    return pk is not None and pk.type() in _CPU_FACTORIES


def create_batch_verifier(
    pk: PubKey, size_hint: int = 0
) -> BatchVerifier:
    """Return the best available batch verifier for this key type.

    size_hint is the expected number of add() calls (a Commit's signature
    count); device backends use it to pick a padded bucket shape and may
    decline small batches (returning None → CPU fallback).
    """
    key_type = pk.type()
    dev = _DEVICE_FACTORIES.get(key_type)
    if dev is not None:
        verifier = dev(size_hint)
        if verifier is not None:
            return verifier
    cpu = _CPU_FACTORIES.get(key_type)
    if cpu is None:
        raise ValueError(f"key type {key_type!r} does not support batching")
    return cpu()


def drain_and_cache(verifier: BatchVerifier, cache_keys) -> tuple:
    """Drain a batch verifier, populating the verified-signature cache
    (crypto.sigcache) for every triple whose bitmap bit is True — the
    drain half of the cross-stage cache: whatever a batch proves here,
    no later stage re-proves. cache_keys aligns with add() order; None
    entries (cache disabled at assembly time) are skipped. Returns
    verify()'s (all_ok, bitmap) unchanged.

    A batch the device faulted under (verifier.faulted — see
    crypto/tpu_verifier.py) never populates the cache, even though its
    CPU re-verify answered correctly: nothing learned while a device
    was misbehaving is allowed to outlive the batch."""
    from . import sigcache

    ok, bits = verifier.verify()
    if getattr(verifier, "faulted", False):
        return ok, bits
    if ok:
        sigcache.add_keys_bulk(
            [key for key in cache_keys if key is not None]
        )
    else:
        sigcache.add_keys_bulk(
            [
                key
                for key, bit in zip(cache_keys, bits)
                if bit and key is not None
            ]
        )
    return ok, bits


def native_cpu_affinity() -> int:
    """Merged-window size when only CPU kernels serve batches. The
    native RLC batch equation is exact-size (no bucket padding) and
    its per-signature cost keeps falling through ~8k terms (PERF.md
    batch curve: 24 us @64 -> 10.4 us @8192), so merging a light
    client's sequential window into one call wins on CPU too. Without
    the native kernel the OpenSSL-sequential fallback gains nothing
    from merging — stay at 1."""
    try:
        from .ed25519 import _native_batch_fn

        return 32 if _native_batch_fn() is not None else 1
    except Exception:  # pragma: no cover - native probing must not raise
        return 1


def _register_defaults() -> None:
    from .ed25519 import KEY_TYPE as ED, Ed25519BatchVerifier

    register_cpu_factory(ED, Ed25519BatchVerifier)
    try:
        from .sr25519 import KEY_TYPE as SR, Sr25519BatchVerifier

        register_cpu_factory(SR, Sr25519BatchVerifier)
    except ImportError:  # sr25519 backend optional
        pass
    from .secp256k1 import KEY_TYPE as SECP, Secp256k1BatchVerifier

    register_cpu_factory(SECP, Secp256k1BatchVerifier)


_register_defaults()
set_group_affinity_fn(native_cpu_affinity)
