"""ristretto255 group (RFC 9496) over the curve25519 Edwards curve.

The prime-order group sr25519/schnorrkel signatures live in
(reference: crypto/sr25519 via curve25519-voi's ristretto/sr25519
primitives). Host implementation on Python ints, sharing the Edwards
point arithmetic with the ed25519 oracle (crypto/ed25519_math.py); the
device-side batch path reuses the ed25519 kernel's curve core with a
ristretto decode front-end (ops/ed25519_kernel.py).

Encode/decode follow RFC 9496 §4.3.1/§4.3.2 exactly; tested against
the RFC's small-multiple vectors.
"""

from __future__ import annotations

from typing import Optional, Tuple

from . import ed25519_math as em

__all__ = [
    "decode",
    "encode",
    "eq",
    "BASE",
    "mul_base",
    "mul_base_ct",
    "add",
    "scalar_mult",
    "L",
]

P = em.P
D = em.D
L = em.L

Point = Tuple[int, int, int, int]  # extended homogeneous (X, Y, Z, T)

_SQRT_M1 = pow(2, (P - 1) // 4, P)
# invsqrt(a - d) with a = -1: 1/sqrt(-1 - d)
_A_MINUS_D = (-1 - D) % P


def _is_negative(x: int) -> bool:
    return (x % P) & 1 == 1


def _abs(x: int) -> int:
    x %= P
    return P - x if _is_negative(x) else x


def _sqrt_ratio_m1(u: int, v: int) -> Tuple[bool, int]:
    """(was_square, r) with r = sqrt(u/v) when it exists, else
    sqrt(i*u/v) (RFC 9496 §4.2)."""
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    u = u % P
    correct = check == u
    flipped = check == (P - u) % P
    flipped_i = check == (P - u) * _SQRT_M1 % P
    if flipped or flipped_i:
        r = r * _SQRT_M1 % P
    return correct or flipped, _abs(r)


_, _INVSQRT_A_MINUS_D = _sqrt_ratio_m1(1, _A_MINUS_D)


def decode(data: bytes) -> Optional[Point]:
    """RFC 9496 §4.3.1: 32 bytes -> extended point, or None."""
    if len(data) != 32:
        return None
    s = int.from_bytes(data, "little")
    if s >= P or _is_negative(s):
        return None
    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2_sqr = u2 * u2 % P
    v = (-(D * u1 % P * u1) - u2_sqr) % P
    was_square, invsqrt = _sqrt_ratio_m1(1, v * u2_sqr % P)
    den_x = invsqrt * u2 % P
    den_y = invsqrt * den_x % P * v % P
    x = _abs(2 * s % P * den_x % P)
    y = u1 * den_y % P
    t = x * y % P
    if not was_square or _is_negative(t) or y == 0:
        return None
    return (x, y, 1, t)


def encode(pt: Point) -> bytes:
    """RFC 9496 §4.3.2: extended point -> canonical 32 bytes."""
    x0, y0, z0, t0 = pt
    u1 = (z0 + y0) * (z0 - y0) % P
    u2 = x0 * y0 % P
    _, invsqrt = _sqrt_ratio_m1(1, u1 * u2 % P * u2 % P)
    den1 = invsqrt * u1 % P
    den2 = invsqrt * u2 % P
    z_inv = den1 * den2 % P * t0 % P
    if _is_negative(t0 * z_inv % P):
        ix0 = x0 * _SQRT_M1 % P
        iy0 = y0 * _SQRT_M1 % P
        x = iy0
        y = ix0
        den_inv = den1 * _INVSQRT_A_MINUS_D % P
    else:
        x = x0
        y = y0
        den_inv = den2
    if _is_negative(x * z_inv % P):
        y = (P - y) % P
    s = _abs(den_inv * ((z0 - y) % P) % P)
    return int(s).to_bytes(32, "little")


def eq(p: Point, q: Point) -> bool:
    """Ristretto equality (RFC 9496 §4.4): X1*Y2 == Y1*X2 or
    Y1*Y2 == X1*X2 (a = -1 form)."""
    x1, y1, _, _ = p
    x2, y2, _, _ = q
    return (
        x1 * y2 % P == y1 * x2 % P or y1 * y2 % P == x1 * x2 % P
    )


BASE: Point = em.B_POINT


def add(p: Point, q: Point) -> Point:
    return em.point_add(p, q)


def scalar_mult(k: int, p: Point) -> Point:
    return em.scalar_mult(k % L, p)


def mul_base(k: int) -> Point:
    return em.mul_base(k % L)


def mul_base_ct(k: int) -> Point:
    """Secret-scalar basepoint multiply: fixed comb structure, masked
    table scan (see ed25519_math.mul_base_ct — the tmct contract)."""
    return em.mul_base_ct(k % L)
