"""Symmetric encryption utilities: XChaCha20-Poly1305 and secretbox.

Reference model: crypto/xchacha20poly1305/xchachapoly.go (24-byte-nonce
AEAD via HChaCha20 subkey derivation) and crypto/xsalsa20symmetric/
symmetric.go (secretbox-style `EncryptSymmetric` with a random nonce,
used by key-file armor tooling). Framework-local deviation: the
secretbox helpers here are built on XChaCha20-Poly1305 instead of
XSalsa20-Poly1305 — same construction shape (random 24-byte nonce
prepended to the sealed box), one cipher family for the whole stack.

The HChaCha20 core is pure Python; its ChaCha permutation is
differential-tested against the `cryptography` package's ChaCha20
keystream (tests/test_symmetric.py), so the only hand-rolled math has
an independent oracle.
"""

from __future__ import annotations

import os
import struct

from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

__all__ = [
    "KEY_SIZE",
    "NONCE_SIZE",
    "XChaCha20Poly1305",
    "encrypt_symmetric",
    "decrypt_symmetric",
    "hchacha20",
]

KEY_SIZE = 32
NONCE_SIZE = 24  # XChaCha20 nonce


def _rotl32(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


def _quarter(st, a, b, c, d) -> None:
    st[a] = (st[a] + st[b]) & 0xFFFFFFFF
    st[d] = _rotl32(st[d] ^ st[a], 16)
    st[c] = (st[c] + st[d]) & 0xFFFFFFFF
    st[b] = _rotl32(st[b] ^ st[c], 12)
    st[a] = (st[a] + st[b]) & 0xFFFFFFFF
    st[d] = _rotl32(st[d] ^ st[a], 8)
    st[c] = (st[c] + st[d]) & 0xFFFFFFFF
    st[b] = _rotl32(st[b] ^ st[c], 7)


_SIGMA = struct.unpack("<4I", b"expand 32-byte k")


def _chacha_rounds(state: list) -> list:
    st = list(state)
    for _ in range(10):  # 20 rounds: 10 column+diagonal double-rounds
        _quarter(st, 0, 4, 8, 12)
        _quarter(st, 1, 5, 9, 13)
        _quarter(st, 2, 6, 10, 14)
        _quarter(st, 3, 7, 11, 15)
        _quarter(st, 0, 5, 10, 15)
        _quarter(st, 1, 6, 11, 12)
        _quarter(st, 2, 7, 8, 13)
        _quarter(st, 3, 4, 9, 14)
    return st


def chacha20_block(key: bytes, counter: int, nonce12: bytes) -> bytes:
    """One RFC 8439 ChaCha20 block (used only by the differential test
    as the bridge between the permutation and the library keystream)."""
    state = list(_SIGMA)
    state += list(struct.unpack("<8I", key))
    state.append(counter & 0xFFFFFFFF)
    state += list(struct.unpack("<3I", nonce12))
    working = _chacha_rounds(state)
    out = [(w + s) & 0xFFFFFFFF for w, s in zip(working, state)]
    return struct.pack("<16I", *out)


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """HChaCha20 subkey derivation (draft-irtf-cfrg-xchacha §2.2):
    the ChaCha permutation without the final feed-forward addition;
    the subkey is words 0-3 and 12-15."""
    if len(key) != KEY_SIZE:
        raise ValueError("hchacha20 key must be 32 bytes")
    if len(nonce16) != 16:
        raise ValueError("hchacha20 input must be 16 bytes")
    state = list(_SIGMA)
    state += list(struct.unpack("<8I", key))
    state += list(struct.unpack("<4I", nonce16))
    st = _chacha_rounds(state)
    return struct.pack("<4I", *st[0:4]) + struct.pack("<4I", *st[12:16])


class XChaCha20Poly1305:
    """AEAD with a 24-byte nonce (reference:
    crypto/xchacha20poly1305/xchachapoly.go): derive a subkey with
    HChaCha20 over the first 16 nonce bytes, then run standard
    ChaCha20-Poly1305 with nonce 0x00000000 || nonce[16:24]."""

    def __init__(self, key: bytes) -> None:
        if len(key) != KEY_SIZE:
            raise ValueError("key must be 32 bytes")
        self._key = key

    def _inner(self, nonce: bytes) -> tuple:
        if len(nonce) != NONCE_SIZE:
            raise ValueError("nonce must be 24 bytes")
        subkey = hchacha20(self._key, nonce[:16])
        return ChaCha20Poly1305(subkey), b"\x00\x00\x00\x00" + nonce[16:]

    def encrypt(
        self, nonce: bytes, plaintext: bytes, aad: bytes | None = None
    ) -> bytes:
        aead, n12 = self._inner(nonce)
        return aead.encrypt(n12, plaintext, aad)

    def decrypt(
        self, nonce: bytes, ciphertext: bytes, aad: bytes | None = None
    ) -> bytes:
        aead, n12 = self._inner(nonce)
        return aead.decrypt(n12, ciphertext, aad)


def encrypt_symmetric(plaintext: bytes, secret: bytes) -> bytes:
    """Seal with a fresh random 24-byte nonce; output nonce || box
    (reference shape: crypto/xsalsa20symmetric/symmetric.go:19-27)."""
    if len(secret) != KEY_SIZE:
        raise ValueError("secret must be 32 bytes")
    nonce = os.urandom(NONCE_SIZE)
    return nonce + XChaCha20Poly1305(secret).encrypt(nonce, plaintext)


def decrypt_symmetric(ciphertext: bytes, secret: bytes) -> bytes:
    """Inverse of encrypt_symmetric; raises on tampering or wrong key
    (reference: symmetric.go:30-46)."""
    if len(secret) != KEY_SIZE:
        raise ValueError("secret must be 32 bytes")
    if len(ciphertext) < NONCE_SIZE + 16:
        raise ValueError("ciphertext too short")
    nonce, box = ciphertext[:NONCE_SIZE], ciphertext[NONCE_SIZE:]
    return XChaCha20Poly1305(secret).decrypt(nonce, box)
