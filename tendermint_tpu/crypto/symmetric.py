"""Symmetric encryption utilities: XChaCha20-Poly1305 and secretbox.

Reference model: crypto/xchacha20poly1305/xchachapoly.go (24-byte-nonce
AEAD via HChaCha20 subkey derivation) and crypto/xsalsa20symmetric/
symmetric.go (secretbox-style `EncryptSymmetric` with a random nonce,
used by key-file armor tooling). Framework-local deviation: the
secretbox helpers here are built on XChaCha20-Poly1305 instead of
XSalsa20-Poly1305 — same construction shape (random 24-byte nonce
prepended to the sealed box), one cipher family for the whole stack.

The HChaCha20 core is pure Python; its ChaCha permutation is
differential-tested against the `cryptography` package's ChaCha20
keystream (tests/test_symmetric.py), so the only hand-rolled math has
an independent oracle.

The `cryptography` wheel is gated: without it, the inner
ChaCha20-Poly1305 AEAD runs a pure-Python RFC 8439 implementation on
the same permutation (validated against the RFC's AEAD test vector in
tests/test_symmetric.py) — identical bytes, slower.
"""

from __future__ import annotations

import hmac as _hmac
import os
import struct

try:
    from cryptography.hazmat.primitives.ciphers.aead import (
        ChaCha20Poly1305,
    )
except ImportError:  # no wheel: pure-Python RFC 8439 AEAD below
    ChaCha20Poly1305 = None

__all__ = [
    "KEY_SIZE",
    "NONCE_SIZE",
    "PureChaCha20Poly1305",
    "XChaCha20Poly1305",
    "encrypt_symmetric",
    "decrypt_symmetric",
    "hchacha20",
]

KEY_SIZE = 32
NONCE_SIZE = 24  # XChaCha20 nonce


def _rotl32(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


def _quarter(st, a, b, c, d) -> None:
    st[a] = (st[a] + st[b]) & 0xFFFFFFFF
    st[d] = _rotl32(st[d] ^ st[a], 16)
    st[c] = (st[c] + st[d]) & 0xFFFFFFFF
    st[b] = _rotl32(st[b] ^ st[c], 12)
    st[a] = (st[a] + st[b]) & 0xFFFFFFFF
    st[d] = _rotl32(st[d] ^ st[a], 8)
    st[c] = (st[c] + st[d]) & 0xFFFFFFFF
    st[b] = _rotl32(st[b] ^ st[c], 7)


_SIGMA = struct.unpack("<4I", b"expand 32-byte k")


def _chacha_rounds(state: list) -> list:
    st = list(state)
    for _ in range(10):  # 20 rounds: 10 column+diagonal double-rounds
        _quarter(st, 0, 4, 8, 12)
        _quarter(st, 1, 5, 9, 13)
        _quarter(st, 2, 6, 10, 14)
        _quarter(st, 3, 7, 11, 15)
        _quarter(st, 0, 5, 10, 15)
        _quarter(st, 1, 6, 11, 12)
        _quarter(st, 2, 7, 8, 13)
        _quarter(st, 3, 4, 9, 14)
    return st


def chacha20_block(key: bytes, counter: int, nonce12: bytes) -> bytes:
    """One RFC 8439 ChaCha20 block (used only by the differential test
    as the bridge between the permutation and the library keystream)."""
    state = list(_SIGMA)
    state += list(struct.unpack("<8I", key))
    state.append(counter & 0xFFFFFFFF)
    state += list(struct.unpack("<3I", nonce12))
    working = _chacha_rounds(state)
    out = [(w + s) & 0xFFFFFFFF for w, s in zip(working, state)]
    return struct.pack("<16I", *out)


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """HChaCha20 subkey derivation (draft-irtf-cfrg-xchacha §2.2):
    the ChaCha permutation without the final feed-forward addition;
    the subkey is words 0-3 and 12-15."""
    if len(key) != KEY_SIZE:
        raise ValueError("hchacha20 key must be 32 bytes")
    if len(nonce16) != 16:
        raise ValueError("hchacha20 input must be 16 bytes")
    state = list(_SIGMA)
    state += list(struct.unpack("<8I", key))
    state += list(struct.unpack("<4I", nonce16))
    st = _chacha_rounds(state)
    return struct.pack("<4I", *st[0:4]) + struct.pack("<4I", *st[12:16])


def _chacha20_xor(key: bytes, counter: int, nonce12: bytes, data: bytes) -> bytes:
    out = bytearray(len(data))
    for i in range(0, len(data), 64):
        block = chacha20_block(key, counter + i // 64, nonce12)
        chunk = data[i : i + 64]
        out[i : i + len(chunk)] = bytes(
            a ^ b for a, b in zip(chunk, block)
        )
    return bytes(out)


def _poly1305(key32: bytes, msg: bytes) -> bytes:
    """RFC 8439 §2.5 one-time authenticator."""
    r = (
        int.from_bytes(key32[:16], "little")
        & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    )
    s = int.from_bytes(key32[16:32], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        acc = (
            (acc + int.from_bytes(msg[i : i + 16] + b"\x01", "little")) * r
        ) % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(b: bytes) -> bytes:
    return b"\x00" * ((16 - len(b) % 16) % 16)


class PureChaCha20Poly1305:
    """RFC 8439 §2.8 AEAD on the module's own ChaCha permutation; same
    construct/encrypt/decrypt surface as the `cryptography` class it
    substitutes when the wheel is absent."""

    def __init__(self, key: bytes) -> None:
        if len(key) != KEY_SIZE:
            raise ValueError("key must be 32 bytes")
        self._key = bytes(key)

    def _mac_data(self, aad: bytes, ct: bytes) -> bytes:
        return (
            aad + _pad16(aad) + ct + _pad16(ct)
            + len(aad).to_bytes(8, "little")
            + len(ct).to_bytes(8, "little")
        )

    def encrypt(self, nonce12: bytes, data: bytes, aad=None) -> bytes:
        aad = aad or b""
        otk = chacha20_block(self._key, 0, nonce12)[:32]
        ct = _chacha20_xor(self._key, 1, nonce12, data)
        return ct + _poly1305(otk, self._mac_data(aad, ct))

    def decrypt(self, nonce12: bytes, data: bytes, aad=None) -> bytes:
        aad = aad or b""
        if len(data) < 16:
            raise ValueError("ciphertext too short")
        ct, tag = data[:-16], data[-16:]
        otk = chacha20_block(self._key, 0, nonce12)[:32]
        if not _hmac.compare_digest(
            tag, _poly1305(otk, self._mac_data(aad, ct))
        ):
            raise ValueError("authentication failed")
        return _chacha20_xor(self._key, 1, nonce12, ct)


class XChaCha20Poly1305:
    """AEAD with a 24-byte nonce (reference:
    crypto/xchacha20poly1305/xchachapoly.go): derive a subkey with
    HChaCha20 over the first 16 nonce bytes, then run standard
    ChaCha20-Poly1305 with nonce 0x00000000 || nonce[16:24]."""

    def __init__(self, key: bytes) -> None:
        if len(key) != KEY_SIZE:
            raise ValueError("key must be 32 bytes")
        self._key = key

    def _inner(self, nonce: bytes) -> tuple:
        if len(nonce) != NONCE_SIZE:
            raise ValueError("nonce must be 24 bytes")
        subkey = hchacha20(self._key, nonce[:16])
        aead_cls = (
            ChaCha20Poly1305
            if ChaCha20Poly1305 is not None
            else PureChaCha20Poly1305
        )
        return aead_cls(subkey), b"\x00\x00\x00\x00" + nonce[16:]

    def encrypt(
        self, nonce: bytes, plaintext: bytes, aad: bytes | None = None
    ) -> bytes:
        aead, n12 = self._inner(nonce)
        return aead.encrypt(n12, plaintext, aad)

    def decrypt(
        self, nonce: bytes, ciphertext: bytes, aad: bytes | None = None
    ) -> bytes:
        aead, n12 = self._inner(nonce)
        return aead.decrypt(n12, ciphertext, aad)


def encrypt_symmetric(plaintext: bytes, secret: bytes) -> bytes:
    """Seal with a fresh random 24-byte nonce; output nonce || box
    (reference shape: crypto/xsalsa20symmetric/symmetric.go:19-27)."""
    if len(secret) != KEY_SIZE:
        raise ValueError("secret must be 32 bytes")
    nonce = os.urandom(NONCE_SIZE)
    return nonce + XChaCha20Poly1305(secret).encrypt(nonce, plaintext)


def decrypt_symmetric(ciphertext: bytes, secret: bytes) -> bytes:
    """Inverse of encrypt_symmetric; raises on tampering or wrong key
    (reference: symmetric.go:30-46)."""
    if len(secret) != KEY_SIZE:
        raise ValueError("secret must be 32 bytes")
    if len(ciphertext) < NONCE_SIZE + 16:
        raise ValueError("ciphertext too short")
    nonce, box = ciphertext[:NONCE_SIZE], ciphertext[NONCE_SIZE:]
    return XChaCha20Poly1305(secret).decrypt(nonce, box)
