"""Merlin transcripts over STROBE-128 (Keccak-f[1600]).

The Fiat-Shamir transcript construction used by schnorrkel/sr25519
(reference: crypto/sr25519 via the curve25519-voi dependency, which is
schnorrkel-compatible; merlin spec: merlin.cool, STROBE spec:
strobe.sourceforge.io). Pure-Python host implementation — transcripts
hash a few hundred bytes per signature, so this is never the hot path;
the curve math is (see crypto/ristretto.py and, device-side, the
ed25519 kernel family).
"""

from __future__ import annotations

import ctypes
import struct

import numpy as np

__all__ = ["Transcript", "TranscriptBatch"]

# -- Keccak-f[1600] ---------------------------------------------------------

_ROUNDS = 24
_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
# rotation offsets r[x][y]
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]
_MASK = (1 << 64) - 1


def _rotl(v: int, n: int) -> int:
    return ((v << n) | (v >> (64 - n))) & _MASK


# Native Keccak-f (tendermint_tpu/native/keccakf.c, ~0.5 µs/permutation
# vs ~1 ms in Python); loaded lazily so importing this module never
# triggers a compile. None = not yet probed, False = unavailable.
_NATIVE = None


def _native_lib():
    global _NATIVE
    if _NATIVE is None:
        from .. import native

        _NATIVE = native.keccakf_lib() or False
    return _NATIVE or None


def _keccak_f(state: bytearray) -> None:
    """In-place permutation of the 200-byte state (lanes LE u64).
    Dispatches to the native library when available; the pure-Python
    body below is the fallback and the differential oracle."""
    lib = _native_lib()
    if lib is not None:
        lib.tm_keccakf(
            ctypes.addressof(ctypes.c_char.from_buffer(state))
        )
        return
    _keccak_f_py(state)


def _keccak_f_py(state: bytearray) -> None:
    lanes = list(struct.unpack("<25Q", state))
    A = [[lanes[x + 5 * y] for y in range(5)] for x in range(5)]
    for rnd in range(_ROUNDS):
        # theta
        C = [A[x][0] ^ A[x][1] ^ A[x][2] ^ A[x][3] ^ A[x][4] for x in range(5)]
        D = [C[(x - 1) % 5] ^ _rotl(C[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                A[x][y] ^= D[x]
        # rho + pi
        B = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                B[y][(2 * x + 3 * y) % 5] = _rotl(A[x][y], _ROT[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                A[x][y] = B[x][y] ^ ((~B[(x + 1) % 5][y]) & B[(x + 2) % 5][y])
        # iota
        A[0][0] ^= _RC[rnd]
    out = [A[x % 5][x // 5] for x in range(25)]
    state[:] = struct.pack("<25Q", *[v & _MASK for v in out])


# -- STROBE-128 -------------------------------------------------------------

_R = 166  # rate for 128-bit security: 200 - 32 - 2
_FLAG_I = 1
_FLAG_A = 1 << 1
_FLAG_C = 1 << 2
_FLAG_T = 1 << 3
_FLAG_M = 1 << 4
_FLAG_K = 1 << 5


def _initial_state() -> bytearray:
    st = bytearray(200)
    st[0:6] = bytes([1, _R + 2, 1, 0, 1, 96])
    st[6:18] = b"STROBEv1.0.2"
    _keccak_f(st)
    return st


_INIT = None  # computed once


class _Strobe128:
    """The merlin subset of STROBE-128: meta-AD, AD, PRF, KEY."""

    def __init__(self, protocol_label: bytes) -> None:
        global _INIT
        if _INIT is None:
            _INIT = _initial_state()
        self.state = bytearray(_INIT)
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol_label, False)

    # operations

    def meta_ad(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_M | _FLAG_A, more)
        self._absorb(data)

    def ad(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool) -> bytes:
        self._begin_op(_FLAG_I | _FLAG_A | _FLAG_C, more)
        return self._squeeze(n)

    def key(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_A | _FLAG_C, more)
        self._overwrite(data)

    # internals

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            if flags != self.cur_flags:
                raise ValueError("'more' must continue the same operation")
            return
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        if (flags & (_FLAG_C | _FLAG_K)) and self.pos != 0:
            self._run_f()

    def _absorb(self, data: bytes) -> None:
        # sliced: XOR whole chunks via big-int ops (C speed) instead of
        # a per-byte Python loop; permutation cadence is unchanged
        off = 0
        n = len(data)
        while off < n:
            take = min(n - off, _R - self.pos)
            p = self.pos
            chunk = data[off : off + take]
            cur = self.state[p : p + take]
            self.state[p : p + take] = (
                int.from_bytes(cur, "little")
                ^ int.from_bytes(chunk, "little")
            ).to_bytes(take, "little")
            self.pos += take
            off += take
            if self.pos == _R:
                self._run_f()

    def _overwrite(self, data: bytes) -> None:
        off = 0
        n = len(data)
        while off < n:
            take = min(n - off, _R - self.pos)
            self.state[self.pos : self.pos + take] = data[
                off : off + take
            ]
            self.pos += take
            off += take
            if self.pos == _R:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            take = min(n - len(out), _R - self.pos)
            p = self.pos
            out += self.state[p : p + take]
            self.state[p : p + take] = bytes(take)
            self.pos += take
            if self.pos == _R:
                self._run_f()
        return bytes(out)

    def _run_f(self) -> None:
        self.state[self.pos] ^= self.pos_begin
        self.state[self.pos + 1] ^= 0x04
        self.state[_R + 1] ^= 0x80
        _keccak_f(self.state)
        self.pos = 0
        self.pos_begin = 0


# -- merlin transcript ------------------------------------------------------

_MERLIN_LABEL = b"Merlin v1.0"


class Transcript:
    """merlin.Transcript: labeled append/challenge over STROBE-128."""

    def __init__(self, label: bytes) -> None:
        self._strobe = _Strobe128(_MERLIN_LABEL)
        self.append_message(b"dom-sep", label)

    def clone(self) -> "Transcript":
        t = object.__new__(Transcript)
        t._strobe = object.__new__(_Strobe128)
        t._strobe.state = bytearray(self._strobe.state)
        t._strobe.pos = self._strobe.pos
        t._strobe.pos_begin = self._strobe.pos_begin
        t._strobe.cur_flags = self._strobe.cur_flags
        return t

    def append_message(self, label: bytes, message: bytes) -> None:
        self._strobe.meta_ad(label, False)
        self._strobe.meta_ad(struct.pack("<I", len(message)), True)
        self._strobe.ad(message, False)

    def append_u64(self, label: bytes, value: int) -> None:
        self.append_message(label, struct.pack("<Q", value))

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        self._strobe.meta_ad(label, False)
        self._strobe.meta_ad(struct.pack("<I", n), True)
        return self._strobe.prf(n, False)


# -- batched transcripts ----------------------------------------------------


class _StrobeBatch:
    """G STROBE-128 states advancing in lockstep.

    The STROBE position/flag state machine depends only on operation
    *lengths*, so G transcripts whose appended messages are
    equal-length per step share one control flow: the 200-byte states
    live in a (G, 200) array, absorbs are vectorized XORs, and the
    permutation runs once per step over the whole group —
    tm_keccakf_n in the native library (one ctypes call), the
    per-state Python permutation as fallback. This is what makes host
    prep for sr25519 device batches scale (one merlin challenge per
    signature; crypto/sr25519.py challenge_batch)."""

    def __init__(self, template: "_Strobe128", g: int) -> None:
        self.states = np.tile(
            np.frombuffer(bytes(template.state), dtype=np.uint8), (g, 1)
        )
        self.pos = template.pos
        self.pos_begin = template.pos_begin
        self.cur_flags = template.cur_flags

    def _run_f(self) -> None:
        self.states[:, self.pos] ^= self.pos_begin
        self.states[:, self.pos + 1] ^= 0x04
        self.states[:, _R + 1] ^= 0x80
        lib = _native_lib()
        if lib is not None:
            st = np.ascontiguousarray(self.states)
            lib.tm_keccakf_n(
                st.ctypes.data_as(ctypes.c_void_p), st.shape[0]
            )
            self.states = st
        else:
            for i in range(self.states.shape[0]):
                row = bytearray(self.states[i].tobytes())
                _keccak_f_py(row)
                self.states[i] = np.frombuffer(row, dtype=np.uint8)
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data: np.ndarray) -> None:
        """data: (G, k) uint8 — per-transcript bytes, equal length."""
        off = 0
        k = data.shape[1]
        while off < k:
            take = min(k - off, _R - self.pos)
            self.states[:, self.pos : self.pos + take] ^= data[
                :, off : off + take
            ]
            self.pos += take
            off += take
            if self.pos == _R:
                self._run_f()

    def _absorb_const(self, data: bytes) -> None:
        self._absorb(
            np.tile(
                np.frombuffer(data, dtype=np.uint8),
                (self.states.shape[0], 1),
            )
        )

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            if flags != self.cur_flags:
                raise ValueError("'more' must continue the same operation")
            return
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb_const(bytes([old_begin, flags]))
        if (flags & (_FLAG_C | _FLAG_K)) and self.pos != 0:
            self._run_f()

    def meta_ad_const(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_M | _FLAG_A, more)
        self._absorb_const(data)

    def ad(self, data: np.ndarray, more: bool) -> None:
        self._begin_op(_FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool) -> np.ndarray:
        self._begin_op(_FLAG_I | _FLAG_A | _FLAG_C, more)
        out = np.empty((self.states.shape[0], n), dtype=np.uint8)
        got = 0
        while got < n:
            take = min(n - got, _R - self.pos)
            out[:, got : got + take] = self.states[
                :, self.pos : self.pos + take
            ]
            self.states[:, self.pos : self.pos + take] = 0
            self.pos += take
            got += take
            if self.pos == _R:
                self._run_f()
        return out


class TranscriptBatch:
    """G merlin transcripts advancing in lockstep (see _StrobeBatch).

    Constructed from a prototype Transcript whose state every group
    member shares (e.g. the constant signing-context prefix); appended
    messages must be equal-length across the group at each step —
    callers group their batch by message length."""

    def __init__(self, prototype: Transcript, g: int) -> None:
        self._strobe = _StrobeBatch(prototype._strobe, g)

    def append_message_const(self, label: bytes, message: bytes) -> None:
        self._strobe.meta_ad_const(label, False)
        self._strobe.meta_ad_const(struct.pack("<I", len(message)), True)
        self._strobe.ad(
            np.tile(
                np.frombuffer(message, dtype=np.uint8),
                (self._strobe.states.shape[0], 1),
            ),
            False,
        )

    def append_messages(self, label: bytes, messages: np.ndarray) -> None:
        """messages: (G, k) uint8 — one equal-length message per
        transcript."""
        self._strobe.meta_ad_const(label, False)
        self._strobe.meta_ad_const(
            struct.pack("<I", messages.shape[1]), True
        )
        self._strobe.ad(messages, False)

    def challenge_bytes(self, label: bytes, n: int) -> np.ndarray:
        """(G, n) uint8 challenge bytes."""
        self._strobe.meta_ad_const(label, False)
        self._strobe.meta_ad_const(struct.pack("<I", n), True)
        return self._strobe.prf(n, False)
