"""secp256k1 ECDSA keys (reference: crypto/secp256k1/secp256k1.go).

33-byte compressed public keys, Bitcoin-style addresses
RIPEMD160(SHA256(pubkey)), 64-byte r||s signatures with low-s
normalization. No batch support (matching the reference —
crypto/batch/batch.go only dispatches ed25519/sr25519).
"""

from __future__ import annotations

import hashlib

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
        encode_dss_signature,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )

    _CURVE = ec.SECP256K1()
except ImportError:  # gated: secp256k1 requires the cryptography wheel
    ec = None
    _CURVE = None

from .keys import Address, PrivKey, PubKey, register_key_type

__all__ = ["PubKeySecp256k1", "PrivKeySecp256k1", "KEY_TYPE"]

KEY_TYPE = "secp256k1"
PUBKEY_SIZE = 33
SIGNATURE_LEN = 64
_ORDER = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141


def _require_openssl() -> None:
    if ec is None:
        raise RuntimeError(
            "secp256k1 requires the `cryptography` wheel, which is not "
            "installed; ed25519/sr25519 keys work without it"
        )


class PubKeySecp256k1(PubKey):
    __slots__ = ("_bytes",)

    def __init__(self, data: bytes) -> None:
        if len(data) != PUBKEY_SIZE:
            raise ValueError(f"secp256k1 pubkey must be {PUBKEY_SIZE} bytes")
        self._bytes = bytes(data)

    def address(self) -> Address:
        sha = hashlib.sha256(self._bytes).digest()
        ripemd = hashlib.new("ripemd160")
        ripemd.update(sha)
        return ripemd.digest()

    def bytes(self) -> bytes:
        return self._bytes

    def type(self) -> str:
        return KEY_TYPE

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_LEN:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        # Reject malleable (high-s) signatures like the reference
        # (crypto/secp256k1/secp256k1.go Verify requires normalized s).
        if s > _ORDER // 2 or r == 0 or s == 0:
            return False
        _require_openssl()
        try:
            pub = ec.EllipticCurvePublicKey.from_encoded_point(
                _CURVE, self._bytes
            )
            pub.verify(
                encode_dss_signature(r, s), msg, ec.ECDSA(hashes.SHA256())
            )
            return True
        except (InvalidSignature, ValueError):
            return False


class PrivKeySecp256k1(PrivKey):
    __slots__ = ("_sk",)

    def __init__(self, data: bytes) -> None:
        if len(data) != 32:
            raise ValueError("secp256k1 privkey must be 32 bytes")
        _require_openssl()
        self._sk = ec.derive_private_key(
            int.from_bytes(data, "big"), _CURVE
        )

    @classmethod
    def generate(cls) -> "PrivKeySecp256k1":
        _require_openssl()
        sk = ec.generate_private_key(_CURVE)
        return cls(
            sk.private_numbers().private_value.to_bytes(32, "big")
        )

    def bytes(self) -> bytes:
        return self._sk.private_numbers().private_value.to_bytes(32, "big")

    def sign(self, msg: bytes) -> bytes:
        der = self._sk.sign(msg, ec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(der)
        if s > _ORDER // 2:
            s = _ORDER - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def pub_key(self) -> PubKey:
        return PubKeySecp256k1(
            self._sk.public_key().public_bytes(
                Encoding.X962, PublicFormat.CompressedPoint
            )
        )

    def type(self) -> str:
        return KEY_TYPE


register_key_type(KEY_TYPE, PubKeySecp256k1, proto_field=2)
