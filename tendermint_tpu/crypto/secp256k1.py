"""secp256k1 ECDSA keys (reference: crypto/secp256k1/secp256k1.go).

33-byte compressed public keys, Bitcoin-style addresses
RIPEMD160(SHA256(pubkey)), 64-byte r||s signatures with low-s
normalization — now a pure-Python CPU-native backend (the PR-1 shim
gated on a `cryptography` wheel this container lacks).

Two arithmetic planes, split by what touches key material (the tmct
structure-not-cycles contract, docs/static_analysis.md):

- **secret plane** (signing, pubkey derivation): Renes–Costello–Batina
  2015 complete projective formulas for j-invariant-0 curves
  (Algorithm 7 addition / Algorithm 9 doubling) — straight-line code
  with no exceptional cases, so scalar multiplication needs no
  secret-dependent branch, and table selection is an arithmetic mask,
  not an index.
- **public plane** (verification): fast branchy Jacobian formulas and
  an interleaved-wNAF Strauss/Shamir u1*G + u2*Q multi-scalar
  multiply. Everything here is published data; branches are free.

Batch verification: ECDSA admits no single random-linear-combination
batch equation over r||s signatures (the R point's y-coordinate is
discarded by the scheme), so `verify_batch` is the Strauss/Shamir path
per signature with shared basepoint tables and per-pubkey decompression
memoized across the batch — registered behind the BatchVerifier plugin
boundary in crypto/batch.py.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from .keys import Address, BatchVerifier, PrivKey, PubKey, register_key_type

__all__ = [
    "PubKeySecp256k1",
    "PrivKeySecp256k1",
    "Secp256k1BatchVerifier",
    "verify_batch",
    "KEY_TYPE",
]

KEY_TYPE = "secp256k1"
PUBKEY_SIZE = 33
SIGNATURE_LEN = 64

# Curve: y^2 = x^3 + 7 over F_P, prime group order N, generator G.
_P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
_ORDER = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_HALF_ORDER = _ORDER >> 1
_B3 = 21  # 3*b for the complete-formula b3 constant (b = 7)
_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

# projective identity for the complete formulas
_INF = (0, 1, 0)


# ---------------------------------------------------------------------------
# secret plane: complete projective formulas (RCB15, a=0), branch-free
# ---------------------------------------------------------------------------


def _ct_add(p: Tuple[int, int, int], q: Tuple[int, int, int]):
    """Complete projective addition (RCB15 Algorithm 7, b3=21).

    Straight-line: valid for every input pair including P+P, P+(-P),
    and the identity — the property that lets the secret-scalar ladder
    run with a fixed instruction trace."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    t0 = x1 * x2 % _P
    t1 = y1 * y2 % _P
    t2 = z1 * z2 % _P
    t3 = (x1 + y1) * (x2 + y2) % _P
    t3 = (t3 - t0 - t1) % _P
    t4 = (y1 + z1) * (y2 + z2) % _P
    t4 = (t4 - t1 - t2) % _P
    x3 = (x1 + z1) * (x2 + z2) % _P
    y3 = (x3 - t0 - t2) % _P
    x3 = (t0 + t0 + t0) % _P
    t2 = _B3 * t2 % _P
    z3 = (t1 + t2) % _P
    t1 = (t1 - t2) % _P
    y3 = _B3 * y3 % _P
    out_x = (t3 * t1 - t4 * y3) % _P
    out_y = (y3 * x3 + t1 * z3) % _P
    out_z = (z3 * t4 + x3 * t3) % _P
    return out_x, out_y, out_z


def _ct_double(p: Tuple[int, int, int]):
    """Exception-free projective doubling (RCB15 Algorithm 9, a=0)."""
    x, y, z = p
    t0 = y * y % _P
    z3 = 8 * t0 % _P
    t1 = y * z % _P
    t2 = _B3 * (z * z) % _P
    x3 = t2 * z3 % _P
    y3 = (t0 + t2) % _P
    z3 = t1 * z3 % _P
    t2 = 3 * t2 % _P
    t0 = (t0 - t2) % _P
    y3 = (t0 * y3 + x3) % _P
    x3 = 2 * (t0 * (x * y % _P)) % _P
    return x3, y3, z3


def _ct_select(table, idx: int) -> Tuple[int, int, int]:
    """Constant-structure table lookup: scan every entry, keep the one
    whose index matches via an arithmetic mask. For j, idx in [0, 15]
    `((j ^ idx) - 1) >> 4` is -1 (all ones) exactly when j == idx and
    0 otherwise — no comparison, no branch, no secret index."""
    x = y = z = 0
    for j in range(16):
        mask = ((j ^ idx) - 1) >> 4
        ex, ey, ez = table[j]
        x |= ex & mask
        y |= ey & mask
        z |= ez & mask
    return x, y, z


_CT_BASE_TABLE: Optional[List[Tuple[int, int, int]]] = None
_ct_table_lock = threading.Lock()


def _ct_base_table() -> List[Tuple[int, int, int]]:
    """[O, G, 2G, ..., 15G] projective — public constants, built once
    with the same complete formulas (cheap: 15 adds)."""
    global _CT_BASE_TABLE
    with _ct_table_lock:
        if _CT_BASE_TABLE is None:
            g = (_GX, _GY, 1)
            tbl = [_INF, g]
            for _ in range(14):
                tbl.append(_ct_add(tbl[-1], g))
            _CT_BASE_TABLE = tbl
        return _CT_BASE_TABLE


def _ct_mul_base(k: int) -> Tuple[int, int, int]:
    """k*G with a fixed execution structure: 64 4-bit windows walked
    most-significant first, four doublings and one masked-table
    addition per window regardless of the scalar's bits. k is secret;
    the loop bound, the branch structure, and the table-scan order are
    not functions of it."""
    table = _ct_base_table()
    acc = _INF
    for i in range(63, -1, -1):
        acc = _ct_double(acc)
        acc = _ct_double(acc)
        acc = _ct_double(acc)
        acc = _ct_double(acc)
        acc = _ct_add(acc, _ct_select(table, (k >> (4 * i)) & 15))
    return acc


def _ct_to_affine(p: Tuple[int, int, int]) -> Tuple[int, int]:
    """Projective -> affine. 3-arg pow is the sanctioned modular
    inverse (tmct's ct-vartime-pow rule flags only the non-modular
    forms; structure-not-cycles is the contract — see
    docs/static_analysis.md)."""
    x, y, z = p
    zi = pow(z, _P - 2, _P)
    return x * zi % _P, y * zi % _P


# ---------------------------------------------------------------------------
# public plane: branchy Jacobian + Strauss/Shamir (verification only)
# ---------------------------------------------------------------------------

_JPoint = Optional[Tuple[int, int, int]]  # None = infinity


def _jac_double(p: _JPoint) -> _JPoint:
    if p is None:
        return None
    x, y, z = p
    if y == 0:
        return None
    a = x * x % _P
    b = y * y % _P
    c = b * b % _P
    d = 2 * ((x + b) * (x + b) - a - c) % _P
    e = 3 * a % _P
    x3 = (e * e - 2 * d) % _P
    y3 = (e * (d - x3) - 8 * c) % _P
    z3 = 2 * y * z % _P
    return x3, y3, z3


def _jac_add_affine(p: _JPoint, q: Tuple[int, int]) -> _JPoint:
    """Mixed Jacobian + affine addition (q has Z=1)."""
    x2, y2 = q
    if p is None:
        return (x2, y2, 1)
    x1, y1, z1 = p
    z1z1 = z1 * z1 % _P
    u2 = x2 * z1z1 % _P
    s2 = y2 * z1 * z1z1 % _P
    if u2 == x1:
        if s2 == y1:
            return _jac_double(p)
        return None
    h = (u2 - x1) % _P
    hh = h * h % _P
    i = 4 * hh % _P
    j = h * i % _P
    rr = 2 * (s2 - y1) % _P
    v = x1 * i % _P
    x3 = (rr * rr - j - 2 * v) % _P
    y3 = (rr * (v - x3) - 2 * y1 * j) % _P
    z3 = ((z1 + h) * (z1 + h) - z1z1 - hh) % _P
    return x3, y3, z3


def _batch_to_affine(points: Sequence[Tuple[int, int, int]]):
    """Montgomery-trick batch normalization: one field inversion for
    the whole table (public data; powers the wNAF precomputation)."""
    n = len(points)
    prefix = [1] * (n + 1)
    for i, (_, _, z) in enumerate(points):
        prefix[i + 1] = prefix[i] * z % _P
    inv_all = pow(prefix[n], _P - 2, _P)
    out: List[Tuple[int, int]] = [(0, 0)] * n
    for i in range(n - 1, -1, -1):
        x, y, z = points[i]
        zi = inv_all * prefix[i] % _P
        inv_all = inv_all * z % _P
        zi2 = zi * zi % _P
        out[i] = (x * zi2 % _P, y * zi2 * zi % _P)
    return out


def _wnaf(k: int, w: int) -> List[int]:
    """Width-w non-adjacent form, little-endian digits (odd or 0)."""
    digits: List[int] = []
    full = 1 << w
    half = full >> 1
    while k:
        if k & 1:
            d = k & (full - 1)
            if d >= half:
                d -= full
            k -= d
        else:
            d = 0
        digits.append(d)
        k >>= 1
    return digits


def _jac_add(p: _JPoint, q: _JPoint) -> _JPoint:
    """General Jacobian + Jacobian addition (public plane)."""
    if p is None:
        return q
    if q is None:
        return p
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1 = z1 * z1 % _P
    z2z2 = z2 * z2 % _P
    u1 = x1 * z2z2 % _P
    u2 = x2 * z1z1 % _P
    s1 = y1 * z2 * z2z2 % _P
    s2 = y2 * z1 * z1z1 % _P
    if u1 == u2:
        if s1 != s2:
            return None
        return _jac_double(p)
    h = (u2 - u1) % _P
    i = 4 * h * h % _P
    j = h * i % _P
    rr = 2 * (s2 - s1) % _P
    v = u1 * i % _P
    x3 = (rr * rr - j - 2 * v) % _P
    y3 = (rr * (v - x3) - 2 * s1 * j) % _P
    z3 = ((z1 + z2) * (z1 + z2) - z1z1 - z2z2) * h % _P
    return x3, y3, z3


def _odd_multiples(point: Tuple[int, int], count: int):
    """[P, 3P, 5P, ...] as affine (batch-normalized), for wNAF tables.
    Valid curve points have prime order, so no chain element here is
    ever the identity."""
    p1 = (point[0], point[1], 1)
    twop = _jac_double(p1)
    jac: List[Tuple[int, int, int]] = [p1]
    for _ in range(count - 1):
        nxt = _jac_add(jac[-1], twop)
        if nxt is None:
            raise ArithmeticError("degenerate odd-multiple chain")
        jac.append(nxt)
    return _batch_to_affine(jac)


_G_WNAF_TABLE: Optional[List[Tuple[int, int]]] = None
_g_table_lock = threading.Lock()
_WNAF_W = 5  # window width: 8 odd multiples per table


def _g_wnaf_table() -> List[Tuple[int, int]]:
    global _G_WNAF_TABLE
    with _g_table_lock:
        if _G_WNAF_TABLE is None:
            _G_WNAF_TABLE = _odd_multiples((_GX, _GY), 1 << (_WNAF_W - 2))
        return _G_WNAF_TABLE


def _shamir(u1: int, u2: int, q: Tuple[int, int]) -> _JPoint:
    """u1*G + u2*Q by interleaved wNAF (Strauss/Shamir): one shared
    doubling chain, per-scalar sparse additions."""
    tg = _g_wnaf_table()
    tq = _odd_multiples(q, 1 << (_WNAF_W - 2))
    n1 = _wnaf(u1, _WNAF_W)
    n2 = _wnaf(u2, _WNAF_W)
    acc: _JPoint = None
    for i in range(max(len(n1), len(n2)) - 1, -1, -1):
        acc = _jac_double(acc)
        d1 = n1[i] if i < len(n1) else 0
        if d1:
            pt = tg[(d1 if d1 > 0 else -d1) >> 1]
            acc = _jac_add_affine(
                acc, pt if d1 > 0 else (pt[0], _P - pt[1])
            )
        d2 = n2[i] if i < len(n2) else 0
        if d2:
            pt = tq[(d2 if d2 > 0 else -d2) >> 1]
            acc = _jac_add_affine(
                acc, pt if d2 > 0 else (pt[0], _P - pt[1])
            )
    return acc


def _decompress(data: bytes) -> Optional[Tuple[int, int]]:
    """33-byte SEC1 compressed point -> affine, or None if invalid.
    Public data: pubkeys arrive on the wire."""
    if len(data) != PUBKEY_SIZE or data[0] not in (2, 3):
        return None
    x = int.from_bytes(data[1:], "big")
    if x >= _P:
        return None
    rhs = (x * x * x + 7) % _P
    y = pow(rhs, (_P + 1) >> 2, _P)
    if y * y % _P != rhs:
        return None  # x is not on the curve
    if (y & 1) != (data[0] & 1):
        y = _P - y
    return x, y


def _compress(x: int, y: int) -> bytes:
    return bytes([2 | (y & 1)]) + x.to_bytes(32, "big")


# ---------------------------------------------------------------------------
# RFC 6979 deterministic nonces
# ---------------------------------------------------------------------------


def _rfc6979_k(secret: bytes, h1: bytes) -> int:
    """HMAC-SHA256 deterministic nonce (RFC 6979 §3.2). qlen = hlen =
    256 bits, so bits2int is the identity and bits2octets is one mod."""
    z2 = (int.from_bytes(h1, "big") % _ORDER).to_bytes(32, "big")
    v = b"\x01" * 32
    key = b"\x00" * 32
    seed = secret + z2
    key = _hmac.new(key, v + b"\x00" + seed, hashlib.sha256).digest()
    v = _hmac.new(key, v, hashlib.sha256).digest()
    key = _hmac.new(key, v + b"\x01" + seed, hashlib.sha256).digest()
    v = _hmac.new(key, v, hashlib.sha256).digest()
    while True:
        v = _hmac.new(key, v, hashlib.sha256).digest()
        k = int.from_bytes(v, "big")
        if 1 <= k < _ORDER:  # tmct: ct-ok — rejection sampling per RFC 6979 §3.2: the retry event has probability ~2^-128 independent of long-term key bits
            return k
        key = _hmac.new(key, v + b"\x00", hashlib.sha256).digest()
        v = _hmac.new(key, v, hashlib.sha256).digest()


def _msg_scalar(msg: bytes) -> int:
    return int.from_bytes(hashlib.sha256(msg).digest(), "big") % _ORDER


# ---------------------------------------------------------------------------
# key classes
# ---------------------------------------------------------------------------


class PubKeySecp256k1(PubKey):
    __slots__ = ("_bytes", "_point")

    def __init__(self, data: bytes) -> None:
        if len(data) != PUBKEY_SIZE:
            raise ValueError(f"secp256k1 pubkey must be {PUBKEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._point: Optional[Tuple[int, int]] = None  # lazy decompress

    def address(self) -> Address:
        sha = hashlib.sha256(self._bytes).digest()
        ripemd = hashlib.new("ripemd160")
        ripemd.update(sha)
        return ripemd.digest()

    def bytes(self) -> bytes:
        return self._bytes

    def type(self) -> str:
        return KEY_TYPE

    def point(self) -> Optional[Tuple[int, int]]:
        """Decompressed affine point, memoized (public data — the
        pubkey IS the wire encoding). None if the encoding is invalid."""
        if self._point is None:
            self._point = _decompress(self._bytes)
        return self._point

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_LEN:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        # Reject malleable (high-s) signatures like the reference
        # (crypto/secp256k1/secp256k1.go Verify requires normalized s).
        if s > _HALF_ORDER or r == 0 or s == 0:
            return False
        if r >= _ORDER:
            return False
        point = self.point()
        if point is None:
            return False
        e = _msg_scalar(msg)
        w = pow(s, _ORDER - 2, _ORDER)
        u1 = e * w % _ORDER
        u2 = r * w % _ORDER
        cap_r = _shamir(u1, u2, point)
        if cap_r is None:
            return False
        x, y, z = cap_r
        # affine x mod N == r, checked projectively: x == r * z^2 also
        # covers the (astronomically rare) r + N < P alias
        zz = z * z % _P
        if (r * zz - x) % _P == 0:
            return True
        alias = r + _ORDER
        return alias < _P and (alias * zz - x) % _P == 0


class PrivKeySecp256k1(PrivKey):
    __slots__ = ("_secret", "_d", "_pub")

    def __init__(self, data: bytes) -> None:
        if len(data) != 32:
            raise ValueError("secp256k1 privkey must be 32 bytes")
        d = int.from_bytes(data, "big")
        if not 1 <= d < _ORDER:  # tmct: ct-ok — scalar range check at key load rejects invalid keys; it reveals only validity, the same bit generate() conditions on
            raise ValueError("secp256k1 privkey scalar out of range")
        self._secret = bytes(data)
        self._d = d
        self._pub: Optional[PubKeySecp256k1] = None

    @classmethod
    def generate(cls) -> "PrivKeySecp256k1":
        while True:
            data = os.urandom(32)
            d = int.from_bytes(data, "big")
            if 1 <= d < _ORDER:  # tmct: ct-ok — rejection sampling at key birth (probability ~2^-128 of retry), standard for uniform scalars
                return cls(data)

    def bytes(self) -> bytes:
        return self._secret

    def sign(self, msg: bytes) -> bytes:
        """RFC 6979 deterministic ECDSA over SHA-256, low-s normalized.

        The nonce-secret path (k*G) runs entirely on the complete-
        formula ladder: fixed window count, masked table selection, no
        secret-dependent structure."""
        h1 = hashlib.sha256(msg).digest()
        e = int.from_bytes(h1, "big") % _ORDER
        extra = 0
        while True:
            k = _rfc6979_k(self._secret, h1) if extra == 0 else (
                _rfc6979_k(
                    self._secret + extra.to_bytes(4, "big"), h1
                )
            )
            x, _y = _ct_to_affine(_ct_mul_base(k))
            r = x % _ORDER
            s = pow(k, _ORDER - 2, _ORDER) * (e + r * self._d) % _ORDER
            if r != 0 and s != 0:  # tmct: ct-ok — r and s ARE the published signature; the zero test gates output validity (probability ~2^-256) and reveals nothing beyond the signature itself
                break
            extra += 1
        # low-s normalization, branch-free: flip = -1 iff s > N/2,
        # then an XOR-select between s and N-s
        flip = (_HALF_ORDER - s) >> 256
        s ^= (s ^ (_ORDER - s)) & flip
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def pub_key(self) -> PubKey:
        if self._pub is None:
            x, y = _ct_to_affine(_ct_mul_base(self._d))
            self._pub = PubKeySecp256k1(_compress(x, y))
        return self._pub

    def type(self) -> str:
        return KEY_TYPE


# ---------------------------------------------------------------------------
# batch verification (Strauss/Shamir path behind the plugin boundary)
# ---------------------------------------------------------------------------


def verify_batch(
    items: Sequence[Tuple[PubKeySecp256k1, bytes, bytes]],
) -> Tuple[bool, List[bool]]:
    """Verify a batch of (pubkey, msg, sig) triples.

    ECDSA's r||s encoding discards R's y-coordinate, so no sound
    random-linear-combination over the batch exists; the batch win is
    the shared Strauss/Shamir machinery — the module-level basepoint
    wNAF table and one decompression per distinct pubkey across the
    batch. Accept/reject is byte-identical to the single-verify loop
    (pinned by test)."""
    point_memo: Dict[bytes, Optional[Tuple[int, int]]] = {}
    bitmap: List[bool] = []
    for pk, msg, sig in items:
        raw = pk.bytes()
        if raw not in point_memo:
            point_memo[raw] = pk.point()
        if point_memo[raw] is None:
            bitmap.append(False)
            continue
        if pk._point is None:
            pk._point = point_memo[raw]
        bitmap.append(pk.verify_signature(msg, sig))
    return all(bitmap) if bitmap else False, bitmap


class Secp256k1BatchVerifier(BatchVerifier):
    """CPU batch verifier for secp256k1 behind the crypto.batch plugin
    boundary. Per-signature Strauss/Shamir with shared tables (see
    verify_batch); the exact-bitmap contract and one-shot drain match
    Ed25519BatchVerifier."""

    def __init__(self) -> None:
        self._items: List[Tuple[PubKeySecp256k1, bytes, bytes]] = []

    def add(self, pub_key: PubKey, message: bytes, signature: bytes) -> None:
        if not isinstance(pub_key, PubKeySecp256k1):
            raise TypeError("Secp256k1BatchVerifier requires secp256k1 keys")
        if len(signature) != SIGNATURE_LEN:
            raise ValueError("malformed signature size")
        self._items.append((pub_key, bytes(message), bytes(signature)))

    def verify(self) -> Tuple[bool, List[bool]]:
        """One-shot: drains the queue; a second verify() without new
        add()s returns (False, []) on every backend."""
        if not self._items:
            return False, []
        items, self._items = self._items, []
        return verify_batch(items)

    def __len__(self) -> int:
        return len(self._items)


register_key_type(KEY_TYPE, PubKeySecp256k1, proto_field=2)
