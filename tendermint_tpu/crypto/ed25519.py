"""ed25519 keys with ZIP-215 verification semantics.

Behavioral parity with the reference's crypto/ed25519 package
(reference: crypto/ed25519/ed25519.go): 32-byte public keys, 64-byte
signatures, address = sha256(pubkey)[:20], ZIP-215 verification so single
and batch verification can never disagree (ed25519.go:27-29).

Fast path: OpenSSL (via the `cryptography` wheel) for signing and strict
verification. Any signature OpenSSL accepts is also ZIP-215-valid
(cofactorless acceptance implies cofactored acceptance, and OpenSSL only
accepts canonical encodings, a subset of ZIP-215's); on OpenSSL rejection we
re-check with the pure-Python ZIP-215 oracle to catch the edge cases
(non-canonical A/R encodings, mixed-cofactor components) that ZIP-215
deliberately accepts.

The wheel is gated, not required: containers without it fall back to
RFC 8032 sign/keygen on ed25519_math's comb tables and ZIP-215
verification through the native kernel (or the pure-Python oracle) —
same bits on the wire, slower signing.
"""

from __future__ import annotations

import hashlib
import os
from typing import List, Optional, Tuple

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        NoEncryption,
        PrivateFormat,
        PublicFormat,
    )

    _HAVE_OPENSSL = True
except ImportError:  # no cryptography wheel: pure-Python/native paths
    _HAVE_OPENSSL = False

from . import ed25519_math
from .keys import (
    Address,
    BatchVerifier,
    PrivKey,
    PubKey,
    address_hash,
    register_key_type,
)

__all__ = ["PubKeyEd25519", "PrivKeyEd25519", "Ed25519BatchVerifier", "KEY_TYPE"]

KEY_TYPE = "ed25519"
PUBKEY_SIZE = 32
PRIVKEY_SIZE = 64  # seed || pubkey, matching the Go ed25519 layout
SIGNATURE_SIZE = 64
JSON_PUBKEY_NAME = "tendermint/PubKeyEd25519"
JSON_PRIVKEY_NAME = "tendermint/PrivKeyEd25519"


class PubKeyEd25519(PubKey):
    __slots__ = ("_bytes", "_addr")

    def __init__(self, data: bytes) -> None:
        if len(data) != PUBKEY_SIZE:
            raise ValueError(f"ed25519 pubkey must be {PUBKEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._addr: Optional[bytes] = None

    def address(self) -> Address:
        # memoized: Vote.verify hashes the address on every gossiped
        # vote, against long-lived validator-set key objects
        addr = self._addr
        if addr is None:
            addr = self._addr = address_hash(self._bytes)
        return addr

    def bytes(self) -> bytes:
        return self._bytes

    def type(self) -> str:
        return KEY_TYPE

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE:
            return False
        if _HAVE_OPENSSL:
            try:
                Ed25519PublicKey.from_public_bytes(self._bytes).verify(
                    sig, msg
                )
                return True
            except (InvalidSignature, ValueError):
                pass
        # OpenSSL is stricter than ZIP-215 (or absent); consult the
        # oracle. The native kernel's n=1 cofactored check IS the
        # ZIP-215 equation ([8](sB-kA-R) == identity) — ~0.12 ms vs
        # ~5 ms for the pure-Python oracle, which matters because this
        # path is adversarially reachable (a flood of edge-case
        # signatures would otherwise cost milliseconds each).
        native = _native_verify_one_zip215(self._bytes, msg, sig)
        if native is not None:
            return native
        return ed25519_math.zip215_verify(self._bytes, msg, sig)


class PrivKeyEd25519(PrivKey):
    __slots__ = ("_seed", "_pub")

    def __init__(self, data: bytes) -> None:
        if len(data) == PRIVKEY_SIZE:
            seed = data[:32]
        elif len(data) == 32:
            seed = data
        else:
            raise ValueError("ed25519 privkey must be 32 or 64 bytes")
        self._seed = bytes(seed)
        if _HAVE_OPENSSL:
            sk = Ed25519PrivateKey.from_private_bytes(self._seed)
            self._pub = sk.public_key().public_bytes(
                Encoding.Raw, PublicFormat.Raw
            )
        else:
            a, _prefix = _expand_seed(self._seed)
            self._pub = ed25519_math.compress(ed25519_math.mul_base_ct(a))

    @classmethod
    def generate(cls) -> "PrivKeyEd25519":
        return cls(os.urandom(32))

    @classmethod
    def from_seed(cls, seed: bytes) -> "PrivKeyEd25519":
        return cls(seed)

    def bytes(self) -> bytes:
        # 64-byte seed||pub layout like Go's ed25519.PrivateKey
        return self._seed + self._pub

    def sign(self, msg: bytes) -> bytes:
        if _HAVE_OPENSSL:
            return Ed25519PrivateKey.from_private_bytes(self._seed).sign(msg)
        # RFC 8032 §5.1.6 on the comb tables — bit-identical output
        a, prefix = _expand_seed(self._seed)
        r = (
            int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little")
            % ed25519_math.L
        )
        R = ed25519_math.compress(ed25519_math.mul_base_ct(r))
        k = ed25519_math.sha512_mod_l(R, self._pub, msg)
        s = (r + k * a) % ed25519_math.L
        return R + s.to_bytes(32, "little")

    def pub_key(self) -> PubKey:
        return PubKeyEd25519(self._pub)

    def type(self) -> str:
        return KEY_TYPE


def _expand_seed(seed: bytes) -> Tuple[int, bytes]:
    """RFC 8032 §5.1.5: SHA-512(seed) → (clamped scalar, prefix)."""
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


# Measured crossover vs OpenSSL sequential: the native equation wins
# from n=2 up (the Straus small-batch MSM has near-zero fixed cost) —
# the same threshold the reference uses (types/validation.go:12
# batchVerifyThreshold = 2).
_NATIVE_BATCH_MIN = 2

def _native_batch_fn():
    """ctypes handle to tm_ed25519_batch_verify, or None (no toolchain /
    disabled). Caching and argtypes live with the other native
    accessors in tendermint_tpu.native."""
    from .. import native

    lib = native.ed25519_batch_lib()
    return None if lib is None else lib.tm_ed25519_batch_verify


def _native_verify_one_zip215(
    pk_bytes: bytes, msg: bytes, sig: bytes
) -> Optional[bool]:
    """One ZIP-215 verify through the native kernel: an n=1 "batch"
    with weight 1 checks [8](s*B - k*A - R) == identity — exactly the
    cofactored ZIP-215 equation (ed25519_math.zip215_verify), via the
    small-batch Straus path. None when native is unavailable."""
    fn = _native_batch_fn()
    if fn is None:
        return None
    s = int.from_bytes(sig[32:], "little")
    if s >= ed25519_math.L:
        return False
    r = sig[:32]
    k = ed25519_math.sha512_mod_l(r, pk_bytes, msg)
    rc = fn(
        pk_bytes,
        r,
        int(s).to_bytes(32, "little"),
        int(k).to_bytes(32, "little"),
        (1).to_bytes(32, "little"),
        1,
    )
    if rc == 1:
        return True
    if rc == 0:
        return False
    # rc == -1: undecodable encoding OR allocation failure — let the
    # pure-Python oracle give the authoritative answer (it rejects
    # undecodable encodings too, so results only differ on alloc
    # failure, where falling back is the correct move)
    return None


def _rlc_scalars(ss, ks):
    """Marshal the random-linear-combination weights for one batch
    equation call (shared by the ed25519 and sr25519 native paths):
    128-bit random z_i; returns (zb, a_sc, z_sc) as the packed
    little-endian scalars the C kernel expects — zb = sum z_i*s_i
    mod L for the B term, a_i = z_i*k_i mod L for the -A_i terms,
    z_i for the -R_i terms."""
    import os as _os

    n = len(ss)
    rand = _os.urandom(16 * n)
    zb = 0
    a_sc = bytearray()
    z_sc = bytearray()
    for i in range(n):
        z = int.from_bytes(rand[16 * i:16 * i + 16], "little")
        zb = (zb + z * ss[i]) % ed25519_math.L
        a_sc += ((z * ks[i]) % ed25519_math.L).to_bytes(32, "little")
        z_sc += z.to_bytes(32, "little")
    return zb.to_bytes(32, "little"), bytes(a_sc), bytes(z_sc)


def _call_verify_full(fn, items) -> bool:
    """Pack (pk, msg, sig) triples for a tm_*_verify_full native entry
    (concatenated keys/sigs, message blob + offset table, caller-drawn
    RLC randomness) and map its 1/0/-1 result. Shared by the ed25519
    and sr25519 whole-batch paths — the packing contract is identical.

    rc == -1 (undecodable or alloc failure) reports invalid-somewhere
    so the caller's per-signature pass produces the exact bitmap."""
    import ctypes
    import os as _os

    n = len(items)
    pk_b = b"".join(pk.bytes() for pk, _m, _s in items)
    sig_b = b"".join(sig for _pk, _m, sig in items)
    offs = (ctypes.c_uint64 * (n + 1))()
    pos = 0
    chunks = []
    for i, (_pk, msg, _sig) in enumerate(items):
        offs[i] = pos
        chunks.append(msg)
        pos += len(msg)
    offs[n] = pos
    rc = fn(pk_b, sig_b, b"".join(chunks), offs, _os.urandom(16 * n), n)
    # tmct: ct-ok — rc is the batch verifier's public verdict; the
    # urandom argument is the RLC randomizer coin, not key material
    return rc == 1


def _native_batch_all_valid(items) -> Optional[bool]:
    """One shot of the cofactored random-linear-combination batch
    equation in C (native/ed25519_batch.c — the CPU analog of the
    reference's curve25519-voi batch verifier,
    crypto/ed25519/ed25519.go:202-237). True = every signature valid;
    False = at least one invalid (caller falls back per-signature for
    the bitmap, as the reference does); None = native unavailable.

    The whole prep — SHA-512 challenges mod L, the 128-bit random
    weights' products — runs inside the native call too
    (tm_ed25519_verify_full); Python only concatenates the inputs. The
    RLC randomness is drawn in _call_verify_full, so the weights stay
    under this package's control."""
    from .. import native

    lib = native.ed25519_batch_lib()
    if lib is None:
        return None
    return _call_verify_full(lib.tm_ed25519_verify_full, items)


class Ed25519BatchVerifier(BatchVerifier):
    """CPU batch verifier with the real batch equation.

    Matches the reference CPU behavior (crypto/ed25519/ed25519.go:202-237
    wraps curve25519-voi's batch verifier): batches of
    >= _NATIVE_BATCH_MIN go through the native cofactored RLC batch
    equation — hashing, scalar products, and the multi-scalar multiply
    all in one native call (see PERF.md for current rates; ~8x OpenSSL
    sequential at large batches). On batch failure — or when the
    native kernel is unavailable — signatures are checked one-by-one
    for the exact bitmap, which is also how the reference attributes
    failures. The TPU implementation lives in
    tendermint_tpu.crypto.tpu_verifier and is selected by crypto.batch
    when a device is available and the batch is large enough.
    """

    def __init__(self) -> None:
        self._items: List[Tuple[PubKeyEd25519, bytes, bytes]] = []

    def add(self, pub_key: PubKey, message: bytes, signature: bytes) -> None:
        if not isinstance(pub_key, PubKeyEd25519):
            raise TypeError("Ed25519BatchVerifier requires ed25519 keys")
        if len(signature) != SIGNATURE_SIZE:
            raise ValueError("malformed signature size")
        self._items.append((pub_key, bytes(message), bytes(signature)))

    def verify(self) -> Tuple[bool, List[bool]]:
        """One-shot: drains the queue, matching the device verifier's
        contract (a BatchVerifier is one batch — the reference builds a
        fresh one per commit); a second verify() without new add()s
        returns (False, []) on every backend."""
        if not self._items:
            return False, []
        items, self._items = self._items, []
        if len(items) >= _NATIVE_BATCH_MIN:
            if _native_batch_all_valid(items) is True:
                return True, [True] * len(items)
            # invalid somewhere (or native unavailable): fall through to
            # per-signature verification for the exact bitmap
        bitmap = [pk.verify_signature(msg, sig) for pk, msg, sig in items]
        return all(bitmap), bitmap

    def __len__(self) -> int:
        return len(self._items)


register_key_type(KEY_TYPE, PubKeyEd25519, proto_field=1)
