"""Crypto layer: keys, signatures, hashing, merkle trees, batch verification.

The public surface mirrors the reference's crypto package family
(crypto/, crypto/batch, crypto/merkle, crypto/tmhash) with a TPU offload
seam behind BatchVerifier (see tendermint_tpu.crypto.tpu_verifier) and a
process-wide verified-signature cache (sigcache) that dedups signature
checks across gossip, commit, replay, and light-client stages.
"""

from .keys import (  # noqa: F401
    Address,
    BatchVerifier,
    PrivKey,
    PubKey,
    address_hash,
    pubkey_from_proto,
    pubkey_from_type_and_bytes,
    pubkey_to_proto,
)
from .ed25519 import (  # noqa: F401
    Ed25519BatchVerifier,
    PrivKeyEd25519,
    PubKeyEd25519,
)
from .secp256k1 import PrivKeySecp256k1, PubKeySecp256k1  # noqa: F401
from .symmetric import (  # noqa: F401
    XChaCha20Poly1305,
    decrypt_symmetric,
    encrypt_symmetric,
)
from . import batch, merkle, sigcache, tmhash  # noqa: F401
