"""Process-wide verified-signature cache — cross-stage dedup of crypto.

The hot path pays for every signature at least twice: a precommit is
verified at gossip time (consensus verify-ahead / VoteSet.add_vote), then
the identical (pubkey, sign_bytes, signature) triple is re-verified from
scratch when verify_commit processes the next height's LastCommit — and
again in replay, blocksync, and light-client re-checks. The committee
signer set is stable across heights, so the re-checks are pure waste
("Performance of EdDSA and BLS Signatures in Committee-Based Consensus",
arXiv:2302.00418, makes the same observation; PERF.md's decoded-point
cache proved the shape one level down). This module remembers which exact
triples have already verified, so every later stage skips the curve math
and the batch paths assemble only cache misses — which also shrinks the
padded device bucket.

Safety model:

- The key is the EXACT (pubkey bytes, sign_bytes, signature) triple — a
  tuple in a set, so a hit requires full byte equality of all three
  components. Any byte difference — forged signature, mutated
  sign-bytes, an equivocating vote's different block ID — is a miss by
  construction; unlike a digest key there is no collision to find, even
  in theory. (The tuple also beats a 128-bit BLAKE2b digest on speed:
  set membership is SipHash — keyed per process, so not
  flood-precomputable — and the pubkey/signature objects are usually
  the same interned bytes across heights, whose hashes CPython caches;
  at 10k signatures the digest alone cost ~10 ms per warm commit.)
- Only SUCCESSFUL verifications are cached; failures are never inserted,
  so a hit can only ever skip work that a fresh verify would repeat.
- The cache carries no acceptance semantics of its own: callers still run
  every address/index/height/double-sign check; only the raw signature
  equation is skipped.
- A second key shape rides the same generations: COMMIT-LEVEL keys
  (seen_commit/add_commit) that record a whole commit verification's
  success, so a fully-warm re-verification short-circuits to the tally
  in O(1) probes. The key binds the verification mode, chain_id, a
  commit content-identity token (Commit.fingerprint_token — replaced on
  any in-place mutation), the validator-set hash, a live fingerprint of
  the voting powers, and the power threshold; the sign-bytes these keys
  implicitly vouch for are machine-proved deterministic in their inputs
  by tmcheck's taint gate, and `scripts/lint.py --memo-audit` re-proves
  that argument for every memoized function on each run — the full
  soundness chain is written up in docs/static_analysis.md
  ("Memo soundness"). Commit keys are 5+-tuples starting with a mode
  string, triples are 3-tuples of bytes: the namespaces cannot collide.

Memory is bounded by two-generation rotation: inserts land in the young
generation; when it fills, the old generation is dropped (counted by
sigcache_evictions) and the young one takes its place. Hits in the old
generation are promoted, so a stable validator set survives rotation
indefinitely. The default per-generation capacity is sized to ~2 heights
of MAX_VOTES_COUNT (types/vote_set.py) precommits, so one rotation spans
several heights even at the 10k-validator stress shape: total resident
keys <= 2 generations x 20k triples; sign-bytes dominate at ~120 bytes
each (pubkeys and signatures are references into live commit/validator
objects), so the full cache tops out around 10 MB.

`TM_TPU_NO_SIGCACHE=1` disables the cache at runtime (lookups miss,
inserts are dropped) with no behavior difference except speed — the A/B
switch idiom of TM_TPU_NO_PKCACHE / TM_TPU_NO_NATIVE. Note the
consensus verify-ahead batch (consensus/state.py _preverify_votes) is
BUILT ON this cache — its results are recorded here — so the gate also
returns gossiped votes to sequential per-vote verification, not just
commits to cold batches.

Instruments (process-global on DEFAULT_REGISTRY, like the tpu_* family —
one cache per process): tendermint_tpu_sigcache_hits_total /
sigcache_misses_total / sigcache_evictions_total.
"""

from __future__ import annotations

import contextlib
import os
import threading

from ..libs import metrics as M

__all__ = [
    "DEFAULT_CAPACITY",
    "add",
    "add_commit",
    "add_key",
    "add_keys_bulk",
    "commit_memo_disabled",
    "commit_memo_enabled",
    "disabled",
    "enabled",
    "key_for",
    "observe",
    "reset",
    "seen",
    "seen_commit",
    "seen_key",
    "seen_keys_bulk",
    "set_capacity",
    "stats",
]

# ~2 heights x MAX_VOTES_COUNT (types/vote_set.py) precommits per
# generation: a full rotation spans several heights even at the
# 10k-validator stress shape, so LastCommit triples verified at gossip
# time are still resident when the next height's block arrives.
DEFAULT_CAPACITY = 20_000

_m_hits = M.new_counter(
    "sigcache", "hits_total",
    "Verified-signature cache hits (signature checks skipped).",
)
_m_misses = M.new_counter(
    "sigcache", "misses_total",
    "Verified-signature cache misses (full verification performed).",
)
_m_evictions = M.new_counter(
    "sigcache", "evictions_total",
    "Verified-signature triples dropped by generation rotation.",
)
_m_commit_hits = M.new_counter(
    "sigcache", "commit_hits_total",
    "Commit-level verification memo hits (whole commits short-"
    "circuited to the tally).",
)
_m_commit_misses = M.new_counter(
    "sigcache", "commit_misses_total",
    "Commit-level verification memo misses (per-triple probing "
    "performed).",
)

_capacity = DEFAULT_CAPACITY
_gen0: set = set()  # young generation: inserts and promotions land here
_gen1: set = set()  # old generation: dropped wholesale on rotation
_lock = threading.Lock()  # guards rotation only; set ops are GIL-atomic
_force_off = False  # tests/bench override, same effect as the env gate
_force_commit_off = False  # bench A/B arm: triple probes only


def enabled() -> bool:
    """False under TM_TPU_NO_SIGCACHE=1 (or a disabled() scope): every
    lookup misses and every insert is dropped — behavior identical to
    the cache never existing, minus the speed."""
    return not (_force_off or os.environ.get("TM_TPU_NO_SIGCACHE"))


@contextlib.contextmanager
def disabled():
    """Scope with the cache forced off (bench cold rows, A/B tests)."""
    global _force_off
    prev = _force_off
    _force_off = True
    try:
        yield
    finally:
        _force_off = prev


def key_for(pk_bytes: bytes, sign_bytes: bytes, signature: bytes) -> tuple:
    """The exact triple IS the key (a tuple): a hit requires full byte
    equality of all three components, so distinct triples can never
    alias. Hot loops may build the tuple inline instead of paying this
    call — the representation is part of the module contract."""
    return (pk_bytes, sign_bytes, signature)


def seen_key(key: tuple) -> bool:
    """Membership check for a precomputed key — no metrics, no enabled()
    gate: batch callers check enabled() once per commit, account hits
    and misses in bulk via observe(), and keep the per-triple cost to
    one tuple build + one set lookup."""
    if key in _gen0:
        return True
    if key in _gen1:
        # promote: a stable signer set's triples survive rotation. The
        # old-generation copy is discarded so entries() never double-
        # counts and rotation's eviction count covers only triples that
        # actually leave the cache.
        # tmlint: disable=lock-global-mutation — set ops are single-
        # bytecode GIL-atomic by design (module docstring); _lock
        # guards only generation rotation
        _gen1.discard(key)
        _insert(key)
        return True
    return False


def seen_keys_bulk(keys) -> set:
    """Bulk membership: returns the subset of `keys` already proven, as
    a set. One set-intersection per generation replaces the per-triple
    probe loop — at 10k signatures the warm scan's dominant Python cost
    after the sign-bytes memo (PERF.md warm-path breakdown). Old-
    generation hits are promoted exactly like seen_key. No metrics and
    no enabled() gate, same contract as seen_key: batch callers check
    enabled() once and account via observe()."""
    if not keys:
        return set()
    ks = keys if isinstance(keys, set) else set(keys)
    # tmlint: disable=lock-global-mutation — GIL-atomic set ops by
    # design (module docstring); _lock guards only generation rotation
    hits = ks & _gen0
    old = (ks - hits) & _gen1
    if old:
        # promote survivors of a stable signer set, discarding the
        # old-generation copies so entries()/evictions stay honest —
        # the bulk form of seen_key's promotion
        _gen1.difference_update(old)  # tmlint: disable=lock-global-mutation
        _gen0.update(old)  # tmlint: disable=lock-global-mutation
        hits |= old
        if len(_gen0) >= _capacity:
            _rotate()
    return hits


def add_key(key: tuple) -> None:
    """Record a precomputed key as verified (caller gates on enabled()
    and MUST only call after a successful verification)."""
    _insert(key)


def add_keys_bulk(keys) -> None:
    """Record many precomputed keys as verified (same caller contract
    as add_key). Inserts are chunked to the remaining generation
    capacity so the documented bound — at most 2 generations x
    capacity resident triples — holds even for a 10k-key drain into a
    nearly-full young generation."""
    keys = list(keys)
    pos = 0
    while pos < len(keys):
        room = max(_capacity - len(_gen0), 1)
        chunk = keys[pos:pos + room]
        pos += room
        # tmlint: disable=lock-global-mutation — GIL-atomic set update
        _gen0.update(chunk)
        if len(_gen0) >= _capacity:
            _rotate()


def _insert(key: tuple) -> None:
    # tmlint: disable=lock-global-mutation — GIL-atomic set add by
    # design; worst case a racing rotation re-checks capacity
    _gen0.add(key)
    if len(_gen0) >= _capacity:
        _rotate()


def _rotate() -> None:
    global _gen0, _gen1
    with _lock:
        if len(_gen0) < _capacity:  # lost the race: already rotated
            return
        if _gen1:
            _m_evictions.inc(len(_gen1))
        _gen1 = _gen0
        _gen0 = set()


def commit_memo_enabled() -> bool:
    """The commit-level verification memo rides the same generations
    but has its own off-switch (TM_TPU_NO_COMMIT_MEMO=1, or a
    commit_memo_disabled() scope) on top of the cache-wide gate — the
    bench's interleaved A/B arm measures the bulk triple-probe path
    with only this half disabled."""
    return enabled() and not (
        _force_commit_off or os.environ.get("TM_TPU_NO_COMMIT_MEMO")
    )


@contextlib.contextmanager
def commit_memo_disabled():
    """Scope with only the commit-level memo off (bench B arm, tests):
    triple probes still hit, so this isolates what the O(1) commit
    short-circuit buys over the bulk probe."""
    global _force_commit_off
    prev = _force_commit_off
    _force_commit_off = True
    try:
        yield
    finally:
        _force_commit_off = prev


def seen_commit(key: tuple) -> bool:
    """Probe the commit-level verification memo: True iff this exact
    (mode, chain_id, commit fingerprint token, validator-set
    fingerprint, threshold) tuple completed a fully-successful
    verification before (types/validation.py builds the key; failures
    are never recorded, so a hit can only skip work a fresh run would
    repeat). Lives in the same two-generation rotation as the triples
    — promotion keeps a live chain's commit memos resident. Counts
    sigcache_commit_{hits,misses}_total; False when disabled."""
    if not commit_memo_enabled():
        return False
    if seen_key(key):
        _m_commit_hits.inc()
        return True
    _m_commit_misses.inc()
    return False


def add_commit(key: tuple) -> None:
    """Record a commit-level key after a FULLY successful commit
    verification (every required signature proven, tally crossed)."""
    if not commit_memo_enabled():
        return
    _insert(key)


def seen(pk_bytes: bytes, sign_bytes: bytes, signature: bytes) -> bool:
    """Single-triple convenience (Vote.verify, evidence): False when
    disabled; counts one hit or miss."""
    if not enabled():
        return False
    if seen_key(key_for(pk_bytes, sign_bytes, signature)):
        _m_hits.inc()
        return True
    _m_misses.inc()
    return False


def add(pk_bytes: bytes, sign_bytes: bytes, signature: bytes) -> None:
    """Single-triple insert after a SUCCESSFUL verification."""
    if not enabled():
        return
    _insert(key_for(pk_bytes, sign_bytes, signature))


def observe(hits: int, misses: int) -> None:
    """Bulk metric accounting for batch callers (one counter touch per
    commit instead of one per signature)."""
    if hits:
        _m_hits.inc(hits)
    if misses:
        _m_misses.inc(misses)


def stats() -> dict:
    return {
        "hits": int(_m_hits.value()),
        "misses": int(_m_misses.value()),
        "evictions": int(_m_evictions.value()),
        "commit_hits": int(_m_commit_hits.value()),
        "commit_misses": int(_m_commit_misses.value()),
        "entries": len(_gen0) + len(_gen1),
        "capacity": _capacity,
    }


def set_capacity(n: int) -> None:
    """Resize the per-generation capacity (tests; operators with bigger
    validator sets). Existing entries are kept until normal rotation."""
    global _capacity
    if n < 1:
        raise ValueError(f"sigcache capacity must be >= 1: {n}")
    _capacity = int(n)


def reset() -> None:
    """Drop every cached triple (tests, bench cold rows)."""
    global _gen0, _gen1
    with _lock:
        _gen0 = set()
        _gen1 = set()


def entries() -> int:
    """Resident triple count across both generations (bound checks)."""
    return len(_gen0) + len(_gen1)
