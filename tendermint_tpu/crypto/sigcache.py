"""Process-wide verified-signature cache — cross-stage dedup of crypto.

The hot path pays for every signature at least twice: a precommit is
verified at gossip time (consensus verify-ahead / VoteSet.add_vote), then
the identical (pubkey, sign_bytes, signature) triple is re-verified from
scratch when verify_commit processes the next height's LastCommit — and
again in replay, blocksync, and light-client re-checks. The committee
signer set is stable across heights, so the re-checks are pure waste
("Performance of EdDSA and BLS Signatures in Committee-Based Consensus",
arXiv:2302.00418, makes the same observation; PERF.md's decoded-point
cache proved the shape one level down). This module remembers which exact
triples have already verified, so every later stage skips the curve math
and the batch paths assemble only cache misses — which also shrinks the
padded device bucket.

Safety model:

- The key is the EXACT (pubkey bytes, sign_bytes, signature) triple — a
  tuple in a set, so a hit requires full byte equality of all three
  components. Any byte difference — forged signature, mutated
  sign-bytes, an equivocating vote's different block ID — is a miss by
  construction; unlike a digest key there is no collision to find, even
  in theory. (The tuple also beats a 128-bit BLAKE2b digest on speed:
  set membership is SipHash — keyed per process, so not
  flood-precomputable — and the pubkey/signature objects are usually
  the same interned bytes across heights, whose hashes CPython caches;
  at 10k signatures the digest alone cost ~10 ms per warm commit.)
- Only SUCCESSFUL verifications are cached; failures are never inserted,
  so a hit can only ever skip work that a fresh verify would repeat.
- The cache carries no acceptance semantics of its own: callers still run
  every address/index/height/double-sign check; only the raw signature
  equation is skipped.

Memory is bounded by two-generation rotation: inserts land in the young
generation; when it fills, the old generation is dropped (counted by
sigcache_evictions) and the young one takes its place. Hits in the old
generation are promoted, so a stable validator set survives rotation
indefinitely. The default per-generation capacity is sized to ~2 heights
of MAX_VOTES_COUNT (types/vote_set.py) precommits, so one rotation spans
several heights even at the 10k-validator stress shape: total resident
keys <= 2 generations x 20k triples; sign-bytes dominate at ~120 bytes
each (pubkeys and signatures are references into live commit/validator
objects), so the full cache tops out around 10 MB.

`TM_TPU_NO_SIGCACHE=1` disables the cache at runtime (lookups miss,
inserts are dropped) with no behavior difference except speed — the A/B
switch idiom of TM_TPU_NO_PKCACHE / TM_TPU_NO_NATIVE. Note the
consensus verify-ahead batch (consensus/state.py _preverify_votes) is
BUILT ON this cache — its results are recorded here — so the gate also
returns gossiped votes to sequential per-vote verification, not just
commits to cold batches.

Instruments (process-global on DEFAULT_REGISTRY, like the tpu_* family —
one cache per process): tendermint_tpu_sigcache_hits_total /
sigcache_misses_total / sigcache_evictions_total.
"""

from __future__ import annotations

import contextlib
import os
import threading

from ..libs import metrics as M

__all__ = [
    "DEFAULT_CAPACITY",
    "add",
    "add_key",
    "disabled",
    "enabled",
    "key_for",
    "observe",
    "reset",
    "seen",
    "seen_key",
    "set_capacity",
    "stats",
]

# ~2 heights x MAX_VOTES_COUNT (types/vote_set.py) precommits per
# generation: a full rotation spans several heights even at the
# 10k-validator stress shape, so LastCommit triples verified at gossip
# time are still resident when the next height's block arrives.
DEFAULT_CAPACITY = 20_000

_m_hits = M.new_counter(
    "sigcache", "hits_total",
    "Verified-signature cache hits (signature checks skipped).",
)
_m_misses = M.new_counter(
    "sigcache", "misses_total",
    "Verified-signature cache misses (full verification performed).",
)
_m_evictions = M.new_counter(
    "sigcache", "evictions_total",
    "Verified-signature triples dropped by generation rotation.",
)

_capacity = DEFAULT_CAPACITY
_gen0: set = set()  # young generation: inserts and promotions land here
_gen1: set = set()  # old generation: dropped wholesale on rotation
_lock = threading.Lock()  # guards rotation only; set ops are GIL-atomic
_force_off = False  # tests/bench override, same effect as the env gate


def enabled() -> bool:
    """False under TM_TPU_NO_SIGCACHE=1 (or a disabled() scope): every
    lookup misses and every insert is dropped — behavior identical to
    the cache never existing, minus the speed."""
    return not (_force_off or os.environ.get("TM_TPU_NO_SIGCACHE"))


@contextlib.contextmanager
def disabled():
    """Scope with the cache forced off (bench cold rows, A/B tests)."""
    global _force_off
    prev = _force_off
    _force_off = True
    try:
        yield
    finally:
        _force_off = prev


def key_for(pk_bytes: bytes, sign_bytes: bytes, signature: bytes) -> tuple:
    """The exact triple IS the key (a tuple): a hit requires full byte
    equality of all three components, so distinct triples can never
    alias. Hot loops may build the tuple inline instead of paying this
    call — the representation is part of the module contract."""
    return (pk_bytes, sign_bytes, signature)


def seen_key(key: tuple) -> bool:
    """Membership check for a precomputed key — no metrics, no enabled()
    gate: batch callers check enabled() once per commit, account hits
    and misses in bulk via observe(), and keep the per-triple cost to
    one tuple build + one set lookup."""
    if key in _gen0:
        return True
    if key in _gen1:
        # promote: a stable signer set's triples survive rotation. The
        # old-generation copy is discarded so entries() never double-
        # counts and rotation's eviction count covers only triples that
        # actually leave the cache.
        # tmlint: disable=lock-global-mutation — set ops are single-
        # bytecode GIL-atomic by design (module docstring); _lock
        # guards only generation rotation
        _gen1.discard(key)
        _insert(key)
        return True
    return False


def add_key(key: tuple) -> None:
    """Record a precomputed key as verified (caller gates on enabled()
    and MUST only call after a successful verification)."""
    _insert(key)


def _insert(key: tuple) -> None:
    # tmlint: disable=lock-global-mutation — GIL-atomic set add by
    # design; worst case a racing rotation re-checks capacity
    _gen0.add(key)
    if len(_gen0) >= _capacity:
        _rotate()


def _rotate() -> None:
    global _gen0, _gen1
    with _lock:
        if len(_gen0) < _capacity:  # lost the race: already rotated
            return
        if _gen1:
            _m_evictions.inc(len(_gen1))
        _gen1 = _gen0
        _gen0 = set()


def seen(pk_bytes: bytes, sign_bytes: bytes, signature: bytes) -> bool:
    """Single-triple convenience (Vote.verify, evidence): False when
    disabled; counts one hit or miss."""
    if not enabled():
        return False
    if seen_key(key_for(pk_bytes, sign_bytes, signature)):
        _m_hits.inc()
        return True
    _m_misses.inc()
    return False


def add(pk_bytes: bytes, sign_bytes: bytes, signature: bytes) -> None:
    """Single-triple insert after a SUCCESSFUL verification."""
    if not enabled():
        return
    _insert(key_for(pk_bytes, sign_bytes, signature))


def observe(hits: int, misses: int) -> None:
    """Bulk metric accounting for batch callers (one counter touch per
    commit instead of one per signature)."""
    if hits:
        _m_hits.inc(hits)
    if misses:
        _m_misses.inc(misses)


def stats() -> dict:
    return {
        "hits": int(_m_hits.value()),
        "misses": int(_m_misses.value()),
        "evictions": int(_m_evictions.value()),
        "entries": len(_gen0) + len(_gen1),
        "capacity": _capacity,
    }


def set_capacity(n: int) -> None:
    """Resize the per-generation capacity (tests; operators with bigger
    validator sets). Existing entries are kept until normal rotation."""
    global _capacity
    if n < 1:
        raise ValueError(f"sigcache capacity must be >= 1: {n}")
    _capacity = int(n)


def reset() -> None:
    """Drop every cached triple (tests, bench cold rows)."""
    global _gen0, _gen1
    with _lock:
        _gen0 = set()
        _gen1 = set()


def entries() -> int:
    """Resident triple count across both generations (bound checks)."""
    return len(_gen0) + len(_gen1)
