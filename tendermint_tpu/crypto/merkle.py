"""RFC-6962-style SHA-256 merkle trees and proofs.

Behavioral parity with the reference's crypto/merkle package: 0x00/0x01
leaf/inner domain separation (crypto/merkle/hash.go:21,34), split point at
the largest power of two < n (crypto/merkle/tree.go:94), empty-tree hash =
sha256("") (hash.go:16), Proof verification with aunts ordered bottom-up
(crypto/merkle/proof.go:52,71), and multi-op ProofOperators chaining
(crypto/merkle/proof_op.go).

The batched/device variant of root computation and proof verification lives
in tendermint_tpu.ops.merkle_kernel; this module is the canonical CPU
implementation and oracle.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from ..libs import trace

__all__ = [
    "hash_from_byte_slices",
    "verify_proofs_batch",
    "verify_multiproofs_batch",
    "proofs_from_byte_slices",
    "multiproofs_from_byte_slices",
    "MerkleMultiTree",
    "Proof",
    "ProofOp",
    "ProofOperators",
    "ValueOp",
    "leaf_hash",
    "inner_hash",
    "empty_hash",
]

_LEAF_PREFIX = b"\x00"
_INNER_PREFIX = b"\x01"

# Device offload hooks, set by ops.merkle_kernel.install(): each takes
# the same inputs as the CPU path and returns None to decline (batch
# too small), keeping CPU the default exactly like the BatchVerifier
# seam (reference plugin boundary: crypto/crypto.go:53-61).
_device_root_hook = None
_device_proofs_hook = None


def empty_hash() -> bytes:
    return hashlib.sha256(b"").digest()


def leaf_hash(leaf: bytes) -> bytes:
    return hashlib.sha256(_LEAF_PREFIX + leaf).digest()


def inner_hash(left: bytes, right: bytes) -> bytes:
    return hashlib.sha256(_INNER_PREFIX + left + right).digest()


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n."""
    if n < 2:
        raise ValueError("n must be >= 2")
    return 1 << ((n - 1).bit_length() - 1)


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    """Merkle root of the list (same tree shape as the reference's
    recursive definition, crypto/merkle/tree.go:11-66). Large lists are
    offloaded when the device backend is installed."""
    if not items:
        return empty_hash()
    # tmcheck: taint-break — telemetry edge: span timing floats feed
    # the trace ring/metrics only and never enter the hash input
    with trace.span("merkle_hash", leaves=len(items)):
        leaf_hashes = [leaf_hash(it) for it in items]
        if _device_root_hook is not None:
            root = _device_root_hook(leaf_hashes)
            if root is not None:
                trace.add_attrs(device=True)
                return root
        return _reduce(leaf_hashes)


def verify_proofs_batch(proofs, root_hash: bytes, leaves: Sequence[bytes]):
    """Batch proof verification: bool bitmap, device-backed when
    installed (reference shape: crypto/merkle/proof.go:52 Verify, run
    per proof; the batch form is the merkle analog of
    BatchVerifier.Verify)."""
    import numpy as _np

    # tmcheck: taint-break — telemetry edge: span timing floats feed
    # the trace ring/metrics only and never enter proof bytes
    with trace.span("merkle_verify_proofs", proofs=len(proofs)):
        checked = _np.array(
            [
                len(p.leaf_hash) == 32 and leaf_hash(leaf) == p.leaf_hash
                for p, leaf in zip(proofs, leaves)
            ],
            dtype=bool,
        )
        if _device_proofs_hook is not None:
            bitmap = _device_proofs_hook(proofs, root_hash)
            if bitmap is not None:
                trace.add_attrs(device=True)
                return checked & bitmap
        cpu = _np.array(
            [p.compute_root_hash() == root_hash for p in proofs], dtype=bool
        )
        return checked & cpu


def _reduce(hashes: List[bytes]) -> bytes:
    if len(hashes) == 1:
        return hashes[0]
    k = _split_point(len(hashes))
    return inner_hash(_reduce(hashes[:k]), _reduce(hashes[k:]))


@dataclass
class Proof:
    """Merkle inclusion proof (reference: crypto/merkle/proof.go:27-38)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: List[bytes] = field(default_factory=list)

    def verify(self, root_hash: bytes, leaf: bytes) -> None:
        if self.total < 0:
            raise ValueError("proof total must be positive")
        if self.index < 0:
            raise ValueError("proof index cannot be negative")
        lh = leaf_hash(leaf)
        if lh != self.leaf_hash:
            raise ValueError("invalid leaf hash")
        computed = self.compute_root_hash()
        if computed != root_hash:
            raise ValueError("invalid root hash")

    def compute_root_hash(self) -> Optional[bytes]:
        return _compute_hash_from_aunts(
            self.index, self.total, self.leaf_hash, self.aunts
        )

    # proto form (reference: proto/tendermint/crypto/proof.pb.go Proof)
    def to_proto_bytes(self) -> bytes:
        from ..encoding.proto import ProtoWriter

        w = ProtoWriter()
        w.int(1, self.total)
        w.int(2, self.index)
        w.bytes(3, self.leaf_hash)
        for aunt in self.aunts:
            w.bytes(4, aunt)
        return w.finish()

    @classmethod
    def from_proto_bytes(cls, data: bytes) -> "Proof":
        from ..encoding.proto import FieldReader

        r = FieldReader(data)
        return cls(
            total=r.int64(1),
            index=r.int64(2),
            leaf_hash=r.bytes(3),
            aunts=list(r.get_all(4)),
        )


def _compute_hash_from_aunts(
    index: int, total: int, leaf: bytes, aunts: List[bytes]
) -> Optional[bytes]:
    if index >= total or index < 0 or total <= 0:
        return None
    if total == 1:
        return leaf if not aunts else None
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _compute_hash_from_aunts(index, k, leaf, aunts[:-1])
        if left is None:
            return None
        return inner_hash(left, aunts[-1])
    right = _compute_hash_from_aunts(index - k, total - k, leaf, aunts[:-1])
    if right is None:
        return None
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(
    items: Sequence[bytes],
) -> tuple[bytes, List[Proof]]:
    """Root hash plus an inclusion proof per item
    (reference: crypto/merkle/proof.go ProofsFromByteSlices)."""
    total = len(items)
    leaf_hashes = [leaf_hash(it) for it in items]
    proofs = [
        Proof(total=total, index=i, leaf_hash=leaf_hashes[i], aunts=[])
        for i in range(total)
    ]
    _build_aunts(leaf_hashes, list(range(total)), proofs)
    root = hash_from_byte_slices(items) if items else empty_hash()
    return root, proofs


class MerkleMultiTree:
    """Level-order hash schedule of the RFC-6962 tree: every inner node
    hashed ONCE, held by level, and shared across all proofs served
    from it.

    The schedule is the iterative form of the reference recursion
    (split at the largest power of two < n, crypto/merkle/tree.go:94):
    each round pairs adjacent nodes left-to-right and carries an odd
    trailing node up unchanged, which defers exactly the remainder
    subtree the recursive split would — the two shapes are identical
    (pinned byte-for-byte against `proofs_from_byte_slices` /
    `_compute_hash_from_aunts` by the property tests in
    tests/test_stateless_bulk.py for randomized sizes).

    This is the stateless-serving workhorse: build once per block
    (N-1 inner hashes, no per-proof recursion, no aunt lists for
    leaves nobody asked about), then answer every multi-proof request
    for that block with pure aunt gathering — K·log2(N) object-array
    lookups, zero hashing."""

    __slots__ = ("total", "levels")

    def __init__(self, leaf_hashes: Sequence[bytes]) -> None:
        levels: List[List[bytes]] = [list(leaf_hashes)]
        sha = hashlib.sha256
        while len(levels[-1]) > 1:
            cur = levels[-1]
            nxt: List[bytes] = []
            append = nxt.append
            top = len(cur) - 1
            i = 0
            while i < top:
                append(sha(_INNER_PREFIX + cur[i] + cur[i + 1]).digest())
                i += 2
            if len(cur) & 1:
                append(cur[-1])
            levels.append(nxt)
        self.total = len(levels[0])
        self.levels = levels

    @classmethod
    def from_byte_slices(cls, items: Sequence[bytes]) -> "MerkleMultiTree":
        sha = hashlib.sha256
        return cls([sha(_LEAF_PREFIX + it).digest() for it in items])

    @property
    def root(self) -> bytes:
        return self.levels[-1][0] if self.total else empty_hash()

    def proof(self, index: int) -> Proof:
        """The inclusion proof for one leaf — aunts bottom-up, exactly
        the list `_build_aunts` would have appended."""
        if index < 0 or index >= self.total:
            raise ValueError(
                f"proof index {index} out of range [0, {self.total})"
            )
        aunts: List[bytes] = []
        pos = index
        for level in self.levels[:-1]:
            sib = pos ^ 1
            if sib < len(level):
                aunts.append(level[sib])
            pos >>= 1
        return Proof(
            total=self.total,
            index=index,
            leaf_hash=self.levels[0][index],
            aunts=aunts,
        )

    def proofs(self, indices: Sequence[int]) -> List[Proof]:
        """Proofs for K indices as one level-order array program:
        sibling positions for all K paths are computed per level with
        numpy int ops and the aunts gathered from that level's node
        array — inner nodes are never re-hashed, duplicated indices
        share the tree for free."""
        import numpy as _np

        idx = _np.asarray(list(indices), dtype=_np.int64)
        if idx.size and (
            int(idx.min()) < 0 or int(idx.max()) >= self.total
        ):
            bad = int(idx.min()) if int(idx.min()) < 0 else int(idx.max())
            raise ValueError(
                f"proof index {bad} out of range [0, {self.total})"
            )
        leaf_level = self.levels[0]
        out = [
            Proof(
                total=self.total,
                index=int(i),
                leaf_hash=leaf_level[i],
                aunts=[],
            )
            for i in idx.tolist()
        ]
        pos = idx
        for level in self.levels[:-1]:
            sib = pos ^ 1
            # K appends per level, never O(level) work: the serving
            # path must stay K·log2(N) so small-K bisection probes
            # don't pay tree-sized copies per request
            sibs = sib.tolist()
            for k in _np.flatnonzero(sib < len(level)).tolist():
                out[k].aunts.append(level[sibs[k]])
            pos = pos >> 1
        return out


def multiproofs_from_byte_slices(
    items: Sequence[bytes], indices: Sequence[int]
) -> tuple[bytes, List[Proof]]:
    """Root hash plus inclusion proofs for the K requested indices,
    built as one level-order schedule (MerkleMultiTree) instead of the
    all-leaves recursion — the bulk form of `proofs_from_byte_slices`,
    byte-identical per proof (total/index/leaf_hash/aunts) to the
    recursive reference, pinned by property test."""
    indices = list(indices)  # consumed twice: span attr + proofs
    # tmcheck: taint-break — telemetry edge: span timing floats feed
    # the trace ring/metrics only and never enter the hash input
    with trace.span(
        "merkle_multiproof", leaves=len(items), k=len(indices)
    ):
        tree = MerkleMultiTree.from_byte_slices(items)
        return tree.root, tree.proofs(indices)


def _root_from_aunts_iter(
    index: int, total: int, leaf: bytes, aunts: List[bytes], inner
) -> Optional[bytes]:
    """Iterative (level-order) twin of `_compute_hash_from_aunts`:
    consumes aunts bottom-up, skips the carried odd node exactly where
    the recursion's size-1 right subtree consumes nothing, and returns
    None for every aunt-count mismatch the recursion rejects. `inner`
    is injected so the batch verifier can memoize shared nodes."""
    if index >= total or index < 0 or total <= 0:
        return None
    h = leaf
    pos, cnt, used = index, total, 0
    n_aunts = len(aunts)
    while cnt > 1:
        sib = pos ^ 1
        if sib < cnt:
            if used >= n_aunts:
                return None
            aunt = aunts[used]
            used += 1
            h = inner(aunt, h) if pos & 1 else inner(h, aunt)
        pos >>= 1
        cnt = (cnt + 1) >> 1
    return h if used == n_aunts else None


def verify_multiproofs_batch(proofs, root_hash: bytes, leaves):
    """Batched verification of K proofs cut from ONE tree: same bool
    bitmap as `verify_proofs_batch`, but inner nodes shared between
    proof paths are hashed once (the memo is keyed by the exact hash
    input, so it is sound for hostile aunts too — they simply never
    share). Verifying all N proofs of an N-leaf tree costs O(N)
    hashes instead of O(N·log N). CPU-only by design: the bulk
    serving path must stay off the device seam (bench.py's banked CPU
    block runs it before the device probe)."""
    import numpy as _np

    sha = hashlib.sha256
    # tmcheck: taint-break — telemetry edge: span timing floats feed
    # the trace ring/metrics only and never enter proof bytes
    with trace.span("merkle_verify_multiproofs", proofs=len(proofs)):
        checked = _np.array(
            [
                len(p.leaf_hash) == 32
                and sha(_LEAF_PREFIX + leaf).digest() == p.leaf_hash
                for p, leaf in zip(proofs, leaves)
            ],
            dtype=bool,
        )
        memo: dict = {}

        def inner(left: bytes, right: bytes) -> bytes:
            key = left + right
            v = memo.get(key)
            if v is None:
                v = memo[key] = sha(_INNER_PREFIX + key).digest()
            return v

        ok = _np.fromiter(
            (
                _root_from_aunts_iter(
                    p.index, p.total, p.leaf_hash, p.aunts, inner
                )
                == root_hash
                for p in proofs
            ),
            dtype=bool,
            count=len(proofs),
        )
        return checked & ok


def _build_aunts(
    hashes: List[bytes], idxs: List[int], proofs: List[Proof]
) -> bytes:
    if len(hashes) == 1:
        return hashes[0]
    k = _split_point(len(hashes))
    left = _build_aunts(hashes[:k], idxs[:k], proofs)
    right = _build_aunts(hashes[k:], idxs[k:], proofs)
    for i in idxs[:k]:
        proofs[i].aunts.append(right)
    for i in idxs[k:]:
        proofs[i].aunts.append(left)
    return inner_hash(left, right)


# -- multi-op proofs (reference: crypto/merkle/proof_op.go) --


@dataclass
class ProofOp:
    type: str
    key: bytes
    data: bytes


class ProofOperator:
    def run(self, values: List[bytes]) -> List[bytes]:
        raise NotImplementedError

    def get_key(self) -> bytes:
        raise NotImplementedError


class ValueOp(ProofOperator):
    """Proves a (key, value) pair rolls up into a merkle root
    (reference: crypto/merkle/proof_value.go)."""

    TYPE = "simple:v"

    def __init__(self, key: bytes, proof: Proof) -> None:
        self.key = key
        self.proof = proof

    def run(self, values: List[bytes]) -> List[bytes]:
        if len(values) != 1:
            raise ValueError("ValueOp expects one value")
        vhash = hashlib.sha256(values[0]).digest()
        from ..encoding.proto import ProtoWriter

        w = ProtoWriter()
        w.bytes(1, self.key)
        w.bytes(2, vhash)
        kv_bytes = w.finish()
        if leaf_hash(kv_bytes) != self.proof.leaf_hash:
            raise ValueError("leaf hash mismatch in ValueOp")
        root = self.proof.compute_root_hash()
        if root is None:
            raise ValueError("bad proof in ValueOp")
        return [root]

    def get_key(self) -> bytes:
        return self.key


class ProofOperators:
    """A chain of operators verified bottom-up against a root
    (reference: crypto/merkle/proof_op.go:60-90)."""

    def __init__(self, ops: List[ProofOperator]) -> None:
        self.ops = ops

    def verify_value(self, root: bytes, keypath: str, value: bytes) -> None:
        self.verify(root, keypath, [value])

    def verify(self, root: bytes, keypath: str, args: List[bytes]) -> None:
        keys = _parse_key_path(keypath)
        for op in self.ops:
            key = op.get_key()
            if key:
                if not keys or keys[-1] != key:
                    raise ValueError(f"key mismatch on path: {key!r}")
                keys.pop()
            args = op.run(args)
        if args != [root]:
            raise ValueError("proof did not produce the expected root")
        if keys:
            raise ValueError("keypath not fully consumed")


def _parse_key_path(path: str) -> List[bytes]:
    """Parse /url-encoded/key/path into keys, last component first
    (reference: crypto/merkle/proof_key_path.go)."""
    from urllib.parse import unquote_to_bytes

    if not path.startswith("/"):
        raise ValueError("key path must start with /")
    parts = [p for p in path.split("/")[1:] if p]
    keys = []
    for part in parts:
        if part.startswith("x:"):
            keys.append(bytes.fromhex(part[2:]))
        else:
            keys.append(unquote_to_bytes(part))
    return keys
