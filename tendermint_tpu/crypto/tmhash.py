"""SHA-256 hashing helpers (reference: crypto/tmhash/hash.go):
full 32-byte digests plus the 20-byte truncated form used for addresses."""

from __future__ import annotations

import hashlib

__all__ = ["SIZE", "TRUNCATED_SIZE", "sum256", "sum_truncated", "new"]

SIZE = 32
TRUNCATED_SIZE = 20


def sum256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sum_truncated(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()[:TRUNCATED_SIZE]


def new():
    return hashlib.sha256()
