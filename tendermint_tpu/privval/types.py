"""PrivValidator interface + in-memory test signer.

reference: types/priv_validator.go:28-33 (GetPubKey/SignVote/SignProposal)
and :63-123 (MockPV).
"""

from __future__ import annotations

import time
from abc import ABC, abstractmethod

from ..crypto.ed25519 import PrivKeyEd25519
from ..crypto.keys import PrivKey, PubKey
from ..types.proposal import Proposal
from ..types.vote import Vote

__all__ = ["PrivValidator", "MockPV"]


class PrivValidator(ABC):
    """Signs votes and proposals, never twice for the same HRS."""

    @abstractmethod
    async def get_pub_key(self) -> PubKey: ...

    @abstractmethod
    async def sign_vote(self, chain_id: str, vote: Vote) -> None:
        """Sets vote.signature (and possibly vote.timestamp_ns) in place."""

    @abstractmethod
    async def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        """Sets proposal.signature in place."""


class MockPV(PrivValidator):
    """Test signer with no double-sign protection
    (reference: types/priv_validator.go:63-123)."""

    def __init__(
        self,
        priv_key: PrivKey | None = None,
        break_proposal_sigs: bool = False,
        break_vote_sigs: bool = False,
    ) -> None:
        self.priv_key = priv_key or PrivKeyEd25519.generate()
        self.break_proposal_sigs = break_proposal_sigs
        self.break_vote_sigs = break_vote_sigs

    async def get_pub_key(self) -> PubKey:
        return self.priv_key.pub_key()

    async def sign_vote(self, chain_id: str, vote: Vote) -> None:
        if self.break_vote_sigs:
            chain_id = "incorrect-chain-id"
        if vote.timestamp_ns == 0:
            vote.timestamp_ns = time.time_ns()
        vote.signature = self.priv_key.sign(vote.sign_bytes(chain_id))

    async def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        if self.break_proposal_sigs:
            chain_id = "incorrect-chain-id"
        if proposal.timestamp_ns == 0:
            proposal.timestamp_ns = time.time_ns()
        proposal.signature = self.priv_key.sign(
            proposal.sign_bytes(chain_id)
        )
