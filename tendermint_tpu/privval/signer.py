"""Remote signer: socket endpoints between a node and an external
signing process holding the validator key.

reference: privval/{signer_listener_endpoint.go, signer_dialer_endpoint
.go, signer_client.go, signer_requestHandler.go, retry_signer_client.go,
secret_connection.go}. Roles match the reference's (slightly
counter-intuitive) arrangement: the NODE listens; the SIGNER dials in,
so the key-holding machine never exposes a listening port. Frames ride
the same X25519/ChaCha20-Poly1305 SecretConnection as p2p, and the
signer authenticates requests only after the node proves possession of
an expected node key (when configured).

The double-sign protection lives with the key, in the signer process's
FilePV last-sign state — the node side is a dumb forwarder, exactly as
in the reference.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..crypto.ed25519 import PrivKeyEd25519
from ..crypto.keys import PrivKey, PubKey, pubkey_from_proto, pubkey_to_proto
from ..encoding.proto import FieldReader, ProtoWriter
from ..libs.log import get_logger
from ..libs.service import Service
from ..p2p.conn import SecretConnection
from ..types.proposal import Proposal
from ..types.vote import Vote
from .types import PrivValidator

__all__ = [
    "RemoteSignerError",
    "RemoteSignerConnectionError",
    "SignerListenerEndpoint",
    "SignerServer",
    "RetrySignerClient",
]


class RemoteSignerError(Exception):
    """Signer replied with an error (e.g. double-sign refusal)."""


class RemoteSignerConnectionError(RemoteSignerError):
    """Transport-shaped failure: safe to retry. Signer-side refusals
    (RemoteSignerError) must NOT be retried — a double-sign refusal
    retried into a different connection would defeat the protection."""


# -- wire messages (oneof; reference: proto/tendermint/privval) -------------

_F_PUBKEY_REQ = 1
_F_PUBKEY_RESP = 2
_F_SIGN_VOTE_REQ = 3
_F_SIGNED_VOTE_RESP = 4
_F_SIGN_PROP_REQ = 5
_F_SIGNED_PROP_RESP = 6
_F_PING_REQ = 7
_F_PING_RESP = 8


def _msg(field: int, body: bytes = b"") -> bytes:
    w = ProtoWriter()
    w.message(field, body)
    return w.finish()


def _req_body(chain_id: str, payload: bytes = b"") -> bytes:
    w = ProtoWriter()
    w.string(1, chain_id)
    if payload:
        w.bytes(2, payload)
    return w.finish()


def _resp_body(payload: bytes = b"", error: str = "") -> bytes:
    w = ProtoWriter()
    if payload:
        w.bytes(1, payload)
    w.string(2, error)
    return w.finish()


def _parse(data: bytes):
    r = FieldReader(data)
    for field in range(_F_PUBKEY_REQ, _F_PING_RESP + 1):
        body = r.get(field)
        if body is not None:
            return field, body
    raise ValueError("unknown remote signer message")


# -- shared frame plumbing --------------------------------------------------


class _Conn:
    """One authenticated signer connection."""

    def __init__(self, secret: SecretConnection) -> None:
        self.secret = secret

    async def send(self, data: bytes) -> None:
        await self.secret.write_frame(data)

    async def recv(self) -> bytes:
        return await self.secret.read_frame()

    def close(self) -> None:
        self.secret.close()


# -- node side --------------------------------------------------------------


class SignerListenerEndpoint(Service, PrivValidator):
    """The node's PrivValidator backed by a remote signer that dials in
    (reference: signer_listener_endpoint.go + signer_client.go).

    Requests are serialized over the single live connection; a broken
    connection fails in-flight requests and waits for the signer to
    re-dial."""

    def __init__(
        self,
        listen_addr: str,
        node_priv_key: PrivKey,
        timeout_read: float = 5.0,
        accept_timeout: float = 30.0,
        ping_interval: float = 10.0,
        authorized_keys: Optional[list] = None,
    ) -> None:
        """authorized_keys: allowed signer transport pubkeys (raw 32-byte
        values). Empty means any dialer that completes the handshake is
        accepted — fine on a private interface, NOT on a public one."""
        Service.__init__(
            self, name="privval-listener", logger=get_logger("privval")
        )
        addr = listen_addr.replace("tcp://", "")
        host, _, port = addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.node_priv_key = node_priv_key
        self.timeout_read = timeout_read
        self.accept_timeout = accept_timeout
        self.ping_interval = ping_interval
        self.authorized_keys = set(authorized_keys or [])
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn: Optional[_Conn] = None
        self._conn_ready = asyncio.Event()
        self._lock = asyncio.Lock()

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def on_start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_signer, self.host, self.port
        )
        self.spawn(self._ping_loop(), "ping")
        self.logger.info(
            "privval listening for signer",
            addr=f"{self.host}:{self.bound_port}",
        )

    async def _ping_loop(self) -> None:
        """Detect silently-dropped connections (NAT/firewall idle
        drops): without this, the signer parks in recv() forever and
        never re-dials (reference: signer_listener_endpoint.go
        pingLoop)."""
        while True:
            await asyncio.sleep(self.ping_interval)
            if not self._conn_ready.is_set():
                continue
            try:
                await self.ping()
            except RemoteSignerError:
                # _request already tore the connection down
                self.logger.info("signer ping failed; awaiting re-dial")

    async def on_stop(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None
        self._conn_ready.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _on_signer(self, reader, writer) -> None:
        try:
            secret = await SecretConnection.handshake(
                reader, writer, self.node_priv_key
            )
        except Exception as e:
            self.logger.info("signer handshake failed", err=str(e))
            writer.close()
            return
        if (
            self.authorized_keys
            and secret.remote_pubkey.bytes() not in self.authorized_keys
        ):
            # authenticated but NOT authorized: an arbitrary dialer must
            # not be able to evict the real signer's connection
            self.logger.info(
                "rejecting unauthorized signer",
                key=secret.remote_pubkey.bytes().hex()[:16],
            )
            secret.close()
            return
        if self._conn is not None:
            # a newer signer connection replaces the old (reference:
            # the listener accepts the latest dial-in)
            self._conn.close()
        self._conn = _Conn(secret)
        self._conn_ready.set()
        self.logger.info("remote signer connected")

    async def _request(self, data: bytes) -> tuple:
        async with self._lock:
            try:
                await asyncio.wait_for(
                    self._conn_ready.wait(), self.accept_timeout
                )
            except asyncio.TimeoutError:
                raise RemoteSignerConnectionError("no signer connected")
            conn = self._conn
            if conn is None:  # shutdown/teardown race
                self._conn_ready.clear()
                raise RemoteSignerConnectionError("signer connection gone")
            try:
                await conn.send(data)
                resp = await asyncio.wait_for(
                    conn.recv(), self.timeout_read
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # ANY failure here (reset, timeout, AEAD InvalidTag on a
                # garbled frame, oversized frame) leaves the secret
                # connection's nonces desynced — the connection is toast
                # either way: drop it and wait for a re-dial
                self._poison(conn)
                raise RemoteSignerConnectionError(
                    f"signer connection failed: {e!r}"
                )
            try:
                return _parse(resp)
            except ValueError as e:
                # decryptable but malformed message: a broken or hostile
                # signer — same treatment as a transport failure, and
                # crucially it must NOT escape as ValueError (the ping
                # loop only absorbs RemoteSignerError; anything else
                # would fail-fast the whole listener service)
                self._poison(conn)
                raise RemoteSignerConnectionError(
                    f"malformed signer message: {e}"
                )

    def _poison(self, conn: Optional[_Conn]) -> None:
        if conn is not None and self._conn is conn:
            self._conn = None
            self._conn_ready.clear()
        if conn is not None:
            conn.close()

    @staticmethod
    def _unwrap(body: bytes, expect_field: int, got_field: int) -> bytes:
        if got_field != expect_field:
            raise RemoteSignerError(
                f"unexpected response type {got_field}"
            )
        r = FieldReader(body)
        err = r.string(2)
        if err:
            raise RemoteSignerError(err)
        return r.bytes(1)

    # -- PrivValidator --

    async def get_pub_key(self) -> PubKey:
        field, body = await self._request(
            _msg(_F_PUBKEY_REQ, _req_body(""))
        )
        payload = self._unwrap(body, _F_PUBKEY_RESP, field)
        return pubkey_from_proto(payload)

    async def sign_vote(self, chain_id: str, vote: Vote) -> None:
        field, body = await self._request(
            _msg(_F_SIGN_VOTE_REQ, _req_body(chain_id, vote.to_proto()))
        )
        payload = self._unwrap(body, _F_SIGNED_VOTE_RESP, field)
        signed = Vote.from_proto(payload)
        vote.signature = signed.signature
        vote.timestamp_ns = signed.timestamp_ns

    async def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        field, body = await self._request(
            _msg(
                _F_SIGN_PROP_REQ, _req_body(chain_id, proposal.to_proto())
            )
        )
        payload = self._unwrap(body, _F_SIGNED_PROP_RESP, field)
        signed = Proposal.from_proto(payload)
        proposal.signature = signed.signature
        proposal.timestamp_ns = signed.timestamp_ns

    async def ping(self) -> None:
        field, _body = await self._request(_msg(_F_PING_REQ))
        if field != _F_PING_RESP:
            raise RemoteSignerError("bad ping response")


class RetrySignerClient(PrivValidator):
    """Retry wrapper around SignerListenerEndpoint
    (reference: retry_signer_client.go). Retries only transport-shaped
    failures; signer-side refusals (double sign!) propagate
    immediately."""

    def __init__(
        self,
        inner: SignerListenerEndpoint,
        retries: int = 5,
        delay: float = 1.0,
    ) -> None:
        self.inner = inner
        self.retries = retries
        self.delay = delay

    async def _retry(self, fn, *args):
        last: Optional[Exception] = None
        for _ in range(self.retries):
            try:
                return await fn(*args)
            except RemoteSignerConnectionError as e:
                last = e
                await asyncio.sleep(self.delay)
        raise last  # type: ignore[misc]

    async def get_pub_key(self) -> PubKey:
        return await self._retry(self.inner.get_pub_key)

    async def sign_vote(self, chain_id: str, vote: Vote) -> None:
        await self._retry(self.inner.sign_vote, chain_id, vote)

    async def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        await self._retry(self.inner.sign_proposal, chain_id, proposal)


# -- signer side ------------------------------------------------------------


class SignerServer(Service):
    """The external signing process: dials the node and serves signing
    requests from a local FilePV (reference: signer_dialer_endpoint.go
    + signer_server.go + signer_requestHandler.go)."""

    def __init__(
        self,
        node_addr: str,
        pv,  # FilePV (holds the key + last-sign state)
        signer_priv_key: Optional[PrivKey] = None,
        expected_node_id: str = "",
        redial_delay: float = 1.0,
        chain_id: str = "",
    ) -> None:
        super().__init__(name="signer-server", logger=get_logger("signer"))
        addr = node_addr.replace("tcp://", "")
        host, _, port = addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.pv = pv
        # non-empty => sign requests for any OTHER chain are refused
        # (reference: signer_requestHandler.go DefaultValidationRequest
        # Handler rejects a chainID mismatch) — a misconfigured or
        # hostile node must not be able to pull signatures for another
        # chain or burn the last-sign HRS state with foreign votes
        self.chain_id = chain_id
        # transport identity for the secret connection (not the
        # validator key)
        self.signer_priv_key = signer_priv_key or PrivKeyEd25519.generate()
        self.expected_node_id = expected_node_id
        self.redial_delay = redial_delay

    async def on_start(self) -> None:
        self.spawn(self._dial_loop(), "dial")

    async def _dial_loop(self) -> None:
        while True:
            try:
                reader, writer = await asyncio.open_connection(
                    self.host, self.port
                )
                secret = await SecretConnection.handshake(
                    reader, writer, self.signer_priv_key
                )
                if self.expected_node_id:
                    from ..p2p.types import node_id_from_pubkey

                    got = node_id_from_pubkey(secret.remote_pubkey)
                    if got != self.expected_node_id:
                        raise ConnectionError(
                            f"node identity mismatch: {got}"
                        )
                self.logger.info("connected to node")
                await self._serve(_Conn(secret))
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.logger.info("signer connection ended", err=str(e))
            await asyncio.sleep(self.redial_delay)

    async def _serve(self, conn: _Conn) -> None:
        try:
            while True:
                field, body = _parse(await conn.recv())
                await conn.send(await self._handle(field, body))
        finally:
            conn.close()

    async def _handle(self, field: int, body: bytes) -> bytes:
        """reference: signer_requestHandler.go DefaultValidationRequest
        Handler."""
        r = FieldReader(body)
        chain_id = r.string(1)
        payload = r.bytes(2)
        try:
            if field == _F_PING_REQ:
                return _msg(_F_PING_RESP)
            if (
                self.chain_id
                and field in (_F_SIGN_VOTE_REQ, _F_SIGN_PROP_REQ)
                and chain_id != self.chain_id
            ):
                raise ValueError(
                    f"sign request for chain {chain_id!r}; this signer "
                    f"serves {self.chain_id!r}"
                )
            if field == _F_PUBKEY_REQ:
                pk = await self.pv.get_pub_key()
                return _msg(
                    _F_PUBKEY_RESP, _resp_body(pubkey_to_proto(pk))
                )
            if field == _F_SIGN_VOTE_REQ:
                vote = Vote.from_proto(payload)
                await self.pv.sign_vote(chain_id, vote)
                return _msg(
                    _F_SIGNED_VOTE_RESP, _resp_body(vote.to_proto())
                )
            if field == _F_SIGN_PROP_REQ:
                proposal = Proposal.from_proto(payload)
                await self.pv.sign_proposal(chain_id, proposal)
                return _msg(
                    _F_SIGNED_PROP_RESP, _resp_body(proposal.to_proto())
                )
        except Exception as e:
            resp_field = {
                _F_PUBKEY_REQ: _F_PUBKEY_RESP,
                _F_SIGN_VOTE_REQ: _F_SIGNED_VOTE_RESP,
                _F_SIGN_PROP_REQ: _F_SIGNED_PROP_RESP,
            }.get(field, _F_PUBKEY_RESP)
            return _msg(resp_field, _resp_body(error=str(e)))
        return _msg(_F_PUBKEY_RESP, _resp_body(error="unknown request"))
