"""gRPC remote signer — the reference's second privval transport
(reference: privval/grpc/{client.go,server.go,util.go}).

Arrangement is inverted from the raw-socket signer (privval/signer.py):
the SIGNER runs a gRPC server and the NODE dials it
(reference: node/setup.go:548 DialRemoteSigner, selected by a
`grpc://` scheme on the priv-validator listen address,
node/setup.go:586). Double-sign protection still lives with the key in
the signer process's FilePV.

Like the ABCI gRPC transport (abci/grpc_transport.py), the three RPCs
— GetPubKey, SignVote, SignProposal, mirroring proto/tendermint/privval
PrivValidatorAPI — carry hand-rolled deterministic proto bodies through
identity (de)serializers, so no generated stubs are needed.

Error contract (reference client.go maps grpc status straight out):
signer-side refusals (double-sign!) surface as RemoteSignerError and
are never retried; transport-shaped failures surface as
RemoteSignerConnectionError (gRPC reconnects under the hood).
"""

from __future__ import annotations

from typing import Optional

import grpc
from grpc import aio as grpc_aio

from ..crypto.keys import PrivKey, PubKey, pubkey_from_proto, pubkey_to_proto
from ..encoding.proto import FieldReader, ProtoWriter
from ..libs.log import get_logger
from ..libs.service import Service
from ..types.proposal import Proposal
from ..types.vote import Vote
from .signer import RemoteSignerConnectionError, RemoteSignerError
from .types import PrivValidator

__all__ = ["GRPCSignerServer", "GRPCSignerClient"]

_SERVICE = "tendermint_tpu.privval.PrivValidatorAPI"
_GET_PUB_KEY = "GetPubKey"
_SIGN_VOTE = "SignVote"
_SIGN_PROPOSAL = "SignProposal"

# transport-shaped gRPC codes -> retryable connection error; everything
# else is a signer-side refusal (reference: InvalidArgument for signing
# errors, NotFound for pubkey, client.go maps them straight out)
_TRANSPORT_CODES = frozenset(
    {
        grpc.StatusCode.UNAVAILABLE,
        grpc.StatusCode.DEADLINE_EXCEEDED,
        grpc.StatusCode.CANCELLED,
        grpc.StatusCode.UNKNOWN,
    }
)


def _strip_scheme(addr: str) -> str:
    for scheme in ("grpc://", "tcp://"):
        if addr.startswith(scheme):
            return addr[len(scheme):]
    return addr


def _req(chain_id: str, payload: bytes = b"") -> bytes:
    w = ProtoWriter()
    w.string(1, chain_id)
    if payload:
        w.bytes(2, payload)
    return w.finish()


def _resp(payload: bytes) -> bytes:
    w = ProtoWriter()
    w.bytes(1, payload)
    return w.finish()


class GRPCSignerServer(Service):
    """The signer process: serves a FilePV over gRPC
    (reference: privval/grpc/server.go SignerServer)."""

    def __init__(
        self,
        listen_addr: str,
        chain_id: str,
        pv,  # FilePV (key + last-sign state)
    ) -> None:
        super().__init__(
            name="privval-grpc-server", logger=get_logger("privval.grpc")
        )
        self.listen_addr = _strip_scheme(listen_addr)
        self.chain_id = chain_id
        self.pv = pv
        self._server: Optional[grpc_aio.Server] = None
        self.bound_port: Optional[int] = None

    async def on_start(self) -> None:
        self._server = grpc_aio.server()
        handlers = {
            _GET_PUB_KEY: grpc.unary_unary_rpc_method_handler(
                self._get_pub_key
            ),
            _SIGN_VOTE: grpc.unary_unary_rpc_method_handler(
                self._sign_vote
            ),
            _SIGN_PROPOSAL: grpc.unary_unary_rpc_method_handler(
                self._sign_proposal
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, handlers),)
        )
        self.bound_port = self._server.add_insecure_port(self.listen_addr)
        await self._server.start()
        self.logger.info(
            "privval grpc signer listening", port=self.bound_port
        )

    async def on_stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=0.5)
            self._server = None

    # -- handlers (reference: server.go GetPubKey/SignVote/SignProposal) --

    async def _get_pub_key(self, request: bytes, context) -> bytes:
        try:
            pk = await self.pv.get_pub_key()
            return _resp(pubkey_to_proto(pk))
        except Exception as e:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"error getting pubkey: {e}"
            )

    async def _sign_vote(self, request: bytes, context) -> bytes:
        r = FieldReader(request)
        chain_id = r.string(1)
        try:
            vote = Vote.from_proto(r.bytes(2))
            await self.pv.sign_vote(chain_id, vote)
            return _resp(vote.to_proto())
        except Exception as e:
            # double-sign refusals land here: InvalidArgument, exactly
            # like the reference server, so the client never retries
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"error signing vote: {e}",
            )

    async def _sign_proposal(self, request: bytes, context) -> bytes:
        r = FieldReader(request)
        chain_id = r.string(1)
        try:
            proposal = Proposal.from_proto(r.bytes(2))
            await self.pv.sign_proposal(chain_id, proposal)
            return _resp(proposal.to_proto())
        except Exception as e:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"error signing proposal: {e}",
            )


class GRPCSignerClient(Service, PrivValidator):
    """The node's PrivValidator dialing a gRPC signer
    (reference: privval/grpc/client.go SignerClient +
    util.go DialRemoteSigner)."""

    def __init__(self, addr: str, timeout: float = 5.0) -> None:
        Service.__init__(
            self, name="privval-grpc-client", logger=get_logger("privval.grpc")
        )
        self.addr = _strip_scheme(addr)
        self.timeout = timeout
        self._channel: Optional[grpc_aio.Channel] = None
        self._calls = {}

    async def on_start(self) -> None:
        self._channel = grpc_aio.insecure_channel(self.addr)
        for method in (_GET_PUB_KEY, _SIGN_VOTE, _SIGN_PROPOSAL):
            self._calls[method] = self._channel.unary_unary(
                f"/{_SERVICE}/{method}",
                request_serializer=None,
                response_deserializer=None,
            )

    async def on_stop(self) -> None:
        if self._channel is not None:
            await self._channel.close()
            self._channel = None
            self._calls = {}

    async def _call(self, method: str, payload: bytes) -> bytes:
        call = self._calls.get(method)
        if call is None:
            raise RemoteSignerConnectionError("grpc signer client not started")
        try:
            return await call(payload, timeout=self.timeout)
        except grpc_aio.AioRpcError as e:
            msg = f"grpc signer: {e.code().name}: {e.details()}"
            if e.code() in _TRANSPORT_CODES:
                raise RemoteSignerConnectionError(msg) from e
            raise RemoteSignerError(msg) from e

    # -- PrivValidator --

    async def get_pub_key(self) -> PubKey:
        data = await self._call(_GET_PUB_KEY, _req(""))
        return pubkey_from_proto(FieldReader(data).bytes(1))

    async def sign_vote(self, chain_id: str, vote: Vote) -> None:
        data = await self._call(
            _SIGN_VOTE, _req(chain_id, vote.to_proto())
        )
        signed = Vote.from_proto(FieldReader(data).bytes(1))
        vote.signature = signed.signature
        vote.timestamp_ns = signed.timestamp_ns

    async def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        data = await self._call(
            _SIGN_PROPOSAL, _req(chain_id, proposal.to_proto())
        )
        signed = Proposal.from_proto(FieldReader(data).bytes(1))
        proposal.signature = signed.signature
        proposal.timestamp_ns = signed.timestamp_ns
