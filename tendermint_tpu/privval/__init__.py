"""Validator signing — the PrivValidator boundary.

reference: types/priv_validator.go:28-33 (interface), privval/file.go
(FilePV with last-sign-state double-sign protection). The signer is a
host-side component by design: consensus safety (never sign twice) is a
disk-durability property, not a compute problem, so nothing here touches
the device.
"""

from .types import MockPV, PrivValidator
from .file import FilePV, FilePVKey, FilePVLastSignState
from .signer import (
    RemoteSignerError,
    RetrySignerClient,
    SignerListenerEndpoint,
    SignerServer,
)

__all__ = [
    "PrivValidator",
    "MockPV",
    "FilePV",
    "FilePVKey",
    "FilePVLastSignState",
    "RemoteSignerError",
    "RetrySignerClient",
    "SignerListenerEndpoint",
    "SignerServer",
]
