"""FilePV — file-backed validator signer with double-sign protection.

reference: privval/file.go — FilePVKey (:39-77), FilePVLastSignState
(:84-168, CheckHRS :109), FilePV (:171-420, signVote :281, signProposal
:341, saveSigned :371), checkVotesOnlyDifferByTimestamp (:388),
checkProposalsOnlyDifferByTimestamp (:404).

Safety invariant: the last-sign-state file is fsynced BEFORE a signature
leaves this process, so a crash can never release two conflicting
signatures for one (height, round, step).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field

from ..crypto import faults
from ..crypto.ed25519 import PrivKeyEd25519
from ..crypto.keys import (
    PrivKey,
    PubKey,
    generate_priv_key,
    privkey_from_type_and_bytes,
    pubkey_from_type_and_bytes,
)
from ..encoding.proto import ProtoWriter, iter_fields
from ..libs.osutil import atomic_write
from ..types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from ..types.proposal import Proposal
from ..types.vote import Vote
from .types import PrivValidator

__all__ = ["FilePV", "FilePVKey", "FilePVLastSignState"]

# Sign step numbering (reference: privval/file.go:29-36)
STEP_NONE = 0
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3


def vote_to_step(vote: Vote) -> int:
    if vote.type == PREVOTE_TYPE:
        return STEP_PREVOTE
    if vote.type == PRECOMMIT_TYPE:
        return STEP_PRECOMMIT
    raise ValueError(f"unknown vote type: {vote.type}")


# A signature must never escape before its HRS checkpoint is on disk;
# the consensus core serializes signing, so the fsync happens at most
# once per own-vote — same policy as the reference's WAL WriteSync.
_atomic_write = atomic_write


def _node_key(state_file_path: str) -> str:
    """Fault-point key for the privval.* points: the node home's
    basename (key/state files live at <home>/config/... and
    <home>/data/...), so a rule's `key=` can target one validator in a
    multi-node net."""
    d = os.path.dirname(state_file_path)
    if os.path.basename(d) in ("config", "data"):
        d = os.path.dirname(d)
    return os.path.basename(d)


def _strip_timestamp(sign_bytes: bytes, ts_field: int) -> bytes:
    """Re-encode canonical sign-bytes with the Timestamp field removed, so
    two requests can be compared modulo timestamp (reference:
    privval/file.go:388-420 zeroes the timestamp and re-marshals)."""
    # sign_bytes is varint-length-prefixed; strip the prefix first.
    from ..encoding.proto import read_length_prefixed

    body, _ = read_length_prefixed(sign_bytes)
    w = ProtoWriter()
    for fieldnum, wtype, value in iter_fields(body):
        if fieldnum == ts_field:
            continue
        if wtype == 0:
            w.uint(fieldnum, value)
        elif wtype == 1:
            w.fixed64(fieldnum, value)
        elif wtype == 2:
            w.bytes(fieldnum, value)
        else:  # pragma: no cover - canonical messages only use 0/1/2
            raise ValueError(f"unexpected wire type {wtype}")
    return w.finish()


@dataclass
class FilePVKey:
    """Immutable key part, stored in the key file
    (reference: privval/file.go:39-77)."""

    address: bytes
    pub_key: PubKey
    # repr=False: the generated __repr__ must never embed key material
    # (tmct ct-leak-telemetry — logs and crash reports render reprs);
    # PrivKey.__repr__ additionally redacts itself, this keeps the key
    # object out of the record's rendering entirely
    priv_key: PrivKey = field(repr=False)
    file_path: str = ""

    def save(self) -> None:
        data = json.dumps(
            {
                "address": self.address.hex().upper(),
                "pub_key": {
                    "type": self.pub_key.type(),
                    "value": self.pub_key.bytes().hex(),
                },
                "priv_key": {
                    "type": self.priv_key.type(),
                    "value": self.priv_key.bytes().hex(),
                },
            },
            indent=2,
        )
        _atomic_write(self.file_path, data)

    @classmethod
    def load(cls, path: str) -> "FilePVKey":
        with open(path) as f:
            raw = json.load(f)
        key_type = raw["priv_key"]["type"]
        priv = privkey_from_type_and_bytes(
            key_type, bytes.fromhex(raw["priv_key"]["value"])
        )
        pub = pubkey_from_type_and_bytes(
            raw["pub_key"]["type"], bytes.fromhex(raw["pub_key"]["value"])
        )
        addr = bytes.fromhex(raw["address"])
        if pub.address() != addr:
            raise ValueError("privval key file address/pubkey mismatch")
        return cls(address=addr, pub_key=pub, priv_key=priv, file_path=path)


@dataclass
class FilePVLastSignState:
    """Mutable part — the double-sign checkpoint
    (reference: privval/file.go:84-168)."""

    height: int = 0
    round: int = 0
    step: int = STEP_NONE
    signature: bytes = b""
    sign_bytes: bytes = b""
    file_path: str = ""

    def check_hrs(self, height: int, round_: int, step: int) -> bool:
        """Error if the HRS regressed; True if this exact HRS was already
        signed (caller must then reuse/refuse) (reference:
        privval/file.go:109-151)."""
        if self.height > height:
            raise ValueError(f"height regression. Got {height}, last height {self.height}")
        if self.height == height:
            if self.round > round_:
                raise ValueError(
                    f"round regression at height {height}. "
                    f"Got {round_}, last round {self.round}"
                )
            if self.round == round_:
                if self.step > step:
                    raise ValueError(
                        f"step regression at height {height} round {round_}. "
                        f"Got {step}, last step {self.step}"
                    )
                if self.step == step:
                    if not self.sign_bytes:
                        raise ValueError("no sign_bytes but HRS matches")
                    if not self.signature:
                        raise RuntimeError(
                            "privval: signature is nil but sign_bytes is not"
                        )
                    return True
        return False

    def save(self) -> None:
        if faults.armed():
            # "privval.save": the checkpoint write/fsync itself fails
            # (io_error) or the process dies before persisting (raise).
            # Keyed by the node home's basename so multi-node chaos
            # scenarios can target one validator's signer.
            faults.fire("privval.save", key=_node_key(self.file_path))
        data = json.dumps(
            {
                "height": self.height,
                "round": self.round,
                "step": self.step,
                "signature": self.signature.hex(),
                "signbytes": self.sign_bytes.hex(),
            },
            indent=2,
        )
        _atomic_write(self.file_path, data)

    @classmethod
    def load(cls, path: str) -> "FilePVLastSignState":
        with open(path) as f:
            raw = json.load(f)
        return cls(
            height=raw.get("height", 0),
            round=raw.get("round", 0),
            step=raw.get("step", STEP_NONE),
            signature=bytes.fromhex(raw.get("signature", "")),
            sign_bytes=bytes.fromhex(raw.get("signbytes", "")),
            file_path=path,
        )


class FilePV(PrivValidator):
    """reference: privval/file.go:171-420."""

    def __init__(self, key: FilePVKey, last_sign_state: FilePVLastSignState):
        self.key = key
        self.last_sign_state = last_sign_state

    # -- construction --

    @classmethod
    def generate(
        cls,
        key_file_path: str,
        state_file_path: str,
        key_type: str = "ed25519",
    ) -> "FilePV":
        """reference: privval/file.go:188 GenFilePV — ed25519 default,
        secp256k1 on request, anything else rejected."""
        priv = generate_priv_key(key_type)
        return cls.from_priv_key(priv, key_file_path, state_file_path)

    @classmethod
    def from_priv_key(
        cls, priv: PrivKey, key_file_path: str, state_file_path: str
    ) -> "FilePV":
        pub = priv.pub_key()
        key = FilePVKey(
            address=pub.address(),
            pub_key=pub,
            priv_key=priv,
            file_path=key_file_path,
        )
        lss = FilePVLastSignState(file_path=state_file_path)
        return cls(key, lss)

    @classmethod
    def load(cls, key_file_path: str, state_file_path: str) -> "FilePV":
        """A missing state file is an error: silently starting from an
        empty last-sign-state would disable double-sign protection after
        e.g. a partial backup restore (reference: privval/file.go
        LoadFilePV vs the separate, explicit LoadFilePVEmptyState)."""
        key = FilePVKey.load(key_file_path)
        lss = FilePVLastSignState.load(state_file_path)
        return cls(key, lss)

    @classmethod
    def load_empty_state(
        cls, key_file_path: str, state_file_path: str
    ) -> "FilePV":
        """Explicitly discard any last-sign-state (reference:
        privval/file.go LoadFilePVEmptyState). Only safe when the operator
        knows this key has never signed, or accepts the slashing risk."""
        key = FilePVKey.load(key_file_path)
        return cls(key, FilePVLastSignState(file_path=state_file_path))

    @classmethod
    def load_or_generate(
        cls,
        key_file_path: str,
        state_file_path: str,
        key_type: str = "ed25519",
    ) -> "FilePV":
        """reference: privval/file.go LoadOrGenFilePV (key_type applies
        only when generating; an existing file keeps its own type)."""
        if os.path.exists(key_file_path):
            return cls.load(key_file_path, state_file_path)
        pv = cls.generate(key_file_path, state_file_path, key_type)
        pv.save()
        return pv

    def save(self) -> None:
        self.key.save()
        self.last_sign_state.save()

    def reset(self) -> None:
        """Dangerous: wipe the double-sign checkpoint
        (reference: privval/file.go:260-270)."""
        self.last_sign_state = FilePVLastSignState(
            file_path=self.last_sign_state.file_path
        )
        self.last_sign_state.save()

    # -- PrivValidator --

    async def get_pub_key(self) -> PubKey:
        return self.key.pub_key

    async def sign_vote(self, chain_id: str, vote: Vote) -> None:
        self._sign_vote(chain_id, vote)

    async def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        self._sign_proposal(chain_id, proposal)

    # -- internals --

    def _sign_vote(self, chain_id: str, vote: Vote) -> None:
        """reference: privval/file.go:281-338."""
        height, round_, step = vote.height, vote.round, vote_to_step(vote)
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)

        if vote.timestamp_ns == 0:
            vote.timestamp_ns = time.time_ns()
        sign_bytes = vote.sign_bytes(chain_id)

        if same_hrs:
            # Only the timestamp may differ; re-release the saved signature
            # with the saved timestamp (reference: privval/file.go:313-330).
            if sign_bytes == lss.sign_bytes:
                vote.signature = lss.signature
            elif _strip_timestamp(sign_bytes, 5) == _strip_timestamp(
                lss.sign_bytes, 5
            ):
                vote.timestamp_ns = _extract_ts(lss.sign_bytes, 5)
                vote.signature = lss.signature
            else:
                raise ValueError(
                    "conflicting data: vote differs from last signed vote "
                    "at the same height/round/step"
                )
            return

        sig = self.key.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        if faults.armed():
            # "privval.release": SIGKILL between the last-sign-state
            # fsync and the vote leaving the process — the seam the
            # double-sign invariant is proven across (the restarted
            # signer must re-release THIS signature, never a
            # conflicting one; tests/test_privval.py pins it)
            faults.fire(
                "privval.release", key=_node_key(lss.file_path)
            )
        vote.signature = sig

    def _sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        """reference: privval/file.go:341-370."""
        height, round_, step = proposal.height, proposal.round, STEP_PROPOSE
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)

        if proposal.timestamp_ns == 0:
            proposal.timestamp_ns = time.time_ns()
        sign_bytes = proposal.sign_bytes(chain_id)

        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                proposal.signature = lss.signature
            elif _strip_timestamp(sign_bytes, 6) == _strip_timestamp(
                lss.sign_bytes, 6
            ):
                proposal.timestamp_ns = _extract_ts(lss.sign_bytes, 6)
                proposal.signature = lss.signature
            else:
                raise ValueError(
                    "conflicting data: proposal differs from last signed "
                    "proposal at the same height/round"
                )
            return

        sig = self.key.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        if faults.armed():
            faults.fire(
                "privval.release", key=_node_key(lss.file_path)
            )
        proposal.signature = sig

    def _save_signed(
        self, height: int, round_: int, step: int,
        sign_bytes: bytes, sig: bytes,
    ) -> None:
        """Persist BEFORE the signature escapes
        (reference: privval/file.go:371-385)."""
        lss = self.last_sign_state
        lss.height = height
        lss.round = round_
        lss.step = step
        lss.signature = sig
        lss.sign_bytes = sign_bytes
        lss.save()


def _extract_ts(sign_bytes: bytes, ts_field: int) -> int:
    """Pull the canonical Timestamp back out of saved sign-bytes."""
    from ..encoding.proto import read_length_prefixed
    from ..types.timestamp import decode_timestamp

    body, _ = read_length_prefixed(sign_bytes)
    for fieldnum, wtype, value in iter_fields(body):
        if fieldnum == ts_field and wtype == 2:
            return decode_timestamp(value)
    return 0
