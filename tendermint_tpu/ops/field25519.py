"""GF(2^255 - 19) arithmetic as batched int32 limb vectors for TPU.

TPUs have no native big-integer or 64-bit-saturating integer units, so field
elements are unsaturated 20-limb radix-2^13 vectors (20 x 13 = 260 bits) in
int32, shaped (..., 20) with arbitrary leading batch dims. Why radix 13: a
schoolbook product coefficient is at most 20 * (2^13)^2 = 1.34e9 < 2^31 - 1,
so the whole multiply pipeline — convolution, carry chains, and the
2^260 ≡ 19*32 = 608 (mod p) fold — stays in native int32 ops the VPU
vectorizes across the batch dimension. This replaces the reference's
curve25519-voi 64-bit limb arithmetic (reference: crypto/ed25519/ed25519.go
via go.mod:23) with a formulation XLA can fuse and shard.

Invariant: every field element handed between public ops here is
"normalized": all limbs in [0, 2^13] (value may exceed p; values are only
made canonical for comparisons/parity via `canonical`).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "NLIMBS",
    "RADIX",
    "P_INT",
    "to_limbs",
    "from_limbs",
    "add",
    "sub",
    "neg",
    "mul",
    "sqr",
    "mul_const",
    "carry",
    "canonical",
    "is_zero",
    "eq",
    "select",
    "pow_constexp",
    "zeros_like_batch",
    "const_limbs",
]

NLIMBS = 20
RADIX = 13
BASE = 1 << RADIX  # 8192
MASK = BASE - 1
P_INT = 2**255 - 19
# 2^260 mod p: limb index NLIMBS wraps with this factor.
FOLD = 19 * (1 << (NLIMBS * RADIX - 255))  # 608

# p and 2p in radix-2^13 limbs (for subtraction bias and canonical reduce)
_P_LIMBS = np.array(
    [(P_INT >> (RADIX * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
)
_2P_LIMBS = np.array(
    [((2 * P_INT) >> (RADIX * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
)


# -- host-side packing --


def to_limbs(x: int) -> np.ndarray:
    x %= P_INT
    return np.array(
        [(x >> (RADIX * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
    )


def from_limbs(limbs) -> int:
    arr = np.asarray(limbs, dtype=np.int64)
    return sum(int(arr[i]) << (RADIX * i) for i in range(NLIMBS)) % P_INT


def const_limbs(x: int) -> jnp.ndarray:
    return jnp.asarray(to_limbs(x))


def zeros_like_batch(batch_shape) -> jnp.ndarray:
    return jnp.zeros((*batch_shape, NLIMBS), dtype=jnp.int32)


# -- carrying --


def _chain(limbs_list):
    """Sequential carry chain over a python list of (...,)-shaped int32
    coefficient arrays. Returns (digits, carry_out)."""
    out = []
    c = None
    for x in limbs_list:
        t = x if c is None else x + c
        out.append(t & MASK)
        c = t >> RADIX
    return out, c


def _pass(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry pass over (..., NLIMBS): every limb sheds its
    high bits to its neighbor simultaneously; the top limb's carry folds
    into limb 0 with the 2^260 ≡ 608 identity. O(1) depth (vs a
    sequential 20-step chain) — this is what keeps the XLA graph small
    and the VPU busy. Works for negative transients too: `& MASK` /
    `>> RADIX` on two's-complement int32 preserve x = (x & MASK) +
    (x >> RADIX) * 2^RADIX."""
    c = x >> RADIX
    d = x & MASK
    shifted = jnp.concatenate(
        [c[..., -1:] * FOLD, c[..., :-1]], axis=-1
    )
    return d + shifted


def carry(x: jnp.ndarray) -> jnp.ndarray:
    """Loose-normalize: input limbs |x_i| < 2^17ish, output limbs in
    [-2^11, 2^13 + 2^11). Two parallel passes suffice: after pass one all
    limbs are <= 2^13 + (2^17 >> 13) + 608*small; after pass two the
    slack is a few units. The loose bound (≤ ~9500) keeps schoolbook
    products within int32: 20 * 9500^2 < 2^31."""
    return _pass(_pass(x))


# -- basic ops (always return normalized elements) --


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    # a - b + 2p: stays positive for normalized inputs.
    return carry(a - b + jnp.asarray(_2P_LIMBS))


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return carry(jnp.asarray(_2P_LIMBS) - a)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook product as 20 shifted multiply-accumulates over 39
    convolution coefficients, carried with parallel passes, then folded
    mod p. Batched over leading dims.

    Bounds: with loose-normalized inputs (|limbs| ≤ ~9500) conv
    coefficients are ≤ 20 * 9500^2 < 2^31. Two widening parallel passes
    plus one plain pass bring all 41 digit slots to ≤ 2^13 + small (the
    product value < 2^523 fits 41 slots, so the last pass provably sheds
    no carry). Digits at positions k ≥ 20 fold back with
    2^(13k) ≡ 608 * 2^(13(k-20)); position 40 folds twice (608^2)."""
    x = None  # (..., 39) conv accumulator
    pad_cfg = [(0, 0)] * (a.ndim - 1)
    for i in range(NLIMBS):
        term = a[..., i : i + 1] * b  # (..., 20)
        shifted = jnp.pad(term, pad_cfg + [(i, NLIMBS - 1 - i)])
        x = shifted if x is None else x + shifted

    # widening parallel passes (carry out of the top slot becomes a new slot)
    for _ in range(2):
        c = x >> RADIX
        d = x & MASK
        zero = jnp.zeros_like(x[..., :1])
        x = jnp.concatenate(
            [d + jnp.concatenate([zero, c[..., :-1]], axis=-1), c[..., -1:]],
            axis=-1,
        )
    # one plain pass (top carry is provably zero now)
    c = x >> RADIX
    d = x & MASK
    zero = jnp.zeros_like(x[..., :1])
    x = d + jnp.concatenate([zero, c[..., :-1]], axis=-1)

    low = x[..., :NLIMBS]
    hi = x[..., NLIMBS : 2 * NLIMBS] * FOLD  # positions 20..39 -> 0..19
    out = low + hi
    out = out.at[..., 0].add(x[..., 2 * NLIMBS] * (FOLD * FOLD))
    # limbs now ≤ 2^13 + 608*2^13 + small < 2^23; two passes normalize.
    return carry(out)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_const(a: jnp.ndarray, c: int) -> jnp.ndarray:
    """Multiply by a small constant (< 2^17), e.g. 2d folding factors."""
    return carry(a * jnp.int32(c)) if c < (1 << 17) else mul(a, const_limbs(c))


# -- canonical form and comparisons --


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Fully reduce to [0, p): fold high bits twice, then two conditional
    subtractions of p."""
    cols = [x[..., i] for i in range(NLIMBS)]
    for _ in range(2):
        # bits >= 255 live in limb 19 from bit 8 up (19*13 = 247)
        hi = cols[NLIMBS - 1] >> (255 - RADIX * (NLIMBS - 1))
        cols[NLIMBS - 1] = cols[NLIMBS - 1] & ((1 << (255 - RADIX * (NLIMBS - 1))) - 1)
        cols[0] = cols[0] + hi * 19
        cols, c = _chain(cols)
        cols[0] = cols[0] + c * FOLD
        cols, _ = _chain(cols)
    v = jnp.stack(cols, axis=-1)
    for _ in range(2):
        v = _cond_sub_p(v)
    return v


def _cond_sub_p(v: jnp.ndarray) -> jnp.ndarray:
    p = jnp.asarray(_P_LIMBS)
    cols = [v[..., i] for i in range(NLIMBS)]
    diff = []
    borrow = None
    for i in range(NLIMBS):
        t = cols[i] - p[i] - (0 if borrow is None else borrow)
        borrow = (t < 0).astype(jnp.int32)
        diff.append(t + borrow * BASE)
    ge = borrow == 0  # v >= p
    d = jnp.stack(diff, axis=-1)
    return jnp.where(ge[..., None], d, v)


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """True where the (possibly non-canonical) element ≡ 0 mod p."""
    return jnp.all(canonical(x) == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return is_zero(sub(a, b))


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Elementwise field select; cond shaped like the batch dims."""
    return jnp.where(cond[..., None], a, b)


def pow_constexp(x: jnp.ndarray, exponent: int) -> jnp.ndarray:
    """x^e for a compile-time-constant exponent via left-to-right
    square-and-multiply under lax.scan (fixed trip count, so XLA compiles
    one body — no data-dependent control flow)."""
    bits = np.array(
        [(exponent >> i) & 1 for i in range(exponent.bit_length())][::-1],
        dtype=np.bool_,
    )
    one = jnp.broadcast_to(const_limbs(1), x.shape)

    def body(acc, bit):
        acc = sqr(acc)
        acc = jnp.where(bit, mul(acc, x), acc)
        return acc, None

    acc, _ = jax.lax.scan(body, one, jnp.asarray(bits))
    return acc
