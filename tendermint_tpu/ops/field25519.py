"""GF(2^255 - 19) arithmetic as batched int32 limb vectors for TPU.

TPUs have no native big-integer or 64-bit-saturating integer units, so field
elements are unsaturated 20-limb radix-2^13 vectors (20 x 13 = 260 bits) in
int32. Why radix 13: a schoolbook product coefficient is at most
20 * (2^13)^2 = 1.34e9 < 2^31 - 1, so the whole multiply pipeline —
convolution, carry chains, and the 2^260 ≡ 19*32 = 608 (mod p) fold — stays
in native int32 ops the VPU vectorizes across the batch dimension. This
replaces the reference's curve25519-voi 64-bit limb arithmetic (reference:
crypto/ed25519/ed25519.go via go.mod:23) with a formulation XLA can fuse
and shard.

Layout: elements are shaped (..., NLIMBS, N) with the BATCH axis minor.
TPU vector registers are (8 sublanes, 128 lanes) over the two minor axes;
putting the batch in the lane axis keeps all 128 lanes busy, whereas a
batch-major (N, 20) layout strands 108 of 128 lanes on the 20-limb axis
(measured ~6x end-to-end difference on v5e). Leading axes (the limb axis
and any coordinate-stacking axes) are free.

Invariant: every field element handed between public ops here is
"normalized": all limbs in [0, 2^13] (value may exceed p; values are only
made canonical for comparisons/parity via `canonical`).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "NLIMBS",
    "RADIX",
    "P_INT",
    "to_limbs",
    "from_limbs",
    "add",
    "sub",
    "neg",
    "mul",
    "sqr",
    "mul_const",
    "carry",
    "carry1",
    "canonical",
    "is_zero",
    "eq",
    "select",
    "pow_p58",
    "pow2k",
    "zeros_like_batch",
    "const_limbs",
]

NLIMBS = 20
RADIX = 13
BASE = 1 << RADIX  # 8192
MASK = BASE - 1
P_INT = 2**255 - 19
# 2^260 mod p: limb index NLIMBS wraps with this factor.
FOLD = 19 * (1 << (NLIMBS * RADIX - 255))  # 608

# p and 2p in radix-2^13 limbs (for subtraction bias and canonical reduce),
# shaped (NLIMBS, 1) so they broadcast against (..., NLIMBS, N).
_P_LIMBS = np.array(
    [(P_INT >> (RADIX * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
)[:, None]
_2P_LIMBS = np.array(
    [((2 * P_INT) >> (RADIX * i)) & MASK for i in range(NLIMBS)],
    dtype=np.int32,
)[:, None]


# -- host-side packing --


def to_limbs(x: int) -> np.ndarray:
    """(NLIMBS,) int32 for a scalar value."""
    x %= P_INT
    return np.array(
        [(x >> (RADIX * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
    )


def from_limbs(limbs) -> int:
    arr = np.asarray(limbs, dtype=np.int64)
    return sum(int(arr[i]) << (RADIX * i) for i in range(NLIMBS)) % P_INT


def const_limbs(x: int) -> jnp.ndarray:
    """(NLIMBS, 1): broadcasts against any batch width."""
    return jnp.asarray(to_limbs(x)[:, None])


def zeros_like_batch(n: int) -> jnp.ndarray:
    return jnp.zeros((NLIMBS, n), dtype=jnp.int32)


# -- carrying --


def _pass(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry pass over (..., NLIMBS, N): every limb sheds its
    high bits to its neighbor simultaneously; the top limb's carry folds
    into limb 0 with the 2^260 ≡ 608 identity. O(1) depth (vs a
    sequential 20-step chain) — this is what keeps the XLA graph small
    and the VPU busy. Works for negative transients too: `& MASK` /
    `>> RADIX` on two's-complement int32 preserve x = (x & MASK) +
    (x >> RADIX) * 2^RADIX."""
    c = x >> RADIX
    d = x & MASK
    shifted = jnp.concatenate(
        [c[..., -1:, :] * FOLD, c[..., :-1, :]], axis=-2
    )
    return d + shifted


def carry(x: jnp.ndarray) -> jnp.ndarray:
    """Loose-normalize: input limbs |x_i| up to ~2^27.5, output limbs
    in [-2^11, 2^13 + 2^11). Two parallel passes suffice: after pass
    one, limb 0 is <= 2^13 + 608*(|x| >> 13) (the top limb's carry
    wraps in multiplied by 608) and the rest <= 2^13 + (|x| >> 13);
    after pass two the slack is <= 608*3 on limb 0 and a few units
    elsewhere. The envelope proof fails above ~2^27.75 (limb 1 would
    exceed 2^13 + 2^11 after pass two), so ~2^27.5 is the contract —
    the heaviest caller, _conv_tail, peaks at ~2^27.3 (analysis in its
    docstring, pinned by tests/test_ops_field.py's envelope cases).
    The loose output bound (≤ ~10300) keeps schoolbook products within
    int32: 20 * 10300 * 9000 < 2^31."""
    return _pass(_pass(x))


def carry1(x: jnp.ndarray) -> jnp.ndarray:
    """Single carry pass — enough when input limbs are < 2^15ish (e.g.
    sums of two normalized elements plus the 2p bias): output limbs
    land in [-small, 2^13 + 2^2]."""
    return _pass(x)


# -- basic ops (always return normalized elements) --


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    # a - b + 2p: stays positive for normalized inputs.
    return carry(a - b + jnp.asarray(_2P_LIMBS))


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return carry(jnp.asarray(_2P_LIMBS) - a)


def _conv_tail(x: jnp.ndarray) -> jnp.ndarray:
    """(…, 39, N) raw convolution coefficients -> (…, 20, N)
    loose-normalized product limbs. One widening pass, one fold, two
    carry passes — shared by mul and sqr.

    Bounds (both operands at the full loose-normal envelope,
    |limbs| ≤ 10240, operand VALUES nonnegative — the program-wide
    invariant kept by the +2p biases and pinned by
    tests/test_ops_field.py's envelope cases):
      conv coeffs |c| ≤ 20 * 10240^2 < 2^31                (int32 safe)
      widening pass: d ∈ [0, 2^13), carry-in |c| ≤ 2^18    -> ≤ 2^18.02
      fold (2^(13k) ≡ 608 * 2^(13(k-20))): |out| ≤ 2^18.02 * 608 < 2^27.3
      carry pass A: limb0 ≤ 2^13 + 608*(2^27.3 >> 13) < 2^23.6,
                    limbs 1..19 ≤ 2^13 + 2^14.3
      carry pass B: limb0 ≤ 2^13 + 608*3 = 10015 < 10240,
                    limb1 ≤ 2^13 + 1465, rest ≤ 2^13 + 3   (envelope)"""
    c = x >> RADIX
    d = x & MASK
    zero = jnp.zeros_like(x[..., :1, :])
    x = jnp.concatenate(
        [
            d + jnp.concatenate([zero, c[..., :-1, :]], axis=-2),
            c[..., -1:, :],
        ],
        axis=-2,
    )  # 40 slots; the full product value lives in them
    low = x[..., :NLIMBS, :]
    hi = x[..., NLIMBS : 2 * NLIMBS, :] * FOLD  # positions 20..39 -> 0..19
    return carry(low + hi)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook product as 20 shifted multiply-accumulates over 39
    convolution coefficients, carried with parallel passes, then folded
    mod p (see _conv_tail for the carry schedule and its bounds).
    Batched over the minor axis."""
    x = None  # (..., 39, N) conv accumulator
    pad_cfg_head = [(0, 0)] * (a.ndim - 2)
    for i in range(NLIMBS):
        term = a[..., i : i + 1, :] * b  # (..., 20, N)
        shifted = jnp.pad(
            term, pad_cfg_head + [(i, NLIMBS - 1 - i), (0, 0)]
        )
        x = shifted if x is None else x + shifted
    return _conv_tail(x)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    """Symmetric schoolbook square: the off-diagonal half-triangle is
    summed once and doubled at the end — 230 MAC rows vs mul's 400.

    int32 bound: inputs are tightened with one extra pass (limbs
    ≤ 2^13 + 2^2), so a coefficient's half-sum is ≤ 10 * 8196^2 < 2^30
    and 2*S + diag < 1.5e9 < 2^31."""
    a = _pass(a)
    x = None
    diag = None
    pad_cfg_head = [(0, 0)] * (a.ndim - 2)
    for i in range(NLIMBS):
        ai = a[..., i : i + 1, :]
        row = ai * a[..., i:, :]  # coeffs 2i .. i+19 (diag first)
        shifted = jnp.pad(
            row, pad_cfg_head + [(2 * i, NLIMBS - 1 - i), (0, 0)]
        )
        x = shifted if x is None else x + shifted
        d = jnp.pad(
            ai * ai, pad_cfg_head + [(2 * i, 2 * (NLIMBS - 1 - i)), (0, 0)]
        )
        diag = d if diag is None else diag + d
    x = x + x - diag  # diag once, off-diagonal twice
    return _conv_tail(x)


def mul_const(a: jnp.ndarray, c: int) -> jnp.ndarray:
    """Multiply by a small constant (< 2^17), e.g. 2d folding factors."""
    return carry(a * jnp.int32(c)) if c < (1 << 17) else mul(a, const_limbs(c))


# -- canonical form and comparisons --


def _chain_cols(cols):
    """Sequential carry chain over a python list of (..., N)-shaped
    arrays. Returns (digits, carry_out)."""
    out = []
    c = None
    for x in cols:
        t = x if c is None else x + c
        out.append(t & MASK)
        c = t >> RADIX
    return out, c


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Fully reduce to [0, p): fold high bits twice, then two conditional
    subtractions of p."""
    cols = [x[..., i, :] for i in range(NLIMBS)]
    for _ in range(2):
        # bits >= 255 live in limb 19 from bit 8 up (19*13 = 247)
        hi = cols[NLIMBS - 1] >> (255 - RADIX * (NLIMBS - 1))
        cols[NLIMBS - 1] = cols[NLIMBS - 1] & (
            (1 << (255 - RADIX * (NLIMBS - 1))) - 1
        )
        cols[0] = cols[0] + hi * 19
        cols, c = _chain_cols(cols)
        cols[0] = cols[0] + c * FOLD
        cols, _ = _chain_cols(cols)
    v = jnp.stack(cols, axis=-2)
    for _ in range(2):
        v = _cond_sub_p(v)
    return v


def _cond_sub_p(v: jnp.ndarray) -> jnp.ndarray:
    p = np.asarray(_P_LIMBS)[:, 0]
    cols = [v[..., i, :] for i in range(NLIMBS)]
    diff = []
    borrow = None
    for i in range(NLIMBS):
        t = cols[i] - int(p[i]) - (0 if borrow is None else borrow)
        borrow = (t < 0).astype(jnp.int32)
        diff.append(t + borrow * BASE)
    ge = borrow == 0  # v >= p
    d = jnp.stack(diff, axis=-2)
    return jnp.where(ge[..., None, :], d, v)


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """True where the (possibly non-canonical) element ≡ 0 mod p.
    Shape (..., NLIMBS, N) -> (..., N)."""
    return jnp.all(canonical(x) == 0, axis=-2)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return is_zero(sub(a, b))


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Elementwise field select; cond shaped like the batch dims (..., N)."""
    return jnp.where(cond[..., None, :], a, b)


def pow2k(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """x^(2^k): k repeated squarings under fori_loop (one compiled body)."""
    return jax.lax.fori_loop(0, k, lambda _i, a: sqr(a), x)


def pow_p58(x: jnp.ndarray) -> jnp.ndarray:
    """x^((p-5)/8) = x^(2^252 - 3) via the standard curve25519 addition
    chain: 254 squarings + 11 multiplies (vs ~252 sqr + ~252 conditional
    muls for naive square-and-multiply — the conditional muls were ~12%
    of the whole verify program)."""
    x2 = sqr(x)  # 2
    t = sqr(sqr(x2))  # 8
    x9 = mul(x, t)  # 9
    x11 = mul(x2, x9)  # 11
    x22 = sqr(x11)  # 22
    x_5_0 = mul(x9, x22)  # 2^5 - 1
    x_10_0 = mul(pow2k(x_5_0, 5), x_5_0)  # 2^10 - 1
    x_20_0 = mul(pow2k(x_10_0, 10), x_10_0)  # 2^20 - 1
    x_40_0 = mul(pow2k(x_20_0, 20), x_20_0)  # 2^40 - 1
    x_50_0 = mul(pow2k(x_40_0, 10), x_10_0)  # 2^50 - 1
    x_100_0 = mul(pow2k(x_50_0, 50), x_50_0)  # 2^100 - 1
    x_200_0 = mul(pow2k(x_100_0, 100), x_100_0)  # 2^200 - 1
    x_250_0 = mul(pow2k(x_200_0, 50), x_50_0)  # 2^250 - 1
    return mul(pow2k(x_250_0, 2), x)  # 2^252 - 3
