"""Batched sr25519 (schnorrkel over ristretto255) verification on TPU.

The device program takes a batch of (pubkey, signature, challenge-scalar)
and returns a validity bitmap — the TPU replacement for the reference's
sr25519 batch verifier (crypto/sr25519/batch.go via curve25519-voi)
behind the same crypto.BatchVerifier seam (crypto/crypto.go:53-61).

Verification equation (schnorrkel sign.rs, cofactorless — ristretto255
is prime order):

    [s]B - [k]A == R   (as ristretto255 group elements)

with k the merlin-transcript Fiat-Shamir challenge. The merlin/STROBE
transcript (Keccak-f permutations over a byte stream) stays on host —
crypto/merlin.py backed by the native keccakf (tendermint_tpu/native) —
because message lengths vary per signature; everything from the 32-byte
challenge onward runs on device:

    ristretto decode of A and R (RFC 9496 §4.3.1, incl. canonicity)
    s < L canonicality + v1 marker-bit check
    [s]B - [k]A via the shared Horner dual-mult
        (ops/ed25519_kernel.dual_mult_sb_minus_ka — same -A table,
        same niels B table, same 64-window radix-16 scan)
    ristretto equality (RFC 9496 §4.4), projective so no inversions

Layout: batch-minor throughout, matching field25519's layout note.
Differential oracle: crypto/ristretto.py (Python ints, RFC 9496
vectors) through crypto/sr25519.py's verify_signature.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto import ed25519_math as em
from . import field25519 as F
from .ed25519_kernel import (
    DEFAULT_BUCKET_SIZES,
    _TOPCLEAR,
    _bytes_const,
    _fe_from_bytes_dev,
    _join_cols,
    _lt_const_dev,
    _nibbles_dev,
    _s_lt_l_dev,
    bucket_for,
    dual_mult_sb_minus_ka,
)

__all__ = ["Sr25519Verifier", "batch_verify_host"]

_P8 = _bytes_const(em.P, 32)  # field prime as 32 LE byte limbs
_SQRT_M1_INT = em.SQRT_M1
_D_INT = em.D


def _abs_dev(x: jnp.ndarray) -> jnp.ndarray:
    """CT_ABS (RFC 9496 §4.1): negate iff the canonical form is odd."""
    parity = F.canonical(x)[..., 0, :] & 1
    return F.select(parity == 1, F.neg(x), x)


def _is_negative_dev(x: jnp.ndarray) -> jnp.ndarray:
    return (F.canonical(x)[..., 0, :] & 1) == 1


def _sqrt_ratio_m1_dev(
    u: jnp.ndarray, v: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """SQRT_RATIO_M1 (RFC 9496 §4.2), batched.

    Returns (was_square (N,), r (NLIMBS, N)) with r = |sqrt(u/v)| when
    u/v is square, else |sqrt(i*u/v)|. The exponentiation reuses the
    (p-5)/8 addition chain (254 squarings) from the ed25519 kernel's
    decompression path."""
    v2 = F.sqr(v)
    v3 = F.mul(v2, v)
    v7 = F.mul(F.sqr(v3), v)
    r = F.mul(F.mul(u, v3), F.pow_p58(F.mul(u, v7)))
    check = F.mul(v, F.sqr(r))
    u_neg = F.neg(u)
    sqrt_m1 = jnp.broadcast_to(F.const_limbs(_SQRT_M1_INT), u.shape)
    correct = F.eq(check, u)
    flipped = F.eq(check, u_neg)
    flipped_i = F.eq(check, F.mul(u_neg, sqrt_m1))
    r = F.select(flipped | flipped_i, F.mul(r, sqrt_m1), r)
    return correct | flipped, _abs_dev(r)


def ristretto_decode_dev(
    b: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched ristretto255 decode (RFC 9496 §4.3.1).

    b: (32, N) int32 byte rows. Returns (point (4, NLIMBS, N) extended
    edwards coords, ok (N,) bool). Invalid encodings (non-canonical,
    negative, non-square, t negative, y = 0) yield ok = False with a
    bounded garbage point that flows safely through the curve math."""
    nonneg = (b[0] & 1) == 0
    canon = _lt_const_dev(b, _P8)  # value < p (bit 255 set fails too)
    s = _fe_from_bytes_dev(
        b & _TOPCLEAR
    )  # mask bit 255 to keep limb bounds; canon already rejects it
    one = jnp.broadcast_to(F.const_limbs(1), s.shape)
    ss = F.sqr(s)
    u1 = F.sub(one, ss)
    u2 = F.add(one, ss)
    u2_sqr = F.sqr(u2)
    d = jnp.broadcast_to(F.const_limbs(_D_INT), s.shape)
    v = F.sub(F.neg(F.mul(d, F.sqr(u1))), u2_sqr)
    was_square, invsqrt = _sqrt_ratio_m1_dev(one, F.mul(v, u2_sqr))
    den_x = F.mul(invsqrt, u2)
    den_y = F.mul(F.mul(invsqrt, den_x), v)
    x = _abs_dev(F.mul(F.add(s, s), den_x))
    y = F.mul(u1, den_y)
    t = F.mul(x, y)
    ok = (
        was_square
        & ~_is_negative_dev(t)
        & ~F.is_zero(y)
        & nonneg
        & canon
    )
    pt = jnp.stack([x, y, one, t], axis=-3)
    return pt, ok


def _ristretto_eq_dev(p3: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Ristretto equality (RFC 9496 §4.4): X1*Y2 == Y1*X2 or
    Y1*Y2 == X1*X2. Projective: the Z factors multiply both sides of
    each equation identically, so T-less (X, Y, Z) stacks suffice.
    p3: (..., >=2, NLIMBS, N) stack; q: same (extra coords ignored)."""
    X1, Y1 = p3[..., 0, :, :], p3[..., 1, :, :]
    X2, Y2 = q[..., 0, :, :], q[..., 1, :, :]
    lhs = jnp.stack([X1, Y1], axis=-3)
    rhs = jnp.stack([Y2, X2], axis=-3)
    cross = F.mul(lhs, rhs)  # X1*Y2, Y1*X2
    eq1 = F.eq(cross[..., 0, :, :], cross[..., 1, :, :])
    straight = F.mul(lhs, jnp.stack([X2, Y2], axis=-3))  # X1*X2, Y1*Y2
    eq2 = F.eq(straight[..., 0, :, :], straight[..., 1, :, :])
    return eq1 | eq2


def _verify_tile_sr(pk_b, sig_b, k_b, dual_fn=None) -> jnp.ndarray:
    """The full sr25519 device program: byte rows in, bitmap out.

    pk_b (32, N) ristretto pubkey bytes; sig_b (64, N) R || s with the
    schnorrkel v1 marker in bit 511; k_b (32, N) LE bytes of the
    merlin challenge already reduced mod L on host. Returns (N,) bool.
    `dual_fn` swaps in the segmented Pallas dual-mult (the same kernel
    the ed25519 hybrid uses — ops/ed25519_pallas.dual_mult_pallas);
    ristretto decode and the equality stay XLA."""
    pk = pk_b.astype(jnp.int32)
    sig = sig_b.astype(jnp.int32)
    kb = k_b.astype(jnp.int32)
    marker_ok = (sig[63] >> 7) == 1  # schnorrkel v1 marker bit
    s = sig[32:] & _TOPCLEAR
    s_ok = _s_lt_l_dev(s)
    A, okA = ristretto_decode_dev(pk)
    R, okR = ristretto_decode_dev(sig[:32])
    dS = _nibbles_dev(s)
    dk = _nibbles_dev(kb)
    if dual_fn is None:
        acc = dual_mult_sb_minus_ka(A, dS, dk)  # [s]B - [k]A, T-less
    else:
        acc = dual_fn(A, dS, dk)
    return _ristretto_eq_dev(acc, R) & okA & okR & s_ok & marker_ok


_JIT_VERIFY_SR = None
_JIT_VERIFY_SR_HYBRID = None


def _jit_verify_tile_sr():
    global _JIT_VERIFY_SR
    if _JIT_VERIFY_SR is None:
        _JIT_VERIFY_SR = jax.jit(_verify_tile_sr)
    return _JIT_VERIFY_SR


def _jit_verify_tile_sr_hybrid():
    """sr25519 program with the Pallas dual-mult segment (same gating
    as the ed25519 hybrid: TM_TPU_PALLAS=1, see
    Ed25519Verifier._pallas_wanted; falls back per-bucket in dispatch
    if Mosaic rejects the kernel)."""
    global _JIT_VERIFY_SR_HYBRID
    if _JIT_VERIFY_SR_HYBRID is None:
        import functools

        from .ed25519_pallas import dual_mult_pallas

        _JIT_VERIFY_SR_HYBRID = jax.jit(
            functools.partial(_verify_tile_sr, dual_fn=dual_mult_pallas)
        )
    return _JIT_VERIFY_SR_HYBRID


class Sr25519Verifier:
    """Compiled, bucketed sr25519 batch verifier (device XLA program).

    Mirrors ops.ed25519_kernel.Ed25519Verifier's dispatch()/gather()
    shape: host work is merlin challenges + byte joins; decode, scalar
    canonicality, and the curve math are one device program per bucket."""

    def __init__(self, bucket_sizes: Optional[Sequence[int]] = None) -> None:
        self.bucket_sizes = sorted(bucket_sizes or DEFAULT_BUCKET_SIZES)
        self._compiled: dict = {}
        # buckets whose hybrid (Pallas dual-mult) program has completed
        # on device at least once — first calls block, see dispatch()
        self._pallas_proven: set = set()

    def _bucket(self, n: int) -> int:
        from .ed25519_kernel import Ed25519Verifier, pallas_bucket

        b = bucket_for(n, self.bucket_sizes)
        if Ed25519Verifier._pallas_wanted():
            b = pallas_bucket(b)
        return b

    def _program(self, size: int):
        """The compiled program for a bucket — one shape-polymorphic
        jitted function by default; the per-size dict exists for
        overrides (ShardedSr25519Verifier's mesh-partitioned programs,
        tendermint_tpu.parallel.sharding; the per-bucket Pallas
        fallback in dispatch)."""
        fn = self._compiled.get(size)
        if fn is None:
            from .ed25519_kernel import Ed25519Verifier

            if Ed25519Verifier._pallas_wanted():
                fn = _jit_verify_tile_sr_hybrid()
            else:
                fn = _jit_verify_tile_sr()
            self._compiled[size] = fn
        return fn

    def verify(
        self,
        pubkeys: Sequence[bytes],
        msgs: Sequence[bytes],
        sigs: Sequence[bytes],
    ) -> np.ndarray:
        return self.gather(self.dispatch(pubkeys, msgs, sigs))

    def dispatch(
        self,
        pubkeys: Sequence[bytes],
        msgs: Sequence[bytes],
        sigs: Sequence[bytes],
    ):
        """Asynchronously launch verification; returns a handle for
        gather(). Malformed sizes are reported invalid per-index."""
        from ..crypto.sr25519 import challenge_batch

        n = len(pubkeys)
        if n == 0:
            return (None, 0, np.zeros(0, dtype=bool))
        size_ok = np.array(
            [
                len(pk) == 32 and len(sig) == 64
                for pk, sig in zip(pubkeys, sigs)
            ],
            dtype=bool,
        )
        if not size_ok.all():
            pubkeys = [
                pk if ok else b"\x00" * 32
                for pk, ok in zip(pubkeys, size_ok)
            ]
            sigs = [
                sig if ok else b"\x00" * 64
                for sig, ok in zip(sigs, size_ok)
            ]
        # host: the merlin Fiat-Shamir challenges, vectorized per
        # message-length group (crypto/sr25519.py challenge_batch —
        # one native keccakf_n permutation call per transcript step)
        ks = [
            k.to_bytes(32, "little")
            for k in challenge_batch(
                pubkeys, msgs, [sig[:32] for sig in sigs]
            )
        ]
        bucket = self._bucket(n)
        pad = bucket - n
        pk_b = _join_cols(pubkeys, 32, pad)
        sig_b = _join_cols(sigs, 64, pad)
        k_b = _join_cols(ks, 32, pad)
        prog = self._program(bucket)
        from .ed25519_kernel import run_with_pallas_fallback

        ok = run_with_pallas_fallback(
            prog,
            (jnp.asarray(pk_b), jnp.asarray(sig_b), jnp.asarray(k_b)),
            is_pallas=(
                _JIT_VERIFY_SR_HYBRID is not None
                and prog is _JIT_VERIFY_SR_HYBRID
            ),
            bucket=bucket,
            proven=self._pallas_proven,
            compiled=self._compiled,
            xla_factory=_jit_verify_tile_sr,
            label="sr25519",
        )
        return (ok, n, size_ok)

    def gather(self, handle) -> np.ndarray:
        ok, n, size_ok = handle
        if ok is None:
            return size_ok
        return np.asarray(ok)[:n] & size_ok


_DEFAULT: Optional[Sr25519Verifier] = None
_DEFAULT_LOCK = threading.Lock()


def default_verifier() -> Sr25519Verifier:
    """The shared module verifier (see ed25519_kernel.default_verifier)."""
    global _DEFAULT
    if _DEFAULT is None:
        # double-checked: the first calls race in from the asyncio loop
        # AND the breaker probe thread (tmrace), and a losing duplicate
        # construction is not just waste — each instance carries its
        # own compiled-program cache, so consensus traffic landing on a
        # discarded instance would recompile every bucket
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = Sr25519Verifier()
    return _DEFAULT


def batch_verify_host(pubkeys, msgs, sigs) -> np.ndarray:
    """Module-level convenience using the shared verifier instance."""
    return default_verifier().verify(pubkeys, msgs, sigs)
