"""Batched twisted-Edwards (ed25519) group ops on TPU.

Points are int32 arrays shaped (..., 4, NLIMBS, N) holding extended
homogeneous coordinates (X, Y, Z, T) with x = X/Z, y = Y/Z, xy = T/Z on
-x^2 + y^2 = 1 + d x^2 y^2, batch axis minor (see field25519 layout
note). The coordinate axis is deliberately part of the array: every
group operation becomes two *stacked* field multiplications over the
(..., 4) axis, so the VPU sees wide fused elementwise work instead of
four scalar-coded muls.

Formulas: add-2008-hwcd-3 and dbl-2008-hwcd (complete for a = -1, d
non-square, so identity/doubling/small-order inputs all flow through the
same code path — no data-dependent branching, which is what jit wants).

The second operand of addition is kept in "cached" form
(Y-X, Y+X, 2d*T, 2Z), turning each addition into exactly: one stacked
4-way mul (A, B, C, D), cheap carried adds/subs, one stacked 4-way mul
(X3, Y3, Z3, T3).

Oracle: tendermint_tpu.crypto.ed25519_math (pure-Python bigints).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..crypto import ed25519_math as em
from . import field25519 as F

__all__ = [
    "identity",
    "point_add_cached",
    "point_double",
    "cache_point",
    "negate",
    "negate_cached",
    "decompress",
    "is_identity",
    "pack_point",
    "niels_table_b",
]

D_INT = em.D
D2_INT = 2 * em.D % em.P
SQRT_M1_INT = em.SQRT_M1

_D2_LIMBS = F.to_limbs(D2_INT)
_ONE = F.to_limbs(1)


def identity(n: int) -> jnp.ndarray:
    """(0, 1, 1, 0) broadcast over the batch -> (4, NLIMBS, N)."""
    pt = np.zeros((4, F.NLIMBS, 1), dtype=np.int32)
    pt[1, :, 0] = _ONE
    pt[2, :, 0] = _ONE
    return jnp.broadcast_to(jnp.asarray(pt), (4, F.NLIMBS, n))


def pack_point(x: int, y: int) -> np.ndarray:
    """Host-side: affine ints -> extended coords limb array (4, NLIMBS)."""
    return np.stack(
        [
            F.to_limbs(x),
            F.to_limbs(y),
            F.to_limbs(1),
            F.to_limbs(x * y % em.P),
        ]
    )


def cache_point(p: jnp.ndarray) -> jnp.ndarray:
    """Extended -> cached (Y-X, Y+X, 2d*T, 2Z) for use as an addition rhs."""
    X = p[..., 0, :, :]
    Y = p[..., 1, :, :]
    Z = p[..., 2, :, :]
    T = p[..., 3, :, :]
    two_p = jnp.asarray(F._2P_LIMBS)
    pre = jnp.stack([Y - X + two_p, Y + X, T, Z + Z], axis=-3)
    pre = F.carry1(pre)
    consts = jnp.stack(
        [
            _ONE[:, None],
            _ONE[:, None],
            _D2_LIMBS[:, None],
            _ONE[:, None],
        ]
    )  # (4, NLIMBS, 1)
    return F.mul(pre, jnp.broadcast_to(jnp.asarray(consts), pre.shape))


def negate_cached(qc: jnp.ndarray) -> jnp.ndarray:
    """Negate a cached point: swap (Y-X, Y+X) and negate the 2dT slot.
    Cheap (no muls) — lets signed-digit windows halve table sizes."""
    ymx = qc[..., 0, :, :]
    ypx = qc[..., 1, :, :]
    t2d = qc[..., 2, :, :]
    z2 = qc[..., 3, :, :]
    return jnp.stack([ypx, ymx, F.neg(t2d), z2], axis=-3)


def point_add_cached(
    p: jnp.ndarray, qc: jnp.ndarray, with_t: bool = True
) -> jnp.ndarray:
    """p (extended) + q (cached) -> extended.

    `with_t=False` drops the T3 output mul (the caller's next op is a
    doubling or a projective compare, neither of which reads T) — the
    output stacks (X3, Y3, Z3) only."""
    X = p[..., 0, :, :]
    Y = p[..., 1, :, :]
    Z = p[..., 2, :, :]
    T = p[..., 3, :, :]
    two_p = jnp.asarray(F._2P_LIMBS)
    lhs = F.carry1(jnp.stack([Y - X + two_p, Y + X, T, Z], axis=-3))
    prods = F.mul(lhs, qc)  # A, B, C, D' (D' = Z1 * 2Z2)
    A = prods[..., 0, :, :]
    B = prods[..., 1, :, :]
    C = prods[..., 2, :, :]
    Dv = prods[..., 3, :, :]
    mids = F.carry1(
        jnp.stack(
            [B - A + two_p, Dv - C + two_p, Dv + C, B + A], axis=-3
        )
    )  # E, F, G, H
    E = mids[..., 0, :, :]
    Fv = mids[..., 1, :, :]
    G = mids[..., 2, :, :]
    H = mids[..., 3, :, :]
    if with_t:
        out_l = jnp.stack([E, G, Fv, E], axis=-3)
        out_r = jnp.stack([Fv, H, G, H], axis=-3)
    else:
        out_l = jnp.stack([E, G, Fv], axis=-3)
        out_r = jnp.stack([Fv, H, G], axis=-3)
    return F.mul(out_l, out_r)  # X3, Y3, Z3(, T3)


def point_double(p: jnp.ndarray, with_t: bool = True) -> jnp.ndarray:
    """Double an extended point. Reads only (X, Y, Z), so a 3-stacked
    T-less input from a previous `with_t=False` op is accepted;
    `with_t=False` likewise drops the T3 output mul (25% of the
    doubling's second stacked multiply) when the next op is another
    doubling or a projective compare."""
    X = p[..., 0, :, :]
    Y = p[..., 1, :, :]
    Z = p[..., 2, :, :]
    sq_in = F.carry1(jnp.stack([X, Y, Z, X + Y], axis=-3))
    sq = F.sqr(sq_in)  # A, B, Zs, S
    A = sq[..., 0, :, :]
    B = sq[..., 1, :, :]
    Zs = sq[..., 2, :, :]
    S = sq[..., 3, :, :]
    two_p = jnp.asarray(F._2P_LIMBS)
    # E = A+B-S, F = 2Zs + (A-B), G = A-B, H = A+B
    mids = F.carry1(
        jnp.stack(
            [
                A + B - S + two_p,
                Zs + Zs + A - B + two_p,
                A - B + two_p,
                A + B,
            ],
            axis=-3,
        )
    )
    E = mids[..., 0, :, :]
    Fv = mids[..., 1, :, :]
    G = mids[..., 2, :, :]
    H = mids[..., 3, :, :]
    if with_t:
        out_l = jnp.stack([E, G, Fv, E], axis=-3)
        out_r = jnp.stack([Fv, H, G, H], axis=-3)
    else:
        out_l = jnp.stack([E, G, Fv], axis=-3)
        out_r = jnp.stack([Fv, H, G], axis=-3)
    return F.mul(out_l, out_r)


def negate(p: jnp.ndarray) -> jnp.ndarray:
    """(X, Y, Z, T) -> (-X, Y, Z, -T)."""
    X = p[..., 0, :, :]
    Y = p[..., 1, :, :]
    Z = p[..., 2, :, :]
    T = p[..., 3, :, :]
    two_p = jnp.asarray(F._2P_LIMBS)
    return F.carry(jnp.stack([two_p - X, Y, Z, two_p - T], axis=-3))


def is_identity(p: jnp.ndarray) -> jnp.ndarray:
    """Projective identity test: X ≡ 0 and Y ≡ Z (mod p)."""
    X = p[..., 0, :, :]
    Y = p[..., 1, :, :]
    Z = p[..., 2, :, :]
    return F.is_zero(X) & F.eq(Y, Z)


# -- decompression (RFC 8032 §5.1.3 with ZIP-215 non-canonical-y
#    acceptance handled host-side by reducing y mod p) --


def decompress(y: jnp.ndarray, sign: jnp.ndarray):
    """Batched point decompression.

    y: (NLIMBS, N) field element (already reduced mod p on host),
    sign: (N,) int32 0/1 — the x-parity bit from the wire encoding.
    Returns (point (4, NLIMBS, N), ok (N,) bool). Mirrors the
    reference's curve25519-voi decompression semantics; the square root
    is computed as u*v^3 * (u*v^7)^((p-5)/8) with the sqrt(-1)
    correction, the exponentiation via the 254-squaring addition chain
    (field25519.pow_p58).
    """
    one = jnp.broadcast_to(F.const_limbs(1), y.shape)
    y2 = F.sqr(y)
    u = F.sub(y2, one)
    v = F.add(
        F.mul(y2, jnp.broadcast_to(F.const_limbs(D_INT), y.shape)), one
    )
    v2 = F.sqr(v)
    v3 = F.mul(v2, v)
    v7 = F.mul(F.sqr(v3), v)
    t = F.pow_p58(F.mul(u, v7))
    x = F.mul(F.mul(u, v3), t)
    vx2 = F.mul(v, F.sqr(x))
    root_ok = F.eq(vx2, u)
    neg_root_ok = F.eq(vx2, F.neg(u))
    x_alt = F.mul(
        x, jnp.broadcast_to(F.const_limbs(SQRT_M1_INT), x.shape)
    )
    x = F.select(neg_root_ok, x_alt, x)
    ok = root_ok | neg_root_ok
    # parity fix: need canonical x for bit 0
    x_can = F.canonical(x)
    parity = x_can[..., 0, :] & 1
    x_flipped = F.neg(x)
    x = F.select(parity != sign, x_flipped, x)
    # x == 0 with sign == 1 is invalid ("-0")
    x_zero = F.is_zero(x)
    ok = ok & ~(x_zero & (sign == 1))
    xy = F.mul(x, y)
    pt = jnp.stack(
        [x, y, jnp.broadcast_to(F.const_limbs(1), y.shape), xy], axis=-3
    )
    return pt, ok


# -- host-side table generation (niels form, Z = 1) --


def niels_table_b(count: int = 9) -> np.ndarray:
    """(count, 4, NLIMBS, 1): cached-form entries for j*B, j = 0..count-1,
    Z = 1. Default 9 entries — the signed-digit half-table (negatives
    come free from the cached-negation identity). Layout matches
    cache_point output: (y-x, y+x, 2d*xy, 2); trailing 1-axis broadcasts
    over the batch."""
    entries = []
    pt = em.IDENTITY
    for _j in range(count):
        X, Y, Z, _T = pt
        zinv = pow(Z, em.P - 2, em.P)
        x, y = X * zinv % em.P, Y * zinv % em.P
        entries.append(
            np.stack(
                [
                    F.to_limbs((y - x) % em.P),
                    F.to_limbs((y + x) % em.P),
                    F.to_limbs(D2_INT * x * y % em.P),
                    F.to_limbs(2),
                ]
            )
        )
        pt = em.point_add(pt, em.B_POINT)
    return np.stack(entries)[..., None]
