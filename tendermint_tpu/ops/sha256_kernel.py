"""Batched SHA-256 as XLA programs (uint32 lanes over the batch axis).

The device hashing primitive behind merkle tree/proof offload
(reference consumers: crypto/merkle/{tree,proof}.go via crypto/tmhash).
Fixed message lengths compile one program per length: padding is
computed at trace time, so the whole schedule + 64 rounds is a single
fused elementwise pipeline the VPU vectorizes across the batch.

Layout matches the ed25519 kernel family: batch axis minor — bytes are
(L, N) uint8 columns, words (16, N) uint32, states (8, N) uint32.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = ["sha256_fixed", "inner_hash_batch", "leaf_hash_batch"]

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
        0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
        0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
        0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
        0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
        0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
        0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
        0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
        0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
        0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
        0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)


def _rotr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _compress(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """One compression: state (8, N), block (16, N) uint32 -> (8, N).

    Backend-conditional at trace time, like sha512_kernel._compress:

    - CPU: lax.scan loops. This jaxlib's CPU backend degenerates on the
      fully-unrolled ~1300-op uint32 rotate/add chain (60s+ compiles
      and runs that never return), while the scan form compiles a
      ~30-op body once.
    - TPU: fully unrolled. The scan serializes 112 tiny device loops
      XLA cannot fuse across (the same shape that cost the sha512 path
      ~24% of ed25519 verify throughput); unrolled, the whole schedule
      + 64 rounds fuse into a few kernels."""
    import jax

    if jax.default_backend() == "tpu":
        return _compress_unrolled(state, block)
    from jax import lax

    def sched_body(last16, _):
        w15 = last16[1]
        w2 = last16[14]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> np.uint32(3))
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> np.uint32(10))
        wt = last16[0] + s0 + last16[9] + s1
        return jnp.concatenate([last16[1:], wt[None]], axis=0), wt

    _, w_ext = lax.scan(sched_body, block, None, length=48)
    w_all = jnp.concatenate([block, w_ext], axis=0)  # (64, N)

    def round_body(st, xs):
        wt, kt = xs
        a, b, c, d, e, f, g, h = st
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kt + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        return jnp.stack(
            [t1 + s0 + maj, a, b, c, d + t1, e, f, g], axis=0
        ), None

    out, _ = lax.scan(
        round_body, state, (w_all, jnp.asarray(_K))
    )
    return state + out


def _compress_unrolled(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """Unrolled compression (see _compress): TPU-only trace-time form."""
    w = [block[i] for i in range(16)]
    for t in range(16, 64):
        w15 = w[t - 15]
        w2 = w[t - 2]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> np.uint32(3))
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> np.uint32(10))
        w.append(w[t - 16] + s0 + w[t - 7] + s1)

    a, b, c, d, e, f, g, h = (state[i] for i in range(8))
    for t in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + np.uint32(_K[t]) + w[t]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + s0 + maj
    return state + jnp.stack([a, b, c, d, e, f, g, h], axis=0)


def sha256_fixed(data: jnp.ndarray) -> jnp.ndarray:
    """SHA-256 of N equal-length messages: (L, N) uint8 -> (32, N).

    L is static, so the merkle-damgard padding (0x80, zeros, 64-bit
    bit length) is laid out at trace time."""
    length, n = data.shape
    bitlen = length * 8
    nblocks = (length + 9 + 63) // 64
    padded_len = nblocks * 64
    pad_rows = []
    pad_rows.append(
        jnp.full((1, n), 0x80, dtype=jnp.uint8)
    )
    zeros = padded_len - length - 1 - 8
    if zeros:
        pad_rows.append(jnp.zeros((zeros, n), dtype=jnp.uint8))
    len_bytes = np.array(
        [(bitlen >> (8 * (7 - i))) & 0xFF for i in range(8)],
        dtype=np.uint8,
    )
    pad_rows.append(
        jnp.broadcast_to(
            jnp.asarray(len_bytes)[:, None], (8, n)
        )
    )
    full = jnp.concatenate([data.astype(jnp.uint8)] + pad_rows, axis=0)
    full = full.astype(jnp.uint32)
    # (nblocks, 16, N) big-endian words
    quads = full.reshape(nblocks, 16, 4, n)
    words = (
        (quads[:, :, 0] << np.uint32(24))
        | (quads[:, :, 1] << np.uint32(16))
        | (quads[:, :, 2] << np.uint32(8))
        | quads[:, :, 3]
    )
    state = jnp.broadcast_to(
        jnp.asarray(_H0)[:, None], (8, n)
    ).astype(jnp.uint32)
    for b in range(nblocks):
        state = _compress(state, words[b])
    # big-endian byte unpack: (8, N) words -> (32, N) bytes
    shifts = np.array([24, 16, 8, 0], dtype=np.uint32)
    out = (state[:, None, :] >> jnp.asarray(shifts)[None, :, None]) & (
        np.uint32(0xFF)
    )
    return out.reshape(32, n).astype(jnp.uint8)


def inner_hash_batch(left: jnp.ndarray, right: jnp.ndarray) -> jnp.ndarray:
    """RFC 6962 inner node: sha256(0x01 || left || right) for N pairs.
    left/right (32, N) uint8 -> (32, N) (reference:
    crypto/merkle/hash.go:34)."""
    n = left.shape[1]
    prefix = jnp.ones((1, n), dtype=jnp.uint8)
    return sha256_fixed(jnp.concatenate([prefix, left, right], axis=0))


def leaf_hash_batch(leaves: jnp.ndarray) -> jnp.ndarray:
    """RFC 6962 leaf node for N equal-length leaves: sha256(0x00 || l)
    (reference: crypto/merkle/hash.go:21)."""
    n = leaves.shape[1]
    prefix = jnp.zeros((1, n), dtype=jnp.uint8)
    return sha256_fixed(jnp.concatenate([prefix, leaves], axis=0))
