"""Batched ed25519 verification as a single XLA program.

The device program takes a whole batch of (pubkey, R, S-digits, k-digits)
and returns a validity bitmap — this is the TPU replacement for the
reference's curve25519-voi batch verifier behind crypto.BatchVerifier
(reference: crypto/ed25519/ed25519.go:202-237, crypto/crypto.go:53-61).

Verification equation (ZIP-215, cofactored — matching
crypto/ed25519/ed25519.go:27-29 and the host oracle in
crypto/ed25519_math.py):

    [8]([S]B - [k]A - R) == identity,  k = SHA512(R || A || M) mod L

Device-side strategy (one lax.scan over 64 radix-16 windows, fixed trip
count, no data-dependent control flow):

    acc <- 16*acc + dk_w * (-A) + dS_w * B

i.e. Horner evaluation for the variable-base term using a per-signature
16-entry cached table of -A built on device, while the fixed-base term
reuses a constant 16-entry niels table of B at every window — scaling by
16^w happens for free inside the shared Horner doublings. Then add -R,
triple-double (x8 cofactor), and test the projective identity.

Scalar prep (SHA-512 of the messages, reduction mod L, nibble
decomposition) happens on host: messages are variable-length and the hash
is cheap relative to the curve math; moving SHA-512 on-device is the
ops/sha512 follow-up.

Shapes are bucketed (pad to the next configured bucket) so XLA compiles a
handful of programs once and reuses them for every Commit size.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..crypto import ed25519_math as em
from . import edwards as E
from . import field25519 as F

__all__ = ["Ed25519Verifier", "batch_verify_host"]

_TB0 = None  # lazy (16, 4, NLIMBS) fixed-base niels table (host numpy;
# converted per use so jit tracing never captures a cached tracer)


def _tb0():
    global _TB0
    if _TB0 is None:
        _TB0 = E.niels_table_b()
    return jnp.asarray(_TB0)


def _build_neg_a_table(A: jnp.ndarray) -> jnp.ndarray:
    """(N, 4, L) extended -A -> (N, 16, 4, L) cached table of j*(-A)."""
    negA = E.negate(A)
    cached_negA = E.cache_point(negA)
    entries = [E.identity(negA.shape[:-2]), negA]
    for j in range(2, 16):
        if j % 2 == 0:
            entries.append(E.point_double(entries[j // 2]))
        else:
            entries.append(E.point_add_cached(entries[j - 1], cached_negA))
    cached = [E.cache_point(e) for e in entries]
    return jnp.stack(cached, axis=1)  # (N, 16, 4, L)


def _scalar_mult_check(
    yA, signA, yR, signR, dS, dk
) -> jnp.ndarray:
    """Core device program. All args batched on dim 0.

    yA/yR: (N, L) field elements; signA/signR: (N,) int32;
    dS/dk: (N, 64) int32 radix-16 digits, little-endian.
    Returns ok: (N,) bool.
    """
    A, okA = E.decompress(yA, signA)
    R, okR = E.decompress(yR, signR)
    TA = _build_neg_a_table(A)  # (N, 16, 4, L)

    tb0 = _tb0()  # (16, 4, L)
    # scan from the most significant window down
    dS_steps = jnp.flip(dS.T, axis=0)  # (64, N)
    dk_steps = jnp.flip(dk.T, axis=0)

    acc0 = E.identity(yA.shape[:-1])

    def body(acc, xs):
        ds_w, dk_w = xs
        acc = lax.fori_loop(0, 4, lambda _i, a: E.point_double(a), acc)
        ta = jnp.take_along_axis(
            TA, dk_w[:, None, None, None], axis=1
        ).squeeze(1)
        acc = E.point_add_cached(acc, ta)
        tb = jnp.take(tb0, ds_w, axis=0)  # (N, 4, L)
        acc = E.point_add_cached(acc, tb)
        return acc, None

    acc, _ = lax.scan(body, acc0, (dS_steps, dk_steps))
    acc = E.point_add_cached(acc, E.cache_point(E.negate(R)))
    for _ in range(3):  # cofactor 8
        acc = E.point_double(acc)
    return E.is_identity(acc) & okA & okR


# -- host packing --


def _fe_from_le32(data: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 LE-encoded y (bit 255 already cleared) -> (N, L)
    int32 limbs, reduced mod p. Vectorized bit repacking."""
    n = data.shape[0]
    bits = np.unpackbits(data, axis=1, bitorder="little")  # (N, 256)
    out = np.zeros((n, F.NLIMBS), dtype=np.int64)
    for i in range(F.NLIMBS):
        lo = F.RADIX * i
        hi = min(lo + F.RADIX, 256)
        w = 1 << np.arange(hi - lo, dtype=np.int64)
        out[:, i] = bits[:, lo:hi] @ w
    # values may be >= p (ZIP-215 accepts); fold bits >= 255 via mod p:
    # bit 255 was cleared by the caller so out < 2^255 < 2p; conditional
    # subtract p once.
    val_ge_p = _ge_p(out)
    out = np.where(val_ge_p[:, None], _sub_p(out), out)
    return out.astype(np.int32)


_P_LIMBS_NP = np.array(
    [(em.P >> (F.RADIX * i)) & (F.BASE - 1) for i in range(F.NLIMBS)],
    dtype=np.int64,
)


def _ge_p(limbs: np.ndarray) -> np.ndarray:
    ge = np.ones(limbs.shape[0], dtype=bool)
    decided = np.zeros(limbs.shape[0], dtype=bool)
    for i in range(F.NLIMBS - 1, -1, -1):
        gt = limbs[:, i] > _P_LIMBS_NP[i]
        lt = limbs[:, i] < _P_LIMBS_NP[i]
        ge = np.where(~decided & gt, True, ge)
        ge = np.where(~decided & lt, False, ge)
        decided |= gt | lt
    return ge


def _sub_p(limbs: np.ndarray) -> np.ndarray:
    out = limbs - _P_LIMBS_NP[None, :]
    for i in range(F.NLIMBS - 1):
        borrow = out[:, i] < 0
        out[:, i] += borrow * F.BASE
        out[:, i + 1] -= borrow
    return out


def _nibbles_le(data: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 -> (N, 64) int32 radix-16 digits, little-endian."""
    lo = (data & 0x0F).astype(np.int32)
    hi = (data >> 4).astype(np.int32)
    return np.stack([lo, hi], axis=2).reshape(data.shape[0], 64)


class Ed25519Verifier:
    """Compiled, bucketed batch verifier.

    One instance caches jitted programs per bucket size. Thread-compatible
    for the asyncio runtime (verification calls are synchronous device
    invocations)."""

    def __init__(self, bucket_sizes: Optional[Sequence[int]] = None) -> None:
        self.bucket_sizes = sorted(bucket_sizes or [8, 32, 128, 512, 2048, 8192, 16384])
        self._compiled = {}

    def _bucket(self, n: int) -> int:
        for b in self.bucket_sizes:
            if n <= b:
                return b
        return n  # oversized: compile exact (rare)

    def _program(self, size: int):
        fn = self._compiled.get(size)
        if fn is None:
            fn = jax.jit(_scalar_mult_check)
            self._compiled[size] = fn
        return fn

    def verify(
        self,
        pubkeys: Sequence[bytes],
        msgs: Sequence[bytes],
        sigs: Sequence[bytes],
    ) -> np.ndarray:
        """Returns a bool bitmap, one per triple. Malformed inputs are
        reported invalid rather than raising (the BatchVerifier.add layer
        enforces sizes upstream)."""
        n = len(pubkeys)
        if n == 0:
            return np.zeros(0, dtype=bool)
        size_ok = np.array(
            [
                len(pk) == 32 and len(sig) == 64
                for pk, sig in zip(pubkeys, sigs)
            ],
            dtype=bool,
        )
        # host scalar prep
        pk_arr = np.zeros((n, 32), dtype=np.uint8)
        r_arr = np.zeros((n, 32), dtype=np.uint8)
        s_ok = np.zeros(n, dtype=bool)
        dS = np.zeros((n, 32), dtype=np.uint8)
        dk = np.zeros((n, 32), dtype=np.uint8)
        for i, (pk, msg, sig) in enumerate(zip(pubkeys, msgs, sigs)):
            if not size_ok[i]:
                continue
            pk_arr[i] = np.frombuffer(pk, dtype=np.uint8)
            r_arr[i] = np.frombuffer(sig[:32], dtype=np.uint8)
            s = int.from_bytes(sig[32:], "little")
            if s >= em.L:
                continue  # ZIP-215 rule 2: S must be canonical
            s_ok[i] = True
            dS[i] = np.frombuffer(sig[32:], dtype=np.uint8)
            k = (
                int.from_bytes(
                    hashlib.sha512(sig[:32] + pk + msg).digest(), "little"
                )
                % em.L
            )
            dk[i] = np.frombuffer(k.to_bytes(32, "little"), dtype=np.uint8)

        signA = (pk_arr[:, 31] >> 7).astype(np.int32)
        signR = (r_arr[:, 31] >> 7).astype(np.int32)
        pk_arr[:, 31] &= 0x7F
        r_arr[:, 31] &= 0x7F
        yA = _fe_from_le32(pk_arr)
        yR = _fe_from_le32(r_arr)

        bucket = self._bucket(n)
        pad = bucket - n
        if pad:
            yA = np.pad(yA, ((0, pad), (0, 0)))
            yR = np.pad(yR, ((0, pad), (0, 0)))
            signA = np.pad(signA, (0, pad))
            signR = np.pad(signR, (0, pad))
            dS = np.pad(dS, ((0, pad), (0, 0)))
            dk = np.pad(dk, ((0, pad), (0, 0)))

        ok = self._program(bucket)(
            jnp.asarray(yA),
            jnp.asarray(signA),
            jnp.asarray(yR),
            jnp.asarray(signR),
            jnp.asarray(_nibbles_le(dS)),
            jnp.asarray(_nibbles_le(dk)),
        )
        ok = np.asarray(ok)[:n]
        return ok & s_ok & size_ok


_DEFAULT: Optional[Ed25519Verifier] = None


def batch_verify_host(pubkeys, msgs, sigs) -> np.ndarray:
    """Module-level convenience using a shared verifier instance."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = Ed25519Verifier()
    return _DEFAULT.verify(pubkeys, msgs, sigs)
