"""Batched ed25519 verification as a single XLA program.

The device program takes a whole batch of (pubkey, R, S-digits, k-digits)
and returns a validity bitmap — this is the TPU replacement for the
reference's curve25519-voi batch verifier behind crypto.BatchVerifier
(reference: crypto/ed25519/ed25519.go:202-237, crypto/crypto.go:53-61).

Verification equation (ZIP-215, cofactored — matching
crypto/ed25519/ed25519.go:27-29 and the host oracle in
crypto/ed25519_math.py):

    [8]([S]B - [k]A - R) == identity,  k = SHA512(R || A || M) mod L

Device-side strategy (one lax.scan over 64 radix-16 windows, fixed trip
count, no data-dependent control flow):

    acc <- 16*acc + dk_w * (-A) + dS_w * B

i.e. Horner evaluation for the variable-base term using a per-signature
9-entry cached table of -A built on device (digits recoded to signed
[-8, 7]; negative entries are the free cached negation), while the
fixed-base term reuses a constant 9-entry niels table of B at every
window — scaling by 16^w happens for free inside the shared Horner
doublings. Then add -R, triple-double (x8 cofactor), and test the
projective identity.

Layout: all device arrays are batch-minor ((NLIMBS, N) field elements,
(4, NLIMBS, N) points — see field25519's layout note; batch-major
stranded ~85% of the VPU lanes). Table indexing is a 9-way one-hot
select (compare + masked accumulate), not a gather: per-lane dynamic
gathers serialize on TPU, while the one-hot form is pure vector ALU.

Scalar prep (SHA-512 of R||A||M, reduction mod L, nibble decomposition)
also runs on device: digests via ops/sha512_kernel.py per
message-length group (sign-bytes in a Commit share one length, so the
common case is a single fused group with no host round-trip), the rest
inside the verify program. Host work is byte joins only.

Shapes are bucketed (pad to the next configured bucket) so XLA compiles a
handful of programs once and reuses them for every Commit size.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..crypto import ed25519_math as em
from . import edwards as E
from . import field25519 as F

__all__ = [
    "Ed25519Verifier",
    "batch_verify_host",
    "dual_mult_sb_minus_ka",
    "DEFAULT_BUCKET_SIZES",
    "bucket_for",
]

# shared by the ed25519 and sr25519 verifiers (ops/sr25519_kernel.py)
# and the [tpu] config section: tune once, everything follows
from ..config import DEFAULT_BUCKET_SIZES  # noqa: E402


def bucket_for(n: int, sizes: Sequence[int]) -> int:
    """Smallest configured bucket >= n, or n itself when oversized."""
    for b in sizes:
        if n <= b:
            return b
    return n

_TB0 = None  # lazy (9, 4, NLIMBS, 1) fixed-base niels table (host numpy;
# converted per use so jit tracing never captures a cached tracer)


def _tb0():
    global _TB0
    if _TB0 is None:
        _TB0 = E.niels_table_b()
    return jnp.asarray(_TB0)


def _build_neg_a_table(A: jnp.ndarray) -> jnp.ndarray:
    """(4, L, N) extended -A -> (9, 4, L, N) cached table of j*(-A),
    j = 0..8 — the signed-digit half-table (digits recoded to [-8, 7],
    negative entries produced by the free cached negation in
    _select_signed). 4 doublings + 3 additions vs the 14 point ops of
    the old full [0, 15] table."""
    negA = E.negate(A)
    cached_negA = E.cache_point(negA)
    e = {0: E.identity(A.shape[-1]), 1: negA}
    e[2] = E.point_double(e[1])
    e[3] = E.point_add_cached(e[2], cached_negA)
    e[4] = E.point_double(e[2])
    e[5] = E.point_add_cached(e[4], cached_negA)
    e[6] = E.point_double(e[3])
    e[7] = E.point_add_cached(e[6], cached_negA)
    e[8] = E.point_double(e[4])
    cached = [E.cache_point(e[j]) for j in range(9)]
    return jnp.stack(cached, axis=0)  # (9, 4, L, N)


def _onehot_select(table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """table (K, 4, L, {N|1}), idx (N,) -> (4, L, N) via K-way masked
    accumulate (no per-lane gather). broadcasted_iota (not arange):
    Mosaic rejects rank-1 iota."""
    k = table.shape[0]
    js = lax.broadcasted_iota(idx.dtype, (k, idx.shape[0]), 0)
    mask = (idx[None, :] == js).astype(table.dtype)  # (K, N)
    return jnp.sum(table * mask[:, None, None, :], axis=0)


def _recode_signed(d: jnp.ndarray) -> jnp.ndarray:
    """(64, N) radix-16 digits in [0, 15], LE -> same value as signed
    digits in [-8, 7]: e_i = t_i - 16*(t_i >= 8), t_i = d_i + c_i,
    c_{i+1} = (t_i >= 8). The carry recurrence is generate/propagate
    (g = d >= 8, p = d == 7), solved in log2(64) Kogge-Stone steps along
    the digit axis — no sequential 64-chain in the graph.

    A carry out of digit 63 is dropped; that loses 2^256, which only
    happens for S >= 2^256 - 8*16^63 — such S fail the S < L
    canonicality check and are already reported invalid, so the curve
    result is irrelevant (same contract as the rest of the math on
    malformed inputs).

    The generate/propagate lattice is kept in int32 0/1, not bool:
    Mosaic cannot concatenate/shift i1 vregs (it bitcasts them to i32,
    which fails with 'Invalid vector register cast' — found via local
    AOT compile against a v5e topology)."""
    g = (d >= 8).astype(d.dtype)
    p = (d == 7).astype(d.dtype)
    shift = 1
    while shift < d.shape[0]:
        zeros = jnp.zeros_like(g[:shift])
        g = g | (p & jnp.concatenate([zeros, g[:-shift]], axis=0))
        p = p & jnp.concatenate([zeros, p[:-shift]], axis=0)
        shift *= 2
    c = jnp.concatenate([jnp.zeros_like(g[:1]), g[:-1]], axis=0)
    t = d + c
    return t - 16 * (t >= 8).astype(d.dtype)


def _select_signed(
    table9: jnp.ndarray, e: jnp.ndarray, mxu: bool = False
) -> jnp.ndarray:
    """table9 (9, 4, L, {N|1}) cached-form entries for j*P, j = 0..8;
    e (N,) signed digit in [-8, 8] -> (4, L, N) cached |e|*P, negated
    when e < 0 (cached negation = swap (Y-X, Y+X), negate 2dT — no
    multiplies, edwards.negate_cached's identity applied post-select).

    mxu=True (lane-shared tables only, i.e. the fixed-base B table):
    the select is a real (9, 4L) x (9, N) contraction, so ride the MXU
    in f32 instead of spending VPU MACs — exact because limbs < 2^24
    and the mask is one-hot (Precision.HIGHEST carries the full f32
    mantissa through the bf16 passes)."""
    idx = jnp.abs(e)
    if mxu and table9.shape[-1] == 1:
        k = table9.shape[0]
        js = lax.broadcasted_iota(idx.dtype, (k, idx.shape[0]), 0)
        mask = (idx[None, :] == js).astype(jnp.float32)  # (9, N)
        tbl = table9[..., 0].reshape(k, -1).astype(jnp.float32)  # (9, 4L)
        sel = jnp.einsum(
            "kc,kn->cn", tbl, mask, precision=lax.Precision.HIGHEST
        )
        sel = sel.reshape(
            table9.shape[1], table9.shape[2], idx.shape[0]
        ).astype(jnp.int32)
    else:
        sel = _onehot_select(table9, idx)
    sgn = (e < 0)[None, None, :]
    return jnp.where(sgn, E.negate_cached(sel), sel)


def dual_mult_sb_minus_ka(
    A: jnp.ndarray,
    dS: jnp.ndarray,
    dk: jnp.ndarray,
    mosaic: bool = False,
    mxu: Optional[bool] = None,
) -> jnp.ndarray:
    """[S]B - [k]A as a T-less (3, NLIMBS, N) projective stack.

    A: (4, L, N) extended point; dS/dk: (64, N) int32 radix-16 digits,
    little-endian, in [0, 15] (recoded to signed [-8, 7] on device —
    half-size tables, negatives via the free cached negation). 64
    windows, most significant first, Horner
    `acc <- 16*acc + dk_w*(-A) + dS_w*B` with a per-signature 9-entry
    cached table of -A built on device and a constant niels table of B.
    Shared by the ed25519 program (cofactored compare follows) and the
    sr25519/ristretto program (ristretto equality follows,
    ops/sr25519_kernel.py).

    Two window-walk forms, same math:
    - mosaic=False (XLA default): lax.scan over pre-flipped digit rows.
    - mosaic=True (the Pallas tile): lax.fori_loop; the window's digit
      row is picked by a one-hot masked sum because Mosaic lowers
      neither scan's xs dynamic_slice nor jnp.flip's rev. 64 extra
      MACs/window are noise next to the point ops.

    `mxu` overrides the fixed-base select engine (default: MXU einsum
    on the XLA path, VPU one-hot in the mosaic/Pallas path) — the
    override exists for device A/B attribution (scripts/probe_r3.py)."""
    if mxu is None:
        mxu = not mosaic
    TA = _build_neg_a_table(A)  # (9, 4, L, N)

    tb0 = _tb0()  # (9, 4, L, 1)

    dS = _recode_signed(dS)
    dk = _recode_signed(dk)

    # The carry is the T-less 3-stack (X, Y, Z): doublings never
    # read T and the final comparison is projective, so only the ops
    # feeding an addition materialize T (point ops drop the T output
    # mul otherwise — 25% of each output multiply).
    acc0 = E.identity(A.shape[-1])[..., :3, :, :]

    def step(acc, ds_w, dk_w):
        acc = lax.fori_loop(
            0, 3, lambda _i, a: E.point_double(a, with_t=False), acc
        )
        acc = E.point_double(acc)  # T feeds the addition below
        acc = E.point_add_cached(acc, _select_signed(TA, dk_w))
        acc = E.point_add_cached(
            acc, _select_signed(tb0, ds_w, mxu=mxu), with_t=False
        )
        return acc

    if mosaic:
        rows = lax.broadcasted_iota(dS.dtype, dS.shape, 0)  # (64, N)

        def body(w, acc):
            sel = (rows == 63 - w).astype(dS.dtype)  # MSB-first walk
            return step(
                acc, jnp.sum(dS * sel, axis=0), jnp.sum(dk * sel, axis=0)
            )

        return lax.fori_loop(0, 64, body, acc0)

    def scan_body(acc, xs):
        ds_w, dk_w = xs
        return step(acc, ds_w, dk_w), None

    acc, _ = lax.scan(
        scan_body, acc0, (jnp.flip(dS, axis=0), jnp.flip(dk, axis=0))
    )
    return acc


def _scalar_mult_check(
    yA, signA, yR, signR, dS, dk, mosaic=False, dual_fn=None
) -> jnp.ndarray:
    """Core device program. Batch axis minor.

    yA/yR: (L, N) field elements; signA/signR: (N,) int32;
    dS/dk: (64, N) int32 radix-16 digits, little-endian.
    Returns ok: (N,) bool. `dual_fn` overrides the dual scalar-mult
    (the segmented Pallas kernel plugs in here; everything around it —
    decompression, cofactor clearing, the projective compare — stays
    XLA, which fuses those fine)."""
    A, okA = E.decompress(yA, signA)
    R, okR = E.decompress(yR, signR)
    if dual_fn is None:
        acc = dual_mult_sb_minus_ka(A, dS, dk, mosaic=mosaic)
    else:
        acc = dual_fn(A, dS, dk)
    # ZIP-215 cofactored equation, rearranged so nothing needs T:
    # [8]([S]B - [k]A) == [8]R  <=>  [8]([S]B - [k]A - R) == identity.
    for _ in range(3):  # cofactor 8, both sides
        acc = E.point_double(acc, with_t=False)
        R = E.point_double(R, with_t=False)
    # projective equality: X1 Z2 == X2 Z1 and Y1 Z2 == Y2 Z1
    lhs = jnp.stack([acc[..., 0, :, :], acc[..., 1, :, :]], axis=-3)
    rhs = jnp.stack([R[..., 0, :, :], R[..., 1, :, :]], axis=-3)
    z_acc = jnp.broadcast_to(acc[..., 2:3, :, :], lhs.shape)
    z_r = jnp.broadcast_to(R[..., 2:3, :, :], rhs.shape)
    cross_l = F.mul(lhs, z_r)
    cross_r = F.mul(rhs, z_acc)
    same = jnp.all(F.eq(cross_l, cross_r), axis=-2)
    return same & okA & okR


# -- device-side scalar prep --
#
# Everything between the SHA-512 digests and the curve math runs inside
# the same jitted program: byte -> limb unpacking, the reduction of the
# 512-bit digest mod L, S < L canonicality, and nibble decomposition.
# Host numpy versions of these were memory-bandwidth-bound (~6 us/sig);
# on device they are a rounding error next to the scalar multiplication.

_L_INT = em.L
_DELTA16_INT = 16 * (_L_INT - (1 << 252))  # 16*delta, 129 bits: 2^256 ≡ -16*delta


def _bytes_const(value: int, k: int) -> np.ndarray:
    """(k, 1) int32 radix-2^8 limbs of a constant."""
    return np.array(
        [(value >> (8 * i)) & 0xFF for i in range(k)], dtype=np.int32
    )[:, None]


_C8 = _bytes_const(_DELTA16_INT, 17)
_L8 = _bytes_const(_L_INT, 32)

# (32, 1) AND-mask clearing the sign bit of byte row 31 — the
# mask-select form of `.at[31].set(b & 0x7F)`; jnp scatter updates
# have no Pallas TPU lowering (Mosaic: "Unimplemented ... scatter")
_TOPCLEAR = np.full((32, 1), 0xFF, dtype=np.int32)
_TOPCLEAR[31, 0] = 0x7F


def _fe_from_bytes_dev(b: jnp.ndarray) -> jnp.ndarray:
    """(32, N) int32 byte rows (bit 7 of row 31 already cleared) ->
    (NLIMBS, N) radix-2^13 limbs. The value (< 2^255) may exceed p —
    fine: field ops accept any normalized-limb representative
    (ZIP-215 accepts non-canonical y encodings)."""
    b = jnp.concatenate(
        [b, jnp.zeros((2, b.shape[1]), dtype=b.dtype)], axis=0
    )
    limbs = []
    for i in range(F.NLIMBS):
        s = F.RADIX * i
        b0 = s >> 3
        v = b[b0] + (b[b0 + 1] << 8) + (b[b0 + 2] << 16)
        limbs.append((v >> (s & 7)) & F.MASK)
    return jnp.stack(limbs, axis=0)


def _norm8(x: jnp.ndarray, passes: int) -> jnp.ndarray:
    """Radix-2^8 carry/borrow propagation, `passes` fixed rounds: lower
    limbs land in [0, 2^8), the top limb keeps the value's sign. A
    ripple can travel one limb per round, so `passes` >= rows for full
    canonicalization; 2 for loose bounding between multiplies."""
    zero = jnp.zeros_like(x[:1])
    for _ in range(passes):
        c = x[:-1] >> 8
        x = jnp.concatenate([x[:-1] - (c << 8), x[-1:]], axis=0)
        x = x + jnp.concatenate([zero, c], axis=0)
    return x


def _mul_c8(a: jnp.ndarray, width: int) -> jnp.ndarray:
    """(ka, N) signed radix-2^8 limbs x 16*delta -> (width, N) raw conv.
    Partial sums <= 17 * 2^9 * 2^8 < 2^22: safely int32."""
    ka = a.shape[0]
    acc = None
    for i in range(_C8.shape[0]):
        t = jnp.pad(a * _C8[i], ((i, width - i - ka), (0, 0)))
        acc = t if acc is None else acc + t
    return acc


def _mod_l_dev(d: jnp.ndarray) -> jnp.ndarray:
    """(64, N) int32 digest byte rows (LE) -> (32, N) canonical byte
    rows of the value mod L.

    Three folds of the high half with 2^256 ≡ -16*delta, then an
    approximate quotient by the top 4 bits and conditional +L fixes:
      fold1: < 2^512          -> |x| < 2^385  (50 rows)
      fold2: |hi| < 2^129     -> |x| < 2^259  (35 rows)
      (full normalize so lo is canonical)
      fold3: |hi| < 2^3       -> x in (-2^132, 2^256)  (33 rows)
      +L if negative; q = x >> 252 in [0,15]; x -= q*L -> (-16d, 2^252)
      +L if negative -> [0, L)."""
    x = d
    for split, width in ((32, 50), (32, 35)):
        lo = jnp.pad(
            x[:split], ((0, width - split), (0, 0))
        )
        x = _norm8(lo - _mul_c8(x[split:], width), 2)
    x = _norm8(x, 36)  # canonical lower limbs, signed top
    lo = jnp.pad(x[:32], ((0, 1), (0, 0)))
    x = _norm8(lo - _mul_c8(x[32:], 33), 34)
    l8_33 = jnp.asarray(np.pad(_L8, ((0, 1), (0, 0))))
    # x[32], not x[-1]: jnp lowers negative indices via dynamic_slice,
    # which Mosaic (Pallas TPU) cannot lower
    neg = (x[32] < 0).astype(jnp.int32)
    x = x + neg[None, :] * l8_33
    x = _norm8(x, 34)
    # value < 2^257: bits 252..255 in row 31, bit 256 in row 32
    q = (x[31] >> 4) + (x[32] << 4)
    x = x - q[None, :] * l8_33
    x = _norm8(x, 34)
    neg = (x[32] < 0).astype(jnp.int32)
    x = x + neg[None, :] * l8_33
    return _norm8(x, 34)[:32]


def _lt_const_dev(rows: jnp.ndarray, const8: np.ndarray) -> jnp.ndarray:
    """(32, N) canonical byte rows (LE) -> (N,) bool: value < const.
    Most-significant-byte-first scan; shared by the S < L check here
    and the ristretto s < p canonicity check (ops/sr25519_kernel.py).

    The decided/lt lattice is int32 0/1, not bool: a scalar-True
    jnp.where operand materializes as an i8 constant that Mosaic must
    trunci to i1 — 'Unsupported target bitwidth for truncation'
    (found via scripts/aot_bisect.py against the local v5e topology)."""
    cb = np.asarray(const8)[:, 0]
    lt = jnp.zeros(rows.shape[1], dtype=jnp.int32)
    decided = jnp.zeros(rows.shape[1], dtype=jnp.int32)
    for i in range(31, -1, -1):
        lo = (rows[i] < int(cb[i])).astype(jnp.int32)
        hi = (rows[i] > int(cb[i])).astype(jnp.int32)
        lt = lt | ((1 - decided) & lo)
        decided = decided | lo | hi
    return lt != 0


def _s_lt_l_dev(s: jnp.ndarray) -> jnp.ndarray:
    """(32, N) int32 byte rows of S (LE) -> (N,) bool: S < L
    (ZIP-215 rule 2: S must be canonical)."""
    return _lt_const_dev(s, _L8)


def _nibbles_dev(b: jnp.ndarray) -> jnp.ndarray:
    """(32, N) canonical byte rows -> (64, N) radix-16 digits, LE."""
    lo = b & 0x0F
    hi = b >> 4
    return jnp.stack([lo, hi], axis=1).reshape(64, b.shape[1])


def _verify_tile(pk_b, sig_b, dig_b, mosaic: bool = False, dual_fn=None) -> jnp.ndarray:
    """The full device program: byte rows in, validity bitmap out.

    pk_b (32, N), sig_b (64, N) uint8/int32 byte rows; dig_b (64, N)
    SHA-512(R||A||M) byte rows. Returns (N,) bool.

    Pure jnp on values — the same body runs as a jitted XLA program
    (CPU and fallback) and, with mosaic=True (Mosaic-lowerable window
    walk, see dual_mult_sb_minus_ka), as the per-tile body of the
    fused Pallas kernel (ops/ed25519_pallas.py). `dual_fn` swaps in the
    segmented Pallas dual-mult while the rest stays XLA."""
    pk = pk_b.astype(jnp.int32)
    sig = sig_b.astype(jnp.int32)
    dig = dig_b.astype(jnp.int32)
    signA = pk[31] >> 7
    pk = pk & _TOPCLEAR
    r = sig[:32]
    signR = r[31] >> 7
    r = r & _TOPCLEAR
    s = sig[32:]
    yA = _fe_from_bytes_dev(pk)
    yR = _fe_from_bytes_dev(r)
    s_ok = _s_lt_l_dev(s)
    dS = _nibbles_dev(s)
    dk = _nibbles_dev(_mod_l_dev(dig))
    ok = _scalar_mult_check(
        yA, signA, yR, signR, dS, dk, mosaic=mosaic, dual_fn=dual_fn
    )
    return ok & s_ok


# -- host packing (only SHA-512 and byte joins remain on host) --


def _join_cols(items: Sequence[bytes], width: int, pad: int) -> np.ndarray:
    """Join n equal-length byte strings into a (width, n+pad) uint8
    array, batch-minor, zero-padded on the right."""
    arr = np.frombuffer(b"".join(items), dtype=np.uint8).reshape(-1, width)
    out = arr.T
    if pad:
        return np.pad(out, ((0, 0), (0, pad)))
    return np.ascontiguousarray(out)


def pallas_bucket(b: int) -> int:
    """Round a bucket up to full Pallas tiles. Rounding small buckets
    up costs nothing: the VPU lane tile is 128 wide, so an 8-lane XLA
    program wastes 94% of every vector register anyway."""
    from .ed25519_pallas import TILE

    return max(TILE, -(-b // TILE) * TILE)


def run_with_pallas_fallback(
    prog, args, *, is_pallas, bucket, proven, compiled, xla_factory, label
):
    """Shared dispatch policy for programs that may contain a Pallas
    kernel (the ed25519 tile/hybrid and the sr25519 hybrid).

    Runs `prog(*args)`. JAX dispatch is asynchronous, so a Mosaic
    *runtime* failure would surface later at gather()'s np.asarray —
    past any fallback; block on the first call of each Pallas bucket so
    device-side kernel failures downgrade HERE. On failure (lowering or
    first-call runtime), log, permanently swap the bucket's entry in
    `compiled` to `xla_factory()` (same math, same semantics), and
    re-run. A non-Pallas program failing is a real error and re-raises."""
    try:
        ok = prog(*args)
        if is_pallas and bucket not in proven:
            jax.block_until_ready(ok)
            proven.add(bucket)
        return ok
    except Exception as e:
        if not is_pallas:
            raise
        import logging

        logging.getLogger("tendermint_tpu.ops").warning(
            "pallas %s kernel failed for bucket %d; "
            "falling back to the XLA program: %s",
            label,
            bucket,
            e,
        )
        fn = xla_factory()
        compiled[bucket] = fn
        return fn(*args)


class Ed25519Verifier:
    """Compiled, bucketed batch verifier.

    One instance caches jitted programs per bucket size. Thread-compatible
    for the asyncio runtime (verification calls are synchronous device
    invocations)."""

    def __init__(self, bucket_sizes: Optional[Sequence[int]] = None) -> None:
        self.bucket_sizes = sorted(bucket_sizes or DEFAULT_BUCKET_SIZES)
        self._compiled = {}
        # buckets whose Pallas program has completed on device at least
        # once (first calls block, see dispatch())
        self._pallas_proven = set()

    @staticmethod
    def _is_pallas(prog) -> bool:
        import sys

        # only consult the pallas module if something already imported
        # it (i.e. a pallas program could possibly be in `prog`) — the
        # default XLA path must never pay for, or fail on, this import
        mod = sys.modules.get(__package__ + ".ed25519_pallas")
        return mod is not None and (
            prog is mod.verify_pallas or prog is mod.verify_hybrid
        )

    def _bucket(self, n: int) -> int:
        b = bucket_for(n, self.bucket_sizes)
        if self._pallas_wanted():
            b = pallas_bucket(b)
        return b

    @staticmethod
    def _pallas_wanted() -> Optional[str]:
        """Fused Pallas kernel gate. Opt-in for now: the kernels are
        differential-verified in interpret mode (tests/test_ops_pallas.py)
        but Mosaic compilation via this environment's remote-compile
        tunnel has not completed for the monolithic kernel, and an
        unbounded first compile must not eat the benchmark window. The
        XLA program remains the measured default.

        TM_TPU_PALLAS=1|hybrid -> the segmented kernel (Pallas
        dual-mult inside an XLA program — ~6x smaller Mosaic module);
        TM_TPU_PALLAS=full -> the monolithic whole-tile kernel."""
        import os

        if os.environ.get("TM_TPU_NO_PALLAS"):
            return None
        if jax.default_backend() != "tpu":
            return None
        v = os.environ.get("TM_TPU_PALLAS")
        if v in ("1", "hybrid"):
            return "hybrid"
        if v == "full":
            return "full"
        return None

    def _program(self, size: int):
        """The compiled program for a bucket. One shape-polymorphic
        jitted function serves every bucket (jit caches per shape
        internally); the per-size dict exists for overrides — the
        Pallas fallback swap in dispatch() and ShardedEd25519Verifier's
        per-bucket sharded programs."""
        fn = self._compiled.get(size)
        if fn is None:
            kind = self._pallas_wanted()
            if kind == "hybrid":
                from .ed25519_pallas import verify_hybrid

                fn = verify_hybrid
            elif kind == "full":
                from .ed25519_pallas import verify_pallas

                fn = verify_pallas
            else:
                fn = _jit_verify_tile()
            self._compiled[size] = fn
        return fn

    def verify(
        self,
        pubkeys: Sequence[bytes],
        msgs: Sequence[bytes],
        sigs: Sequence[bytes],
    ) -> np.ndarray:
        """Returns a bool bitmap, one per triple. Malformed inputs are
        reported invalid rather than raising (the BatchVerifier.add layer
        enforces sizes upstream)."""
        return self.gather(self.dispatch(pubkeys, msgs, sigs))

    def dispatch(
        self,
        pubkeys: Sequence[bytes],
        msgs: Sequence[bytes],
        sigs: Sequence[bytes],
    ):
        """Asynchronously launch verification; returns an opaque handle
        for gather(). Device dispatch is non-blocking in JAX, so several
        batches can be in flight at once — on a tunneled device this
        hides the per-call round-trip latency (the verify-ahead pattern
        from SURVEY §7: stream commits through the device without
        stalling the consensus loop)."""
        n = len(pubkeys)
        if n == 0:
            return (None, 0, np.zeros(0, dtype=bool))
        size_ok = np.array(
            [
                len(pk) == 32 and len(sig) == 64
                for pk, sig in zip(pubkeys, sigs)
            ],
            dtype=bool,
        )
        if not size_ok.all():
            pubkeys = [
                pk if ok else b"\x00" * 32
                for pk, ok in zip(pubkeys, size_ok)
            ]
            sigs = [
                sig if ok else b"\x00" * 64
                for sig, ok in zip(sigs, size_ok)
            ]
        # host work is byte joins only; hashing (SHA-512 of R||A||M),
        # limb unpacking, mod-L, S-canonicality, digits, and the curve
        # math all run on device
        bucket = self._bucket(n)
        pad = bucket - n
        pk_b = _join_cols(pubkeys, 32, pad)
        sig_b = _join_cols(sigs, 64, pad)
        dig_b = self._digest_rows(pubkeys, msgs, sigs, bucket)
        prog = self._program(bucket)
        ok = run_with_pallas_fallback(
            prog,
            (jnp.asarray(pk_b), jnp.asarray(sig_b), jnp.asarray(dig_b)),
            is_pallas=self._is_pallas(prog),
            bucket=bucket,
            proven=self._pallas_proven,
            compiled=self._compiled,
            xla_factory=_jit_verify_tile,
            label="ed25519",
        )
        return (ok, n, size_ok)

    def _digest_rows(self, pubkeys, msgs, sigs, bucket):
        """(64, bucket) rows of SHA512(R || A || M).

        Device-hashed per message-length group (ops/sha512_kernel.py
        compiles one program per length); the single-length common case
        — every sign-bytes in a Commit has the same shape — keeps the
        digests on device, feeding the verify program without a host
        round-trip. TM_TPU_HOST_SHA512=1 restores hashlib (bench
        comparisons)."""
        import os

        n = len(pubkeys)
        if os.environ.get("TM_TPU_HOST_SHA512"):
            return _join_cols(
                [
                    hashlib.sha512(sig[:32] + pk + msg).digest()
                    for pk, msg, sig in zip(pubkeys, msgs, sigs)
                ],
                64,
                bucket - n,
            )
        groups: dict = {}
        for i, m in enumerate(msgs):
            groups.setdefault(len(m), []).append(i)
        if len(groups) == 1:
            ((mlen, _),) = groups.items()
            pre = _join_cols(
                [
                    sig[:32] + pk + msg
                    for pk, msg, sig in zip(pubkeys, msgs, sigs)
                ],
                64 + mlen,
                bucket - n,
            )
            return _jit_sha512()(jnp.asarray(pre))
        dig = np.zeros((64, bucket), dtype=np.uint8)
        for mlen, idxs in groups.items():
            g = len(idxs)
            gb = bucket_for(g, self.bucket_sizes)
            pre = _join_cols(
                [
                    sigs[i][:32] + pubkeys[i] + msgs[i]
                    for i in idxs
                ],
                64 + mlen,
                gb - g,
            )
            out = np.asarray(_jit_sha512()(jnp.asarray(pre)))
            dig[:, idxs] = out[:, :g]
        return dig

    def gather(self, handle) -> np.ndarray:
        """Block on a dispatch() handle and return the bitmap."""
        ok, n, size_ok = handle
        if ok is None:
            return size_ok
        return np.asarray(ok)[:n] & size_ok


_JIT_VERIFY = None
_JIT_SHA512 = None


def _jit_sha512():
    """Shared jitted sha512_fixed (one compile per message length +
    bucket shape inside jax's cache)."""
    global _JIT_SHA512
    if _JIT_SHA512 is None:
        from .sha512_kernel import sha512_fixed

        _JIT_SHA512 = jax.jit(sha512_fixed)
    return _JIT_SHA512


def _jit_verify_tile():
    """Shared jitted XLA program (shape-polymorphic; compiles once per
    bucket shape inside jax's own cache)."""
    global _JIT_VERIFY
    if _JIT_VERIFY is None:
        _JIT_VERIFY = jax.jit(_verify_tile)
    return _JIT_VERIFY


_DEFAULT: Optional[Ed25519Verifier] = None
_DEFAULT_LOCK = threading.Lock()


def default_verifier() -> Ed25519Verifier:
    """The shared module verifier (compiled programs cached across the
    process; also the dispatch/gather handle source for the streaming
    batch seam, crypto/tpu_verifier.py)."""
    global _DEFAULT
    if _DEFAULT is None:
        # double-checked: the first calls race in from the asyncio loop
        # AND the breaker probe thread (tmrace), and a losing duplicate
        # construction is not just waste — each instance carries its
        # own compiled-program cache, so consensus traffic landing on a
        # discarded instance would recompile every bucket
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = Ed25519Verifier()
    return _DEFAULT


def batch_verify_host(pubkeys, msgs, sigs) -> np.ndarray:
    """Module-level convenience using the shared verifier instance."""
    return default_verifier().verify(pubkeys, msgs, sigs)
