"""Device merkle: batched tree roots and proof verification.

The module crypto/merkle.py names as its device counterpart. Two
offloads (reference shapes: crypto/merkle/tree.go:68 HashFromByteSlices,
proof.go:52 Proof.Verify):

- tree_root(leaf_hashes): the n-1 inner hashes of an RFC 6962 tree.
  Level-by-level pairwise reduction (odd node passes through), which
  reproduces the reference's split-at-largest-power-of-two shape; each
  level is one device call hashing all pairs at once.

- verify_proofs(...): K inclusion proofs checked in one device program:
  a lax.scan over proof depth where each lane either absorbs its aunt
  on the left, on the right, or passes through (padding for shorter
  proofs) — the select form keeps all lanes busy with no per-lane
  control flow.

Both are installed behind crypto.merkle's device hook by install(),
gated on batch size the same way the ed25519 verifier is
(crypto/tpu_verifier.py): small inputs stay on the host CPU path.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..libs import trace
from . import sha256_kernel as S

__all__ = [
    "tree_root",
    "verify_proofs",
    "install",
    "installed",
    "stats",
]

# proof-step flags
_STEP_LEFT = 0  # our hash is the left child:  h = inner(h, aunt)
_STEP_RIGHT = 1  # our hash is the right child: h = inner(aunt, h)
_STEP_NOOP = 2  # padding beyond this proof's depth

_inner_jit = jax.jit(S.inner_hash_batch)


def _bucket(n: int) -> int:
    """Next power of two >= n (min 8): bounds the number of compiled
    program shapes — tree levels halve in width every step, so without
    padding every tree size would compile its own ladder of programs."""
    b = 8
    while b < n:
        b <<= 1
    return b


def _inner_bucketed(left: jnp.ndarray, right: jnp.ndarray) -> jnp.ndarray:
    """Device-resident bucketed inner hash: no host transfer — callers
    chain levels and fetch once at the end."""
    n = left.shape[1]
    b = _bucket(n)
    if b != n:
        left = jnp.pad(left, ((0, 0), (0, b - n)))
        right = jnp.pad(right, ((0, 0), (0, b - n)))
    return _inner_jit(left, right)[:, :n]


def _to_cols(hashes: Sequence[bytes]) -> np.ndarray:
    return np.frombuffer(b"".join(hashes), dtype=np.uint8).reshape(-1, 32).T


def tree_root(leaf_hashes: Sequence[bytes]) -> bytes:
    """Root from already-hashed leaves. Pairwise level reduction: for
    n hashes per level, hash the floor(n/2) adjacent pairs in one
    device call; an odd trailing node passes through unchanged. This
    pairing yields exactly the reference's recursive
    split-point tree (tree.go:94): the split at the largest power of
    two < n is what adjacent pairing produces level by level."""
    n = len(leaf_hashes)
    if n == 0:
        raise ValueError("tree_root requires at least one leaf hash")
    # the whole reduction stays device-resident: one upload, log2(n)
    # async dispatches, ONE blocking download at the end (a host
    # round-trip per level would pay the tunnel RTT log2(n) times)
    level = jnp.asarray(_to_cols(leaf_hashes))  # (32, n)
    while level.shape[1] > 1:
        m = level.shape[1]
        pairs = m // 2
        hashed = _inner_bucketed(
            level[:, 0 : 2 * pairs : 2],
            level[:, 1 : 2 * pairs : 2],
        )
        if m % 2:
            hashed = jnp.concatenate([hashed, level[:, -1:]], axis=1)
        level = hashed
    return np.asarray(level[:, 0]).tobytes()


def _sides_for(index: int, total: int) -> List[int]:
    """Bottom-up left/right flags matching Proof.aunts order
    (reference recursion: crypto/merkle/proof.go:71
    computeHashFromAunts)."""
    out: List[int] = []

    def rec(idx: int, tot: int) -> None:
        if tot == 1:
            return
        k = 1 << ((tot - 1).bit_length() - 1)
        if idx < k:
            rec(idx, k)
            out.append(_STEP_LEFT)
        else:
            rec(idx - k, tot - k)
            out.append(_STEP_RIGHT)

    rec(index, total)
    return out


@jax.jit
def _verify_program(leaf, aunts, flags):
    """leaf (32, K) u8; aunts (D, 32, K) u8; flags (D, K) i32.
    Returns computed roots (32, K)."""

    def step(h, xs):
        aunt, flag = xs
        as_left = S.inner_hash_batch(h, aunt)
        as_right = S.inner_hash_batch(aunt, h)
        h = jnp.where(flag[None, :] == _STEP_LEFT, as_left, h)
        h = jnp.where(flag[None, :] == _STEP_RIGHT, as_right, h)
        return h, None

    root, _ = lax.scan(step, leaf, (aunts, flags))
    return root


def verify_proofs(
    proofs: Sequence,  # crypto.merkle.Proof
    root_hash: bytes,
) -> np.ndarray:
    """Batch-verify K inclusion proofs against one root. Returns a
    bool bitmap (structurally invalid proofs are False, not raised —
    BatchVerifier semantics, crypto/crypto.go:56-60)."""
    k = len(proofs)
    if k == 0:
        return np.zeros(0, dtype=bool)
    sides: List[Optional[List[int]]] = []
    max_d = 0
    for p in proofs:
        if (
            p.index < 0
            or p.total <= 0
            or p.index >= p.total
            or len(p.leaf_hash) != 32
            or any(len(a) != 32 for a in p.aunts)
        ):
            sides.append(None)
            continue
        s = _sides_for(p.index, p.total)
        if len(s) != len(p.aunts):
            sides.append(None)
            continue
        sides.append(s)
        max_d = max(max_d, len(s))
    structural_ok = np.array([s is not None for s in sides], dtype=bool)
    if not structural_ok.any():
        return structural_ok
    kb = _bucket(k)  # pad batch and depth to bound compiled shapes
    db = _bucket(max(max_d, 1))
    leaf = np.zeros((32, kb), dtype=np.uint8)
    aunts = np.zeros((db, 32, kb), dtype=np.uint8)
    flags = np.full((db, kb), _STEP_NOOP, dtype=np.int32)
    for i, (p, s) in enumerate(zip(proofs, sides)):
        if s is None:
            continue
        leaf[:, i] = np.frombuffer(p.leaf_hash, dtype=np.uint8)
        for d, (aunt, side) in enumerate(zip(p.aunts, s)):
            aunts[d, :, i] = np.frombuffer(aunt, dtype=np.uint8)
            flags[d, i] = side
    roots = np.asarray(
        _verify_program(
            jnp.asarray(leaf), jnp.asarray(aunts), jnp.asarray(flags)
        )
    )[:, :k]
    want = np.frombuffer(root_hash, dtype=np.uint8)[:, None]
    return structural_ok & (roots == want).all(axis=0)


# -- crypto.merkle device hook ---------------------------------------------

_installed: Optional[int] = None
_stats = {"roots": 0, "leaves": 0, "proofs": 0}


def installed() -> Optional[int]:
    return _installed


def stats() -> dict:
    return dict(_stats)


def install(min_leaves: int = 512) -> None:
    """Route large merkle roots and proof batches through the device
    (the hook crypto/merkle.py consults; mirrors
    crypto/tpu_verifier.install)."""
    global _installed
    from ..crypto import merkle as cm

    _installed = min_leaves

    def _root_hook(leaf_hashes: List[bytes]) -> Optional[bytes]:
        if len(leaf_hashes) < min_leaves:
            return None
        _stats["roots"] += 1
        _stats["leaves"] += len(leaf_hashes)
        with trace.span("merkle_device_root", leaves=len(leaf_hashes)):
            return tree_root(leaf_hashes)

    def _proofs_hook(proofs, root_hash: bytes):
        if len(proofs) < max(min_leaves // 8, 2):
            return None
        _stats["proofs"] += len(proofs)
        with trace.span("merkle_device_proofs", proofs=len(proofs)):
            return verify_proofs(proofs, root_hash)

    cm._device_root_hook = _root_hook
    cm._device_proofs_hook = _proofs_hook


def uninstall() -> None:
    global _installed
    from ..crypto import merkle as cm

    _installed = None
    cm._device_root_hook = None
    cm._device_proofs_hook = None
