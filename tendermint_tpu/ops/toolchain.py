"""Toolchain capability probes for the Mosaic/XLA lowering contracts.

The kernels in this package keep their jaxprs free of primitives
Mosaic cannot lower (scatter, gather, dynamic_slice, rev, rank-1
iota — each found the hard way on hardware, PERF.md). That contract
is enforced by tests/test_ops_pallas.py::test_mosaic_jaxpr_clean, but
the *jaxpr a given jax version produces for the same source* is not
stable: jax 0.4.37 lowers a static slice written with a
zero-width ellipsis (`x[..., :-1, :]` on a rank-2 array — the
field25519 carry-pass idiom) to `gather`, where newer versions emit
`slice`. On such a toolchain the cleanliness check cannot
distinguish "our code regressed" from "the tracer spells static
slices differently", so the test must skip — with the probe result
recorded, not silently.

`mosaic_probe()` traces a catalog of known-clean constructs (each one
an idiom the kernels actually use, none of which *semantically*
needs a banned primitive) and reports which banned primitives the
installed toolchain introduces for them. A non-empty `introduced`
map means jaxpr-level cleanliness checks are meaningless on this
toolchain; the device campaign's AOT path (scripts/aot_check.py, on
real hardware) remains the ground truth there.

The probe is cheap (<100 ms after jax import), touches no backend
(pure abstract tracing of constant-free functions), and its result
rides in the bench JSON (`mosaic_probe` key) so every BENCH_* record
names the toolchain capability it was measured under.
"""

from __future__ import annotations

from typing import Dict, List

BANNED = (
    "scatter",
    "scatter-add",
    "gather",
    "dynamic_slice",
    "dynamic_update_slice",
    "rev",
)

__all__ = ["BANNED", "banned_prims_of", "mosaic_probe"]


def banned_prims_of(fn, *avals) -> set:
    """The banned-primitive names appearing anywhere in fn's jaxpr
    (sub-jaxprs included), plus rank-1 iota reported as
    'iota(rank-1)'. Shared by the mosaic cleanliness test and the
    probe so both walk the exact same definition of 'clean'."""
    import jax

    seen: set = set()

    def walk(jaxpr):
        for eq in jaxpr.eqns:
            name = eq.primitive.name
            if name in BANNED:
                seen.add(name)
            if name == "iota" and len(eq.outvars[0].aval.shape) == 1:
                seen.add("iota(rank-1)")
            for p in eq.params.values():
                if hasattr(p, "jaxpr"):
                    walk(p.jaxpr)
                elif isinstance(p, (list, tuple)):
                    for q in p:
                        if hasattr(q, "jaxpr"):
                            walk(q.jaxpr)

    walk(jax.make_jaxpr(fn)(*avals).jaxpr)
    return seen


def _clean_constructs():
    """Constructs the kernels rely on that have a banned-free lowering
    (newer jax emits slice/broadcast for every one). Keyed by the
    idiom's name; each value is (fn, avals)."""
    import jax
    import jax.numpy as jnp

    i32 = jnp.int32
    r2 = jax.ShapeDtypeStruct((20, 8), i32)
    r3 = jax.ShapeDtypeStruct((4, 20, 8), i32)
    return {
        # field25519._pass: carry fold, ellipsis consumes zero dims
        "ellipsis-static-slice-rank2": (
            lambda x: jnp.concatenate(
                [x[..., -1:, :], x[..., :-1, :]], axis=-2
            ),
            (r2,),
        ),
        # the same slices on a rank-3 stack (edwards point coords)
        "ellipsis-static-slice-rank3": (
            lambda x: jnp.concatenate(
                [x[..., -1:, :], x[..., :-1, :]], axis=-2
            ),
            (r3,),
        ),
        # _onehot_select: broadcasted-iota masked accumulate
        "onehot-masked-select": (
            lambda t, i: jnp.sum(
                t
                * (
                    i[None, :]
                    == jax.lax.broadcasted_iota(i32, (4, 8), 0)
                ).astype(i32)[:, None, :],
                axis=0,
            ),
            (r3, jax.ShapeDtypeStruct((8,), i32)),
        ),
    }


def mosaic_probe() -> Dict[str, object]:
    """Probe the installed toolchain: does tracing known-clean
    constructs introduce Mosaic-banned primitives? Returns
    {"clean": bool, "introduced": {construct: [prims]},
    "jax_version": str}. clean=False means jaxpr-level banned-prim
    checks cannot run on this toolchain (skip, don't fail)."""
    import jax

    introduced: Dict[str, List[str]] = {}
    for name, (fn, avals) in _clean_constructs().items():
        bad = banned_prims_of(fn, *avals)
        if bad:
            introduced[name] = sorted(bad)
    return {
        "clean": not introduced,
        "introduced": introduced,
        "jax_version": jax.__version__,
    }
