"""Fused Pallas TPU kernel for batched ed25519 verification.

The XLA path in ops/ed25519_kernel.py expresses the verification
program as thousands of separate HLO ops per scan window; XLA fuses
elementwise chains but every pad/concatenate/reduce materializes an
intermediate, and the scan body round-trips HBM many times per window.
This module runs the *same* tile body (ed25519_kernel._verify_tile —
the math is shared, not duplicated) inside one `pl.pallas_call`, tiled
along the batch axis: intermediates of the 64-window double-scalar
multiplication stay in VMEM, the grid pipelines the byte-row DMA
against compute, and the only HBM traffic is the byte rows in and the
validity bitmap out.

Pallas kernels cannot close over array constants, and the field/curve
layer materializes its limb constants (2p, L, the fixed-base niels
table…) at trace time. `_closed_tile()` lifts them off the traced
jaxpr once, dedupes identical arrays (the 2p bias alone appears dozens
of times), and the wrapper feeds them to the kernel as broadcast
inputs — every grid step maps block (0, …) of each constant.

Layout per tile: byte rows (32|64, TILE) int32 with the batch in the
lane axis, exactly the batch-minor convention of field25519 — one tile
is (sublanes=bytes, lanes=TILE signatures).

This is the device program behind the reference's batch-verifier seam
(crypto/ed25519/ed25519.go:202-237, crypto/crypto.go:53-61); the
ZIP-215 semantics and the per-index validity bitmap are identical to
the XLA path, which remains the fallback on CPU and the differential
oracle in tests.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["TILE", "verify_pallas"]

TILE = 128  # lanes per grid step: one full VPU lane tile


@functools.lru_cache(maxsize=4)
def _closed_tile(tile: int = TILE):
    """(closed_fn, unique_consts, index_map): the tile body with every
    trace-time array constant hoisted to an explicit argument."""
    from . import ed25519_kernel as K

    avals = (
        jax.ShapeDtypeStruct((32, tile), jnp.int32),
        jax.ShapeDtypeStruct((64, tile), jnp.int32),
        jax.ShapeDtypeStruct((64, tile), jnp.int32),
    )
    # jax.closure_convert hoists only captured jax arrays; the limb
    # constants here materialize during tracing (np -> jaxpr consts),
    # so lift them straight off the jaxpr instead.
    cj = jax.make_jaxpr(
        lambda pk, sig, dig: K._verify_tile(pk, sig, dig, mosaic=True)
    )(*avals)
    consts = cj.consts

    def closed(pk, sig, dig, *hoisted):
        (out,) = jax.core.eval_jaxpr(cj.jaxpr, hoisted, pk, sig, dig)
        return out
    uniq: list[np.ndarray] = []
    index: list[int] = []
    seen: dict = {}
    for c in consts:
        arr = np.asarray(c)
        key = (arr.shape, arr.dtype.str, arr.tobytes())
        if key not in seen:
            seen[key] = len(uniq)
            uniq.append(arr)
        index.append(seen[key])
    return closed, uniq, index


def _make_kernel(tile: int):
    def _kernel(*refs):
        closed, uniq, index = _closed_tile(tile)
        pk_ref, sig_ref, dig_ref = refs[:3]
        const_refs = refs[3 : 3 + len(uniq)]
        out_ref = refs[-1]
        consts = [const_refs[j][...] for j in index]
        ok = closed(pk_ref[...], sig_ref[...], dig_ref[...], *consts)
        out_ref[...] = ok.astype(jnp.int32)[None, :]

    return _kernel


def _const_spec(arr: np.ndarray) -> pl.BlockSpec:
    nd = arr.ndim
    return pl.BlockSpec(
        arr.shape, lambda i, _nd=nd: (0,) * _nd, memory_space=pltpu.VMEM
    )


@functools.partial(jax.jit, static_argnames=("interpret", "tile"))
def verify_pallas(pk_b, sig_b, dig_b, interpret: bool = False, tile: int = TILE):
    """pk_b (32, N), sig_b (64, N), dig_b (64, N) int32 byte rows with
    N a multiple of `tile` -> (N,) bool validity bitmap. `tile` stays at
    the 128-lane default on hardware; tests shrink it (with interpret
    mode) to keep the differential cheap."""
    n = pk_b.shape[1]
    assert n % tile == 0, n
    _, uniq, _ = _closed_tile(tile)
    grid = (n // tile,)
    ok = pl.pallas_call(
        _make_kernel(tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (32, tile), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (64, tile), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (64, tile), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
            *[_const_spec(c) for c in uniq],
        ],
        out_specs=pl.BlockSpec(
            (1, tile), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int32),
        interpret=interpret,
    )(
        pk_b.astype(jnp.int32),
        sig_b.astype(jnp.int32),
        dig_b.astype(jnp.int32),
        *[jnp.asarray(c) for c in uniq],
    )
    return ok[0] != 0
