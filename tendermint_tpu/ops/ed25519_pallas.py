"""Fused Pallas TPU kernels for batched ed25519 verification.

The XLA path in ops/ed25519_kernel.py expresses the verification
program as thousands of separate HLO ops per scan window; XLA fuses
elementwise chains but every pad/concatenate/reduce materializes an
intermediate, and the scan body round-trips HBM many times per window.
The kernels here run the *same* math (ed25519_kernel's tile body — the
code is shared, not duplicated) inside `pl.pallas_call`, tiled along
the batch axis: intermediates stay in VMEM, the grid pipelines the
byte-row DMA against compute, and the only HBM traffic is rows in and
results out.

Two granularities, because Mosaic compile cost scales with program
size (the monolithic tile is ~37k jaxpr eqns and has never finished
compiling through the remote-compile tunnel; the dual-mult segment is
~7k):

- verify_pallas: the whole `_verify_tile` body in one kernel
  (decompression + scalar prep + 64-window walk + compare).
- dual_mult_pallas + verify_hybrid: ONLY the dual scalar
  multiplication `[S]B - [k]A` (table build + 64 windows — the
  dominant cost) as the kernel; decompression, mod-L prep, and the
  projective compare remain XLA ops around it, fused by XLA as usual.

Pallas kernels cannot close over array constants, and the field/curve
layer materializes its limb constants (2p, L, the fixed-base niels
table…) at trace time. `_closed()` lifts them off the traced jaxpr
once, dedupes identical arrays (the 2p bias alone appears dozens of
times), and the wrappers feed them to the kernel as broadcast inputs —
every grid step maps block (0, …) of each constant.

Layout per tile: byte rows (32|64, TILE) int32 with the batch in the
lane axis, exactly the batch-minor convention of field25519 — one tile
is (sublanes, lanes=TILE signatures).

This is the device program behind the reference's batch-verifier seam
(crypto/ed25519/ed25519.go:202-237, crypto/crypto.go:53-61); the
ZIP-215 semantics and the per-index validity bitmap are identical to
the XLA path, which remains the fallback on CPU and the differential
oracle in tests.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["TILE", "verify_pallas", "verify_hybrid", "dual_mult_pallas"]

TILE = 128  # lanes per grid step: one full VPU lane tile


def _body_and_avals(kind: str, tile: int):
    from . import ed25519_kernel as K
    from . import field25519 as F

    if kind == "tile":
        fn = lambda pk, sig, dig: K._verify_tile(pk, sig, dig, mosaic=True)
        shapes = ((32, tile), (64, tile), (64, tile))
    elif kind == "dual":
        fn = lambda A, dS, dk: K.dual_mult_sb_minus_ka(
            A, dS, dk, mosaic=True
        )
        shapes = ((4, F.NLIMBS, tile), (64, tile), (64, tile))
    else:  # pragma: no cover
        raise ValueError(kind)
    avals = tuple(jax.ShapeDtypeStruct(s, jnp.int32) for s in shapes)
    return fn, avals


@functools.lru_cache(maxsize=8)
def _closed(kind: str, tile: int):
    """(closed_fn, unique_consts, index_map): the requested body with
    every trace-time array constant hoisted to an explicit argument."""
    fn, avals = _body_and_avals(kind, tile)
    # jax.closure_convert hoists only captured jax arrays; the limb
    # constants here materialize during tracing (np -> jaxpr consts),
    # so lift them straight off the jaxpr instead.
    cj = jax.make_jaxpr(fn)(*avals)
    consts = cj.consts
    n_in = len(avals)

    def closed(*args):
        ins, hoisted = args[:n_in], args[n_in:]
        outs = jax.core.eval_jaxpr(cj.jaxpr, list(hoisted), *ins)
        return outs[0] if len(outs) == 1 else outs

    uniq: list[np.ndarray] = []
    index: list[int] = []
    seen: dict = {}
    for c in consts:
        arr = np.asarray(c)
        key = (arr.shape, arr.dtype.str, arr.tobytes())
        if key not in seen:
            seen[key] = len(uniq)
            uniq.append(arr)
        index.append(seen[key])
    return closed, uniq, index


def _make_kernel(kind: str, tile: int, n_in: int):
    def _kernel(*refs):
        closed, uniq, index = _closed(kind, tile)
        in_refs = refs[:n_in]
        const_refs = refs[n_in : n_in + len(uniq)]
        out_ref = refs[-1]
        consts = [const_refs[j][...] for j in index]
        out = closed(*[r[...] for r in in_refs], *consts)
        if kind == "tile":
            out_ref[...] = out.astype(jnp.int32)[None, :]
        else:
            out_ref[...] = out

    return _kernel


def _const_spec(arr: np.ndarray) -> pl.BlockSpec:
    nd = arr.ndim
    return pl.BlockSpec(
        arr.shape, lambda i, _nd=nd: (0,) * _nd, memory_space=pltpu.VMEM
    )


def _batch_spec(shape) -> pl.BlockSpec:
    """Block over the trailing batch axis; leading axes whole."""
    nd = len(shape)
    return pl.BlockSpec(
        shape, lambda i, _nd=nd: (0,) * (_nd - 1) + (i,),
        memory_space=pltpu.VMEM,
    )


@functools.partial(jax.jit, static_argnames=("interpret", "tile"))
def verify_pallas(pk_b, sig_b, dig_b, interpret: bool = False, tile: int = TILE):
    """pk_b (32, N), sig_b (64, N), dig_b (64, N) int32 byte rows with
    N a multiple of `tile` -> (N,) bool validity bitmap. `tile` stays at
    the 128-lane default on hardware; tests shrink it (with interpret
    mode) to keep the differential cheap."""
    n = pk_b.shape[1]
    assert n % tile == 0, n
    _, uniq, _ = _closed("tile", tile)
    grid = (n // tile,)
    ok = pl.pallas_call(
        _make_kernel("tile", tile, 3),
        grid=grid,
        in_specs=[
            _batch_spec((32, tile)),
            _batch_spec((64, tile)),
            _batch_spec((64, tile)),
            *[_const_spec(c) for c in uniq],
        ],
        out_specs=_batch_spec((1, tile)),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int32),
        interpret=interpret,
    )(
        pk_b.astype(jnp.int32),
        sig_b.astype(jnp.int32),
        dig_b.astype(jnp.int32),
        *[jnp.asarray(c) for c in uniq],
    )
    return ok[0] != 0


@functools.partial(jax.jit, static_argnames=("interpret", "tile"))
def dual_mult_pallas(A, dS, dk, interpret: bool = False, tile: int = TILE):
    """[S]B - [k]A as a Pallas kernel. A (4, L, N) extended point,
    dS/dk (64, N) int32 radix-16 digits in [0, 15] -> (3, L, N) T-less
    projective stack (same contract as dual_mult_sb_minus_ka)."""
    from . import field25519 as F

    n = A.shape[-1]
    assert n % tile == 0, n
    _, uniq, _ = _closed("dual", tile)
    grid = (n // tile,)
    return pl.pallas_call(
        _make_kernel("dual", tile, 3),
        grid=grid,
        in_specs=[
            _batch_spec((4, F.NLIMBS, tile)),
            _batch_spec((64, tile)),
            _batch_spec((64, tile)),
            *[_const_spec(c) for c in uniq],
        ],
        out_specs=_batch_spec((3, F.NLIMBS, tile)),
        out_shape=jax.ShapeDtypeStruct((3, F.NLIMBS, n), jnp.int32),
        interpret=interpret,
    )(A, dS, dk, *[jnp.asarray(c) for c in uniq])


@functools.partial(jax.jit, static_argnames=("interpret", "tile"))
def verify_hybrid(pk_b, sig_b, dig_b, interpret: bool = False, tile: int = TILE):
    """The segmented program: XLA for decompression/scalar prep/compare,
    the Pallas dual-mult kernel for the 64-window scalar multiplication.
    Same signature and semantics as verify_pallas."""
    from . import ed25519_kernel as K

    dual = functools.partial(dual_mult_pallas, interpret=interpret, tile=tile)
    return K._verify_tile(pk_b, sig_b, dig_b, dual_fn=dual)
