"""Batched SHA-512 as an XLA program (uint32 half-word lanes).

The device hash behind ed25519's k = SHA512(R || A || M) scalar prep
(reference: the one-call verify boundary crypto/ed25519/ed25519.go:202-237
hides this inside curve25519-voi) — with it, the host side of a batch
verify is byte joins only (ops/ed25519_kernel.py dispatch).

TPUs have no 64-bit integer units, so every 64-bit word is an
(hi, lo) pair of uint32 planes: arrays carry an extra axis of size 2
right before the batch axis ((16, 2, N) blocks, (8, 2, N) states).
Rotations split across the halves at trace time (constant shift
counts); additions ripple one carry from lo to hi. Rounds and schedule
are lax.scan loops over a ~40-op body, matching the sha256 kernel's
compile-size strategy (ops/sha256_kernel.py).

Fixed message lengths compile one program per (length, batch-bucket):
padding is laid out at trace time. Callers group variable-length
batches by length (the ed25519 verifier does).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

__all__ = ["sha512_fixed"]

_K64 = [
    0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F,
    0xE9B5DBA58189DBBC, 0x3956C25BF348B538, 0x59F111F1B605D019,
    0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118, 0xD807AA98A3030242,
    0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
    0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235,
    0xC19BF174CF692694, 0xE49B69C19EF14AD2, 0xEFBE4786384F25E3,
    0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65, 0x2DE92C6F592B0275,
    0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
    0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F,
    0xBF597FC7BEEF0EE4, 0xC6E00BF33DA88FC2, 0xD5A79147930AA725,
    0x06CA6351E003826F, 0x142929670A0E6E70, 0x27B70A8546D22FFC,
    0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
    0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6,
    0x92722C851482353B, 0xA2BFE8A14CF10364, 0xA81A664BBC423001,
    0xC24B8B70D0F89791, 0xC76C51A30654BE30, 0xD192E819D6EF5218,
    0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
    0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99,
    0x34B0BCB5E19B48A8, 0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB,
    0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3, 0x748F82EE5DEFB2FC,
    0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
    0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915,
    0xC67178F2E372532B, 0xCA273ECEEA26619C, 0xD186B8C721C0C207,
    0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178, 0x06F067AA72176FBA,
    0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
    0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC,
    0x431D67C49C100D4C, 0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A,
    0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
]
# (80, 2) -> hi/lo planes
_K = np.array(
    [[(k >> 32) & 0xFFFFFFFF, k & 0xFFFFFFFF] for k in _K64],
    dtype=np.uint32,
)

_H0_64 = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B,
    0xA54FF53A5F1D36F1, 0x510E527FADE682D1, 0x9B05688C2B3E6C1F,
    0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]
_H0 = np.array(
    [[(h >> 32) & 0xFFFFFFFF, h & 0xFFFFFFFF] for h in _H0_64],
    dtype=np.uint32,
)


def _rotr(w: jnp.ndarray, n: int) -> jnp.ndarray:
    """Rotate-right of (..., 2, N) uint32 hi/lo pairs by constant n."""
    hi = w[..., 0, :]
    lo = w[..., 1, :]
    if n == 32:
        return jnp.stack([lo, hi], axis=-2)
    if n > 32:
        hi, lo = lo, hi
        n -= 32
    h = (hi >> np.uint32(n)) | (lo << np.uint32(32 - n))
    l = (lo >> np.uint32(n)) | (hi << np.uint32(32 - n))
    return jnp.stack([h, l], axis=-2)


def _shr(w: jnp.ndarray, n: int) -> jnp.ndarray:
    """Logical right shift of hi/lo pairs by constant n < 32."""
    hi = w[..., 0, :]
    lo = w[..., 1, :]
    h = hi >> np.uint32(n)
    l = (lo >> np.uint32(n)) | (hi << np.uint32(32 - n))
    return jnp.stack([h, l], axis=-2)


def _add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """64-bit add of hi/lo pairs (uint32 wrap + one carry ripple)."""
    lo = a[..., 1, :] + b[..., 1, :]
    carry = (lo < a[..., 1, :]).astype(jnp.uint32)
    hi = a[..., 0, :] + b[..., 0, :] + carry
    return jnp.stack([hi, lo], axis=-2)


def _compress(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """One SHA-512 compression: state (8, 2, N), block (16, 2, N).

    Two trace-time forms, chosen by backend:

    - TPU: fully unrolled (Python loops, ~4.5k vector ops). A lax.scan
      body this small serializes 144 tiny device loops XLA cannot fuse
      across — measured at 8192 lanes the scan form cost ~24% of total
      ed25519 verify throughput; unrolled it fuses into a handful of
      kernels and disappears into the noise.
    - CPU: the scan form. The CPU backend compiles the unrolled chain
      in ~2-4 s per (length, bucket) program, which multiplies across
      the test suite's many message lengths; the scan compiles the
      ~40-op body once and CPU throughput is not the target."""
    import jax

    if jax.default_backend() != "tpu":
        return _compress_scan(state, block)
    w = [block[i] for i in range(16)]
    for t in range(16, 80):
        w15 = w[t - 15]
        w2 = w[t - 2]
        s0 = _rotr(w15, 1) ^ _rotr(w15, 8) ^ _shr(w15, 7)
        s1 = _rotr(w2, 19) ^ _rotr(w2, 61) ^ _shr(w2, 6)
        w.append(_add(_add(w[t - 16], s0), _add(w[t - 7], s1)))

    n = state.shape[-1]
    a, b, c, d, e, f, g, h = (state[i] for i in range(8))
    for t in range(80):
        kt = jnp.broadcast_to(jnp.asarray(_K[t])[:, None], (2, n))
        s1 = _rotr(e, 14) ^ _rotr(e, 18) ^ _rotr(e, 41)
        ch = (e & f) ^ (~e & g)
        t1 = _add(_add(h, s1), _add(ch, _add(kt, w[t])))
        s0 = _rotr(a, 28) ^ _rotr(a, 34) ^ _rotr(a, 39)
        maj = (a & b) ^ (a & c) ^ (b & c)
        h, g, f, e, d, c, b, a = (
            g, f, e, _add(d, t1), c, b, a, _add(t1, _add(s0, maj)),
        )
    out = jnp.stack([a, b, c, d, e, f, g, h], axis=0)
    return jnp.stack(
        [_add(state[i], out[i]) for i in range(8)], axis=0
    )


def _compress_scan(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """Scan-form compression (see _compress): one ~40-op body, 144
    sequential steps. Compile-cheap; serialization-bound on TPU."""

    def sched_body(last16, _):
        w15 = last16[1]
        w2 = last16[14]
        s0 = _rotr(w15, 1) ^ _rotr(w15, 8) ^ _shr(w15, 7)
        s1 = _rotr(w2, 19) ^ _rotr(w2, 61) ^ _shr(w2, 6)
        wt = _add(_add(last16[0], s0), _add(last16[9], s1))
        return jnp.concatenate([last16[1:], wt[None]], axis=0), wt

    _, w_ext = lax.scan(sched_body, block, None, length=64)
    w_all = jnp.concatenate([block, w_ext], axis=0)  # (80, 2, N)

    n = state.shape[-1]
    k_bcast = jnp.broadcast_to(
        jnp.asarray(_K)[:, :, None], (80, 2, n)
    )

    def round_body(st, xs):
        wt, kt = xs
        a, b, c, d, e, f, g, h = (st[i] for i in range(8))
        s1 = _rotr(e, 14) ^ _rotr(e, 18) ^ _rotr(e, 41)
        ch = (e & f) ^ (~e & g)
        t1 = _add(_add(h, s1), _add(ch, _add(kt, wt)))
        s0 = _rotr(a, 28) ^ _rotr(a, 34) ^ _rotr(a, 39)
        maj = (a & b) ^ (a & c) ^ (b & c)
        return jnp.stack(
            [_add(t1, _add(s0, maj)), a, b, c, _add(d, t1), e, f, g],
            axis=0,
        ), None

    out, _ = lax.scan(round_body, state, (w_all, k_bcast))
    return jnp.stack(
        [_add(state[i], out[i]) for i in range(8)], axis=0
    )


def sha512_fixed(data: jnp.ndarray) -> jnp.ndarray:
    """SHA-512 of N equal-length messages: (L, N) uint8 -> (64, N).

    L is static: merkle-damgard padding (0x80, zeros, 128-bit bit
    length) is laid out at trace time."""
    length, n = data.shape
    bitlen = length * 8
    nblocks = (length + 17 + 127) // 128
    padded_len = nblocks * 128
    pad_rows = [jnp.full((1, n), 0x80, dtype=jnp.uint8)]
    zeros = padded_len - length - 1 - 8
    if zeros:
        # the upper 8 of the 16 length bytes are always zero here
        # (messages < 2^61 bytes), so they fold into the zero run
        pad_rows.append(jnp.zeros((zeros, n), dtype=jnp.uint8))
    len_bytes = np.array(
        [(bitlen >> (8 * (7 - i))) & 0xFF for i in range(8)],
        dtype=np.uint8,
    )
    pad_rows.append(
        jnp.broadcast_to(jnp.asarray(len_bytes)[:, None], (8, n))
    )
    full = jnp.concatenate([data.astype(jnp.uint8)] + pad_rows, axis=0)
    full = full.astype(jnp.uint32)
    # (nblocks, 16, 2, N): big-endian bytes -> hi/lo uint32 planes
    octets = full.reshape(nblocks, 16, 2, 4, n)
    words = (
        (octets[..., 0, :] << np.uint32(24))
        | (octets[..., 1, :] << np.uint32(16))
        | (octets[..., 2, :] << np.uint32(8))
        | octets[..., 3, :]
    )
    state = jnp.broadcast_to(
        jnp.asarray(_H0)[:, :, None], (8, 2, n)
    ).astype(jnp.uint32)
    for b in range(nblocks):
        state = _compress(state, words[b])
    # big-endian unpack: (8, 2, N) words -> (64, N) bytes
    shifts = np.array([24, 16, 8, 0], dtype=np.uint32)
    out = (state[:, :, None, :] >> jnp.asarray(shifts)[None, None, :, None]) & np.uint32(0xFF)
    return out.reshape(64, n).astype(jnp.uint8)
