"""Node configuration.

Mirrors the reference's master Config struct and sections (reference:
config/config.go:61-74 — Base, RPC, P2P, Mempool, StateSync, Consensus,
TxIndex, Instrumentation, PrivValidator) with TOML persistence via stdlib
tomllib for reads and a template writer for `init`.

Consensus timeouts follow config/config.go:923-939 (propose/prevote/
precommit + deltas, timeout-commit).
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Optional

__all__ = [
    "Config",
    "BaseConfig",
    "RPCConfig",
    "P2PConfig",
    "MempoolConfig",
    "StateSyncConfig",
    "BlockSyncConfig",
    "ConsensusConfig",
    "TxIndexConfig",
    "InstrumentationConfig",
    "PrivValidatorConfig",
    "TPUConfig",
    "load_config",
    "write_config",
]

MODE_VALIDATOR = "validator"
MODE_FULL = "full"
MODE_SEED = "seed"

# Canonical device-batch bucket sizes: the single source both curves'
# verifiers follow (ops.ed25519_kernel re-exports this as
# DEFAULT_BUCKET_SIZES; config.py owns it because it must stay
# importable without jax).
# 12288 exists for the 10k-validator commit config (BASELINE 5): padding
# 10k sigs to 16384 wastes 39% of the device program; 12288 = 96 * 128
# stays Pallas-tile aligned and cuts that to 18%.
DEFAULT_BUCKET_SIZES = (8, 32, 128, 512, 2048, 8192, 12288, 16384)


@dataclass
class BaseConfig:
    chain_id: str = ""
    moniker: str = "anonymous"
    mode: str = MODE_VALIDATOR
    home: str = "~/.tendermint_tpu"
    # sqlite | memdb. A deliberate cut from the reference's five
    # backends (config.go:179-197 goleveldb/cleveldb/boltdb/rocksdb/
    # badgerdb, all ordered KV stores behind tm-db): sqlite is the
    # embedded on-disk default (store/kv.py SqliteKV implements the
    # same ordered-KV contract), memdb serves tests/ephemeral nodes.
    # Another engine is one KVStore subclass away — register it with
    # store.kv.register_backend(name, factory) before node start and
    # set this knob to that name; nothing above store/kv.py knows
    # which engine is underneath ("goleveldb"/"default" alias to
    # sqlite so reference config.toml files work unchanged).
    db_backend: str = "sqlite"  # sqlite | memdb | registered name
    db_dir: str = "data"
    log_level: str = "info"
    log_format: str = "plain"
    genesis_file: str = "config/genesis.json"
    node_key_file: str = "config/node_key.json"
    abci: str = "builtin"  # builtin | socket | grpc
    proxy_app: str = "kvstore"

    def root(self) -> str:
        return os.path.expanduser(self.home)

    def path(self, rel: str) -> str:
        return os.path.join(self.root(), rel)


@dataclass
class PrivValidatorConfig:
    key_file: str = "config/priv_validator_key.json"
    state_file: str = "data/priv_validator_state.json"
    listen_addr: str = ""  # non-empty => remote signer


@dataclass
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    max_subscriptions_per_client: int = 5
    timeout_broadcast_tx_commit: float = 10.0
    max_body_bytes: int = 1_000_000
    # per-block serving cache (rpc/servingcache.py): LRU capacity in
    # blocks for each artifact family (encoded LightBlock blobs, held
    # tx-proof merkle trees); 0 disables the cache for this node
    serving_cache_blocks: int = 64


@dataclass
class P2PConfig:
    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    persistent_peers: str = ""
    bootstrap_peers: str = ""
    max_connections: int = 64
    max_incoming_connection_attempts: int = 100
    send_rate: int = 5_120_000
    recv_rate: int = 5_120_000
    pex: bool = True
    handshake_timeout: float = 20.0
    dial_timeout: float = 3.0
    queue_type: str = "priority"  # fifo | priority
    # jittered capped exponential dial backoff (peermanager)
    min_retry_time: float = 0.25
    max_retry_time: float = 600.0
    max_retry_time_persistent: float = 20.0
    # keepalive liveness (router; any received traffic counts)
    ping_interval: float = 30.0
    pong_timeout: float = 15.0
    # slow-peer shedding: this many send-queue drops inside the window
    # evicts the peer with reason slow_peer and bans it for the sit-out
    slow_peer_drop_threshold: int = 64
    slow_peer_window: float = 10.0
    slow_peer_ban: float = 30.0


@dataclass
class MempoolConfig:
    recheck: bool = True
    broadcast: bool = True
    size: int = 5000
    max_txs_bytes: int = 1 << 30
    cache_size: int = 10000
    keep_invalid_txs_in_cache: bool = False
    max_tx_bytes: int = 1 << 20
    ttl_duration: float = 0.0  # seconds; 0 = no TTL
    ttl_num_blocks: int = 0
    # Admission shards: CheckTx takes only its tx-key-hashed shard's
    # lock, so concurrent admissions on different shards overlap their
    # app round-trips instead of convoying behind one pool-wide lock.
    # Consensus's lock() is an epoch barrier across every shard, so the
    # Commit+Update exclusion is unchanged. 1 = the pre-shard layout.
    shards: int = 8
    # Max txs bundled into one gossip envelope / one batched admission
    # call (broadcast_tx ingestion and post-commit recheck reuse it as
    # the ABCI pipelining grain).
    tx_batch_size: int = 64


@dataclass
class StateSyncConfig:
    enable: bool = False
    rpc_servers: list[str] = field(default_factory=list)
    trust_height: int = 0
    trust_hash: str = ""
    trust_period: float = 168 * 3600.0
    discovery_time: float = 15.0
    chunk_request_timeout: float = 15.0
    fetchers: int = 4


@dataclass
class BlockSyncConfig:
    enable: bool = True


@dataclass
class ConsensusConfig:
    wal_file: str = "data/cs.wal/wal"
    # Reference defaults, config/config.go:923-939 (milliseconds there).
    timeout_propose: float = 3.0
    timeout_propose_delta: float = 0.5
    timeout_prevote: float = 1.0
    timeout_prevote_delta: float = 0.5
    timeout_precommit: float = 1.0
    timeout_precommit_delta: float = 0.5
    timeout_commit: float = 1.0
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval: float = 0.0
    peer_gossip_sleep_duration: float = 0.1
    # Block-part gossip window: how many missing parts one data-gossip
    # iteration may burst to a peer before sleeping. Sends beyond the
    # first use try_send, so a slow peer's full send queue sheds the
    # rest of the window (backpressure) instead of stalling the routine.
    peer_gossip_part_window: int = 16
    peer_query_maj23_sleep_duration: float = 2.0
    double_sign_check_height: int = 0

    def propose_timeout(self, round_: int) -> float:
        return self.timeout_propose + self.timeout_propose_delta * round_

    def prevote_timeout(self, round_: int) -> float:
        return self.timeout_prevote + self.timeout_prevote_delta * round_

    def precommit_timeout(self, round_: int) -> float:
        return self.timeout_precommit + self.timeout_precommit_delta * round_


@dataclass
class TxIndexConfig:
    # kv | null | psql (reference: config/config.go TxIndexConfig +
    # the psql sink under internal/state/indexer/sink/psql)
    indexer: list[str] = field(default_factory=lambda: ["kv"])
    # DSN for the "psql" sink: sqlite:<path>, sqlite::memory:, or
    # postgres://... (needs psycopg). Empty = sqlite file in the data dir.
    psql_conn: str = ""


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    namespace: str = "tendermint_tpu"
    # span tracing (libs/trace.py): record the commit-verification
    # pipeline into the in-memory ring, exportable as Chrome-trace JSON
    # via the debug bundle. Off by default — the disabled path is a
    # no-op. Process-wide switch (the ring is shared).
    trace_spans: bool = False
    trace_ring_capacity: int = 8192
    # slow-request exemplars (libs/trace.py): requests exceeding their
    # per-route SLO (rpc/metrics.py slo_for) capture their span tree
    # into a second bounded ring, exported in the debug bundle as
    # slow_requests.json. Off by default; process-wide like the ring.
    slo_exemplars: bool = False
    slo_exemplar_capacity: int = 64
    # consensus flight recorder (consensus/timeline.py): bounded
    # per-node ring of height/round events (step transitions,
    # threshold crossings, timeouts, gossip stall-resets), served by
    # the consensus_timeline RPC route and the debug bundle. ON by
    # default — like the WAL it earns its keep post-mortem; the
    # disabled path is one attribute check per step transition.
    consensus_timeline: bool = True
    consensus_timeline_capacity: int = 4096
    # wall-clock sampling profiler (libs/profiler.py): daemon sampler
    # over sys._current_frames() with subsystem + asyncio-task
    # attribution, served by the `profile` RPC route, the debug
    # bundle (profile.json) and the tmload bottleneck ledger. Off by
    # default — sampling costs ~1-3% wall at the default 97 Hz;
    # task-label *arming* (profiler_labels) is on so a profile
    # started mid-run over RPC still sees long-lived pumps' origins
    # (one attribute write per task spawn).
    profiler: bool = False
    profiler_hz: float = 97.0
    profiler_max_stacks: int = 2048
    profiler_labels: bool = True


@dataclass
class TPUConfig:
    """Device-offload knobs — no analog in the reference; this gates the
    TPU-backed BatchVerifier and merkle kernels (the north-star seam,
    reference: crypto/crypto.go:53-61)."""

    enable: bool = True
    min_batch_size: int = 8  # below this, CPU single-verify wins
    bucket_sizes: list[int] = field(
        default_factory=lambda: list(DEFAULT_BUCKET_SIZES)
    )
    donate_buffers: bool = True
    # devices > 1 shards signature batches over a data-parallel
    # jax.sharding.Mesh of that many devices (tendermint_tpu.parallel);
    # 0 = every visible device, 1 = single chip (no mesh)
    devices: int = 1


@dataclass
class Config:
    base: BaseConfig = field(default_factory=BaseConfig)
    priv_validator: PrivValidatorConfig = field(default_factory=PrivValidatorConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    blocksync: BlockSyncConfig = field(default_factory=BlockSyncConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = field(
        default_factory=InstrumentationConfig
    )
    tpu: TPUConfig = field(default_factory=TPUConfig)

    def ensure_dirs(self) -> None:
        root = self.base.root()
        for sub in ("config", "data", os.path.dirname(self.consensus.wal_file)):
            os.makedirs(os.path.join(root, sub), exist_ok=True)


_SECTIONS = {
    "base": BaseConfig,
    "priv_validator": PrivValidatorConfig,
    "rpc": RPCConfig,
    "p2p": P2PConfig,
    "mempool": MempoolConfig,
    "statesync": StateSyncConfig,
    "blocksync": BlockSyncConfig,
    "consensus": ConsensusConfig,
    "tx_index": TxIndexConfig,
    "instrumentation": InstrumentationConfig,
    "tpu": TPUConfig,
}


def _parse_toml_value(val: str):
    """One scalar/list/inline-table value of the supported subset.
    Raises ValueError on anything else."""
    import ast

    if val == "true":
        return True
    if val == "false":
        return False
    if val.startswith("{") and val.endswith("}"):
        # inline table of scalars (e2e manifests: {double-prevote = 3})
        out = {}
        inner = val[1:-1].strip()
        if inner:
            for pair in inner.split(","):
                k, eq, v = pair.partition("=")
                if not eq:
                    raise ValueError(f"bad inline table entry: {pair!r}")
                out[k.strip()] = _parse_toml_value(v.strip())
        return out
    try:
        # numbers, quoted strings (same escapes our writers emit),
        # and flat lists thereof
        return ast.literal_eval(val)
    except (ValueError, SyntaxError) as e:
        raise ValueError(f"unsupported TOML value: {val!r}") from e


def _parse_toml_subset(text: str) -> dict:
    """Fallback parser for the TOML subset our own writers emit
    (write_config, e2e manifests: sections incl. dotted names;
    bool/number/string/flat-list/inline-table values) — Python < 3.11
    ships no tomllib, and the container may not carry tomli."""
    raw: dict = {}
    cur: dict = raw  # keys before any [section] are document-root keys
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("#"):
            continue
        if '"' not in line:
            # trailing comments are only safe to strip when no string
            # value could contain the '#'
            line = line.split("#", 1)[0].strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            cur = raw
            for part in line[1:-1].strip().split("."):
                cur = cur.setdefault(part.strip(), {})
            continue
        if "=" not in line:
            # tomllib rejects junk lines; silently skipping would let a
            # typo'd setting fall back to its default with no error
            raise ValueError(f"unparseable TOML line: {line!r}")
        key, _, val = line.partition("=")
        cur[key.strip()] = _parse_toml_value(val.strip())
    return raw


def load_config(path: str) -> Config:
    try:
        import tomllib
    except ImportError:
        tomllib = None

    if tomllib is not None:
        with open(path, "rb") as f:
            raw = tomllib.load(f)
    else:
        with open(path, encoding="utf-8") as f:
            raw = _parse_toml_subset(f.read())
    cfg = Config()
    for section, cls in _SECTIONS.items():
        data = raw.get(section, {})
        known = {f.name for f in dataclasses.fields(cls)}
        setattr(
            cfg, section, cls(**{k: v for k, v in data.items() if k in known})
        )
    return cfg


def _toml_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, list):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    raise TypeError(f"unsupported TOML value: {v!r}")


def write_config(cfg: Config, path: str) -> None:
    lines = ["# tendermint-tpu node configuration", ""]
    for section in _SECTIONS:
        obj = getattr(cfg, section)
        lines.append(f"[{section}]")
        for f in dataclasses.fields(obj):
            lines.append(f"{f.name} = {_toml_value(getattr(obj, f.name))}")
        lines.append("")
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as fh:
        fh.write("\n".join(lines))
