"""Run orchestration: scenario → live net → report.

`run_scenario` drives an already-running net (any list of RPC
addresses; pass the Node objects too and the scraper samples their
registries mid-run). `run_localnet_scenario` is the batteries-included
entry: boot an in-process N-validator localnet, run the scenario,
tear down, return the report — what bench.py's `load_smoke` row and
the tier-1 smoke test call.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional, Sequence

from .driver import (
    ClientPool,
    SubscriberPool,
    run_closed_loop,
    run_open_loop,
)
from ..libs import profiler
from .localnet import start_localnet
from .profilemerge import build_ledger, capture_profile
from .report import build_report
from .scenario import Scenario
from .scrape import Scraper

__all__ = ["run_scenario", "run_localnet_scenario"]


async def run_scenario(
    scn: Scenario,
    rpc_addrs: Sequence[str],
    nodes: Optional[Sequence[object]] = None,
) -> dict:
    """Apply `scn` against live RPC endpoints and return the report.

    Phases: subscribers connect → warmup traffic (unmeasured) →
    measured window with the scrape loop sampling each node's registry
    → teardown → merged report."""
    scn.validate()
    if not rpc_addrs:
        raise ValueError("need at least one RPC address")
    per_pool = max(1, scn.max_inflight // len(rpc_addrs))
    pools = [
        ClientPool(addr, size=per_pool, timeout_s=scn.timeout_s)
        for addr in rpc_addrs
    ]
    subs = SubscriberPool(scn, rpc_addrs)
    scraper = (
        Scraper(nodes, interval_s=scn.scrape_interval_s)
        if nodes
        else None
    )
    scrape_task = None
    stop = asyncio.Event()
    try:
        await subs.start()
        if scn.warmup_s > 0:
            warm_stop = asyncio.Event()
            warm = asyncio.ensure_future(
                run_closed_loop(
                    scn.with_(concurrency=min(scn.concurrency, 2)),
                    pools,
                    warm_stop,
                    stream_base=1_000_000,  # disjoint from measured keys
                )
            )
            await asyncio.sleep(scn.warmup_s)
            warm_stop.set()
            await warm

        scrape_task = (
            asyncio.ensure_future(scraper.run(stop))
            if scraper is not None
            else None
        )
        # profiling plane (libs/profiler.py): a subsystem-count
        # reading at window start isolates the measured window's
        # samples from warmup/boot for the bottleneck ledger
        profiler_counts_before = (
            profiler.subsystem_counts() if profiler.is_enabled() else None
        )
        t0 = time.perf_counter()
        scheduled = 0
        if scn.mode == "open":
            stats, scheduled = await run_open_loop(scn, pools)
        else:
            stopper = asyncio.get_event_loop().call_later(
                scn.duration_s, stop.set
            )
            stats = await run_closed_loop(scn, pools, stop)
            stopper.cancel()
        wall = time.perf_counter() - t0
        held = subs.held()
        stop.set()
        if scrape_task is not None:
            await scrape_task
            scrape_task = None
        _, events = await subs.stop()
        # consensus decomposition from the fleet's flight recorders
        # (in-process nodes only): a slow broadcast_tx_commit p99 is
        # either consensus-side — visible here as proposal->polka /
        # polka->quorum / commit-spread stages — or serving-side,
        # visible in the per-route sketches (docs/observability.md)
        tl_summary = None
        if nodes:
            from . import timeline as fleet_timeline

            try:
                tl_summary = fleet_timeline.fleet_summary(
                    fleet_timeline.collect(nodes)
                )
            except Exception:
                tl_summary = None  # recorder disabled / foreign nodes
        # bottleneck ledger: profiler shares ⋈ scraper saturation ⋈
        # flight-recorder split (loadgen/profilemerge.py)
        profile_doc = ledger = None
        if profiler.is_enabled():
            profile_doc = capture_profile(profiler_counts_before)
            ledger = build_ledger(
                profile_doc,
                scraper.saturation() if scraper is not None else {},
                tl_summary,
            )
        return build_report(
            scn,
            stats,
            wall,
            n_nodes=len(rpc_addrs),
            subscribers_connected=subs.connected,
            subscribers_held=held,
            subscriber_events=events,
            scraper=scraper,
            scheduled_arrivals=scheduled,
            timeline=tl_summary,
            profile=profile_doc,
            ledger=ledger,
        )
    finally:
        # unconditional teardown: a driver or scraper exception must
        # not orphan the WS drain tasks / scrape task (asyncio.run
        # would otherwise destroy them pending and bury the real error)
        stop.set()
        if scrape_task is not None:
            scrape_task.cancel()
            await asyncio.gather(scrape_task, return_exceptions=True)
        await subs.stop()
        for p in pools:
            await p.close()


async def run_localnet_scenario(
    scn: Scenario,
    n_nodes: int,
    home: str,
    chain_id: str = "loadnet",
    timeout_commit: float = 0.2,
    profile: bool = False,
) -> dict:
    """Boot an in-process localnet, run the scenario, tear down.
    `profile=True` runs the wall-clock sampler for the whole run and
    banks the bottleneck ledger into the report."""
    net = await start_localnet(
        n_nodes,
        home,
        chain_id=chain_id,
        seed=scn.seed,
        timeout_commit=timeout_commit,
        profiler=profile,
    )
    try:
        return await run_scenario(
            scn, net.rpc_addrs, nodes=net.nodes
        )
    finally:
        await net.stop()
