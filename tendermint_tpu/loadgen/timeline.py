"""Fleet timeline merger — the cross-node layer of the flight recorder.

Each node's consensus timeline (consensus/timeline.py) tells one
node's story; this module scrapes ALL localnet nodes' rings, aligns
events by (height, round) on the shared wall clock (one box — the
in-process localnet's standing assumption), and answers the questions
no single ring can:

* **per-height phase attribution** — for every committed height: how
  long from entering the height to the proposal landing (proposer
  lag), how spread the +2/3 prevote / +2/3 precommit crossings were
  across nodes (per-vote-type gossip lag), rounds burned, timeout and
  stall-reset counts, and — when span tracing is on — the verify time
  the height spent in addVote (libs/trace.py span data).
* **recovery phase decomposition** — after a chaos heal instant, the
  TTFC number splits into named phases: heal detection (first
  stall-reset tick), gossip catch-up (first threshold crossing from
  resent votes), first fresh proposal, quorum, commit. Every
  scenario row in BENCH_CHAOS.json carries this artifact
  (loadgen/chaos.py); the tmload report carries the steady-state
  aggregate (loadgen/run.py) so a slow broadcast_tx_commit p99
  decomposes into consensus pipeline stages.

Zero RPC: the collectors read the in-process nodes' rings directly
(the same trust model as chaos.py's store-level safety check). For
process nets, the `consensus_timeline` RPC route serves the same
events page by page.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..consensus.timeline import (
    EV_COMMIT,
    EV_NEW_HEIGHT,
    EV_POLKA,
    EV_PRECOMMIT_QUORUM,
    EV_PREVOTE_ANY,
    EV_PROPOSAL,
    EV_STALL_RESET,
    EV_TIMEOUT,
)

__all__ = [
    "attribute_heights",
    "collect",
    "decompose_recovery",
    "fleet_summary",
    "stall_reset_counts",
    "verify_ms_by_height",
]

# the threshold-crossing kinds resent gossip re-assembles first after
# a heal — the "gossip catch-up" phase marker
_CROSSINGS = (EV_PREVOTE_ANY, EV_POLKA, EV_PRECOMMIT_QUORUM)


def collect(ln_or_nodes) -> Dict[str, List[dict]]:
    """Scrape every localnet node's timeline ring: moniker -> event
    dicts, oldest first. Accepts a Localnet or any sequence of Node
    objects (a crash-restarted node contributes its fresh ring — the
    pre-crash one died with the instance; its WAL still has the
    history, see scripts/timeline_replay.py)."""
    nodes = getattr(ln_or_nodes, "nodes", ln_or_nodes)
    out: Dict[str, List[dict]] = {}
    for i, node in enumerate(nodes):
        base = getattr(getattr(node, "cfg", None), "base", None)
        label = base.moniker if base is not None else f"node{i}"
        out[label] = [
            e.to_dict() for e in node.consensus.timeline.snapshot()
        ]
    return out


def verify_ms_by_height() -> Dict[int, float]:
    """Total addVote span time per height from the PROCESS-GLOBAL
    trace ring (libs/trace.py) — the fleet's verify time per height
    when span tracing is enabled, empty otherwise. Process-global:
    in-process localnet nodes share the ring, so this is a fleet
    total, not per-node."""
    from ..libs import trace

    out: Dict[int, float] = {}
    for s in trace.snapshot():
        if s.name == "addVote":
            h = s.attrs.get("height")
            if isinstance(h, int):
                out[h] = out.get(h, 0.0) + s.dur_us / 1000.0
    return out


def _first(evs: List[dict], kind: str) -> Optional[int]:
    ts = [e["t_wall_ns"] for e in evs if e["kind"] == kind]
    return min(ts) if ts else None


def _last(evs: List[dict], kind: str) -> Optional[int]:
    ts = [e["t_wall_ns"] for e in evs if e["kind"] == kind]
    return max(ts) if ts else None


def _ms(a: Optional[int], b: Optional[int]) -> Optional[float]:
    if a is None or b is None:
        return None
    return round((b - a) / 1e6, 3)


def stall_reset_counts(
    fleet: Dict[str, List[dict]], after_wall_ns: int = 0
) -> Dict[str, int]:
    """Fleet-wide stall-reset tick counts by reset kind (catchup /
    live / last_commit), optionally only after a cut instant."""
    out: Dict[str, int] = {}
    for evs in fleet.values():
        for e in evs:
            if (
                e["kind"] == EV_STALL_RESET
                and e["t_wall_ns"] > after_wall_ns
            ):
                k = e.get("reset", "unknown")
                out[k] = out.get(k, 0) + 1
    return out


def attribute_heights(
    fleet: Dict[str, List[dict]],
    verify_ms: Optional[Dict[int, float]] = None,
) -> List[dict]:
    """Per-height phase attribution across the fleet: one row per
    height ANY node committed, from the merged event streams. Wall
    clocks align because the fleet shares one box (module doc)."""
    if verify_ms is None:
        verify_ms = verify_ms_by_height()
    by_height: Dict[int, List[dict]] = {}
    committed: Dict[int, List[int]] = {}
    for node, evs in fleet.items():
        for e in evs:
            h = e["height"]
            by_height.setdefault(h, []).append(e)
            if e["kind"] == EV_COMMIT:
                committed.setdefault(h, []).append(e["t_wall_ns"])
    rows: List[dict] = []
    for h in sorted(committed):
        evs = by_height[h]
        first_enter = _first(evs, EV_NEW_HEIGHT)
        first_proposal = _first(evs, EV_PROPOSAL)
        commits = committed[h]
        row = {
            "height": h,
            "nodes_committed": len(commits),
            "rounds_burned": max(e["round"] for e in evs),
            # entering the height -> the (first copy of the) proposal
            # landing anywhere: block creation + first gossip hop
            "proposer_lag_ms": _ms(first_enter, first_proposal),
            # crossing spread across nodes = how long gossip took to
            # carry each vote type's quorum fleet-wide
            "prevote_gossip_lag_ms": _ms(
                _first(evs, EV_POLKA), _last(evs, EV_POLKA)
            ),
            "precommit_gossip_lag_ms": _ms(
                _first(evs, EV_PRECOMMIT_QUORUM),
                _last(evs, EV_PRECOMMIT_QUORUM),
            ),
            "proposal_to_polka_ms": _ms(
                first_proposal, _first(evs, EV_POLKA)
            ),
            "polka_to_quorum_ms": _ms(
                _first(evs, EV_POLKA),
                _first(evs, EV_PRECOMMIT_QUORUM),
            ),
            "commit_spread_ms": _ms(min(commits), max(commits)),
            "timeouts": sum(
                1
                for e in evs
                if e["kind"] == EV_TIMEOUT
                and e.get("step") != "RoundStepNewHeight"
            ),
            "stall_resets": sum(
                1 for e in evs if e["kind"] == EV_STALL_RESET
            ),
        }
        if h in verify_ms:
            row["verify_ms"] = round(verify_ms[h], 3)
        rows.append(row)
    return rows


def decompose_recovery(
    fleet: Dict[str, List[dict]],
    heal_wall_ns: int,
    heal_height: int,
) -> dict:
    """Split a chaos scenario's time-to-first-commit-after-heal into
    named phases, all seconds since the heal instant:

      heal_detection_s   first stall-reset tick after heal (the
                         wedge-save firing; None = no reset needed)
      gossip_catchup_s   first +2/3 threshold crossing anywhere (the
                         resent votes re-assembling a quorum)
      first_proposal_s   first proposal for FRESH work (height past
                         the heal-instant network height)
      quorum_s           first +2/3 precommit on that fresh work
      commit_s           the SLOWEST node's first commit past the
                         heal height — the timeline's own TTFC twin

    Phases are fleet-wide minima (first anywhere) except commit_s
    (slowest node — matching the chaos recovery verdict)."""

    def since(t: Optional[int]) -> Optional[float]:
        if t is None:
            return None
        return round((t - heal_wall_ns) / 1e9, 3)

    after = [
        e
        for evs in fleet.values()
        for e in evs
        if e["t_wall_ns"] > heal_wall_ns
    ]
    t_detect = min(
        (
            e["t_wall_ns"]
            for e in after
            if e["kind"] == EV_STALL_RESET
        ),
        default=None,
    )
    t_catchup = min(
        (
            e["t_wall_ns"]
            for e in after
            if e["kind"] in _CROSSINGS
        ),
        default=None,
    )
    t_proposal = min(
        (
            e["t_wall_ns"]
            for e in after
            if e["kind"] == EV_PROPOSAL and e["height"] > heal_height
        ),
        default=None,
    )
    t_quorum = min(
        (
            e["t_wall_ns"]
            for e in after
            if e["kind"] == EV_PRECOMMIT_QUORUM
            and e["height"] > heal_height
        ),
        default=None,
    )
    per_node_commit: List[int] = []
    all_committed = True
    for evs in fleet.values():
        ts = [
            e["t_wall_ns"]
            for e in evs
            if e["kind"] == EV_COMMIT
            and e["height"] > heal_height
            and e["t_wall_ns"] > heal_wall_ns
        ]
        if ts:
            per_node_commit.append(min(ts))
        else:
            all_committed = False
    t_commit = (
        max(per_node_commit)
        if per_node_commit and all_committed
        else None
    )
    return {
        "heal_height": heal_height,
        "phases": {
            "heal_detection_s": since(t_detect),
            "gossip_catchup_s": since(t_catchup),
            "first_proposal_s": since(t_proposal),
            "quorum_s": since(t_quorum),
            "commit_s": since(t_commit),
        },
        "stall_resets_after_heal": stall_reset_counts(
            fleet, heal_wall_ns
        ),
        "stall_resets_total": stall_reset_counts(fleet),
    }


def fleet_summary(fleet: Dict[str, List[dict]]) -> dict:
    """Steady-state aggregate of the per-height attribution — the
    tmload report's consensus decomposition (a slow
    broadcast_tx_commit p99 is either consensus-side, visible here,
    or serving-side, visible in the route sketches)."""
    rows = attribute_heights(fleet)

    def agg(key: str) -> dict:
        vals = [r[key] for r in rows if r.get(key) is not None]
        if not vals:
            return {"mean_ms": None, "max_ms": None}
        return {
            "mean_ms": round(sum(vals) / len(vals), 3),
            "max_ms": round(max(vals), 3),
        }

    return {
        "heights_attributed": len(rows),
        "events_total": sum(len(v) for v in fleet.values()),
        "rounds_burned_total": sum(r["rounds_burned"] for r in rows),
        "timeouts_total": sum(r["timeouts"] for r in rows),
        "stall_resets": stall_reset_counts(fleet),
        "proposer_lag": agg("proposer_lag_ms"),
        "proposal_to_polka": agg("proposal_to_polka_ms"),
        "polka_to_quorum": agg("polka_to_quorum_ms"),
        "prevote_gossip_lag": agg("prevote_gossip_lag_ms"),
        "precommit_gossip_lag": agg("precommit_gossip_lag_ms"),
        "commit_spread": agg("commit_spread_ms"),
    }
