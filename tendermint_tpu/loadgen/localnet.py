"""In-process multi-validator localnet with live RPC listeners.

The harness target: N validator Nodes over a MemoryNetwork (the e2e
runner's transport), each with a REAL TCP JSON-RPC listener on an
ephemeral 127.0.0.1 port — load flows over actual HTTP/websocket so the
per-route metrics recorded in rpc/jsonrpc.py measure the same code path
production traffic takes. The device verifier stays OFF
(`tpu.enable=false`): the load harness must never initialize the jax
backend (bench.py's banked CPU block runs it before the device probe —
a wedged claim hangs backend init), and single-validator-scale commits
never reach the batch threshold anyway.
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass
from typing import List, Optional

from ..config import Config
from ..crypto.ed25519 import PrivKeyEd25519
from ..node import NodeKey, make_node
from ..p2p.transport import MemoryNetwork, MemoryTransport
from ..privval import FilePV
from ..types.genesis import GenesisDoc, GenesisValidator

__all__ = ["Localnet", "start_localnet"]


@dataclass
class Localnet:
    nodes: List[object]
    chain_id: str
    cfgs: List[Config]
    net: MemoryNetwork

    @property
    def rpc_addrs(self) -> List[str]:
        return [
            f"127.0.0.1:{n.rpc_server.bound_port}" for n in self.nodes
        ]

    def monikers(self) -> List[str]:
        """The nodes' net-fault-plane labels — what TM_TPU_PARTITION
        members name (loadgen nodes are load0, load1, ...)."""
        return [c.base.moniker for c in self.cfgs]

    async def wait_for_height(self, height: int, timeout: float = 60.0):
        await asyncio.gather(
            *(
                n.consensus.wait_for_height(height, timeout=timeout)
                for n in self.nodes
            )
        )

    async def restart(self, i: int, start_timeout: float = 60.0):
        """Crash-restart node i in place: tear the old instance down,
        boot a fresh Node from the same home + a fresh memory
        transport. With the default memdb backend the reborn node has
        EMPTY stores (crash with disk loss — it must blocksync-catch-up
        from its peers); with db_backend="sqlite" its stores survive
        like a real SIGKILL'd process. Returns the new node once
        started (NOT once caught up — that is the scenario's recovery
        measurement)."""
        cfg = self.cfgs[i]
        try:
            await self.nodes[i].stop()
        except Exception:
            pass  # a crashed node crashes; the restart is the point
        node = make_node(
            cfg,
            transport=MemoryTransport(self.net, cfg.p2p.laddr),
        )
        await asyncio.wait_for(node.start(), timeout=start_timeout)
        # tmlive: bounded= in-place replacement of slot i — the list
        # stays exactly n_nodes long for the Localnet's lifetime
        self.nodes[i] = node
        return node

    async def stop(self) -> None:
        for n in self.nodes:
            await n.stop()


async def start_localnet(
    n_nodes: int,
    home: str,
    chain_id: str = "loadnet",
    seed: int = 2026,
    timeout_commit: float = 0.2,
    trace_spans: bool = False,
    slo_exemplars: bool = False,
    profiler: bool = False,
    genesis_time_ns: Optional[int] = None,
    db_backend: str = "memdb",
    ping_interval: float = 30.0,
    pong_timeout: float = 15.0,
) -> Localnet:
    """Boot an N-validator in-process net and wait for height 1 on
    every node (traffic against a chain that hasn't committed yet
    measures boot, not serving)."""
    if n_nodes < 1:
        raise ValueError(f"n_nodes must be >= 1: {n_nodes}")
    privs = [
        PrivKeyEd25519.from_seed(
            seed.to_bytes(8, "big") + bytes([i]) * 24
        )
        for i in range(n_nodes)
    ]
    genesis = GenesisDoc(
        chain_id=chain_id,
        genesis_time_ns=(
            genesis_time_ns
            if genesis_time_ns is not None
            else time.time_ns()
        ),
        validators=[
            GenesisValidator(pub_key=p.pub_key(), power=10)
            for p in privs
        ],
    )
    net = MemoryNetwork()
    cfgs = []
    for i, priv in enumerate(privs):
        cfg = Config()
        cfg.base.home = os.path.join(home, f"load{i}")
        cfg.base.chain_id = chain_id
        # the moniker is the node's net-fault-plane label: what
        # TM_TPU_PARTITION members and p2p rule src=/dst= filters name
        cfg.base.moniker = f"load{i}"
        cfg.base.db_backend = db_backend
        cfg.tpu.enable = False  # the jax-free guarantee (module doc)
        cfg.consensus.timeout_propose = 2.0
        cfg.consensus.timeout_prevote = 1.0
        cfg.consensus.timeout_precommit = 1.0
        cfg.consensus.timeout_commit = timeout_commit
        cfg.consensus.peer_gossip_sleep_duration = 0.01
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.laddr = f"load{i}:26656"
        # snappy self-healing for an in-process net: boot dials race
        # node startup and chaos scenarios measure recovery in seconds
        # — a 20 s persistent-peer backoff cap would dominate both
        cfg.p2p.min_retry_time = 0.1
        cfg.p2p.max_retry_time_persistent = 2.0
        cfg.p2p.ping_interval = ping_interval
        cfg.p2p.pong_timeout = pong_timeout
        cfg.instrumentation.trace_spans = trace_spans
        cfg.instrumentation.slo_exemplars = slo_exemplars
        # the sampler is process-wide; the first node to start owns it
        # and stop-and-joins it at teardown (node/node.py _teardown)
        cfg.instrumentation.profiler = profiler and i == 0
        cfg.ensure_dirs()
        genesis.save_as(cfg.base.path(cfg.base.genesis_file))
        FilePV.from_priv_key(
            priv,
            cfg.base.path(cfg.priv_validator.key_file),
            cfg.base.path(cfg.priv_validator.state_file),
        ).save()
        cfgs.append(cfg)
    node_ids = [
        NodeKey.load_or_generate(
            c.base.path(c.base.node_key_file)
        ).node_id
        for c in cfgs
    ]
    for i, cfg in enumerate(cfgs):
        cfg.p2p.persistent_peers = ",".join(
            f"{node_ids[j]}@load{j}:26656"
            for j in range(n_nodes)
            if j != i
        )
    nodes = [
        make_node(
            c, transport=MemoryTransport(net, f"load{i}:26656")
        )
        for i, c in enumerate(cfgs)
    ]
    started = []
    try:
        for n in nodes:
            await n.start()
            started.append(n)
        ln = Localnet(
            nodes=nodes, chain_id=chain_id, cfgs=cfgs, net=net
        )
        # consensus height 2 = block 1 committed and stored everywhere
        # (height 1 is where consensus STARTS — waiting for it returns
        # immediately and load would then measure boot, not serving)
        await ln.wait_for_height(2, timeout=60.0)
        return ln
    except BaseException:
        for n in started:
            try:
                await n.stop()
            except Exception:
                pass
        raise
