"""Fold one run's artifacts into the BENCH_LOAD row.

The row is the operator's first-questions answer sheet: sustained
txs/s (client-observed accepted writes AND chain-committed), per-route
p50/p99/p999 from the merged latency sketches, error/timeout counts,
concurrent subscribers held, and the scrape-derived saturation peaks.
bench.py persists it as BENCH_LOAD.json.
"""

from __future__ import annotations

from typing import Dict, Optional

from .driver import RouteStats
from .scenario import Scenario
from .scrape import Scraper

__all__ = ["build_report"]

_TX_OPS = ("broadcast_tx_sync", "broadcast_tx_async")


def build_report(
    scn: Scenario,
    route_stats: Dict[str, RouteStats],
    wall_s: float,
    n_nodes: int,
    subscribers_connected: int = 0,
    subscribers_held: int = 0,
    subscriber_events: int = 0,
    scraper: Optional[Scraper] = None,
    scheduled_arrivals: int = 0,
    timeline: Optional[dict] = None,
    profile: Optional[dict] = None,
    ledger: Optional[dict] = None,
) -> dict:
    routes = {op: st.to_dict() for op, st in sorted(route_stats.items())}
    total = sum(st.count for st in route_stats.values())
    errors = sum(st.errors for st in route_stats.values())
    timeouts = sum(st.timeouts for st in route_stats.values())
    tx_ok = sum(
        route_stats[op].ok for op in _TX_OPS if op in route_stats
    )
    sat = scraper.saturation() if scraper is not None else {}
    committed = sat.get("consensus_total_txs_delta", 0.0)
    report = {
        "schema": "bench_load/v1",
        "scenario": scn.to_dict(),
        "nodes": n_nodes,
        "wall_s": round(wall_s, 3),
        "requests_total": total,
        "requests_per_s": round(total / wall_s, 2) if wall_s else 0.0,
        "errors_total": errors,
        "timeouts_total": timeouts,
        # client-observed accepted writes per second — the "sustained"
        # number: requests the mempool took, at the offered rate
        "sustained_txs_per_s": (
            round(tx_ok / wall_s, 2) if wall_s else 0.0
        ),
        # chain-side confirmation from the scrape delta (0.0 when the
        # scraper was off): txs that actually landed in blocks
        "committed_txs_per_s": (
            round(committed / wall_s, 2) if wall_s else 0.0
        ),
        "routes": routes,
        "subscribers": {
            "requested": scn.subscribers,
            "connected": subscribers_connected,
            "held": subscribers_held,
            "events_received": subscriber_events,
        },
        "saturation": sat,
    }
    if timeline is not None:
        # the fleet flight-recorder aggregate (loadgen/timeline.py):
        # the consensus half of a slow-commit decomposition
        report["consensus_timeline"] = timeline
    if profile is not None:
        # the profiling plane's raw material (loadgen/profilemerge.py
        # capture_profile): subsystem counts + the hot folded stacks
        report["profile"] = profile
    if ledger is not None:
        # the ranked bottleneck table — profiler shares joined with
        # saturation signals and the consensus-vs-serving split
        report["bottleneck_ledger"] = ledger
    if scn.mode == "open":
        report["scheduled_arrivals"] = scheduled_arrivals
        report["offered_rate_per_s"] = scn.rate
    return report
