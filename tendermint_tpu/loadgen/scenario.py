"""The declarative load-scenario spec.

A Scenario is everything one run needs: the traffic model (open- or
closed-loop), the arrival process, the route mix, the subscriber
count, and ONE seed — `libs/rng.derive(seed, label)` hands every
concern (arrival schedule, op mix, payload bytes) its own independent
stream, so the same Scenario replays the same request sequence.
docs/load.md explains the open-vs-closed distinction and why open-loop
latency is measured from the intended send time.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, Tuple

__all__ = ["OPS", "Scenario"]

# the route vocabulary the driver knows how to exercise: the write
# flood, the read shapes (including the two stateless-client serving
# routes, light_blocks + tx_proofs), and the cheap liveness probe
OPS = (
    "broadcast_tx_sync",
    "broadcast_tx_async",
    "abci_query",
    "block",
    "light_blocks",
    "tx_proofs",
    "status",
)

# a production-ish default: write-heavy with a read tail
DEFAULT_MIX: Tuple[Tuple[str, float], ...] = (
    ("broadcast_tx_sync", 4.0),
    ("abci_query", 2.0),
    ("block", 2.0),
    ("light_blocks", 1.0),
    ("status", 1.0),
)


@dataclass
class Scenario:
    """One reproducible load run.

    mode="closed": `concurrency` workers issue requests back-to-back —
    throughput finds its own level, latency excludes queueing you
    didn't create. mode="open": requests arrive on a seeded schedule
    (`arrival` = "poisson" or "fixed") at `rate`/s (linearly ramped
    over `ramp_s`), and latency is measured from the *intended* arrival
    time — a stalled server keeps accruing latency for requests it
    hasn't absorbed yet (coordinated-omission correction).
    `max_inflight` is the client-side connection budget, not a
    throttle: arrivals past it queue with their intended timestamps
    intact.
    """

    seed: int = 2026
    mode: str = "open"  # "open" | "closed"
    duration_s: float = 10.0
    warmup_s: float = 0.0  # traffic before measurement starts
    # open-loop arrival process
    rate: float = 200.0  # target arrivals/s after the ramp
    ramp_s: float = 0.0  # linear 0 -> rate ramp at run start
    arrival: str = "poisson"  # "poisson" | "fixed"
    max_inflight: int = 64
    # closed-loop shape
    concurrency: int = 8
    # route mix: (op, weight) — weights need not sum to anything
    mix: Tuple[Tuple[str, float], ...] = DEFAULT_MIX
    # websocket subscribers held for the whole run
    subscribers: int = 8
    subscribe_query: str = "tm.event='NewBlock'"
    # per-request client timeout (timeouts are counted, not fatal)
    timeout_s: float = 5.0
    tx_value_bytes: int = 32
    scrape_interval_s: float = 0.5

    def validate(self) -> "Scenario":
        if self.mode not in ("open", "closed"):
            raise ValueError(f"mode must be open|closed: {self.mode!r}")
        if self.arrival not in ("poisson", "fixed"):
            raise ValueError(
                f"arrival must be poisson|fixed: {self.arrival!r}"
            )
        if self.duration_s <= 0:
            raise ValueError(f"duration_s must be > 0: {self.duration_s}")
        if self.mode == "open" and self.rate <= 0:
            raise ValueError(f"open-loop rate must be > 0: {self.rate}")
        if self.mode == "closed" and self.concurrency < 1:
            raise ValueError(
                f"closed-loop concurrency must be >= 1: {self.concurrency}"
            )
        if not self.mix:
            raise ValueError("mix must name at least one op")
        for op, w in self.mix:
            if op not in OPS:
                raise ValueError(f"unknown op {op!r} (known: {OPS})")
            if w <= 0:
                raise ValueError(f"mix weight for {op!r} must be > 0: {w}")
        if self.subscribers < 0 or self.max_inflight < 1:
            raise ValueError("subscribers >= 0, max_inflight >= 1")
        return self

    def with_(self, **kw) -> "Scenario":
        return replace(self, **kw).validate()

    def mix_ops(self) -> Tuple[str, ...]:
        return tuple(op for op, _ in self.mix)

    def to_dict(self) -> Dict:
        return {
            "seed": self.seed,
            "mode": self.mode,
            "duration_s": self.duration_s,
            "warmup_s": self.warmup_s,
            "rate": self.rate,
            "ramp_s": self.ramp_s,
            "arrival": self.arrival,
            "max_inflight": self.max_inflight,
            "concurrency": self.concurrency,
            "mix": [list(m) for m in self.mix],
            "subscribers": self.subscribers,
            "subscribe_query": self.subscribe_query,
            "timeout_s": self.timeout_s,
            "tx_value_bytes": self.tx_value_bytes,
            # part of the recipe: coarser sampling misses saturation
            # peaks, so an A/B row must name its scrape cadence
            "scrape_interval_s": self.scrape_interval_s,
        }

